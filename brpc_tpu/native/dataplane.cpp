// dataplane — the native transport core (SURVEY §7: "C++ ... must be native
// to hit latency targets"; reference socket.cpp / event_dispatcher_epoll.cpp
// / input_messenger.cpp are the blueprint, re-designed for a hybrid
// C++-engine + Python-policy stack).
//
// What runs here, GIL-free, on dedicated event-loop threads:
//   - epoll event loops (reference EventDispatcher::Run,
//     event_dispatcher_epoll.cpp:196-206), one epoll per loop thread,
//     connections spread round-robin (event_dispatcher_num analog)
//   - nonblocking sockets with claimed-writer inline send + queued drain on
//     EPOLLOUT (reference Socket::StartWrite/KeepWrite, socket.cpp:1692)
//   - TRPC/TSTR frame cutting straight off the read buffer (reference
//     InputMessenger::CutInputMessage, input_messenger.cpp:84)
//   - native services: registered (service, method) pairs answered entirely
//     in C++ (the reference's user code IS C++; echo is the built-in one)
//   - a minimal protobuf wire reader/writer for RpcMeta — just the fields
//     the fast path needs (proto/rpc_meta.proto layout)
//
// Everything else — protocol policy, retries, auth, limiters, user Python
// services — stays in Python: complete frames are handed up through a
// poll()-based event queue (one malloc per message, batch retrieval), and
// Python hands packed response/request packets back through dp_send.
// Connections that speak anything other than the TRPC frame family are
// DETACHED: removed from the native epoll and surfaced with their fd and
// buffered bytes so the Python stack (http dashboard, grpc, redis ...)
// takes over that connection transparently.
//
// No dependencies beyond libc/pthread. C ABI only (ctypes loads it).

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <stdio.h>
#include <time.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <sys/types.h>
#include <sys/uio.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <deque>
#include <functional>
#include <memory>
#include <mutex>
#include <string>
#include <random>
#include <thread>
#include <unordered_map>
#include <vector>

#include "hpack_tables.h"  // RFC 7541 data, generated from policy/hpack.py

namespace {

// ---------------------------------------------------------------- constants
constexpr uint32_t kHeaderSize = 12;
constexpr uint64_t kDefaultMaxBody = 512ull << 20;
constexpr uint64_t kWriteQueueMax = 64ull << 20;   // EOVERCROWDED beyond
constexpr uint64_t kEventQueueMaxBytes = 512ull << 20;
constexpr size_t kReadChunk = 256 * 1024;

// TPUC tunnel framing (brpc_tpu/tpu/transport.py wire format — the
// RDMA-endpoint analog: shm block pools + credit window over a TCP
// bootstrap; this engine speaks it natively for the zero-kernel-copy
// payload path)
constexpr uint32_t kTpuHdrSize = 9;  // "TPUC" + u8 type + u32 len (BE)
enum { TFT_HELLO = 1, TFT_HELLO_ACK = 2, TFT_DATA = 3, TFT_ACK = 4,
       TFT_BYE = 5 };
constexpr uint32_t kTpuInlineMax = 16 << 10;
constexpr uint32_t kTpuBlockSize = 256 << 10;
constexpr uint32_t kTpuBlockCount = 64;   // 16 MB window per direction
constexpr int kTpuMaxSegs = 32;

// event kinds (Python mirror in rpc/native_transport.py)
enum {
  EV_FRAME = 1,     // tag: 0 TRPC / 1 TSTR; meta+body buffers
  EV_FAILED = 2,    // tag: error class; meta: reason text
  EV_ACCEPTED = 3,  // aux: listener id; meta: "host:port" of peer
  EV_DETACHED = 4,  // aux: fd (now owned by consumer); meta: buffered bytes
  // fast-path events: the engine already parsed RpcMeta — Python never
  // touches protobuf on these (reference keeps ProcessRpcRequest native,
  // baidu_rpc_protocol.cpp:565; this is our analog for Python services)
  EV_REQUEST = 5,   // aux: cid; meta: ReqLite+svc+method; body: payload+att
  EV_RESPONSE = 6,  // aux: cid; tag: error_code; meta: RespLite+error_text
  // zero-copy tunnel response: the payload stays in the registered pool
  // blocks (reference rdma zero-copy recv: blocks attach straight to the
  // IOBuf, block_pool.cpp). meta: RespLite + u32 nsegs + nsegs*(u64 ptr,
  // u64 len) + u32 ack_len + ack body; the consumer reads the segments,
  // then MUST dp_tpu_ack the ack blob to return the peer's credits.
  EV_RESPONSE_ZC = 7,
};

// packed structs riding EV_REQUEST / EV_RESPONSE meta buffers (same-machine
// host endianness; Python reads them with struct.unpack_from)
struct ReqLite {
  uint64_t cid;
  uint64_t attempt;
  uint64_t att_size;
  int64_t log_id;
  int64_t trace_id;   // sampled traces ride the fast path end to end
  int64_t span_id;
  int32_t timeout_ms;
  uint16_t svc_len;
  uint16_t meth_len;
};
struct RespLite {
  uint64_t attempt;
  uint64_t att_size;
};

// frames at/above this take the zero-copy donation path (EV_FRAME with the
// whole read buffer) instead of the parsed fast path — the pb meta parse is
// noise at that size and the memcpy is not
constexpr uint64_t kFastFrameMax = 64 << 10;

// error classes for EV_FAILED.tag / dp_send return (Python maps to errors.py)
enum {
  DPE_OK = 0,
  DPE_EOF = 1,         // clean close by peer
  DPE_IO = 2,          // errno-style failure
  DPE_PROTOCOL = 3,    // bad frame
  DPE_OVERCROWDED = 4, // write queue limit
  DPE_NOTFOUND = 5,    // unknown conn id
  DPE_TIMEDOUT = 6,    // dp_call_sync deadline exceeded
};

struct DpEvent {
  int32_t kind;
  int32_t tag;
  uint64_t conn_id;
  int64_t aux;
  void* base;  // single free() handle for meta+body
  void* meta;
  uint64_t meta_len;
  void* body;
  uint64_t body_len;
};

// ------------------------------------------------------------ pb wire codec
// Minimal protobuf reader for RpcMeta / RequestMeta (proto/rpc_meta.proto).
bool pb_varint(const uint8_t*& p, const uint8_t* end, uint64_t* v) {
  uint64_t r = 0;
  int shift = 0;
  while (p < end && shift < 64) {
    uint8_t b = *p++;
    r |= uint64_t(b & 0x7f) << shift;
    if (!(b & 0x80)) {
      *v = r;
      return true;
    }
    shift += 7;
  }
  return false;
}

bool pb_skip(const uint8_t*& p, const uint8_t* end, uint32_t wire_type) {
  uint64_t tmp;
  switch (wire_type) {
    case 0:
      return pb_varint(p, end, &tmp);
    case 1:
      if (end - p < 8) return false;
      p += 8;
      return true;
    case 2:
      if (!pb_varint(p, end, &tmp) || uint64_t(end - p) < tmp) return false;
      p += tmp;
      return true;
    case 5:
      if (end - p < 4) return false;
      p += 4;
      return true;
    default:
      return false;
  }
}

void pb_put_varint(std::string* out, uint64_t v) {
  while (v >= 0x80) {
    out->push_back(char(v | 0x80));
    v >>= 7;
  }
  out->push_back(char(v));
}

void pb_put_tag(std::string* out, uint32_t field, uint32_t wt) {
  pb_put_varint(out, (field << 3) | wt);
}

// Parsed just-enough RpcMeta for routing + the native fast path.
struct MetaLite {
  bool has_request = false;
  bool has_response = false;
  bool has_stream_settings = false;
  bool has_auth = false;
  uint64_t correlation_id = 0;
  uint64_t attempt_version = 0;
  uint64_t compress_type = 0;
  uint64_t attachment_size = 0;
  uint64_t checksum = 0;
  int64_t log_id = 0;
  int64_t trace_id = 0;
  int64_t span_id = 0;
  int64_t timeout_ms = 0;
  int64_t resp_error_code = 0;
  std::string resp_error_text;
  std::string service;
  std::string method;
};

bool parse_request_meta(const uint8_t* p, const uint8_t* end, MetaLite* m) {
  while (p < end) {
    uint64_t key;
    if (!pb_varint(p, end, &key)) return false;
    uint32_t field = key >> 3, wt = key & 7;
    if (field == 1 && wt == 2) {
      uint64_t len;
      if (!pb_varint(p, end, &len) || uint64_t(end - p) < len) return false;
      m->service.assign(reinterpret_cast<const char*>(p), len);
      p += len;
    } else if (field == 2 && wt == 2) {
      uint64_t len;
      if (!pb_varint(p, end, &len) || uint64_t(end - p) < len) return false;
      m->method.assign(reinterpret_cast<const char*>(p), len);
      p += len;
    } else if (field == 3 && wt == 0) {
      uint64_t v;
      if (!pb_varint(p, end, &v)) return false;
      m->log_id = int64_t(v);
    } else if ((field == 4 || field == 5) && wt == 0) {
      uint64_t v;
      if (!pb_varint(p, end, &v)) return false;
      // traces ride the fast path: ReqLite carries the ids end to end
      if (field == 4) m->trace_id = int64_t(v);
      else m->span_id = int64_t(v);
    } else if (field == 7 && wt == 0) {
      uint64_t v;
      if (!pb_varint(p, end, &v)) return false;
      m->timeout_ms = int64_t(v);
    } else if (!pb_skip(p, end, wt)) {
      return false;
    }
  }
  return true;
}

bool parse_response_meta(const uint8_t* p, const uint8_t* end, MetaLite* m) {
  while (p < end) {
    uint64_t key;
    if (!pb_varint(p, end, &key)) return false;
    uint32_t field = key >> 3, wt = key & 7;
    if (field == 1 && wt == 0) {
      uint64_t v;
      if (!pb_varint(p, end, &v)) return false;
      // int32 on the wire: negatives arrive as 10-byte varints
      m->resp_error_code = int64_t(int32_t(uint32_t(v)));
    } else if (field == 2 && wt == 2) {
      uint64_t len;
      if (!pb_varint(p, end, &len) || uint64_t(end - p) < len) return false;
      m->resp_error_text.assign(reinterpret_cast<const char*>(p), len);
      p += len;
    } else if (!pb_skip(p, end, wt)) {
      return false;
    }
  }
  return true;
}

bool parse_meta_lite(const uint8_t* p, const uint8_t* end, MetaLite* m) {
  while (p < end) {
    uint64_t key;
    if (!pb_varint(p, end, &key)) return false;
    uint32_t field = key >> 3, wt = key & 7;
    uint64_t v;
    switch (field) {
      case 1:  // RequestMeta
        if (wt != 2) return false;
        if (!pb_varint(p, end, &v) || uint64_t(end - p) < v) return false;
        m->has_request = true;
        if (!parse_request_meta(p, p + v, m)) return false;
        p += v;
        break;
      case 2:  // ResponseMeta
        if (wt != 2) return false;
        if (!pb_varint(p, end, &v) || uint64_t(end - p) < v) return false;
        m->has_response = true;
        if (!parse_response_meta(p, p + v, m)) return false;
        p += v;
        break;
      case 3:
        if (!pb_varint(p, end, &m->correlation_id)) return false;
        break;
      case 4:
        if (!pb_varint(p, end, &m->attempt_version)) return false;
        break;
      case 5:
        if (!pb_varint(p, end, &m->compress_type)) return false;
        break;
      case 6:
        if (!pb_varint(p, end, &m->attachment_size)) return false;
        break;
      case 7:
        if (!pb_varint(p, end, &m->checksum)) return false;
        break;
      case 8:
        m->has_stream_settings = true;
        if (!pb_skip(p, end, wt)) return false;
        break;
      case 9:
        m->has_auth = true;
        if (!pb_skip(p, end, wt)) return false;
        break;
      default:
        if (!pb_skip(p, end, wt)) return false;
    }
  }
  return true;
}

// RpcMeta for a native fast-path response:
//   response{} (empty = OK), correlation_id, attempt_version,
//   attachment_size — mirroring server_processing._send_response.
std::string build_echo_response_meta(const MetaLite& req) {
  std::string meta;
  pb_put_tag(&meta, 2, 2);  // response submessage, present-but-empty = OK
  pb_put_varint(&meta, 0);
  if (req.correlation_id) {
    pb_put_tag(&meta, 3, 0);
    pb_put_varint(&meta, req.correlation_id);
  }
  if (req.attempt_version) {
    pb_put_tag(&meta, 4, 0);
    pb_put_varint(&meta, req.attempt_version);
  }
  if (req.attachment_size) {
    pb_put_tag(&meta, 6, 0);
    pb_put_varint(&meta, req.attachment_size);
  }
  return meta;
}

// General response RpcMeta for dp_respond (server_processing._send_response
// kept native): response{error_code,error_text}, cid, attempt, att_size.
std::string build_response_meta(uint64_t cid, uint64_t attempt,
                                int32_t error_code, const char* etext,
                                uint64_t etext_len, uint64_t att_size,
                                int32_t compress_type = 0) {
  std::string resp;
  if (error_code) {
    pb_put_tag(&resp, 1, 0);
    pb_put_varint(&resp, uint64_t(uint32_t(error_code)));
  }
  if (etext_len) {
    pb_put_tag(&resp, 2, 2);
    pb_put_varint(&resp, etext_len);
    resp.append(etext, etext_len);
  }
  std::string meta;
  pb_put_tag(&meta, 2, 2);
  pb_put_varint(&meta, resp.size());
  meta.append(resp);
  if (cid) {
    pb_put_tag(&meta, 3, 0);
    pb_put_varint(&meta, cid);
  }
  if (attempt) {
    pb_put_tag(&meta, 4, 0);
    pb_put_varint(&meta, attempt);
  }
  if (compress_type) {
    pb_put_tag(&meta, 5, 0);
    pb_put_varint(&meta, uint64_t(uint32_t(compress_type)));
  }
  if (att_size) {
    pb_put_tag(&meta, 6, 0);
    pb_put_varint(&meta, att_size);
  }
  return meta;
}

// Request RpcMeta for dp_call (Controller._issue_rpc's meta kept native).
std::string build_request_meta(const char* svc, uint64_t svc_len,
                               const char* meth, uint64_t meth_len,
                               uint64_t cid, uint64_t attempt,
                               int64_t log_id, int64_t trace_id,
                               int64_t span_id, int32_t timeout_ms,
                               uint64_t att_size) {
  std::string rm;
  pb_put_tag(&rm, 1, 2);
  pb_put_varint(&rm, svc_len);
  rm.append(svc, svc_len);
  pb_put_tag(&rm, 2, 2);
  pb_put_varint(&rm, meth_len);
  rm.append(meth, meth_len);
  if (log_id) {
    pb_put_tag(&rm, 3, 0);
    pb_put_varint(&rm, uint64_t(log_id));
  }
  if (trace_id) {
    pb_put_tag(&rm, 4, 0);
    pb_put_varint(&rm, uint64_t(trace_id));
  }
  if (span_id) {
    pb_put_tag(&rm, 5, 0);
    pb_put_varint(&rm, uint64_t(span_id));
  }
  if (timeout_ms) {
    pb_put_tag(&rm, 7, 0);
    pb_put_varint(&rm, uint64_t(uint32_t(timeout_ms)));
  }
  std::string meta;
  pb_put_tag(&meta, 1, 2);
  pb_put_varint(&meta, rm.size());
  meta.append(rm);
  if (cid) {
    pb_put_tag(&meta, 3, 0);
    pb_put_varint(&meta, cid);
  }
  if (attempt) {
    pb_put_tag(&meta, 4, 0);
    pb_put_varint(&meta, attempt);
  }
  if (att_size) {
    pb_put_tag(&meta, 6, 0);
    pb_put_varint(&meta, att_size);
  }
  return meta;
}

// 12-byte TRPC header in front of a meta+body packet.
void put_trpc_header(std::string* out, uint64_t meta_size,
                     uint64_t body_size) {
  out->append("TRPC", 4);
  uint32_t ms = htonl(uint32_t(meta_size));
  uint32_t bs = htonl(uint32_t(body_size));
  out->append(reinterpret_cast<char*>(&ms), 4);
  out->append(reinterpret_cast<char*>(&bs), 4);
}

// --------------------------------------------------------------- data types
struct Runtime;

struct RBuf {
  uint8_t* data = nullptr;
  size_t cap = 0;
  size_t size = 0;
  ~RBuf() { free(data); }
  uint8_t* tail(size_t need) {
    if (size + need > cap) {
      size_t ncap = cap ? cap * 2 : (64 << 10);
      while (ncap < size + need) ncap *= 2;
      data = static_cast<uint8_t*>(realloc(data, ncap));
      cap = ncap;
    }
    return data + size;
  }
  // grow once to `total` — doubling reallocs memcpy an MB-scale frame
  // several times over on the shared core
  void reserve(size_t total) {
    if (total > cap) {
      data = static_cast<uint8_t*>(realloc(data, total));
      cap = total;
    }
  }
};

// Tunnel state for a TPUC conn (reference RdmaEndpoint: registered block
// pool, credit window, bootstrap handshake — rdma_endpoint.cpp:127-130,
// block_pool.cpp, rdma_endpoint.h:256-261).
struct TpuState {
  // our receive pool: WE create it, the PEER writes into it
  std::string pool_name;
  uint8_t* pool = nullptr;
  size_t pool_len = 0;
  uint32_t bs = kTpuBlockSize, bc = kTpuBlockCount;
  bool pool_owner = false;
  // the peer's pool: we write request/response bytes into it
  uint8_t* peer = nullptr;
  size_t peer_len = 0;
  uint32_t peer_bs = 0, peer_bc = 0;
  bool inline_only = false;  // cross-host fallback (pool not attachable)
  std::vector<uint8_t> inflight;  // per-block: handed out, not yet ACKed
  // sender-side credit window over the peer's blocks
  std::mutex cmu;
  std::condition_variable ccv;
  std::deque<uint32_t> credits;
  bool closed = false;
  // tunnel senders serialize (frame order IS stream order)
  std::mutex smu;
  // handshake rendezvous (dp_connect_tpu blocks here)
  std::mutex hmu;
  std::condition_variable hcv;
  bool ready = false;
  std::string err;
  int ordinal = 0;
  // native-service responses NEVER send from the loop thread (it must stay
  // free to process the credit ACKs); one per-conn sender worker drains
  // this queue in order
  struct Resp {
    std::string head;
    uint8_t* base = nullptr;     // free() after send (stolen stream buffer)
    const uint8_t* body = nullptr;
    uint64_t blen = 0;
    // zero-copy echo: body segments referencing OUR pool blocks; `ack`
    // (the TFT_ACK body returning those blocks) is sent AFTER the
    // response bytes leave — the peer must not reuse them mid-read
    std::vector<std::pair<const uint8_t*, uint64_t>> segs;
    std::string ack;
  };
  std::mutex qmu;
  std::condition_variable qcv;
  std::deque<Resp> respq;
  bool sender_running = false;
  bool q_closed = false;  // closed-mirror guarded by qmu (wakeup safety)

  ~TpuState() {
    if (pool) munmap(pool, pool_len);
    if (peer) munmap(peer, peer_len);
    if (pool_owner && !pool_name.empty()) {
      shm_unlink(("/" + pool_name).c_str());
    }
  }
};

// ------------------------------------------------------------------ HTTP/2
// Native h2c + gRPC data plane (VERDICT r4 #5; reference
// policy/http2_rpc_protocol.cpp + details/hpack.cpp, re-designed for the
// hybrid engine). The engine owns h2 FRAMING, HPACK and flow control;
// grpc unary requests ride the same EV_REQUEST fast path / native-echo
// registry as the std protocol. A server conn whose FIRST request is not
// application/grpc is detached with its raw bytes (from the preface)
// replayed, and the Python h2 stack takes over — dashboard-over-h2 and
// exotic h2 stay at Python speed, grpc runs at engine speed.
constexpr uint8_t H2F_DATA = 0x0, H2F_HEADERS = 0x1, H2F_RST = 0x3,
    H2F_SETTINGS = 0x4, H2F_PING = 0x6, H2F_GOAWAY = 0x7,
    H2F_WINUP = 0x8, H2F_CONT = 0x9;
constexpr uint8_t H2FL_END_STREAM = 0x1, H2FL_ACK = 0x1,
    H2FL_END_HEADERS = 0x4, H2FL_PADDED = 0x8, H2FL_PRIORITY = 0x20;
constexpr uint32_t kH2RecvWindow = 1u << 30;  // our advertised window
constexpr uint32_t kH2MaxFrame = 1u << 20;    // our SETTINGS_MAX_FRAME_SIZE
static const char kH2Preface[] = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";
constexpr size_t kH2PrefaceLen = 24;

const std::unordered_map<uint64_t, int>& huff_decode_map() {
  static const std::unordered_map<uint64_t, int>* m = [] {
    auto* t = new std::unordered_map<uint64_t, int>();
    for (int i = 0; i < 257; i++) {
      (*t)[(uint64_t(kHuffCodes[i].bits) << 32) | kHuffCodes[i].code] = i;
    }
    return t;
  }();
  return *m;
}

bool huff_decode(const uint8_t* p, size_t len, std::string* out) {
  const auto& m = huff_decode_map();
  uint32_t code = 0;
  int bits = 0;
  for (size_t i = 0; i < len; i++) {
    for (int b = 7; b >= 0; b--) {
      code = (code << 1) | ((p[i] >> b) & 1);
      bits++;
      auto it = m.find((uint64_t(bits) << 32) | code);
      if (it != m.end()) {
        if (it->second == 256) return false;  // EOS inside a string
        out->push_back(char(it->second));
        code = 0;
        bits = 0;
      } else if (bits > 30) {
        return false;
      }
    }
  }
  // trailing padding must be a (possibly empty) all-ones EOS prefix
  return bits == 0 || code == (1u << bits) - 1;
}

bool hp_read_int(const uint8_t* p, size_t len, size_t* pos, int prefix,
                 uint64_t* out) {
  if (*pos >= len) return false;
  uint64_t max_pfx = (1u << prefix) - 1;
  uint64_t v = p[(*pos)++] & max_pfx;
  if (v < max_pfx) {
    *out = v;
    return true;
  }
  int shift = 0;
  for (;;) {
    if (*pos >= len || shift > 56) return false;
    uint8_t b = p[(*pos)++];
    v += uint64_t(b & 0x7f) << shift;
    if (!(b & 0x80)) break;
    shift += 7;
  }
  *out = v;
  return true;
}

bool hp_read_str(const uint8_t* p, size_t len, size_t* pos,
                 std::string* out) {
  if (*pos >= len) return false;
  bool huff = (p[*pos] & 0x80) != 0;
  uint64_t n;
  if (!hp_read_int(p, len, pos, 7, &n)) return false;
  if (n > len - *pos || n > (64u << 20)) return false;
  if (huff) {
    if (!huff_decode(p + *pos, size_t(n), out)) return false;
  } else {
    out->assign(reinterpret_cast<const char*>(p + *pos), size_t(n));
  }
  *pos += size_t(n);
  return true;
}

using HdrList = std::vector<std::pair<std::string, std::string>>;

struct HpackDec {
  std::deque<std::pair<std::string, std::string>> dyn;  // front = newest
  size_t dyn_bytes = 0;
  size_t max_bytes = 4096;

  void evict() {
    while (dyn_bytes > max_bytes && !dyn.empty()) {
      dyn_bytes -= dyn.back().first.size() + dyn.back().second.size() + 32;
      dyn.pop_back();
    }
  }
  void add(const std::string& n, const std::string& v) {
    dyn.emplace_front(n, v);
    dyn_bytes += n.size() + v.size() + 32;
    evict();
  }
  bool get(uint64_t idx, std::string* n, std::string* v) const {
    if (idx >= 1 && idx <= 61) {
      *n = kHpackStatic[idx - 1].name;
      *v = kHpackStatic[idx - 1].value;
      return true;
    }
    uint64_t di = idx - 62;
    if (di >= dyn.size()) return false;
    *n = dyn[size_t(di)].first;
    *v = dyn[size_t(di)].second;
    return true;
  }
};

bool hpack_decode_block(HpackDec* d, const uint8_t* p, size_t len,
                        HdrList* out) {
  size_t pos = 0;
  while (pos < len) {
    uint8_t b = p[pos];
    if (b & 0x80) {  // indexed field
      uint64_t idx;
      if (!hp_read_int(p, len, &pos, 7, &idx) || idx == 0) return false;
      std::string n, v;
      if (!d->get(idx, &n, &v)) return false;
      out->emplace_back(std::move(n), std::move(v));
    } else if ((b & 0xc0) == 0x40) {  // literal + incremental indexing
      uint64_t idx;
      if (!hp_read_int(p, len, &pos, 6, &idx)) return false;
      std::string n, v, ign;
      if (idx) {
        if (!d->get(idx, &n, &ign)) return false;
      } else if (!hp_read_str(p, len, &pos, &n)) {
        return false;
      }
      if (!hp_read_str(p, len, &pos, &v)) return false;
      d->add(n, v);
      out->emplace_back(std::move(n), std::move(v));
    } else if ((b & 0xe0) == 0x20) {  // dynamic table size update
      uint64_t sz;
      if (!hp_read_int(p, len, &pos, 5, &sz)) return false;
      if (sz > (1u << 22)) return false;
      d->max_bytes = size_t(sz);
      d->evict();
    } else {  // literal without indexing / never-indexed (prefix 4)
      uint64_t idx;
      if (!hp_read_int(p, len, &pos, 4, &idx)) return false;
      std::string n, v, ign;
      if (idx) {
        if (!d->get(idx, &n, &ign)) return false;
      } else if (!hp_read_str(p, len, &pos, &n)) {
        return false;
      }
      if (!hp_read_str(p, len, &pos, &v)) return false;
      out->emplace_back(std::move(n), std::move(v));
    }
  }
  return true;
}

// HPACK encoding — static-table-only (stateless: no dynamic-table sync)
void hp_put_int(std::string* o, uint64_t v, int prefix, uint8_t first) {
  uint64_t max_pfx = (1u << prefix) - 1;
  if (v < max_pfx) {
    o->push_back(char(first | v));
    return;
  }
  o->push_back(char(first | max_pfx));
  v -= max_pfx;
  while (v >= 128) {
    o->push_back(char(0x80 | (v & 0x7f)));
    v >>= 7;
  }
  o->push_back(char(v));
}

void hp_put_str(std::string* o, const char* s, size_t n) {
  hp_put_int(o, n, 7, 0x00);  // raw (no huffman) is always valid
  o->append(s, n);
}

void hp_put_indexed(std::string* o, int idx) { hp_put_int(o, idx, 7, 0x80); }

// literal without indexing; name_idx > 0 names via the static table
void hp_put_literal(std::string* o, int name_idx, const char* name,
                    const char* value, size_t value_len) {
  if (name_idx > 0) {
    hp_put_int(o, uint64_t(name_idx), 4, 0x00);
  } else {
    o->push_back(0x00);
    hp_put_str(o, name, strlen(name));
  }
  hp_put_str(o, value, value_len);
}

void h2_frame_hdr(std::string* o, uint32_t len, uint8_t type,
                  uint8_t flags, uint32_t sid) {
  o->push_back(char((len >> 16) & 0xff));
  o->push_back(char((len >> 8) & 0xff));
  o->push_back(char(len & 0xff));
  o->push_back(char(type));
  o->push_back(char(flags));
  uint32_t s = htonl(sid & 0x7fffffffu);
  o->append(reinterpret_cast<const char*>(&s), 4);
}

// reference grpc.cpp ErrorCodeToGrpcStatus / mirror of
// policy/grpc_protocol.py BRPC_TO_GRPC (errors.py numeric codes)
int grpc_status_of(int code) {
  switch (code) {
    case 0: return 0;
    case 1001: case 1002: return 12;   // UNIMPLEMENTED
    case 1003: return 3;               // INVALID_ARGUMENT
    case 1008: return 4;               // DEADLINE_EXCEEDED
    case 1012: case 2004: return 8;    // RESOURCE_EXHAUSTED
    case 1009: case 1010: case 1011: return 14;  // UNAVAILABLE
    case 2003: return 16;              // UNAUTHENTICATED
    case 1015: return 1;               // CANCELLED
    default: return 13;                // INTERNAL
  }
}

int brpc_code_of_grpc(int g) {
  switch (g) {
    case 0: return 0;
    case 1: return 1015;
    case 3: return 1003;
    case 4: return 1008;
    case 5: case 12: return 1002;
    case 8: return 1012;
    case 14: return 1010;
    case 16: return 2003;
    default: return 2001;
  }
}

int parse_grpc_timeout(const std::string& v) {  // -> ms (0 = none)
  if (v.empty()) return 0;
  char unit = v.back();
  // RFC: at most 8 ASCII digits — also the overflow guard (an attacker-
  // controlled value must not wrap into a negative/instant deadline)
  if (v.size() > 9) return 0;
  long long n = atoll(v.substr(0, v.size() - 1).c_str());
  if (n < 0) return 0;
  long long ms;
  switch (unit) {
    case 'H': ms = n * 3600000; break;
    case 'M': ms = n * 60000; break;
    case 'S': ms = n * 1000; break;
    case 'm': ms = n; break;
    case 'u': ms = n / 1000; break;
    case 'n': ms = n / 1000000; break;
    default: return 0;
  }
  if (ms > 0x7fffffff) ms = 0x7fffffff;
  return int(ms);
}

struct H2Stream {
  HdrList headers;
  bool headers_done = false;
  std::string data;        // inbound DATA accumulation (grpc-framed)
  // outbound flow control (bytes not yet emitted)
  int64_t send_window = 65535;
  std::string out;         // grpc-framed payload awaiting window
  size_t out_off = 0;
  std::string trailers;    // server: trailers frame to send after out
  bool end_after_out = false;  // client: END_STREAM on the last DATA
  bool sent_all = false;
  uint64_t cid = 0;        // client: correlation id
};

struct H2State {
  std::mutex mu;  // streams + windows + send state (parse loop + senders)
  bool client = false;
  int phase = 0;  // server: 0 preface, 1 sniffing, 2 engine-owned
  std::string prelude;      // raw bytes kept for a possible detach
  std::string pending_ctrl; // pre-decision replies (pongs), sent at engage
  int unacked_settings = 0;
  HpackDec dec;
  std::unordered_map<uint32_t, H2Stream> streams;
  int64_t conn_send_window = 65535;
  uint32_t peer_initial_window = 65535;
  uint32_t peer_max_frame = 16384;
  uint64_t recv_since_update = 0;
  uint32_t cont_sid = 0;    // CONTINUATION reassembly
  uint8_t cont_flags = 0;
  std::string cont_buf;
  uint32_t next_stream_id = 1;  // client request sids (odd)
  std::string authority;        // client: host:port for :authority
};

struct Conn {
  int listener_id = -1;
  uint64_t id = 0;
  int fd = -1;
  int loop = 0;
  bool is_server = false;
  std::atomic<bool> failed{false};
  bool detached = false;
  // parsed fast-path events enabled (server conns: copied from the
  // listener at accept; client conns: dp_conn_set_fastpath)
  std::atomic<bool> py_fast{false};

  // queued dp_respond/dp_call packets awaiting dp_flush_all (one writev
  // per poll batch instead of one per RPC — single-core syscalls are the
  // hybrid lane's wall clock)
  std::mutex pmu;
  std::string pending;
  int pending_msgs = 0;

  // TPUC tunnel: 0 = plain TCP conn; 1 = negotiating; 2 = ready
  int tpu_mode = 0;
  std::unique_ptr<TpuState> tpu;
  // HTTP/2: 0 = not h2; 2 = engine-owned h2 conn (grpc fast path)
  int h2_mode = 0;
  std::unique_ptr<H2State> h2;
  // read side (loop thread only)
  RBuf rbuf;
  size_t rpos = 0;
  // reassembled tunnel byte stream (TRPC frames are cut from here)
  RBuf sbuf;
  size_t spos = 0;

  // write side (any thread; wmu guards)
  std::mutex wmu;
  std::deque<std::string> wq;
  size_t wq_off = 0;  // offset into wq.front()
  uint64_t wq_bytes = 0;
  bool want_write = false;

  std::atomic<uint64_t> in_bytes{0}, out_bytes{0};
  std::atomic<uint64_t> in_msgs{0}, out_msgs{0};
  // zero-copy events referencing this conn's pool still in consumer hands
  std::atomic<int> zc_outstanding{0};
};

struct Listener {
  int fd = -1;
  int port = 0;
  int tpu_ordinal = -1;  // >=0: conns speak the TPUC tunnel natively
  bool py_fast = false;  // parsed EV_REQUEST events for Python services
  bool logoff = false;   // graceful stop: native services answer ELOGOFF
};

struct Loop {
  int epfd = -1;
  int evfd = -1;  // eventfd wakeup for the task queue
  std::thread thr;
  std::mutex tmu;
  std::vector<std::function<void()>> tasks;
};

// A Python thread blocked inside dp_call_sync (GIL released): the poller
// threads complete it directly — no event queue, no Python poller, no
// threading.Event. This is what makes N sync client threads scale: they
// park in C, so the interpreter only ever runs ONE of them at a time for
// the ~µs of pb work around the call. (Reference analog: a bthread
// blocking on its CallId butex, brpc/controller.cpp Join.)
struct SyncWaiter {
  uint64_t cid = 0;
  uint64_t conn_id = 0;
  std::mutex mu;
  std::condition_variable cv;
  bool done = false;
  int32_t terr = 0;           // transport error (DPE_*), 0 = completed
  int32_t code = 0;           // app-level error code from RpcMeta
  uint64_t attempt = 0;
  uint64_t att_size = 0;
  std::string etext;
  uint8_t* base = nullptr;    // free() handle (may differ from body)
  uint8_t* body = nullptr;
  uint64_t body_len = 0;
};

struct Runtime {
  std::vector<std::unique_ptr<Loop>> loops;
  std::atomic<bool> running{true};
  uint64_t max_body = kDefaultMaxBody;

  std::mutex swmu;  // outstanding dp_call_sync waiters by cid
  std::unordered_map<uint64_t, SyncWaiter*> sync_waiters;

  std::mutex cmu;  // conns + listeners
  std::unordered_map<uint64_t, std::shared_ptr<Conn>> conns;
  std::vector<Listener> listeners;
  std::atomic<uint64_t> next_conn_id{1};
  std::atomic<int> rr{0};

  std::mutex emu;
  std::condition_variable ecv;
  std::deque<DpEvent> events;
  uint64_t event_bytes = 0;

  // Native services run the reference's FULL per-request path in the
  // engine: admission (logoff + concurrency limit) and method status
  // (qps/latency/errors) are native, like MethodStatus::OnRequested in
  // baidu_rpc_protocol.cpp:661-712 — not a policy bypass.
  struct EchoSvc {
    int lid;  // native services are scoped to their listener — one
              // server's fast path must not answer another's traffic
    std::string service;
    std::string method;
    int32_t max_concurrency = 0;  // 0 = unlimited
    std::atomic<bool> logoff{false};
    std::atomic<int32_t> concurrency{0};
    std::atomic<uint64_t> requests{0};
    std::atomic<uint64_t> errors{0};
    std::atomic<uint64_t> latency_sum_ns{0};
    std::atomic<uint64_t> latency_max_ns{0};
  };
  std::mutex rmu;  // native service registry
  std::vector<std::unique_ptr<EchoSvc>> echo_services;

  // TPUC per-conn sender workers: tracked (not detached) so shutdown can
  // quiesce them before the Runtime dies. Finished entries are reaped on
  // the next registration (one worker per conn lifetime keeps this small).
  struct SenderSlot {
    std::thread thr;
    std::shared_ptr<std::atomic<bool>> done;
  };
  std::mutex smu_senders;
  std::vector<SenderSlot> senders;

  // listeners muted after EMFILE/ENFILE (fd exhaustion): disarmed from
  // epoll so level-triggered readiness cannot busy-spin loop 0, re-armed
  // by the loop tick once the backoff expires
  std::mutex amu;
  std::vector<std::pair<int, int64_t>> muted_listeners;  // (lid, rearm_ns)

  // conns with queued dp_respond/dp_call packets (dp_flush_all drains)
  std::mutex fmu;
  std::vector<std::shared_ptr<Conn>> flush_list;

  // pools of failed conns with zero-copy events still out: the mapping
  // must outlive the consumer's reads (freed at shutdown; bounded by
  // conns that die with events in flight)
  std::mutex gmu;
  std::vector<std::unique_ptr<TpuState>> tpu_graveyard;
};

int64_t mono_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return int64_t(ts.tv_sec) * 1000000000 + ts.tv_nsec;
}

void register_sender(Runtime* rt, std::thread thr,
                     std::shared_ptr<std::atomic<bool>> done) {
  std::lock_guard<std::mutex> lk(rt->smu_senders);
  for (auto it = rt->senders.begin(); it != rt->senders.end();) {
    if (it->done->load()) {
      it->thr.join();
      it = rt->senders.erase(it);
    } else {
      ++it;
    }
  }
  rt->senders.push_back({std::move(thr), std::move(done)});
}

// ------------------------------------------------------------------ helpers
void push_event(Runtime* rt, DpEvent ev) {
  std::unique_lock<std::mutex> lk(rt->emu);
  rt->event_bytes += ev.meta_len + ev.body_len + sizeof(DpEvent);
  // soft cap: beyond it the loop threads stall here — natural backpressure
  // (the consumer is the Python poller; it drains in batches)
  while (rt->running.load() && rt->event_bytes > kEventQueueMaxBytes &&
         rt->events.size() > 16) {
    lk.unlock();
    usleep(1000);
    lk.lock();
  }
  bool was_empty = rt->events.empty();
  rt->events.push_back(ev);
  if (was_empty) {
    // consumers only sleep when the queue is empty (predicate-gated
    // wait), so the 0->1 transition is the only one that needs a signal —
    // per-message notifies were a futex syscall per frame under load
    rt->ecv.notify_one();
  }
}

// Batched variant: one lock round trip for a whole parse pass of frames
// (order within the batch is the conn's arrival order).
void push_event_batch(Runtime* rt, std::vector<DpEvent>& evs) {
  if (evs.empty()) return;
  uint64_t add = 0;
  for (auto& ev : evs) add += ev.meta_len + ev.body_len + sizeof(DpEvent);
  std::unique_lock<std::mutex> lk(rt->emu);
  rt->event_bytes += add;
  while (rt->running.load() && rt->event_bytes > kEventQueueMaxBytes &&
         rt->events.size() > 16) {
    lk.unlock();
    usleep(1000);
    lk.lock();
  }
  bool was_empty = rt->events.empty();
  for (auto& ev : evs) rt->events.push_back(ev);
  if (was_empty) rt->ecv.notify_one();
  lk.unlock();
  evs.clear();
}

void emit_failed(Runtime* rt, Conn* c, int err_class, const char* reason) {
  size_t rl = strlen(reason);
  char* buf = static_cast<char*>(malloc(rl ? rl : 1));
  memcpy(buf, reason, rl);
  DpEvent ev{};
  ev.kind = EV_FAILED;
  ev.tag = err_class;
  ev.conn_id = c->id;
  ev.base = buf;
  ev.meta = buf;
  ev.meta_len = rl;
  push_event(rt, ev);
}

void loop_submit(Runtime* rt, int li, std::function<void()> fn) {
  Loop* l = rt->loops[li].get();
  {
    std::lock_guard<std::mutex> lk(l->tmu);
    l->tasks.push_back(std::move(fn));
  }
  uint64_t one = 1;
  ssize_t r = write(l->evfd, &one, 8);
  (void)r;
}

// epoll re-arm helper. Loop-thread-only for IN; OUT armed from writers too
// (epoll_ctl is thread-safe).
void arm(Runtime* rt, Conn* c, bool out) {
  epoll_event ev{};
  ev.events = out ? (EPOLLIN | EPOLLOUT) : EPOLLIN;
  ev.data.u64 = c->id;
  epoll_ctl(rt->loops[c->loop]->epfd, EPOLL_CTL_MOD, c->fd, &ev);
}

// ------------------------------------------------------------- tpu tunnel
// Clamp a requested pool geometry to sane bounds (reference negotiates
// queue geometry in its handshake, rdma_endpoint.cpp:127-130; a peer must
// not be able to demand an absurd registration)
void tpu_clamp_geometry(uint32_t* bs, uint32_t* bc) {
  if (*bs == 0) *bs = kTpuBlockSize;
  if (*bc == 0) *bc = kTpuBlockCount;
  if (*bs < (16u << 10)) *bs = 16u << 10;
  if (*bs > (4u << 20)) *bs = 4u << 20;
  *bs = (*bs + 4095u) & ~4095u;  // page-align
  if (*bc < 4) *bc = 4;
  if (*bc > 512) *bc = 512;
  while (uint64_t(*bs) * *bc > (512ull << 20) && *bc > 4) *bc /= 2;
}

bool tpu_create_pool(TpuState* t) {
  char name[64];
  static std::atomic<uint32_t> seq{0};
  uint32_t rnd = 0;
  {
    std::random_device rd;  // unseeded rand() repeats across processes
    rnd = rd();
  }
  snprintf(name, sizeof(name), "brpctpu_%x_%08x%04x", getpid(), rnd,
           seq.fetch_add(1) & 0xffff);
  t->pool_name = name;
  int fd = shm_open(("/" + t->pool_name).c_str(),
                    O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return false;
  t->pool_len = size_t(t->bs) * t->bc;
  if (ftruncate(fd, off_t(t->pool_len)) != 0) {
    close(fd);
    shm_unlink(("/" + t->pool_name).c_str());
    return false;
  }
  t->pool = static_cast<uint8_t*>(mmap(nullptr, t->pool_len,
                                       PROT_READ | PROT_WRITE, MAP_SHARED,
                                       fd, 0));
  close(fd);
  if (t->pool == MAP_FAILED) {
    t->pool = nullptr;
    shm_unlink(("/" + t->pool_name).c_str());
    return false;
  }
  t->pool_owner = true;
  return true;
}

bool tpu_attach_peer(TpuState* t, const std::string& name, uint32_t bs,
                     uint32_t bc) {
  if (bs == 0 || bc == 0 || uint64_t(bs) * bc > (1ull << 30)) return false;
  if (name.find('/') != std::string::npos) return false;
  int fd = shm_open(("/" + name).c_str(), O_RDWR, 0600);
  if (fd < 0) return false;
  size_t len = size_t(bs) * bc;
  struct stat st {};
  // the claimed geometry must fit the object's REAL size at attach time —
  // mapping past EOF turns the first copy into a SIGBUS. NOTE this cannot
  // stop a peer that ftruncates its pool AFTER the handshake; tunnel
  // peers are processes of the same deployment (the reference's RDMA
  // peers hold registered memory under the same trust model).
  if (fstat(fd, &st) != 0 || uint64_t(st.st_size) < len) {
    close(fd);
    return false;
  }
  t->peer = static_cast<uint8_t*>(mmap(nullptr, len,
                                       PROT_READ | PROT_WRITE, MAP_SHARED,
                                       fd, 0));
  close(fd);
  if (t->peer == MAP_FAILED) {
    t->peer = nullptr;
    return false;
  }
  t->peer_len = len;
  t->peer_bs = bs;
  t->peer_bc = bc;
  {
    std::lock_guard<std::mutex> lk(t->cmu);
    t->credits.clear();
    for (uint32_t i = 0; i < bc; i++) t->credits.push_back(i);
    t->inflight.assign(bc, 0);
  }
  return true;
}

// flat-JSON field scanners — the HELLO body is a fixed flat dict
// (tpu/transport.py _hello_body); a full JSON parser is not needed
size_t json_value_pos(const std::string& s, const char* key) {
  // position after `"key"` + `:` + optional whitespace; npos if absent
  std::string pat = std::string("\"") + key + "\"";
  size_t p = s.find(pat);
  if (p == std::string::npos) return std::string::npos;
  p += pat.size();
  while (p < s.size() && (s[p] == ' ' || s[p] == '\t')) p++;
  if (p >= s.size() || s[p] != ':') return std::string::npos;
  p++;
  while (p < s.size() && (s[p] == ' ' || s[p] == '\t')) p++;
  return p;
}

bool json_str(const std::string& s, const char* key, std::string* out) {
  size_t p = json_value_pos(s, key);
  if (p == std::string::npos || p >= s.size() || s[p] != '"') return false;
  p++;
  size_t e = s.find('"', p);
  if (e == std::string::npos) return false;
  *out = s.substr(p, e - p);
  return true;
}

bool json_int(const std::string& s, const char* key, int64_t* out) {
  size_t p = json_value_pos(s, key);
  if (p == std::string::npos) return false;
  char* end = nullptr;
  long long v = strtoll(s.c_str() + p, &end, 10);
  if (end == s.c_str() + p) return false;
  *out = v;
  return true;
}

std::string tpu_hello_json(TpuState* t, int ordinal) {
  char buf[256];
  snprintf(buf, sizeof(buf),
           "{\"v\": 1, \"pool\": \"%s\", \"bs\": %u, \"bc\": %u, "
           "\"ordinal\": %d, \"pid\": %d}",
           t->pool_name.c_str(), t->bs, t->bc, ordinal, getpid());
  return buf;
}

int conn_writev(Runtime* rt, const std::shared_ptr<Conn>& c,
                const uint8_t* const* bufs, const uint64_t* lens, int nseg,
                int nmsgs = 1);
int tpu_send_packet(Runtime* rt, const std::shared_ptr<Conn>& c,
                    const uint8_t* const* bufs, const uint64_t* lens,
                    int nseg);

// send one TPUC ctrl frame: 9-byte header + body segments
int tpu_ctrl_send(Runtime* rt, const std::shared_ptr<Conn>& c, uint8_t ftype,
                  const uint8_t* const* body_bufs, const uint64_t* body_lens,
                  int nbody) {
  uint64_t body_len = 0;
  for (int i = 0; i < nbody; i++) body_len += body_lens[i];
  uint8_t hdr[kTpuHdrSize];
  memcpy(hdr, "TPUC", 4);
  hdr[4] = ftype;
  uint32_t be = htonl(uint32_t(body_len));
  memcpy(hdr + 5, &be, 4);
  if (nbody < 0 || nbody > 33) return DPE_PROTOCOL;
  const uint8_t* bufs[34];
  uint64_t lens[34];
  bufs[0] = hdr;
  lens[0] = kTpuHdrSize;
  for (int i = 0; i < nbody; i++) {
    bufs[i + 1] = body_bufs[i];
    lens[i + 1] = body_lens[i];
  }
  return conn_writev(rt, c, bufs, lens, nbody + 1);
}

void tpu_teardown(Conn* c) {
  TpuState* t = c->tpu.get();
  if (t == nullptr) return;
  {
    std::lock_guard<std::mutex> lk(t->cmu);
    t->closed = true;
  }
  t->ccv.notify_all();
  {
    // set the flag and notify UNDER qmu: a notify racing the sender's
    // predicate evaluation would otherwise be lost forever, pinning the
    // sender thread (and the conn + shm mappings it holds) for good
    std::lock_guard<std::mutex> lk(t->qmu);
    t->q_closed = true;
    for (auto& r : t->respq) free(r.base);
    t->respq.clear();
    t->qcv.notify_all();
  }
  {
    std::lock_guard<std::mutex> lk(t->hmu);
    if (!t->ready && t->err.empty()) t->err = "connection failed";
  }
  t->hcv.notify_all();
}

// Fail a connection: unregister, close, emit event, drop from table.
// Runs on the owning loop thread (writers route through loop_submit).
void sync_fail_conn(Runtime* rt, uint64_t conn_id, int err_class,
                    const char* reason);

void conn_fail(Runtime* rt, const std::shared_ptr<Conn>& c, int err_class,
               const char* reason) {
  static const bool h2dbg = getenv("DP_H2_DEBUG") != nullptr;
  if (h2dbg) {
    fprintf(stderr, "[dp] conn_fail id=%llu class=%d reason=%s h2=%d\n",
            (unsigned long long)c->id, err_class, reason ? reason : "",
            c->h2_mode);
  }
  bool expected = false;
  if (!c->failed.compare_exchange_strong(expected, true)) return;
  {
    // exclude in-flight writers before closing: a writev racing the close
    // could otherwise land on a recycled fd of a brand-new connection
    std::lock_guard<std::mutex> wlk(c->wmu);
    epoll_ctl(rt->loops[c->loop]->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
    close(c->fd);
    c->fd = -1;
  }
  tpu_teardown(c.get());
  if (c->tpu && c->zc_outstanding.load() > 0) {
    // a consumer still holds pointers into the pool — keep the mapping
    // alive past the conn (reclaimed at runtime shutdown)
    std::lock_guard<std::mutex> glk(rt->gmu);
    rt->tpu_graveyard.push_back(std::move(c->tpu));
  }
  emit_failed(rt, c.get(), err_class, reason);
  sync_fail_conn(rt, c->id, err_class, reason);
  std::lock_guard<std::mutex> lk(rt->cmu);
  rt->conns.erase(c->id);
}

// ----------------------------------------------------------------- writing
// dp_send core: claimed-writer inline vectored send, queue remainder, arm
// EPOLLOUT (reference Socket::StartWrite, socket.cpp:1692-1800). One packet
// = n segments (header/meta/payload/attachment refs from the IOBuf chain);
// the common case finishes in one writev with ZERO assembly copies.
int conn_writev(Runtime* rt, const std::shared_ptr<Conn>& c,
                const uint8_t* const* bufs, const uint64_t* lens, int nseg,
                int nmsgs) {
  uint64_t len = 0;
  for (int i = 0; i < nseg; i++) len += lens[i];
  if (c->failed.load()) return DPE_IO;
  std::lock_guard<std::mutex> lk(c->wmu);
  if (c->failed.load() || c->fd < 0) return DPE_IO;
  if (c->wq_bytes + len > kWriteQueueMax) return DPE_OVERCROWDED;
  uint64_t off = 0;  // bytes of the packet already on the wire
  if (c->wq.empty()) {
    iovec iov[64];
    while (off < len) {
      // rebuild the iov for the unwritten tail
      uint64_t skip = off;
      int iv = 0;
      for (int i = 0; i < nseg && iv < 64; i++) {
        if (skip >= lens[i]) {
          skip -= lens[i];
          continue;
        }
        iov[iv].iov_base = const_cast<uint8_t*>(bufs[i]) + skip;
        iov[iv].iov_len = size_t(lens[i] - skip);
        skip = 0;
        iv++;
      }
      ssize_t n = ::writev(c->fd, iov, iv);
      if (n > 0) {
        off += uint64_t(n);
      } else if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        break;
      } else if (n < 0 && errno == EINTR) {
        continue;
      } else {
        // hard error: the loop will observe it too; report now
        return DPE_IO;
      }
    }
    c->out_bytes.fetch_add(off, std::memory_order_relaxed);
  }
  if (off < len) {
    // assemble only the unwritten remainder
    std::string rest;
    rest.reserve(size_t(len - off));
    uint64_t skip = off;
    for (int i = 0; i < nseg; i++) {
      if (skip >= lens[i]) {
        skip -= lens[i];
        continue;
      }
      rest.append(reinterpret_cast<const char*>(bufs[i]) + skip,
                  size_t(lens[i] - skip));
      skip = 0;
    }
    c->wq_bytes += rest.size();
    c->wq.push_back(std::move(rest));
    if (!c->want_write) {
      c->want_write = true;
      arm(rt, c.get(), true);
    }
  }
  c->out_msgs.fetch_add(uint64_t(nmsgs), std::memory_order_relaxed);
  return DPE_OK;
}

int conn_write(Runtime* rt, const std::shared_ptr<Conn>& c,
               const uint8_t* data, uint64_t len) {
  const uint8_t* bufs[1] = {data};
  const uint64_t lens[1] = {len};
  return conn_writev(rt, c, bufs, lens, 1);
}

// EPOLLOUT drain on the loop thread (KeepWrite analog).
void conn_drain_writes(Runtime* rt, const std::shared_ptr<Conn>& c) {
  std::lock_guard<std::mutex> lk(c->wmu);
  if (c->failed.load() || c->fd < 0) return;
  while (!c->wq.empty()) {
    std::string& front = c->wq.front();
    size_t left = front.size() - c->wq_off;
    ssize_t n = ::send(c->fd, front.data() + c->wq_off, left, MSG_NOSIGNAL);
    if (n > 0) {
      c->out_bytes.fetch_add(uint64_t(n), std::memory_order_relaxed);
      c->wq_bytes -= uint64_t(n);
      c->wq_off += size_t(n);
      if (c->wq_off == front.size()) {
        c->wq.pop_front();
        c->wq_off = 0;
      }
    } else if (n < 0 && errno == EINTR) {
      continue;
    } else if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      return;  // stay armed
    } else {
      c->want_write = false;
      // fail from the loop thread after the lock is released
      loop_submit(rt, c->loop, [rt, c] { conn_fail(rt, c, DPE_IO, "send"); });
      return;
    }
  }
  c->want_write = false;
  arm(rt, c.get(), false);
}

// ----------------------------------------------------------------- parsing
// Accumulators for one parse pass: native echo responses coalesce into a
// handful of writev calls and delivered events into one queue push — on a
// single shared core, syscalls and lock round trips ARE the QPS ceiling
// (reference batches the same way: KeepWrite gathers up to 256 IOBufs,
// socket.cpp:1789; OnNewMessages NOSIGNAL-batches, input_messenger.cpp:194).
struct ParseBatch {
  std::vector<DpEvent> events;
  // (head, body-ref) pairs; heads in a deque so appends don't move them
  std::deque<std::string> heads;
  std::vector<std::pair<const uint8_t*, uint64_t>> segs;
  int nresp = 0;
};

// Flush echo responses + events. MUST run before the read buffer is
// compacted/stolen (segs reference it) and before any conn_fail/detach
// (frames precede EV_FAILED in the queue).
void flush_batch(Runtime* rt, const std::shared_ptr<Conn>& c, ParseBatch* b) {
  if (!b->segs.empty()) {
    size_t i = 0;
    bool wrote_err = false;
    while (i < b->segs.size() && !wrote_err) {
      const uint8_t* bufs[64];
      uint64_t lens[64];
      int n = 0;
      int msgs = 0;
      while (i < b->segs.size() && n + 2 <= 64) {
        bufs[n] = b->segs[i].first;
        lens[n] = b->segs[i].second;
        bufs[n + 1] = b->segs[i + 1].first;
        lens[n + 1] = b->segs[i + 1].second;
        n += 2;
        i += 2;
        msgs++;
      }
      int rc = conn_writev(rt, c, bufs, lens, n, msgs);
      if (rc != DPE_OK) {
        // a consumed request whose response can't go out leaves the
        // client hanging — the stream contract is broken, tear down
        loop_submit(rt, c->loop, [rt, c, rc] {
          conn_fail(rt, c, rc == DPE_OVERCROWDED ? DPE_OVERCROWDED : DPE_IO,
                    "native echo response undeliverable");
        });
        wrote_err = true;
      }
    }
    b->segs.clear();
    b->heads.clear();
    b->nresp = 0;
  }
  push_event_batch(rt, b->events);
}

Runtime::EchoSvc* echo_match(Runtime* rt, int lid, const MetaLite& m) {
  if (lid < 0) return nullptr;
  std::lock_guard<std::mutex> lk(rt->rmu);
  for (auto& sm : rt->echo_services) {
    if (sm->lid == lid && sm->service == m.service &&
        sm->method == m.method) {
      return sm.get();  // registry only grows; entries are stable
    }
  }
  return nullptr;
}

// brpc_tpu/rpc/errors.py mirrors (native admission responses)
constexpr int32_t kElogoff = 1011;
constexpr int32_t kElimit = 1012;

// Native request-path admission + method status (reference
// MethodStatus::OnRequested, baidu_rpc_protocol.cpp:661-712).
struct EchoAdmit {
  Runtime::EchoSvc* svc = nullptr;
  int64_t t0 = 0;
  int32_t ecode = 0;
  const char* etext = "";
  bool counted = false;
};

// False: not a registered native service (frame goes to Python). True:
// admission ran; a->ecode holds the rejection (0 = admitted).
bool echo_admit(Runtime* rt, Conn* c, const MetaLite& m, EchoAdmit* a) {
  if (!c->is_server || !m.has_request || m.has_response || m.compress_type ||
      m.checksum || m.has_stream_settings || m.has_auth) {
    return false;
  }
  a->svc = echo_match(rt, c->listener_id, m);
  if (a->svc == nullptr) return false;
  a->t0 = mono_ns();
  a->svc->requests.fetch_add(1, std::memory_order_relaxed);
  if (a->svc->logoff.load(std::memory_order_relaxed)) {
    a->ecode = kElogoff;
    a->etext = "server is stopping";
  } else if (a->svc->max_concurrency) {
    int32_t cur = a->svc->concurrency.fetch_add(
                      1, std::memory_order_relaxed) + 1;
    if (cur > a->svc->max_concurrency) {
      a->svc->concurrency.fetch_sub(1, std::memory_order_relaxed);
      a->ecode = kElimit;
      a->etext = "method concurrency limit";
    } else {
      a->counted = true;
    }
  }
  return true;
}

void echo_settle(EchoAdmit* a) {
  if (a->counted) {
    a->svc->concurrency.fetch_sub(1, std::memory_order_relaxed);
  }
  if (a->ecode) a->svc->errors.fetch_add(1, std::memory_order_relaxed);
  uint64_t dt = uint64_t(mono_ns() - a->t0);
  a->svc->latency_sum_ns.fetch_add(dt, std::memory_order_relaxed);
  uint64_t prev = a->svc->latency_max_ns.load(std::memory_order_relaxed);
  while (dt > prev &&
         !a->svc->latency_max_ns.compare_exchange_weak(prev, dt)) {
  }
}

// Queue a tunnel response on the per-conn sender worker (NEVER send from
// the loop thread: tpu_send_packet may wait for credit ACKs that only the
// loop can deliver). Spawns the worker on first use; ts is captured by
// value — conn_fail may move the TpuState into the graveyard, but the
// object itself stays alive.
void tpu_enqueue_resp(Runtime* rt, const std::shared_ptr<Conn>& c,
                      TpuState* ts, TpuState::Resp&& resp) {
  {
    std::lock_guard<std::mutex> lk(ts->qmu);
    ts->respq.push_back(std::move(resp));
    if (!ts->sender_running) {
      ts->sender_running = true;
      auto done = std::make_shared<std::atomic<bool>>(false);
      std::thread thr([rt, c, ts, done] {
        for (;;) {
          TpuState::Resp item;
          {
            std::unique_lock<std::mutex> qlk(ts->qmu);
            ts->qcv.wait(qlk, [ts, &c] {
              return !ts->respq.empty() || ts->q_closed ||
                     c->failed.load();
            });
            if (ts->respq.empty()) {  // closed/failed: drain done
              done->store(true);
              return;
            }
            item = std::move(ts->respq.front());
            ts->respq.pop_front();
          }
          int rc;
          if (!item.segs.empty()) {
            // zero-copy echo: head + pool-block segments, then the ACK
            // returning those blocks (never before — the peer may reuse
            // them the instant the credit lands)
            std::vector<const uint8_t*> bb(item.segs.size() + 1);
            std::vector<uint64_t> ll(item.segs.size() + 1);
            bb[0] = reinterpret_cast<const uint8_t*>(item.head.data());
            ll[0] = item.head.size();
            for (size_t si = 0; si < item.segs.size(); si++) {
              bb[si + 1] = item.segs[si].first;
              ll[si + 1] = item.segs[si].second;
            }
            rc = tpu_send_packet(rt, c, bb.data(), ll.data(),
                                 int(bb.size()));
          } else {
            const uint8_t* bb[2] = {
                reinterpret_cast<const uint8_t*>(item.head.data()),
                item.body};
            const uint64_t ll[2] = {item.head.size(), item.blen};
            rc = tpu_send_packet(rt, c, bb, ll, 2);
          }
          if (rc == DPE_OK && !item.ack.empty()) {
            // the donated blocks go back on EVERY outcome that keeps the
            // conn alive — an admission-rejected request (segs empty, no
            // body echoed) must still return the peer's credits
            const uint8_t* ab[1] = {
                reinterpret_cast<const uint8_t*>(item.ack.data())};
            const uint64_t al[1] = {item.ack.size()};
            rc = tpu_ctrl_send(rt, c, TFT_ACK, ab, al, 1);
          }
          free(item.base);
          if (rc != DPE_OK) {
            if (rt->running.load()) {
              loop_submit(rt, c->loop, [rt, c] {
                conn_fail(rt, c, DPE_IO,
                          "native service response undeliverable");
              });
            }
            done->store(true);
            return;
          }
        }
      });
      register_sender(rt, std::move(thr), done);
    }
  }
  ts->qcv.notify_one();
}

std::string echo_response_head(const MetaLite& m, const EchoAdmit& a,
                               uint64_t body_len) {
  std::string meta = a.ecode
      ? build_response_meta(m.correlation_id, m.attempt_version, a.ecode,
                            a.etext, strlen(a.etext), 0)
      : build_echo_response_meta(m);
  std::string head;
  head.reserve(kHeaderSize + meta.size());
  put_trpc_header(&head, meta.size(), a.ecode ? 0 : body_len);
  head.append(meta);
  return head;
}

// Answer a registered echo request natively, running the full native
// request path: admission (logoff, per-method concurrency limit) +
// method status (qps/latency/errors) + user code (echo) + response pack.
// Returns false if the frame should go to Python instead.
bool try_native_echo(Runtime* rt, const std::shared_ptr<Conn>& c,
                     const MetaLite& m, const uint8_t* body,
                     uint64_t body_len, RBuf* whole_buf, ParseBatch* batch) {
  if (m.attachment_size > body_len) return false;
  EchoAdmit admit;
  if (!echo_admit(rt, c.get(), m, &admit)) return false;
  int32_t ecode = admit.ecode;
  auto settle = [&](bool) { echo_settle(&admit); };
  if (ecode) body_len = 0;  // admission rejections carry no body
  std::string head = echo_response_head(m, admit, body_len);
  // body still points into the conn's read buffer: conn_writev either puts
  // it on the wire or copies the remainder before returning, so the
  // zero-assembly reference is safe
  if (c->tpu_mode != 0) {
    // NEVER send from the loop thread: tpu_send_packet may wait for
    // credit ACKs that only this thread can deliver. One per-conn sender
    // worker drains responses in order; a send failure fails the conn
    // (a consumed request must never be silently dropped).
    TpuState* t = c->tpu.get();
    if (t == nullptr) return false;
    TpuState::Resp resp;
    resp.head = std::move(head);
    if (whole_buf != nullptr && body_len >= (64 << 10)) {
      // the stream buffer holds exactly this one frame: donate it to the
      // sender instead of copying the body (single-core: copies are
      // serial wall-clock)
      resp.base = whole_buf->data;
      resp.body = body;
      resp.blen = body_len;
      whole_buf->data = nullptr;
      whole_buf->cap = 0;
      whole_buf->size = 0;
    } else {
      resp.base = static_cast<uint8_t*>(malloc(body_len ? body_len : 1));
      memcpy(resp.base, body, body_len);
      resp.body = resp.base;
      resp.blen = body_len;
    }
    tpu_enqueue_resp(rt, c, t, std::move(resp));
    settle(ecode != 0);
    return true;
  }
  // TCP lane: accumulate; the whole parse pass flushes in a few writevs
  // (bodies point into the conn's read buffer, stable until flush)
  batch->heads.push_back(std::move(head));
  const std::string& h = batch->heads.back();
  batch->segs.emplace_back(reinterpret_cast<const uint8_t*>(h.data()),
                           h.size());
  batch->segs.emplace_back(body, body_len);
  batch->nresp++;
  settle(ecode != 0);
  return true;
}

// Zero-copy consumption of one DATA frame whose pool blocks hold exactly
// one complete TRPC frame (the common bulk-transfer shape: one message
// per DATA frame once the window is negotiated). Two routes skip the
// stream-reassembly copy entirely (reference rdma zero-copy recv —
// blocks attach straight to the IOBuf, block_pool.cpp):
//   - native echo: respond straight FROM the blocks, ACK after the send
//   - client response on a fast conn: EV_RESPONSE_ZC hands the consumer
//     segment views + the ACK blob (dp_tpu_ack returns the credits)
// Returns true when fully handled; false -> caller takes the copy path.
bool tpu_try_zero_copy(Runtime* rt, const std::shared_ptr<Conn>& c,
                       TpuState* t, const uint8_t* body, uint32_t nsegs) {
  struct Seg {
    const uint8_t* p;
    uint32_t len;
    uint32_t idx;
  };
  if (nsegs > 64) return false;
  Seg segs[64];
  uint64_t total = 0;
  const uint8_t* sp = body + 8;
  for (uint32_t i = 0; i < nsegs; i++) {
    uint32_t idx = ntohl(*reinterpret_cast<const uint32_t*>(sp + i * 8));
    uint32_t ln = ntohl(*reinterpret_cast<const uint32_t*>(sp + i * 8 + 4));
    if (idx >= t->bc || ln > t->bs || ln == 0) return false;
    segs[i] = {t->pool + size_t(idx) * t->bs, ln, idx};
    total += ln;
  }
  if (segs[0].len < kHeaderSize) return false;
  const uint8_t* h = segs[0].p;
  if (memcmp(h, "TRPC", 4) != 0) return false;  // TSTR: copy path
  uint64_t meta_size = ntohl(*reinterpret_cast<const uint32_t*>(h + 4));
  uint64_t body_size = ntohl(*reinterpret_cast<const uint32_t*>(h + 8));
  if (kHeaderSize + meta_size + body_size != total) return false;
  if (kHeaderSize + meta_size > segs[0].len) return false;  // meta split
  if (meta_size + body_size > rt->max_body) return false;
  MetaLite m;
  if (!parse_meta_lite(h + kHeaderSize, h + kHeaderSize + meta_size, &m)) {
    return false;  // copy path surfaces the protocol error
  }
  if (m.attachment_size > body_size) return false;
  // payload views: bytes after header+meta, spanning the blocks
  std::vector<std::pair<const uint8_t*, uint64_t>> views;
  uint64_t skip = kHeaderSize + meta_size;
  for (uint32_t i = 0; i < nsegs; i++) {
    if (skip >= segs[i].len) {
      skip -= segs[i].len;
      continue;
    }
    views.emplace_back(segs[i].p + skip, uint64_t(segs[i].len) - skip);
    skip = 0;
  }
  // the ACK returning exactly these blocks
  std::string ack;
  ack.resize(4 + size_t(nsegs) * 4);
  uint32_t n_be = htonl(nsegs);
  memcpy(&ack[0], &n_be, 4);
  for (uint32_t i = 0; i < nsegs; i++) {
    uint32_t idx_be = htonl(segs[i].idx);
    memcpy(&ack[4 + size_t(i) * 4], &idx_be, 4);
  }
  // route 1: native echo — reply straight from the blocks
  EchoAdmit admit;
  if (echo_admit(rt, c.get(), m, &admit)) {
    c->in_msgs.fetch_add(1, std::memory_order_relaxed);
    TpuState::Resp resp;
    resp.head = echo_response_head(m, admit, body_size);
    if (!admit.ecode) resp.segs = std::move(views);
    resp.ack = std::move(ack);
    tpu_enqueue_resp(rt, c, t, std::move(resp));
    echo_settle(&admit);
    return true;
  }
  // route 2: client-side response on a fast conn — deliver views + ack
  if (!c->is_server && c->py_fast.load(std::memory_order_relaxed) &&
      m.has_response && !m.has_request && !m.compress_type && !m.checksum &&
      !m.has_stream_settings && !m.has_auth) {
    c->in_msgs.fetch_add(1, std::memory_order_relaxed);
    size_t et = m.resp_error_text.size();
    size_t need = sizeof(RespLite) + 4 + views.size() * 16 + 4 +
                  ack.size() + et;
    uint8_t* blk = static_cast<uint8_t*>(malloc(need ? need : 1));
    RespLite rl{};
    rl.attempt = m.attempt_version;
    rl.att_size = m.attachment_size;
    memcpy(blk, &rl, sizeof(rl));
    uint8_t* w = blk + sizeof(rl);
    uint32_t nv = uint32_t(views.size());
    memcpy(w, &nv, 4);
    w += 4;
    for (auto& v : views) {
      uint64_t p = reinterpret_cast<uint64_t>(v.first);
      memcpy(w, &p, 8);
      memcpy(w + 8, &v.second, 8);
      w += 16;
    }
    uint32_t alen = uint32_t(ack.size());
    memcpy(w, &alen, 4);
    w += 4;
    memcpy(w, ack.data(), ack.size());
    w += ack.size();
    memcpy(w, m.resp_error_text.data(), et);
    DpEvent ev{};
    ev.kind = EV_RESPONSE_ZC;
    ev.tag = int32_t(m.resp_error_code);
    ev.conn_id = c->id;
    ev.aux = int64_t(m.correlation_id);
    ev.base = blk;
    ev.meta = blk;
    ev.meta_len = need;
    ev.body = nullptr;
    ev.body_len = body_size;  // informational: total payload bytes
    c->zc_outstanding.fetch_add(1, std::memory_order_relaxed);
    push_event(rt, ev);
    return true;
  }
  return false;  // anything else: the copy path handles it
}

// Detach: hand the fd + buffered bytes to Python (non-TRPC protocol on a
// native port — http dashboard, grpc, redis... take over seamlessly).
void conn_detach(Runtime* rt, const std::shared_ptr<Conn>& c,
                 const std::string* prefix = nullptr) {
  bool expected = false;
  if (!c->failed.compare_exchange_strong(expected, true)) return;
  int fd;
  {
    std::lock_guard<std::mutex> wlk(c->wmu);
    c->detached = true;
    epoll_ctl(rt->loops[c->loop]->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
    fd = c->fd;
    c->fd = -1;  // ownership transfers to the consumer via the event
  }
  // prefix: bytes already consumed by a protocol sniff (h2 preface +
  // pre-decision frames) — replayed so the Python stack starts from a
  // pristine byte stream
  size_t plen = prefix ? prefix->size() : 0;
  size_t left = c->rbuf.size - c->rpos;
  uint8_t* blk =
      static_cast<uint8_t*>(malloc((plen + left) ? (plen + left) : 1));
  if (plen) memcpy(blk, prefix->data(), plen);
  memcpy(blk + plen, c->rbuf.data + c->rpos, left);
  DpEvent ev{};
  ev.kind = EV_DETACHED;
  ev.tag = 0;
  ev.conn_id = c->id;
  ev.aux = fd;
  ev.base = blk;
  ev.meta = blk;
  ev.meta_len = plen + left;
  push_event(rt, ev);
  std::lock_guard<std::mutex> lk(rt->cmu);
  rt->conns.erase(c->id);
}

// ---- sync-waiter completion (dp_call_sync)
SyncWaiter* sync_take(Runtime* rt, uint64_t cid) {
  std::lock_guard<std::mutex> lk(rt->swmu);
  auto it = rt->sync_waiters.find(cid);
  if (it == rt->sync_waiters.end()) return nullptr;
  SyncWaiter* w = it->second;
  rt->sync_waiters.erase(it);
  return w;
}

// Conn-scoped take: a response only completes a waiter parked on ITS
// connection (cids are process-unique, but a buggy/malicious peer could
// echo a guessed cid — without this check it would complete another
// channel's call with foreign bytes).
SyncWaiter* sync_take_conn(Runtime* rt, uint64_t cid, uint64_t conn_id) {
  std::lock_guard<std::mutex> lk(rt->swmu);
  auto it = rt->sync_waiters.find(cid);
  if (it == rt->sync_waiters.end()) return nullptr;
  if (it->second->conn_id != conn_id) return nullptr;
  SyncWaiter* w = it->second;
  rt->sync_waiters.erase(it);
  return w;
}

// After notify, the completer must not touch w again: the waiter owns the
// storage (stack frame) and frees it once it re-acquires w->mu and sees
// done. Holding mu across the notify makes that handoff safe.
void sync_complete(SyncWaiter* w, int32_t code, uint64_t attempt,
                   uint64_t att_size, const char* etext, size_t elen,
                   uint8_t* base, uint8_t* body, uint64_t blen) {
  std::lock_guard<std::mutex> lk(w->mu);
  w->code = code;
  w->attempt = attempt;
  w->att_size = att_size;
  if (elen) w->etext.assign(etext, elen);
  w->base = base;
  w->body = body;
  w->body_len = blen;
  w->done = true;
  w->cv.notify_one();
}

// Wake every sync waiter parked on a failing conn (transport error).
void sync_fail_conn(Runtime* rt, uint64_t conn_id, int err_class,
                    const char* reason) {
  std::vector<SyncWaiter*> hit;
  {
    std::lock_guard<std::mutex> lk(rt->swmu);
    for (auto it = rt->sync_waiters.begin();
         it != rt->sync_waiters.end();) {
      if (it->second->conn_id == conn_id) {
        hit.push_back(it->second);
        it = rt->sync_waiters.erase(it);
      } else {
        ++it;
      }
    }
  }
  for (auto* w : hit) {
    std::lock_guard<std::mutex> lk(w->mu);
    w->terr = err_class ? err_class : DPE_IO;
    if (reason) w->etext.assign(reason);
    w->done = true;
    w->cv.notify_one();
  }
}

// Parsed fast-path event builders (meta struct + names/text + body in ONE
// allocation — dp_free stays a single free()).
void batch_fast_request(ParseBatch* b, Conn* c, const MetaLite& m,
                        const uint8_t* body, uint64_t body_len) {
  size_t hdr = sizeof(ReqLite) + m.service.size() + m.method.size();
  uint8_t* blk = static_cast<uint8_t*>(malloc(hdr + body_len + 1));
  ReqLite rl{};
  rl.cid = m.correlation_id;
  rl.attempt = m.attempt_version;
  rl.att_size = m.attachment_size;
  rl.log_id = m.log_id;
  rl.trace_id = m.trace_id;
  rl.span_id = m.span_id;
  rl.timeout_ms = int32_t(m.timeout_ms);
  rl.svc_len = uint16_t(m.service.size());
  rl.meth_len = uint16_t(m.method.size());
  memcpy(blk, &rl, sizeof(rl));
  memcpy(blk + sizeof(rl), m.service.data(), m.service.size());
  memcpy(blk + sizeof(rl) + m.service.size(), m.method.data(),
         m.method.size());
  memcpy(blk + hdr, body, body_len);
  DpEvent ev{};
  ev.kind = EV_REQUEST;
  ev.conn_id = c->id;
  ev.aux = int64_t(m.correlation_id);
  ev.base = blk;
  ev.meta = blk;
  ev.meta_len = hdr;
  ev.body = blk + hdr;
  ev.body_len = body_len;
  b->events.push_back(ev);
}

void batch_fast_response(ParseBatch* b, Conn* c, const MetaLite& m,
                         const uint8_t* body, uint64_t body_len) {
  size_t hdr = sizeof(RespLite) + m.resp_error_text.size();
  uint8_t* blk = static_cast<uint8_t*>(malloc(hdr + body_len + 1));
  RespLite rl{};
  rl.attempt = m.attempt_version;
  rl.att_size = m.attachment_size;
  memcpy(blk, &rl, sizeof(rl));
  memcpy(blk + sizeof(rl), m.resp_error_text.data(),
         m.resp_error_text.size());
  memcpy(blk + hdr, body, body_len);
  DpEvent ev{};
  ev.kind = EV_RESPONSE;
  ev.tag = int32_t(m.resp_error_code);
  ev.conn_id = c->id;
  ev.aux = int64_t(m.correlation_id);
  ev.base = blk;
  ev.meta = blk;
  ev.meta_len = hdr;
  ev.body = blk + hdr;
  ev.body_len = body_len;
  b->events.push_back(ev);
}

// Cut complete TRPC/TSTR frames out of (buf, pos) — the wire buffer for
// plain conns, the reassembled tunnel stream for TPUC conns.
void cut_trpc(Runtime* rt, const std::shared_ptr<Conn>& c, RBuf& buf,
              size_t& pos, bool allow_detach) {
  ParseBatch batch;
  bool fast = c->py_fast.load(std::memory_order_relaxed);
  for (;;) {
    size_t avail = buf.size - pos;
    if (avail < kHeaderSize) break;
    const uint8_t* p = buf.data + pos;
    bool is_trpc = memcmp(p, "TRPC", 4) == 0;
    bool is_tstr = !is_trpc && memcmp(p, "TSTR", 4) == 0;
    if (!is_trpc && !is_tstr) {
      flush_batch(rt, c, &batch);  // frames precede the detach/fail event
      if (allow_detach) {
        conn_detach(rt, c);
      } else {
        conn_fail(rt, c, DPE_PROTOCOL, "garbage in tunnel stream");
      }
      return;
    }
    uint32_t meta_size = ntohl(*reinterpret_cast<const uint32_t*>(p + 4));
    uint32_t body_size = ntohl(*reinterpret_cast<const uint32_t*>(p + 8));
    uint64_t total = uint64_t(meta_size) + body_size;
    if (total > rt->max_body) {
      flush_batch(rt, c, &batch);
      conn_fail(rt, c, DPE_PROTOCOL, "frame exceeds max_body");
      return;
    }
    if (avail < kHeaderSize + total) break;
    const uint8_t* meta = p + kHeaderSize;
    const uint8_t* body = meta + meta_size;
    c->in_msgs.fetch_add(1, std::memory_order_relaxed);
    bool handled = false;
    bool whole = (pos == 0 && kHeaderSize + total == buf.size);
    MetaLite m;
    bool meta_ok = false;
    if (is_trpc) {
      if (parse_meta_lite(meta, meta + meta_size, &m)) {
        meta_ok = true;
        handled = try_native_echo(rt, c, m, body, body_size,
                                  whole ? &buf : nullptr, &batch);
        if (handled && buf.data == nullptr) {
          pos = 0;  // the echo stole the buffer (tpu lane, single frame:
                    // batch is necessarily empty of body refs)
          flush_batch(rt, c, &batch);
          return;
        }
        if (c->failed.load()) {  // tpu-lane echo enqueue tore it down
          flush_batch(rt, c, &batch);
          return;
        }
      } else {
        flush_batch(rt, c, &batch);
        conn_fail(rt, c, DPE_PROTOCOL, "bad RpcMeta");
        return;
      }
    }
    if (!handled) {
      // a Python thread parked in dp_call_sync for this cid? complete it
      // right here on the parse thread — no event queue, no GIL. Only
      // plain responses (no compress/checksum/stream riders) finish
      // natively; anything else falls through to the EV_FRAME path and
      // the Python fallback completes the waiter via dp_sync_complete_py.
      if (is_trpc && meta_ok && !c->is_server && m.has_response &&
          !m.has_request && !m.compress_type && !m.checksum &&
          !m.has_stream_settings && m.attachment_size <= body_size) {
        SyncWaiter* w = sync_take_conn(rt, m.correlation_id, c->id);
        if (w != nullptr) {
          if (whole && total >= kFastFrameMax) {
            // steal the read buffer like the EV_FRAME donation path:
            // megabyte responses reach the sync caller with ZERO copies
            uint8_t* base = buf.data;
            uint8_t* bp = buf.data + kHeaderSize + meta_size;
            buf.data = nullptr;
            buf.cap = 0;
            buf.size = 0;
            pos = 0;
            flush_batch(rt, c, &batch);
            sync_complete(w, int32_t(m.resp_error_code),
                          m.attempt_version, m.attachment_size,
                          m.resp_error_text.data(),
                          m.resp_error_text.size(), base, bp, body_size);
            return;
          }
          uint8_t* blk = nullptr;
          if (body_size) {
            blk = static_cast<uint8_t*>(malloc(body_size));
            memcpy(blk, body, body_size);
          }
          sync_complete(w, int32_t(m.resp_error_code), m.attempt_version,
                        m.attachment_size, m.resp_error_text.data(),
                        m.resp_error_text.size(), blk, blk, body_size);
          pos += kHeaderSize + total;
          continue;
        }
      }
      // BIG fast-eligible server requests skip the EV_FRAME donation and
      // ride the parsed fast path too (VERDICT r3 #6): one native memcpy
      // here (GIL-free) replaces the Python-side pb meta parse + IOBuf
      // split/pack copies of the full pipeline, and the response returns
      // through dp_respond's zero-copy writev. Pooled bulk conns then
      // only serialize on the two unavoidable Python copies.
      if (fast && is_trpc && meta_ok && c->is_server && m.has_request &&
          !m.has_response && !m.compress_type && !m.checksum &&
          !m.has_stream_settings && !m.has_auth &&
          m.attachment_size <= body_size) {
        batch_fast_request(&batch, c.get(), m, body, body_size);
        pos += kHeaderSize + total;
        continue;
      }
      if (whole && total >= kFastFrameMax) {
        // the buffer holds exactly this one large frame: hand the WHOLE
        // buffer to the consumer instead of memcpy'ing megabytes — the
        // dominant copy on the delivery path (this machine is single-core;
        // every copy is serial wall-clock)
        DpEvent ev{};
        ev.kind = EV_FRAME;
        ev.tag = is_tstr ? 1 : 0;
        ev.conn_id = c->id;
        ev.base = buf.data;
        ev.meta = buf.data + kHeaderSize;
        ev.meta_len = meta_size;
        ev.body = buf.data + kHeaderSize + meta_size;
        ev.body_len = body_size;
        buf.data = nullptr;
        buf.cap = 0;
        buf.size = 0;
        pos = 0;
        batch.events.push_back(ev);
        flush_batch(rt, c, &batch);
        return;
      }
      // parsed fast-path events: Python receives pre-cracked meta fields
      // and never runs protobuf on the hot path. Anything with policy
      // riding the meta (compress, checksum, auth, streams) takes the
      // full EV_FRAME path; trace ids ride ReqLite natively. (Server
      // requests of EVERY size were already taken above.)
      if (fast && is_trpc && meta_ok && !m.compress_type && !m.checksum &&
          !m.has_stream_settings && !m.has_auth &&
          m.attachment_size <= body_size &&
          !c->is_server && m.has_response && !m.has_request) {
        batch_fast_response(&batch, c.get(), m, body, body_size);
        pos += kHeaderSize + total;
        continue;
      }
      uint8_t* blk = static_cast<uint8_t*>(
          malloc(uint64_t(meta_size) + body_size + 1));
      memcpy(blk, meta, meta_size);
      memcpy(blk + meta_size, body, body_size);
      DpEvent ev{};
      ev.kind = EV_FRAME;
      ev.tag = is_tstr ? 1 : 0;
      ev.conn_id = c->id;
      ev.base = blk;
      ev.meta = blk;
      ev.meta_len = meta_size;
      ev.body = blk + meta_size;
      ev.body_len = body_size;
      batch.events.push_back(ev);
    }
    pos += kHeaderSize + total;
  }
  flush_batch(rt, c, &batch);  // before compaction: segs reference buf
  // compact
  if (pos == buf.size) {
    buf.size = 0;
    pos = 0;
  } else if (pos > (1 << 20)) {
    memmove(buf.data, buf.data + pos, buf.size - pos);
    buf.size -= pos;
    pos = 0;
  }
}

// ---- TPUC tunnel frame processing (reference RdmaEndpoint recv path:
// blocks -> reassembled stream -> the SAME message cutter as TCP,
// input_messenger.cpp:416)
void tpu_handle_hello(Runtime* rt, const std::shared_ptr<Conn>& c,
                      const std::string& body);
void tpu_handle_hello_ack(Runtime* rt, const std::shared_ptr<Conn>& c,
                          const std::string& body);

void tpu_parse(Runtime* rt, const std::shared_ptr<Conn>& c) {
  RBuf& buf = c->rbuf;
  TpuState* t = c->tpu.get();
  for (;;) {
    size_t avail = buf.size - c->rpos;
    if (avail < kTpuHdrSize) break;
    const uint8_t* p = buf.data + c->rpos;
    if (memcmp(p, "TPUC", 4) != 0) {
      conn_fail(rt, c, DPE_PROTOCOL, "bad tunnel magic");
      return;
    }
    uint8_t ftype = p[4];
    uint32_t blen = ntohl(*reinterpret_cast<const uint32_t*>(p + 5));
    if (ftype < TFT_HELLO || ftype > TFT_BYE || blen > (32u << 20)) {
      conn_fail(rt, c, DPE_PROTOCOL, "bad tunnel frame");
      return;
    }
    if (avail < kTpuHdrSize + blen) break;
    const uint8_t* body = p + kTpuHdrSize;
    switch (ftype) {
      case TFT_HELLO:
        tpu_handle_hello(rt, c, std::string(
            reinterpret_cast<const char*>(body), blen));
        break;
      case TFT_HELLO_ACK:
        tpu_handle_hello_ack(rt, c, std::string(
            reinterpret_cast<const char*>(body), blen));
        break;
      case TFT_DATA: {
        if (blen < 8) {
          conn_fail(rt, c, DPE_PROTOCOL, "short DATA frame");
          return;
        }
        uint32_t inline_len = ntohl(*reinterpret_cast<const uint32_t*>(body));
        uint32_t nsegs = ntohl(*reinterpret_cast<const uint32_t*>(body + 4));
        if (8 + uint64_t(inline_len) + uint64_t(nsegs) * 8 > blen ||
            nsegs > 4096) {
          conn_fail(rt, c, DPE_PROTOCOL, "bad DATA frame");
          return;
        }
        if (inline_len == 0 && nsegs > 0 && c->sbuf.size == c->spos &&
            t != nullptr && t->pool != nullptr &&
            tpu_try_zero_copy(rt, c, t, body, nsegs)) {
          if (c->failed.load()) return;
          c->rpos += kTpuHdrSize + blen;
          continue;  // consumed without touching the stream buffer
        }
        if (inline_len) {
          memcpy(c->sbuf.tail(inline_len), body + 8, inline_len);
          c->sbuf.size += inline_len;
        }
        if (nsegs) {
          // presize the reassembled stream to the frame being built: the
          // stream head names its total length (TRPC/TSTR header)
          size_t shave = c->sbuf.size - c->spos;
          if (shave >= kHeaderSize) {
            const uint8_t* sp = c->sbuf.data + c->spos;
            if (!memcmp(sp, "TRPC", 4) || !memcmp(sp, "TSTR", 4)) {
              uint64_t ftotal = kHeaderSize +
                  uint64_t(ntohl(*reinterpret_cast<const uint32_t*>(
                      sp + 4))) +
                  uint64_t(ntohl(*reinterpret_cast<const uint32_t*>(
                      sp + 8)));
              if (ftotal <= rt->max_body + kHeaderSize) {
                c->sbuf.reserve(c->spos + ftotal);
              }
            }
          }
          // copy the peer-written registered blocks into the stream, then
          // return the credits (reference explicit-ACK sliding window)
          std::string ack;
          ack.resize(4 + size_t(nsegs) * 4);
          uint32_t n_be = htonl(nsegs);
          memcpy(&ack[0], &n_be, 4);
          const uint8_t* sp = body + 8 + inline_len;
          for (uint32_t i = 0; i < nsegs; i++) {
            uint32_t idx = ntohl(*reinterpret_cast<const uint32_t*>(
                sp + i * 8));
            uint32_t ln = ntohl(*reinterpret_cast<const uint32_t*>(
                sp + i * 8 + 4));
            if (t == nullptr || t->pool == nullptr || idx >= t->bc ||
                ln > t->bs) {
              conn_fail(rt, c, DPE_PROTOCOL, "bad block ref");
              return;
            }
            memcpy(c->sbuf.tail(ln), t->pool + size_t(idx) * t->bs, ln);
            c->sbuf.size += ln;
            uint32_t idx_be = htonl(idx);
            memcpy(&ack[4 + size_t(i) * 4], &idx_be, 4);
          }
          const uint8_t* ab[1] = {
              reinterpret_cast<const uint8_t*>(ack.data())};
          const uint64_t al[1] = {ack.size()};
          if (tpu_ctrl_send(rt, c, TFT_ACK, ab, al, 1) != DPE_OK) {
            conn_fail(rt, c, DPE_IO, "ACK send failed");
            return;
          }
        }
        break;
      }
      case TFT_ACK: {
        if (blen < 4) break;
        uint32_t n = ntohl(*reinterpret_cast<const uint32_t*>(body));
        if (4 + uint64_t(n) * 4 > blen) break;
        if (t != nullptr) {
          {
            std::lock_guard<std::mutex> lk(t->cmu);
            for (uint32_t i = 0; i < n; i++) {
              uint32_t idx = ntohl(*reinterpret_cast<const uint32_t*>(
                  body + 4 + size_t(i) * 4));
              // only blocks actually in flight earn a credit back:
              // replayed/forged ACKs must not inflate the window or hand
              // a block to two writers at once
              if (idx < t->peer_bc && idx < t->inflight.size() &&
                  t->inflight[idx]) {
                t->inflight[idx] = 0;
                t->credits.push_back(idx);
              }
            }
          }
          t->ccv.notify_all();
        }
        break;
      }
      case TFT_BYE:
        conn_fail(rt, c, DPE_EOF, "peer sent BYE");
        return;
    }
    if (c->failed.load()) return;
    c->rpos += kTpuHdrSize + blen;
  }
  // compact the wire buffer
  if (c->rpos == buf.size) {
    buf.size = 0;
    c->rpos = 0;
  } else if (c->rpos > (1 << 20)) {
    memmove(buf.data, buf.data + c->rpos, buf.size - c->rpos);
    buf.size -= c->rpos;
    c->rpos = 0;
  }
  // cut RPC messages from the reassembled stream — same cutter as TCP
  cut_trpc(rt, c, c->sbuf, c->spos, /*allow_detach=*/false);
}

// --------------------------------------------------------- h2 parse side
int flush_conn_pending(Runtime* rt, const std::shared_ptr<Conn>& c);
void queue_packet(Runtime* rt, const std::shared_ptr<Conn>& c,
                  const std::string& head, const uint8_t* payload,
                  uint64_t plen, const uint8_t* att, uint64_t alen);

// EV_REQUEST for a grpc stream — same packed layout as
// batch_fast_request, pushed directly (h2 frames are not batch-cut).
// ``strip``: stream whose inbound buffers are dropped BEFORE the event
// is pushed — the instant the poller can see the event it may respond
// and erase the stream node, so the parse loop must not touch it after.
void h2_push_request_event(Runtime* rt, Conn* c, const MetaLite& m,
                           const uint8_t* body, uint64_t body_len,
                           H2Stream* strip) {
  size_t hdr = sizeof(ReqLite) + m.service.size() + m.method.size();
  uint8_t* blk = static_cast<uint8_t*>(malloc(hdr + body_len + 1));
  ReqLite rl{};
  rl.cid = m.correlation_id;
  rl.attempt = 0;
  rl.att_size = 0;
  rl.log_id = 0;
  rl.trace_id = 0;
  rl.span_id = 0;
  rl.timeout_ms = int32_t(m.timeout_ms);
  rl.svc_len = uint16_t(m.service.size());
  rl.meth_len = uint16_t(m.method.size());
  memcpy(blk, &rl, sizeof(rl));
  memcpy(blk + sizeof(rl), m.service.data(), m.service.size());
  memcpy(blk + sizeof(rl) + m.service.size(), m.method.data(),
         m.method.size());
  memcpy(blk + hdr, body, body_len);
  if (strip != nullptr) {  // body was just copied; see the contract above
    strip->data.clear();
    strip->data.shrink_to_fit();
    strip->headers.clear();
  }
  DpEvent ev{};
  ev.kind = EV_REQUEST;
  ev.conn_id = c->id;
  ev.aux = int64_t(m.correlation_id);
  ev.base = blk;
  ev.meta = blk;
  ev.meta_len = hdr;
  ev.body = blk + hdr;
  ev.body_len = body_len;
  push_event(rt, ev);
}

// Client-side completion: a response stream finished (trailers or
// headers-only reply). Completes the parked sync waiter, else pushes
// EV_RESPONSE with the batch_fast_response layout.
void h2_client_complete(Runtime* rt, const std::shared_ptr<Conn>& c,
                        uint32_t sid) {
  H2State* h = c->h2.get();
  H2Stream st;
  {
    std::lock_guard<std::mutex> lk(h->mu);
    auto it = h->streams.find(sid);
    if (it == h->streams.end()) return;
    st = std::move(it->second);
    h->streams.erase(it);
  }
  int gstatus = -1;
  std::string gmsg;
  std::string http_status;
  for (auto& kv : st.headers) {
    if (kv.first == "grpc-status") gstatus = atoi(kv.second.c_str());
    else if (kv.first == "grpc-message") gmsg = kv.second;
    else if (kv.first == ":status") http_status = kv.second;
  }
  int code;
  if (gstatus == 0) {
    code = 0;
  } else if (gstatus > 0) {
    code = brpc_code_of_grpc(gstatus);
  } else {
    code = 2002;  // ERESPONSE: no grpc-status at all
    gmsg = "missing grpc-status (:status " + http_status + ")";
  }
  const uint8_t* body = nullptr;
  uint64_t blen = 0;
  if (code == 0) {
    // a grpc-status-0 response MUST carry one well-formed identity
    // message; a short/truncated/compressed frame is ERESPONSE, not a
    // silently-empty success (mirrors the server-side rejects)
    if (st.data.size() < 5 || st.data[0] != 0) {
      code = 2002;
      gmsg = "bad grpc response frame";
    } else {
      uint32_t mlen = ntohl(*reinterpret_cast<const uint32_t*>(
          st.data.data() + 1));
      if (uint64_t(mlen) + 5 > st.data.size()) {
        code = 2002;
        gmsg = "grpc response frame truncated";
      } else {
        body = reinterpret_cast<const uint8_t*>(st.data.data()) + 5;
        blen = mlen;
      }
    }
  }
  c->in_msgs.fetch_add(1, std::memory_order_relaxed);
  SyncWaiter* w = sync_take_conn(rt, st.cid, c->id);
  if (w != nullptr) {
    uint8_t* blk = static_cast<uint8_t*>(malloc(blen ? blen : 1));
    if (blen) memcpy(blk, body, blen);
    sync_complete(w, code, 0, 0, gmsg.data(), gmsg.size(), blk, blk,
                  blen);
    return;
  }
  if (!c->py_fast.load(std::memory_order_relaxed)) return;
  size_t hdr = sizeof(RespLite) + (code ? gmsg.size() : 0);
  uint8_t* blk = static_cast<uint8_t*>(malloc(hdr + blen + 1));
  RespLite rl{};
  memcpy(blk, &rl, sizeof(rl));
  if (code && !gmsg.empty()) {
    memcpy(blk + sizeof(rl), gmsg.data(), gmsg.size());
  }
  if (blen) memcpy(blk + hdr, body, blen);
  DpEvent ev{};
  ev.kind = EV_RESPONSE;
  ev.tag = code;
  ev.conn_id = c->id;
  ev.aux = int64_t(st.cid);
  ev.base = blk;
  ev.meta = blk;
  ev.meta_len = hdr;
  ev.body = blk + hdr;
  ev.body_len = blen;
  push_event(rt, ev);
}

std::string h2_settings_prefix() {
  // SETTINGS{MAX_CONCURRENT_STREAMS, INITIAL_WINDOW_SIZE, MAX_FRAME_SIZE}
  // + conn WINDOW_UPDATE up to kH2RecvWindow
  std::string o;
  std::string body;
  auto put16 = [&](uint16_t v) {
    uint16_t be = htons(v);
    body.append(reinterpret_cast<const char*>(&be), 2);
  };
  auto put32 = [&](uint32_t v) {
    uint32_t be = htonl(v);
    body.append(reinterpret_cast<const char*>(&be), 4);
  };
  put16(0x3); put32(1024);            // MAX_CONCURRENT_STREAMS
  put16(0x4); put32(kH2RecvWindow);   // INITIAL_WINDOW_SIZE
  put16(0x5); put32(kH2MaxFrame);     // MAX_FRAME_SIZE
  h2_frame_hdr(&o, uint32_t(body.size()), H2F_SETTINGS, 0, 0);
  o.append(body);
  std::string wu;
  uint32_t inc = htonl(kH2RecvWindow - 65535);
  wu.append(reinterpret_cast<const char*>(&inc), 4);
  h2_frame_hdr(&o, 4, H2F_WINUP, 0, 0);
  o.append(wu);
  return o;
}

// Emit whatever the peer's windows allow for one stream (h->mu held).
// Appends DATA frames (grpc-framed bytes already in st->out) and, once
// drained, the server trailers / client END_STREAM.
void h2_emit_stream(H2State* h, uint32_t sid, H2Stream* st,
                    std::string* frames) {
  while (st->out_off < st->out.size()) {
    int64_t win = std::min(st->send_window, h->conn_send_window);
    if (win <= 0) return;  // parked until WINDOW_UPDATE
    uint64_t chunk = std::min<uint64_t>(
        std::min<uint64_t>(uint64_t(win), st->out.size() - st->out_off),
        h->peer_max_frame);
    bool last = (st->out_off + chunk == st->out.size());
    uint8_t fl = (last && st->end_after_out && st->trailers.empty())
                     ? H2FL_END_STREAM : 0;
    h2_frame_hdr(frames, uint32_t(chunk), H2F_DATA, fl, sid);
    frames->append(st->out.data() + st->out_off, size_t(chunk));
    st->out_off += size_t(chunk);
    st->send_window -= int64_t(chunk);
    h->conn_send_window -= int64_t(chunk);
  }
  if (st->out_off >= st->out.size()) {
    if (!st->trailers.empty()) {
      frames->append(st->trailers);
      st->trailers.clear();
    }
    st->sent_all = true;
  }
}

// Re-try parked streams after a WINDOW_UPDATE / SETTINGS change (loop
// thread). h->mu is held across the emit AND the write: per-stream frame
// order is the h->mu acquisition order, so a pump can never overtake the
// HEADERS+first-chunk a responder emitted under the same lock (pending
// flushes first for the queued-respond case).
void h2_pump(Runtime* rt, const std::shared_ptr<Conn>& c) {
  H2State* h = c->h2.get();
  std::string frames;
  std::vector<uint32_t> done;
  std::lock_guard<std::mutex> lk(h->mu);
  for (auto& kv : h->streams) {
    if (kv.second.out_off < kv.second.out.size() ||
        !kv.second.trailers.empty()) {
      h2_emit_stream(h, kv.first, &kv.second, &frames);
      if (kv.second.sent_all && !h->client) done.push_back(kv.first);
    }
  }
  for (uint32_t sid : done) h->streams.erase(sid);
  if (!frames.empty()) {
    flush_conn_pending(rt, c);
    conn_write(rt, c, reinterpret_cast<const uint8_t*>(frames.data()),
               frames.size());
  }
}

// Server-side grpc response, entirely in-engine. Called from the parse
// loop (native echo / rejects) and from dp_respond (Python services).
int h2_grpc_respond(Runtime* rt, const std::shared_ptr<Conn>& c,
                    uint32_t sid, int code, const char* etext,
                    uint64_t etext_len, const uint8_t* payload,
                    uint64_t plen, const uint8_t* att, uint64_t alen,
                    int queue) {
  H2State* h = c->h2.get();
  std::string hb;
  hp_put_indexed(&hb, 8);  // :status 200
  hp_put_literal(&hb, 31, nullptr, "application/grpc", 16);
  std::string frames;
  h2_frame_hdr(&frames, uint32_t(hb.size()), H2F_HEADERS, H2FL_END_HEADERS,
               sid);
  frames.append(hb);
  std::string msg;  // grpc length-prefixed message (payload + attachment)
  if (code == 0) {
    uint64_t mlen = plen + alen;
    msg.reserve(5 + mlen);
    msg.push_back(0);
    uint32_t be = htonl(uint32_t(mlen));
    msg.append(reinterpret_cast<const char*>(&be), 4);
    if (plen) msg.append(reinterpret_cast<const char*>(payload),
                         size_t(plen));
    if (alen) msg.append(reinterpret_cast<const char*>(att), size_t(alen));
  }
  std::string tb;
  std::string gs = std::to_string(grpc_status_of(code));
  hp_put_literal(&tb, 0, "grpc-status", gs.data(), gs.size());
  if (code != 0 && etext_len) {
    hp_put_literal(&tb, 0, "grpc-message",
                   reinterpret_cast<const char*>(etext),
                   size_t(etext_len));
  }
  std::string trailers;
  h2_frame_hdr(&trailers, uint32_t(tb.size()), H2F_HEADERS,
               H2FL_END_HEADERS | H2FL_END_STREAM, sid);
  trailers.append(tb);
  // h->mu is held through the write/enqueue: a WINDOW_UPDATE pump on the
  // loop thread must not interleave this stream's continuation ahead of
  // the HEADERS + first chunk emitted here (lock order: h->mu -> pmu/wmu)
  std::lock_guard<std::mutex> lk(h->mu);
  auto it = h->streams.find(sid);
  if (it == h->streams.end()) {
    // stream already gone (client RST / conn teardown): dropping the
    // response is the h2 contract — resurrecting the sid would send
    // frames on a closed stream
    return DPE_OK;
  }
  H2Stream& st = it->second;
  st.out = std::move(msg);
  st.out_off = 0;
  st.trailers = std::move(trailers);
  h2_emit_stream(h, sid, &st, &frames);
  if (st.sent_all) h->streams.erase(it);
  if (queue) {
    queue_packet(rt, c, frames, nullptr, 0, nullptr, 0);
    return DPE_OK;
  }
  return conn_write(rt, c,
                    reinterpret_cast<const uint8_t*>(frames.data()),
                    frames.size());
}

// Client-side grpc request: HEADERS + flow-controlled DATA(+END_STREAM).
// The attachment rides the body (grpc has no attachment concept —
// policy/grpc_protocol.py does the same).
int h2_grpc_call(Runtime* rt, const std::shared_ptr<Conn>& c,
                 const char* svc, uint64_t svc_len, const char* meth,
                 uint64_t meth_len, uint64_t cid, int32_t timeout_ms,
                 const uint8_t* payload, uint64_t plen,
                 const uint8_t* att, uint64_t alen, int queue) {
  H2State* h = c->h2.get();
  std::string path;
  path.reserve(svc_len + meth_len + 2);
  path.push_back('/');
  path.append(svc, svc_len);
  path.push_back('/');
  path.append(meth, meth_len);
  std::string hb;
  hp_put_indexed(&hb, 3);  // :method POST
  hp_put_indexed(&hb, 6);  // :scheme http
  hp_put_literal(&hb, 4, nullptr, path.data(), path.size());
  hp_put_literal(&hb, 1, nullptr, h->authority.data(),
                 h->authority.size());
  hp_put_literal(&hb, 31, nullptr, "application/grpc", 16);
  hp_put_literal(&hb, 0, "te", "trailers", 8);
  std::string tv;
  if (timeout_ms > 0) {
    tv = std::to_string(timeout_ms) + "m";
    hp_put_literal(&hb, 0, "grpc-timeout", tv.data(), tv.size());
  }
  std::string msg;
  msg.reserve(5 + plen + alen);
  msg.push_back(0);
  uint32_t be = htonl(uint32_t(plen + alen));
  msg.append(reinterpret_cast<const char*>(&be), 4);
  if (plen) msg.append(reinterpret_cast<const char*>(payload),
                       size_t(plen));
  if (alen) msg.append(reinterpret_cast<const char*>(att), size_t(alen));
  std::string frames;
  // h->mu held from sid allocation through the write/enqueue: RFC 9113
  // requires monotonically increasing stream ids ON THE WIRE, so the
  // allocation and the socket handoff must be one atomic step when
  // several threads share the conn (channel "single" semantics)
  std::lock_guard<std::mutex> lk(h->mu);
  uint32_t sid = h->next_stream_id;
  h->next_stream_id += 2;
  h2_frame_hdr(&frames, uint32_t(hb.size()), H2F_HEADERS,
               H2FL_END_HEADERS, sid);
  frames.append(hb);
  H2Stream& st = h->streams[sid];
  st.send_window = int64_t(h->peer_initial_window);
  st.cid = cid;
  st.headers_done = false;
  st.out = std::move(msg);
  st.end_after_out = true;
  h2_emit_stream(h, sid, &st, &frames);
  // the stream node survives until the response completes it
  if (queue) {
    queue_packet(rt, c, frames, nullptr, 0, nullptr, 0);
    return DPE_OK;
  }
  return conn_write(rt, c,
                    reinterpret_cast<const uint8_t*>(frames.data()),
                    frames.size());
}

// Completed inbound server stream -> native echo / EV_REQUEST / reject.
void h2_dispatch(Runtime* rt, const std::shared_ptr<Conn>& c, uint32_t sid,
                 H2Stream* st) {
  std::string path, ctype, timeout;
  for (auto& kv : st->headers) {
    if (kv.first == ":path") path = kv.second;
    else if (kv.first == "content-type") ctype = kv.second;
    else if (kv.first == "grpc-timeout") timeout = kv.second;
  }
  c->in_msgs.fetch_add(1, std::memory_order_relaxed);
  if (ctype.compare(0, 16, "application/grpc") != 0) {
    static const char e[] = "not a grpc request";
    h2_grpc_respond(rt, c, sid, 1002, e, sizeof(e) - 1, nullptr, 0,
                    nullptr, 0, /*queue=*/0);
    return;
  }
  // "/pkg.Service/Method" — Python registers bare names; take the last
  // dot component (grpc_protocol.py does the same)
  size_t s1 = path.find('/', 1);
  if (path.empty() || path[0] != '/' || s1 == std::string::npos) {
    static const char e[] = "bad grpc path";
    h2_grpc_respond(rt, c, sid, 1002, e, sizeof(e) - 1, nullptr, 0,
                    nullptr, 0, 0);
    return;
  }
  std::string svc_full = path.substr(1, s1 - 1);
  std::string meth = path.substr(s1 + 1);
  size_t dot = svc_full.rfind('.');
  std::string svc =
      dot == std::string::npos ? svc_full : svc_full.substr(dot + 1);
  // grpc message framing: flag byte (0 = identity) + u32 length
  if (st->data.size() < 5 || st->data[0] != 0) {
    static const char e[] = "bad grpc frame";
    h2_grpc_respond(rt, c, sid, 1003, e, sizeof(e) - 1, nullptr, 0,
                    nullptr, 0, 0);
    return;
  }
  uint32_t mlen = ntohl(*reinterpret_cast<const uint32_t*>(
      st->data.data() + 1));
  if (uint64_t(mlen) + 5 > st->data.size()) {
    static const char e[] = "grpc frame truncated";
    h2_grpc_respond(rt, c, sid, 1003, e, sizeof(e) - 1, nullptr, 0,
                    nullptr, 0, 0);
    return;
  }
  const uint8_t* body =
      reinterpret_cast<const uint8_t*>(st->data.data()) + 5;
  MetaLite m;
  m.has_request = true;
  m.correlation_id = sid;
  m.service = svc;
  m.method = meth;
  m.timeout_ms = parse_grpc_timeout(timeout);
  EchoAdmit admit;
  if (echo_admit(rt, c.get(), m, &admit)) {
    // native service: answer in-engine (C++ user code lane, grpc flavor)
    int code = admit.ecode;
    h2_grpc_respond(rt, c, sid, code, admit.etext,
                    code ? strlen(admit.etext) : 0, code ? nullptr : body,
                    code ? 0 : mlen, nullptr, 0, 0);
    echo_settle(&admit);
    return;
  }
  if (c->py_fast.load(std::memory_order_relaxed)) {
    // EV_REQUEST fast path: same packed layout as the std protocol.
    // After the push the poller may respond + erase the stream node at
    // any moment — st must not be touched again on this thread.
    m.attachment_size = 0;
    h2_push_request_event(rt, c.get(), m, body, mlen, st);
    return;
  }
  static const char e[] = "no such grpc service";
  h2_grpc_respond(rt, c, sid, 1001, e, sizeof(e) - 1, nullptr, 0, nullptr,
                  0, 0);
}

// Parse loop for an h2 conn (server sniff + engine-owned, both roles).
void h2_parse_inner(Runtime* rt, const std::shared_ptr<Conn>& c) {
  H2State* h = c->h2.get();
  RBuf& buf = c->rbuf;
  for (;;) {
    size_t avail = buf.size - c->rpos;
    const uint8_t* p = buf.data + c->rpos;
    if (h->phase == 0) {  // server: await the full client preface
      if (avail < kH2PrefaceLen) return;
      if (memcmp(p, kH2Preface, kH2PrefaceLen) != 0) {
        conn_fail(rt, c, DPE_PROTOCOL, "bad h2 preface");
        return;
      }
      h->prelude.append(reinterpret_cast<const char*>(p), kH2PrefaceLen);
      c->rpos += kH2PrefaceLen;
      h->phase = 1;
      continue;
    }
    if (avail < 9) return;
    uint32_t flen = (uint32_t(p[0]) << 16) | (uint32_t(p[1]) << 8) | p[2];
    uint8_t type = p[3];
    uint8_t flags = p[4];
    uint32_t sid = ntohl(*reinterpret_cast<const uint32_t*>(p + 5))
                   & 0x7fffffffu;
    if (flen > kH2MaxFrame + 1024) {
      conn_fail(rt, c, DPE_PROTOCOL, "h2 frame too large");
      return;
    }
    if (avail < 9 + uint64_t(flen)) return;
    const uint8_t* fp = p + 9;
    static const bool h2dbg = getenv("DP_H2_DEBUG") != nullptr;
    if (h2dbg) {
      fprintf(stderr, "[dp] h2 frame type=%d flags=%d sid=%u flen=%u phase=%d client=%d\n",
              type, flags, sid, flen, h->phase, int(h->client));
    }
    if (h->phase == 1) {
      h->prelude.append(reinterpret_cast<const char*>(p), 9 + flen);
    }
    c->rpos += 9 + flen;
    switch (type) {
      case H2F_SETTINGS: {
        if (flags & H2FL_ACK) break;
        for (uint32_t off = 0; off + 6 <= flen; off += 6) {
          uint16_t id = ntohs(*reinterpret_cast<const uint16_t*>(
              fp + off));
          uint32_t val = ntohl(*reinterpret_cast<const uint32_t*>(
              fp + off + 2));
          std::lock_guard<std::mutex> lk(h->mu);
          if (id == 0x4) {  // INITIAL_WINDOW_SIZE: adjust live streams
            int64_t delta =
                int64_t(val) - int64_t(h->peer_initial_window);
            h->peer_initial_window = val;
            for (auto& kv : h->streams) kv.second.send_window += delta;
          } else if (id == 0x5 && val >= 16384 && val <= (1u << 24)) {
            h->peer_max_frame = val;
          }
        }
        if (h->phase == 2) {
          std::string ack;
          h2_frame_hdr(&ack, 0, H2F_SETTINGS, H2FL_ACK, 0);
          conn_write(rt, c,
                     reinterpret_cast<const uint8_t*>(ack.data()),
                     ack.size());
          h2_pump(rt, c);  // window growth may release parked data
        } else {
          h->unacked_settings++;
        }
        break;
      }
      case H2F_PING: {
        if (flags & H2FL_ACK) break;
        std::string pong;
        h2_frame_hdr(&pong, flen, H2F_PING, H2FL_ACK, 0);
        pong.append(reinterpret_cast<const char*>(fp), flen);
        if (h->phase == 2) {
          conn_write(rt, c,
                     reinterpret_cast<const uint8_t*>(pong.data()),
                     pong.size());
        } else {
          h->pending_ctrl.append(pong);  // replied only if we engage
        }
        break;
      }
      case H2F_WINUP: {
        if (flen != 4) break;
        uint32_t inc = ntohl(*reinterpret_cast<const uint32_t*>(fp))
                       & 0x7fffffffu;
        {
          std::lock_guard<std::mutex> lk(h->mu);
          if (sid == 0) {
            h->conn_send_window += inc;
          } else {
            auto it = h->streams.find(sid);
            if (it != h->streams.end()) it->second.send_window += inc;
          }
        }
        if (h->phase == 2) h2_pump(rt, c);
        break;
      }
      case H2F_RST: {
        uint64_t cancelled_cid = 0;
        {
          std::lock_guard<std::mutex> lk(h->mu);
          auto it = h->streams.find(sid);
          if (it != h->streams.end()) {
            cancelled_cid = it->second.cid;
            h->streams.erase(it);
          }
        }
        if (h->client && cancelled_cid != 0) {
          // the in-flight call must complete, not hang (ECANCELED=1015)
          SyncWaiter* w = sync_take_conn(rt, cancelled_cid, c->id);
          static const char kRst[] = "stream reset by peer";
          if (w != nullptr) {
            uint8_t* blk = static_cast<uint8_t*>(malloc(1));
            sync_complete(w, 1015, 0, 0, kRst, sizeof(kRst) - 1, blk,
                          blk, 0);
          } else if (c->py_fast.load(std::memory_order_relaxed)) {
            size_t hdr = sizeof(RespLite) + sizeof(kRst) - 1;
            uint8_t* blk = static_cast<uint8_t*>(malloc(hdr + 1));
            RespLite rl{};
            memcpy(blk, &rl, sizeof(rl));
            memcpy(blk + sizeof(rl), kRst, sizeof(kRst) - 1);
            DpEvent ev{};
            ev.kind = EV_RESPONSE;
            ev.tag = 1015;
            ev.conn_id = c->id;
            ev.aux = int64_t(cancelled_cid);
            ev.base = blk;
            ev.meta = blk;
            ev.meta_len = hdr;
            push_event(rt, ev);
          }
        }
        break;
      }
      case H2F_GOAWAY:
        if (h->client) {
          conn_fail(rt, c, DPE_EOF, "h2 GOAWAY");
          return;
        }
        break;
      case H2F_HEADERS:
      case H2F_CONT: {
        const uint8_t* hb = fp;
        uint32_t hlen = flen;
        if (type == H2F_HEADERS) {
          if (flags & H2FL_PADDED) {
            if (!hlen) break;
            uint8_t pad = hb[0];
            hb++;
            hlen--;
            if (pad > hlen) break;
            hlen -= pad;
          }
          if (flags & H2FL_PRIORITY) {
            if (hlen < 5) break;
            hb += 5;
            hlen -= 5;
          }
          h->cont_sid = sid;
          h->cont_flags = flags;
          h->cont_buf.assign(reinterpret_cast<const char*>(hb), hlen);
        } else {
          if (sid != h->cont_sid) break;
          h->cont_buf.append(reinterpret_cast<const char*>(hb), hlen);
          h->cont_flags |= (flags & H2FL_END_HEADERS);
        }
        if (!(h->cont_flags & H2FL_END_HEADERS)) {
          break;  // CONTINUATION follows
        }
        HdrList hdrs;
        if (!hpack_decode_block(
                &h->dec,
                reinterpret_cast<const uint8_t*>(h->cont_buf.data()),
                h->cont_buf.size(), &hdrs)) {
          conn_fail(rt, c, DPE_PROTOCOL, "hpack decode failed");
          return;
        }
        h->cont_buf.clear();
        bool end_stream = (h->cont_flags & H2FL_END_STREAM) != 0;
        if (h->phase == 1) {
          // the sniff decision: first request grpc -> engine; else the
          // Python h2 stack takes the conn (raw bytes replayed)
          std::string ctype;
          for (auto& kv : hdrs) {
            if (kv.first == "content-type") ctype = kv.second;
          }
          if (ctype.compare(0, 16, "application/grpc") == 0) {
            h->phase = 2;
            std::string pre = h2_settings_prefix();
            for (; h->unacked_settings > 0; h->unacked_settings--) {
              h2_frame_hdr(&pre, 0, H2F_SETTINGS, H2FL_ACK, 0);
            }
            pre.append(h->pending_ctrl);
            h->pending_ctrl.clear();
            h->prelude.clear();
            h->prelude.shrink_to_fit();
            conn_write(rt, c,
                       reinterpret_cast<const uint8_t*>(pre.data()),
                       pre.size());
          } else {
            conn_detach(rt, c, &h->prelude);
            return;
          }
        }
        H2Stream* st;
        {
          std::lock_guard<std::mutex> lk(h->mu);
          auto ins = h->streams.try_emplace(sid);
          st = &ins.first->second;
          if (ins.second) {
            st->send_window = int64_t(h->peer_initial_window);
          }
          if (!st->headers_done) {
            st->headers = std::move(hdrs);
            st->headers_done = true;
          } else {
            // trailers (client side: grpc-status etc.)
            for (auto& kv : hdrs) st->headers.push_back(std::move(kv));
          }
        }
        if (end_stream) {
          if (h->client) {
            h2_client_complete(rt, c, sid);
          } else {
            h2_dispatch(rt, c, sid, st);
            std::lock_guard<std::mutex> lk(h->mu);
            auto it = h->streams.find(sid);
            // keep only streams with parked response bytes
            if (it != h->streams.end() && it->second.sent_all) {
              h->streams.erase(it);
            }
          }
        }
        break;
      }
      case H2F_DATA: {
        const uint8_t* db = fp;
        uint32_t dlen = flen;
        if (flags & H2FL_PADDED) {
          if (!dlen) break;
          uint8_t pad = db[0];
          db++;
          dlen--;
          if (pad > dlen) break;
          dlen -= pad;
        }
        bool complete = false;
        {
          std::lock_guard<std::mutex> lk(h->mu);
          auto it = h->streams.find(sid);
          if (it == h->streams.end()) break;
          H2Stream& st = it->second;
          if (st.data.size() + dlen > rt->max_body) {
            conn_fail(rt, c, DPE_PROTOCOL, "grpc body exceeds max_body");
            return;
          }
          st.data.append(reinterpret_cast<const char*>(db), dlen);
          complete = (flags & H2FL_END_STREAM) != 0;
        }
        h->recv_since_update += flen;
        if (h->recv_since_update > kH2RecvWindow / 2) {
          std::string wu;
          uint32_t inc = htonl(uint32_t(h->recv_since_update));
          h2_frame_hdr(&wu, 4, H2F_WINUP, 0, 0);
          wu.append(reinterpret_cast<const char*>(&inc), 4);
          conn_write(rt, c,
                     reinterpret_cast<const uint8_t*>(wu.data()),
                     wu.size());
          h->recv_since_update = 0;
        }
        if (complete) {
          if (h->client) {
            h2_client_complete(rt, c, sid);
          } else {
            H2Stream* st;
            {
              std::lock_guard<std::mutex> lk(h->mu);
              st = &h->streams[sid];
            }
            h2_dispatch(rt, c, sid, st);
            std::lock_guard<std::mutex> lk(h->mu);
            auto it = h->streams.find(sid);
            if (it != h->streams.end() && it->second.sent_all) {
              h->streams.erase(it);
            }
          }
        }
        break;
      }
      default:
        break;  // PRIORITY / PUSH_PROMISE / unknown: ignored
    }
    if (c->failed.load()) return;
  }
}

void h2_parse(Runtime* rt, const std::shared_ptr<Conn>& c) {
  h2_parse_inner(rt, c);
  if (c->failed.load()) return;
  RBuf& buf = c->rbuf;
  if (c->rpos == buf.size) {
    buf.size = 0;
    c->rpos = 0;
  } else if (c->rpos > (1 << 20)) {
    memmove(buf.data, buf.data + c->rpos, buf.size - c->rpos);
    buf.size -= c->rpos;
    c->rpos = 0;
  }
}

// Parse dispatcher (loop thread only).
void conn_parse(Runtime* rt, const std::shared_ptr<Conn>& c) {
  if (c->tpu_mode != 0) {
    tpu_parse(rt, c);
    return;
  }
  if (c->h2_mode != 0) {
    h2_parse(rt, c);
    return;
  }
  // h2c prior-knowledge sniff (server conns on fast-path listeners): the
  // client preface never collides with TRPC/TSTR/TPUC magics
  if (c->is_server && c->py_fast.load(std::memory_order_relaxed)) {
    size_t avail = c->rbuf.size - c->rpos;
    size_t n = avail < kH2PrefaceLen ? avail : kH2PrefaceLen;
    if (n != 0 && memcmp(c->rbuf.data + c->rpos, kH2Preface, n) == 0) {
      if (avail < kH2PrefaceLen) return;  // wait for the whole preface
      c->h2_mode = 2;
      c->h2.reset(new H2State());
      h2_parse(rt, c);
      return;
    }
  }
  // a TPUC HELLO on a tpu-enabled native listener upgrades the conn to a
  // native tunnel endpoint (reference AppConnect handshake-then-switch);
  // on a plain listener it detaches to the Python transport
  if (c->is_server && c->rbuf.size - c->rpos >= 4 &&
      memcmp(c->rbuf.data + c->rpos, "TPUC", 4) == 0) {
    int ordinal = -1;
    {
      std::lock_guard<std::mutex> lk(rt->cmu);
      if (c->listener_id >= 0 &&
          size_t(c->listener_id) < rt->listeners.size()) {
        ordinal = rt->listeners[size_t(c->listener_id)].tpu_ordinal;
      }
    }
    if (ordinal >= 0) {
      c->tpu_mode = 1;
      c->tpu.reset(new TpuState());
      c->tpu->ordinal = ordinal;
      tpu_parse(rt, c);
      return;
    }
  }
  cut_trpc(rt, c, c->rbuf, c->rpos, /*allow_detach=*/true);
}

void conn_readable(Runtime* rt, const std::shared_ptr<Conn>& c) {
  for (;;) {
    // when mid-frame, read the whole remainder in one recv
    size_t want = kReadChunk;
    size_t avail = c->rbuf.size - c->rpos;
    if (avail >= kHeaderSize) {
      const uint8_t* p = c->rbuf.data + c->rpos;
      if (!memcmp(p, "TRPC", 4) || !memcmp(p, "TSTR", 4)) {
        uint64_t total = kHeaderSize +
            uint64_t(ntohl(*reinterpret_cast<const uint32_t*>(p + 4))) +
            uint64_t(ntohl(*reinterpret_cast<const uint32_t*>(p + 8)));
        if (total > avail && total - avail > want &&
            total <= rt->max_body + kHeaderSize) {
          want = total - avail;
        }
      }
    }
    uint8_t* dst = c->rbuf.tail(want);
    ssize_t n = ::recv(c->fd, dst, want, 0);
    if (n > 0) {
      c->rbuf.size += size_t(n);
      c->in_bytes.fetch_add(uint64_t(n), std::memory_order_relaxed);
      conn_parse(rt, c);
      if (c->failed.load()) return;
      if (size_t(n) < want) return;  // drained
    } else if (n == 0) {
      conn_parse(rt, c);
      if (!c->failed.load()) conn_fail(rt, c, DPE_EOF, "peer closed");
      return;
    } else if (errno == EINTR) {
      continue;
    } else if (errno == EAGAIN || errno == EWOULDBLOCK) {
      return;
    } else {
      conn_fail(rt, c, DPE_IO, strerror(errno));
      return;
    }
  }
}

void tpu_handle_hello(Runtime* rt, const std::shared_ptr<Conn>& c,
                      const std::string& body) {
  TpuState* t = c->tpu.get();
  if (t == nullptr || c->tpu_mode == 2 || !c->is_server ||
      t->pool != nullptr) {
    // a client conn (or a conn that already created its pool) must never
    // re-run pool creation — it would leak the prior shm mapping
    conn_fail(rt, c, DPE_PROTOCOL, "unexpected HELLO");
    return;
  }
  std::string pool;
  int64_t bs = 0, bc = 0, requested = 0;
  json_str(body, "pool", &pool);
  json_int(body, "bs", &bs);
  json_int(body, "bc", &bc);
  json_int(body, "ordinal", &requested);
  // mirror the dialer's geometry for OUR receive pool (window negotiation:
  // a bulk-transfer client gets a bulk-sized window both ways)
  if (bs > 0 && bc > 0) {
    uint32_t mbs = uint32_t(bs), mbc = uint32_t(bc);
    tpu_clamp_geometry(&mbs, &mbc);
    t->bs = mbs;
    t->bc = mbc;
  }
  if (t->ordinal >= 0 && requested != t->ordinal) {
    // refuse a dial addressed to a device this server does not front
    char err[160];
    snprintf(err, sizeof(err),
             "{\"v\": 1, \"pool\": \"\", \"bs\": 0, \"bc\": 0, "
             "\"ordinal\": %d, \"err\": \"server fronts device %d, "
             "dial requested %d\"}",
             t->ordinal, t->ordinal, int(requested));
    const uint8_t* b[1] = {reinterpret_cast<const uint8_t*>(err)};
    const uint64_t l[1] = {strlen(err)};
    tpu_ctrl_send(rt, c, TFT_HELLO_ACK, b, l, 1);
    conn_fail(rt, c, DPE_PROTOCOL, "device ordinal mismatch");
    return;
  }
  if (!tpu_create_pool(t)) {
    conn_fail(rt, c, DPE_IO, "cannot create shm pool");
    return;
  }
  if (pool.empty() ||
      !tpu_attach_peer(t, pool, uint32_t(bs), uint32_t(bc))) {
    t->inline_only = true;  // cross-host fallback: inline DATA frames only
  }
  std::string ack = tpu_hello_json(t, int(t->ordinal >= 0 ? t->ordinal
                                                          : requested));
  const uint8_t* b[1] = {reinterpret_cast<const uint8_t*>(ack.data())};
  const uint64_t l[1] = {ack.size()};
  if (tpu_ctrl_send(rt, c, TFT_HELLO_ACK, b, l, 1) != DPE_OK) {
    conn_fail(rt, c, DPE_IO, "HELLO_ACK send failed");
    return;
  }
  c->tpu_mode = 2;
}

void tpu_handle_hello_ack(Runtime* rt, const std::shared_ptr<Conn>& c,
                          const std::string& body) {
  TpuState* t = c->tpu.get();
  if (t == nullptr) {
    conn_fail(rt, c, DPE_PROTOCOL, "unexpected HELLO_ACK");
    return;
  }
  std::string err;
  if (json_str(body, "err", &err) && !err.empty()) {
    {
      std::lock_guard<std::mutex> lk(t->hmu);
      t->err = err;
    }
    t->hcv.notify_all();
    conn_fail(rt, c, DPE_PROTOCOL, "handshake refused");
    return;
  }
  std::string pool;
  int64_t bs = 0, bc = 0;
  json_str(body, "pool", &pool);
  json_int(body, "bs", &bs);
  json_int(body, "bc", &bc);
  if (pool.empty() ||
      !tpu_attach_peer(t, pool, uint32_t(bs), uint32_t(bc))) {
    t->inline_only = true;
  }
  c->tpu_mode = 2;
  {
    std::lock_guard<std::mutex> lk(t->hmu);
    t->ready = true;
  }
  t->hcv.notify_all();
}

// Ship one RPC packet through the tunnel (reference CutFromIOBufList,
// rdma_endpoint.h:89: post blocks, window--, stream through on exhaustion).
int tpu_send_packet(Runtime* rt, const std::shared_ptr<Conn>& c,
                    const uint8_t* const* bufs, const uint64_t* lens,
                    int nseg) {
  TpuState* t = c->tpu.get();
  if (t == nullptr || c->tpu_mode != 2) return DPE_IO;
  uint64_t total = 0;
  for (int i = 0; i < nseg; i++) total += lens[i];
  std::lock_guard<std::mutex> slk(t->smu);  // frame order IS stream order
  if (c->failed.load()) return DPE_IO;
  int vi = 0;
  uint64_t voff = 0;
  auto copy_out = [&](uint8_t* dst, uint64_t want) -> uint64_t {
    uint64_t done = 0;
    while (done < want && vi < nseg) {
      uint64_t take = lens[vi] - voff;
      if (take > want - done) take = want - done;
      memcpy(dst + done, bufs[vi] + voff, take);
      voff += take;
      done += take;
      if (voff == lens[vi]) {
        vi++;
        voff = 0;
      }
    }
    return done;
  };
  if (t->inline_only || total <= kTpuInlineMax) {
    uint64_t left = total;
    while (left > 0 || total == 0) {
      uint64_t part = left < kTpuBlockSize ? left : kTpuBlockSize;
      std::string body;
      body.resize(8 + part);
      uint32_t il_be = htonl(uint32_t(part));
      uint32_t z = 0;
      memcpy(&body[0], &il_be, 4);
      memcpy(&body[4], &z, 4);
      copy_out(reinterpret_cast<uint8_t*>(&body[8]), part);
      const uint8_t* b[1] = {reinterpret_cast<const uint8_t*>(body.data())};
      const uint64_t l[1] = {body.size()};
      int rc = tpu_ctrl_send(rt, c, TFT_DATA, b, l, 1);
      if (rc != DPE_OK) {
        if (left != total) {
          // mid-packet failure desyncs the stream for good
          loop_submit(rt, c->loop, [rt, c] {
            conn_fail(rt, c, DPE_IO, "mid-packet tunnel send failure");
          });
        }
        return rc;
      }
      left -= part;
      if (total == 0) break;
    }
    return DPE_OK;
  }
  uint64_t sent = 0;
  while (sent < total) {
    uint32_t want_blocks =
        uint32_t((total - sent + t->peer_bs - 1) / t->peer_bs);
    if (want_blocks > uint32_t(kTpuMaxSegs)) want_blocks = kTpuMaxSegs;
    std::vector<uint32_t> got;
    {
      std::unique_lock<std::mutex> lk(t->cmu);
      if (!t->ccv.wait_for(lk, std::chrono::seconds(30), [t] {
            return !t->credits.empty() || t->closed;
          })) {
        lk.unlock();
        if (sent > 0) {
          // frames of this packet already reached the peer's stream: it is
          // desynced for good (Python send_packet fails the tunnel the
          // same way)
          loop_submit(rt, c->loop, [rt, c] {
            conn_fail(rt, c, DPE_OVERCROWDED, "tunnel window wedged");
          });
        }
        return DPE_OVERCROWDED;
      }
      if (t->closed) return DPE_IO;
      while (!t->credits.empty() && got.size() < want_blocks) {
        uint32_t idx = t->credits.front();
        t->credits.pop_front();
        if (idx < t->inflight.size()) t->inflight[idx] = 1;
        got.push_back(idx);
      }
    }
    std::vector<std::pair<uint32_t, uint32_t>> segs;
    for (uint32_t idx : got) {
      uint64_t want = total - sent;
      if (want > t->peer_bs) want = t->peer_bs;
      if (want == 0) break;
      uint64_t wrote = copy_out(t->peer + size_t(idx) * t->peer_bs, want);
      segs.emplace_back(idx, uint32_t(wrote));
      sent += wrote;
    }
    if (segs.size() < got.size()) {
      // grabbed more credits than needed — return the extras
      std::lock_guard<std::mutex> lk(t->cmu);
      for (size_t i = segs.size(); i < got.size(); i++) {
        if (got[i] < t->inflight.size()) t->inflight[got[i]] = 0;
        t->credits.push_back(got[i]);
      }
    }
    std::string body;
    body.resize(8 + segs.size() * 8);
    uint32_t z = 0, ns_be = htonl(uint32_t(segs.size()));
    memcpy(&body[0], &z, 4);
    memcpy(&body[4], &ns_be, 4);
    for (size_t i = 0; i < segs.size(); i++) {
      uint32_t idx_be = htonl(segs[i].first);
      uint32_t ln_be = htonl(segs[i].second);
      memcpy(&body[8 + i * 8], &idx_be, 4);
      memcpy(&body[8 + i * 8 + 4], &ln_be, 4);
    }
    const uint8_t* b[1] = {reinterpret_cast<const uint8_t*>(body.data())};
    const uint64_t l[1] = {body.size()};
    int rc = tpu_ctrl_send(rt, c, TFT_DATA, b, l, 1);
    if (rc != DPE_OK) {
      // the peer never saw these blocks: reclaim the credits, then kill
      // the desynced stream if part of the packet already went out
      {
        std::lock_guard<std::mutex> lk(t->cmu);
        for (auto& s : segs) {
          if (s.first < t->inflight.size()) t->inflight[s.first] = 0;
          t->credits.push_back(s.first);
        }
      }
      loop_submit(rt, c->loop, [rt, c] {
        conn_fail(rt, c, DPE_IO, "mid-packet tunnel send failure");
      });
      return rc;
    }
  }
  return DPE_OK;
}

// --------------------------------------------- queued packets (fast path)
// dp_respond/dp_call with queue=1 append whole packets here; dp_flush_all
// drains every queued conn in one writev each. The Python poller answers a
// whole poll batch, then flushes once — syscalls per RPC drop below one.
void queue_packet(Runtime* rt, const std::shared_ptr<Conn>& c,
                  const std::string& head, const uint8_t* payload,
                  uint64_t plen, const uint8_t* att, uint64_t alen) {
  bool first = false;
  {
    std::lock_guard<std::mutex> lk(c->pmu);
    first = c->pending.empty();
    c->pending.reserve(c->pending.size() + head.size() + plen + alen);
    c->pending.append(head);
    if (plen) c->pending.append(reinterpret_cast<const char*>(payload),
                                size_t(plen));
    if (alen) c->pending.append(reinterpret_cast<const char*>(att),
                                size_t(alen));
    c->pending_msgs++;
  }
  if (first) {
    std::lock_guard<std::mutex> lk(rt->fmu);
    rt->flush_list.push_back(c);
  }
}

int flush_conn_pending(Runtime* rt, const std::shared_ptr<Conn>& c) {
  // pmu is held ACROSS the write (not just the swap): with the swap
  // outside, a second flusher racing this one could write newer bytes
  // before these leave, breaking per-conn FIFO — fatal for h2 streams
  // (HEADERS must precede their window-parked DATA continuations).
  // conn_writev is nonblocking (EAGAIN queues to wq), so the hold is
  // short; lock order pmu -> wmu matches every other path.
  std::unique_lock<std::mutex> lk(c->pmu);
  std::string out;
  int msgs = 0;
  out.swap(c->pending);
  msgs = c->pending_msgs;
  c->pending_msgs = 0;
  if (out.empty()) return DPE_OK;
  const uint8_t* b[1] = {reinterpret_cast<const uint8_t*>(out.data())};
  const uint64_t l[1] = {out.size()};
  int rc = c->tpu_mode != 0 ? tpu_send_packet(rt, c, b, l, 1)
                            : conn_writev(rt, c, b, l, 1, msgs);
  lk.unlock();
  if (rc != DPE_OK && !c->failed.load()) {
    // queued responses that can't go out leave callers hanging forever —
    // same contract breach as the native echo path: tear down
    loop_submit(rt, c->loop, [rt, c, rc] {
      conn_fail(rt, c, rc == DPE_OVERCROWDED ? DPE_OVERCROWDED : DPE_IO,
                "queued packet undeliverable");
    });
  }
  return rc;
}

// ------------------------------------------------------------ registration
std::shared_ptr<Conn> create_conn(Runtime* rt, int fd, bool is_server) {
  auto c = std::make_shared<Conn>();
  c->id = rt->next_conn_id.fetch_add(1);
  c->fd = fd;
  c->is_server = is_server;
  c->loop = rt->rr.fetch_add(1) % int(rt->loops.size());
  std::lock_guard<std::mutex> lk(rt->cmu);
  rt->conns[c->id] = c;
  return c;
}

// Arm the conn's fd in its loop's epoll. Must run AFTER any bookkeeping
// whose events must precede the conn's first frame (ACCEPTED ordering).
void activate_conn(Runtime* rt, const std::shared_ptr<Conn>& c) {
  loop_submit(rt, c->loop, [rt, c] {
    // under wmu: a writer that queued bytes BEFORE this ADD ran saw its
    // EPOLL_CTL_MOD fail silently (fd not registered yet) — honoring
    // want_write here closes the lost-EPOLLOUT race (first large call on
    // a fresh conn would otherwise truncate and time out)
    std::lock_guard<std::mutex> wlk(c->wmu);
    if (c->failed.load() || c->fd < 0) return;
    epoll_event ev{};
    ev.events = c->want_write ? (EPOLLIN | EPOLLOUT) : EPOLLIN;
    ev.data.u64 = c->id;
    if (epoll_ctl(rt->loops[c->loop]->epfd, EPOLL_CTL_ADD, c->fd, &ev) != 0) {
      loop_submit(rt, c->loop, [rt, c] {
        conn_fail(rt, c, DPE_IO, "epoll add");
      });
    }
  });
}

void accept_ready(Runtime* rt, int lid) {
  int lfd = -1;
  bool py_fast = false;
  {
    // dp_listen may grow the vector and dp_listener_close retire the fd
    // concurrently — snapshot under the lock
    std::lock_guard<std::mutex> lk(rt->cmu);
    if (lid < 0 || size_t(lid) >= rt->listeners.size()) return;
    lfd = rt->listeners[size_t(lid)].fd;
    py_fast = rt->listeners[size_t(lid)].py_fast;
  }
  if (lfd < 0) return;
  for (;;) {
    sockaddr_storage ss{};
    socklen_t slen = sizeof(ss);
    int fd = accept4(lfd, reinterpret_cast<sockaddr*>(&ss), &slen,
                     SOCK_NONBLOCK | SOCK_CLOEXEC);
    if (fd < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) return;
      if (errno == EMFILE || errno == ENFILE || errno == ENOBUFS ||
          errno == ENOMEM) {
        // fd exhaustion: the listener stays readable forever under
        // level-triggered epoll, which would turn loop 0 into a 100% spin.
        // Disarm it and let the loop tick re-arm after a backoff.
        epoll_ctl(rt->loops[0]->epfd, EPOLL_CTL_DEL, lfd, nullptr);
        std::lock_guard<std::mutex> lk(rt->amu);
        rt->muted_listeners.emplace_back(lid, mono_ns() + 100000000);
      }
      return;
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    int bufsz = 4 << 20;  // deep buffers keep MB-scale echoes streaming
    setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &bufsz, sizeof(bufsz));
    setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &bufsz, sizeof(bufsz));
    auto c = create_conn(rt, fd, /*is_server=*/true);
    c->listener_id = lid;
    c->py_fast.store(py_fast, std::memory_order_relaxed);
    char host[NI_MAXHOST] = "?", serv[NI_MAXSERV] = "0";
    getnameinfo(reinterpret_cast<sockaddr*>(&ss), slen, host, sizeof(host),
                serv, sizeof(serv), NI_NUMERICHOST | NI_NUMERICSERV);
    std::string peer = std::string(host) + ":" + serv;
    char* blk = static_cast<char*>(malloc(peer.size() + 1));
    memcpy(blk, peer.data(), peer.size());
    DpEvent ev{};
    ev.kind = EV_ACCEPTED;
    ev.conn_id = c->id;
    ev.aux = lid;
    ev.base = blk;
    ev.meta = blk;
    ev.meta_len = peer.size();
    push_event(rt, ev);         // ACCEPTED strictly precedes the conn's frames
    activate_conn(rt, c);
  }
}

// -------------------------------------------------------------- loop body
// epoll data encoding: conn events carry the conn id; listener i is encoded
// as (1<<63)|i; the eventfd as ~0.
constexpr uint64_t kListenerBit = 1ull << 63;
constexpr uint64_t kEventFdKey = ~0ull;

void loop_run(Runtime* rt, int li) {
  Loop* l = rt->loops[li].get();
  std::vector<epoll_event> evs(256);
  while (rt->running.load()) {
    int n = epoll_wait(l->epfd, evs.data(), int(evs.size()), 100);
    if (n < 0) {
      if (errno == EINTR) continue;
      break;
    }
    if (li == 0) {
      // re-arm listeners muted by fd exhaustion once their backoff expires
      std::lock_guard<std::mutex> alk(rt->amu);
      if (!rt->muted_listeners.empty()) {
        int64_t now = mono_ns();
        for (auto it = rt->muted_listeners.begin();
             it != rt->muted_listeners.end();) {
          if (now < it->second) {
            ++it;
            continue;
          }
          int lfd = -1;
          {
            std::lock_guard<std::mutex> clk(rt->cmu);
            if (it->first >= 0 &&
                size_t(it->first) < rt->listeners.size()) {
              lfd = rt->listeners[size_t(it->first)].fd;
            }
          }
          if (lfd >= 0) {
            epoll_event ev{};
            ev.events = EPOLLIN;
            ev.data.u64 = kListenerBit | uint64_t(it->first);
            if (epoll_ctl(l->epfd, EPOLL_CTL_ADD, lfd, &ev) != 0 &&
                errno != EEXIST) {
              // still under resource pressure: keep retrying, never leave
              // the listener in neither epoll nor the retry list
              it->second = now + 100000000;
              ++it;
              continue;
            }
          }
          it = rt->muted_listeners.erase(it);
        }
      }
    }
    for (int i = 0; i < n; i++) {
      uint64_t key = evs[i].data.u64;
      if (key == kEventFdKey) {
        uint64_t drain;
        ssize_t r = read(l->evfd, &drain, 8);
        (void)r;
        std::vector<std::function<void()>> tasks;
        {
          std::lock_guard<std::mutex> lk(l->tmu);
          tasks.swap(l->tasks);
        }
        for (auto& t : tasks) t();
        continue;
      }
      if (key & kListenerBit) {
        accept_ready(rt, int(key & ~kListenerBit));
        continue;
      }
      std::shared_ptr<Conn> c;
      {
        std::lock_guard<std::mutex> lk(rt->cmu);
        auto it = rt->conns.find(key);
        if (it != rt->conns.end()) c = it->second;
      }
      if (!c || c->failed.load()) continue;
      if (evs[i].events & (EPOLLHUP | EPOLLERR)) {
        // let the read path surface the exact error/EOF
        conn_readable(rt, c);
        continue;
      }
      if (evs[i].events & EPOLLOUT) conn_drain_writes(rt, c);
      if (c->failed.load()) continue;
      if (evs[i].events & EPOLLIN) conn_readable(rt, c);
    }
  }
}

}  // namespace

// ===================================================================== ABI
extern "C" {

int dp_abi_version() { return 3; }

void* dp_rt_create(int nloops, uint64_t max_body) {
  if (nloops <= 0) nloops = 2;
  auto* rt = new Runtime();
  if (max_body) rt->max_body = max_body;
  for (int i = 0; i < nloops; i++) {
    auto loop = std::make_unique<Loop>();
    loop->epfd = epoll_create1(EPOLL_CLOEXEC);
    loop->evfd = eventfd(0, EFD_NONBLOCK | EFD_CLOEXEC);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u64 = kEventFdKey;
    epoll_ctl(loop->epfd, EPOLL_CTL_ADD, loop->evfd, &ev);
    rt->loops.push_back(std::move(loop));
  }
  for (int i = 0; i < nloops; i++) {
    rt->loops[size_t(i)]->thr = std::thread(loop_run, rt, i);
  }
  return rt;
}

void dp_rt_shutdown(void* h) {
  auto* rt = static_cast<Runtime*>(h);
  rt->running.store(false);
  {
    // wake every parked sync caller before the loops die
    std::vector<SyncWaiter*> all;
    {
      std::lock_guard<std::mutex> lk(rt->swmu);
      for (auto& kv : rt->sync_waiters) all.push_back(kv.second);
      rt->sync_waiters.clear();
    }
    for (auto* w : all) {
      std::lock_guard<std::mutex> lk(w->mu);
      w->terr = DPE_IO;
      w->etext = "runtime shutdown";
      w->done = true;
      w->cv.notify_one();
    }
  }
  for (auto& l : rt->loops) {
    uint64_t one = 1;
    ssize_t r = write(l->evfd, &one, 8);
    (void)r;
  }
  for (auto& l : rt->loops) {
    if (l->thr.joinable()) l->thr.join();
  }
  // Quiesce every conn BEFORE tearing the Runtime down: mark failed and
  // retire the fd under wmu (so an in-flight writer can't land on a
  // recycled fd), then wake the TPUC machinery so blocked sender workers
  // observe closed/q_closed and exit.
  std::vector<std::shared_ptr<Conn>> conns;
  {
    std::lock_guard<std::mutex> lk(rt->cmu);
    for (auto& kv : rt->conns) conns.push_back(kv.second);
    rt->conns.clear();
    for (auto& l : rt->listeners) {
      if (l.fd >= 0) close(l.fd);
    }
  }
  for (auto& c : conns) {
    c->failed.store(true);
    {
      std::lock_guard<std::mutex> wlk(c->wmu);
      if (c->fd >= 0) close(c->fd);
      c->fd = -1;
    }
    tpu_teardown(c.get());
  }
  {
    std::lock_guard<std::mutex> lk(rt->smu_senders);
    for (auto& s : rt->senders) {
      if (s.thr.joinable()) s.thr.join();
    }
    rt->senders.clear();
  }
  conns.clear();
  {
    std::lock_guard<std::mutex> lk(rt->fmu);
    rt->flush_list.clear();
  }
  {
    std::lock_guard<std::mutex> lk(rt->emu);
    for (auto& ev : rt->events) free(ev.base);
    rt->events.clear();
    rt->ecv.notify_all();
  }
  {
    // consumers are gone: zero-copy mappings kept for them can go too
    std::lock_guard<std::mutex> lk(rt->gmu);
    rt->tpu_graveyard.clear();
  }
  for (auto& l : rt->loops) {
    close(l->epfd);
    close(l->evfd);
  }
  delete rt;
}

// Returns listener id >= 0, or -errno.
int dp_listen(void* h, const char* host, int port) {
  auto* rt = static_cast<Runtime*>(h);
  int fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK | SOCK_CLOEXEC, 0);
  if (fd < 0) return -errno;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(uint16_t(port));
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    close(fd);
    return -EINVAL;
  }
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(fd, 1024) != 0) {
    int e = errno;
    close(fd);
    return -e;
  }
  sockaddr_in bound{};
  socklen_t blen = sizeof(bound);
  getsockname(fd, reinterpret_cast<sockaddr*>(&bound), &blen);
  int lid;
  {
    std::lock_guard<std::mutex> lk(rt->cmu);
    lid = int(rt->listeners.size());
    rt->listeners.push_back({fd, ntohs(bound.sin_port)});
  }
  // all listeners live on loop 0 (accepted conns spread round-robin)
  loop_submit(rt, 0, [rt, fd, lid] {
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u64 = kListenerBit | uint64_t(lid);
    epoll_ctl(rt->loops[0]->epfd, EPOLL_CTL_ADD, fd, &ev);
  });
  return lid;
}

int dp_listener_close(void* h, int lid) {
  auto* rt = static_cast<Runtime*>(h);
  int fd = -1;
  {
    std::lock_guard<std::mutex> lk(rt->cmu);
    if (lid < 0 || size_t(lid) >= rt->listeners.size()) return -1;
    fd = rt->listeners[size_t(lid)].fd;
    rt->listeners[size_t(lid)].fd = -1;
  }
  if (fd < 0) return -1;
  loop_submit(rt, 0, [rt, fd] {
    epoll_ctl(rt->loops[0]->epfd, EPOLL_CTL_DEL, fd, nullptr);
    close(fd);
  });
  return 0;
}

int dp_listener_set_tpu(void* h, int lid, int ordinal) {
  auto* rt = static_cast<Runtime*>(h);
  std::lock_guard<std::mutex> lk(rt->cmu);
  if (lid < 0 || size_t(lid) >= rt->listeners.size()) return -1;
  rt->listeners[size_t(lid)].tpu_ordinal = ordinal;
  return 0;
}

int dp_listen_port(void* h, int lid) {
  auto* rt = static_cast<Runtime*>(h);
  std::lock_guard<std::mutex> lk(rt->cmu);
  if (lid < 0 || size_t(lid) >= rt->listeners.size()) return -1;
  return rt->listeners[size_t(lid)].port;
}

int dp_register_echo(void* h, int lid, const char* service,
                     const char* method) {
  auto* rt = static_cast<Runtime*>(h);
  if (lid < 0) return -1;
  auto svc = std::make_unique<Runtime::EchoSvc>();
  svc->lid = lid;
  svc->service = service;
  svc->method = method;
  std::lock_guard<std::mutex> lk(rt->rmu);
  rt->echo_services.push_back(std::move(svc));
  return 0;
}

// drop a listener's native services (Server teardown). Entries are marked
// dead, not freed: a loop thread may hold an EchoSvc* across the
// unregister (pointers stay valid for the runtime's lifetime).
int dp_unregister_listener_echoes(void* h, int lid) {
  auto* rt = static_cast<Runtime*>(h);
  std::lock_guard<std::mutex> lk(rt->rmu);
  for (auto& e : rt->echo_services) {
    if (e->lid == lid) e->lid = -2;
  }
  return 0;
}

// per-method concurrency limit for a native service (MethodStatus analog)
int dp_svc_set_limit(void* h, int lid, const char* service,
                     const char* method, int max_concurrency) {
  auto* rt = static_cast<Runtime*>(h);
  std::lock_guard<std::mutex> lk(rt->rmu);
  for (auto& e : rt->echo_services) {
    if (e->lid == lid && e->service == service && e->method == method) {
      e->max_concurrency = max_concurrency;
      return 0;
    }
  }
  return -1;
}

// graceful-stop: native services of this listener answer ELOGOFF
int dp_listener_set_logoff(void* h, int lid, int on) {
  auto* rt = static_cast<Runtime*>(h);
  std::lock_guard<std::mutex> lk(rt->rmu);
  for (auto& e : rt->echo_services) {
    if (e->lid == lid) e->logoff.store(on != 0, std::memory_order_relaxed);
  }
  return 0;
}

// method status counters for a native service (surfaced at /status)
int dp_svc_stats(void* h, int lid, const char* service, const char* method,
                 uint64_t* requests, uint64_t* errs, uint64_t* latency_sum_ns,
                 uint64_t* latency_max_ns, int32_t* concurrency) {
  auto* rt = static_cast<Runtime*>(h);
  std::lock_guard<std::mutex> lk(rt->rmu);
  for (auto& e : rt->echo_services) {
    if (e->lid == lid && e->service == service && e->method == method) {
      *requests = e->requests.load(std::memory_order_relaxed);
      *errs = e->errors.load(std::memory_order_relaxed);
      *latency_sum_ns = e->latency_sum_ns.load(std::memory_order_relaxed);
      *latency_max_ns = e->latency_max_ns.load(std::memory_order_relaxed);
      *concurrency = e->concurrency.load(std::memory_order_relaxed);
      return 0;
    }
  }
  return -1;
}

// Returns conn id > 0, or 0 with *err_out=errno.
uint64_t dp_connect_ex(void* h, const char* host, int port,
                       int timeout_ms, int* err_out, int grpc_mode) {
  auto* rt = static_cast<Runtime*>(h);
  int fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK | SOCK_CLOEXEC, 0);
  if (fd < 0) {
    *err_out = errno;
    return 0;
  }
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(uint16_t(port));
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    // resolve
    addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    if (getaddrinfo(host, nullptr, &hints, &res) != 0 || !res) {
      close(fd);
      *err_out = EHOSTUNREACH;
      return 0;
    }
    addr.sin_addr = reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
    freeaddrinfo(res);
  }
  int rc = connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
  if (rc != 0 && errno == EINPROGRESS) {
    pollfd pfd{fd, POLLOUT, 0};
    rc = poll(&pfd, 1, timeout_ms > 0 ? timeout_ms : 3000);
    if (rc <= 0) {
      close(fd);
      *err_out = rc == 0 ? ETIMEDOUT : errno;
      return 0;
    }
    int soerr = 0;
    socklen_t slen = sizeof(soerr);
    getsockopt(fd, SOL_SOCKET, SO_ERROR, &soerr, &slen);
    if (soerr != 0) {
      close(fd);
      *err_out = soerr;
      return 0;
    }
  } else if (rc != 0) {
    *err_out = errno;
    close(fd);
    return 0;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  int bufsz = 4 << 20;
  setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &bufsz, sizeof(bufsz));
  setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &bufsz, sizeof(bufsz));
  auto c = create_conn(rt, fd, /*is_server=*/false);
  if (grpc_mode) {
    // h2 state MUST exist before the loop thread can read from the fd
    // (a grpc server may speak first with its SETTINGS preface)
    c->h2_mode = 2;
    c->h2.reset(new H2State());
    c->h2->client = true;
    c->h2->phase = 2;
    c->h2->authority = std::string(host) + ":" + std::to_string(port);
  }
  activate_conn(rt, c);
  if (grpc_mode) {
    std::string pre(kH2Preface, kH2PrefaceLen);
    pre.append(h2_settings_prefix());
    if (conn_write(rt, c, reinterpret_cast<const uint8_t*>(pre.data()),
                   pre.size()) != DPE_OK) {
      *err_out = EPIPE;
      return 0;
    }
  }
  *err_out = 0;
  return c->id;
}

uint64_t dp_connect(void* h, const char* host, int port, int timeout_ms,
                    int* err_out) {
  return dp_connect_ex(h, host, port, timeout_ms, err_out, 0);
}

void dp_conn_close(void* h, uint64_t conn_id);

// Dial a tpu:// endpoint natively: TCP bootstrap + TPUC handshake + shm
// pools, entirely in the engine (reference RdmaEndpoint AppConnect).
// bs/bc request the tunnel window geometry (0 = defaults); the server
// mirrors them for its own receive pool, so bulk dials get bulk windows.
uint64_t dp_connect_tpu2(void* h, const char* host, int port, int ordinal,
                         int timeout_ms, uint32_t bs, uint32_t bc,
                         int* err_out) {
  auto* rt = static_cast<Runtime*>(h);
  uint64_t cid = dp_connect(h, host, port, timeout_ms, err_out);
  if (!cid) return 0;
  std::shared_ptr<Conn> c;
  {
    std::lock_guard<std::mutex> lk(rt->cmu);
    auto it = rt->conns.find(cid);
    if (it != rt->conns.end()) c = it->second;
  }
  if (!c) {
    *err_out = ECONNRESET;
    return 0;
  }
  auto* t = new TpuState();
  t->ordinal = ordinal;
  tpu_clamp_geometry(&bs, &bc);
  t->bs = bs;
  t->bc = bc;
  c->tpu.reset(t);
  c->tpu_mode = 1;  // published before any byte can arrive: the peer only
                    // speaks after our HELLO below
  if (!tpu_create_pool(t)) {
    dp_conn_close(h, cid);
    *err_out = ENOMEM;
    return 0;
  }
  std::string hello = tpu_hello_json(t, ordinal);
  const uint8_t* b[1] = {reinterpret_cast<const uint8_t*>(hello.data())};
  const uint64_t l[1] = {hello.size()};
  if (tpu_ctrl_send(rt, c, TFT_HELLO, b, l, 1) != DPE_OK) {
    dp_conn_close(h, cid);
    *err_out = EPIPE;
    return 0;
  }
  {
    std::unique_lock<std::mutex> lk(t->hmu);
    if (!t->hcv.wait_for(lk, std::chrono::milliseconds(
            timeout_ms > 0 ? timeout_ms : 3000),
            [t] { return t->ready || !t->err.empty(); })) {
      lk.unlock();
      dp_conn_close(h, cid);
      *err_out = ETIMEDOUT;
      return 0;
    }
    if (!t->ready) {
      lk.unlock();
      dp_conn_close(h, cid);
      *err_out = ECONNREFUSED;
      return 0;
    }
  }
  return cid;
}

uint64_t dp_connect_tpu(void* h, const char* host, int port, int ordinal,
                        int timeout_ms, int* err_out) {
  return dp_connect_tpu2(h, host, port, ordinal, timeout_ms, 0, 0, err_out);
}

// gRPC client conn (h2c prior knowledge): dp_call / dp_call_sync on the
// returned conn speak grpc end to end inside the engine.
uint64_t dp_connect_grpc(void* h, const char* host, int port,
                         int timeout_ms, int* err_out) {
  return dp_connect_ex(h, host, port, timeout_ms, err_out, 1);
}

int dp_send(void* h, uint64_t conn_id, const uint8_t* data, uint64_t len) {
  auto* rt = static_cast<Runtime*>(h);
  std::shared_ptr<Conn> c;
  {
    std::lock_guard<std::mutex> lk(rt->cmu);
    auto it = rt->conns.find(conn_id);
    if (it != rt->conns.end()) c = it->second;
  }
  if (!c) return DPE_NOTFOUND;
  if (c->tpu_mode != 0) {
    const uint8_t* bufs[1] = {data};
    const uint64_t lens[1] = {len};
    return tpu_send_packet(rt, c, bufs, lens, 1);
  }
  return conn_write(rt, c, data, len);
}

// Vectored variant: one RPC packet as up to 64 segments, written without
// assembling (the IOBuf ref chain crosses the boundary as pointers).
int dp_sendv(void* h, uint64_t conn_id, const uint8_t* const* bufs,
             const uint64_t* lens, int nseg) {
  if (nseg <= 0 || nseg > 64) return DPE_PROTOCOL;
  auto* rt = static_cast<Runtime*>(h);
  std::shared_ptr<Conn> c;
  {
    std::lock_guard<std::mutex> lk(rt->cmu);
    auto it = rt->conns.find(conn_id);
    if (it != rt->conns.end()) c = it->second;
  }
  if (!c) return DPE_NOTFOUND;
  if (c->tpu_mode != 0) return tpu_send_packet(rt, c, bufs, lens, nseg);
  return conn_writev(rt, c, bufs, lens, nseg);
}

// Enable parsed EV_REQUEST events for a listener's conns (Python servers
// that understand the fast path flip this right after dp_listen).
int dp_listener_set_fastpath(void* h, int lid, int on) {
  auto* rt = static_cast<Runtime*>(h);
  std::lock_guard<std::mutex> lk(rt->cmu);
  if (lid < 0 || size_t(lid) >= rt->listeners.size()) return -1;
  rt->listeners[size_t(lid)].py_fast = on != 0;
  return 0;
}

// Enable parsed EV_RESPONSE events for a client conn.
int dp_conn_set_fastpath(void* h, uint64_t conn_id, int on) {
  auto* rt = static_cast<Runtime*>(h);
  std::lock_guard<std::mutex> lk(rt->cmu);
  auto it = rt->conns.find(conn_id);
  if (it == rt->conns.end()) return -1;
  it->second->py_fast.store(on != 0, std::memory_order_relaxed);
  return 0;
}

// Server response, packed natively (server_processing._send_response with
// zero Python protobuf). queue=1 defers the write to dp_flush_all.
int dp_respond(void* h, uint64_t conn_id, uint64_t cid, uint64_t attempt,
               int error_code, const char* etext, uint64_t etext_len,
               const uint8_t* payload, uint64_t plen, const uint8_t* att,
               uint64_t alen, int compress_type, int queue) {
  auto* rt = static_cast<Runtime*>(h);
  std::shared_ptr<Conn> c;
  {
    std::lock_guard<std::mutex> lk(rt->cmu);
    auto it = rt->conns.find(conn_id);
    if (it != rt->conns.end()) c = it->second;
  }
  if (!c) return DPE_NOTFOUND;
  if (c->h2_mode != 0) {
    // grpc stream response: cid IS the h2 stream id
    return h2_grpc_respond(rt, c, uint32_t(cid), error_code, etext,
                           etext_len, payload, plen, att, alen, queue);
  }
  std::string meta = build_response_meta(cid, attempt, error_code, etext,
                                         etext_len, alen,
                                         int32_t(compress_type));
  std::string head;
  head.reserve(kHeaderSize + meta.size());
  put_trpc_header(&head, meta.size(), plen + alen);
  head.append(meta);
  if (queue) {
    queue_packet(rt, c, head, payload, plen, att, alen);
    return DPE_OK;
  }
  const uint8_t* bufs[3] = {reinterpret_cast<const uint8_t*>(head.data()),
                            payload, att};
  const uint64_t lens[3] = {head.size(), plen, alen};
  int nseg = alen ? 3 : (plen ? 2 : 1);
  if (c->tpu_mode != 0) return tpu_send_packet(rt, c, bufs, lens, nseg);
  return conn_writev(rt, c, bufs, lens, nseg);
}

// Client request, packed natively (Controller._issue_rpc's meta build with
// zero Python protobuf). queue=1 defers the write to dp_flush_all.
int dp_call(void* h, uint64_t conn_id, const char* svc, uint64_t svc_len,
            const char* meth, uint64_t meth_len, uint64_t cid,
            uint64_t attempt, int64_t log_id, int64_t trace_id,
            int64_t span_id, int32_t timeout_ms, const uint8_t* payload,
            uint64_t plen, const uint8_t* att, uint64_t alen, int queue) {
  auto* rt = static_cast<Runtime*>(h);
  std::shared_ptr<Conn> c;
  {
    std::lock_guard<std::mutex> lk(rt->cmu);
    auto it = rt->conns.find(conn_id);
    if (it != rt->conns.end()) c = it->second;
  }
  if (!c) return DPE_NOTFOUND;
  if (c->h2_mode != 0) {
    return h2_grpc_call(rt, c, svc, svc_len, meth, meth_len, cid,
                        timeout_ms, payload, plen, att, alen, queue);
  }
  std::string meta = build_request_meta(svc, svc_len, meth, meth_len, cid,
                                        attempt, log_id, trace_id, span_id,
                                        timeout_ms, alen);
  std::string head;
  head.reserve(kHeaderSize + meta.size());
  put_trpc_header(&head, meta.size(), plen + alen);
  head.append(meta);
  if (queue) {
    queue_packet(rt, c, head, payload, plen, att, alen);
    return DPE_OK;
  }
  const uint8_t* bufs[3] = {reinterpret_cast<const uint8_t*>(head.data()),
                            payload, att};
  const uint64_t lens[3] = {head.size(), plen, alen};
  int nseg = alen ? 3 : (plen ? 2 : 1);
  if (c->tpu_mode != 0) return tpu_send_packet(rt, c, bufs, lens, nseg);
  return conn_writev(rt, c, bufs, lens, nseg);
}

// Struct-parameter call (layout mirrored by _CALL_IN in
// rpc/native_transport.py): the async client lane's dp_call with 17
// marshalled scalars folded into one reusable param block.
struct CallParams {
  uint64_t conn_id;    //  0
  uint64_t cid;        //  8
  int64_t log_id;      // 16
  int64_t trace_id;    // 24
  int64_t span_id;     // 32
  int32_t timeout_ms;  // 40
  int32_t queue;       // 44
};

int dp_call2(void* h, const uint8_t* pb, const char* svc,
             uint64_t svc_len, const char* meth, uint64_t meth_len,
             const uint8_t* payload, uint64_t plen, const uint8_t* att,
             uint64_t alen) {
  auto* p = reinterpret_cast<const CallParams*>(pb);
  return dp_call(h, p->conn_id, svc, svc_len, meth, meth_len, p->cid, 0,
                 p->log_id, p->trace_id, p->span_id, p->timeout_ms,
                 payload, plen, att, alen, p->queue);
}

// Struct-parameter respond (layout mirrored by _RESPOND_IN in
// rpc/native_transport.py): 13 marshalled scalars -> pointers + sizes.
struct RespondParams {
  uint64_t conn_id;    //  0
  uint64_t cid;        //  8
  uint64_t attempt;    // 16
  int32_t error_code;  // 24
  int32_t compress;    // 28
  int32_t queue;       // 32
  int32_t _pad;        // 36
};

int dp_respond2(void* h, const uint8_t* pb, const char* etext,
                uint64_t etext_len, const uint8_t* payload, uint64_t plen,
                const uint8_t* att, uint64_t alen) {
  auto* p = reinterpret_cast<const RespondParams*>(pb);
  return dp_respond(h, p->conn_id, p->cid, p->attempt, p->error_code,
                    etext, etext_len, payload, plen, att, alen,
                    p->compress, p->queue);
}

// Blocking fast call: the calling (Python) thread parks HERE, in C, with
// the GIL released — the engine's parse thread completes it directly.
// Returns DPE_OK when an RPC-level answer arrived (out_code = app error
// code, body ownership passes to the caller: free via dp_free(out_base)),
// DPE_TIMEDOUT on deadline, other DPE_* on transport failure.
int dp_call_sync(void* h, uint64_t conn_id, const char* svc,
                 uint64_t svc_len, const char* meth, uint64_t meth_len,
                 uint64_t cid, int64_t log_id, int64_t trace_id,
                 int64_t span_id, int32_t timeout_ms,
                 const uint8_t* payload, uint64_t plen, const uint8_t* att,
                 uint64_t alen, int32_t* out_code, uint64_t* out_attempt,
                 uint64_t* out_att_size, void** out_base, void** out_body,
                 uint64_t* out_body_len, char* etext_buf,
                 uint64_t* etext_cap_len) {
  auto* rt = static_cast<Runtime*>(h);
  SyncWaiter w;
  w.cid = cid;
  w.conn_id = conn_id;
  {
    std::lock_guard<std::mutex> lk(rt->swmu);
    rt->sync_waiters.emplace(cid, &w);
  }
  int rc = dp_call(h, conn_id, svc, svc_len, meth, meth_len, cid, 0,
                   log_id, trace_id, span_id, timeout_ms, payload, plen,
                   att, alen, 0);
  if (rc != DPE_OK) {
    if (sync_take(rt, cid) != nullptr) {  // nobody owns us: bail
      if (etext_cap_len) *etext_cap_len = 0;
      return rc;
    }
    // a completer (conn_fail fan-out) already took the waiter — it is
    // committed to signaling; take its verdict below
  }
  {
    std::unique_lock<std::mutex> lk(w.mu);
    if (timeout_ms > 0) {
      if (!w.cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                         [&] { return w.done; })) {
        lk.unlock();
        if (sync_take(rt, cid) != nullptr) {
          if (etext_cap_len) *etext_cap_len = 0;
          return DPE_TIMEDOUT;
        }
        lk.lock();  // completion in flight: it is quick, wait it out
        w.cv.wait(lk, [&] { return w.done; });
      }
    } else {
      w.cv.wait(lk, [&] { return w.done; });
    }
  }
  uint64_t cap = etext_cap_len ? *etext_cap_len : 0;
  uint64_t n = cap < w.etext.size() ? cap : w.etext.size();
  if (n) memcpy(etext_buf, w.etext.data(), n);
  if (etext_cap_len) *etext_cap_len = n;
  if (w.terr) return w.terr;
  *out_code = w.code;
  *out_attempt = w.attempt;
  *out_att_size = w.att_size;
  *out_base = w.base;
  *out_body = w.body;
  *out_body_len = w.body_len;
  return DPE_OK;
}

// Struct-parameter variant of dp_call_sync: ctypes marshals TWO pointer
// args instead of 23 scalars (~4us/call of marshalling on the shared
// core). Layout mirrored by _SYNC_PARAMS in rpc/native_transport.py.
struct SyncCallParams {
  uint64_t conn_id;    //  0  in
  uint64_t cid;        //  8  in
  int64_t log_id;      // 16  in
  int64_t trace_id;    // 24  in
  int64_t span_id;     // 32  in
  int32_t timeout_ms;  // 40  in
  int32_t code;        // 44  out: app error code
  uint64_t attempt;    // 48  out
  uint64_t att_size;   // 56  out
  uint64_t base;       // 64  out: free handle (dp_free)
  uint64_t body;       // 72  out
  uint64_t body_len;   // 80  out
  uint64_t etext_len;  // 88  out
  char etext[256];     // 96  out
};

int dp_call_sync2(void* h, uint8_t* pb, const char* svc, uint64_t svc_len,
                  const char* meth, uint64_t meth_len,
                  const uint8_t* payload, uint64_t plen,
                  const uint8_t* att, uint64_t alen) {
  auto* p = reinterpret_cast<SyncCallParams*>(pb);
  int32_t code = 0;
  uint64_t attempt = 0, att_size = 0, blen = 0;
  void* base = nullptr;
  void* body = nullptr;
  uint64_t elen = sizeof(p->etext);
  int rc = dp_call_sync(h, p->conn_id, svc, svc_len, meth, meth_len,
                        p->cid, p->log_id, p->trace_id, p->span_id,
                        p->timeout_ms, payload, plen, att, alen, &code,
                        &attempt, &att_size, &base, &body, &blen,
                        p->etext, &elen);
  p->code = code;
  p->attempt = attempt;
  p->att_size = att_size;
  p->base = reinterpret_cast<uint64_t>(base);
  p->body = reinterpret_cast<uint64_t>(body);
  p->body_len = blen;
  p->etext_len = elen;
  return rc;
}

// Python-side fallback completion: a response that needed Python policy
// (decompression, big donated frame via EV_FRAME, ZC tunnel reassembly)
// finishes a parked sync caller through here.
int dp_sync_complete_py(void* h, uint64_t cid, int32_t code,
                        const char* etext, uint64_t elen,
                        const uint8_t* body, uint64_t blen,
                        uint64_t att_size, uint64_t attempt) {
  auto* rt = static_cast<Runtime*>(h);
  SyncWaiter* w = sync_take(rt, cid);
  if (w == nullptr) return DPE_NOTFOUND;
  uint8_t* blk = nullptr;
  if (blen) {
    blk = static_cast<uint8_t*>(malloc(blen));
    memcpy(blk, body, blen);
  }
  sync_complete(w, code, attempt, att_size, etext, elen, blk, blk, blen);
  return DPE_OK;
}

// Return the pool blocks named by an EV_RESPONSE_ZC ack blob to the peer
// (the consumer has finished reading the zero-copy segments).
int dp_tpu_ack(void* h, uint64_t conn_id, const uint8_t* ack, uint64_t len) {
  auto* rt = static_cast<Runtime*>(h);
  std::shared_ptr<Conn> c;
  {
    std::lock_guard<std::mutex> lk(rt->cmu);
    auto it = rt->conns.find(conn_id);
    if (it != rt->conns.end()) c = it->second;
  }
  if (!c) return DPE_NOTFOUND;  // conn died; its pool sits in the graveyard
  c->zc_outstanding.fetch_sub(1, std::memory_order_relaxed);
  if (c->failed.load()) return DPE_IO;
  const uint8_t* b[1] = {ack};
  const uint64_t l[1] = {len};
  return tpu_ctrl_send(rt, c, TFT_ACK, b, l, 1);
}

// Drain every conn with queued packets (call once per answered poll batch).
int dp_flush_all(void* h) {
  auto* rt = static_cast<Runtime*>(h);
  std::vector<std::shared_ptr<Conn>> conns;
  {
    std::lock_guard<std::mutex> lk(rt->fmu);
    conns.swap(rt->flush_list);
  }
  int bad = 0;
  for (auto& c : conns) {
    if (flush_conn_pending(rt, c) != DPE_OK) bad++;
  }
  return bad;
}

int dp_poll(void* h, DpEvent* out, int maxn, int timeout_ms) {
  auto* rt = static_cast<Runtime*>(h);
  std::unique_lock<std::mutex> lk(rt->emu);
  if (rt->events.empty()) {
    rt->ecv.wait_for(lk, std::chrono::milliseconds(timeout_ms), [rt] {
      return !rt->events.empty() || !rt->running.load();
    });
  }
  int n = 0;
  while (n < maxn && !rt->events.empty()) {
    out[n] = rt->events.front();
    rt->event_bytes -=
        out[n].meta_len + out[n].body_len + sizeof(DpEvent);
    rt->events.pop_front();
    n++;
  }
  return n;
}

// Batched event delivery with inline payloads: one ctypes call + ONE
// buffer read hands Python a whole poll batch (VERDICT r3 #1 — the
// interpreter boundary is crossed per BATCH, not per event). Small events
// are memcpy'd back-to-back into the caller's buffer and freed here (no
// per-event dp_free crossing); big events (donated read buffers, ZC
// tunnel descriptors) stay zero-copy as pointer records the consumer
// frees as before. Record layout (host endian, packed):
//   i32 kind (bit 30 set = pointer record)  i32 tag
//   u64 conn_id  i64 aux  u64 meta_len  u64 body_len
//   inline:  meta bytes, body bytes
//   pointer: u64 base, u64 meta_ptr, u64 body_ptr
constexpr int32_t kPackedPtrFlag = 1 << 30;
constexpr uint64_t kPackInlineMax = 8 << 10;  // per-event inline budget
constexpr uint64_t kPackedHdr = 40;

int dp_poll_packed(void* h, uint8_t* buf, uint64_t cap, int timeout_ms,
                   int maxn) {
  auto* rt = static_cast<Runtime*>(h);
  // Phase 1 (under the event lock): POP the fitting events into a local
  // batch — fit arithmetic only, no memcpy/free, so the engine's parse
  // threads never stall on rt->emu behind a megabyte of packing.
  std::vector<DpEvent> batch;
  {
    std::unique_lock<std::mutex> lk(rt->emu);
    if (rt->events.empty()) {
      rt->ecv.wait_for(lk, std::chrono::milliseconds(timeout_ms), [rt] {
        return !rt->events.empty() || !rt->running.load();
      });
    }
    uint64_t off = 0;
    while (int(batch.size()) < maxn && !rt->events.empty()) {
      DpEvent& ev = rt->events.front();
      uint64_t blen = ev.body ? ev.body_len : 0;
      uint64_t blob = ev.meta_len + blen;
      uint64_t need = kPackedHdr + (blob <= kPackInlineMax ? blob : 24);
      if (off + need > cap) break;  // delivered next call
      off += need;
      rt->event_bytes -= ev.meta_len + ev.body_len + sizeof(DpEvent);
      batch.push_back(ev);
      rt->events.pop_front();
    }
  }
  // Phase 2 (lock-free): pack into the caller's buffer.
  uint64_t off = 0;
  for (DpEvent& ev : batch) {
    // EV_RESPONSE_ZC carries body=nullptr with an INFORMATIONAL body_len
    // (the payload lives in pool blocks named by the meta); copy/ship
    // only bytes that exist
    uint64_t blen = ev.body ? ev.body_len : 0;
    uint64_t blob = ev.meta_len + blen;
    bool inlined = blob <= kPackInlineMax;
    uint8_t* p = buf + off;
    int32_t kind = ev.kind | (inlined ? 0 : kPackedPtrFlag);
    memcpy(p, &kind, 4);
    memcpy(p + 4, &ev.tag, 4);
    memcpy(p + 8, &ev.conn_id, 8);
    memcpy(p + 16, &ev.aux, 8);
    memcpy(p + 24, &ev.meta_len, 8);
    memcpy(p + 32, &blen, 8);
    p += kPackedHdr;
    if (inlined) {
      if (ev.meta_len) memcpy(p, ev.meta, ev.meta_len);
      if (blen) memcpy(p + ev.meta_len, ev.body, blen);
      free(ev.base);
      off += kPackedHdr + blob;
    } else {
      uint64_t base = reinterpret_cast<uint64_t>(ev.base);
      uint64_t mp = reinterpret_cast<uint64_t>(ev.meta);
      uint64_t bp = reinterpret_cast<uint64_t>(ev.body);
      memcpy(p, &base, 8);
      memcpy(p + 8, &mp, 8);
      memcpy(p + 16, &bp, 8);
      off += kPackedHdr + 24;
    }
  }
  return int(off);  // bytes written; 0 = timeout/empty
}

void dp_free(void* base) { free(base); }

void dp_conn_close(void* h, uint64_t conn_id) {
  auto* rt = static_cast<Runtime*>(h);
  std::shared_ptr<Conn> c;
  {
    std::lock_guard<std::mutex> lk(rt->cmu);
    auto it = rt->conns.find(conn_id);
    if (it != rt->conns.end()) c = it->second;
  }
  if (!c) return;
  loop_submit(rt, c->loop,
              [rt, c] { conn_fail(rt, c, DPE_EOF, "closed locally"); });
}

int dp_conn_stats(void* h, uint64_t conn_id, uint64_t* in_bytes,
                  uint64_t* out_bytes, uint64_t* in_msgs,
                  uint64_t* out_msgs) {
  auto* rt = static_cast<Runtime*>(h);
  std::lock_guard<std::mutex> lk(rt->cmu);
  auto it = rt->conns.find(conn_id);
  if (it == rt->conns.end()) return -1;
  auto& c = it->second;
  *in_bytes = c->in_bytes.load();
  *out_bytes = c->out_bytes.load();
  *in_msgs = c->in_msgs.load();
  *out_msgs = c->out_msgs.load();
  return 0;
}

// ------------------------------------------------------------------ bench
// The reference measures its framework with C++ client binaries
// (example/multi_threaded_echo_c++/client.cpp, rdma_performance/client.cpp).
// This is ours: a pipelined echo client that drives the SAME engine lane
// (dp_connect / conn_writev / the frame cutter) against a server, entirely
// in C++, and reports QPS + latency percentiles + bandwidth.
int dp_bench_echo2(const char* host, int port, int use_tpu, int nconns,
                   int depth, uint64_t payload_len, int duration_ms,
                   const char* service, const char* method,
                   double* out_qps, double* out_gbps, double* out_p50_us,
                   double* out_p99_us, double* out_p999_us) {
  void* h = dp_rt_create(2, 0);
  // request packet: header + meta(RequestMeta{service,method}, cid) + body
  std::string reqmeta_tail;  // everything except the cid varint
  {
    std::string rm;
    pb_put_tag(&rm, 1, 2);
    pb_put_varint(&rm, strlen(service));
    rm.append(service);
    pb_put_tag(&rm, 2, 2);
    pb_put_varint(&rm, strlen(method));
    rm.append(method);
    pb_put_tag(&reqmeta_tail, 1, 2);
    pb_put_varint(&reqmeta_tail, rm.size());
    reqmeta_tail.append(rm);
  }
  std::string body(size_t(payload_len), '\xab');
  // bulk payloads dial with a bulk window: ~8 messages in flight
  // (negotiated geometry; the server mirrors it)
  uint32_t want_bs = 0, want_bc = 0;
  if (use_tpu == 1 && payload_len > (256u << 10)) {
    want_bs = uint32_t(std::min<uint64_t>(4u << 20, payload_len / 8));
    want_bc = 64;
  }
  std::vector<uint64_t> conns;
  for (int i = 0; i < nconns; i++) {
    int err = 0;
    // use_tpu: 0 = plain TCP trpc_std, 1 = TPUC tunnel, 2 = grpc/h2
    uint64_t cid = use_tpu == 1
        ? dp_connect_tpu2(h, host, port, 0, 5000, want_bs, want_bc, &err)
        : use_tpu == 2
            ? dp_connect_grpc(h, host, port, 3000, &err)
            : dp_connect(h, host, port, 3000, &err);
    if (!cid) {
      dp_rt_shutdown(h);
      return -1;
    }
    // parsed EV_RESPONSE completions: cid arrives pre-cracked in ev.aux
    dp_conn_set_fastpath(h, cid, 1);
    conns.push_back(cid);
  }
  std::atomic<uint64_t> done_count{0}, errors_seen{0};
  std::atomic<bool> stop{false};
  std::mutex lat_mu;
  std::vector<double> latencies;
  latencies.reserve(1 << 20);
  // per-correlation-id send timestamps (cid space: conn_index * depth + slot)
  std::vector<std::atomic<int64_t>> sent_ns(size_t(nconns) * depth);
  auto now_ns = [] {
    timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return int64_t(ts.tv_sec) * 1000000000 + ts.tv_nsec;
  };
  // queued sends (one writev per conn per poll batch via dp_flush_all —
  // the same batched lane the Python fast path drives)
  // queueing copies the payload once — a win for small frames (syscalls
  // dominate), a loss for MB-scale ones (writev from the caller's buffer)
  const int q_mode = payload_len < (64 << 10) ? 1 : 0;
  auto send_one = [&](int conn_idx, int slot) {
    uint64_t cid = uint64_t(conn_idx) * depth + slot + 1;
    sent_ns[cid - 1].store(now_ns(), std::memory_order_relaxed);
    return dp_call(h, conns[size_t(conn_idx)], service, strlen(service),
                   method, strlen(method), cid, 0, 0, 0, 0, 0,
                   reinterpret_cast<const uint8_t*>(body.data()),
                   body.size(), nullptr, 0, q_mode);
  };
  // prime the pipeline
  for (int ci = 0; ci < nconns; ci++) {
    for (int s = 0; s < depth; s++) {
      if (send_one(ci, s) != DPE_OK) {
        dp_rt_shutdown(h);
        return -2;
      }
    }
  }
  dp_flush_all(h);
  int64_t t_start = now_ns();
  int64_t t_end = t_start + int64_t(duration_ms) * 1000000;
  // consumer: poll completions, re-issue (the framework's event queue IS
  // the completion channel; same lane Python uses)
  std::vector<DpEvent> evs(256);
  while (!stop.load()) {
    int n = dp_poll(h, evs.data(), int(evs.size()), 50);
    int64_t now = now_ns();
    bool queued = false;
    for (int i = 0; i < n; i++) {
      DpEvent& ev = evs[i];
      uint64_t cid = 0;
      if (ev.kind == EV_RESPONSE) {
        cid = uint64_t(ev.aux);
      } else if (ev.kind == EV_RESPONSE_ZC) {
        // zero-copy completion: touch the payload views (they live in OUR
        // registered pool — that IS the receive), then return the credits
        cid = uint64_t(ev.aux);
        const uint8_t* mp = static_cast<const uint8_t*>(ev.meta);
        uint32_t nv;
        memcpy(&nv, mp + sizeof(RespLite), 4);
        const uint8_t* w = mp + sizeof(RespLite) + 4;
        volatile uint8_t sink = 0;
        for (uint32_t v = 0; v < nv; v++) {
          uint64_t p, ln;
          memcpy(&p, w, 8);
          memcpy(&ln, w + 8, 8);
          if (ln) sink ^= *reinterpret_cast<const uint8_t*>(p);
          w += 16;
        }
        (void)sink;
        uint32_t alen;
        memcpy(&alen, w, 4);
        dp_tpu_ack(h, ev.conn_id, w + 4, alen);
      } else if (ev.kind == EV_FRAME) {
        // big frames (>=64KB) still arrive as donated EV_FRAME buffers
        MetaLite m;
        const uint8_t* mp = static_cast<const uint8_t*>(ev.meta);
        if (parse_meta_lite(mp, mp + ev.meta_len, &m)) {
          cid = m.correlation_id;
        }
      } else if (ev.kind == EV_FAILED) {
        errors_seen.fetch_add(1);
      }
      if (cid && cid <= uint64_t(nconns) * uint64_t(depth)) {
        int64_t t0 = sent_ns[cid - 1].load(std::memory_order_relaxed);
        {
          std::lock_guard<std::mutex> lk(lat_mu);
          latencies.push_back(double(now - t0) / 1000.0);
        }
        done_count.fetch_add(1);
        if (now < t_end) {
          int conn_idx = int((cid - 1) / depth);
          int slot = int((cid - 1) % depth);
          send_one(conn_idx, slot);
          queued = true;
        }
      }
      free(ev.base);
    }
    if (queued) dp_flush_all(h);
    if (now >= t_end) {
      // drain stragglers briefly, then stop
      static const int64_t grace = 200000000;
      if (now >= t_end + grace) stop.store(true);
      if (n == 0) stop.store(true);
    }
    if (errors_seen.load() > uint64_t(nconns)) {
      dp_rt_shutdown(h);
      return -3;
    }
  }
  int64_t elapsed = now_ns() - t_start;
  double secs = double(elapsed) / 1e9;
  uint64_t completed = done_count.load();
  std::sort(latencies.begin(), latencies.end());
  auto pct = [&](double p) -> double {
    if (latencies.empty()) return 0.0;
    size_t idx = size_t(p * double(latencies.size()));
    if (idx >= latencies.size()) idx = latencies.size() - 1;
    return latencies[idx];
  };
  *out_qps = double(completed) / secs;
  *out_gbps = 2.0 * double(payload_len) * double(completed) / secs / 1e9;
  *out_p50_us = pct(0.5);
  *out_p99_us = pct(0.99);
  *out_p999_us = pct(0.999);
  dp_rt_shutdown(h);
  return 0;
}

int dp_bench_echo(const char* host, int port, int nconns, int depth,
                  uint64_t payload_len, int duration_ms,
                  const char* service, const char* method,
                  double* out_qps, double* out_gbps, double* out_p50_us,
                  double* out_p99_us, double* out_p999_us) {
  return dp_bench_echo2(host, port, 0, nconns, depth, payload_len,
                        duration_ms, service, method, out_qps, out_gbps,
                        out_p50_us, out_p99_us, out_p999_us);
}

}  // extern "C"
