// brpc_tpu native core — hot-path primitives behind ctypes.
//
// Counterpart of the reference's native base kit: crc32c (butil/crc32c.cc,
// hardware-accelerated with a software fallback), fast_rand
// (butil/fast_rand.cpp, wyrand-style), and a batched TRPC frame scanner
// (the inner loop of InputMessenger::CutInputMessage, input_messenger.cpp:84,
// done natively so pipelined traffic cuts N frames per interpreter call).
//
// Build: g++ -O3 -shared -fPIC (see brpc_tpu/native/__init__.py); exposes a
// plain C ABI so ctypes needs no binding generator.

#include <cstdint>
#include <cstring>

#if defined(__SSE4_2__)
#include <nmmintrin.h>
#endif

extern "C" {

// ------------------------------------------------------------------ crc32c
static uint32_t g_crc_table[8][256];
static bool g_crc_init = false;

static void crc32c_init_table() {
    const uint32_t POLY = 0x82F63B78u;
    for (int i = 0; i < 256; ++i) {
        uint32_t crc = (uint32_t)i;
        for (int j = 0; j < 8; ++j)
            crc = (crc & 1) ? (crc >> 1) ^ POLY : crc >> 1;
        g_crc_table[0][i] = crc;
    }
    for (int i = 0; i < 256; ++i) {
        uint32_t crc = g_crc_table[0][i];
        for (int k = 1; k < 8; ++k) {
            crc = g_crc_table[0][crc & 0xFF] ^ (crc >> 8);
            g_crc_table[k][i] = crc;
        }
    }
    g_crc_init = true;
}

uint32_t tn_crc32c(const uint8_t* data, uint64_t len, uint32_t value) {
    uint32_t crc = value ^ 0xFFFFFFFFu;
#if defined(__SSE4_2__)
    while (len >= 8) {
        uint64_t chunk;
        memcpy(&chunk, data, 8);
        crc = (uint32_t)_mm_crc32_u64((uint64_t)crc, chunk);
        data += 8;
        len -= 8;
    }
    while (len--) crc = _mm_crc32_u8(crc, *data++);
#else
    if (!g_crc_init) crc32c_init_table();
    // slicing-by-8
    while (len >= 8) {
        uint64_t chunk;
        memcpy(&chunk, data, 8);
        crc ^= (uint32_t)chunk;
        uint32_t hi = (uint32_t)(chunk >> 32);
        crc = g_crc_table[7][crc & 0xFF] ^ g_crc_table[6][(crc >> 8) & 0xFF] ^
              g_crc_table[5][(crc >> 16) & 0xFF] ^ g_crc_table[4][crc >> 24] ^
              g_crc_table[3][hi & 0xFF] ^ g_crc_table[2][(hi >> 8) & 0xFF] ^
              g_crc_table[1][(hi >> 16) & 0xFF] ^ g_crc_table[0][hi >> 24];
        data += 8;
        len -= 8;
    }
    while (len--)
        crc = g_crc_table[0][(crc ^ *data++) & 0xFF] ^ (crc >> 8);
#endif
    return crc ^ 0xFFFFFFFFu;
}

// --------------------------------------------------------------- fast_rand
// wyrand-style: one 64-bit state word, multiply-xorshift output.
uint64_t tn_fast_rand(uint64_t* state) {
    *state += 0xa0761d6478bd642full;
    __uint128_t t = (__uint128_t)(*state ^ 0xe7037ed1a0b428dbull) * (*state);
    return (uint64_t)(t >> 64) ^ (uint64_t)t;
}

uint64_t tn_fast_rand_less_than(uint64_t* state, uint64_t bound) {
    if (bound == 0) return 0;
    // Lemire's multiply-shift bounded rand (no modulo bias worth caring
    // about at these ranges; the reference's fast_rand is similarly loose)
    __uint128_t m = (__uint128_t)tn_fast_rand(state) * bound;
    return (uint64_t)(m >> 64);
}

// ------------------------------------------------------------ frame scanner
// Scan consecutive complete "TRPC"/"TSTR" frames in a contiguous buffer.
// Writes for each complete frame: offsets[i*3] = frame start,
// offsets[i*3+1] = meta_size, offsets[i*3+2] = body_size. Returns the
// number of complete frames (<= max_frames); *consumed = bytes covered by
// them. Returns -1 on a malformed header (bad magic at a frame boundary or
// size > max_body), with *consumed = bytes up to the bad frame.
int tn_frame_scan(const uint8_t* buf, uint64_t len, uint64_t max_body,
                  uint64_t* offsets, int max_frames, uint64_t* consumed) {
    uint64_t pos = 0;
    int n = 0;
    while (n < max_frames && len - pos >= 12) {
        const uint8_t* h = buf + pos;
        bool trpc = (h[0] == 'T' && h[1] == 'R' && h[2] == 'P' && h[3] == 'C');
        bool tstr = (h[0] == 'T' && h[1] == 'S' && h[2] == 'T' && h[3] == 'R');
        if (!trpc && !tstr) {
            *consumed = pos;
            return -1;
        }
        uint32_t meta_size = ((uint32_t)h[4] << 24) | ((uint32_t)h[5] << 16) |
                             ((uint32_t)h[6] << 8) | (uint32_t)h[7];
        uint32_t body_size = ((uint32_t)h[8] << 24) | ((uint32_t)h[9] << 16) |
                             ((uint32_t)h[10] << 8) | (uint32_t)h[11];
        if ((uint64_t)meta_size + body_size > max_body) {
            *consumed = pos;
            return -1;
        }
        uint64_t total = 12ull + meta_size + body_size;
        if (len - pos < total) break;  // incomplete tail frame
        offsets[n * 3] = pos;
        offsets[n * 3 + 1] = meta_size;
        offsets[n * 3 + 2] = body_size;
        pos += total;
        ++n;
    }
    *consumed = pos;
    return n;
}

// ------------------------------------------------------------------- probe
int tn_abi_version() { return 1; }

}  // extern "C"
