"""Generated protobuf modules (protoc --python_out from the .proto sources)."""
