"""brpc_tpu — a TPU-native RPC/collective framework with bRPC's capabilities.

Rebuild of Apache bRPC (reference: /root/reference, v1.15.0) designed
TPU-first: the control plane is a Channel/Server/Controller RPC engine over
TCP bootstrap sockets; the data plane rides PJRT host<->HBM transfers and XLA
collectives over ICI/DCN (`brpc_tpu.tpu`). Combo channels (Parallel/
Partition/Selective) lower onto mesh-axis collectives via shard_map.

Layers (mirrors SURVEY.md §1):
  butil/   — IOBuf, EndPoint (incl tpu://), versioned pools, DoublyBuffered
  fiber/   — task runtime: execution queues, timers, versioned call ids
  metrics/ — bvar equivalent: contention-free counters, windows, percentiles
  rpc/     — Socket, EventDispatcher, InputMessenger, Channel/Server/Controller,
             Stream, ParallelChannel/PartitionChannel/SelectiveChannel
  policy/  — protocols, load balancers, naming services, limiters
  tpu/     — TpuSocket, mesh naming, collective lowering, ring primitives
  builtin/ — observability HTTP services (/status /vars /flags /rpcz ...)
  trace/   — span/rpcz, rpc_dump/replay
  native/  — C++ core (event loop, framing, crc32c) via ctypes
"""

__version__ = "0.1.0"

# ------------------------------------------------------------ public surface
# The front door mirrors the reference's brpc/ headers: everything a user of
# channel.h/server.h/stream.h/parallel_channel.h reaches for, importable
# from the package root. (brpc_tpu.tpu is imported explicitly — it pulls in
# jax, which the RPC core does not need.)
from brpc_tpu.rpc import (  # noqa: E402
    Channel,
    ChannelOptions,
    Controller,
    GenericService,
    MethodDescriptor,
    RawMessage,
    RpcError,
    Server,
    ServerOptions,
    Service,
    Stub,
    errors,
)
from brpc_tpu.rpc.combo_channels import (  # noqa: E402
    SKIP,
    CallMapper,
    DynamicPartitionChannel,
    ParallelChannel,
    PartitionChannel,
    PartitionParser,
    ResponseMerger,
    SelectiveChannel,
)
from brpc_tpu.rpc.ssl_helper import (  # noqa: E402
    ClientSslOptions,
    ServerSslOptions,
)
from brpc_tpu.rpc.stream import (  # noqa: E402
    StreamOptions,
    stream_accept,
    stream_close,
    stream_create,
    stream_write,
)

__all__ = [
    "__version__",
    "Channel", "ChannelOptions", "Controller", "GenericService",
    "MethodDescriptor", "RawMessage", "RpcError", "Server", "ServerOptions",
    "Service", "Stub", "errors",
    "SKIP", "CallMapper", "DynamicPartitionChannel", "ParallelChannel",
    "PartitionChannel", "PartitionParser", "ResponseMerger",
    "SelectiveChannel",
    "ClientSslOptions", "ServerSslOptions",
    "StreamOptions", "stream_accept", "stream_close", "stream_create",
    "stream_write",
]
