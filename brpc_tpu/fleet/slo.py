"""SLO engine — declarative objectives, multi-window error-budget burn.

An :class:`SloObjective` states what "good" means for one method (or one
tenant's lane): a latency bound its p99 must stay under, and an error-rate
ceiling, each allowed to be violated at most an ``objective`` fraction of
the time. Every sampler tick the engine measures, over a fast and a slow
rolling window of the 1-second series:

- **latency burn** — the fraction of window seconds whose p99 sample broke
  the bound, divided by ``objective`` (burn 1.0 = spending budget exactly
  at the allowed rate, >1 = burning it down);
- **error burn** — errors/total over the window divided by ``objective``.

The per-window burn is the worse of the two. The headline
``g_slo_<name>_burn`` gauge is the **min of the fast and slow burns** —
the standard multi-window gate: the fast window must agree (it's really
happening now) *and* the slow window must agree (it's not a one-second
blip), which is what makes the paired ``slo_burn_<name>`` watch rule both
quick and flap-resistant. Bounds are reloadable via the
``slo_burn_threshold`` flag; objectives install declaratively through the
``slo_objectives`` flag or programmatically via :func:`global_slo`.

On a fleet observer the engine reads the scrape-merged series (cluster
view); standalone it reads the local registry. Either way evaluation runs
as a series post-tick hook writing a plain cached dict, and the exposed
``g_slo_*`` vars only read that cache — a var whose get_value touched the
series registry would deadlock inside the sweep's lock.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from brpc_tpu import flags as _flags
from brpc_tpu.fleet.merge import MergedVar
from brpc_tpu.metrics.series import (
    SeriesRegistry,
    ensure_series_installed,
    global_series,
)
from brpc_tpu.metrics.watch import (
    KIND_THRESHOLD,
    WatchRule,
    ensure_watch_hooked,
    global_watch,
)

slo_burn_threshold = _flags.define(
    "slo_burn_threshold", 1.0,
    "slo_burn_* watch rules fire when an objective's multi-window burn "
    "rate (min of fast and slow) exceeds this (reloadable: the rules "
    "read the flag at every tick)", validator=lambda v: v > 0)


def _slug(text: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_"
                   for c in text.strip().lower())


class SloObjective:
    """One declarative objective over a method's (or tenant lane's) vars."""

    def __init__(self, name: str, latency_var: str = "",
                 latency_bound_us: float = 0.0, errors_var: str = "",
                 total_var: str = "", objective: float = 0.01,
                 fast_window_s: int = 10, slow_window_s: int = 60,
                 tenant: str = ""):
        if not (0.0 < objective <= 1.0):
            raise ValueError(f"objective {objective!r} out of (0, 1]")
        if fast_window_s < 2 or slow_window_s < fast_window_s:
            raise ValueError("need slow_window_s >= fast_window_s >= 2")
        if not latency_var and not errors_var:
            raise ValueError("objective needs a latency_var or errors_var")
        self.name = _slug(name)
        self.latency_var = latency_var
        self.latency_bound_us = float(latency_bound_us)
        self.errors_var = errors_var
        self.total_var = total_var
        self.objective = float(objective)
        self.fast_window_s = int(fast_window_s)
        self.slow_window_s = int(slow_window_s)
        self.tenant = tenant

    @classmethod
    def from_spec(cls, entry: str) -> "SloObjective":
        """``name:key=value,key=value,...``. ``var=<stem>`` derives
        ``<stem>_latency_p99`` / ``<stem>_errors`` / ``<stem>_count`` (a
        LatencyRecorder stem, e.g. rpc_method_echoservice_echo); explicit
        latency_var/errors_var/total_var override. ``bound_ms``/``bound_us``
        set the latency bound; ``objective``, ``fast_s``, ``slow_s``,
        ``tenant`` map directly."""
        name, _, rest = entry.partition(":")
        if not name.strip():
            raise ValueError(f"slo spec entry without a name: {entry!r}")
        kv: Dict[str, str] = {}
        for piece in rest.split(","):
            piece = piece.strip()
            if not piece:
                continue
            if "=" not in piece:
                raise ValueError(f"slo spec piece without '=': {piece!r}")
            k, v = piece.split("=", 1)
            kv[k.strip()] = v.strip()
        stem = kv.get("var", "")
        latency_var = kv.get("latency_var",
                             f"{stem}_latency_p99" if stem else "")
        errors_var = kv.get("errors_var", f"{stem}_errors" if stem else "")
        total_var = kv.get("total_var", f"{stem}_count" if stem else "")
        bound_us = float(kv["bound_us"]) if "bound_us" in kv else \
            float(kv.get("bound_ms", 0)) * 1000.0
        return cls(name.strip(), latency_var=latency_var,
                   latency_bound_us=bound_us, errors_var=errors_var,
                   total_var=total_var,
                   objective=float(kv.get("objective", 0.01)),
                   fast_window_s=int(kv.get("fast_s", 10)),
                   slow_window_s=int(kv.get("slow_s", 60)),
                   tenant=kv.get("tenant", ""))

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "latency_var": self.latency_var,
            "latency_bound_us": self.latency_bound_us,
            "errors_var": self.errors_var,
            "total_var": self.total_var,
            "objective": self.objective,
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
            "tenant": self.tenant,
        }


class SloEngine:
    """Objectives + the post-tick burn evaluation."""

    def __init__(self):
        self._lock = threading.Lock()
        self._objectives: Dict[str, SloObjective] = {}
        self._vars: Dict[str, MergedVar] = {}
        # name -> {"burn", "burn_fast", "burn_slow", "budget_left", parts}
        # written only by evaluate(); the g_slo_* vars read it — never the
        # series registry (the sweep holds its lock while calling get_value)
        self._state: Dict[str, dict] = {}
        self._observer = None
        self._hooked = False

    # ---------------------------------------------------------- objectives
    def add(self, obj: SloObjective) -> SloObjective:
        with self._lock:
            self._objectives[obj.name] = obj
        self._expose(obj)
        self._install_rule(obj)
        return obj

    def remove(self, name: str) -> None:
        with self._lock:
            self._objectives.pop(name, None)
            self._state.pop(name, None)
        global_watch().remove(f"slo_burn_{name}")
        for key in ("burn", "burn_fast", "burn_slow", "budget_left"):
            var = self._vars.pop(f"g_slo_{name}_{key}", None)
            if var is not None:
                var.hide()

    def objectives(self) -> List[SloObjective]:
        with self._lock:
            return sorted(self._objectives.values(), key=lambda o: o.name)

    def clear(self) -> None:
        """Test hook: drop objectives, vars and their watch rules."""
        for obj in self.objectives():
            self.remove(obj.name)

    def attach_observer(self, observer) -> "SloEngine":
        """Evaluate from the observer's scrape-merged series (cluster
        view) instead of the local registry. None detaches."""
        self._observer = observer
        return self

    # ------------------------------------------------------------ exposure
    def _expose(self, obj: SloObjective) -> None:
        n = obj.name
        readers = {
            "burn": lambda: self._cached(n, "burn"),
            "burn_fast": lambda: self._cached(n, "burn_fast"),
            "burn_slow": lambda: self._cached(n, "burn_slow"),
            "budget_left": lambda: self._cached(n, "budget_left"),
        }
        helps = {
            "burn": f"multi-window burn rate of SLO {n} (min of fast and "
                    f"slow window burns; 1.0 = spending error budget at "
                    f"exactly the allowed rate)",
            "burn_fast": f"burn rate of SLO {n} over the fast "
                         f"{obj.fast_window_s}s window",
            "burn_slow": f"burn rate of SLO {n} over the slow "
                         f"{obj.slow_window_s}s window",
            "budget_left": f"remaining error-budget fraction of SLO {n} "
                           f"over the slow window (1 - burn_slow, floored "
                           f"at 0)",
        }
        for key, fn in readers.items():
            vname = f"g_slo_{n}_{key}"
            if vname in self._vars:
                continue
            self._vars[vname] = MergedVar(
                fn, "gauge", help_text=helps[key]).expose(vname)

    def _cached(self, name: str, key: str) -> float:
        state = self._state.get(name)
        default = 1.0 if key == "budget_left" else 0.0
        return float(state.get(key, default)) if state else default

    def _install_rule(self, obj: SloObjective) -> None:
        watch = global_watch()
        rule_name = f"slo_burn_{obj.name}"
        if any(r.name == rule_name for r in watch.rules()):
            return
        watch.add(WatchRule(
            rule_name, f"g_slo_{obj.name}_burn", KIND_THRESHOLD, ">",
            float(_flags.get("slo_burn_threshold")), window_s=10,
            for_ticks=2, clear_ticks=3,
            value_fn=lambda: _flags.get("slo_burn_threshold")))

    # ------------------------------------------------------------ evaluate
    def install(self, series: Optional[SeriesRegistry] = None) -> "SloEngine":
        """Chain burn evaluation onto the series sweep (idempotent),
        before the watch hook so rules read this tick's series."""
        if not self._hooked:
            self._hooked = True
            (series or global_series()).post_tick_hooks.insert(
                0, self.evaluate)
            ensure_series_installed()
        ensure_watch_hooked(series)
        return self

    def _samples(self, registry: SeriesRegistry, name: str,
                 window: int) -> Optional[List[float]]:
        """Last ``window`` real 1-second samples of one var, from the
        observer's merged view when attached, else the local registry."""
        if not name:
            return None
        if self._observer is not None:
            doc = self._observer.merged_series(name)
            if doc is None:
                return None
            sec = list(doc.get("second") or [])
            count = int(doc.get("count", len(sec)))
        else:
            vs = registry.get(name)
            if vs is None:
                return None
            sec = vs.second.ordered()
            count = vs.count
        have = min(count, len(sec))
        if have < 1:
            return None
        return [float(v) for v in sec[len(sec) - min(have, window):]]

    def _window_burn(self, registry: SeriesRegistry, obj: SloObjective,
                     window: int) -> dict:
        latency_burn = 0.0
        error_burn = 0.0
        lat = self._samples(registry, obj.latency_var, window) \
            if obj.latency_bound_us > 0 else None
        if lat:
            violations = sum(1 for v in lat if v > obj.latency_bound_us)
            latency_burn = (violations / len(lat)) / obj.objective
        errs = self._samples(registry, obj.errors_var, window)
        total = self._samples(registry, obj.total_var, window)
        if errs and total and len(errs) >= 2 and len(total) >= 2:
            err_delta = max(0.0, errs[-1] - errs[0])
            total_delta = max(0.0, total[-1] - total[0])
            if total_delta > 0:
                error_burn = (err_delta / total_delta) / obj.objective
        return {"latency_burn": latency_burn, "error_burn": error_burn,
                "burn": max(latency_burn, error_burn)}

    def evaluate(self, registry: SeriesRegistry) -> None:
        """Series post-tick hook: recompute every objective's burn cache."""
        for obj in self.objectives():
            fast = self._window_burn(registry, obj, obj.fast_window_s)
            slow = self._window_burn(registry, obj, obj.slow_window_s)
            self._state[obj.name] = {
                "burn_fast": fast["burn"],
                "burn_slow": slow["burn"],
                # multi-window gate: both windows must burn to alert
                "burn": min(fast["burn"], slow["burn"]),
                "budget_left": max(0.0, 1.0 - slow["burn"]),
                "fast": fast,
                "slow": slow,
            }

    # ---------------------------------------------------------------- view
    def to_dict(self) -> dict:
        rules = {r.name: r.to_dict() for r in global_watch().rules()
                 if r.name.startswith("slo_burn_")}
        out = []
        for obj in self.objectives():
            state = self._state.get(obj.name, {})
            out.append({
                **obj.to_dict(),
                "burn": state.get("burn", 0.0),
                "burn_fast": state.get("burn_fast", 0.0),
                "burn_slow": state.get("burn_slow", 0.0),
                "budget_left": state.get("budget_left", 1.0),
                "rule": rules.get(f"slo_burn_{obj.name}"),
            })
        return {"threshold": float(_flags.get("slo_burn_threshold")),
                "source": "fleet" if self._observer is not None else "local",
                "objectives": out}


_global_slo = SloEngine()


def global_slo() -> SloEngine:
    return _global_slo


def _apply_objectives_string(text: str) -> bool:
    """Validator for the ``slo_objectives`` flag: ``;``-separated
    :meth:`SloObjective.from_spec` entries, e.g.
    ``echo:var=rpc_method_echoservice_echo,bound_ms=50,objective=0.02``.
    Setting the flag installs the listed objectives on the global engine
    (an empty string is a no-op; remove via global_slo().remove())."""
    text = text.strip()
    if not text:
        return True
    try:
        parsed = [SloObjective.from_spec(entry)
                  for entry in text.split(";") if entry.strip()]
    except (ValueError, KeyError):
        return False
    engine = global_slo()
    engine.install()
    for obj in parsed:
        engine.add(obj)
    return True


_flags.define(
    "slo_objectives", "",
    "Install SLO objectives from a string: "
    "'name:var=<stem>,bound_ms=...,objective=...;...' (applied on set; "
    "see fleet/slo.py SloObjective.from_spec)",
    validator=_apply_objectives_string)
