"""Fleet plane — cross-server observation (the sensing half of fleet ops).

Three parts, layered on the per-process observability stack:

- :mod:`brpc_tpu.fleet.merge` — the op-correct merge semantics (Adder sums
  stay exact, windowed latencies weight by qps, percentiles take the
  conservative max) extracted from the in-process shard aggregation
  (``shard/fleet.py``) so one merge core serves both planes.
- :mod:`brpc_tpu.fleet.observer` — :class:`FleetObserver` scrapes
  ``/vars?series=json`` / ``/serving?format=json`` / ``/watch?format=json``
  from a member set and exposes merged ``cluster_*`` vars + the ``/fleet``
  builtin.
- :mod:`brpc_tpu.fleet.slo` — declarative latency/error objectives over
  the merged series with multi-window burn rates, ``g_slo_*`` vars,
  ``slo_burn`` watch rules and the ``/slo`` builtin.
"""

from brpc_tpu.fleet.merge import (  # noqa: F401
    OP_AVG,
    OP_MAX,
    OP_MIN,
    OP_SUM,
    OP_WAVG_QPS,
    MergedVar,
    merge_op,
    merge_values,
    qps_weight_name,
    snapshot_vars,
)
from brpc_tpu.fleet.observer import (  # noqa: F401
    FleetMember,
    FleetObserver,
    global_observer,
    set_global_observer,
)
from brpc_tpu.fleet.slo import (  # noqa: F401
    SloEngine,
    SloObjective,
    global_slo,
)

__all__ = [
    "OP_AVG", "OP_MAX", "OP_MIN", "OP_SUM", "OP_WAVG_QPS",
    "MergedVar", "merge_op", "merge_values", "qps_weight_name",
    "snapshot_vars",
    "FleetMember", "FleetObserver", "global_observer",
    "set_global_observer",
    "SloEngine", "SloObjective", "global_slo",
]
