"""Op-correct variable merge core, shared by shard workers and the fleet.

PR 11 proved these semantics in-process (``shard/fleet.py`` merging worker
snapshots into the parent's /vars); the fleet observer reuses the same core
across *servers* so ``cluster_x == sum(member_x)`` holds exactly for
Adder-backed counters, windowed latency means stay qps-weighted, and
percentiles degrade to the conservative max instead of a fake average.

The unit of exchange is the flat snapshot ``{name: [op, ptype, value]}``:
the side that owns the variable derives the merge op from what the variable
*is*, so no consumer ever guesses.
"""

from __future__ import annotations

import json
from typing import Dict, Optional, Sequence

from brpc_tpu.metrics.status import PassiveStatus
from brpc_tpu.metrics.variable import exposed_variables

# merge ops carried in snapshots
OP_SUM = "sum"
OP_MAX = "max"
OP_MIN = "min"
OP_AVG = "avg"
OP_WAVG_QPS = "wavg_qps"   # qps-weighted mean (windowed latency averages)


def merge_op(name: str, var) -> str:
    """Pick the cross-process merge op for one variable."""
    if getattr(var, "prometheus_type", "gauge") == "counter":
        return OP_SUM
    if name.endswith(("_qps", "_count", "_second", "_errors", "_error")):
        return OP_SUM
    if "_latency_p" in name:
        # per-process percentiles don't compose exactly; max is the
        # conservative fleet upper bound (documented in docs/observability)
        return OP_MAX
    tokens = name.split("_")
    if "max" in tokens:        # max_latency et al, before the _latency check
        return OP_MAX
    if "min" in tokens:
        return OP_MIN
    if name.endswith("_latency"):
        return OP_WAVG_QPS
    return OP_AVG


def qps_weight_name(name: str) -> str:
    """The sibling qps var used to weight a ``*_latency`` window average."""
    return name[: -len("_latency")] + "_qps"


def merge_values(op: str, values: Sequence[float],
                 weights: Optional[Sequence[float]] = None) -> float:
    """Merge already-collected member values under one op.

    ``weights`` applies only to ``OP_WAVG_QPS`` (qps of each member); when
    missing or all-zero the merge falls back to the plain mean.
    """
    if not values:
        return 0.0
    if op == OP_SUM:
        return sum(values)
    if op == OP_MAX:
        return max(values)
    if op == OP_MIN:
        return min(values)
    if op == OP_WAVG_QPS and weights is not None and sum(weights) > 0:
        total = sum(weights)
        return sum(v * w for v, w in zip(values, weights)) / total
    return sum(values) / len(values)


def snapshot_vars(skip_prefixes: Sequence[str] = ()) -> Dict[str, list]:
    """Flat ``{name: [op, ptype, value]}`` of every exposed numeric var.

    ``skip_prefixes`` drops derived families (e.g. a scraper skips
    ``cluster_*`` so an observer scraping an observer never feeds its own
    aggregates back into the merge).
    """
    out: Dict[str, list] = {}
    for name, var in exposed_variables():
        if skip_prefixes and name.startswith(tuple(skip_prefixes)):
            continue
        try:
            value = var.get_value()
        except Exception:
            continue
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            continue
        ptype = getattr(var, "prometheus_type", "gauge")
        out[name] = [merge_op(name, var), ptype, value]
    return out


def worker_snapshot(index: int) -> bytes:
    """The W_VARS payload shipped by shard workers over the stats lane."""
    return json.dumps({"index": index, "vars": snapshot_vars()}).encode()


class MergedVar(PassiveStatus):
    """PassiveStatus with exposition metadata slots (type + HELP) and a
    series opt-out knob — plain attrs read by prometheus_text and the
    series sweep."""

    def __init__(self, fn, ptype: str = "gauge", help_text: str = "",
                 opt_out: bool = False):
        super().__init__(fn)
        self.prometheus_type = ptype
        if help_text:
            self.prometheus_help = help_text
        if opt_out:
            self.series_opt_out = True
