"""FleetObserver — scrape member servers, merge their vars op-correctly.

One process (an operator box, or any member doubling as observer) scrapes
``/vars?series=json``, ``/serving?format=json`` and ``/watch?format=json``
from every fleet member and keeps the latest documents. Merged ``cluster_*``
vars are exposed in the local registry with the same op-correct semantics
the shard plane proved in-process (:mod:`brpc_tpu.fleet.merge`): Adder
counters sum exactly, windowed latency means weight by member qps,
percentiles take the conservative max. Live-ness is crash-tolerant: a
member whose scrape fails is marked stale and simply drops out of the
merge until it answers again — the observer never dies with the member.

Membership comes from static ``list://`` seeds today; any
:class:`~brpc_tpu.policy.naming.NamingService` instance plugs into the same
slot (``get_servers()`` is re-consulted every scrape round), which is the
hook the future autoscaler rides.

The scrape loop is budget-gated twice (enforced by the ``budget-gated-scrape``
lint rule): the interval re-reads the reloadable ``fleet_scrape_interval_s``
flag every round, and each round first asks the shared metrics Collector
for a grant so N observers can never stampede a fleet past
``collector_max_samples_per_second``.

Fault point ``fleet.scrape.fail`` (ctx key ``member``) injects scrape
failures per member for chaos tests.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, List, Optional

from brpc_tpu import fault as _fault
from brpc_tpu import flags as _flags
from brpc_tpu.fleet.merge import (
    OP_WAVG_QPS,
    MergedVar,
    merge_values,
    qps_weight_name,
)
from brpc_tpu.metrics.collector import global_collector
from brpc_tpu.metrics.series import ensure_series_installed
from brpc_tpu.metrics.watch import ensure_watch_hooked

fleet_scrape_interval_s = _flags.define(
    "fleet_scrape_interval_s", 2.0,
    "seconds between fleet observer scrape rounds (reloadable: the loop "
    "re-reads the flag every round)", validator=lambda v: v > 0)
fleet_stale_after_s = _flags.define(
    "fleet_stale_after_s", 10.0,
    "a member whose last good scrape is older than this is reported "
    "stale in /fleet even if no scrape has failed since (reloadable)",
    validator=lambda v: v > 0)

_fault.register(
    "fleet.scrape.fail",
    "fail a fleet observer scrape of one member (ctx: member=host:port)")

# derived families a scrape must never re-ingest: an observer scraping an
# observer (or itself) would otherwise feed its own aggregates back into
# the merge and double-count the fleet
SKIP_SCRAPED_PREFIXES = ("cluster_", "g_slo_")


def _default_fetch(addr: str, path: str) -> dict:
    """Scrape one JSON endpoint over the normal HTTP lane."""
    from brpc_tpu.policy.http_protocol import http_fetch
    resp = http_fetch(addr, "GET", path, timeout=3.0)
    if resp.status != 200:
        raise ConnectionError(f"{addr}{path} -> HTTP {resp.status}")
    return json.loads(bytes(resp.body).decode())


class FleetMember:
    """Latest scraped state of one fleet member."""

    def __init__(self, addr: str):
        self.addr = addr
        # {name: (op, ptype, value)} from /vars?series=json "vars"
        self.vars: Dict[str, tuple] = {}
        self.series: Dict[str, dict] = {}
        self.serving: dict = {}
        self.watch: List[dict] = []
        self.scrapes_ok = 0
        self.scrapes_failed = 0
        self.consecutive_failures = 0
        self.last_ok_mono = 0.0
        self.last_error = ""

    def live(self) -> bool:
        """Deterministic liveness: at least one good scrape and the most
        recent attempt succeeded. Wall-clock staleness is reported
        separately (age vs fleet_stale_after_s) so tests without a running
        scrape thread stay time-independent."""
        return self.scrapes_ok > 0 and self.consecutive_failures == 0

    def age_s(self) -> float:
        if self.last_ok_mono == 0.0:
            return float("inf")
        return time.monotonic() - self.last_ok_mono

    def stale(self) -> bool:
        return (not self.live()
                or self.age_s() > float(_flags.get("fleet_stale_after_s")))

    def to_dict(self) -> dict:
        age = self.age_s()
        return {
            "addr": self.addr,
            "live": self.live(),
            "stale": self.stale(),
            "age_s": round(age, 3) if age != float("inf") else None,
            "scrapes_ok": self.scrapes_ok,
            "scrapes_failed": self.scrapes_failed,
            "consecutive_failures": self.consecutive_failures,
            "last_error": self.last_error,
            "vars": len(self.vars),
            "firing": [r["name"] for r in self.watch
                       if r.get("state") == "firing"],
        }


class FleetObserver:
    """Scrape + merge loop over a fleet member set."""

    def __init__(self, seeds, fetch: Optional[Callable[[str, str], dict]] = None):
        """``seeds``: 'list://h1:p1,h2:p2', plain 'h1:p1,h2:p2', a list of
        addr strings, or a NamingService instance (re-consulted every
        scrape round — the naming hook)."""
        self._naming = None
        self._static: List[str] = []
        if hasattr(seeds, "get_servers"):
            self._naming = seeds
        else:
            if isinstance(seeds, str):
                text = seeds[len("list://"):] if seeds.startswith("list://") \
                    else seeds
                items = [s for s in text.split(",") if s.strip()]
            else:
                items = list(seeds)
            self._static = [str(s).strip().split()[0] for s in items]
        self._lock = threading.Lock()
        self._members: Dict[str, FleetMember] = {}
        self._cluster_vars: Dict[str, MergedVar] = {}
        self._count_vars: List[MergedVar] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._fetch = fetch or _default_fetch
        self._expose_counts()

    # -------------------------------------------------------------- members
    def member_addrs(self) -> List[str]:
        if self._naming is not None:
            try:
                return [str(n.ep) for n in self._naming.get_servers()]
            except Exception:
                with self._lock:
                    return sorted(self._members)
        return list(self._static)

    def members(self) -> List[FleetMember]:
        with self._lock:
            return [self._members[a] for a in sorted(self._members)]

    def live_members(self) -> List[FleetMember]:
        return [m for m in self.members() if m.live()]

    # --------------------------------------------------------------- scrape
    def scrape_once(self) -> int:
        """One scrape round over the current member set; returns the number
        of members that answered. Never raises."""
        ok = 0
        for addr in self.member_addrs():
            with self._lock:
                member = self._members.get(addr)
                if member is None:
                    member = self._members[addr] = FleetMember(addr)
            if self._scrape_member(member):
                ok += 1
        self._refresh_cluster_vars()
        return ok

    def _scrape_member(self, member: FleetMember) -> bool:
        try:
            if _fault.hit("fleet.scrape.fail",
                          member=member.addr) is not None:
                raise ConnectionError("injected: fleet.scrape.fail")
            vars_doc = self._fetch(member.addr, "/vars?series=json")
            serving_doc = self._fetch(member.addr, "/serving?format=json")
            watch_doc = self._fetch(member.addr, "/watch?format=json")
        except Exception as e:
            with self._lock:
                member.scrapes_failed += 1
                member.consecutive_failures += 1
                member.last_error = f"{type(e).__name__}: {e}"
            return False
        snap = {}
        for name, rec in (vars_doc.get("vars") or {}).items():
            if str(name).startswith(SKIP_SCRAPED_PREFIXES):
                continue
            if (isinstance(rec, list) and len(rec) == 3
                    and isinstance(rec[2], (int, float))
                    and not isinstance(rec[2], bool)):
                snap[str(name)] = (str(rec[0]), str(rec[1]), rec[2])
        series = {str(k): v for k, v in (vars_doc.get("series") or {}).items()
                  if not str(k).startswith(SKIP_SCRAPED_PREFIXES)}
        with self._lock:
            member.vars = snap
            member.series = series
            member.serving = serving_doc
            member.watch = list(watch_doc.get("rules") or [])
            member.scrapes_ok += 1
            member.consecutive_failures = 0
            member.last_ok_mono = time.monotonic()
            member.last_error = ""
        return True

    # ---------------------------------------------------------------- merge
    def _refresh_cluster_vars(self) -> None:
        with self._lock:
            names = set()
            for m in self._members.values():
                if m.live():
                    names.update(m.vars)
            missing = [(n, self._op_of(n)) for n in names
                       if f"cluster_{n}" not in self._cluster_vars]
        for name, (op, ptype) in missing:
            cname = f"cluster_{name}"
            var = MergedVar(
                self._cluster_reader(name), ptype,
                help_text=f"{op} of {name} over live fleet members "
                          f"(fleet scrape merge)")
            var.expose(cname)
            with self._lock:
                self._cluster_vars[cname] = var

    def _op_of(self, name: str):
        for m in self._members.values():
            rec = m.vars.get(name)
            if rec is not None:
                return (rec[0], rec[1])
        return ("avg", "gauge")

    def _cluster_reader(self, name: str):
        def read():
            with self._lock:
                recs = [m.vars[name] for m in self._members.values()
                        if m.live() and name in m.vars]
                if not recs:
                    return 0
                op = recs[0][0]
                values = [rec[2] for rec in recs]
                weights = None
                if op == OP_WAVG_QPS:
                    wname = qps_weight_name(name)
                    weights = [m.vars.get(wname, (0, 0, 0))[2]
                               for m in self._members.values()
                               if m.live() and name in m.vars]
            return merge_values(op, values, weights)
        return read

    def cluster_value(self, name: str):
        """Merged value of one scraped var (without going through /vars)."""
        return self._cluster_reader(name)()

    def merged_series(self, name: str) -> Optional[dict]:
        """Element-wise merge of one var's scraped second-tier series over
        live members, honoring the var's merge op (the SLO engine's feed)."""
        with self._lock:
            docs = []
            weights = []
            op = None
            wname = None
            for m in self._members.values():
                if not m.live():
                    continue
                doc = m.series.get(name)
                if not doc:
                    continue
                rec = m.vars.get(name)
                docs.append(doc)
                if rec is not None and op is None:
                    op = rec[0]
                if op == OP_WAVG_QPS and wname is None:
                    wname = qps_weight_name(name)
                weights.append(
                    m.vars.get(wname, (0, 0, 1))[2] if wname else 1.0)
        if not docs:
            return None
        op = op or "avg"
        length = min(len(d.get("second") or []) for d in docs)
        if length == 0:
            return None
        merged = []
        for i in range(length):
            column = [float(d["second"][len(d["second"]) - length + i])
                      for d in docs]
            merged.append(merge_values(op, column, weights))
        count = max(int(d.get("count", 0)) for d in docs)
        return {"second": merged, "count": count, "op": op}

    # --------------------------------------------------------------- views
    def serving_shard_union(self) -> Dict[str, str]:
        """Union of member serving shard maps, keyed '<addr>/<seq>'."""
        out: Dict[str, str] = {}
        with self._lock:
            for m in self._members.values():
                for engine in (m.serving.get("engines") or []):
                    shard_map = (engine.get("kv") or {}).get("shard_map") or {}
                    for seq, shard in shard_map.items():
                        out[f"{m.addr}/{seq}"] = str(shard)
        return out

    def firing_rules(self) -> Dict[str, List[str]]:
        out: Dict[str, List[str]] = {}
        for m in self.members():
            names = [r["name"] for r in m.watch if r.get("state") == "firing"]
            if names:
                out[m.addr] = names
        return out

    def fleet_trace(self, trace_id: str) -> dict:
        """Pull one trace's retained spans from every live member and
        stitch them into a single tree via merge_trace_docs."""
        from brpc_tpu.trace.span import merge_trace_docs
        docs = []
        for m in self.live_members():
            try:
                doc = self._fetch(m.addr, f"/rpcz/{trace_id}?format=json")
            except Exception:
                continue
            if doc.get("spans"):
                docs.append(doc)
        return merge_trace_docs(docs)

    def to_dict(self) -> dict:
        members = [m.to_dict() for m in self.members()]
        with self._lock:
            cluster = sorted(self._cluster_vars)
        return {
            "members": members,
            "live": sum(1 for m in members if m["live"]),
            "cluster_vars": len(cluster),
            "interval_s": float(_flags.get("fleet_scrape_interval_s")),
            "serving_shards": self.serving_shard_union(),
            "firing": self.firing_rules(),
        }

    # ------------------------------------------------------------ lifecycle
    def _expose_counts(self) -> None:
        total = MergedVar(
            lambda: len(self.members()), "gauge",
            "fleet members known to this observer")
        live = MergedVar(
            lambda: len(self.live_members()), "gauge",
            "fleet members whose latest scrape succeeded")
        self._count_vars = [total.expose("cluster_fleet_members"),
                            live.expose("cluster_fleet_members_live")]

    def start(self) -> "FleetObserver":
        """Start the background scrape loop (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return self
        ensure_series_installed()
        ensure_watch_hooked()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="fleet-observer", daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            # budget gate: one grant per scrape round from the shared
            # Collector bucket, so observers can't stampede the fleet
            if global_collector().ask_to_be_sampled():
                try:
                    self.scrape_once()
                except Exception:
                    pass
            self._stop.wait(
                max(0.2, float(_flags.get("fleet_scrape_interval_s"))))

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5.0)
            self._thread = None

    def hide_all(self) -> None:
        """Withdraw every exposed cluster_* var (test hygiene)."""
        for var in self._count_vars:
            var.hide()
        with self._lock:
            cluster = list(self._cluster_vars.values())
            self._cluster_vars.clear()
        for var in cluster:
            var.hide()


_global_observer: Optional[FleetObserver] = None
_observer_lock = threading.Lock()


def global_observer() -> Optional[FleetObserver]:
    return _global_observer


def set_global_observer(obs: Optional[FleetObserver]) -> Optional[FleetObserver]:
    """Install (or clear, with None) the process-wide observer the /fleet
    and /slo builtins report on. Returns the previous one."""
    global _global_observer
    with _observer_lock:
        prev, _global_observer = _global_observer, obs
    return prev
