"""Butex — wait/wake on a 32-bit word (reference bthread/butex.h:41-84).

The foundation of every blocking primitive in the reference: a fiber waits
until the word's value differs from an expected value; wakers change the word
and wake sleepers. Our adaptation keeps the compare-and-sleep contract (it is
what Stream flow control and call-id join are written against) on top of a
condition variable; on the TPU datapath the "waker" is a PJRT completion
callback (SURVEY §5.8: butex signaled from PJRT callback).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from brpc_tpu.fiber import wakeup as _wakeup

# shared spin budget for every butex wait (per-site granularity lives in
# the contention table; the spin policy adapts to the process-wide mix)
_spin = _wakeup.get_spin("butex")

# contention bookkeeping (reference bthread/mutex.cpp:63-80 contention
# profiler): per-site wait counts + total wait time, sampled cheaply —
# only waits that actually blocked are recorded
_contention: Dict[str, List[int]] = {}
_contention_lock = threading.Lock()


def record_contention(site: str, wait_ns: int) -> None:
    with _contention_lock:
        ent = _contention.get(site)
        if ent is None:
            if len(_contention) >= 1024:  # bounded table
                return
            _contention[site] = [1, wait_ns]
        else:
            ent[0] += 1
            ent[1] += wait_ns
    _maybe_capture_stack(site, wait_ns)


def contention_stats() -> List[Tuple[str, int, int]]:
    """[(site, waits, total_wait_ns)] sorted by wait time desc."""
    with _contention_lock:
        rows = [(site, ent[0], ent[1]) for site, ent in _contention.items()]
    return sorted(rows, key=lambda r: -r[2])


# sampled waiter STACKS per site (reference contention profiler records
# where waiters came from, not just the wait word's label): site ->
# collapsed stack -> [waits, total_wait_ns]. Collector-budget-gated so the
# capture cost scales with the observability budget, not the wait rate.
_contention_stacks: Dict[str, Dict[Tuple[str, ...], List[int]]] = {}
_MAX_STACK_SITES = 256
_MAX_STACKS_PER_SITE = 8
_collector = None


def _maybe_capture_stack(site: str, wait_ns: int) -> None:
    global _collector
    if _collector is None:
        from brpc_tpu.metrics.collector import global_collector

        _collector = global_collector()
    if (time.monotonic() < _collector._deny_until
            or not _collector.ask_to_be_sampled()):
        return
    import sys

    from brpc_tpu.profiling.sampler import collapse

    # _getframe(2): the caller of record_contention — the wait site itself
    # (Butex.wait, TrackedLock.acquire, ...)
    try:
        frame = sys._getframe(2)
    except ValueError:
        frame = sys._getframe()
    stack = collapse(frame)
    with _contention_lock:
        stacks = _contention_stacks.get(site)
        if stacks is None:
            if len(_contention_stacks) >= _MAX_STACK_SITES:
                return
            stacks = _contention_stacks[site] = {}
        ent = stacks.get(stack)
        if ent is None:
            if len(stacks) >= _MAX_STACKS_PER_SITE:
                return
            stacks[stack] = [1, wait_ns]
        else:
            ent[0] += 1
            ent[1] += wait_ns


def contention_stacks() -> Dict[str, List[Tuple[str, int, int]]]:
    """site -> [(folded_stack, waits, total_wait_ns)] sorted by wait time
    desc within each site."""
    with _contention_lock:
        out = {}
        for site, stacks in _contention_stacks.items():
            rows = [(";".join(st), ent[0], ent[1])
                    for st, ent in stacks.items()]
            out[site] = sorted(rows, key=lambda r: -r[2])
    return out


def reset_contention_for_test() -> None:
    with _contention_lock:
        _contention.clear()
        _contention_stacks.clear()


class Butex:
    __slots__ = ("_value", "_cond", "_site")

    def __init__(self, value: int = 0, site: str = ""):
        self._value = value
        self._cond = threading.Condition()
        self._site = site

    @property
    def value(self) -> int:
        return self._value

    def set_value(self, value: int) -> None:
        with self._cond:
            self._value = value

    def wait(self, expected: int, timeout: Optional[float] = None) -> bool:
        """Block while value == expected. True if woken, False on timeout.

        Returns immediately if the value already differs (the lost-wakeup
        guard that makes the butex protocol race-free).
        """
        # spin-then-park: probe the word lock-free before paying for the
        # condition variable (racy read is safe — the locked re-check below
        # is still the authority; a spin "win" only short-circuits a park)
        if self._value != expected:
            return True
        if (timeout is None or timeout > 0) and _spin.spin(
                lambda: self._value != expected):
            return True
        with self._cond:
            if self._value != expected:
                return True
            t0 = time.monotonic_ns()
            woken = self._cond.wait_for(
                lambda: self._value != expected, timeout=timeout
            )
            if self._site:
                record_contention(self._site, time.monotonic_ns() - t0)
            return woken

    def wake(self, value: Optional[int] = None, n: Optional[int] = None) -> None:
        """Optionally store a new value, then wake sleepers (all by default)."""
        with self._cond:
            if value is not None:
                self._value = value
            if n is None:
                self._cond.notify_all()
            else:
                self._cond.notify(n)

    def add_and_wake(self, delta: int = 1) -> int:
        with self._cond:
            self._value += delta
            self._cond.notify_all()
            return self._value
