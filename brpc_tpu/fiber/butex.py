"""Butex — wait/wake on a 32-bit word (reference bthread/butex.h:41-84).

The foundation of every blocking primitive in the reference: a fiber waits
until the word's value differs from an expected value; wakers change the word
and wake sleepers. Our adaptation keeps the compare-and-sleep contract (it is
what Stream flow control and call-id join are written against) on top of a
condition variable; on the TPU datapath the "waker" is a PJRT completion
callback (SURVEY §5.8: butex signaled from PJRT callback).
"""

from __future__ import annotations

import threading
from typing import Optional


class Butex:
    __slots__ = ("_value", "_cond")

    def __init__(self, value: int = 0):
        self._value = value
        self._cond = threading.Condition()

    @property
    def value(self) -> int:
        return self._value

    def set_value(self, value: int) -> None:
        with self._cond:
            self._value = value

    def wait(self, expected: int, timeout: Optional[float] = None) -> bool:
        """Block while value == expected. True if woken, False on timeout.

        Returns immediately if the value already differs (the lost-wakeup
        guard that makes the butex protocol race-free).
        """
        with self._cond:
            if self._value != expected:
                return True
            return self._cond.wait_for(
                lambda: self._value != expected, timeout=timeout
            )

    def wake(self, value: Optional[int] = None, n: Optional[int] = None) -> None:
        """Optionally store a new value, then wake sleepers (all by default)."""
        with self._cond:
            if value is not None:
                self._value = value
            if n is None:
                self._cond.notify_all()
            else:
                self._cond.notify(n)

    def add_and_wake(self, delta: int = 1) -> int:
        with self._cond:
            self._value += delta
            self._cond.notify_all()
            return self._value
