"""CallId — versioned correlation id with built-in lock + error channel.

Rebuild of ``bthread/id.h:28-48`` / ``id.cpp``: one CallId per RPC. It is
simultaneously (a) a weak reference (stale ids never resolve after destroy —
VersionedPool), (b) a mutex serializing everything that touches the RPC's
state (response processing, timeout, socket failure), and (c) an error
channel: ``id_error`` delivers a code to the owner's on_error under the lock,
deferred if the lock is held. Retries bump an in-id call version
(``id.cpp:396,405`` ranged versions) so responses to an abandoned attempt
fail verification and are dropped — the stale-response race the reference
guards at controller.cpp:1059-1066.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional, Tuple

from brpc_tpu.butil.resource_pool import VersionedPool


class IdGone(Exception):
    """The id was destroyed (RPC completed) — stale reference."""


class _Id:
    __slots__ = (
        "data",
        "on_error",
        "cond",
        "locked",
        "destroyed",
        "pending_errors",
        "call_version",
        "join_event",
    )

    def __init__(self, data, on_error):
        self.data = data
        self.on_error: Optional[Callable] = on_error
        self.cond = threading.Condition()
        self.locked = False
        self.destroyed = False
        self.pending_errors: List[int] = []
        self.call_version = 1
        self.join_event = threading.Event()


_pool: VersionedPool = VersionedPool()


def id_create(data=None, on_error: Optional[Callable] = None) -> int:
    """New call id. on_error(data, call_id, error_code) runs under the lock."""
    return _pool.insert(_Id(data, on_error))


def _resolve(call_id: int) -> _Id:
    ident = _pool.address(call_id)
    if ident is None:
        raise IdGone(f"call id {call_id:#x} destroyed")
    return ident


def id_lock(call_id: int, timeout: Optional[float] = None):
    """Acquire the id's lock; returns data. Raises IdGone if destroyed."""
    ident = _resolve(call_id)
    with ident.cond:
        ok = ident.cond.wait_for(
            lambda: not ident.locked or ident.destroyed, timeout=timeout
        )
        if not ok:
            raise TimeoutError("id_lock timeout")
        if ident.destroyed:
            raise IdGone(f"call id {call_id:#x} destroyed")
        ident.locked = True
        return ident.data


def id_lock_verify(call_id: int, call_version: int):
    """Lock only if the in-id call version matches (stale-response guard)."""
    data = id_lock(call_id)
    ident = _resolve(call_id)
    if ident.call_version != call_version:
        id_unlock(call_id)
        raise IdGone(
            f"call id {call_id:#x} at version {ident.call_version}, "
            f"response for stale version {call_version}"
        )
    return data


def id_version(call_id: int) -> int:
    return _resolve(call_id).call_version


def id_bump_version(call_id: int) -> int:
    """Caller must hold the lock; invalidates in-flight responses (retry)."""
    ident = _resolve(call_id)
    ident.call_version += 1
    return ident.call_version


def id_unlock(call_id: int) -> None:
    try:
        ident = _resolve(call_id)
    except IdGone:
        return
    # Deliver deferred errors one at a time while keeping the lock; the
    # handler must finish with id_unlock or id_unlock_and_destroy, so loop
    # until the queue drains or the handler destroys the id.
    while True:
        with ident.cond:
            if ident.destroyed:
                ident.cond.notify_all()
                return
            if not ident.pending_errors:
                ident.locked = False
                ident.cond.notify()
                return
            code = ident.pending_errors.pop(0)
            handler = ident.on_error
            data = ident.data
        if handler is None:
            continue
        handler(data, call_id, code)
        # handler unlocked (or destroyed) the id; re-acquire for next error
        try:
            with ident.cond:
                if ident.destroyed:
                    return
                if ident.locked:
                    # handler kept it locked — its responsibility now
                    return
                if ident.pending_errors:
                    ident.locked = True
                    continue
                return
        except IdGone:
            return


def id_unlock_and_destroy(call_id: int) -> None:
    ident = _pool.address(call_id)
    if ident is None:
        return
    with ident.cond:
        ident.destroyed = True
        ident.locked = False
        ident.pending_errors.clear()
        ident.cond.notify_all()
        ident.join_event.set()
    _pool.remove(call_id)


def id_join(call_id: int, timeout: Optional[float] = None) -> bool:
    """Block until the id is destroyed (RPC fully finished)."""
    ident = _pool.address(call_id)
    if ident is None:
        return True  # already gone
    return ident.join_event.wait(timeout)


def id_error(call_id: int, error_code: int) -> bool:
    """Deliver an error to the id's owner.

    If the id is unlocked: lock it and run on_error on this thread.
    If locked: queue the error; the current holder delivers it at unlock.
    Returns False if the id is already destroyed.
    """
    try:
        ident = _resolve(call_id)
    except IdGone:
        return False
    with ident.cond:
        if ident.destroyed:
            return False
        if ident.locked:
            ident.pending_errors.append(error_code)
            return True
        ident.locked = True
        handler = ident.on_error
        data = ident.data
    if handler is not None:
        handler(data, call_id, error_code)
    else:
        id_unlock_and_destroy(call_id)
    return True


def id_about_to_destroy(call_id: int) -> None:
    """Reject future errors early (reference bthread_id_about_to_destroy)."""
    try:
        ident = _resolve(call_id)
    except IdGone:
        return
    with ident.cond:
        ident.on_error = None
        ident.pending_errors.clear()
