"""fiber — task runtime: the bthread equivalent (SURVEY §2.2)."""

from brpc_tpu.fiber.butex import Butex
from brpc_tpu.fiber.runtime import (
    TaskControl,
    FiberTask,
    global_control,
    start_background,
    start_urgent,
    DEFAULT_TAG,
)
from brpc_tpu.fiber.timer import TimerThread, global_timer, timer_add, timer_del
from brpc_tpu.fiber.execution_queue import ExecutionQueue
from brpc_tpu.fiber.call_id import (
    IdGone,
    id_create,
    id_lock,
    id_lock_verify,
    id_unlock,
    id_unlock_and_destroy,
    id_join,
    id_error,
    id_version,
    id_bump_version,
    id_about_to_destroy,
)

__all__ = [
    "Butex",
    "TaskControl",
    "FiberTask",
    "global_control",
    "start_background",
    "start_urgent",
    "DEFAULT_TAG",
    "TimerThread",
    "global_timer",
    "timer_add",
    "timer_del",
    "ExecutionQueue",
    "IdGone",
    "id_create",
    "id_lock",
    "id_lock_verify",
    "id_unlock",
    "id_unlock_and_destroy",
    "id_join",
    "id_error",
    "id_version",
    "id_bump_version",
    "id_about_to_destroy",
]
