"""Fiber runtime — the M:N task scheduler (reference bthread TaskControl/
TaskGroup, task_control.cpp:213/task_group.cpp:470).

Semantics carried over, not code: ``start_background`` enqueues a task for
any worker; ``start_urgent`` runs it at the head of the queue (the
reference's start_foreground makes the *caller* yield — meaningless under
the GIL, so urgency maps to queue position); workers own a local deque and
steal from siblings when idle (Chase-Lev in the reference,
work_stealing_queue.h:32); tagged worker groups isolate pools
(task_control.cpp:291). Python threads are the "pthread workers"; tasks are
plain callables — IO-bound RPC work is where M:N pays off under the GIL,
and device-bound work is dispatched to XLA asynchronously anyway.

Wakeup design (reference ParkingLot, parking_lot.h / task_control.cpp:565):
every submit bumps a per-group signal word and wakes exactly one parked
worker; a worker about to park re-checks the word it read before its last
(futile) scan, so a submit that raced the scan is never missed.  No polling
loops — dispatch latency at idle is one condvar notify.
"""

from __future__ import annotations

import random
import threading
from collections import deque
from typing import Callable, Dict, List, Optional

from brpc_tpu.fiber.butex import contention_stats  # noqa: F401  (re-export for /hotspots/contention)
from brpc_tpu.metrics.reducer import Adder

DEFAULT_TAG = 0


class FiberTask:
    __slots__ = ("fn", "args", "done", "error", "_event", "keytable")

    def __init__(self, fn, args):
        self.fn = fn
        self.args = args
        self.done = False
        self.error: Optional[BaseException] = None
        self._event = threading.Event()
        self.keytable = None  # fiber-local storage (fiber/local.py)

    def run(self) -> None:
        from brpc_tpu.fiber import local as _local

        _local._enter_task(self)
        try:
            self.fn(*self.args)
        except BaseException as e:  # noqa: BLE001 - task errors are captured
            self.error = e
        finally:
            _local._exit_task(self)
            self.done = True
            self._event.set()

    def join(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)


class ParkingLot:
    """Futex-style sleep/wake for idle workers (reference parking_lot.h).

    ``state()`` returns the current signal word; ``wait(expected)`` parks
    only if the word is still ``expected`` (i.e. no signal arrived since the
    caller last looked for work); ``signal()`` bumps the word and wakes one
    parked worker.  The value-compare closes the scan→park race without any
    polling interval.  Deliberately NOT built on fiber.butex.Butex: the
    reference likewise keeps ParkingLot separate from butex
    (parking_lot.h vs butex.cpp) — butex carries contention accounting the
    scheduler idle path must not pay, and a spurious wakeup here is harmless
    (the worker just rescans for work).
    """

    __slots__ = ("_cond", "_signal", "_parked")

    def __init__(self):
        self._cond = threading.Condition()
        self._signal = 0
        self._parked = 0

    def state(self) -> int:
        with self._cond:
            return self._signal

    def signal(self, all_workers: bool = False) -> None:
        with self._cond:
            self._signal += 1
            if all_workers:
                self._cond.notify_all()
            elif self._parked:
                self._cond.notify()

    def wait(self, expected: int, timeout: Optional[float] = None) -> None:
        with self._cond:
            if self._signal != expected:
                return
            self._parked += 1
            try:
                self._cond.wait(timeout)
            finally:
                self._parked -= 1


class _Worker(threading.Thread):
    def __init__(self, control: "TaskControl", index: int, tag: int):
        super().__init__(name=f"fiber-worker-{tag}-{index}", daemon=True)
        self.control = control
        self.index = index
        self.tag = tag
        self.local: deque = deque()
        self.lock = threading.Lock()
        self.current: Optional[FiberTask] = None  # /fibers task visibility

    def run(self) -> None:
        from brpc_tpu.profiling import registry as _prof

        _prof.register_current_thread(_prof.ROLE_WORKER)
        control = self.control
        lot = control._lot(self.tag)
        while not control._stopped:
            expected = lot.state()
            task = self._next_task()
            if task is None:
                # Park until a submit bumps the signal word. A submit that
                # landed after our scan already changed the word, so wait()
                # returns immediately (reference TaskGroup::wait_task,
                # task_group.cpp:162).
                lot.wait(expected, timeout=1.0)
                continue
            control.tasks_executed.put(1)
            self.current = task
            try:
                task.run()
            finally:
                self.current = None

    def _next_task(self) -> Optional[FiberTask]:
        with self.lock:
            if self.local:
                return self.local.popleft()
        return self.control._steal(self)

    def push(self, task: FiberTask, urgent: bool) -> None:
        with self.lock:
            if urgent:
                self.local.appendleft(task)
            else:
                self.local.append(task)

    def depth(self) -> int:
        return len(self.local)  # racy read is fine — used as a heuristic


class TaskControl:
    """Global scheduler: owns workers per tag group, submits to the
    shallowest queue, wakes a parked worker on every submit, and lets idle
    workers steal from siblings."""

    def __init__(self, concurrency: int = 8):
        self._workers: Dict[int, List[_Worker]] = {}
        self._lots: Dict[int, ParkingLot] = {}
        self._stopped = False
        self._lock = threading.Lock()
        self._default_concurrency = concurrency
        self.tasks_executed = Adder()

    def _lot_locked(self, tag: int) -> ParkingLot:
        # caller holds self._lock
        lot = self._lots.get(tag)
        if lot is None:
            lot = self._lots[tag] = ParkingLot()
        return lot

    def _lot(self, tag: int) -> ParkingLot:
        # lock-free fast path: lots are created once and never removed
        lot = self._lots.get(tag)
        if lot is not None:
            return lot
        with self._lock:
            return self._lot_locked(tag)

    def _group(self, tag: int) -> List[_Worker]:
        group = self._workers.get(tag)
        if group is not None:
            return group
        with self._lock:
            group = self._workers.get(tag)
            if group is None:
                self._lot_locked(tag)
                group = [
                    _Worker(self, i, tag)
                    for i in range(self._default_concurrency)
                ]
                self._workers[tag] = group
                for w in group:
                    w.start()
            return group

    def add_workers(self, n: int, tag: int = DEFAULT_TAG) -> None:
        with self._lock:
            self._lot_locked(tag)
            group = self._workers.setdefault(tag, [])
            base = len(group)
            new = [_Worker(self, base + i, tag) for i in range(n)]
            group.extend(new)
        for w in new:
            w.start()

    def concurrency(self, tag: int = DEFAULT_TAG) -> int:
        with self._lock:
            return len(self._workers.get(tag, ())) or self._default_concurrency

    # ------------------------------------------------------------ submission
    def submit(self, fn: Callable, args=(), urgent: bool = False,
               tag: int = DEFAULT_TAG) -> FiberTask:
        task = FiberTask(fn, args)
        group = self._group(tag)
        # Power-of-two-choices on queue depth: cheaper than a full scan at
        # large concurrency, and avoids the blind round-robin pile-up the
        # reference solves with per-group signalling (task_control.cpp:565).
        n = len(group)
        if n == 1:
            worker = group[0]
        else:
            a = group[random.randrange(n)]
            b = group[random.randrange(n)]
            worker = a if a.depth() <= b.depth() else b
        worker.push(task, urgent)
        self._lot(tag).signal()
        return task

    # -------------------------------------------------------------- stealing
    def _steal(self, thief: _Worker) -> Optional[FiberTask]:
        group = self._workers.get(thief.tag, ())
        n = len(group)
        if n <= 1:
            return None
        start = random.randrange(n)
        for i in range(n):
            victim = group[(start + i) % n]
            if victim is thief:
                continue
            with victim.lock:
                if victim.local:
                    return victim.local.pop()  # steal from the tail
        return None

    def stop(self) -> None:
        self._stopped = True
        with self._lock:
            lots = list(self._lots.values())
        for lot in lots:
            lot.signal(all_workers=True)


_global_control: Optional[TaskControl] = None
_global_lock = threading.Lock()


def global_control() -> TaskControl:
    global _global_control
    with _global_lock:
        if _global_control is None:
            _global_control = TaskControl()
        return _global_control


def start_background(fn: Callable, *args, tag: int = DEFAULT_TAG) -> FiberTask:
    """Queue a task for any worker (bthread_start_background)."""
    return global_control().submit(fn, args, urgent=False, tag=tag)


def start_urgent(fn: Callable, *args, tag: int = DEFAULT_TAG) -> FiberTask:
    """Queue a task at the head — processed before background work
    (bthread_start_urgent semantics, minus the caller-yield)."""
    return global_control().submit(fn, args, urgent=True, tag=tag)
