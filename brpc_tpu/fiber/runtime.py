"""Fiber runtime — the M:N task scheduler (reference bthread TaskControl/
TaskGroup, task_control.cpp:213/task_group.cpp:470).

Semantics carried over, not code: ``start_background`` enqueues a task for
any worker; ``start_urgent`` runs it at the head of the queue (the
reference's start_foreground makes the *caller* yield — meaningless under
the GIL, so urgency maps to queue position); workers own a local deque and
steal from siblings when idle (Chase-Lev in the reference,
work_stealing_queue.h:32); tagged worker groups isolate pools
(task_control.cpp:291). Python threads are the "pthread workers"; tasks are
plain callables — IO-bound RPC work is where M:N pays off under the GIL,
and device-bound work is dispatched to XLA asynchronously anyway.
"""

from __future__ import annotations

import itertools
import random
import threading
from collections import deque
from typing import Callable, Dict, List, Optional

from brpc_tpu.fiber.butex import contention_stats  # noqa: F401  (re-export for /hotspots/contention)
from brpc_tpu.metrics.reducer import Adder

DEFAULT_TAG = 0


class FiberTask:
    __slots__ = ("fn", "args", "done", "error", "_event")

    def __init__(self, fn, args):
        self.fn = fn
        self.args = args
        self.done = False
        self.error: Optional[BaseException] = None
        self._event = threading.Event()

    def run(self) -> None:
        try:
            self.fn(*self.args)
        except BaseException as e:  # noqa: BLE001 - task errors are captured
            self.error = e
        finally:
            self.done = True
            self._event.set()

    def join(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)


class _Worker(threading.Thread):
    def __init__(self, control: "TaskControl", index: int, tag: int):
        super().__init__(name=f"fiber-worker-{tag}-{index}", daemon=True)
        self.control = control
        self.index = index
        self.tag = tag
        self.local: deque = deque()
        self.lock = threading.Lock()
        self.signal = threading.Event()

    def run(self) -> None:
        control = self.control
        while not control._stopped:
            task = self._next_task()
            if task is None:
                self.signal.wait(timeout=0.05)
                self.signal.clear()
                continue
            control.tasks_executed.put(1)
            task.run()

    def _next_task(self) -> Optional[FiberTask]:
        with self.lock:
            if self.local:
                return self.local.popleft()
        return self.control._steal(self)

    def push(self, task: FiberTask, urgent: bool) -> None:
        with self.lock:
            if urgent:
                self.local.appendleft(task)
            else:
                self.local.append(task)
        self.signal.set()


class TaskControl:
    """Global scheduler: owns workers per tag group, round-robins submission,
    lets idle workers steal from siblings."""

    def __init__(self, concurrency: int = 8):
        self._workers: Dict[int, List[_Worker]] = {}
        self._rr = itertools.count()
        self._stopped = False
        self._lock = threading.Lock()
        self._default_concurrency = concurrency
        self.tasks_executed = Adder()

    def _group(self, tag: int) -> List[_Worker]:
        with self._lock:
            group = self._workers.get(tag)
            if group is None:
                group = [
                    _Worker(self, i, tag)
                    for i in range(self._default_concurrency)
                ]
                self._workers[tag] = group
                for w in group:
                    w.start()
            return group

    def add_workers(self, n: int, tag: int = DEFAULT_TAG) -> None:
        with self._lock:
            group = self._workers.setdefault(tag, [])
            base = len(group)
            new = [_Worker(self, base + i, tag) for i in range(n)]
            group.extend(new)
        for w in new:
            w.start()

    def concurrency(self, tag: int = DEFAULT_TAG) -> int:
        with self._lock:
            return len(self._workers.get(tag, ())) or self._default_concurrency

    # ------------------------------------------------------------ submission
    def submit(self, fn: Callable, args=(), urgent: bool = False,
               tag: int = DEFAULT_TAG) -> FiberTask:
        task = FiberTask(fn, args)
        group = self._group(tag)
        worker = group[next(self._rr) % len(group)]
        worker.push(task, urgent)
        return task

    # -------------------------------------------------------------- stealing
    def _steal(self, thief: _Worker) -> Optional[FiberTask]:
        group = self._workers.get(thief.tag, ())
        n = len(group)
        if n <= 1:
            return None
        start = random.randrange(n)
        for i in range(n):
            victim = group[(start + i) % n]
            if victim is thief:
                continue
            with victim.lock:
                if victim.local:
                    return victim.local.pop()  # steal from the tail
        return None

    def stop(self) -> None:
        self._stopped = True
        with self._lock:
            groups = [w for g in self._workers.values() for w in g]
        for w in groups:
            w.signal.set()


_global_control: Optional[TaskControl] = None
_global_lock = threading.Lock()


def global_control() -> TaskControl:
    global _global_control
    with _global_lock:
        if _global_control is None:
            _global_control = TaskControl()
        return _global_control


def start_background(fn: Callable, *args, tag: int = DEFAULT_TAG) -> FiberTask:
    """Queue a task for any worker (bthread_start_background)."""
    return global_control().submit(fn, args, urgent=False, tag=tag)


def start_urgent(fn: Callable, *args, tag: int = DEFAULT_TAG) -> FiberTask:
    """Queue a task at the head — processed before background work
    (bthread_start_urgent semantics, minus the caller-yield)."""
    return global_control().submit(fn, args, urgent=True, tag=tag)
