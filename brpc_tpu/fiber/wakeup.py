"""Adaptive spin-then-park wakeup discipline (reference bthread/butex.cpp
wait-free wakeup + the spin loop ahead of futex_wait in bthread mutexes).

Every blocking primitive in the small-RPC path (butex, the tunnel's
PeerWindow, the endpoint-ready gate, the event dispatcher's select) pays a
park/unpark round trip per message at low depth — on this lane that is a
condition-variable sleep plus a scheduler wakeup, several hundred
microseconds of the 64B echo's millisecond. A waiter that *spins briefly
first* wins that round trip back whenever the wake arrives within the spin
window (the common case under pipelined load).

Spinning is only a win when wakes actually arrive fast, so the budget is
adaptive per wait-site: a spin that observes the wake before exhausting its
budget grows the budget (up to a cap); a spin that exhausts it and parks
anyway shrinks it toward a small floor. On a box where spins never win
(single core, idle link) the budget collapses to the floor — a handful of
``time.sleep(0)`` yields, microseconds — so parking stays the steady state
and the spin is a cheap probe, not a burn.

Every spin iteration yields the GIL (``time.sleep(0)``): the waker is
usually another thread of this very interpreter, and a non-yielding loop
would hold it off for a full switch interval.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict

from brpc_tpu.metrics.reducer import Adder
from brpc_tpu.metrics.status import PassiveStatus

# global wakeup counters (one set across all sites — the per-site signal
# lives in each AdaptiveSpin's budget, exposed via stats())
g_wakeup_spins = Adder("g_wakeup_spins")            # spin iterations burned
g_wakeup_spin_wins = Adder("g_wakeup_spin_wins")    # wake seen while spinning
g_wakeup_spin_losses = Adder("g_wakeup_spin_losses")  # budget spent, parked
g_wakeup_parks = Adder("g_wakeup_parks")            # fell through to a park


class AdaptiveSpin:
    """One wait-site's spin budget: iterations to burn before parking.

    Thread-safety: budget updates are racy by design (plain int store under
    the GIL); the budget is a heuristic, not an invariant, and a lost
    update merely delays adaptation by one round.
    """

    __slots__ = ("name", "budget", "floor", "ceiling")

    def __init__(self, name: str, initial: int = 32, floor: int = 4,
                 ceiling: int = 4096):
        self.name = name
        self.budget = initial
        self.floor = floor
        self.ceiling = ceiling

    # ------------------------------------------------------------- policy
    def note_win(self) -> None:
        """The wake arrived inside the spin window: spinning pays here."""
        b = self.budget
        self.budget = min(self.ceiling, b + (b >> 1) + 1)
        g_wakeup_spin_wins.put(1)

    def note_loss(self) -> None:
        """Budget exhausted without a wake: decay toward the probe floor."""
        self.budget = max(self.floor, self.budget >> 1)
        g_wakeup_spin_losses.put(1)
        g_wakeup_parks.put(1)

    # -------------------------------------------------------------- spinning
    def spin(self, satisfied: Callable[[], bool]) -> bool:
        """Burn up to ``budget`` yielding iterations waiting for
        ``satisfied()``; True if it held before the budget ran out.
        The caller parks on False (counted as a park here)."""
        spins = 0
        # bounded by the adaptive spin budget snapshot taken here
        for _ in range(self.budget):
            if satisfied():
                g_wakeup_spins.put(spins)
                self.note_win()
                return True
            spins += 1
            time.sleep(0)  # yield the GIL to the prospective waker
        if spins:
            g_wakeup_spins.put(spins)
        self.note_loss()
        return False


# ----------------------------------------------------------------- registry
_instances: Dict[str, AdaptiveSpin] = {}
_instances_lock = threading.Lock()


def get_spin(name: str, **kwargs) -> AdaptiveSpin:
    """The shared AdaptiveSpin for a named wait-site (create on first use)."""
    inst = _instances.get(name)
    if inst is None:
        with _instances_lock:
            inst = _instances.get(name)
            if inst is None:
                inst = AdaptiveSpin(name, **kwargs)
                _instances[name] = inst
    return inst


def budgets() -> Dict[str, int]:
    """Current adaptive budget per wait-site (for /tpu + tests)."""
    with _instances_lock:
        return {name: s.budget for name, s in sorted(_instances.items())}


def stats() -> Dict[str, object]:
    """Snapshot for the /tpu builtin and tests."""
    return {
        "spins": g_wakeup_spins.get_value(),
        "spin_wins": g_wakeup_spin_wins.get_value(),
        "spin_losses": g_wakeup_spin_losses.get_value(),
        "parks": g_wakeup_parks.get_value(),
        "budgets": budgets(),
    }


g_wakeup_spin_budgets = PassiveStatus(budgets).expose("g_wakeup_spin_budgets")
