"""Fiber-local storage — keytables (reference bthread/key.cpp:
bthread_key_create/delete, bthread_setspecific/getspecific).

Semantics carried over:
  - a key is created process-wide with an optional destructor;
  - values are scoped to the RUNNING FIBER TASK (each FiberTask gets a
    lazily-created keytable); code running on a plain thread falls back to
    a thread-local keytable, exactly like bthread_getspecific called from
    a pthread;
  - destructors run when the task finishes (reference keytable teardown at
    task end) or when the key is deleted;
  - a deleted key's slot never resolves again (version check — reference
    key.cpp versioned KeyInfo), so stale keys can't read another key's
    value after slot reuse.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional, Tuple

_registry_lock = threading.Lock()
_destructors: Dict[int, Tuple[int, Optional[Callable[[Any], None]]]] = {}
_next_slot = [0]
_thread_tables = threading.local()

# set by the fiber runtime around task execution
_current = threading.local()


def _table_for_current() -> Dict[int, Any]:
    task = getattr(_current, "task", None)
    if task is not None:
        table = getattr(task, "keytable", None)
        if table is None:
            table = task.keytable = {}
        return table
    table = getattr(_thread_tables, "table", None)
    if table is None:
        table = _thread_tables.table = {}
    return table


def key_create(destructor: Optional[Callable[[Any], None]] = None) -> int:
    """Returns a new key (slot | version<<32, like bthread_key_t)."""
    with _registry_lock:
        slot = _next_slot[0]
        _next_slot[0] += 1
        version = 1
        _destructors[slot] = (version, destructor)
        return slot | (version << 32)


def key_delete(key: int) -> None:
    """Invalidate the key; existing values are abandoned (their destructors
    run only via explicit table teardown, matching the reference's
    'destructor may still run after delete returns' caveat)."""
    slot = key & 0xFFFFFFFF
    with _registry_lock:
        cur = _destructors.get(slot)
        if cur is not None and cur[0] == key >> 32:
            _destructors[slot] = (cur[0] + 1, None)


def _key_valid(key: int) -> bool:
    slot, version = key & 0xFFFFFFFF, key >> 32
    with _registry_lock:
        cur = _destructors.get(slot)
        return cur is not None and cur[0] == version


def set_specific(key: int, value: Any) -> bool:
    if not _key_valid(key):
        return False
    _table_for_current()[key] = value
    return True


def get_specific(key: int, default: Any = None) -> Any:
    if not _key_valid(key):
        return default
    return _table_for_current().get(key, default)


def _run_destructors(table: Dict[int, Any]) -> None:
    """Called by the fiber runtime when a task with a keytable ends."""
    for key, value in list(table.items()):
        slot, version = key & 0xFFFFFFFF, key >> 32
        with _registry_lock:
            cur = _destructors.get(slot)
            dtor = cur[1] if cur is not None and cur[0] == version else None
        if dtor is not None and value is not None:
            try:
                dtor(value)
            except Exception:
                pass
    table.clear()


def _enter_task(task) -> None:
    _current.task = task


def _exit_task(task) -> None:
    _current.task = None
    table = getattr(task, "keytable", None)
    if table:
        _run_destructors(table)


def current_task():
    """The FiberTask running on this thread, or None (pthread context)."""
    return getattr(_current, "task", None)
