"""ExecutionQueue — ordered async execution with an on-demand consumer.

Rebuild of ``bthread/execution_queue.h:30-35``: producers from any thread
push items wait-free; a single consumer task is started only when the queue
transitions empty->non-empty and drains everything in order, then parks.
Guarantees strict ordering without a dedicated thread per queue — the
mechanism Streaming RPC uses for in-order message delivery (stream.cpp).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, List, Optional

from brpc_tpu.fiber import runtime


class ExecutionQueue:
    """execute(item) enqueues; consumer_fn(items: list) handles batches in
    submission order. stop() + join() for graceful shutdown (a None batch is
    delivered last, like the reference's iterated-stop signal)."""

    def __init__(self, consumer_fn: Callable[[Optional[List]], None],
                 control: Optional[runtime.TaskControl] = None,
                 batch_max: int = 64):
        self._consumer_fn = consumer_fn
        self._control = control
        self._queue: deque = deque()
        self._lock = threading.Lock()
        self._running = False
        self._stopped = False
        self._drained = threading.Event()
        self._drained.set()
        self._batch_max = batch_max

    def execute(self, item) -> bool:
        """Returns False if the queue is stopped."""
        with self._lock:
            if self._stopped:
                return False
            self._queue.append(item)
            if self._running:
                return True
            # empty -> non-empty: this producer starts the consumer
            self._running = True
            self._drained.clear()
        self._spawn_consumer()
        return True

    def _spawn_consumer(self) -> None:
        if self._control is not None:
            self._control.submit(self._consume)
        else:
            runtime.start_background(self._consume)

    def _consume(self) -> None:
        while True:
            with self._lock:
                if not self._queue:
                    self._running = False
                    stopped = self._stopped
                    self._drained.set()
                    break
                batch = []
                while self._queue and len(batch) < self._batch_max:
                    batch.append(self._queue.popleft())
            try:
                self._consumer_fn(batch)
            except Exception:
                pass
        if stopped:
            try:
                self._consumer_fn(None)  # stop signal, delivered once drained
            except Exception:
                pass

    def stop(self) -> None:
        notify_now = False
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            if not self._running and not self._queue:
                notify_now = True
        if notify_now:
            try:
                self._consumer_fn(None)
            except Exception:
                pass

    def join(self, timeout: Optional[float] = None) -> bool:
        return self._drained.wait(timeout)

    def __len__(self) -> int:
        with self._lock:
            return len(self._queue)
