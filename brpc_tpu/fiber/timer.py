"""TimerThread — schedule/unschedule callbacks at an absolute time.

Rebuild of ``bthread/timer_thread.h:53-82``: backs every RPC timeout and
backup-request timer. One daemon thread sleeps on a heap of deadlines;
``unschedule`` marks the entry dead (O(1)) instead of re-heapifying, matching
the reference's lazy-deletion design.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Callable, Optional


class _Entry:
    __slots__ = ("deadline", "seq", "fn", "args", "cancelled")

    def __init__(self, deadline: float, seq: int, fn, args):
        self.deadline = deadline
        self.seq = seq
        self.fn = fn
        self.args = args
        self.cancelled = False

    def __lt__(self, other: "_Entry") -> bool:
        return (self.deadline, self.seq) < (other.deadline, other.seq)


class TimerThread:
    def __init__(self, name: str = "fiber-timer"):
        self._heap: list = []
        self._entries = {}
        self._cond = threading.Condition()
        self._seq = itertools.count()
        self._stopped = False
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._started = False

    def _ensure_started(self) -> None:
        if not self._started:
            self._started = True
            self._thread.start()

    # ------------------------------------------------------------------- api
    def schedule(self, fn: Callable, delay_s: float, *args) -> int:
        """Run fn(*args) after delay_s seconds; returns a timer id."""
        return self.schedule_abs(fn, time.monotonic() + delay_s, *args)

    def schedule_abs(self, fn: Callable, abstime: float, *args) -> int:
        entry = _Entry(abstime, next(self._seq), fn, args)
        with self._cond:
            self._ensure_started()
            # wake the sleeper only when this deadline becomes the new
            # head: an RPC-timeout timer landing behind the current head
            # (the overwhelmingly common case) must not cost a thread
            # wakeup per call — the sleeper's timed wait already covers it
            wake = not self._heap or abstime < self._heap[0].deadline
            heapq.heappush(self._heap, entry)
            self._entries[entry.seq] = entry
            if wake:
                self._cond.notify()
        return entry.seq

    def unschedule(self, timer_id: int) -> bool:
        """Cancel; True if the timer had not fired yet."""
        with self._cond:
            entry = self._entries.pop(timer_id, None)
            if entry is None or entry.cancelled:
                return False
            entry.cancelled = True
            return True

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify()

    # ------------------------------------------------------------------ loop
    def _run(self) -> None:
        from brpc_tpu.profiling import registry as _prof

        _prof.register_current_thread(_prof.ROLE_TIMER)
        while True:
            with self._cond:
                while not self._stopped:
                    if not self._heap:
                        self._cond.wait()
                        continue
                    now = time.monotonic()
                    head = self._heap[0]
                    if head.cancelled:
                        heapq.heappop(self._heap)
                        continue
                    if head.deadline <= now:
                        entry = heapq.heappop(self._heap)
                        self._entries.pop(entry.seq, None)
                        break
                    self._cond.wait(timeout=head.deadline - now)
                else:
                    return
            # fire outside the lock
            try:
                entry.fn(*entry.args)
            except Exception:
                pass


_global_timer: Optional[TimerThread] = None
_global_timer_lock = threading.Lock()


def global_timer() -> TimerThread:
    global _global_timer
    with _global_timer_lock:
        if _global_timer is None:
            _global_timer = TimerThread()
        return _global_timer


def timer_add(fn: Callable, delay_s: float, *args) -> int:
    return global_timer().schedule(fn, delay_s, *args)


def timer_del(timer_id: int) -> bool:
    return global_timer().unschedule(timer_id)
