"""Shard dispatch worker — one OS process owning a cid-slice of the lane.

Spawned by the parent's ShardPlane as ``python -m brpc_tpu.shard.worker``
with a one-line JSON config on stdin. The worker attaches the two shm
doorbell rings by name, builds a dispatch-only Server (services from the
configured factory, no listener, no crash handler), and runs a
cut-loop-shaped main loop: pop raw TRPC frames off the in-ring, feed them
through the STOCK InputMessenger + server_processing stack (rtc fastpath
included — the PR-9 machinery runs unchanged in here), and ship responses
back on the out-ring:

- small response  -> ``W_RESP`` (whole packet bytes; parent banks it into
  the coalesced doorbell fan-in write),
- bulk response   -> fill leased sub-window blocks (ONE memcpy, directly
  into client-visible registered memory) -> ``W_RESP_SEGS`` (indices +
  lengths only),
- giant response  -> spill to a fresh named shm segment -> ``W_RESP_SHM``
  (name + length; the parent streams it through the credit window and
  unlinks it). Handles cross the ring; payload bytes never ride a pipe.

Lifecycle: stdin EOF means the parent died — a watcher thread hard-exits
so no orphan survives a parent crash. ``R_QUIT`` is the orderly goodbye.
Every thread registers with the profiling registry under the
``worker:<i>/`` role prefix, so /hotspots/continuous stacks sampled here
(and shipped home as ``W_PROF`` folded lines) attribute to this worker.
"""

from __future__ import annotations

import json
import os
import struct
import sys
import threading
import time as _time
from typing import Dict, Optional

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.shard import wire
from brpc_tpu.shard.ring import ShardRing
from brpc_tpu.shard.subwindow import SubWindow

_II = struct.Struct("!II")
_I = struct.Struct("!I")

# flags the parent mirrors into the worker (all reloadable): the dispatch
# stack in here must classify/inline exactly like the parent's would
_FLAG_ALLOWLIST = (
    "rtc_enable", "rtc_budget_us", "rtc_cheap_us", "rtc_max_body",
    "stream_body_min_bytes", "max_body_size", "shard_vars_interval_s",
    "var_series_enabled",
)

STATS_INTERVAL_S = 0.5
PROF_INTERVAL_S = 2.0
LEASE_REQ_MIN_INTERVAL_S = 0.05


class _WorkerEndpoint:
    """Worker-side state for one adopted tunnel endpoint: a duck vsock the
    RPC stack dispatches through, plus the credit sub-window (None on
    inline-only tunnels)."""

    __slots__ = ("ep_id", "epoch", "vsock", "sub", "last_lease_req")

    def __init__(self, ep_id: int, epoch: int, vsock, sub):
        self.ep_id = ep_id
        self.epoch = epoch
        self.vsock = vsock
        self.sub: Optional[SubWindow] = sub
        self.last_lease_req = 0.0


class WorkerVSocket:
    """Duck-typed stand-in for TpuTransportSocket on the worker side: the
    stock InputMessenger/server_processing stack reads and writes exactly
    this surface. ``write`` routes the packed response to the worker's
    out-ring instead of a ctrl socket."""

    def __init__(self, worker: "ShardWorker", server):
        self.worker = worker
        self.wep: Optional[_WorkerEndpoint] = None   # set right after ctor
        self.read_buf = IOBuf()
        self.pending_body = None
        self.preferred_protocol = None
        self.failed = False
        self.error_code = 0
        self.error_text = ""
        self.remote = None
        self.owner_server = server
        self.user_data = None
        self.in_bytes = 0
        self.out_bytes = 0
        self.in_messages = 0
        self.out_messages = 0
        self.last_active = _time.monotonic()

    # pending-id surface: workers see requests only, never call replies
    def add_pending_id(self, cid: int) -> None:
        pass

    def remove_pending_id(self, cid: int) -> bool:
        return False

    def write(self, data, id_wait: Optional[int] = None) -> int:
        packet = data if isinstance(data, IOBuf) else IOBuf(bytes(data))
        rc = self.worker.send_response(self.wep, packet)
        if rc == 0:
            self.out_messages += 1
            self.out_bytes += len(packet)
        return rc

    def set_failed(self, code: int, reason: str = "") -> None:
        # a bad forwarded frame poisons only itself: each R_MSG carries one
        # complete validated TRPC frame, so drop buffered state and keep
        # serving — the parent-side tunnel owns real failure semantics
        self.worker.parse_errors += 1
        self.error_code = code
        self.error_text = reason
        self.pending_body = None
        self.read_buf.clear()


class ShardWorker:
    def __init__(self, cfg: dict, in_ring: ShardRing, out_ring: ShardRing):
        self.cfg = cfg
        self.index = int(cfg["index"])
        self.gen = int(cfg.get("gen", 0))
        self.in_ring = in_ring          # parent -> worker
        self.out_ring = out_ring        # worker -> parent
        self._out_lock = threading.Lock()
        self._quit = False
        self.eps: Dict[int, _WorkerEndpoint] = {}
        self.dispatched = 0
        self.resp_inline = 0
        self.resp_segs = 0
        self.resp_shm = 0
        self.parse_errors = 0
        self.server = None
        self.messenger = None

    # ------------------------------------------------------------- bootstrap
    def build_server(self):
        from brpc_tpu.policy import ensure_registered
        from brpc_tpu.rpc.input_messenger import InputMessenger
        from brpc_tpu.rpc.server import Server, ServerOptions

        ensure_registered()
        srv = Server(ServerOptions())
        factory = self.cfg.get("factory") or "brpc_tpu.shard.testing:echo_services"
        mod_name, _, attr = factory.partition(":")
        import importlib

        mod = importlib.import_module(mod_name)
        for svc in getattr(mod, attr or "services")():
            srv.add_service(svc)
        # dispatch-only: no listener, no crash handler — just flip the
        # admission gate so process_rpc_request serves instead of ELOGOFF
        srv._running = True
        srv._logoff = False
        self.server = srv
        self.messenger = InputMessenger(server=srv)

    # ------------------------------------------------------------- out-ring
    def push_out(self, rtype: int, payload: bytes) -> bool:
        if len(payload) + 8 > self.out_ring.capacity:
            return False
        while True:
            with self._out_lock:
                if self.out_ring.push(rtype, payload):
                    return True
            if self._quit:
                return False
            _time.sleep(0.0005)  # parked until the collector drains a slot

    # -------------------------------------------------------- response path
    def send_response(self, wep: Optional[_WorkerEndpoint],
                      packet: IOBuf) -> int:
        if wep is None:
            return -1
        total = len(packet)
        head = packet.fetch(12)
        if len(head) < 12 or head[:4] != b"TRPC":
            return -1
        meta_size = int.from_bytes(head[4:8], "big")
        cid = wire.response_cid(packet.fetch(12 + meta_size), meta_size)
        from brpc_tpu.tpu.transport import INLINE_MAX

        sub = wep.sub
        if total > INLINE_MAX and sub is not None:
            got = sub.take_now(-(-total // sub.block_size))
            if got is not None:
                return self._respond_segs(wep, cid, packet, total, got)
            self._maybe_request_lease(wep, -(-total // sub.block_size))
        if total + wire._IQ.size + 8 > self.out_ring.capacity // 4:
            return self._respond_shm(wep, cid, packet, total)
        self.resp_inline += 1
        ok = self.push_out(wire.W_RESP,
                           wire.encode_resp(wep.ep_id, cid,
                                            packet.tobytes()))
        return 0 if ok else -1

    def _respond_segs(self, wep, cid: int, packet: IOBuf, total: int,
                      got) -> int:
        sub = wep.sub
        bs = sub.block_size
        views = [memoryview(v) for v in packet.iter_blocks() if len(v)]
        segs = []
        sent = 0
        vi, voff = 0, 0
        buf = sub._shm.buf
        for idx in got:
            base = idx * bs
            blk_off = 0
            while blk_off < bs and sent < total:
                v = views[vi]
                take = min(bs - blk_off, len(v) - voff)
                buf[base + blk_off:base + blk_off + take] = \
                    v[voff:voff + take]
                blk_off += take
                voff += take
                sent += take
                if voff == len(v):
                    vi += 1
                    voff = 0
            segs.append((idx, blk_off))
            if sent >= total:
                break
        self.resp_segs += 1
        ok = self.push_out(wire.W_RESP_SEGS,
                           wire.encode_resp_segs(wep.ep_id, wep.epoch, cid,
                                                 segs))
        return 0 if ok else -1

    def _respond_shm(self, wep, cid: int, packet: IOBuf, total: int) -> int:
        """Giant-response escape: the packet doesn't fit the ring and no
        lease covers it — spill to a fresh named segment and ship the
        handle. The PARENT unlinks after streaming it out."""
        import secrets
        from multiprocessing import shared_memory as _shm

        from brpc_tpu.shard.ring import _untrack

        name = f"brpctpu_spill_{os.getpid():x}_{secrets.token_hex(4)}"
        seg = _shm.SharedMemory(name=name, create=True, size=max(total, 1))
        off = 0
        for v in packet.iter_blocks():
            seg.buf[off:off + len(v)] = v
            off += len(v)
        seg.close()
        _untrack(name)  # parent owns the unlink from here
        self.resp_shm += 1
        body = struct.pack("!IQQ", wep.ep_id, cid, total) + name.encode()
        ok = self.push_out(wire.W_RESP_SHM, body)
        if not ok:
            try:
                _shm.SharedMemory(name=name).unlink()
            except Exception:
                pass
            return -1
        return 0

    def _maybe_request_lease(self, wep, want: int) -> None:
        now = _time.monotonic()
        if now - wep.last_lease_req < LEASE_REQ_MIN_INTERVAL_S:
            return
        wep.last_lease_req = now
        with self._out_lock:
            self.out_ring.push(wire.W_LEASE_REQUEST,
                               wire.encode_want(wep.ep_id, max(want, 4)))

    # --------------------------------------------------------- in-ring side
    def handle(self, rtype: int, payload: bytes) -> None:
        if rtype == wire.R_MSG:
            ep_id, frame = wire.decode_msg(payload)
            wep = self.eps.get(ep_id)
            if wep is None:
                return
            wep.vsock.read_buf.append(frame)
            wep.vsock.in_bytes += len(frame)
            wep.vsock.last_active = _time.monotonic()
            self.dispatched += 1
            self.messenger.cut_messages(wep.vsock)
        elif rtype == wire.R_ATTACH:
            ep_id, epoch = _II.unpack_from(payload)
            info = json.loads(payload[_II.size:].decode())
            old = self.eps.pop(ep_id, None)
            if old is not None and old.sub is not None:
                old.sub.close()
            sub = None
            if info.get("pool"):
                try:
                    sub = SubWindow(info["pool"], int(info["bs"]),
                                    int(info["bc"]), epoch)
                except Exception:
                    sub = None   # cross-host tunnel: W_RESP fallback only
            vs = WorkerVSocket(self, self.server)
            wep = _WorkerEndpoint(ep_id, epoch, vs, sub)
            vs.wep = wep
            self.eps[ep_id] = wep
        elif rtype == wire.R_LEASE_GRANT:
            ep_id, epoch, idxs = wire.decode_indices(payload)
            wep = self.eps.get(ep_id)
            if wep is None or wep.sub is None \
                    or not wep.sub.grant(idxs, epoch):
                # unknown endpoint / stale epoch: bounce the credits home
                self.push_out(wire.W_LEASE_RETURN,
                              wire.encode_indices(ep_id, epoch, idxs))
        elif rtype == wire.R_LEASE_RECLAIM:
            ep_id, want = wire.decode_want(payload)
            wep = self.eps.get(ep_id)
            if wep is not None and wep.sub is not None:
                back = wep.sub.give_back(want)
                if back:
                    self.push_out(wire.W_LEASE_RETURN,
                                  wire.encode_indices(ep_id, wep.epoch,
                                                      back))
        elif rtype == wire.R_DETACH:
            wep = self.eps.pop(_I.unpack_from(payload)[0], None)
            if wep is not None and wep.sub is not None:
                wep.sub.close()
        elif rtype == wire.R_QUIT:
            self._quit = True

    # ------------------------------------------------------------ telemetry
    def _stats_json(self) -> bytes:
        eps = {}
        for ep_id, wep in self.eps.items():
            sub = wep.sub
            eps[str(ep_id)] = {
                "lease_free": sub.free_count() if sub else 0,
                "lease_granted": sub.granted_total if sub else 0,
                "lease_taken": sub.taken_total if sub else 0,
            }
        return json.dumps({
            "pid": os.getpid(),
            "gen": self.gen,
            "dispatched": self.dispatched,
            "resp_inline": self.resp_inline,
            "resp_segs": self.resp_segs,
            "resp_shm": self.resp_shm,
            "parse_errors": self.parse_errors,
            "ring_pushed": self.out_ring.pushed,
            "ring_full": self.out_ring.push_full,
            "eps": eps,
        }).encode()

    def _prof_lines(self, since: float) -> bytes:
        try:
            from brpc_tpu.profiling.sampler import continuous

            cont = continuous()
            if cont is None:
                return b""
            prof = cont.query(from_ts=since)
            lines = prof.folded_lines(tag_role=True, tag_phase=True)
            lines.sort(key=lambda ln: -int(ln.rsplit(" ", 1)[1]))
            return "\n".join(lines[:40]).encode()
        except Exception:
            return b""

    # ------------------------------------------------------------ main loop
    def run(self) -> int:
        from brpc_tpu.fiber import wakeup as _wakeup
        from brpc_tpu.profiling import registry as _prof
        from brpc_tpu.profiling.sampler import ensure_continuous_started

        _prof.register_current_thread("shard_cut")
        ensure_continuous_started()
        self.build_server()
        self.push_out(wire.W_READY, _I.pack(os.getpid()))
        spin = _wakeup.get_spin("shard_worker_ring", initial=64,
                                ceiling=2048)
        idle_sleep = 0.0
        last_stats = _time.monotonic()
        last_prof = last_stats
        last_vars = last_stats
        last_prof_ts = _time.time()
        from brpc_tpu import flags as _flags
        from brpc_tpu.shard.fleet import worker_snapshot
        vars_interval = float(_flags.get("shard_vars_interval_s"))
        while not self._quit:
            recs = self.in_ring.pop(64)
            if recs:
                idle_sleep = 0.0
                for rtype, payload in recs:
                    self.handle(rtype, payload)
            else:
                if not spin.spin(lambda: not self.in_ring.empty):
                    # escalate toward a 2ms floor: idle worker stays <1%
                    # CPU on the shared core, busy ring picked up in-spin
                    idle_sleep = min(0.002, idle_sleep + 0.0002)
                    _time.sleep(idle_sleep)
            now = _time.monotonic()
            if now - last_stats >= STATS_INTERVAL_S:
                last_stats = now
                with self._out_lock:
                    self.out_ring.push(wire.W_STATS, self._stats_json())
            if now - last_prof >= PROF_INTERVAL_S:
                last_prof = now
                lines = self._prof_lines(last_prof_ts)
                last_prof_ts = _time.time()
                if lines:
                    with self._out_lock:
                        self.out_ring.push(wire.W_PROF, lines)
            if now - last_vars >= vars_interval:
                last_vars = now
                try:
                    snap = worker_snapshot(self.index)
                except Exception:
                    snap = b""
                if snap:
                    with self._out_lock:
                        self.out_ring.push(wire.W_VARS, snap)
        for wep in self.eps.values():
            if wep.sub is not None:
                wep.sub.close()
        self.in_ring.close()
        self.out_ring.close()
        return 0


def _watch_parent() -> None:
    """Block on stdin until EOF (parent exited or closed our pipe), then
    hard-exit: an orphan worker must never outlive its plane. Raw os.read
    — not sys.stdin.buffer — so this daemon thread never holds the
    buffered-reader lock the interpreter wants back at shutdown."""
    try:
        while os.read(0, 65536):
            pass
    except Exception:
        pass
    os._exit(0)


def main() -> int:
    line = sys.stdin.buffer.readline()
    if not line:
        return 1
    cfg = json.loads(line.decode())
    watcher = threading.Thread(target=_watch_parent, name="shard-parent-eof",
                               daemon=True)
    watcher.start()
    from brpc_tpu import flags as _flags
    from brpc_tpu.profiling import registry as _prof

    _prof.set_role_prefix(f"worker:{cfg['index']}/")
    for name in _FLAG_ALLOWLIST:
        if name in cfg.get("flags", {}):
            try:
                _flags.set_flag(name, cfg["flags"][name])
            except Exception:
                pass
    in_ring = ShardRing.attach(cfg["in_ring"])
    out_ring = ShardRing.attach(cfg["out_ring"])
    return ShardWorker(cfg, in_ring, out_ring).run()


if __name__ == "__main__":
    sys.exit(main())
