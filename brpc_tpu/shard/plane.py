"""ShardPlane — parent-side orchestration of the sharded dispatch plane.

One plane per Server (created at ``Server.start`` when
``tpu_shard_workers > 0``). It owns:

- **workers**: N ``brpc_tpu.shard.worker`` processes, each wired up with a
  pair of shm SPSC rings (ring.py) created — and unlinked — by the parent;
- **routing**: ``shard_for(cid, n)`` hashes correlation ids to workers, so
  one call's request, retries, and response accounting all land on the
  same worker (cid-sharded tunnels);
- **the lane hook**: ``_EndpointLane.pump`` runs inside the parent's cut
  loop (input_messenger) and skims complete TRPC request frames off an
  adopted endpoint's read_buf BEFORE the parent parses them — the varint
  scan (wire.scan_request_meta) reads just enough meta to route; the
  request's Python-heavy parse/execute/respond happens in the worker;
- **doorbell fan-in**: one collector thread drains every worker's out-ring
  and banks small responses into ONE coalesced ctrl write per endpoint per
  drain round (``TpuEndpoint.fan_in_flush``), posts leased-block bulk
  responses (``post_worker_segments``), and services the lease protocol;
- **lifecycle**: a monitor thread hosts the ``worker.crash`` fault point,
  detects death, fans retriable errors to the dead worker's in-flight
  cids (exactly like tunnel death: EFAILEDSOCKET is in
  ``errors.DEFAULT_RETRYABLE``), reclaims its credit leases wholesale,
  bumps the plane generation, and respawns with backoff.

Anything the plane cannot forward (ring full, worker dead, bulk request,
non-TRPC bytes, streams) falls back to the parent's in-process dispatch —
sharding is an optimization, never a correctness gate.
"""

from __future__ import annotations

import json
import os
import secrets
import struct
import subprocess
import sys
import threading
import time as _time
from typing import Dict, List, Optional

from brpc_tpu import fault as _fault
from brpc_tpu import flags as _flags
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.metrics.reducer import Adder
from brpc_tpu.profiling import registry as _prof
from brpc_tpu.rpc import errors
from brpc_tpu.shard import wire
from brpc_tpu.shard.fleet import FleetVars
from brpc_tpu.shard.ring import ShardRing
from brpc_tpu.shard.subwindow import LeaseManager

_II = struct.Struct("!II")

g_shard_forwarded = Adder("g_shard_forwarded")
g_shard_fallback = Adder("g_shard_fallback")
g_shard_fanin_flushes = Adder("g_shard_fanin_flushes")
g_shard_fanin_frames = Adder("g_shard_fanin_frames")
g_shard_worker_deaths = Adder("g_shard_worker_deaths")
g_shard_respawns = Adder("g_shard_respawns")


def shard_for(cid: int, n: int) -> int:
    """cid -> worker index, stable across processes and runs — routing
    stability is load-bearing: a retry re-issued with the same cid lands
    on the same worker.

    Full splitmix64 avalanche, not a bare multiplicative hash: real cids
    from a low-concurrency channel are ``version << 32`` (VersionedPool
    reuses slot 0, only the high-bits version advances), so any scheme
    that reads a fixed bit range of ``cid * K`` sees a constant — the
    original Knuth hash pinned every request of a sequential client to
    worker 0."""
    h = cid & 0xFFFFFFFFFFFFFFFF
    h ^= h >> 30
    h = (h * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    h ^= h >> 27
    h = (h * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    h ^= h >> 31
    return h % n


class WorkerHandle:
    """Parent-side record of one worker slot (survives respawns: the slot
    keeps its index, the process generation bumps)."""

    def __init__(self, index: int):
        self.index = index
        self.gen = 0
        self.proc: Optional[subprocess.Popen] = None
        self.in_ring: Optional[ShardRing] = None   # parent -> worker
        self.out_ring: Optional[ShardRing] = None  # worker -> parent
        self.alive = False          # READY seen, attaches broadcast
        self.spawned = False
        self.pid = 0
        self.respawns = 0
        # push_lock serializes ring pushes with the inflight map so a
        # worker-death snapshot can never miss a forwarded cid
        self.push_lock = threading.Lock()
        self.inflight: Dict[int, tuple] = {}   # cid -> (ep_id, attempt)
        self.stats: dict = {}
        self.prof_lines: str = ""


class _EndpointLane:
    """Per-adopted-endpoint shard state; ``pump`` is the cut-loop hook."""

    __slots__ = ("plane", "ep", "ep_id", "attached_epoch", "lm",
                 "attached_workers", "_attach_body")

    def __init__(self, plane: "ShardPlane", ep, ep_id: int):
        self.plane = plane
        self.ep = ep
        self.ep_id = ep_id
        self.attached_epoch = -1
        self.lm: Optional[LeaseManager] = None
        # (index, gen) pairs that have seen this lane's current R_ATTACH —
        # forward() only targets these, so a worker can never receive an
        # R_MSG for an endpoint it does not know (guarded by _attach_lock)
        self.attached_workers: set = set()
        self._attach_body = b""

    # ------------------------------------------------------------- attach
    def _ensure_attached(self) -> bool:
        ep = self.ep
        if ep._failed or not ep.ready.is_set():
            return False
        if self.attached_epoch == ep.epoch:
            return True
        win = ep.window
        info = {"pool": win._shm.name if win is not None else "",
                "bs": win.block_size if win is not None else 0,
                "bc": win.block_count if win is not None else 0}
        body = _II.pack(self.ep_id, ep.epoch) + json.dumps(info).encode()
        plane = self.plane
        with plane._attach_lock:
            self.lm = LeaseManager(win, ep.epoch) if win is not None else None
            self.attached_epoch = ep.epoch
            self.attached_workers.clear()
            self._attach_body = body
            for w in plane.workers:
                if w.alive:
                    plane._attach_to_worker(w, self)
        return True

    # --------------------------------------------------------------- pump
    def pump(self, sock) -> int:
        """Skim complete, small, cid-addressed TRPC request frames off the
        endpoint's read_buf and forward them to workers. Runs on the cut
        loop inside its batch bracket: pop_front of a forwarded frame
        fires the borrowed blocks' release hooks HERE, so their credits
        coalesce into the batch's one FT_ACK exactly as in-process parsing
        would. Anything it declines stays for the in-process parser."""
        plane = self.plane
        if plane._stop.is_set() or self.ep._failed or sock.failed:
            return 0
        if not self._ensure_attached():
            return 0
        buf = sock.read_buf
        fmax = plane.forward_max
        count = 0
        while len(buf) >= 12:
            head = buf.fetch(12)
            if head[:4] != b"TRPC":
                break
            total = 12 + int.from_bytes(head[4:8], "big") \
                + int.from_bytes(head[8:12], "big")
            if total > fmax or len(buf) < total:
                break
            frame = buf.fetch(total)   # one copy; handles/bytes, no views
            meta_size = int.from_bytes(head[4:8], "big")
            info = wire.scan_request_meta(frame[12:12 + meta_size])
            if info is None:
                break
            has_req, cid, attempt, has_stream = info
            if not has_req or has_stream or cid == 0:
                break   # responses/streams/cid-less: in-process path
            w = plane.workers[shard_for(cid, len(plane.workers))]
            if not plane.forward(w, self, cid, attempt, frame):
                g_shard_fallback.put(1)
                plane.fallback += 1
                break
            buf.pop_front(total)
            sock.in_messages += 1
            count += 1
        return count


class ShardPlane:
    def __init__(self, server=None, workers: Optional[int] = None,
                 factory: Optional[str] = None):
        self.server = server
        n = int(_flags.get("tpu_shard_workers")) if workers is None \
            else workers
        self.n = max(1, n)
        self.factory = factory
        self.forward_max = int(_flags.get("tpu_shard_forward_max"))
        self.ring_bytes = int(_flags.get("tpu_shard_ring_mb")) * (1 << 20)
        self.respawn_max = int(_flags.get("tpu_shard_respawn_max"))
        self.respawn_backoff_ms = int(
            _flags.get("tpu_shard_respawn_backoff_ms"))
        self.rebalance_pct = int(_flags.get("tpu_shard_rebalance_pct"))
        self.workers: List[WorkerHandle] = [WorkerHandle(i)
                                            for i in range(self.n)]
        self.lanes: Dict[int, _EndpointLane] = {}
        self._next_ep = 0
        self._ep_lock = threading.Lock()
        self._attach_lock = threading.Lock()
        self._stop = threading.Event()
        self._shutdown_done = False
        self.generation = 0
        self.forwarded = 0
        self.fallback = 0
        self.fanin_batches = 0
        self.fanin_frames = 0
        self.fleet = FleetVars()
        for w in self.workers:
            self._spawn(w)
        self._collector_t = threading.Thread(
            target=self._collector, name="shard-collector", daemon=True)
        self._monitor_t = threading.Thread(
            target=self._monitor, name="shard-monitor", daemon=True)
        self._collector_t.start()
        self._monitor_t.start()

    # ------------------------------------------------------------ lifecycle
    def _spawn(self, w: WorkerHandle) -> None:
        token = secrets.token_hex(3)
        base = f"brpctpu_shard_{os.getpid():x}_{w.index}_{w.gen}_{token}"
        w.in_ring = ShardRing.create(base + "_i", self.ring_bytes)
        w.out_ring = ShardRing.create(base + "_o", self.ring_bytes)
        cfg = {
            "index": w.index,
            "gen": w.gen,
            "in_ring": w.in_ring.name,
            "out_ring": w.out_ring.name,
            "factory": self.factory,
            "flags": {name: _flags.get(name)
                      for name in ("rtc_enable", "rtc_budget_us",
                                   "rtc_cheap_us", "rtc_max_body",
                                   "stream_body_min_bytes",
                                   "max_body_size",
                                   "shard_vars_interval_s",
                                   "var_series_enabled")},
        }
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        w.proc = subprocess.Popen(
            [sys.executable, "-m", "brpc_tpu.shard.worker"],
            stdin=subprocess.PIPE, stdout=subprocess.DEVNULL, env=env)
        w.proc.stdin.write(json.dumps(cfg).encode() + b"\n")
        w.proc.stdin.flush()
        w.spawned = True
        w.pid = w.proc.pid

    def wait_ready(self, timeout: float = 15.0) -> bool:
        """Block until every worker slot reported READY (tests/bench use
        this; serving does not — un-ready workers just mean fallback)."""
        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            if all(w.alive for w in self.workers):
                return True
            _time.sleep(0.02)
        return all(w.alive for w in self.workers)

    def adopt_endpoint(self, ep) -> Optional[_EndpointLane]:
        """Hook a server-side tunnel endpoint into the plane: its vsock
        gets a ``shard_lane`` the cut loop pumps through."""
        if self._stop.is_set() or getattr(ep, "role", "") != "server":
            return None
        with self._ep_lock:
            self._next_ep += 1
            lane = _EndpointLane(self, ep, self._next_ep)
            self.lanes[lane.ep_id] = lane
        ep.vsock.shard_lane = lane
        pv = ep._pri_vsock
        if pv is not None:
            pv.shard_lane = lane
        return lane

    # ------------------------------------------------------------ forwarding
    def forward(self, w: WorkerHandle, lane: _EndpointLane, cid: int,
                attempt: int, frame: bytes) -> bool:
        key = (w.index, w.gen)
        with w.push_lock:
            if not w.alive or key not in lane.attached_workers:
                return False
            if not w.in_ring.push(wire.R_MSG,
                                  wire.encode_msg(lane.ep_id, frame)):
                return False
            w.inflight[cid] = (lane.ep_id, attempt)
        self.forwarded += 1
        g_shard_forwarded.put(1)
        return True

    def _attach_to_worker(self, w: WorkerHandle, lane: _EndpointLane) -> None:
        """Push this lane's R_ATTACH (+ initial lease) to one worker.
        Caller holds _attach_lock; ring FIFO guarantees the worker sees
        ATTACH before any R_MSG forward() sends after we mark it."""
        with w.push_lock:
            if not w.in_ring.push(wire.R_ATTACH, lane._attach_body):
                return
            lane.attached_workers.add((w.index, w.gen))
        lm = lane.lm
        if lm is None:
            return
        # initial sub-window lease: half the window split across workers,
        # the other half stays with the parent's own send path
        want = max(1, lm.window.block_count // (2 * len(self.workers)))
        got = lm.grant(w.index, want, timeout=0.02)
        if got:
            with w.push_lock:
                ok = w.in_ring.push(
                    wire.R_LEASE_GRANT,
                    wire.encode_indices(lane.ep_id, lane.attached_epoch,
                                        got))
            if not ok:
                lm.ungrant(w.index, got)

    # ------------------------------------------------------------- collector
    def _collector(self) -> None:
        _prof.register_current_thread("shard_collector")
        idle = 0.0
        while not self._stop.is_set():
            n = self._drain_once()
            if n:
                idle = 0.0
            else:
                # escalate to a 2ms poll floor: idle plane <1% of the core
                idle = min(0.002, idle + 0.0002)
                self._stop.wait(idle)

    def _lane(self, ep_id: int) -> Optional[_EndpointLane]:
        lane = self.lanes.get(ep_id)
        if lane is None or lane.ep._failed:
            return None
        return lane

    def _drain_once(self) -> int:
        total = 0
        for w in self.workers:
            ring = w.out_ring
            if ring is None:
                continue
            recs = ring.pop(128)
            if not recs:
                continue
            total += len(recs)
            smalls: Dict[_EndpointLane, List[bytes]] = {}
            for rtype, payload in recs:
                try:
                    self._handle_rec(w, rtype, payload, smalls)
                except Exception:
                    pass   # one malformed record must not kill the drain
            for lane, frames in smalls.items():
                rc = lane.ep.fan_in_flush(frames)
                if rc == 0:
                    self.fanin_batches += 1
                    self.fanin_frames += len(frames)
                    g_shard_fanin_flushes.put(1)
                    g_shard_fanin_frames.put(len(frames))
        return total

    def _handle_rec(self, w: WorkerHandle, rtype: int, payload: bytes,
                    smalls: Dict[_EndpointLane, List[bytes]]) -> None:
        from brpc_tpu.tpu.transport import INLINE_MAX

        if rtype == wire.W_RESP:
            ep_id, cid, pkt = wire.decode_resp(payload)
            with w.push_lock:
                w.inflight.pop(cid, None)
            lane = self._lane(ep_id)
            if lane is None:
                return
            if len(pkt) <= INLINE_MAX and lane.ep.peer_version >= 3:
                smalls.setdefault(lane, []).append(pkt)
            else:
                lane.ep.send_packet(IOBuf(pkt))
        elif rtype == wire.W_RESP_SEGS:
            ep_id, epoch, cid, segs = wire.decode_resp_segs(payload)
            with w.push_lock:
                w.inflight.pop(cid, None)
            lane = self._lane(ep_id)
            if lane is None or lane.lm is None:
                return
            # the credits leave the lease NOW (they ride to the client and
            # come home as FT_ACKs) — even if the post fails, the tunnel
            # fail path owns them, not the lease
            lane.lm.note_posted(w.index, [i for i, _ in segs])
            lane.ep.post_worker_segments(segs, epoch)
        elif rtype == wire.W_RESP_SHM:
            ep_id, cid, total = struct.unpack_from("!IQQ", payload)
            name = payload[20:].decode()
            with w.push_lock:
                w.inflight.pop(cid, None)
            data = self._read_spill(name, total)
            lane = self._lane(ep_id)
            if lane is not None and data is not None:
                lane.ep.send_packet(IOBuf(data))
        elif rtype == wire.W_LEASE_RETURN:
            ep_id, epoch, idxs = wire.decode_indices(payload)
            lane = self.lanes.get(ep_id)
            if lane is not None and lane.lm is not None \
                    and lane.attached_epoch == epoch:
                lane.lm.note_returned(w.index, idxs)
        elif rtype == wire.W_LEASE_REQUEST:
            ep_id, want = wire.decode_want(payload)
            self._service_lease_request(w, ep_id, want)
        elif rtype == wire.W_READY:
            self._on_worker_ready(w, struct.unpack_from("!I", payload)[0])
        elif rtype == wire.W_STATS:
            w.stats = json.loads(payload.decode())
        elif rtype == wire.W_PROF:
            w.prof_lines = payload.decode()
        elif rtype == wire.W_VARS:
            self.fleet.on_snapshot(w.index, payload)

    @staticmethod
    def _read_spill(name: str, total: int) -> Optional[bytes]:
        from multiprocessing import shared_memory as _shm

        try:
            seg = _shm.SharedMemory(name=name)
        except Exception:
            return None
        try:
            return bytes(seg.buf[:total])
        finally:
            try:
                seg.close()
                seg.unlink()
            except Exception:
                pass

    def _on_worker_ready(self, w: WorkerHandle, pid: int) -> None:
        w.pid = pid
        with self._attach_lock:
            for lane in list(self.lanes.values()):
                if lane.attached_epoch >= 0 and not lane.ep._failed:
                    self._attach_to_worker(w, lane)
            w.alive = True

    def _service_lease_request(self, w: WorkerHandle, ep_id: int,
                               want: int) -> None:
        lane = self._lane(ep_id)
        if lane is None or lane.lm is None:
            return
        got = lane.lm.grant(w.index, want, timeout=0.02)
        if got:
            with w.push_lock:
                ok = w.alive and w.in_ring.push(
                    wire.R_LEASE_GRANT,
                    wire.encode_indices(ep_id, lane.attached_epoch, got))
            if not ok:
                lane.lm.ungrant(w.index, got)
            return
        # window dry: occupancy has skewed — reclaim from the richest
        # sibling so the starved worker's next request can be granted
        self._rebalance(lane, exclude=w.index, want=want)

    def _rebalance(self, lane: _EndpointLane, exclude: int,
                   want: int) -> Optional[int]:
        """Ask the worker holding the most idle lease credits of this
        endpoint to give some back (R_LEASE_RECLAIM). Returns the chosen
        worker index, or None when nobody holds enough to matter."""
        lm = lane.lm
        if lm is None:
            return None
        richest, free = None, 0
        for cand in self.workers:
            if cand.index == exclude or not cand.alive:
                continue
            ep_stats = (cand.stats.get("eps") or {}).get(str(lane.ep_id))
            cand_free = int(ep_stats["lease_free"]) if ep_stats else \
                lm.leased_count(cand.index)
            if cand_free > free:
                richest, free = cand, cand_free
        # only reclaim when the sibling's idle share crosses the skew
        # threshold — constant reclaim churn under balanced load is worse
        # than a few W_RESP fallbacks
        threshold = max(1, lm.window.block_count * self.rebalance_pct
                        // (100 * max(1, len(self.workers))))
        if richest is None or free < threshold:
            return None
        with richest.push_lock:
            richest.in_ring.push(wire.R_LEASE_RECLAIM,
                                 wire.encode_want(lane.ep_id, want))
        return richest.index

    # --------------------------------------------------------------- monitor
    def _monitor(self) -> None:
        _prof.register_current_thread("shard_monitor")
        last_prune = _time.monotonic()
        while not self._stop.wait(0.02):
            for w in self.workers:
                if w.proc is None:
                    continue
                if _fault.hit("worker.crash", worker=w.index) is not None:
                    try:
                        w.proc.kill()
                    except Exception:
                        pass
                if w.proc.poll() is not None:
                    self._on_worker_death(w)
            now = _time.monotonic()
            if now - last_prune >= 1.0:
                last_prune = now
                self._prune_lanes()

    def _prune_lanes(self) -> None:
        dead = [ep_id for ep_id, lane in list(self.lanes.items())
                if lane.ep._failed]
        for ep_id in dead:
            with self._ep_lock:
                lane = self.lanes.pop(ep_id, None)
            if lane is None:
                continue
            for w in self.workers:
                if w.alive:
                    with w.push_lock:
                        w.in_ring.push(wire.R_DETACH,
                                       struct.pack("!I", ep_id))
            # the window died with the endpoint; leases are moot but the
            # ledger still wants its acquire/release books balanced
            if lane.lm is not None:
                lane.lm.release_all()

    def _on_worker_death(self, w: WorkerHandle) -> None:
        w.alive = False
        g_shard_worker_deaths.put(1)
        self.generation += 1
        with w.push_lock:
            inflight = dict(w.inflight)
            w.inflight.clear()
        for lane in list(self.lanes.values()):
            lane.attached_workers = {k for k in lane.attached_workers
                                     if k[0] != w.index}
            if lane.lm is not None:
                lane.lm.reclaim_worker(w.index)
        # in-flight cids fan RETRIABLE errors, exactly like tunnel death:
        # the channel's retry policy re-issues them (EFAILEDSOCKET is in
        # errors.DEFAULT_RETRYABLE)
        for cid, (ep_id, attempt) in inflight.items():
            lane = self._lane(ep_id)
            if lane is not None:
                self._fan_error(lane.ep, cid, attempt)
        if w.in_ring is not None:
            w.in_ring.close()
            w.out_ring.close()
            w.in_ring = w.out_ring = None
        try:
            w.proc.stdin.close()
        except Exception:
            pass
        w.proc = None
        w.spawned = False
        if self._stop.is_set() or w.respawns >= self.respawn_max:
            return
        w.respawns += 1
        g_shard_respawns.put(1)
        _time.sleep(self.respawn_backoff_ms * w.respawns / 1000.0)
        w.gen += 1
        self._spawn(w)

    @staticmethod
    def _fan_error(ep, cid: int, attempt: int) -> None:
        from brpc_tpu.proto import rpc_meta_pb2
        from brpc_tpu.rpc.protocol import find_protocol

        meta = rpc_meta_pb2.RpcMeta()
        meta.correlation_id = cid
        if attempt:
            meta.attempt_version = attempt
        meta.response.error_code = errors.EFAILEDSOCKET
        meta.response.error_text = "shard worker died; retry"
        pkt = find_protocol("trpc_std").pack_response(meta, b"")
        ep.send_packet(pkt)

    # -------------------------------------------------------------- shutdown
    def shutdown(self, timeout: float = 2.0) -> None:
        """Orderly teardown, called BEFORE the server closes its endpoints
        so every leased credit is home when the CreditLedger audits the
        windows at close."""
        if self._shutdown_done:
            return
        self._shutdown_done = True
        for lane in list(self.lanes.values()):
            lane.ep.vsock.shard_lane = None
            pv = lane.ep._pri_vsock
            if pv is not None:
                pv.shard_lane = None
        for w in self.workers:
            if w.alive and w.in_ring is not None:
                with w.push_lock:
                    w.in_ring.push(wire.R_QUIT, b"")
        # drain in-flight responses before stopping the collector loop
        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            if not any(w.proc is not None and w.proc.poll() is None
                       for w in self.workers):
                break
            _time.sleep(0.01)
        self._stop.set()
        self._collector_t.join(timeout=1.0)
        self._monitor_t.join(timeout=1.0)
        self._drain_once()
        self.fleet.hide_all()
        for lane in list(self.lanes.values()):
            if lane.lm is not None:
                lane.lm.release_all()
        for w in self.workers:
            if w.proc is not None:
                try:
                    w.proc.stdin.close()
                except Exception:
                    pass
                try:
                    w.proc.wait(timeout=1.0)
                except Exception:
                    try:
                        w.proc.kill()
                        w.proc.wait(timeout=1.0)
                    except Exception:
                        pass
                w.proc = None
            w.alive = False
            if w.in_ring is not None:
                w.in_ring.close()
                w.out_ring.close()
                w.in_ring = w.out_ring = None

    # ------------------------------------------------------------ state view
    def state_dict(self) -> dict:
        """The /tpu builtin's ``shard`` section."""
        workers = []
        for w in self.workers:
            st = w.stats or {}
            lease_free = sum(int(e.get("lease_free", 0))
                             for e in (st.get("eps") or {}).values())
            lease_held = 0
            for lane in list(self.lanes.values()):
                if lane.lm is not None:
                    lease_held += lane.lm.leased_count(w.index)
            workers.append({
                "index": w.index,
                "pid": w.pid,
                "role": f"worker:{w.index}",
                "alive": w.alive,
                "gen": w.gen,
                "respawns": w.respawns,
                "inflight_cids": len(w.inflight),
                "lease_held": lease_held,
                "lease_free": lease_free,
                "dispatched": int(st.get("dispatched", 0)),
                "resp_inline": int(st.get("resp_inline", 0)),
                "resp_segs": int(st.get("resp_segs", 0)),
            })
        return {
            "workers_configured": self.n,
            "generation": self.generation,
            "forwarded": self.forwarded,
            "fallback": self.fallback,
            "fanin_batches": self.fanin_batches,
            "fanin_frames": self.fanin_frames,
            "endpoints": len(self.lanes),
            "workers": workers,
        }

    def worker_folded_lines(self) -> List[str]:
        """Latest W_PROF folded-stack lines from every worker (already
        role-tagged ``worker:<i>/...`` by the registry prefix) for the
        /hotspots/continuous merge."""
        out: List[str] = []
        for w in self.workers:
            if w.prof_lines:
                out.extend(ln for ln in w.prof_lines.splitlines() if ln)
        return out
