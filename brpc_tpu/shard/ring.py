"""Shared-memory SPSC byte ring — the shard plane's doorbell channel.

One producer process, one consumer process, no locks across the boundary
and no pipes/pickles: records are length-prefixed byte strings in a shm
segment, and the head/tail cursors are 8-byte-aligned u64 cells in the
segment header. On x86-64 an aligned 8-byte store is a single atomic
memcpy and the architecture is TSO (stores are not reordered past
stores), so "write payload, then publish tail" is a correct
release/acquire pair without fences — the same reasoning the kernel's
own shm rings rely on. Within one process, multiple logical producers
serialize on a plain threading.Lock held by the owner (the ring itself
stays single-producer).

Record layout, 8-byte aligned:

    u32 length | u8 type | 3 pad | payload | pad to 8

A record never wraps the segment end: when the tail-to-end gap is too
small, an 8-byte WRAP marker record (length=0xFFFFFFFF) fills the gap
and the record starts at offset 0. Because capacity and every record
size are multiples of 8, the gap is always >= 8 when nonzero, so the
marker always fits.

Consumers poll: the parent's collector and the worker's cut loop both
sit in AdaptiveSpin-then-sleep loops (fiber/wakeup.py) — measured on the
1-core CI box the escalating sleep floor keeps an idle 2-worker plane
under 1% CPU while a busy ring is picked up within the spin budget.
"""

from __future__ import annotations

import struct
from multiprocessing import shared_memory as _shm
from typing import List, Optional, Tuple

HDR_SIZE = 64          # u64 head @0 (consumer), u64 tail @8 (producer)
_REC_HDR = struct.Struct("<IB3x")   # length, type, pad -> 8 bytes
REC_OVERHEAD = _REC_HDR.size
_WRAP = 0xFFFFFFFF
_U64 = struct.Struct("<Q")

DEFAULT_RING_BYTES = 4 * 1024 * 1024


def _untrack(name: str) -> None:
    """Detach this process's resource_tracker claim on an attached segment
    so interpreter exit does not unlink shm another process still owns
    (same idiom as tpu/transport's pool attach)."""
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister("/" + name, "shared_memory")
    except Exception:
        pass


def _pad8(n: int) -> int:
    return (n + 7) & ~7


class ShardRing:
    """One direction of a parent<->worker doorbell pair."""

    def __init__(self, shm: _shm.SharedMemory, owner: bool):
        self._shm = shm
        self._owner = owner
        self.name = shm.name
        self.capacity = (len(shm.buf) - HDR_SIZE) & ~7
        self._buf = shm.buf
        # local cursor caches: the producer owns tail, the consumer owns
        # head — each re-reads only the cell the OTHER side publishes
        self._head_cache = self._load(0)
        self._tail_cache = self._load(8)
        # lifetime tallies (process-local, for /tpu + W_STATS)
        self.pushed = 0
        self.push_full = 0
        self.popped = 0

    # ------------------------------------------------------------ lifecycle
    @classmethod
    def create(cls, name: str, size: int = DEFAULT_RING_BYTES) -> "ShardRing":
        size = _pad8(max(size, 64 * 1024)) + HDR_SIZE
        shm = _shm.SharedMemory(name=name, create=True, size=size)
        shm.buf[:HDR_SIZE] = bytes(HDR_SIZE)
        return cls(shm, owner=True)

    @classmethod
    def attach(cls, name: str) -> "ShardRing":
        shm = _shm.SharedMemory(name=name)
        _untrack(name)
        return cls(shm, owner=False)

    def close(self) -> None:
        try:
            self._buf = None
            self._shm.close()
        except Exception:
            pass
        if self._owner:
            try:
                self._shm.unlink()
            except Exception:
                pass

    # ------------------------------------------------------------- cursors
    def _load(self, off: int) -> int:
        return _U64.unpack_from(self._shm.buf, off)[0]

    def _store(self, off: int, val: int) -> None:
        _U64.pack_into(self._shm.buf, off, val)

    # ------------------------------------------------------------ producer
    def push(self, rtype: int, payload: bytes) -> bool:
        """Append one record; False when the ring lacks space (caller
        falls back — in-process dispatch on the parent side, retry on the
        worker side). Never blocks."""
        buf = self._buf
        if buf is None:
            return False
        need = REC_OVERHEAD + _pad8(len(payload))
        if need > self.capacity:
            return False
        head = self._load(0)
        tail = self._tail_cache
        free = self.capacity - (tail - head)
        off = tail % self.capacity
        gap = self.capacity - off
        if need > gap:
            # wrap: burn the gap with a marker record, restart at 0
            if free < gap + need:
                self.push_full += 1
                return False
            _REC_HDR.pack_into(buf, HDR_SIZE + off, _WRAP, 0)
            tail += gap
            off = 0
        elif free < need:
            self.push_full += 1
            return False
        _REC_HDR.pack_into(buf, HDR_SIZE + off, len(payload), rtype)
        buf[HDR_SIZE + off + REC_OVERHEAD:
            HDR_SIZE + off + REC_OVERHEAD + len(payload)] = payload
        # publish AFTER the payload bytes land (x86 TSO store order)
        tail += need
        self._tail_cache = tail
        self._store(8, tail)
        self.pushed += 1
        return True

    def free_bytes(self) -> int:
        return self.capacity - (self._tail_cache - self._load(0))

    # ------------------------------------------------------------ consumer
    @property
    def empty(self) -> bool:
        return self._head_cache == self._load(8)

    def pop(self, max_records: int = 64) -> List[Tuple[int, bytes]]:
        """Drain up to max_records; returns [] when the ring is empty.
        Payload bytes are copied out (the slot is reusable the moment the
        head cursor publishes past it)."""
        buf = self._buf
        if buf is None:
            return []
        head = self._head_cache
        tail = self._load(8)
        out: List[Tuple[int, bytes]] = []
        while head < tail and len(out) < max_records:
            off = head % self.capacity
            ln, typ = _REC_HDR.unpack_from(buf, HDR_SIZE + off)
            if ln == _WRAP:
                head += self.capacity - off
                continue
            start = HDR_SIZE + off + REC_OVERHEAD
            out.append((typ, bytes(buf[start:start + ln])))
            head += REC_OVERHEAD + _pad8(ln)
        if out:
            self._head_cache = head
            self._store(0, head)
            self.popped += len(out)
        return out
