"""Credit sub-windows: leasing slices of a PeerWindow to worker processes.

The parent endpoint owns the tunnel's credit window (transport.PeerWindow —
the free-list over the CLIENT's registered block pool). Workers must be
able to post bulk responses into that pool without a parent round-trip per
response, but the credit machinery (acquire parks, FT_ACK releases, the
CreditLedger's balance checks) must stay single-owner. The lease protocol
splits the difference:

- the parent's ``LeaseManager`` acquires batches of block indices from the
  PeerWindow (bounded, non-parking: short timeout) and GRANTS them to a
  worker over its ring (``R_LEASE_GRANT``); the grant is just integers;
- the worker's ``SubWindow`` holds granted indices in a local free-list
  and takes them **all-or-nothing, never blocking** (``take_now``) — the
  worker's dispatch loop also services grants, so parking on one would
  self-deadlock;
- a posted response's credits flow home on the normal path: client parses,
  client FT_ACKs, parent ``on_ack`` releases into the PeerWindow. The
  LeaseManager only forgets them (``note_posted``);
- un-posted credits come back explicitly (``W_LEASE_RETURN`` →
  ``note_returned``) or wholesale when the worker dies
  (``reclaim_worker``), so the ledger balances at teardown no matter how
  the worker exits.

Epoch discipline: every grant carries the window generation it was cut
from. A re-handshake swaps the pool + epoch; stale grants are dropped by
the worker and stale returns by the parent — credits never cross epochs.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional, Set


class LeaseManager:
    """Parent-side bookkeeping of which worker holds which credits of one
    endpoint's PeerWindow. All methods are thread-safe (collector thread +
    shutdown path)."""

    def __init__(self, window, epoch: int):
        self.window = window
        self.epoch = epoch
        self._lock = threading.Lock()
        self._leased: Dict[int, Set[int]] = {}   # worker idx -> indices held
        self.grants = 0
        self.grant_misses = 0
        self.reclaims = 0

    def grant(self, widx: int, want: int,
              timeout: float = 0.05) -> Optional[List[int]]:
        """Acquire up to ``want`` credits for worker ``widx``. Bounded wait:
        the collector thread must not park a credit round-trip long — an
        empty window answers None and the worker keeps using its W_RESP
        fallback until credits free up."""
        if want <= 0:
            return None
        got = self.window.acquire(want, timeout=timeout)
        if not got:
            self.grant_misses += 1
            return None
        with self._lock:
            self._leased.setdefault(widx, set()).update(got)
        self.grants += 1
        return got

    def ungrant(self, widx: int, indices) -> None:
        """A grant that never reached the worker (ring full, worker died
        between grant and push): release straight back to the window."""
        indices = list(indices)
        with self._lock:
            held = self._leased.get(widx)
            if held is not None:
                held.difference_update(indices)
        self.window.release(indices)

    def note_posted(self, widx: int, indices) -> None:
        """Worker filled these blocks and the parent posted the segs frame:
        the credits are now in flight to the client and return through the
        normal FT_ACK -> on_ack -> window.release path."""
        with self._lock:
            held = self._leased.get(widx)
            if held is not None:
                held.difference_update(indices)

    def note_returned(self, widx: int, indices) -> None:
        """Worker handed unused credits back (idle shrink or reclaim)."""
        indices = list(indices)
        with self._lock:
            held = self._leased.get(widx)
            if held is None:
                fresh = indices
            else:
                fresh = [i for i in indices if i in held]
                held.difference_update(fresh)
        if fresh:
            self.window.release(fresh)

    def reclaim_worker(self, widx: int) -> int:
        """Worker death: every credit it still holds goes back to the
        window in one motion (its shm mapping died with it; the blocks
        themselves are parent/client-owned and unaffected)."""
        with self._lock:
            held = self._leased.pop(widx, None)
        if not held:
            return 0
        self.reclaims += 1
        self.window.release(sorted(held))
        return len(held)

    def release_all(self) -> int:
        """Plane shutdown: force-return every outstanding lease so the
        endpoint's orderly close finds the window whole."""
        with self._lock:
            all_held = [i for s in self._leased.values() for i in s]
            self._leased.clear()
        if all_held:
            self.window.release(sorted(all_held))
        return len(all_held)

    def leased_count(self, widx: int) -> int:
        with self._lock:
            held = self._leased.get(widx)
            return len(held) if held else 0

    def leased_counts(self) -> Dict[int, int]:
        with self._lock:
            return {w: len(s) for w, s in self._leased.items()}


class SubWindow:
    """Worker-side slice of the client's registered pool: the shm segment
    attached BY NAME plus a local free-list of leased block indices. No
    conditions, no parking — ``take_now`` either satisfies the whole ask
    from leased credits or answers None and the caller falls back to the
    inline W_RESP path."""

    def __init__(self, name: str, block_size: int, block_count: int,
                 epoch: int):
        from multiprocessing import shared_memory as _shm

        from brpc_tpu.shard.ring import _untrack

        self._shm = _shm.SharedMemory(name=name)
        _untrack(name)
        self.name = name
        self.block_size = block_size
        self.block_count = block_count
        self.epoch = epoch
        self._lock = threading.Lock()
        self._free: deque = deque()
        self.granted_total = 0
        self.taken_total = 0
        self.take_misses = 0

    def grant(self, indices, epoch: int) -> bool:
        """Accept a lease grant; stale-epoch grants are dropped (their
        indices were already reclaimed parent-side when the epoch turned)."""
        if epoch != self.epoch:
            return False
        with self._lock:
            self._free.extend(indices)
        self.granted_total += len(indices)
        return True

    def take_now(self, want: int) -> Optional[List[int]]:
        """All-or-nothing, non-blocking: a partial bulk response would
        strand a half-written packet, so either the whole ask is served
        from leased credits or the caller uses the W_RESP fallback."""
        with self._lock:
            if want <= 0 or len(self._free) < want:
                self.take_misses += 1
                return None
            got = [self._free.popleft() for _ in range(want)]
        self.taken_total += want
        return got

    def give_back(self, want: int) -> List[int]:
        """Surrender up to ``want`` free credits (R_LEASE_RECLAIM): the
        caller ships them home as W_LEASE_RETURN."""
        with self._lock:
            take = min(want, len(self._free))
            return [self._free.popleft() for _ in range(take)]

    def free_count(self) -> int:
        return len(self._free)

    def fill(self, idx: int, data, length: int) -> None:
        """memcpy ``length`` bytes into leased block ``idx`` — the single
        copy a sharded bulk response pays, landing directly in
        client-visible registered memory."""
        base = idx * self.block_size
        self._shm.buf[base:base + length] = data

    def close(self) -> None:
        try:
            self._shm.close()
        except Exception:
            pass
