"""Default service factory for shard workers.

A worker process builds its dispatch stack from a factory named as
``"module:attr"`` in the spawn config (``ServerOptions.shard_factory``).
The factory returns the list of Service instances to register — it runs
INSIDE the worker, so services are constructed per-process (no pickled
service objects cross the boundary; the cross-process-ownership lint rule
enforces the spirit of that for the whole package).

This module's ``echo_services`` is the default: the same trpc_std echo
the benchmarks and equivalence tests speak.
"""

from __future__ import annotations

from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import Service


class ShardEchoService(Service):
    DESCRIPTOR = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]

    def Echo(self, cntl, request, done):
        cntl.response_attachment = cntl.request_attachment
        return echo_pb2.EchoResponse(message=request.message,
                                     payload=request.payload)


def echo_services():
    return [ShardEchoService()]
