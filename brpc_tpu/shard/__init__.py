"""Sharded dispatch plane — the Python lane spread over N worker processes.

PR 9 (docs/small-message-fastpath.md) measured the single-core ceiling:
~323µs of irreducible Python CPU per call, all latency tricks applied. The
reference escapes this with bthread's M:N scheduler spreading work over
every core (PAPER.md, runtime layer); CPython cannot — one GIL per
process — and ``tools/subinterp_probe.py`` recorded the negative result
for same-process subinterpreter dispatch. So our idiomatic analog is OS
processes: a parent keeps owning the tunnel's control plane (handshake,
epochs, credit window, healer) while the CPU-heavy middle — TRPC frame
parse, method dispatch, response pack — runs in worker processes.

The contract that makes this cheap is **handles cross the process
boundary, never bytes that own anything**:

- workers map the SAME shm block pools the tunnel already registered,
  *by name* (the pool name went over the HELLO wire for exactly this
  reason) — a bulk response is memcpy'd once, by the worker, directly
  into client-visible registered memory;
- the parent leases each worker a **credit sub-window** — block indices
  acquired from its PeerWindow — so workers never talk to the credit
  machinery, and a dead worker's lease is reclaimed wholesale;
- requests/responses cross on shm SPSC byte rings as raw wire frames +
  integer handles (see wire.py); the ``cross-process-ownership`` tpulint
  rule enforces that no ``IOBuf``/``Block``/socket object is ever
  pickled across.

Responses fan back in through the parent's existing coalesced-doorbell
write (``TpuEndpoint.fan_in_flush``): one collector thread drains every
worker's ring and posts a poll batch of worker responses as ONE ctrl
write. Worker death rides the healer philosophy: a ``worker.crash``
fault point for chaos tests, parent-side respawn with a generation
bump, and every in-flight cid on the dead worker fanned a retriable
code exactly like tunnel death does.

``tpu_shard_workers=0`` (the default) is a strict no-op: no process is
spawned, no lane hook installed, the PR-9 fastpath runs unchanged.
"""

from __future__ import annotations

from brpc_tpu import fault as _fault

# chaos hook: SIGKILL worker <match_worker> (or any worker when unmatched)
# from the plane's monitor loop — the shard analog of tpu.tunnel.kill.
# Needs the fault_injection_enabled master gate like every fault point.
_fault.register(
    "worker.crash",
    "SIGKILL a shard dispatch worker from the plane monitor "
    "(match_worker=<index> targets one); exercises lease reclaim, "
    "retriable fan-out to in-flight cids, and generation-bump respawn")
