"""Fleet var aggregation — worker metrics merged into the parent's /vars.

Since PR 11 the Python lane runs in N worker processes, each with its own
metrics registry: the parent's /vars told only the parent's share of the
truth. This module closes that gap over the existing stats lane:

- **worker side** — :func:`worker_snapshot` walks the worker's exposed
  numeric variables into a flat JSON blob ``{name: [op, ptype, value]}``,
  shipped as a ``W_VARS`` record once per ``shard_vars_interval_s``. The
  merge op is derived from what the variable *is* (Adder counters sum,
  ``*_max*`` maxes, window averages weight by qps), so the parent never
  guesses.
- **parent side** — :class:`FleetVars` keeps the latest snapshot per worker
  and exposes two var families: namespaced ``worker<i>_<name>`` mirrors
  (opted out of series retention — high-cardinality by construction) and
  op-correct ``fleet_<name>`` aggregates merged across workers only, so
  ``fleet_x == sum(worker<i>_x)`` holds exactly for Adder-backed counters.
  Fleet vars carry a Prometheus ``# HELP`` naming the merge, and
  ``fleet_shard_workers`` says how many workers the aggregate covers.

The merge semantics themselves (op derivation, op arithmetic, the snapshot
walk) live in :mod:`brpc_tpu.fleet.merge` since the fleet observer merges
the same way across *servers*; this module keeps the parent-side store and
the historical names.

Payloads are UTF-8 JSON of flat scalars — flat bytes over the ring, no
pickle, same as W_STATS.
"""

from __future__ import annotations

import threading
from typing import Dict, List

from brpc_tpu.fleet.merge import (  # noqa: F401  (re-exported names)
    OP_AVG,
    OP_MAX,
    OP_MIN,
    OP_SUM,
    OP_WAVG_QPS,
    MergedVar as _FleetVar,
    merge_op as _merge_op,
    merge_values,
    qps_weight_name,
    worker_snapshot,
)
from brpc_tpu.metrics.status import PassiveStatus


class FleetVars:
    """Parent-side store + /vars exposure of worker snapshots."""

    def __init__(self):
        self._lock = threading.Lock()
        # worker index -> {name: (op, ptype, value)}
        self._snaps: Dict[int, Dict[str, tuple]] = {}
        self._vars: Dict[str, PassiveStatus] = {}
        self._count_var = _FleetVar(
            lambda: len(self._snaps), "gauge",
            "shard workers currently reporting W_VARS snapshots")
        self._count_var.expose("fleet_shard_workers")

    # ------------------------------------------------------------ ingest
    def on_snapshot(self, index: int, payload: bytes) -> None:
        try:
            import json
            doc = json.loads(payload.decode())
            snap = {str(name): (str(rec[0]), str(rec[1]), rec[2])
                    for name, rec in doc["vars"].items()
                    if isinstance(rec, list) and len(rec) == 3
                    and isinstance(rec[2], (int, float))}
        except Exception:
            return
        with self._lock:
            self._snaps[index] = snap
        self._ensure_exposed(index, snap)

    def _ensure_exposed(self, index: int, snap: Dict[str, tuple]) -> None:
        for name, (op, ptype, _value) in snap.items():
            wname = f"worker{index}_{name}"
            if wname not in self._vars:
                self._vars[wname] = _FleetVar(
                    self._worker_reader(index, name), ptype,
                    opt_out=True).expose(wname)
            fname = f"fleet_{name}"
            if fname not in self._vars:
                self._vars[fname] = _FleetVar(
                    self._fleet_reader(name), ptype,
                    help_text=f"{op} of {name} over reporting shard "
                              f"workers (W_VARS merge)").expose(fname)

    # ------------------------------------------------------------ readers
    def _worker_reader(self, index: int, name: str):
        def read():
            with self._lock:
                rec = self._snaps.get(index, {}).get(name)
            return rec[2] if rec is not None else 0
        return read

    def _fleet_reader(self, name: str):
        def read():
            with self._lock:
                recs = [(i, s[name]) for i, s in self._snaps.items()
                        if name in s]
                if not recs:
                    return 0
                op = recs[0][1][0]
                values = [rec[2] for _, rec in recs]
                if op == OP_WAVG_QPS:
                    wname = qps_weight_name(name)
                    weights = [self._snaps[i].get(wname, (0, 0, 0))[2]
                               for i, _ in recs]
                else:
                    weights = None
            return merge_values(op, values, weights)
        return read

    # ------------------------------------------------------------- views
    def workers_reporting(self) -> int:
        with self._lock:
            return len(self._snaps)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._vars)

    def hide_all(self) -> None:
        self._count_var.hide()
        for var in self._vars.values():
            var.hide()
        self._vars.clear()
        with self._lock:
            self._snaps.clear()
