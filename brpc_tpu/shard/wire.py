"""Ring record codecs + the minimal RpcMeta scanner the router needs.

Everything that crosses a shard ring is flat bytes: struct-packed integer
handles (endpoint ids, epochs, block indices, byte lengths) plus raw wire
frames. There is deliberately no pickle anywhere in this package — the
``cross-process-ownership`` tpulint rule pins that invariant.

The scanner is a top-level protobuf varint walk over an RpcMeta blob: the
parent must route by correlation id BEFORE parsing (parsing is exactly
the CPU the workers exist to absorb), so it reads just the four facts
routing needs — request-ness, cid, attempt_version, stream-ness — from
the ~30-byte meta without materializing a message object. Field numbers
from brpc_tpu/proto/rpc_meta.proto: request=1, correlation_id=3,
attempt_version=4, stream_settings=8.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Tuple

# parent -> worker
R_ATTACH = 1         # !II ep_id epoch + json {pool, bs, bc, remote}
R_DETACH = 2         # !I  ep_id
R_MSG = 3            # !I  ep_id + raw TRPC frame bytes
R_LEASE_GRANT = 4    # !IIH ep_id epoch n + !{n}I block indices
R_LEASE_RECLAIM = 5  # !II ep_id want
R_QUIT = 6           # (empty)

# worker -> parent
W_READY = 32         # !I pid
W_RESP = 33          # !IQ ep_id cid + whole response packet bytes
W_RESP_SEGS = 34     # !IIQH ep_id epoch cid nsegs + (!II idx len)*n
W_LEASE_RETURN = 35  # !IIH ep_id epoch n + !{n}I block indices
W_LEASE_REQUEST = 36 # !II ep_id want
W_STATS = 37         # utf-8 json
W_PROF = 38          # utf-8 folded stack lines
W_RESP_SHM = 39      # !IQQ ep_id cid total + utf-8 spill segment name
W_VARS = 40          # utf-8 json windowed var snapshot (shard/fleet.py)

_II = struct.Struct("!II")
_I = struct.Struct("!I")
_IIH = struct.Struct("!IIH")
_IQ = struct.Struct("!IQ")
_IIQH = struct.Struct("!IIQH")


def encode_msg(ep_id: int, frame: bytes) -> bytes:
    return _I.pack(ep_id) + frame


def decode_msg(b: bytes) -> Tuple[int, bytes]:
    return _I.unpack_from(b)[0], b[_I.size:]


def encode_indices(ep_id: int, epoch: int, indices) -> bytes:
    indices = list(indices)
    return (_IIH.pack(ep_id, epoch, len(indices))
            + struct.pack(f"!{len(indices)}I", *indices))


def decode_indices(b: bytes) -> Tuple[int, int, List[int]]:
    ep_id, epoch, n = _IIH.unpack_from(b)
    return ep_id, epoch, list(struct.unpack_from(f"!{n}I", b, _IIH.size))


def encode_want(ep_id: int, want: int) -> bytes:
    return _II.pack(ep_id, want)


def decode_want(b: bytes) -> Tuple[int, int]:
    return _II.unpack(b[:_II.size])


def encode_resp(ep_id: int, cid: int, packet: bytes) -> bytes:
    return _IQ.pack(ep_id, cid) + packet


def decode_resp(b: bytes) -> Tuple[int, int, bytes]:
    ep_id, cid = _IQ.unpack_from(b)
    return ep_id, cid, b[_IQ.size:]


def encode_resp_segs(ep_id: int, epoch: int, cid: int, segs) -> bytes:
    segs = list(segs)
    out = _IIQH.pack(ep_id, epoch, cid, len(segs))
    return out + b"".join(_II.pack(i, ln) for i, ln in segs)


def decode_resp_segs(b: bytes) -> Tuple[int, int, int, List[Tuple[int, int]]]:
    ep_id, epoch, cid, n = _IIQH.unpack_from(b)
    off = _IIQH.size
    segs = [_II.unpack_from(b, off + k * _II.size) for k in range(n)]
    return ep_id, epoch, cid, segs


# ----------------------------------------------------------------- scanner
def _uvarint(b: bytes, i: int) -> Tuple[int, int]:
    val = 0
    shift = 0
    while True:
        byte = b[i]
        i += 1
        val |= (byte & 0x7F) << shift
        if not byte & 0x80:
            return val, i
        shift += 7
        if shift > 63:
            raise ValueError("varint overflow")


def scan_request_meta(mb) -> Optional[Tuple[bool, int, int, bool]]:
    """(has_request, correlation_id, attempt_version, has_stream) from a
    serialized RpcMeta, or None when the blob does not walk cleanly (the
    in-process parser then owns it — the shard lane only skims)."""
    i, n = 0, len(mb)
    has_req = False
    cid = 0
    attempt = 0
    has_stream = False
    try:
        while i < n:
            key, i = _uvarint(mb, i)
            field, wt = key >> 3, key & 7
            if wt == 0:
                v, i = _uvarint(mb, i)
                if field == 3:
                    cid = v
                elif field == 4:
                    attempt = v
            elif wt == 2:
                ln, i = _uvarint(mb, i)
                if field == 1:
                    has_req = True
                elif field == 8:
                    has_stream = True
                i += ln
            elif wt == 5:
                i += 4
            elif wt == 1:
                i += 8
            else:
                return None
        if i != n:
            return None
    except (IndexError, ValueError):
        return None
    return has_req, cid, attempt, has_stream


def response_cid(header_and_meta: bytes, meta_size: int) -> int:
    """correlation_id scanned out of a response packet's own meta (the
    worker packs responses, so it holds header+meta contiguously)."""
    info = scan_request_meta(header_and_meta[12:12 + meta_size])
    return info[1] if info else 0
