"""json2pb — bidirectional JSON <-> protobuf bridge with conversion options.

Counterpart of the reference's ``src/json2pb`` (``pb_to_json.cpp`` /
``json_to_pb.cpp`` and their Pb2JsonOptions / Json2PbOptions): the HTTP
protocol family serves protobuf services to JSON clients, and proxies need
control over the conversion rules, not a fixed mapping. This is an
options-driven descriptor walker of our own:

  - maps (string/int/bool keys), nested + repeated messages, oneof
  - enums by name or number (``enum_as_name``), unknown enum tolerance
  - bytes as base64 (or latin-1 passthrough when ``bytes_to_base64=False``
    — the reference's raw-bytes escape hatch)
  - 64-bit ints as JSON strings (``int64_as_string``) for JS safety
  - NaN/Infinity round-tripping for float/double
  - ``always_print_primitive_fields`` / ``jsonify_empty_array`` dump shaping
  - unknown-field tolerance on parse (``ignore_unknown_fields``),
    camelCase json_name acceptance

Limitation: well-known types (google.protobuf.Timestamp/Duration/Struct/
wrappers) are treated as plain messages, not their proto3 JSON special
forms — none of this framework's schemas use them; add handling before
introducing one.

The old two-function surface (json_to_pb / pb_to_json) is kept for the
HTTP family; options objects are additive.
"""

from __future__ import annotations

import base64
import json
import math
from dataclasses import dataclass
from typing import Optional, Type

from google.protobuf import descriptor as _desc

_FD = _desc.FieldDescriptor

_INT_TYPES = {
    _FD.CPPTYPE_INT32, _FD.CPPTYPE_INT64,
    _FD.CPPTYPE_UINT32, _FD.CPPTYPE_UINT64,
}
_WIDE_TYPES = {_FD.CPPTYPE_INT64, _FD.CPPTYPE_UINT64}
_FLOAT_TYPES = {_FD.CPPTYPE_FLOAT, _FD.CPPTYPE_DOUBLE}


class Json2PbError(ValueError):
    pass


@dataclass
class Pb2JsonOptions:
    """reference pb_to_json.h Pb2JsonOptions (subset, renamed pythonic)."""

    enum_as_name: bool = True
    bytes_to_base64: bool = True
    int64_as_string: bool = True
    jsonify_empty_array: bool = False
    always_print_primitive_fields: bool = False
    pretty: bool = False


@dataclass
class Json2PbOptions:
    """reference json_to_pb.h Json2PbOptions (subset)."""

    base64_to_bytes: bool = True
    ignore_unknown_fields: bool = True
    allow_unknown_enum: bool = False  # drop unknown enum names vs error


# ------------------------------------------------------------------ pb->json
def _value_to_json(field, value, opts: Pb2JsonOptions):
    cpp = field.cpp_type
    if cpp == _FD.CPPTYPE_MESSAGE:
        return _message_to_dict(value, opts)
    if cpp == _FD.CPPTYPE_ENUM:
        if opts.enum_as_name:
            ev = field.enum_type.values_by_number.get(value)
            return ev.name if ev is not None else value
        return value
    if cpp == _FD.CPPTYPE_BOOL:
        return bool(value)
    if cpp in _FLOAT_TYPES:
        if math.isnan(value):
            return "NaN"
        if math.isinf(value):
            return "Infinity" if value > 0 else "-Infinity"
        return value
    if cpp in _WIDE_TYPES and opts.int64_as_string:
        return str(value)
    if cpp == _FD.CPPTYPE_STRING:
        if field.type == _FD.TYPE_BYTES:
            if opts.bytes_to_base64:
                return base64.b64encode(value).decode("ascii")
            return value.decode("latin-1")
        return value
    return value


def _repeated(field) -> bool:
    # protobuf >=5.30 exposes is_repeated as an attribute; older versions
    # only have .label (deprecated but functional)
    rep = getattr(field, "is_repeated", None)
    if isinstance(rep, bool):
        return rep
    return field.label == _FD.LABEL_REPEATED


def _is_map_field(field) -> bool:
    return (_repeated(field)
            and field.cpp_type == _FD.CPPTYPE_MESSAGE
            and field.message_type.GetOptions().map_entry)


def _message_to_dict(msg, opts: Pb2JsonOptions) -> dict:
    out = {}
    for field in msg.DESCRIPTOR.fields:
        name = field.name
        if _is_map_field(field):
            mapping = getattr(msg, name)
            if not mapping and not opts.jsonify_empty_array:
                continue
            vfield = field.message_type.fields_by_name["value"]
            out[name] = {str(k).lower() if isinstance(k, bool) else str(k):
                         _value_to_json(vfield, v, opts)
                         for k, v in sorted(mapping.items(),
                                            key=lambda kv: str(kv[0]))}
            continue
        if _repeated(field):
            items = getattr(msg, name)
            if not items and not opts.jsonify_empty_array:
                continue
            out[name] = [_value_to_json(field, v, opts) for v in items]
            continue
        if field.cpp_type == _FD.CPPTYPE_MESSAGE:
            if msg.HasField(name):
                out[name] = _message_to_dict(getattr(msg, name), opts)
            continue
        if field.containing_oneof is not None \
                or getattr(field, "has_presence", False):
            # explicit presence (oneof member, proto3 `optional` via its
            # synthetic oneof, proto2 optional scalar): emission follows
            # the has-bit, so a field explicitly set to its default
            # survives the round trip (reference pb_to_json.cpp checks
            # has-bits, not values)
            if msg.HasField(name):
                out[name] = _value_to_json(field, getattr(msg, name), opts)
            continue
        value = getattr(msg, name)
        if value == field.default_value \
                and not opts.always_print_primitive_fields:
            continue
        out[name] = _value_to_json(field, value, opts)
    return out


def pb_to_json(message, pretty: bool = False,
               always_print_fields_with_no_presence: bool = False,
               options: Optional[Pb2JsonOptions] = None) -> str:
    opts = options or Pb2JsonOptions(
        pretty=pretty,
        always_print_primitive_fields=always_print_fields_with_no_presence)
    try:
        d = _message_to_dict(message, opts)
        return json.dumps(d, indent=2 if opts.pretty else None,
                          sort_keys=False)
    except Json2PbError:
        raise
    except Exception as e:
        raise Json2PbError(str(e)) from None


# ------------------------------------------------------------------ json->pb
def _json_to_value(field, value, opts: Json2PbOptions, where: str):
    cpp = field.cpp_type
    if cpp == _FD.CPPTYPE_ENUM:
        if isinstance(value, str):
            ev = field.enum_type.values_by_name.get(value)
            if ev is None:
                if opts.allow_unknown_enum:
                    return None
                raise Json2PbError(f"{where}: unknown enum name {value!r}")
            return ev.number
        if isinstance(value, bool) or not isinstance(value, int):
            raise Json2PbError(f"{where}: bad enum value {value!r}")
        return value
    if cpp == _FD.CPPTYPE_BOOL:
        if isinstance(value, bool):
            return value
        raise Json2PbError(f"{where}: expected bool, got {value!r}")
    if cpp in _INT_TYPES:
        if isinstance(value, bool) or not isinstance(value, (int, str)):
            raise Json2PbError(f"{where}: expected int, got {value!r}")
        try:
            return int(value)
        except ValueError:
            raise Json2PbError(f"{where}: bad int {value!r}") from None
    if cpp in _FLOAT_TYPES:
        if isinstance(value, str):
            if value == "NaN":
                return math.nan
            if value == "Infinity":
                return math.inf
            if value == "-Infinity":
                return -math.inf
            try:
                return float(value)
            except ValueError:
                raise Json2PbError(f"{where}: bad float {value!r}") from None
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            return float(value)
        raise Json2PbError(f"{where}: expected number, got {value!r}")
    if cpp == _FD.CPPTYPE_STRING:
        if field.type == _FD.TYPE_BYTES:
            if not isinstance(value, str):
                raise Json2PbError(f"{where}: expected string for bytes")
            if opts.base64_to_bytes:
                try:
                    return base64.b64decode(value, validate=True)
                except Exception:
                    raise Json2PbError(
                        f"{where}: invalid base64") from None
            return value.encode("latin-1")
        if not isinstance(value, str):
            raise Json2PbError(f"{where}: expected string, got {value!r}")
        return value
    raise Json2PbError(f"{where}: unhandled field type")


def _map_key_from_json(kfield, key: str, where: str):
    cpp = kfield.cpp_type
    if cpp == _FD.CPPTYPE_BOOL:
        if key in ("true", "false"):
            return key == "true"
        raise Json2PbError(f"{where}: bad bool map key {key!r}")
    if cpp in _INT_TYPES:
        try:
            return int(key)
        except ValueError:
            raise Json2PbError(f"{where}: bad int map key {key!r}") from None
    return key


def _dict_to_message(d: dict, msg, opts: Json2PbOptions, where: str) -> None:
    if not isinstance(d, dict):
        raise Json2PbError(f"{where}: expected object, got {d!r}")
    fields = msg.DESCRIPTOR.fields_by_name
    for key, value in d.items():
        field = fields.get(key)
        if field is None:
            # also accept camelCase against snake_case schemas
            field = next((f for f in msg.DESCRIPTOR.fields
                          if f.json_name == key), None)
        if field is None:
            if opts.ignore_unknown_fields:
                continue
            raise Json2PbError(f"{where}: unknown field {key!r}")
        fwhere = f"{where}.{field.name}"
        if value is None:
            continue  # JSON null = leave default (proto3 json mapping)
        if _is_map_field(field):
            if not isinstance(value, dict):
                raise Json2PbError(f"{fwhere}: expected object for map")
            kfield = field.message_type.fields_by_name["key"]
            vfield = field.message_type.fields_by_name["value"]
            target = getattr(msg, field.name)
            for k, v in value.items():
                pk = _map_key_from_json(kfield, k, fwhere)
                if vfield.cpp_type == _FD.CPPTYPE_MESSAGE:
                    _dict_to_message(v, target[pk], opts, f"{fwhere}[{k}]")
                else:
                    converted = _json_to_value(vfield, v, opts,
                                               f"{fwhere}[{k}]")
                    if converted is not None:
                        target[pk] = converted
            continue
        if _repeated(field):
            if not isinstance(value, list):
                raise Json2PbError(f"{fwhere}: expected array")
            target = getattr(msg, field.name)
            for i, item in enumerate(value):
                iw = f"{fwhere}[{i}]"
                if field.cpp_type == _FD.CPPTYPE_MESSAGE:
                    _dict_to_message(item, target.add(), opts, iw)
                else:
                    converted = _json_to_value(field, item, opts, iw)
                    if converted is not None:
                        target.append(converted)
            continue
        if field.cpp_type == _FD.CPPTYPE_MESSAGE:
            _dict_to_message(value, getattr(msg, field.name), opts, fwhere)
            continue
        converted = _json_to_value(field, value, opts, fwhere)
        if converted is not None:
            setattr(msg, field.name, converted)


def json_to_pb(data, message_class: Type,
               ignore_unknown_fields: bool = True,
               options: Optional[Json2PbOptions] = None):
    """Parse a JSON document (str/bytes) into a new message instance."""
    opts = options or Json2PbOptions(
        ignore_unknown_fields=ignore_unknown_fields)
    if isinstance(data, (bytes, bytearray, memoryview)):
        try:
            data = bytes(data).decode("utf-8")
        except UnicodeDecodeError as e:
            raise Json2PbError(str(e)) from None
    msg = message_class()
    if data.strip() == "":
        return msg  # empty body = default message (GET-style calls)
    try:
        parsed = json.loads(data)
    except json.JSONDecodeError as e:
        raise Json2PbError(str(e)) from None
    try:
        _dict_to_message(parsed, msg, opts, message_class.DESCRIPTOR.name)
    except Json2PbError:
        raise
    except ValueError as e:
        # protobuf setattr range checks (int32 overflow, negative uint...)
        raise Json2PbError(str(e)) from None
    return msg
