"""json2pb — JSON <-> protobuf bridging for the HTTP protocol family.

Counterpart of the reference's ``src/json2pb`` (``pb_to_json.cpp`` /
``json_to_pb.cpp``): the HTTP protocol serves protobuf services to JSON
clients by converting request bodies to messages and responses back. We
build on ``google.protobuf.json_format`` rather than a hand-rolled walker —
the conversion rules (int64 as string, bytes as base64, enums by name) match
proto3 JSON mapping, which is what the reference's grpc/http gateway peers
expect.
"""

from __future__ import annotations

from typing import Optional, Type

from google.protobuf import json_format


class Json2PbError(ValueError):
    pass


def json_to_pb(data, message_class: Type, ignore_unknown_fields: bool = True):
    """Parse a JSON document (str/bytes) into a new message instance."""
    if isinstance(data, (bytes, bytearray, memoryview)):
        data = bytes(data).decode("utf-8", errors="strict")
    msg = message_class()
    if data.strip() == "":
        return msg  # empty body = default message (GET-style calls)
    try:
        json_format.Parse(data, msg,
                          ignore_unknown_fields=ignore_unknown_fields)
    except (json_format.ParseError, UnicodeDecodeError) as e:
        raise Json2PbError(str(e)) from None
    return msg


def pb_to_json(message, pretty: bool = False,
               always_print_fields_with_no_presence: bool = False) -> str:
    try:
        return json_format.MessageToJson(
            message,
            indent=2 if pretty else None,
            preserving_proto_field_name=True,
            always_print_fields_with_no_presence=(
                always_print_fields_with_no_presence),
        )
    except Exception as e:
        raise Json2PbError(str(e)) from None
