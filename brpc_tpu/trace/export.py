"""Span export to OTLP JSON lines — the bridge from /rpcz to external
tracing backends.

When the reloadable ``span_export_path`` flag names a file, every finished
span appends ONE line to it: a complete OTLP ``ExportTraceServiceRequest``
JSON object (resourceSpans -> scopeSpans -> spans), so each line is
independently ingestible by an OTLP file receiver / collector — and by
``jq`` — without framing state. Phase marks become ``phase.<name>``
double attributes, structured events become OTLP span events, and the
trace/span ids are the same ids /rpcz shows (trace ids zero-padded to the
OTLP 128-bit width).

The hook is :func:`maybe_export`, called from ``Span.end``; with the flag
empty it is one dict lookup and a falsy check, so the tracing hot path
pays nothing when export is off.

No clock reads here: timestamps derive from the span's already-captured
wall-clock start and monotonic latency.
"""

from __future__ import annotations

import json
import threading
from typing import Any, Dict

from brpc_tpu import flags as _flags
from brpc_tpu.metrics.reducer import Adder

g_spans_exported = Adder("g_spans_exported")
g_span_export_errors = Adder("g_span_export_errors")

# OTLP SpanKind enum values (trace.proto): SERVER=2, CLIENT=3
_OTLP_KIND = {"server": 2, "client": 3}

_lock = threading.Lock()
_file = None
_file_path = None


def _attr(key: str, value) -> Dict[str, Any]:
    if isinstance(value, bool):
        return {"key": key, "value": {"boolValue": value}}
    if isinstance(value, int):
        return {"key": key, "value": {"intValue": str(value)}}
    if isinstance(value, float):
        return {"key": key, "value": {"doubleValue": value}}
    return {"key": key, "value": {"stringValue": str(value)}}


def span_to_otlp(span) -> Dict[str, Any]:
    """One span as an OTLP Span JSON object."""
    start_ns = int(span.start_us * 1000.0)
    # derive the end from the integer start so the span width survives
    # float64 rounding at epoch-nanosecond magnitudes
    end_ns = start_ns + int(round(span.latency_us * 1000.0))
    attrs = [
        _attr("rpc.service", span.service),
        _attr("rpc.method", span.method),
        _attr("rpc.request_size", int(span.request_size)),
        _attr("rpc.response_size", int(span.response_size)),
    ]
    if span.peer:
        attrs.append(_attr("net.peer", span.peer))
    for name, us in sorted(span.phases.items()):
        attrs.append(_attr(f"phase.{name}", float(us)))
    events = []
    for off_us, name, fields in span.events:
        events.append({
            "timeUnixNano": str(int((span.start_us + off_us) * 1000.0)),
            "name": name,
            "attributes": [_attr(k, v) for k, v in fields.items()],
        })
    for off_us, text in span.annotations:
        events.append({
            "timeUnixNano": str(int((span.start_us + off_us) * 1000.0)),
            "name": "annotation",
            "attributes": [_attr("text", text)],
        })
    out: Dict[str, Any] = {
        "traceId": f"{span.trace_id:032x}",
        "spanId": f"{span.span_id:016x}",
        "name": f"{span.service}.{span.method}",
        "kind": _OTLP_KIND.get(span.kind, 0),
        "startTimeUnixNano": str(start_ns),
        "endTimeUnixNano": str(end_ns),
        "attributes": attrs,
        "status": ({"code": 2, "message": f"error_code={span.error_code}"}
                   if span.error_code else {"code": 1}),
    }
    if span.parent_span_id:
        out["parentSpanId"] = f"{span.parent_span_id:016x}"
    if events:
        out["events"] = events
    return out


def envelope(otlp_span: Dict[str, Any],
             service_name: str = "brpc_tpu") -> Dict[str, Any]:
    """Wrap one OTLP span in a full ExportTraceServiceRequest."""
    return {"resourceSpans": [{
        "resource": {"attributes": [_attr("service.name", service_name)]},
        "scopeSpans": [{"scope": {"name": "brpc_tpu.trace"},
                        "spans": [otlp_span]}],
    }]}


def _writer(path: str):
    global _file, _file_path
    if path != _file_path:
        if _file is not None:
            try:
                _file.close()
            except OSError:
                pass
        _file = open(path, "a", encoding="utf-8")
        _file_path = path
    return _file


def maybe_export(span) -> bool:
    """Append ``span`` to the file named by ``span_export_path``; no-op
    (False) when the flag is empty. Never raises — export failures count
    on g_span_export_errors and the RPC path moves on."""
    path = _flags.get("span_export_path")
    if not path:
        return False
    try:
        line = json.dumps(envelope(span_to_otlp(span)),
                          separators=(",", ":"))
        with _lock:
            f = _writer(path)
            f.write(line + "\n")
            f.flush()
    except (OSError, ValueError, TypeError):
        g_span_export_errors.put(1)
        return False
    g_spans_exported.put(1)
    return True


def reset_for_test() -> None:
    global _file, _file_path
    with _lock:
        if _file is not None:
            try:
                _file.close()
            except OSError:
                pass
        _file = None
        _file_path = None
