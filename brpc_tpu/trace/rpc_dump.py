"""rpc_dump — rate-limited request sampling to replayable files.

Rebuild of the reference's ``rpc_dump.h:30-57`` (AskToBeSampled hooked into
ProcessRpcRequest) + the dump format consumed by ``tools/rpc_replay``. A
sampled request is serialized as one length-prefixed record::

    u32 meta_size | u32 body_size | RpcMeta pb | body bytes

so a dump file is just a trpc_std byte stream minus the magic — replay can
re-pack each record through any protocol.
"""

from __future__ import annotations

import os
import random
import struct
import threading
import time
from typing import Iterator, Optional, Tuple

from brpc_tpu import flags as _flags
from brpc_tpu.proto import rpc_meta_pb2

_REC_FMT = "!II"
_REC_SIZE = struct.calcsize(_REC_FMT)

MAX_FILE_BYTES = 64 << 20


class RpcDumper:
    """Per-server sampler writing to <dir>/requests.<n>.dump files."""

    def __init__(self, directory: str, max_file_bytes: int = MAX_FILE_BYTES):
        self.directory = directory
        self.max_file_bytes = max_file_bytes
        self._lock = threading.Lock()
        self._file = None
        self._file_bytes = 0
        self._file_index = 0
        self.sampled_count = 0
        os.makedirs(directory, exist_ok=True)

    def ask_to_be_sampled(self) -> bool:
        ratio = _flags.get("rpc_dump_ratio")
        if ratio <= 0.0:
            return False
        if ratio < 1.0 and random.random() >= ratio:
            return False
        # ratio selects; the shared Collector budget caps (reference
        # rpc_dump.h:46-57 speed-limit via bvar Collector)
        from brpc_tpu.metrics.collector import global_collector

        return global_collector().ask_to_be_sampled()

    def sample(self, meta: rpc_meta_pb2.RpcMeta, body: bytes) -> None:
        record = pack_record(meta, body)
        with self._lock:
            if self._file is None or self._file_bytes > self.max_file_bytes:
                self._roll()
            self._file.write(record)
            self._file.flush()
            self._file_bytes += len(record)
            self.sampled_count += 1

    def _roll(self) -> None:
        if self._file is not None:
            self._file.close()
        path = os.path.join(self.directory,
                            f"requests.{self._file_index}.dump")
        self._file_index += 1
        self._file = open(path, "wb")
        self._file_bytes = 0

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None


def pack_record(meta: rpc_meta_pb2.RpcMeta, body: bytes) -> bytes:
    meta_bytes = meta.SerializeToString()
    return (struct.pack(_REC_FMT, len(meta_bytes), len(body))
            + meta_bytes + body)


class RpcDumpLoader:
    """Iterate records of one dump file (or a directory of them)."""

    def __init__(self, path: str):
        self.paths = []
        if os.path.isdir(path):
            self.paths = sorted(
                os.path.join(path, f) for f in os.listdir(path)
                if f.endswith(".dump"))
        else:
            self.paths = [path]

    def __iter__(self) -> Iterator[Tuple[rpc_meta_pb2.RpcMeta, bytes]]:
        for p in self.paths:
            with open(p, "rb") as f:
                data = f.read()
            pos = 0
            while pos + _REC_SIZE <= len(data):
                meta_size, body_size = struct.unpack_from(_REC_FMT, data, pos)
                pos += _REC_SIZE
                if pos + meta_size + body_size > len(data):
                    break  # truncated tail record
                meta = rpc_meta_pb2.RpcMeta.FromString(
                    data[pos:pos + meta_size])
                pos += meta_size
                body = data[pos:pos + body_size]
                pos += body_size
                yield meta, body
