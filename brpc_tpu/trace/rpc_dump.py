"""rpc_dump — rate-limited request sampling to replayable files.

Rebuild of the reference's ``rpc_dump.h:30-57`` (AskToBeSampled hooked into
ProcessRpcRequest) + the dump format consumed by ``tools/rpc_replay``.

Two on-disk formats:

v1 (legacy, headerless) — one length-prefixed record per sample::

    u32 meta_size | u32 body_size | RpcMeta pb | body bytes

v2 — the file opens with the magic ``RPCDUMP2\\n``; each record carries an
extra JSON blob ahead of the raw wire bytes::

    u32 extra_size | u32 meta_size | u32 body_size | extra json | meta | body

The extra blob stamps what replay and diffing need and the RpcMeta alone
can't say: the arrival wall-clock timestamp (inter-arrival gaps for
open-loop replay), trace/span ids as hex, service.method, the deadline
budget and priority, and — because a record is committed when the request
*settles*, not when it arrives — the server span's complete phase timeline
plus final latency and error code. ``RpcDumpLoader`` sniffs the header per
file and yields :class:`DumpRecord` objects that still unpack as
``(meta, body)`` tuples, so v1-era consumers read both formats unchanged.

Clocks: interval/rate accounting (the token bucket) runs on the monotonic
clock like everything in ``trace/``; the wall clock appears only inside
the on-disk record timestamp.
"""

from __future__ import annotations

import json
import os
import random
import struct
import threading
import time
from typing import Any, Dict, Iterator, Optional

from brpc_tpu import flags as _flags
from brpc_tpu.metrics.reducer import Adder
from brpc_tpu.proto import rpc_meta_pb2

_REC_FMT = "!II"
_REC_SIZE = struct.calcsize(_REC_FMT)
_REC2_FMT = "!III"
_REC2_SIZE = struct.calcsize(_REC2_FMT)

MAGIC_V2 = b"RPCDUMP2\n"

MAX_FILE_BYTES = 64 << 20

g_dump_sampled = Adder("g_dump_sampled")      # records committed to disk
g_dump_skipped = Adder("g_dump_skipped")      # ratio-selected but shed
g_dump_bytes = Adder("g_dump_bytes")          # record bytes written
g_dump_rotations = Adder("g_dump_rotations")  # file rolls past the first
g_dump_errors = Adder("g_dump_errors")        # write failures (disk full)


class RpcDumper:
    """Per-server sampler writing to <dir>/requests.<n>.dump files."""

    def __init__(self, directory: str, max_file_bytes: int = MAX_FILE_BYTES):
        self.directory = directory
        self.max_file_bytes = max_file_bytes
        self._lock = threading.Lock()
        self._file = None
        self._file_bytes = 0
        self._file_index = 0
        self.sampled_count = 0
        self.per_method: Dict[str, int] = {}
        # token bucket for rpc_dump_max_per_sec (monotonic clock); starts
        # with one token so a fresh dumper can always take its first sample
        self._tokens = 1.0
        self._tokens_t = time.monotonic()
        os.makedirs(directory, exist_ok=True)

    def ask_to_be_sampled(self) -> bool:
        ratio = _flags.get("rpc_dump_ratio")
        if ratio <= 0.0:
            return False
        if ratio < 1.0 and random.random() >= ratio:
            return False
        if not self._take_token():
            g_dump_skipped.put(1)
            return False
        # ratio selects; the shared Collector budget caps (reference
        # rpc_dump.h:46-57 speed-limit via bvar Collector)
        from brpc_tpu.metrics.collector import global_collector

        if not global_collector().ask_to_be_sampled():
            g_dump_skipped.put(1)
            return False
        return True

    def _take_token(self) -> bool:
        cap = _flags.get("rpc_dump_max_per_sec")
        if cap <= 0:
            return True
        with self._lock:
            now = time.monotonic()
            self._tokens = min(float(cap),
                               self._tokens + (now - self._tokens_t) * cap)
            self._tokens_t = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    # ------------------------------------------------------------ v2 records
    def begin(self, meta: rpc_meta_pb2.RpcMeta, body: bytes) -> Dict[str, Any]:
        """Open a v2 record at dispatch time: the arrival timestamp and the
        request identity are stamped now; the record is written by
        :meth:`commit` once the span's phase timeline is complete."""
        req = meta.request
        return {
            "v": 2,
            # wall clock is the on-disk arrival stamp only (inter-arrival
            # gaps for replay pacing + cross-host alignment); never used
            # for interval math in-process
            "ts_us": time.time() * 1e6,  # tpulint: disable=monotonic-clock
            "service": req.service_name,
            "method": req.method_name,
            "trace_id": f"{req.trace_id:016x}",
            "span_id": f"{req.span_id:016x}",
            "log_id": int(req.log_id),
            "timeout_ms": int(req.timeout_ms or 0),
            # QoS identity: which fair-share lane the request billed
            # against and how protected it is — rpc_replay re-stamps both
            # so replayed overload waves shed the same tenants
            "tenant": req.tenant_id,
            "priority": int(req.priority),
            "_meta": meta,
            "_body": bytes(body),
        }

    def commit(self, pending: Dict[str, Any], span=None,
               error_code: int = 0) -> None:
        """Write the record opened by :meth:`begin`, folding in the settled
        span's phase timeline (may be None: the record then carries raw
        wire bytes only, like v1 did)."""
        meta = pending.pop("_meta")
        body = pending.pop("_body")
        pending["error_code"] = int(error_code)
        if span is not None:
            pending["latency_us"] = round(span.latency_us, 1)
            pending["phases"] = {k: round(v, 1)
                                 for k, v in span.phases.items()}
        else:
            pending["latency_us"] = 0.0
            pending["phases"] = {}
        record = pack_record_v2(meta, body, pending)
        key = f"{pending['service']}.{pending['method']}"
        try:
            with self._lock:
                if self._file is None or self._file_bytes > self.max_file_bytes:
                    self._roll()
                self._file.write(record)
                self._file.flush()
                self._file_bytes += len(record)
                self.sampled_count += 1
                self.per_method[key] = self.per_method.get(key, 0) + 1
        except OSError:
            g_dump_errors.put(1)
            return
        g_dump_sampled.put(1)
        g_dump_bytes.put(len(record))

    def sample(self, meta: rpc_meta_pb2.RpcMeta, body: bytes) -> None:
        """One-shot record with no phase timeline — ``commit(begin(...))``
        for callers that never see the span settle."""
        self.commit(self.begin(meta, body))

    def _roll(self) -> None:
        if self._file is not None:
            self._file.close()
            g_dump_rotations.put(1)
        path = os.path.join(self.directory,
                            f"requests.{self._file_index}.dump")
        self._file_index += 1
        self._file = open(path, "wb")
        self._file.write(MAGIC_V2)
        self._file_bytes = len(MAGIC_V2)

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None

    def state(self) -> Dict[str, Any]:
        """Snapshot for the /dump builtin view."""
        with self._lock:
            return {
                "directory": self.directory,
                "max_file_bytes": self.max_file_bytes,
                "file_index": self._file_index,
                "file_bytes": self._file_bytes,
                "sampled": self.sampled_count,
                "per_method": dict(self.per_method),
            }


def pack_record(meta: rpc_meta_pb2.RpcMeta, body: bytes) -> bytes:
    """v1 record (kept for back-compat fixtures and old dumps)."""
    meta_bytes = meta.SerializeToString()
    return (struct.pack(_REC_FMT, len(meta_bytes), len(body))
            + meta_bytes + body)


def pack_record_v2(meta: rpc_meta_pb2.RpcMeta, body: bytes,
                   info: Dict[str, Any]) -> bytes:
    extra = json.dumps(info, separators=(",", ":"),
                       sort_keys=True).encode("utf-8")
    meta_bytes = meta.SerializeToString()
    return (struct.pack(_REC2_FMT, len(extra), len(meta_bytes), len(body))
            + extra + meta_bytes + body)


class DumpRecord:
    """One loaded record. Unpacks as ``(meta, body)`` for v1-era callers;
    the v2 extras live in :attr:`info` (empty dict for v1 records)."""

    __slots__ = ("meta", "body", "info", "version")

    def __init__(self, meta: rpc_meta_pb2.RpcMeta, body: bytes,
                 info: Optional[Dict[str, Any]] = None, version: int = 1):
        self.meta = meta
        self.body = body
        self.info = info or {}
        self.version = version

    def __iter__(self):
        return iter((self.meta, self.body))

    @property
    def trace_id(self) -> int:
        tid = self.info.get("trace_id", "")
        if tid:
            try:
                return int(tid, 16)
            except ValueError:
                pass
        return int(self.meta.request.trace_id)

    @property
    def span_id(self) -> int:
        sid = self.info.get("span_id", "")
        if sid:
            try:
                return int(sid, 16)
            except ValueError:
                pass
        return int(self.meta.request.span_id)

    @property
    def ts_us(self) -> float:
        """Arrival wall-clock timestamp (0.0 on v1 records)."""
        return float(self.info.get("ts_us", 0.0))

    @property
    def method_key(self) -> str:
        svc = self.info.get("service") or self.meta.request.service_name
        meth = self.info.get("method") or self.meta.request.method_name
        return f"{svc}.{meth}"


class RpcDumpLoader:
    """Iterate records of one dump file (or a directory of them); format
    detected per file, truncated tail records tolerated (partial write on
    crash loses at most the last record)."""

    def __init__(self, path: str):
        self.paths = []
        if os.path.isdir(path):
            self.paths = sorted(
                os.path.join(path, f) for f in os.listdir(path)
                if f.endswith(".dump"))
        else:
            self.paths = [path]

    def __iter__(self) -> Iterator[DumpRecord]:
        for p in self.paths:
            with open(p, "rb") as f:
                data = f.read()
            if data.startswith(MAGIC_V2):
                yield from self._iter_v2(data)
            else:
                yield from self._iter_v1(data)

    @staticmethod
    def _iter_v1(data: bytes) -> Iterator[DumpRecord]:
        pos = 0
        while pos + _REC_SIZE <= len(data):
            meta_size, body_size = struct.unpack_from(_REC_FMT, data, pos)
            pos += _REC_SIZE
            if pos + meta_size + body_size > len(data):
                break  # truncated tail record
            try:
                meta = rpc_meta_pb2.RpcMeta.FromString(
                    data[pos:pos + meta_size])
            except Exception:
                break  # corrupt meta: stop at the damage
            pos += meta_size
            body = data[pos:pos + body_size]
            pos += body_size
            yield DumpRecord(meta, body, None, 1)

    @staticmethod
    def _iter_v2(data: bytes) -> Iterator[DumpRecord]:
        pos = len(MAGIC_V2)
        while pos + _REC2_SIZE <= len(data):
            extra_size, meta_size, body_size = struct.unpack_from(
                _REC2_FMT, data, pos)
            pos += _REC2_SIZE
            if pos + extra_size + meta_size + body_size > len(data):
                break  # truncated tail record
            try:
                info = json.loads(data[pos:pos + extra_size])
            except ValueError:
                break
            pos += extra_size
            try:
                meta = rpc_meta_pb2.RpcMeta.FromString(
                    data[pos:pos + meta_size])
            except Exception:
                break
            pos += meta_size
            body = data[pos:pos + body_size]
            pos += body_size
            yield DumpRecord(meta, body, info, 2)
