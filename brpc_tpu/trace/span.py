"""Span — one timed segment of an RPC, the unit of /rpcz.

Rebuild of ``src/brpc/span.h:47-88`` / ``span.cpp``: a client span is born
in Channel.call_method, a server span in request processing; both carry
trace_id/span_id/parent_span_id (propagated via RpcMeta, SURVEY §5.1) and a
list of timestamped annotations. Finished spans land in a bounded in-memory
SpanDB (the reference persists to disk via the bvar Collector; our DB is a
ring — the /rpcz surface is identical, the storage budget explicit).

Beyond the reference, spans carry a **phase timeline**: typed duration
marks (:data:`PHASE_NAMES` — queue/parse/credit_wait/send/batch_wait/
execute/respond) accumulated by the layers a request crosses, plus a
bounded list of structured **events** (credit stalls, send quanta, healer
dials, epoch restarts, batch flushes). Durations are measured on the
monotonic clock (``time.monotonic_ns``); the wall clock is kept only for
the displayed start timestamp, so NTP skew can't produce negative or
inflated latencies. ``to_dict``/``trace_to_dict`` export the whole
timeline as JSON for ``/rpcz?format=json`` and ``tools/trace_view.py``.

Sampling: ``rpcz_sample_ratio`` flag (1.0 = record everything). The
decision is made once per trace at the root and inherited downstream, so a
trace is either fully recorded or not at all.
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from brpc_tpu import flags as _flags

SPAN_DB_CAPACITY = 10000

KIND_CLIENT = "client"
KIND_SERVER = "server"

# The typed phase vocabulary. add_phase accepts any name, but
# only these roll up into the process-wide g_span_phase_* aggregates so a
# buggy caller can't mint unbounded /vars.
PHASE_NAMES = ("queue_us", "parse_us", "credit_wait_us", "send_us",
               "batch_wait_us", "execute_us", "respond_us",
               # serving plane: prompt prefill and the request's share of
               # each fused decode step, stamped by the engine's step loop
               "prefill_us", "decode_us")

# Hard cap on structured events per span: a 16MB streaming send emits one
# event per pipeline quantum, which is bounded, but a pathological retry
# loop isn't — drop past the cap and count the drops.
MAX_EVENTS_PER_SPAN = 64


def _mono_us() -> float:
    return time.monotonic_ns() / 1000.0


class Span:
    __slots__ = ("trace_id", "span_id", "parent_span_id", "kind",
                 "service", "method", "peer", "start_us", "end_us",
                 "start_mono_us", "end_mono_us",
                 "error_code", "request_size", "response_size",
                 "annotations", "phases", "events", "events_dropped",
                 "retained_reason", "_ended")

    def __init__(self, trace_id: int, span_id: int, parent_span_id: int,
                 kind: str, service: str = "", method: str = "",
                 peer: str = ""):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_span_id = parent_span_id
        self.kind = kind
        self.service = service
        self.method = method
        self.peer = peer
        # wall clock for display/cross-process alignment only; all
        # durations come from the monotonic pair below.
        self.start_us = time.time() * 1e6  # tpulint: disable=monotonic-clock
        self.end_us = 0.0
        self.start_mono_us = _mono_us()
        self.end_mono_us = 0.0
        self.error_code = 0
        self.request_size = 0
        self.response_size = 0
        self.annotations: List = []  # (offset_us from start, text)
        self.phases: Dict[str, float] = {}
        self.events: List = []  # (offset_us from start, name, fields dict)
        self.events_dropped = 0
        # non-empty once tail retention committed this span to rpc_dump
        # ("slow_p99" / "error" / "qos_shed" / "watch:<rule>") — the
        # /rpcz?retained=tail filter key
        self.retained_reason = ""
        self._ended = False

    # ------------------------------------------------------------ lifecycle
    def annotate(self, text: str) -> None:
        """TRACEPRINTF equivalent."""
        self.annotations.append((_mono_us() - self.start_mono_us, text))

    def add_phase(self, name: str, us: float) -> None:
        """Accumulate ``us`` microseconds into the named phase (a phase
        may be touched several times — e.g. credit_wait once per send
        quantum — and the mark is the sum)."""
        if us < 0.0:
            us = 0.0
        self.phases[name] = self.phases.get(name, 0.0) + us

    def event(self, name: str, **fields) -> None:
        """Record a structured point-in-time event (credit stall, send
        quantum, healer dial, epoch restart, batch flush...)."""
        if len(self.events) >= MAX_EVENTS_PER_SPAN:
            self.events_dropped += 1
            return
        self.events.append((_mono_us() - self.start_mono_us, name, fields))

    def end(self, error_code: int = 0) -> None:
        if self._ended:
            return
        self._ended = True
        # display twin of end_mono_us, never differenced against a start
        self.end_us = time.time() * 1e6  # tpulint: disable=monotonic-clock
        self.end_mono_us = _mono_us()
        self.error_code = error_code
        _account_phases(self.phases)
        _db_add(self)
        _maybe_export(self)

    @property
    def latency_us(self) -> float:
        return (self.end_mono_us or _mono_us()) - self.start_mono_us

    # ------------------------------------------------------------ export
    def to_dict(self) -> Dict[str, Any]:
        """JSON-shaped export (trace -> spans -> phases/events), the unit
        of ``/rpcz?format=json`` consumed by tools/trace_view.py."""
        d: Dict[str, Any] = {
            "trace_id": f"{self.trace_id:016x}",
            "span_id": f"{self.span_id:016x}",
            "parent_span_id": f"{self.parent_span_id:016x}",
            "kind": self.kind,
            "service": self.service,
            "method": self.method,
            "peer": self.peer,
            "start_us": self.start_us,
            "latency_us": self.latency_us,
            "error_code": self.error_code,
            "request_size": self.request_size,
            "response_size": self.response_size,
            "phases": {k: round(v, 1) for k, v in self.phases.items()},
            "events": [{"offset_us": round(off, 1), "name": name,
                        **fields} for off, name, fields in self.events],
            "annotations": [{"offset_us": round(off, 1), "text": text}
                            for off, text in self.annotations],
        }
        if self.events_dropped:
            d["events_dropped"] = self.events_dropped
        if self.retained_reason:
            d["retained_reason"] = self.retained_reason
        return d

    # ------------------------------------------------------------ rendering
    def render_row(self) -> str:
        ts = time.strftime("%Y-%m-%d %H:%M:%S",
                           time.localtime(self.start_us / 1e6))
        return (f"{ts}  {self.trace_id:016x} {self.span_id:08x}  "
                f"{self.kind:<6}{self.latency_us:>10.0f}  "
                f"{self.service}.{self.method}")

    def render(self) -> str:
        out = [self.render_row()]
        if self.peer:
            out.append(f"    peer={self.peer}")
        if self.error_code:
            out.append(f"    error_code={self.error_code}")
        out.append(f"    request_size={self.request_size} "
                   f"response_size={self.response_size}")
        if self.phases:
            total = self.latency_us or 1.0
            parts = []
            for name in PHASE_NAMES:
                if name in self.phases:
                    v = self.phases[name]
                    parts.append(f"{name[:-3]}={v:.0f}us"
                                 f"({100.0 * v / total:.0f}%)")
            for name, v in self.phases.items():
                if name not in PHASE_NAMES:
                    parts.append(f"{name}={v:.0f}us")
            out.append("    phases: " + " ".join(parts))
        for off, name, fields in self.events:
            kv = " ".join(f"{k}={v}" for k, v in fields.items())
            out.append(f"    +{off:.0f}us  [{name}] {kv}".rstrip())
        if self.events_dropped:
            out.append(f"    ... {self.events_dropped} events dropped")
        for off, text in self.annotations:
            out.append(f"    +{off:.0f}us  {text}")
        return "\n".join(out) + "\n"


# ---------------------------------------------------- phase aggregation
# Process-wide per-phase totals, exported on /vars and prometheus_text as
# g_span_phase_<name> (microsecond counters across all sampled spans).
_phase_adders: Dict[str, Any] = {}
_phase_lock = threading.Lock()


def _account_phases(phases: Dict[str, float]) -> None:
    if not phases:
        return
    from brpc_tpu.metrics.reducer import Adder

    for name in phases:
        if name not in PHASE_NAMES:
            continue
        adder = _phase_adders.get(name)
        if adder is None:
            with _phase_lock:
                adder = _phase_adders.get(name)
                if adder is None:
                    adder = Adder(f"g_span_phase_{name}")
                    _phase_adders[name] = adder
        adder.put(int(phases[name]))


# ------------------------------------------------------------------- export
# OTLP/JSON-lines export hook (trace/export.py). Module cached after the
# first ended span; with span_export_path empty the call is one dict
# lookup, so untraced deployments pay nothing.
_export_mod = None


def _maybe_export(span: "Span") -> None:
    global _export_mod
    if _export_mod is None:
        from brpc_tpu.trace import export as _export_mod_imported

        _export_mod = _export_mod_imported
    _export_mod.maybe_export(span)


# -------------------------------------------------------------------- SpanDB
_db: deque = deque(maxlen=SPAN_DB_CAPACITY)
_by_trace: Dict[int, List[Span]] = {}
_db_lock = threading.Lock()


def _db_add(span: Span) -> None:
    with _db_lock:
        if len(_db) == _db.maxlen:
            old = _db[0]
            spans = _by_trace.get(old.trace_id)
            if spans is not None:
                try:
                    spans.remove(old)
                except ValueError:
                    pass
                if not spans:
                    del _by_trace[old.trace_id]
        _db.append(span)
        _by_trace.setdefault(span.trace_id, []).append(span)


def recent_spans(count: int = 50, method: str = "",
                 min_latency_us: float = 0.0,
                 error_only: bool = False,
                 retained: str = "") -> List[Span]:
    """Newest-first finished spans, optionally filtered (the /rpcz query
    surface): ``method`` is a substring match against service.method,
    ``min_latency_us`` keeps only slower spans, ``error_only`` keeps only
    spans with a non-zero error code, ``retained="tail"`` keeps only spans
    committed to rpc_dump by tail retention (any reason)."""
    with _db_lock:
        spans = list(_db)
    out: List[Span] = []
    for sp in reversed(spans):
        if method and method not in f"{sp.service}.{sp.method}":
            continue
        if min_latency_us and sp.latency_us < min_latency_us:
            continue
        if error_only and not sp.error_code:
            continue
        if retained and not sp.retained_reason:
            continue
        out.append(sp)
        if len(out) >= count:
            break
    return out


def spans_of_trace(trace_id: int) -> List[Span]:
    with _db_lock:
        return list(_by_trace.get(trace_id, ()))


def trace_to_dict(trace_id: int) -> Dict[str, Any]:
    """Whole-trace JSON export: trace -> spans -> phases/events, plus the
    stitched parent->child ``tree`` (client and server spans of one trace
    nest by parent_span_id — the ids line up across processes)."""
    spans = [sp.to_dict() for sp in spans_of_trace(trace_id)]
    return {"trace_id": f"{trace_id:016x}",
            "spans": spans,
            "tree": build_span_tree(spans)}


def build_span_tree(span_dicts: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Nest span dicts (``to_dict`` shape) into parent->children trees by
    span id: a server span hangs under the client span that issued it, a
    downstream client span under the server span whose handler made the
    call. Returns the roots (spans whose parent isn't in the set), each
    node a copy of the span dict plus a ``children`` list; siblings order
    by wall-clock start."""
    nodes = [{**d, "children": []} for d in span_dicts]
    by_id: Dict[Any, Dict[str, Any]] = {}
    for n in nodes:
        by_id.setdefault(n.get("span_id"), n)
    roots = []
    for n in nodes:
        parent = by_id.get(n.get("parent_span_id"))
        if parent is not None and parent is not n:
            parent["children"].append(n)
        else:
            roots.append(n)

    def _sort(ns: List[Dict[str, Any]]) -> None:
        ns.sort(key=lambda d: d.get("start_us", 0.0))
        for d in ns:
            _sort(d["children"])

    _sort(roots)
    return roots


def merge_trace_docs(docs: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Stitch trace exports from several processes into one doc: the
    client half of a trace lives in the caller's span DB, the server half
    in the callee's — fetch ``/rpcz/<trace_id>?format=json`` from each and
    merge. Spans dedup by (span_id, kind); the result carries a rebuilt
    ``tree``."""
    seen = set()
    spans: List[Dict[str, Any]] = []
    tid = ""
    for doc in docs:
        for d in doc.get("spans", []):
            key = (d.get("span_id"), d.get("kind"))
            if key in seen:
                continue
            seen.add(key)
            spans.append(d)
        tid = tid or doc.get("trace_id", "")
    spans.sort(key=lambda d: d.get("start_us", 0.0))
    return {"trace_id": tid, "spans": spans,
            "tree": build_span_tree(spans)}


def reset_for_test() -> None:
    with _db_lock:
        _db.clear()
        _by_trace.clear()


# ----------------------------------------------------- current span context
# Server request processing parks its span here while user code runs, so
# downstream client calls made inside a handler stitch into the same trace
# (the reference parks the Span on the bthread's local storage).
_current = threading.local()


def set_current(span: Optional[Span]):
    prev = getattr(_current, "span", None)
    _current.span = span
    return prev


def current_span() -> Optional[Span]:
    return getattr(_current, "span", None)


# ------------------------------------------------------------------ creation
def _gen_id() -> int:
    return random.getrandbits(63) | 1


_collector_mod = None


def _sampled() -> bool:
    # the selection ratio rides the PROCESS-WIDE sampling budget shared
    # with rpc_dump etc. (metrics/collector.py, reference bvar Collector)
    global _collector_mod
    if _collector_mod is None:  # lazy: collector imports flags at load
        from brpc_tpu.metrics import collector as _collector_mod_

        _collector_mod = _collector_mod_
    # cache the MODULE, not the instance: tests (and a future reset) swap
    # collector._collector, and a cached instance would gate on the dead
    # one's budget
    coll = _collector_mod._collector
    if coll is None:
        coll = _collector_mod.global_collector()
    # pre-gate on the collector's standing denial window (`_deny_until` is
    # a documented contract, collector.py): during a denial no draw can
    # succeed, so skip the ratio draw entirely — this runs once per
    # untraced RPC on BOTH roles and the saved microseconds are measurable
    # at small-echo rates
    if time.monotonic() < coll._deny_until:
        return False
    ratio = _flags.get("rpcz_sample_ratio")
    if ratio < 1.0 and random.random() >= ratio:
        return False
    return coll.ask_to_be_sampled()


def start_client_span(service: str, method: str,
                      parent: Optional[Span] = None) -> Optional[Span]:
    """Root or child client span. Returns None when the trace isn't
    sampled (callers must tolerate span=None everywhere)."""
    if parent is not None:
        return Span(parent.trace_id, _gen_id(), parent.span_id,
                    KIND_CLIENT, service, method)
    if not _sampled():
        return None
    tid = _gen_id()
    return Span(tid, tid, 0, KIND_CLIENT, service, method)


def start_server_span(meta, service: str, method: str,
                      peer: str = "") -> Optional[Span]:
    """Server span continuing a propagated trace (or rooting a new one
    when the client didn't trace)."""
    return start_server_span_ids(
        meta.request.trace_id if meta is not None else 0,
        meta.request.span_id if meta is not None else 0,
        service, method, peer)


def start_server_span_ids(trace_id: int, parent_span_id: int, service: str,
                          method: str, peer: str = "") -> Optional[Span]:
    """Same as :func:`start_server_span` from pre-cracked ids (the native
    fast path delivers trace/span ids without a meta pb)."""
    if trace_id:
        return Span(trace_id, _gen_id(), parent_span_id,
                    KIND_SERVER, service, method, peer)
    if not _sampled():
        return None
    tid = _gen_id()
    return Span(tid, tid, 0, KIND_SERVER, service, method, peer)
