"""Span — one timed segment of an RPC, the unit of /rpcz.

Rebuild of ``src/brpc/span.h:47-88`` / ``span.cpp``: a client span is born
in Channel.call_method, a server span in request processing; both carry
trace_id/span_id/parent_span_id (propagated via RpcMeta, SURVEY §5.1) and a
list of timestamped annotations. Finished spans land in a bounded in-memory
SpanDB (the reference persists to disk via the bvar Collector; our DB is a
ring — the /rpcz surface is identical, the storage budget explicit).

Sampling: ``rpcz_sample_ratio`` flag (1.0 = record everything). The
decision is made once per trace at the root and inherited downstream, so a
trace is either fully recorded or not at all.
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from brpc_tpu import flags as _flags

SPAN_DB_CAPACITY = 10000

KIND_CLIENT = "client"
KIND_SERVER = "server"


class Span:
    __slots__ = ("trace_id", "span_id", "parent_span_id", "kind",
                 "service", "method", "peer", "start_us", "end_us",
                 "error_code", "request_size", "response_size",
                 "annotations", "_ended")

    def __init__(self, trace_id: int, span_id: int, parent_span_id: int,
                 kind: str, service: str = "", method: str = "",
                 peer: str = ""):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_span_id = parent_span_id
        self.kind = kind
        self.service = service
        self.method = method
        self.peer = peer
        self.start_us = time.time() * 1e6
        self.end_us = 0.0
        self.error_code = 0
        self.request_size = 0
        self.response_size = 0
        self.annotations: List = []  # (us, text)
        self._ended = False

    # ------------------------------------------------------------ lifecycle
    def annotate(self, text: str) -> None:
        """TRACEPRINTF equivalent."""
        self.annotations.append((time.time() * 1e6, text))

    def end(self, error_code: int = 0) -> None:
        if self._ended:
            return
        self._ended = True
        self.end_us = time.time() * 1e6
        self.error_code = error_code
        _db_add(self)

    @property
    def latency_us(self) -> float:
        return (self.end_us or time.time() * 1e6) - self.start_us

    # ------------------------------------------------------------ rendering
    def render_row(self) -> str:
        ts = time.strftime("%Y-%m-%d %H:%M:%S",
                           time.localtime(self.start_us / 1e6))
        return (f"{ts}  {self.trace_id:016x} {self.span_id:08x}  "
                f"{self.kind:<6}{self.latency_us:>10.0f}  "
                f"{self.service}.{self.method}")

    def render(self) -> str:
        out = [self.render_row()]
        if self.peer:
            out.append(f"    peer={self.peer}")
        if self.error_code:
            out.append(f"    error_code={self.error_code}")
        out.append(f"    request_size={self.request_size} "
                   f"response_size={self.response_size}")
        for us, text in self.annotations:
            out.append(f"    +{us - self.start_us:.0f}us  {text}")
        return "\n".join(out) + "\n"


# -------------------------------------------------------------------- SpanDB
_db: deque = deque(maxlen=SPAN_DB_CAPACITY)
_by_trace: Dict[int, List[Span]] = {}
_db_lock = threading.Lock()


def _db_add(span: Span) -> None:
    with _db_lock:
        if len(_db) == _db.maxlen:
            old = _db[0]
            spans = _by_trace.get(old.trace_id)
            if spans is not None:
                try:
                    spans.remove(old)
                except ValueError:
                    pass
                if not spans:
                    del _by_trace[old.trace_id]
        _db.append(span)
        _by_trace.setdefault(span.trace_id, []).append(span)


def recent_spans(count: int = 50) -> List[Span]:
    with _db_lock:
        return list(_db)[-count:][::-1]


def spans_of_trace(trace_id: int) -> List[Span]:
    with _db_lock:
        return list(_by_trace.get(trace_id, ()))


def reset_for_test() -> None:
    with _db_lock:
        _db.clear()
        _by_trace.clear()


# ----------------------------------------------------- current span context
# Server request processing parks its span here while user code runs, so
# downstream client calls made inside a handler stitch into the same trace
# (the reference parks the Span on the bthread's local storage).
_current = threading.local()


def set_current(span: Optional[Span]):
    prev = getattr(_current, "span", None)
    _current.span = span
    return prev


def current_span() -> Optional[Span]:
    return getattr(_current, "span", None)


# ------------------------------------------------------------------ creation
def _gen_id() -> int:
    return random.getrandbits(63) | 1


def _sampled() -> bool:
    ratio = _flags.get("rpcz_sample_ratio")
    if ratio < 1.0 and random.random() >= ratio:
        return False
    # the selection ratio rides the PROCESS-WIDE sampling budget shared
    # with rpc_dump etc. (metrics/collector.py, reference bvar Collector)
    from brpc_tpu.metrics.collector import global_collector

    return global_collector().ask_to_be_sampled()


def start_client_span(service: str, method: str,
                      parent: Optional[Span] = None) -> Optional[Span]:
    """Root or child client span. Returns None when the trace isn't
    sampled (callers must tolerate span=None everywhere)."""
    if parent is not None:
        return Span(parent.trace_id, _gen_id(), parent.span_id,
                    KIND_CLIENT, service, method)
    if not _sampled():
        return None
    tid = _gen_id()
    return Span(tid, tid, 0, KIND_CLIENT, service, method)


def start_server_span(meta, service: str, method: str,
                      peer: str = "") -> Optional[Span]:
    """Server span continuing a propagated trace (or rooting a new one
    when the client didn't trace)."""
    return start_server_span_ids(
        meta.request.trace_id if meta is not None else 0,
        meta.request.span_id if meta is not None else 0,
        service, method, peer)


def start_server_span_ids(trace_id: int, parent_span_id: int, service: str,
                          method: str, peer: str = "") -> Optional[Span]:
    """Same as :func:`start_server_span` from pre-cracked ids (the native
    fast path delivers trace/span ids without a meta pb)."""
    if trace_id:
        return Span(trace_id, _gen_id(), parent_span_id,
                    KIND_SERVER, service, method, peer)
    if not _sampled():
        return None
    tid = _gen_id()
    return Span(tid, tid, 0, KIND_SERVER, service, method, peer)


