"""trace — per-RPC span tracing (rpcz) and request sampling (rpc_dump).

Counterpart of the reference's ``src/brpc/span.*`` + ``rpc_dump.*``
(SURVEY §5.1): client and server spans with annotations, sampled into an
in-memory SpanDB browsable at ``/rpcz``; trace ids propagate through
RpcMeta so multi-hop calls stitch into one trace. rpc_dump samples inbound
requests to files that ``tools/rpc_replay`` re-issues.
"""

from brpc_tpu.trace.span import (
    Span,
    PHASE_NAMES,
    start_client_span,
    start_server_span,
    recent_spans,
    spans_of_trace,
    trace_to_dict,
    reset_for_test,
)
from brpc_tpu.trace.rpc_dump import RpcDumper, RpcDumpLoader

__all__ = [
    "Span",
    "PHASE_NAMES",
    "start_client_span",
    "start_server_span",
    "recent_spans",
    "spans_of_trace",
    "trace_to_dict",
    "reset_for_test",
    "RpcDumper",
    "RpcDumpLoader",
]
