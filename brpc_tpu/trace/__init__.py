"""trace — per-RPC span tracing (rpcz), request sampling (rpc_dump),
phase-timeline diffing, and OTLP export.

Counterpart of the reference's ``src/brpc/span.*`` + ``rpc_dump.*``
(SURVEY §5.1): client and server spans with annotations, sampled into an
in-memory SpanDB browsable at ``/rpcz``; trace ids propagate through
RpcMeta so multi-hop calls stitch into one trace. rpc_dump samples inbound
requests to v2 records (wire bytes + arrival timestamp + the server span's
settled phase timeline) that ``tools/rpc_replay`` re-issues at N× rate and
``trace/diff.py`` compares against the recording to localize a regression
to a phase. ``trace/export.py`` streams finished spans as OTLP JSON lines
behind the ``span_export_path`` flag.
"""

from brpc_tpu.trace.span import (
    Span,
    PHASE_NAMES,
    start_client_span,
    start_server_span,
    recent_spans,
    spans_of_trace,
    trace_to_dict,
    build_span_tree,
    merge_trace_docs,
    reset_for_test,
)
from brpc_tpu.trace.rpc_dump import (
    RpcDumper,
    RpcDumpLoader,
    DumpRecord,
)

__all__ = [
    "Span",
    "PHASE_NAMES",
    "start_client_span",
    "start_server_span",
    "recent_spans",
    "spans_of_trace",
    "trace_to_dict",
    "build_span_tree",
    "merge_trace_docs",
    "reset_for_test",
    "RpcDumper",
    "RpcDumpLoader",
    "DumpRecord",
]
