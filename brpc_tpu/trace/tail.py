"""Tail-based trace retention — keep the traces worth replaying.

Head sampling (``rpc_dump_ratio``) decides at *arrival*, so at ratio r it
keeps r of everything — including r of the slow/errored tail an operator
actually replays. Tail retention moves the decision to *settle* time, when
the span's latency, error code and the process's health are all known:

- **retain immediately** when the request errored, was QoS-shed
  (EOVERCROWDED / ELIMIT), or ran slower than
  ``rpc_dump_tail_slow_x`` × its method's live p99;
- **hold** everything else in a bounded deferred-decision ring for
  ``rpc_dump_tail_hold_s`` seconds — if a watch rule fires inside the
  window, the held traces around the firing are retained too
  (reason ``watch:<rule>``), which is exactly the context an incident
  post-mortem wants and head sampling statistically discards;
- expired holds are dropped unwritten.

Every commit still passes the ``rpc_dump_tail_max_per_sec`` token bucket
(same monotonic-bucket shape as RpcDumper's), so a latency storm can't turn
the retainer into its own overload. Records land in the normal v2 dump
stream with ``retained: "tail"`` + ``retention_reason`` stamped into the
extra blob, and the settled span carries the reason for the
``/rpcz?retained=tail`` filter.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, Optional

from brpc_tpu import flags as _flags
from brpc_tpu.metrics.reducer import Adder
from brpc_tpu.metrics.watch import STATE_FIRING, global_watch
from brpc_tpu.rpc.errors import ELIMIT, EOVERCROWDED

rpc_dump_tail = _flags.define(
    "rpc_dump_tail", False,
    "tail-based trace retention: commit settled requests to rpc_dump "
    "when slow vs their method p99, errored, QoS-shed, or correlated "
    "with a firing watch rule (independent of the rpc_dump_ratio head "
    "sampler)", reloadable=True)
rpc_dump_tail_slow_x = _flags.define(
    "rpc_dump_tail_slow_x", 2.0,
    "retain a settled request whose latency exceeds this multiple of "
    "its method's live p99 (reloadable)", validator=lambda v: v > 0)
rpc_dump_tail_max_per_sec = _flags.define(
    "rpc_dump_tail_max_per_sec", 50,
    "token-bucket cap on tail-retained dump records per second "
    "(0 = uncapped)", validator=lambda v: v >= 0)
rpc_dump_tail_hold_s = _flags.define(
    "rpc_dump_tail_hold_s", 2.0,
    "seconds a settled, individually-unremarkable request is held for "
    "watch-rule correlation before being dropped unwritten (reloadable)",
    validator=lambda v: v > 0)
rpc_dump_tail_ring = _flags.define(
    "rpc_dump_tail_ring", 256,
    "capacity of the deferred-decision ring; the oldest held request is "
    "dropped when a newer one needs the slot", validator=lambda v: v > 0)

g_dump_tail_retained = Adder("g_dump_tail_retained")  # committed records
g_dump_tail_dropped = Adder("g_dump_tail_dropped")    # holds expired/evicted
g_dump_tail_shed = Adder("g_dump_tail_shed")          # token bucket said no

REASON_SLOW = "slow_p99"
REASON_ERROR = "error"
REASON_SHED = "qos_shed"


class TailRetainer:
    """Settle-time retention front of one server's RpcDumper."""

    def __init__(self, dumper):
        self._dumper = dumper
        self._lock = threading.Lock()
        # (deadline_mono_s, pending, span, error_code)
        self._ring: deque = deque()
        self._tokens = 1.0
        self._tokens_t = time.monotonic()
        self._closed = False
        self._hook = self._on_watch
        global_watch().transition_hooks.append(self._hook)

    # ------------------------------------------------------------- decide
    @staticmethod
    def enabled() -> bool:
        return bool(_flags.get("rpc_dump_tail"))

    def offer(self, pending: Dict[str, Any], span, error_code: int,
              method_p99_us: float) -> None:
        """Hand over a settled request for the retention decision.

        ``pending`` is the dict RpcDumper.begin() returned at dispatch;
        ownership transfers here — it is either committed or dropped."""
        if span is None or self._closed:
            return
        reason = self._reason(span, error_code, method_p99_us)
        if reason is None:
            # watch correlation: a rule already firing retains immediately
            firing = global_watch().firing()
            if firing:
                reason = f"watch:{firing[0].name}"
        if reason is not None:
            self._commit(pending, span, error_code, reason)
            self._sweep()
            return
        hold_s = float(_flags.get("rpc_dump_tail_hold_s"))
        cap = int(_flags.get("rpc_dump_tail_ring"))
        with self._lock:
            while len(self._ring) >= cap:
                self._ring.popleft()
                g_dump_tail_dropped.put(1)
            self._ring.append(
                (time.monotonic() + hold_s, pending, span, error_code))
        self._sweep()

    @staticmethod
    def _reason(span, error_code: int, method_p99_us: float) -> Optional[str]:
        if error_code in (EOVERCROWDED, ELIMIT):
            return REASON_SHED
        if error_code:
            return REASON_ERROR
        slow_x = float(_flags.get("rpc_dump_tail_slow_x"))
        if method_p99_us > 0 and span.latency_us > slow_x * method_p99_us:
            return REASON_SLOW
        return None

    # -------------------------------------------------------------- commit
    def _commit(self, pending: Dict[str, Any], span, error_code: int,
                reason: str) -> None:
        if not self._take_token():
            g_dump_tail_shed.put(1)
            return
        pending["retained"] = "tail"
        pending["retention_reason"] = reason
        self._dumper.commit(pending, span, error_code)
        span.retained_reason = reason
        g_dump_tail_retained.put(1)

    def _take_token(self) -> bool:
        cap = int(_flags.get("rpc_dump_tail_max_per_sec"))
        if cap <= 0:
            return True
        with self._lock:
            now = time.monotonic()
            self._tokens = min(float(cap),
                               self._tokens + (now - self._tokens_t) * cap)
            self._tokens_t = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def _sweep(self) -> None:
        """Expire holds past their correlation deadline."""
        now = time.monotonic()
        with self._lock:
            expired = 0
            while self._ring and self._ring[0][0] <= now:
                self._ring.popleft()
                expired += 1
        if expired:
            g_dump_tail_dropped.put(expired)

    # --------------------------------------------------- watch correlation
    def _on_watch(self, rule, new_state: str) -> None:
        """Transition hook: a rule starting to fire retains every held
        request in the correlation window — the traffic *around* the
        incident is the context a post-mortem replays."""
        if new_state != STATE_FIRING or self._closed:
            return
        with self._lock:
            held = list(self._ring)
            self._ring.clear()
        for _deadline, pending, span, error_code in held:
            self._commit(pending, span, error_code, f"watch:{rule.name}")

    # ------------------------------------------------------------ lifecycle
    def close(self) -> None:
        self._closed = True
        try:
            global_watch().transition_hooks.remove(self._hook)
        except ValueError:
            pass
        with self._lock:
            dropped = len(self._ring)
            self._ring.clear()
        if dropped:
            g_dump_tail_dropped.put(dropped)

    def state(self) -> Dict[str, Any]:
        with self._lock:
            held = len(self._ring)
        return {
            "enabled": self.enabled(),
            "held": held,
            "slow_x": float(_flags.get("rpc_dump_tail_slow_x")),
            "hold_s": float(_flags.get("rpc_dump_tail_hold_s")),
            "max_per_sec": int(_flags.get("rpc_dump_tail_max_per_sec")),
        }
