"""Phase-timeline diff — localize a latency regression to a *phase*.

Every span carries the typed additive phase timeline (queue/parse/
credit_wait/send/batch_wait/execute/respond), so two runs of the same
workload — a recorded dump and its replay, or yesterday's baseline and
today's build — can be compared per method per phase instead of per p99:
the report says WHICH stage moved ("credit_wait p99 +38% on Echo.echo"),
not just that something did.

Inputs are interchangeable:

- ``/rpcz?format=json`` exports (``{"spans": [...]}``, the live surface
  chaos_run saves) — server spans by default;
- rpc_dump v2 files/directories (each record carries the server span's
  settled phases + latency).

Samples group into per-method :class:`MethodProfile` buckets; each phase
is summarized at a percentile (nearest-rank). A regression needs BOTH a
relative move past ``threshold`` AND an absolute move past
``min_delta_us`` (so a 3us->6us jitter never pages anyone), with at least
``min_samples`` on each side.

Consumed by ``tools/trace_diff.py`` and chaos_run's ``--diff-baseline``
regression gate.
"""

from __future__ import annotations

import json
import math
import os
from typing import Any, Dict, Iterable, List, Optional

DEFAULT_PERCENTILE = 0.99
DEFAULT_THRESHOLD = 0.30
DEFAULT_MIN_DELTA_US = 2000.0
DEFAULT_MIN_SAMPLES = 3

# latency rides the profiles as a pseudo-phase so reports show the
# end-to-end move next to the per-phase ones; it is NOT flagged as a
# regression on its own — the phases are the localization
LATENCY_KEY = "latency_us"


def percentile(values, q: float) -> float:
    """Nearest-rank percentile (q in [0, 1])."""
    vs = sorted(values)
    if not vs:
        return 0.0
    idx = int(math.ceil(q * len(vs))) - 1
    return vs[max(0, min(idx, len(vs) - 1))]


class MethodProfile:
    """All phase samples of one service.method in one run."""

    __slots__ = ("method", "count", "phases")

    def __init__(self, method: str):
        self.method = method
        self.count = 0
        self.phases: Dict[str, List[float]] = {}

    def add(self, phases: Dict[str, float], latency_us: float) -> None:
        self.count += 1
        for k, v in phases.items():
            self.phases.setdefault(k, []).append(float(v))
        self.phases.setdefault(LATENCY_KEY, []).append(float(latency_us))

    def phase_percentile(self, phase: str, q: float) -> float:
        return percentile(self.phases.get(phase, ()), q)


class PhaseRegression:
    """One flagged move: a phase of a method got slower between runs."""

    __slots__ = ("method", "phase", "percentile", "base_us", "new_us",
                 "base_count", "new_count")

    def __init__(self, method: str, phase: str, q: float,
                 base_us: float, new_us: float,
                 base_count: int, new_count: int):
        self.method = method
        self.phase = phase
        self.percentile = q
        self.base_us = base_us
        self.new_us = new_us
        self.base_count = base_count
        self.new_count = new_count

    @property
    def delta_pct(self) -> float:
        if self.base_us <= 0.0:
            return float("inf")
        return 100.0 * (self.new_us - self.base_us) / self.base_us

    def describe(self) -> str:
        short = self.phase[:-3] if self.phase.endswith("_us") else self.phase
        pct = int(round(self.percentile * 100))
        if math.isinf(self.delta_pct):
            move = "new"
        else:
            move = f"+{self.delta_pct:.0f}%"
        return (f"{short} p{pct} {move} on {self.method} "
                f"({self.base_us:.0f}us -> {self.new_us:.0f}us, "
                f"n={self.base_count}/{self.new_count})")

    def to_dict(self) -> Dict[str, Any]:
        return {"method": self.method, "phase": self.phase,
                "percentile": self.percentile,
                "base_us": round(self.base_us, 1),
                "new_us": round(self.new_us, 1),
                "base_count": self.base_count, "new_count": self.new_count,
                "summary": self.describe()}


# --------------------------------------------------------------- collection
def profiles_from_spans(span_dicts: Iterable[Dict[str, Any]],
                        kind: str = "server") -> Dict[str, MethodProfile]:
    """Group span dicts (``Span.to_dict`` shape) into method profiles.
    ``kind`` filters ("server"/"client"; "" keeps both)."""
    out: Dict[str, MethodProfile] = {}
    for d in span_dicts:
        if kind and d.get("kind") != kind:
            continue
        m = f"{d.get('service', '')}.{d.get('method', '')}"
        prof = out.get(m)
        if prof is None:
            prof = out[m] = MethodProfile(m)
        prof.add(d.get("phases") or {}, float(d.get("latency_us", 0.0)))
    return out


def profiles_from_dump(path: str) -> Dict[str, MethodProfile]:
    """Method profiles from rpc_dump v2 records (v1 records carry no
    phase timeline and are skipped)."""
    from brpc_tpu.trace.rpc_dump import RpcDumpLoader

    out: Dict[str, MethodProfile] = {}
    for rec in RpcDumpLoader(path):
        info = rec.info
        if not info:
            continue
        m = rec.method_key
        prof = out.get(m)
        if prof is None:
            prof = out[m] = MethodProfile(m)
        prof.add(info.get("phases") or {},
                 float(info.get("latency_us", 0.0)))
    return out


def load_profiles(source, kind: str = "server") -> Dict[str, MethodProfile]:
    """Profiles from any supported source: an already-parsed /rpcz doc
    (dict), a ``.dump`` file, a directory containing ``*.dump`` files, or
    a JSON export file."""
    if isinstance(source, dict):
        return profiles_from_spans(source.get("spans", []), kind)
    if os.path.isdir(source):
        if any(f.endswith(".dump") for f in os.listdir(source)):
            return profiles_from_dump(source)
        source = os.path.join(source, "traces.json")
    if source.endswith(".dump"):
        return profiles_from_dump(source)
    with open(source) as f:
        doc = json.load(f)
    return profiles_from_spans(doc.get("spans", []), kind)


# --------------------------------------------------------------------- diff
def diff_profiles(base: Dict[str, MethodProfile],
                  new: Dict[str, MethodProfile],
                  q: float = DEFAULT_PERCENTILE,
                  threshold: float = DEFAULT_THRESHOLD,
                  min_delta_us: float = DEFAULT_MIN_DELTA_US,
                  min_samples: int = DEFAULT_MIN_SAMPLES,
                  ) -> List[PhaseRegression]:
    """Phases (per method) whose percentile-``q`` value regressed from
    ``base`` to ``new``, worst absolute move first. Methods present on
    only one side are skipped (nothing to compare), as are methods with
    fewer than ``min_samples`` on either side."""
    regs: List[PhaseRegression] = []
    for method in sorted(new):
        np = new[method]
        bp = base.get(method)
        if bp is None or bp.count < min_samples or np.count < min_samples:
            continue
        names = (set(bp.phases) | set(np.phases)) - {LATENCY_KEY}
        for phase in sorted(names):
            b = bp.phase_percentile(phase, q)
            n = np.phase_percentile(phase, q)
            if n - b < min_delta_us:
                continue
            if b > 0.0 and (n - b) / b < threshold:
                continue
            regs.append(PhaseRegression(method, phase, q, b, n,
                                        bp.count, np.count))
    regs.sort(key=lambda r: r.base_us - r.new_us)
    return regs


def render_report(base: Dict[str, MethodProfile],
                  new: Dict[str, MethodProfile],
                  regressions: List[PhaseRegression],
                  q: float = DEFAULT_PERCENTILE) -> str:
    """Human-readable diff: a per-method phase table (base vs new at the
    chosen percentile) and the regression verdict."""
    pct = int(round(q * 100))
    lines = [f"phase diff at p{pct} (base vs new, us)"]
    for method in sorted(set(base) | set(new)):
        bp = base.get(method)
        np = new[method] if method in new else None
        bn = bp.count if bp else 0
        nn = np.count if np else 0
        lines.append(f"  {method}  n={bn}/{nn}")
        names = set()
        if bp:
            names |= set(bp.phases)
        if np:
            names |= set(np.phases)
        for phase in sorted(names - {LATENCY_KEY}) + [LATENCY_KEY]:
            if phase not in names:
                continue
            b = bp.phase_percentile(phase, q) if bp else 0.0
            n = np.phase_percentile(phase, q) if np else 0.0
            mark = ""
            if any(r.method == method and r.phase == phase
                   for r in regressions):
                mark = "  <-- REGRESSED"
            lines.append(f"    {phase:<16} {b:>10.0f} {n:>10.0f}{mark}")
    if regressions:
        lines.append("")
        lines.append(f"{len(regressions)} phase regression(s):")
        for r in regressions:
            lines.append(f"  {r.describe()}")
    else:
        lines.append("")
        lines.append("no phase regressions")
    return "\n".join(lines) + "\n"
