"""Speculative decoding's draft lane: prompt-lookup drafting, host-side.

The serving plane's per-token cost floor is one fused decode launch per
step. Speculative decoding (Leviathan et al. 2023) raises tokens/step
above 1.0 by *drafting* k candidate tokens cheaply and then *verifying*
all of them in a single fused launch (model.verify_step). With greedy
acceptance the committed stream is bit-identical to the non-speculative
lane — the rare speedup with an exact equality oracle.

This module is the draft half, and it is deliberately boring hardware-
wise: prompt-lookup / n-gram drafting (Saxena 2023) proposes the
continuation that followed the most recent earlier occurrence of the
sequence's trailing n-gram — pure host Python over the committed token
history, zero model weights, zero device work. The ``draft-no-device-
sync`` tpulint rule pins that down: nothing in this file may import jax
or touch jit/device-dispatch/host-sync primitives, so drafting can never
reintroduce a second sync into the engine's (1,1) step invariant.

Pieces:

- :func:`draft_tokens` — the matcher. Longest trailing n-gram first
  (``ngram_max`` down to 1), most recent earlier occurrence wins, the
  k tokens that followed it are the draft. Empty draft when nothing
  matches — the step degrades to a normal 1-token decode.
- :class:`AdaptiveK` — per-sequence draft-length controller. Grows k
  toward ``k_max`` while drafts keep being accepted, halves it on
  zero-accept steps, and *collapses to 0* (speculation disabled for the
  sequence) after ``collapse_after`` consecutive zero-accept steps —
  the draft-collapse guard that bounds worst-case overhead under
  adversarial drafts to a constant number of wasted rows.
- :func:`accept_longest_prefix` — greedy acceptance: the longest prefix
  of the draft agreeing with the verifier's argmax, plus the one bonus
  token the verifier produced at the first disagreement (or past the
  last accepted draft), exactly Leviathan's rule at temperature 0.
- ``g_serving_spec_*`` metric vars and :func:`note_step`, feeding the
  ``serving_spec_collapse`` watch rule's accept-rate gauge over a
  sliding window of recent steps.

Fault point ``serving.spec.misdraft`` swaps real drafts for adversarial
garbage (a deterministic vocab walk that greedy verification rejects),
driving accept rate to ~0 to exercise rollback and the collapse guard.
"""

from __future__ import annotations

import collections
import threading
from typing import List, Optional, Sequence, Tuple

from brpc_tpu import fault as _fault
from brpc_tpu.metrics.reducer import Adder
from brpc_tpu.metrics.status import PassiveStatus

_fault.register("serving.spec.misdraft",
                "replace speculative drafts with adversarial garbage "
                "(token=<fixed token> overrides the vocab walk)")

g_serving_spec_draft_tokens = Adder("g_serving_spec_draft_tokens")
g_serving_spec_accepted_tokens = Adder("g_serving_spec_accepted_tokens")
g_serving_spec_rejected_tokens = Adder("g_serving_spec_rejected_tokens")
g_serving_spec_bonus_tokens = Adder("g_serving_spec_bonus_tokens")

# accept rate over a sliding window of recent engine steps (not
# cumulative — the serving_spec_collapse watch rule needs to see a
# *current* collapse, not one damped by hours of healthy history).
_rate_lock = threading.Lock()
_recent_steps: collections.deque = collections.deque(maxlen=256)


def note_step(drafted: int, accepted: int) -> None:
    """Record one engine step's aggregate draft outcome (all sequences)."""
    if drafted <= 0:
        return
    with _rate_lock:
        _recent_steps.append((int(drafted), int(accepted)))


def accept_rate() -> float:
    """Accepted/drafted over the recent-step window; 1.0 when idle so the
    collapse watch rule stays quiet on engines that aren't speculating."""
    with _rate_lock:
        drafted = sum(d for d, _ in _recent_steps)
        accepted = sum(a for _, a in _recent_steps)
    if drafted <= 0:
        return 1.0
    return accepted / drafted


def reset_rate_window() -> None:
    """Test hook: forget the recent-step window."""
    with _rate_lock:
        _recent_steps.clear()


g_serving_spec_accept_rate = PassiveStatus(accept_rate) \
    .expose("g_serving_spec_accept_rate")
g_serving_spec_accept_rate.prometheus_type = "gauge"


def _lookup(history: Sequence[int], k: int, ngram_max: int) -> List[int]:
    """Most recent earlier occurrence of the trailing n-gram, longest n
    first; returns up to k continuation tokens (possibly fewer near the
    end of history)."""
    h = [int(t) for t in history]
    n_hi = min(ngram_max, len(h) - 1)
    for n in range(n_hi, 0, -1):
        tail = h[-n:]
        for j in range(len(h) - n - 1, -1, -1):
            if h[j:j + n] == tail:
                return h[j + n:j + n + k]
    return []


def draft_tokens(history: Sequence[int], k: int, ngram_max: int = 3,
                 vocab: int = 0) -> List[int]:
    """Draft up to ``k`` tokens for a sequence whose committed history
    (prompt + generated) is ``history``. Host-side only. Returns [] when
    no n-gram matches (the step falls back to plain decode).

    Under the armed ``serving.spec.misdraft`` fault the draft is replaced
    with a deterministic garbage walk of length ``k`` — maximum draft
    spend, ~zero acceptance — regardless of what the matcher found."""
    if k <= 0 or len(history) < 2:
        drafted: List[int] = []
    else:
        drafted = _lookup(history, k, ngram_max)
    params = _fault.hit("serving.spec.misdraft")
    if params is not None and k > 0:
        fixed = params.get("token")
        if fixed is not None:
            return [int(fixed)] * k
        last = int(history[-1]) if len(history) else 0
        mod = int(vocab) if vocab and int(vocab) > 1 else 1 << 30
        # walk away from the last token: greedy cycles repeat it, so a
        # strictly-moving walk is the adversarial worst case
        return [(last + 1 + i) % mod for i in range(k)]
    return drafted


def accept_longest_prefix(draft: Sequence[int],
                          scores: Sequence[int]) -> Tuple[int, List[int]]:
    """Greedy acceptance. ``scores`` is the verifier's argmax at each of
    the k+1 scored positions (m_0 for the last committed token, m_j for
    draft token j). Accept draft tokens while they agree with the argmax
    at the *previous* position; the first disagreeing position's argmax
    is the bonus token. Returns ``(accepted, committed)`` where
    ``committed == scores[:accepted+1]`` — always at least one token, at
    most k+1."""
    a = 0
    while a < len(draft) and int(draft[a]) == int(scores[a]):
        a += 1
    return a, [int(scores[j]) for j in range(a + 1)]


class AdaptiveK:
    """Per-sequence draft length: optimistic start at ``k_max``, grow on
    full accepts, halve on zero-accept steps, collapse to 0 after
    ``collapse_after`` consecutive zero-accept steps. Once collapsed the
    sequence speculates no more (its steps are plain 1-token decodes),
    bounding adversarial-draft overhead; partial accepts re-aim k at the
    observed accept length."""

    def __init__(self, k_max: int, collapse_after: int = 4):
        self.k_max = max(0, int(k_max))
        self.k = self.k_max
        self.collapse_after = max(1, int(collapse_after))
        self.zero_streak = 0
        self.collapsed = False

    def update(self, drafted: int, accepted: int) -> None:
        if drafted <= 0 or self.collapsed:
            return
        if accepted >= drafted:
            self.zero_streak = 0
            self.k = min(self.k + 1, self.k_max)
        elif accepted == 0:
            self.zero_streak += 1
            if self.zero_streak >= self.collapse_after:
                self.k = 0
                self.collapsed = True
            else:
                self.k = max(1, self.k // 2)
        else:
            self.zero_streak = 0
            self.k = max(1, min(self.k_max, accepted + 1))


class SpecStats:
    """Per-engine speculative counters (module vars aggregate the
    process; these keep A/B lanes and /serving snapshots per-engine)."""

    __slots__ = ("drafted", "accepted", "rejected", "bonus", "spec_steps",
                 "collapsed_seqs")

    def __init__(self):
        self.drafted = 0
        self.accepted = 0
        self.rejected = 0
        self.bonus = 0
        self.spec_steps = 0
        self.collapsed_seqs = 0

    def accept_rate(self) -> float:
        return self.accepted / self.drafted if self.drafted else 1.0

    def snapshot(self) -> dict:
        return {
            "drafted": self.drafted,
            "accepted": self.accepted,
            "rejected": self.rejected,
            "bonus": self.bonus,
            "spec_steps": self.spec_steps,
            "collapsed_seqs": self.collapsed_seqs,
            "accept_rate": round(self.accept_rate(), 4),
        }
