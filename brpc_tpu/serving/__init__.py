"""Continuous-batching inference serving plane.

The device-side pieces of this repo (STREAM-rate HBM staging, flash/ring
attention, the batch runtime, streams, priority lanes) compose here into
one LLM-shaped request path, the way bRPC's value was the composed
Server + batching + streaming + deadline stack rather than any single
mechanism:

- :mod:`brpc_tpu.serving.kv_cache` — paged KV-cache block manager over
  DeviceStore HBM handles (fixed-size blocks, per-sequence block tables,
  refcounts, watermark admission backpressure).
- :mod:`brpc_tpu.serving.model` — a toy transformer whose weights and KV
  pools are streamed into HBM by handle; flash-attention prefill and a
  ring-attention long-context path.
- :mod:`brpc_tpu.serving.engine` — the iteration-level scheduler: each
  step is a mixed prefill+decode batch under a token budget, new requests
  admitted *between* decode steps (continuous batching).
- :mod:`brpc_tpu.serving.service` — the LlmService RPC surface with
  per-request token streaming over the Stream API.
- :mod:`brpc_tpu.serving.mesh_model` + the sharded KV classes — the
  mesh-sharded lane: per-device KV pools over the serving mesh's ``dp``
  axis, shard_map prefill/decode that keeps each engine step at ONE
  fused launch + ONE host sync across the whole mesh.
- :mod:`brpc_tpu.serving.router` — client-side shard routing: Generate
  lands on the owning partition through PartitionChannel (prefix-hash
  routed when the fleet runs the prefix cache); shard failures come back
  retriable (EFAILEDSOCKET).
- :mod:`brpc_tpu.serving.prefix_cache` — radix tree over token prefixes
  mapping to refcounted KV block chains: admission forks the longest
  cached prefix (zero copies), completion commits blocks back
  (insert-or-share), eviction is watermark-aware LRU over refcount-1
  chains.
- :mod:`brpc_tpu.serving.migration` — live KV block-chain migration over
  the ``tpu://`` record lane: a prefill shard hands a just-prefilled
  sequence to a decode shard (disaggregated serving), and a dying shard
  drains its live sequences onto survivors, with the paged ledger's
  quiesce/export/adopt handshake keeping block ownership single-writer
  throughout.
- :mod:`brpc_tpu.serving.qos` — multi-tenant QoS: weighted fair-share
  admission (stride-scheduled token budget per tenant), per-tenant
  queue caps, and the closed-loop overload governor — an AutoLimiter
  gradient ceiling driven by the queue-wait series ring, shedding
  best-effort lanes first so a protected tenant survives an overload
  wave EOVERCROWDED-retriable instead of everyone drowning together.
- :mod:`brpc_tpu.serving.speculative` — the speculative-decoding draft
  lane: host-side prompt-lookup drafting (zero weights, zero device
  work, lint-pinned) feeding the model's one fused ``verify_step``
  launch per step; greedy acceptance keeps outputs bit-identical to
  plain decode while committing up to k+1 tokens per step.
"""

from brpc_tpu.serving.kv_cache import (KVCacheConfig, PagedKVCache,
                                       ShardedKVCache, ShardTable)
from brpc_tpu.serving.model import ModelConfig, TinyTransformer
from brpc_tpu.serving.engine import EngineConfig, ServingEngine, active_engines
from brpc_tpu.serving.prefix_cache import (PrefixCache, ShardedPrefixCache,
                                           build_prefix_cache,
                                           prefix_route_key)
from brpc_tpu.serving.qos import (QosConfig, QosGovernor, QosLimiter,
                                  TenantScheduler)
from brpc_tpu.serving.service import LlmServingService
from brpc_tpu.serving.speculative import (AdaptiveK, accept_longest_prefix,
                                          draft_tokens)


def __getattr__(name):
    # MeshTransformer / ShardedLlmChannel import lazily: they pull in the
    # mesh + combo-channel stacks, which plain single-device users of
    # this package never need at import time
    if name == "MeshTransformer":
        from brpc_tpu.serving.mesh_model import MeshTransformer
        return MeshTransformer
    if name == "ShardedLlmChannel":
        from brpc_tpu.serving.router import ShardedLlmChannel
        return ShardedLlmChannel
    # the migration plane imports lazily too: co-located deployments
    # never pay for the record-lane / fault wiring at import time
    if name == "KVMigrator":
        from brpc_tpu.serving.migration import KVMigrator
        return KVMigrator
    if name == "MigrationReceiver":
        from brpc_tpu.serving.migration import MigrationReceiver
        return MigrationReceiver
    raise AttributeError(name)


__all__ = [
    "KVCacheConfig", "PagedKVCache", "ShardedKVCache", "ShardTable",
    "ModelConfig", "TinyTransformer", "MeshTransformer",
    "EngineConfig", "ServingEngine", "active_engines",
    "PrefixCache", "ShardedPrefixCache", "build_prefix_cache",
    "prefix_route_key",
    "LlmServingService", "ShardedLlmChannel",
    "KVMigrator", "MigrationReceiver",
    "AdaptiveK", "accept_longest_prefix", "draft_tokens",
    "QosConfig", "QosGovernor", "QosLimiter", "TenantScheduler",
]
