"""KV block-chain migration over the tpu:// record lane.

The missing primitive of the disaggregated serving plane: ship a LIVE
sequence's paged KV between shards without re-prefilling a single token.
The control message (:class:`MigrateRequest`, the manifest: tokens so
far, chain geometry, refcount-audited length) rides a normal RPC; the
raw block bytes do NOT — they stream over the existing STREAM→HBM record
lane (``tpu/device_stream.py``): one 16-byte ``(handle, nbytes)`` record
per block, credit-windowed on staged HBM bytes, the same lane the bench
drives at 158.5 GB/s (BENCH_r05).

Ownership is a two-phase handshake with **no window where the chain is
owned by nobody or by both sides**:

1. source: ``quiesce_sequence`` (forced ledger audit; any write clears
   the mark) → ``export_chain`` → ``MigrateOpen`` with the manifest; the
   destination allocates a *staging* chain (blocks owned by the staging
   id throughout the transfer) and accepts the record stream.
2. source streams one record per block (k-half ‖ v-half, position
   order); the destination materializes each staged payload host-side
   (:func:`~brpc_tpu.tpu.device_stream.host_sink_options`), credits flow
   back as consumption happens.
3. when the last block lands the destination scatters the chain into its
   pools with ONE functional update per pool (``assert_writable`` first
   — staging blocks are refcount-1 by construction, and the
   cow-before-write lint holds here like everywhere else), adopts the
   chain under the destination sequence id (``adopt_sequence``,
   refcount++), frees the staging id, and parks the sequence in the
   destination engine.
4. ``MigrateCommit``'s reply IS the adoption ACK: only on
   ``accepted=True`` does the source ``release_exported`` its chain.
   Any failure — stream write error, drop fault, timeout, engine
   stopped — leaves the source chain intact (``unquiesce_sequence``)
   so the sequence falls back to local decode.

Fault points: ``serving.migrate.stall`` (delay_ms per block record on
the source) and ``serving.migrate.drop`` (destination tunnel dies
mid-migration: the receiver fails the transfer, frees its staging chain,
and the source keeps the sequence — chaos-gated with zero leaked blocks
on both pools).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from brpc_tpu import fault as _fault
from brpc_tpu import flags as _flags
from brpc_tpu.metrics.reducer import Adder
from brpc_tpu.metrics.status import PassiveStatus

_fault.register("serving.migrate.stall",
                "stall the source between migrated block records "
                "(delay_ms=)")
_fault.register("serving.migrate.drop",
                "kill the destination tunnel mid-migration (after=N "
                "records): the receiver fails the transfer and the "
                "source retains the chain")

g_serving_migrate_seqs = Adder("g_serving_migrate_seqs")
g_serving_migrate_blocks = Adder("g_serving_migrate_blocks")
g_serving_migrate_bytes = Adder("g_serving_migrate_bytes")
g_serving_migrate_failed = Adder("g_serving_migrate_failed")

_inflight_lock = threading.Lock()
_inflight = [0]  # migration state machines live in this process (out+in)


def _inflight_delta(d: int) -> None:
    with _inflight_lock:
        _inflight[0] += d


g_serving_migrate_inflight = PassiveStatus(lambda: _inflight[0]) \
    .expose("g_serving_migrate_inflight")
g_serving_migrate_inflight.prometheus_type = "gauge"


# --------------------------------------------------------- pool plumbing
def _pool_views(kv, table) -> Tuple[object, object]:
    """The (k, v) device arrays holding ``table``'s slots — the stacked
    per-mesh pools for a :class:`ShardedKVCache` chain, the flat pools
    otherwise."""
    shard = getattr(table, "shard", None)
    if shard is not None and hasattr(kv, "k_pools"):
        return kv.k_pools[shard], kv.v_pools[shard]
    return kv.k_pool, kv.v_pool


def _slot_index(table, block_size: int) -> np.ndarray:
    return np.concatenate([np.arange(b * block_size, (b + 1) * block_size)
                           for b in table])


def read_chain_blocks(kv, table, block_bytes: int) -> List[bytes]:
    """Serialize a chain's blocks for the record lane: ONE host
    materialization of the gathered slots per pool, then one
    ``k ‖ v`` payload per block, in table (= position) order."""
    bs = kv.block_size
    k, v = _pool_views(kv, table)
    idx = _slot_index(table, bs)
    k_host = np.ascontiguousarray(np.asarray(k[:, idx, :]))
    v_host = np.ascontiguousarray(np.asarray(v[:, idx, :]))
    out: List[bytes] = []
    for i in range(len(table)):
        s = slice(i * bs, (i + 1) * bs)
        payload = (k_host[:, s, :].tobytes() + v_host[:, s, :].tobytes())
        if len(payload) != block_bytes:
            raise AssertionError(
                f"block payload {len(payload)}B != manifest "
                f"{block_bytes}B")
        out.append(payload)
    return out


_scatter_jit = None


def _fused_scatter():
    """One donated dispatch for both pools — the eager two-``.at[].set``
    form costs two launches plus two full-pool copies, all spent while
    ``pool_gate`` is stalling the destination's decode loop."""
    global _scatter_jit
    if _scatter_jit is None:
        import jax

        def impl(kp, vp, idx, kn, vn):
            return kp.at[:, idx, :].set(kn), vp.at[:, idx, :].set(vn)

        _scatter_jit = jax.jit(impl, donate_argnums=(0, 1))
    return _scatter_jit


def write_chain_blocks(kv, staging_table, payloads: List[bytes],
                       ntokens: int) -> None:
    """Scatter received block payloads into the destination pools: one
    fused donated launch + one ``update_pools`` swap for the WHOLE
    chain. Staging blocks are exclusively owned (refcount 1) by the
    staging id — ``assert_writable`` proves it under the armed ledger
    before any slot is touched."""
    bs = kv.block_size
    layers, kv_dim = kv.layers, kv.kv_dim
    kv.assert_writable(staging_table, 0, len(staging_table) * bs)
    ks, vs = [], []
    for p in payloads:
        arr = np.frombuffer(p, dtype=np.float32).reshape(
            2, layers, bs, kv_dim)
        ks.append(arr[0])
        vs.append(arr[1])
    # pad the scatter to a power-of-two block count (re-writing block 0
    # with its own data) — chain lengths vary per migration, and a fresh
    # shape means a fresh jit trace stalling the decode loop ~50ms
    padn = max(4, 1 << (len(payloads) - 1).bit_length())
    ks.extend([ks[0]] * (padn - len(payloads)))
    vs.extend([vs[0]] * (padn - len(payloads)))
    k_new = np.concatenate(ks, axis=1)  # (layers, padn*bs, kv_dim)
    v_new = np.concatenate(vs, axis=1)
    idx = _slot_index(staging_table, bs)
    idx = np.concatenate(
        [idx] + [idx[:bs]] * (padn - len(payloads)))
    shard = getattr(staging_table, "shard", None)
    if shard is not None and hasattr(kv, "k_pools"):
        k2 = kv.k_pools.at[shard, :, idx, :].set(k_new)
        v2 = kv.v_pools.at[shard, :, idx, :].set(v_new)
    else:
        # the engine's own decode step donates the pools every launch,
        # so donation here follows the same ownership discipline (the
        # caller holds pool_gate — no concurrent reader of the old refs)
        k2, v2 = _fused_scatter()(kv.k_pool, kv.v_pool, idx,
                                  k_new, v_new)
    kv.update_pools(k2, v2)


def chain_block_bytes(kv) -> int:
    """Per-record payload size: k and v halves of one block."""
    return 2 * kv.layers * kv.block_size * kv.kv_dim * 4  # float32


# ---------------------------------------------------------------- source
class KVMigrator:
    """Source side: serialize + stream + release-on-ACK.

    One migrator per (engine, destination) pair; the engine calls
    :meth:`migrate` from its step loop (post-prefill handoff) or from
    the drain path in ``stop()`` (shard-death recovery). The sequence
    MUST be quiescent — no launch outstanding — which both call sites
    guarantee by construction; ``quiesce_sequence`` re-audits the ledger
    and arms the export gate regardless."""

    def __init__(self, dest_addr: str, dest_shard: int = 0,
                 window_bytes: Optional[int] = None,
                 timeout_s: Optional[float] = None,
                 channel_options=None):
        self.dest_addr = dest_addr
        self.dest_shard = dest_shard
        self._window = window_bytes
        self._timeout = timeout_s
        self._channel_options = channel_options
        self._channel = None
        self._lock = threading.Lock()
        self.seqs = 0
        self.blocks = 0
        self.bytes = 0
        self.failed = 0
        self.send_s = 0.0  # wall seconds inside stream+commit (gbps)

    # lazily built so constructing a migrator never dials anything
    def _stub(self):
        from brpc_tpu.proto import serving_pb2
        from brpc_tpu.rpc import Channel, ChannelOptions, Stub

        with self._lock:
            if self._channel is None:
                opts = self._channel_options or ChannelOptions(
                    protocol="trpc_std", timeout_ms=60000)
                ch = Channel(opts)
                ch.init(self.dest_addr)
                self._channel = ch
            return Stub(self._channel,
                        serving_pb2.DESCRIPTOR.services_by_name[
                            "LlmService"])

    def _window_bytes(self) -> int:
        if self._window is not None:
            return self._window
        return int(_flags.get("serving_migrate_window_mb")) << 20

    def _timeout_s(self) -> float:
        if self._timeout is not None:
            return self._timeout
        return float(_flags.get("serving_migrate_timeout_ms")) / 1000.0

    def migrate(self, seq, kv, recovery: bool = False) -> Optional[int]:
        """Ship ``seq``'s chain to the destination engine. Returns the
        adopted destination sequence id, or None — in which case the
        chain is STILL OWNED LOCALLY and the sequence can keep decoding
        here (fallback) or be aborted retriably by the caller."""
        from brpc_tpu.proto import serving_pb2
        from brpc_tpu.rpc import Controller, RpcError
        from brpc_tpu.rpc.stream import (StreamOptions, stream_close,
                                         stream_create)
        from brpc_tpu.tpu.device_stream import record_measure, send_handle

        timeout = self._timeout_s()
        _inflight_delta(1)
        sid = 0
        try:
            kv.quiesce_sequence(seq.seq_id)
            table, ntokens = kv.export_chain(seq.seq_id)
            block_bytes = chain_block_bytes(kv)
            manifest = serving_pb2.MigrateRequest(
                seq_id=seq.seq_id,
                prompt_tokens=[int(t) for t in seq.prompt],
                out_tokens=[int(t) for t in seq.out_tokens],
                max_new_tokens=seq.max_new_tokens,
                stop_token=seq.stop_token,
                ntokens=ntokens,
                n_blocks=len(table),
                block_size=kv.block_size,
                layers=kv.layers,
                kv_dim=kv.kv_dim,
                block_bytes=block_bytes,
                recovery=recovery)
            stub = self._stub()
            t0 = time.monotonic()
            sid = stream_create(StreamOptions(
                window_bytes=self._window_bytes(),
                measure=record_measure))
            cntl = Controller()
            cntl.stream_id = sid
            cntl.timeout_ms = int(timeout * 1000)
            ack = stub.MigrateOpen(manifest, controller=cntl)
            if not ack.accepted:
                raise RuntimeError(f"migrate rejected: {ack.message!r}")
            store = kv.store
            payloads = read_chain_blocks(kv, table, block_bytes)
            for payload in payloads:
                _fault.maybe_sleep(_fault.hit("serving.migrate.stall"))
                h, n = store.put(payload)
                rc = send_handle(sid, h, n, timeout=timeout)
                if rc != 0:
                    store.free(h)
                    raise RuntimeError(
                        f"migration stream write failed rc={rc}")
            cntl2 = Controller()
            cntl2.timeout_ms = int(timeout * 1000)
            ack2 = stub.MigrateCommit(
                serving_pb2.MigrateCommitRequest(seq_id=seq.seq_id),
                controller=cntl2)
            if not ack2.accepted:
                raise RuntimeError(
                    f"migrate commit rejected: {ack2.message!r}")
            # the destination ACKed adoption — ownership moves NOW
            freed = kv.release_exported(seq.seq_id)
            dt = time.monotonic() - t0
            with self._lock:
                self.seqs += 1
                self.blocks += len(table)
                self.bytes += block_bytes * len(table)
                self.send_s += dt
            g_serving_migrate_seqs.put(1)
            g_serving_migrate_blocks.put(len(table))
            g_serving_migrate_bytes.put(block_bytes * len(table))
            del freed
            return int(ack2.dest_seq_id)
        except (RpcError, RuntimeError, AssertionError, KeyError,
                OSError):
            # the chain never left local ownership: un-arm the export
            # gate and let the caller fall back to local decode
            try:
                kv.unquiesce_sequence(seq.seq_id)
            except Exception:
                pass
            with self._lock:
                self.failed += 1
            g_serving_migrate_failed.put(1)
            return None
        finally:
            if sid:
                stream_close(sid)
            _inflight_delta(-1)

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            gbps = (self.bytes / self.send_s / 1e9) if self.send_s else 0.0
            return {"dest": self.dest_addr, "dest_shard": self.dest_shard,
                    "seqs": self.seqs, "blocks": self.blocks,
                    "bytes": self.bytes, "failed": self.failed,
                    "gbps": gbps}


# ------------------------------------------------------------- receiver
class _Inbound:
    """One in-flight inbound migration's state machine."""

    __slots__ = ("manifest", "staging_id", "staging_table", "payloads",
                 "state", "event", "dest_seq_id", "message", "lock",
                 "t_open")

    def __init__(self, manifest, staging_id, staging_table):
        self.manifest = manifest
        self.staging_id = staging_id
        self.staging_table = staging_table
        self.payloads: List[bytes] = []
        self.state = "open"  # open -> done | failed
        self.event = threading.Event()
        self.dest_seq_id = 0
        self.message = ""
        self.lock = threading.Lock()
        self.t_open = time.monotonic()


class MigrationReceiver:
    """Destination side: staging-alloc → buffer stream → scatter →
    adopt → park in the engine. Owned by :class:`LlmServingService`;
    the ``MigrateOpen``/``MigrateCommit`` handlers delegate here."""

    def __init__(self, engine):
        self.engine = engine
        self._lock = threading.Lock()
        self._inbound: Dict[int, _Inbound] = {}
        self.seqs_in = 0
        self.failed_in = 0

    # ------------------------------------------------------------- open
    def open(self, cntl, request):
        from brpc_tpu.proto import serving_pb2
        from brpc_tpu.rpc.stream import stream_accept
        from brpc_tpu.serving.kv_cache import KVCacheFull
        from brpc_tpu.tpu.device_stream import host_sink_options

        kv = self.engine.kv

        def reject(msg: str):
            return serving_pb2.MigrateAck(accepted=False, message=msg)

        meta = getattr(cntl, "_srv_meta", None)
        sid = 0
        if meta is not None and meta.stream_settings.stream_id:
            sid = meta.stream_settings.stream_id
        if not sid:
            return reject("migration needs a record stream")
        if (request.block_size != kv.block_size
                or request.layers != kv.layers
                or request.kv_dim != kv.kv_dim):
            return reject(
                f"geometry mismatch: got bs={request.block_size}/"
                f"L={request.layers}/d={request.kv_dim}, pool has "
                f"bs={kv.block_size}/L={kv.layers}/d={kv.kv_dim}")
        if request.block_bytes != chain_block_bytes(kv):
            return reject(f"block_bytes {request.block_bytes} != "
                          f"{chain_block_bytes(kv)}")
        if request.n_blocks != kv.blocks_for(request.ntokens):
            return reject(f"{request.n_blocks} blocks cannot carry "
                          f"{request.ntokens} tokens")
        if not self.engine.running:
            return reject("destination engine is not running")
        # the staging id owns the blocks for the whole transfer; engine
        # sequence ids start at 1, so the negated source id never
        # collides with a live local table
        staging_id = -(abs(int(request.seq_id)) + 1)
        try:
            staging_table = kv.alloc_sequence(staging_id, request.ntokens)
        except (KVCacheFull, ValueError) as e:
            return reject(f"staging alloc failed: {e}")
        inb = _Inbound(request.__class__.FromString(
            request.SerializeToString()), staging_id, staging_table)
        with self._lock:
            self._inbound[int(request.seq_id)] = inb
        _inflight_delta(1)
        window = int(_flags.get("serving_migrate_window_mb")) << 20

        def sink(data: bytes) -> None:
            self._on_block(inb, data)

        def on_closed(_sid: int) -> None:
            # producer went away without completing: fail + free staging
            self._fail(inb, "stream closed before commit")

        stream_accept(cntl, host_sink_options(
            sink, window, store=kv.store, on_closed=on_closed))
        return serving_pb2.MigrateAck(accepted=True,
                                      blocks=request.n_blocks)

    # ------------------------------------------------------- stream sink
    def _on_block(self, inb: _Inbound, data: bytes) -> None:
        drop = _fault.hit("serving.migrate.drop")
        with inb.lock:
            if inb.state != "open":
                return  # already failed/done — discard stragglers
            if drop is not None:
                pass  # fall through to the failure path below
            elif len(data) != inb.manifest.block_bytes:
                drop = {"reason": f"short block ({len(data)}B)"}
            else:
                inb.payloads.append(data)
                if len(inb.payloads) < inb.manifest.n_blocks:
                    return
        if drop is not None:
            self._fail(inb, str(drop.get("reason",
                                         "destination tunnel killed")))
            return
        self._commit_inbound(inb)

    def _commit_inbound(self, inb: _Inbound) -> None:
        """All blocks landed: scatter, adopt, park. Runs on the stream's
        receive thread — the scatter is one fused update per pool."""
        kv = self.engine.kv
        m = inb.manifest
        try:
            # pool_gate keeps the scatter off the step loop's donated
            # buffers — an unsynchronized .at[].set races the decode
            # launch and dies with "buffer has been deleted or donated"
            with self.engine.pool_gate:
                write_chain_blocks(kv, inb.staging_table, inb.payloads,
                                   m.ntokens)
            seq = self.engine.make_adopted_sequence(
                np.asarray(list(m.prompt_tokens), dtype=np.int32),
                list(m.out_tokens), m.max_new_tokens, m.stop_token)
            kv.adopt_sequence(seq.seq_id, inb.staging_table, m.ntokens)
            if not self.engine.adopt_migrated(seq, recovery=m.recovery):
                kv.free_sequence(seq.seq_id)
                raise RuntimeError("destination engine refused adoption")
        except Exception as e:  # noqa: BLE001 — any failure = clean abort
            self._fail(inb, f"adoption failed: {e}")
            return
        kv.free_sequence(inb.staging_id)  # chain now owned by seq alone
        with inb.lock:
            inb.state = "done"
            inb.dest_seq_id = seq.seq_id
        with self._lock:
            self.seqs_in += 1
        _inflight_delta(-1)
        inb.event.set()

    def _fail(self, inb: _Inbound, msg: str) -> None:
        with inb.lock:
            if inb.state != "open":
                return
            inb.state = "failed"
            inb.message = msg
        self.engine.kv.free_sequence(inb.staging_id)  # zero leaked blocks
        with self._lock:
            self.failed_in += 1
            for key, v in list(self._inbound.items()):
                if v is inb:
                    del self._inbound[key]
        _inflight_delta(-1)
        g_serving_migrate_failed.put(1)
        inb.event.set()

    # ------------------------------------------------------------ commit
    def commit(self, cntl, request):
        from brpc_tpu.proto import serving_pb2

        with self._lock:
            inb = self._inbound.pop(int(request.seq_id), None)
        if inb is None:
            return serving_pb2.MigrateAck(
                accepted=False, message=f"no open migration for "
                                        f"sequence {request.seq_id}")
        timeout = float(_flags.get("serving_migrate_timeout_ms")) / 1000.0
        if not inb.event.wait(timeout):
            self._fail(inb, "migration timed out awaiting blocks")
        with inb.lock:
            ok = inb.state == "done"
            return serving_pb2.MigrateAck(
                accepted=ok, dest_seq_id=inb.dest_seq_id,
                blocks=len(inb.payloads), message=inb.message)

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {"seqs_in": self.seqs_in, "failed_in": self.failed_in,
                    "pending_in": len(self._inbound)}
