"""Iteration-level scheduler: continuous batching over the paged KV cache.

Orca-style scheduling mapped onto this repo's server: the engine thread
runs a step loop where each step is a mixed prefill+decode batch under a
token budget, and new requests are admitted *between* decode steps —
a long generation never blocks a short one behind it (continuous
batching). ``scheduling="static"`` keeps the classic gang behavior (admit
a batch, drain it fully, admit the next) purely as the bench comparison
lane.

Admission is where policy concentrates, mirroring the server's own
front door:

- **deadline** — a request whose client budget is already spent
  (``cntl.deadline_mono``, stamped by server-side deadline enforcement)
  is rejected with ERPCTIMEDOUT before it ever holds KV blocks; the same
  re-check the batch runtime does at enqueue.
- **KV watermark** — :meth:`PagedKVCache.can_admit` keeps decode headroom
  above the watermark; rejects surface EOVERCROWDED, which the tunnel
  retry policy already backs off on.
- **queue depth** — a bounded waiting queue, EOVERCROWDED past the cap.

Each step issues ONE fused device program for the whole decode batch and
one per prefill (see serving/model.py) — dispatch coalescing at the step
level. Tokens are host-materialized exactly once per step; per-token
streaming writes fan out of that single sync (tpulint's
``no-per-token-host-sync`` rule keeps it that way).

Streaming: a request that arrived with stream settings gets TokenDelta
frames as steps complete, so TTFT is a stream-arrival time, decoupled
from the RPC response (which carries the full token list at completion).

Fault points: ``serving.decode.stall`` (injects latency into the step
loop) and ``serving.kv.exhaust`` (forces admission rejections). A tunnel
kill mid-generation is detected via the request socket's failed flag;
in-flight sequences are aborted with EFAILEDSOCKET (retriable) and every
KV block returns to the pool — ``assert_idle`` audits that, the way the
CreditLedger audits window teardown.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Deque, Dict, List, Optional

import numpy as np

from brpc_tpu import fault as _fault
from brpc_tpu.metrics.latency_recorder import LatencyRecorder
from brpc_tpu.metrics.reducer import Adder
from brpc_tpu.metrics.status import PassiveStatus
from brpc_tpu.profiling import registry as _prof
from brpc_tpu.rpc import errors
from brpc_tpu.serving import qos as _qos
from brpc_tpu.serving import speculative as _spec
from brpc_tpu.serving.kv_cache import KVCacheFull, PagedKVCache
from brpc_tpu.serving.model import TinyTransformer

_fault.register("serving.decode.stall",
                "stall the serving engine's decode step (delay_ms=)")
_fault.register("serving.kv.exhaust",
                "force KV-pool admission rejections (EOVERCROWDED)")

g_serving_steps = Adder("g_serving_steps")
g_serving_tokens = Adder("g_serving_tokens")
g_serving_prefill_tokens = Adder("g_serving_prefill_tokens")
g_serving_admitted = Adder("g_serving_admitted")
g_serving_rejected = Adder("g_serving_rejected")
g_serving_aborted = Adder("g_serving_aborted")
g_serving_completed = Adder("g_serving_completed")
g_serving_deadline_rejects = Adder("g_serving_deadline_rejects")
g_serving_step = LatencyRecorder().expose("g_serving_step")
g_serving_ttft = LatencyRecorder().expose("g_serving_ttft")
g_serving_itl = LatencyRecorder().expose("g_serving_itl")

_engines: List["ServingEngine"] = []
_engines_lock = threading.Lock()


def active_engines() -> List["ServingEngine"]:
    with _engines_lock:
        return [e for e in _engines if e.running]


def _sum_engines(fn) -> int:
    return sum(fn(e) for e in active_engines())


g_serving_queue_depth = PassiveStatus(
    lambda: _sum_engines(lambda e: e.queue_depth)) \
    .expose("g_serving_queue_depth")
g_serving_queue_depth.prometheus_type = "gauge"
g_serving_running = PassiveStatus(
    lambda: _sum_engines(lambda e: e.running_count)) \
    .expose("g_serving_running")
g_serving_running.prometheus_type = "gauge"


SCHED_CONTINUOUS = "continuous"
SCHED_STATIC = "static"


ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"
ROLE_BOTH = "both"


class EngineConfig:
    def __init__(self, max_batch: int = 8, token_budget: int = 512,
                 max_queue: int = 64, max_new_tokens_cap: int = 512,
                 scheduling: str = SCHED_CONTINUOUS,
                 idle_wait_s: float = 0.05, role: str = ROLE_BOTH,
                 spec_k: int = 0, spec_ngram: int = 3,
                 spec_collapse_after: int = 4, qos=None):
        if scheduling not in (SCHED_CONTINUOUS, SCHED_STATIC):
            raise ValueError(f"unknown scheduling {scheduling!r}")
        if role not in (ROLE_PREFILL, ROLE_DECODE, ROLE_BOTH):
            raise ValueError(f"unknown role {role!r}")
        if spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {spec_k}")
        self.max_batch = max_batch
        # per-step budget over prefill tokens + one decode token per
        # running sequence — the Orca iteration-level knob
        self.token_budget = token_budget
        self.max_queue = max_queue
        self.max_new_tokens_cap = max_new_tokens_cap
        self.scheduling = scheduling
        self.idle_wait_s = idle_wait_s
        # disaggregated serving: a "prefill" engine runs prefill then
        # migrates each chain to its KVMigrator's destination (falling
        # back to local decode when migration fails); a "decode" engine
        # mostly adopts migrated sequences but still accepts fresh
        # submissions (roles are scheduling placement, not capability)
        self.role = role
        # speculative decoding: spec_k > 0 turns each decode step into
        # draft-k + one fused verify (serving/speculative.py); per
        # sequence the AdaptiveK controller shrinks k on rejection and
        # collapses to plain decode after spec_collapse_after
        # consecutive zero-accept steps
        self.spec_k = spec_k
        self.spec_ngram = spec_ngram
        self.spec_collapse_after = spec_collapse_after
        # multi-tenant QoS: a serving.qos.QosConfig turns admission into
        # weighted fair share + the closed-loop overload governor; None
        # keeps the single-tenant FIFO path byte-for-byte as before
        self.qos = qos


STATE_WAITING = "waiting"
STATE_RUNNING = "running"
STATE_DONE = "done"


class Sequence:
    """One in-flight generation request."""

    _ids = [0]
    _ids_lock = threading.Lock()

    def __init__(self, prompt: np.ndarray, max_new_tokens: int,
                 stop_token: int = 0, cntl=None, done=None,
                 stream_id: int = 0, tenant_id: str = "",
                 priority: int = 0):
        with Sequence._ids_lock:
            Sequence._ids[0] += 1
            self.seq_id = Sequence._ids[0]
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.stop_token = stop_token
        self.cntl = cntl
        self.done = done
        self.stream_id = stream_id
        # QoS identity (decoded off RequestMeta by the dispatch paths):
        # which fair-share lane this bills, how protected under shedding
        self.tenant_id = tenant_id
        self.priority = priority
        self.state = STATE_WAITING
        self.out_tokens: List[int] = []
        # tokens covered by a forked prefix-cache chain (block-aligned);
        # prefill runs only the suffix past this point
        self.prefix_len = 0
        self.t_submit = time.monotonic()
        self.t_first_token = 0.0
        self.t_last_token = 0.0
        self.finish_reason = ""
        # disaggregation: a migrated-in sequence is "adopted" and decodes
        # with no client bound until a stage-2/retry Generate attaches;
        # handoff_base marks how many out_tokens the prefill shard
        # already returned (a resume attach replies only the suffix)
        self.adopted = False
        self.handoff_base = 0
        self.resume_attach = False
        self._attached = False
        self._deferred: Optional[tuple] = None
        self.t_adopted = 0.0
        # speculative decoding: per-sequence adaptive draft-length
        # controller, created lazily by the engine when spec_k > 0
        self.spec = None

    @property
    def pos(self) -> int:
        """0-based position of the NEXT token to append."""
        return len(self.prompt) + len(self.out_tokens) - 1

    def context_len(self) -> int:
        return len(self.prompt) + len(self.out_tokens)


class ServingEngine:
    def __init__(self, model: TinyTransformer, kv: Optional[PagedKVCache] = None,
                 config: Optional[EngineConfig] = None, prefix_cache=None):
        self.model = model
        self.kv = kv if kv is not None else model.kv
        self.config = config or EngineConfig()
        # radix prefix cache: None auto-builds over the pool (gated per
        # admission by the serving_prefix_cache_enabled flag), False
        # disables outright (cold A/B lanes, oracle reference engines)
        if prefix_cache is None and hasattr(model, "prefill_suffix"):
            from brpc_tpu.serving.prefix_cache import build_prefix_cache
            prefix_cache = build_prefix_cache(self.kv)
        self.prefix = prefix_cache or None
        self._cv = threading.Condition()
        self._waiting: Deque[Sequence] = collections.deque()
        self._running: List[Sequence] = []
        self._thread: Optional[threading.Thread] = None
        self.running = False
        self.steps = 0
        self.tokens_generated = 0
        self.last_step_us = 0.0
        self._occupancy_sum = 0
        # disaggregation plumbing: the migrator ships chains OUT (set via
        # set_migrator), the receiver (installed by LlmServingService)
        # adopts chains IN; _adopted parks migrated-in sequences until a
        # stage-2/retry Generate attaches a client to them
        self.migrator = None
        self._migration_rx = None
        self._adopted: Dict[int, Sequence] = {}
        # adopted chains wait here for a max_batch slot — direct entry
        # into _running would let migration bursts inflate the decode
        # batch past any size admission ever dispatches
        self._adopted_pending: Deque[Sequence] = collections.deque()
        # serializes pool mutation between the step loop (prefill/decode
        # donate the pool buffers) and migration adoption's host-side
        # scatter — concurrent writers see deleted/donated buffers
        self.pool_gate = threading.Lock()
        self._recover_index: Dict[tuple, Deque[int]] = {}
        # per-engine counters the disaggregation oracle and bench need
        # (the g_serving_* fleet vars cannot isolate one engine)
        self.prefill_tokens = 0
        self.ttft_samples: List[float] = []  # us, bounded
        self.itl_samples: List[float] = []   # us, bounded
        # speculative decoding: per-engine counters (the A/B bench and
        # the oracle need per-lane isolation, like the fields above)
        self.spec_stats = (_spec.SpecStats()
                           if self.config.spec_k > 0 else None)
        # multi-tenant QoS: the fair-share scheduler replaces _waiting
        # as the queue substrate and the governor closes the overload
        # loop from the sampler tick (installed in start())
        self.qos = (_qos.TenantScheduler(self.config.qos, engine=self)
                    if self.config.qos is not None else None)
        self._qos_governor = (_qos.QosGovernor(self)
                              if self.qos is not None else None)
        # per-shard decode attribution: shard -> [steps, total_us,
        # last_us, seq_steps] (only shards with live sequences tick)
        self._shard_step: Dict[int, List[float]] = {}
        with _engines_lock:
            _engines.append(self)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "ServingEngine":
        with self._cv:
            if self.running:
                return self
            self.running = True
        if self._qos_governor is not None:
            # close the loop: the governor rides the 1 Hz sampler tick,
            # sampling the queue-wait series ring the sweep just filled
            from brpc_tpu.metrics.series import (ensure_series_installed,
                                                 global_series)

            ensure_series_installed()
            hooks = global_series().post_tick_hooks
            if self._qos_governor not in hooks:
                hooks.append(self._qos_governor)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="brpc-serving-engine")
        self._thread.start()
        return self

    def set_migrator(self, migrator) -> "ServingEngine":
        """Install the outbound KV migrator (serving/migration.py). A
        prefill-role engine hands every prefilled chain to it from the
        step loop; ANY engine with one drains live sequences to the
        destination on stop() instead of aborting them from scratch."""
        self.migrator = migrator
        return self

    def stop(self, abort_code: int = errors.ELOGOFF) -> None:
        with self._cv:
            if not self.running:
                return
            self.running = False
            self._cv.notify_all()
        if self._qos_governor is not None:
            from brpc_tpu.metrics.series import global_series

            try:
                global_series().post_tick_hooks.remove(self._qos_governor)
            except ValueError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        # shard-death recovery: with a migrator installed, live chains
        # move to the survivor (the step loop is parked, so every
        # sequence is quiescent) instead of dying retry-from-scratch
        if self.migrator is not None:
            self._drain_migrate()
        # fan a retriable error to anything still in flight, then prove
        # the pool whole — the CreditLedger teardown discipline
        self._abort_all_locked_out(abort_code, "engine stopped")
        with self._cv:
            self._adopted.clear()
            self._recover_index.clear()
        if self.prefix is not None:
            # release every tree hold so assert_idle sees the pool whole
            self.prefix.clear()
        with _engines_lock:
            if self in _engines:
                _engines.remove(self)

    # ------------------------------------------------------------ admission
    def submit(self, prompt: np.ndarray, max_new_tokens: int,
               stop_token: int = 0, cntl=None, done=None,
               stream_id: int = 0,
               resume_seq_id: int = 0, tenant_id: Optional[str] = None,
               priority: Optional[int] = None,
               _synthetic: bool = False) -> "tuple[int, Optional[Sequence]]":
        """Admission front door (runs on the RPC thread). Returns
        (error_code, seq): 0 + the queued sequence, or a reject code the
        caller surfaces through cntl.set_failed.

        ``resume_seq_id`` attaches to a migrated-in sequence (two-stage
        disaggregated dispatch: the stage-1 handoff reply named it) —
        no admission, no allocation, the chain is already here.

        ``tenant_id``/``priority`` default to the wire identity on
        ``cntl`` (RequestMeta → dispatch → cntl); pass them explicitly
        when no controller carries them. ``_synthetic`` marks burst
        clones fabricated by the serving.qos.burst fault point so they
        cannot re-trigger it."""
        if resume_seq_id:
            with self._cv:
                seq = self._adopted.get(resume_seq_id)
            if seq is None:
                return errors.EREQUEST, None
            return self._bind_attach(seq, cntl, done, stream_id,
                                     resume=True)
        if max_new_tokens < 1:
            return errors.EREQUEST, None
        max_new_tokens = min(max_new_tokens, self.config.max_new_tokens_cap)
        if len(prompt) < 1 or (len(prompt) + max_new_tokens
                               > self.model.config.max_context):
            return errors.EREQUEST, None
        # shard-death recovery: a retried request whose sequence was
        # drain-migrated here picks up the live generation instead of
        # re-prefilling a single token
        if self._recover_index:
            cand = self._recover_match(prompt, max_new_tokens, stop_token)
            if cand is not None:
                return self._bind_attach(cand, cntl, done, stream_id,
                                         resume=False)
        # deadline at admission (PR 4's server-side enforcement, re-checked
        # here exactly like the batch runtime re-checks at enqueue)
        deadline = getattr(cntl, "deadline_mono", 0.0) if cntl else 0.0
        if deadline and time.monotonic() >= deadline:
            g_serving_deadline_rejects.put(1)
            g_serving_rejected.put(1)
            return errors.ERPCTIMEDOUT, None
        if _fault.hit("serving.kv.exhaust") is not None:
            self.kv.note_rejected()
            g_serving_rejected.put(1)
            return errors.EOVERCROWDED, None
        if tenant_id is None:
            tenant_id = getattr(cntl, "tenant_id", "") if cntl else ""
        if priority is None:
            priority = getattr(cntl, "priority", 0) if cntl else 0
        if self.qos is not None and not _synthetic:
            # chaos: inflate this tenant's arrival rate at admission —
            # each real submit fans out factor-1 synthetic clones that
            # bill the same lane (and shed the same way)
            burst = _fault.hit("serving.qos.burst", tenant=tenant_id)
            if burst is not None:
                for _ in range(max(0, int(burst.get("factor", 2)) - 1)):
                    self.submit(prompt, max_new_tokens,
                                stop_token=stop_token,
                                tenant_id=tenant_id, priority=priority,
                                _synthetic=True)
        with self._cv:
            if not self.running:
                return errors.ELOGOFF, None
            if self.qos is None \
                    and len(self._waiting) >= self.config.max_queue:
                g_serving_rejected.put(1)
                return errors.EOVERCROWDED, None
            # watermark backpressure counts queued-but-unadmitted prefill
            # tokens too, else a burst overcommits the pool before the
            # step loop catches up. The sequence exists before the check
            # so a sharded pool can route it (route_key -> owning shard's
            # watermark; the single-pool cache ignores the key).
            seq = Sequence(prompt, max_new_tokens, stop_token, cntl, done,
                           stream_id, tenant_id=tenant_id,
                           priority=priority)
            queued = sum(s.context_len() for s in self._iter_waiting())
            need = queued + len(prompt)
            shard = None
            if self.prefix is not None:
                # a cached prefix's blocks are already counted in pool
                # occupancy — only the suffix is new demand; prefix-hash
                # placement beats the seq-id route so the hit lands on
                # the shard holding the chain
                shard = self.prefix.route_shard(prompt)
                need = queued + max(1, len(prompt)
                                    - self.prefix.match_len(prompt))
            if not self.kv.can_admit(need, route_key=seq.seq_id,
                                     shard=shard):
                # before rejecting, ask the tree to give back LRU
                # refcount-1 chains — EOVERCROWDED semantics unchanged,
                # the watermark just sees fewer cache-held blocks
                if not (self.prefix is not None
                        and self.prefix.evict_for_admission(
                            need, shard=shard, route_key=seq.seq_id)
                        and self.kv.can_admit(need, route_key=seq.seq_id,
                                              shard=shard)):
                    self.kv.note_rejected()
                    g_serving_rejected.put(1)
                    return errors.EOVERCROWDED, None
            if self.qos is not None:
                # weighted fair-share lane: enqueue re-evaluates the QoS
                # admission predicate (deadline + tenant cap + limiter
                # ceiling) under the lock — check and append are one
                # decision
                code = self.qos.enqueue(seq)
                if code != 0:
                    if code == errors.ERPCTIMEDOUT:
                        g_serving_deadline_rejects.put(1)
                    g_serving_rejected.put(1)
                    return code, None
            else:
                self._waiting.append(seq)
            self._cv.notify()
        return 0, seq

    def _iter_waiting(self):
        """Every queued-but-unadmitted sequence (lock held): the FIFO
        deque, or the fair-share lanes when QoS is on."""
        if self.qos is not None:
            return self.qos.iter_waiting()
        return iter(self._waiting)

    @property
    def queue_depth(self) -> int:
        if self.qos is not None:
            return self.qos.total_depth()
        return len(self._waiting)

    @property
    def running_count(self) -> int:
        return len(self._running)

    # -------------------------------------------------- migration adoption
    def make_adopted_sequence(self, prompt: np.ndarray,
                              out_tokens: List[int], max_new_tokens: int,
                              stop_token: int = 0) -> Sequence:
        """Fabricate the destination-side Sequence for a migrated chain.
        The caller (MigrationReceiver) adopts the KV under the returned
        ``seq_id`` BEFORE handing it to :meth:`adopt_migrated` — the
        sequence must never be visible to the step loop without blocks."""
        seq = Sequence(prompt, max_new_tokens, stop_token)
        seq.out_tokens = list(out_tokens)
        seq.handoff_base = len(out_tokens)
        seq.adopted = True
        seq.state = STATE_RUNNING
        # out_tokens is never empty post-prefill: TTFT was recorded by
        # the source shard. t_last_token stays 0 so the first local
        # decode RESETS the ITL clock — transfer + slot-wait latency
        # belongs to the handoff, not this engine's inter-token gap
        seq.t_first_token = time.monotonic()
        seq.t_last_token = 0.0
        return seq

    def adopt_migrated(self, seq: Sequence, recovery: bool = False) -> bool:
        """Queue a migrated-in sequence for decode (its KV is already
        adopted). The step loop drains it into the running set under the
        same max_batch cap as admission; tokens buffer on the sequence
        until a client attaches. ``recovery`` additionally indexes it
        for prompt-match attach (shard-death retry traffic has no
        resume_seq_id — the original reply never arrived)."""
        with self._cv:
            if not self.running:
                return False
            seq.t_adopted = time.monotonic()
            self._adopted[seq.seq_id] = seq
            if recovery:
                key = (tuple(int(t) for t in seq.prompt),
                       int(seq.max_new_tokens), int(seq.stop_token))
                self._recover_index.setdefault(
                    key, collections.deque()).append(seq.seq_id)
            self._adopted_pending.append(seq)
            self._cv.notify()
        return True

    def _recover_match(self, prompt: np.ndarray, max_new_tokens: int,
                       stop_token: int) -> Optional[Sequence]:
        key = (tuple(int(t) for t in prompt), int(max_new_tokens),
               int(stop_token))
        with self._cv:
            dq = self._recover_index.get(key)
            while dq:
                rid = dq.popleft()
                if not dq:
                    self._recover_index.pop(key, None)
                    dq = None
                cand = self._adopted.get(rid)
                if cand is not None and not cand._attached:
                    return cand
        return None

    def _bind_attach(self, seq: Sequence, cntl, done, stream_id: int,
                     resume: bool) -> "tuple[int, Optional[Sequence]]":
        """Attach a client to a parked migrated sequence. Live sequences
        stream the tokens generated since the handoff point and keep
        decoding; already-finished ones complete the RPC immediately
        from the deferred result."""
        with self._cv:
            if seq._attached or seq.done is not None:
                return errors.EREQUEST, None
            seq._attached = True
            seq.resume_attach = resume
            seq.cntl = cntl
            seq.stream_id = stream_id
            deferred = seq._deferred
            base = seq.handoff_base if resume else 0
            replay = list(seq.out_tokens[base:])
            finished = seq.state == STATE_DONE
            if deferred is None:
                seq.done = done
            else:
                self._adopted.pop(seq.seq_id, None)
        if deferred is not None:
            code, reason = deferred
            try:
                if code != 0 and cntl is not None:
                    cntl.set_failed(code, reason)
                    done(None)
                else:
                    done(self._response_for(seq))
            except Exception:
                pass
            return 0, seq
        if replay and stream_id:
            # catch the client up on tokens decoded before it attached
            self._stream_delta(seq, replay, finished)
        return 0, seq

    # ------------------------------------------------------------ step loop
    def _loop(self) -> None:
        _prof.register_current_thread("serving")
        try:
            while True:
                with self._cv:
                    while (self.running and not self._waiting
                           and not self._running
                           and not self._adopted_pending
                           and (self.qos is None
                                or self.qos.total_depth() == 0)):
                        self._cv.wait(self.config.idle_wait_s)
                    if not self.running:
                        return
                    admitted = self._admit_locked()
                if not admitted and not self._running:
                    # waiting work exists but the pool is full — let
                    # in-flight frees land instead of spinning the step
                    time.sleep(0.002)
                    continue
                try:
                    with self.pool_gate:
                        self._step(admitted)
                except Exception as e:  # engine must survive a bad step
                    for seq in list(self._running):
                        self._finish(seq, errors.EINTERNAL,
                                     f"step failed: {e}")
                    self._running = []
        finally:
            _prof.unregister_current_thread()

    def _admit_locked(self) -> List[Sequence]:
        """Pull waiting sequences into the running set — called between
        steps with the lock held. Continuous mode refills whenever a slot
        and budget exist; static mode only when the gang drained."""
        cfg = self.config
        if cfg.scheduling == SCHED_STATIC and self._running:
            return []
        admitted: List[Sequence] = []
        # migrated-in chains first (already prefilled, zero prefill
        # cost) — capped by max_batch so the decode batch never exceeds
        # a size admission itself would dispatch
        while self._adopted_pending and len(self._running) < cfg.max_batch:
            seq = self._adopted_pending.popleft()
            self._running.append(seq)
            admitted.append(seq)
        # accepted-length is variable spend: a speculating sequence can
        # commit up to 1 + k tokens per step, so it reserves that many
        # budget slots, not one (a collapsed sequence is back to 1)
        budget = cfg.token_budget - sum(self._decode_cost(s)
                                        for s in self._running)
        if self.qos is not None:
            return self._admit_qos_locked(admitted, budget)
        while (self._waiting and len(self._running) < cfg.max_batch
               and budget >= self._prefill_cost(self._waiting[0])):
            seq = self._waiting[0]
            deadline = (getattr(seq.cntl, "deadline_mono", 0.0)
                        if seq.cntl else 0.0)
            if deadline and time.monotonic() >= deadline:
                self._waiting.popleft()
                g_serving_deadline_rejects.put(1)
                self._finish(seq, errors.ERPCTIMEDOUT,
                             "deadline expired in serving queue")
                continue
            try:
                self._alloc_for(seq)
            except KVCacheFull:
                # one retry after asking the tree for its LRU refcount-1
                # chains; still full means genuinely out of headroom
                if not (self.prefix is not None
                        and self.prefix.evict_for_admission(
                            seq.context_len(), route_key=seq.seq_id)):
                    break  # keep FIFO order; retry next step
                try:
                    self._alloc_for(seq)
                except KVCacheFull:
                    break
            self._waiting.popleft()
            budget -= self._prefill_cost(seq)
            seq.state = STATE_RUNNING
            self._running.append(seq)
            admitted.append(seq)
            g_serving_admitted.put(1)
        return admitted

    def _admit_qos_locked(self, admitted: List[Sequence],
                          budget: int) -> List[Sequence]:
        """Fair-share admission: each pull serves the backlogged tenant
        with the smallest virtual clock (stride scheduling meters the
        step's token budget by weight); the deadline is re-checked per
        sequence exactly as the FIFO path does, and a pool-full head
        keeps its turn for the next step's full budget."""
        cfg = self.config
        while len(self._running) < cfg.max_batch:
            seq = self.qos.peek(budget, self._prefill_cost)
            if seq is None:
                break
            deadline = (getattr(seq.cntl, "deadline_mono", 0.0)
                        if seq.cntl else 0.0)
            if deadline and time.monotonic() >= deadline:
                self.qos.drop(seq)
                g_serving_deadline_rejects.put(1)
                self._finish(seq, errors.ERPCTIMEDOUT,
                             "deadline expired in serving queue")
                continue
            try:
                self._alloc_for(seq)
            except KVCacheFull:
                if not (self.prefix is not None
                        and self.prefix.evict_for_admission(
                            seq.context_len(), route_key=seq.seq_id)):
                    break
                try:
                    self._alloc_for(seq)
                except KVCacheFull:
                    break
            cost = self._prefill_cost(seq)
            self.qos.commit(seq, cost)
            budget -= cost
            seq.state = STATE_RUNNING
            self._running.append(seq)
            admitted.append(seq)
            g_serving_admitted.put(1)
        return admitted

    def _decode_cost(self, seq: Sequence) -> int:
        """Iteration-budget cost of one decode step for ``seq``: the max
        tokens it can commit (1 + its current draft length)."""
        if self.config.spec_k <= 0:
            return 1
        k = seq.spec.k if seq.spec is not None else self.config.spec_k
        return 1 + k

    def _prefill_cost(self, seq: Sequence) -> int:
        """Iteration-budget cost of prefilling ``seq``: only the suffix
        past the cached prefix runs through the model (≥ 1 — the first
        token is always sampled by this engine)."""
        if self.prefix is None:
            return len(seq.prompt)
        if seq.prefix_len:  # already forked (allocated, not yet stepped)
            return max(1, len(seq.prompt) - seq.prefix_len)
        return max(1, len(seq.prompt) - self.prefix.match_len(seq.prompt))

    def _alloc_for(self, seq: Sequence) -> None:
        """Allocate ``seq``'s block table — forking the longest cached
        prefix chain when the radix tree has one (refcount++, zero
        copies), falling back to a cold allocation (prefix-hash placed
        on the sharded pool, so a first-seen prefix builds its chain on
        the shard later hits will route to)."""
        if self.prefix is None:
            self.kv.alloc_sequence(seq.seq_id, seq.context_len())
            return
        matched = self.prefix.fork(seq.seq_id, seq.prompt)
        if matched:
            seq.prefix_len = matched
            try:
                # grow the adopted chain to cover prompt + decode slot
                self.kv.extend_sequence(seq.seq_id, seq.context_len())
            except KVCacheFull:
                self.kv.free_sequence(seq.seq_id)  # unwind the fork
                seq.prefix_len = 0
                raise
            return
        shard = self.prefix.route_shard(seq.prompt)
        if shard is not None:
            self.kv.alloc_sequence(seq.seq_id, seq.context_len(),
                                   shard=shard)
        else:
            self.kv.alloc_sequence(seq.seq_id, seq.context_len())

    def _step(self, admitted: List[Sequence]) -> None:
        t0 = time.perf_counter_ns()
        # ---- prefill phase: one fused program per new sequence
        if admitted:
            prev = _prof.set_phase("prefill")
            try:
                for seq in admitted:
                    if seq.adopted:
                        continue  # chain arrived prefilled — decode only
                    tp0 = time.perf_counter_ns()
                    if seq.prefix_len:
                        # forked chain: cow-split the divergence block if
                        # shared, then run only the suffix — hit TTFT is
                        # one decode-shaped launch, not O(prompt) prefill
                        self.kv.ensure_writable(seq.seq_id, seq.prefix_len)
                        table = self.kv.block_table(seq.seq_id)
                        first = self.model.prefill_suffix(
                            seq.prompt, table, seq.prefix_len)
                        g_serving_prefill_tokens.put(
                            len(seq.prompt) - seq.prefix_len)
                        self.prefill_tokens += (len(seq.prompt)
                                                - seq.prefix_len)
                    else:
                        table = self.kv.block_table(seq.seq_id)
                        first = self.model.prefill(seq.prompt, table)
                        g_serving_prefill_tokens.put(len(seq.prompt))
                        self.prefill_tokens += len(seq.prompt)
                    self._append_token(seq, first)
                    span = getattr(seq.cntl, "span", None)
                    if span is not None:
                        span.add_phase(
                            "prefill_us",
                            (time.perf_counter_ns() - tp0) / 1000.0)
            finally:
                _prof.set_phase(prev)
        self._reap_finished()
        # ---- disaggregated handoff: a prefill-role engine ships every
        # live chain to the decode shard right after its first token; a
        # failed migration leaves the sequence here (local-decode
        # fallback), retried next step
        if self.config.role == ROLE_PREFILL and self.migrator is not None:
            self._migrate_handoff()
        # ---- decode phase: ONE fused program for the whole batch
        batch = list(self._running)
        if batch:
            prev = _prof.set_phase("decode")
            try:
                _fault.maybe_sleep(_fault.hit("serving.decode.stall"))
                td0 = time.perf_counter_ns()
                cfg = self.config
                spec_on = (cfg.spec_k > 0
                           and hasattr(self.model, "verify_step"))
                tokens = np.array([s.out_tokens[-1] for s in batch],
                                  dtype=np.int32)
                # the step's input token (last sampled) is written at the
                # end of the current context, so capacity must cover
                # context_len() and the write position is context_len()-1
                positions = np.array([s.pos for s in batch],
                                     dtype=np.int32)
                if spec_on:
                    # draft lane: host-side prompt-lookup over committed
                    # history — zero device work before the one verify
                    # launch. k is capped at remaining-1 (a full accept
                    # plus bonus lands exactly on max_new_tokens) so the
                    # chain never outgrows the admitted KV bound.
                    vocab = getattr(self.model.config, "vocab", 0)
                    drafts = []
                    for s in batch:
                        if s.spec is None:
                            s.spec = _spec.AdaptiveK(
                                cfg.spec_k, cfg.spec_collapse_after)
                        k = min(s.spec.k,
                                max(0, s.max_new_tokens
                                    - len(s.out_tokens) - 1))
                        drafts.append(_spec.draft_tokens(
                            list(s.prompt) + s.out_tokens, k,
                            cfg.spec_ngram, vocab) if k > 0 else [])
                    tables = []
                    for s, d in zip(batch, drafts):
                        tables.append(self.kv.extend_sequence(
                            s.seq_id, s.context_len() + len(d)))
                else:
                    tables = []
                    for s in batch:
                        tables.append(self.kv.extend_sequence(
                            s.seq_id, s.context_len()))
                # dispatch-count invariant: under an armed ledger, the
                # whole decode batch — across every mesh shard, and all
                # k+1 verify rows per sequence — must cost exactly ONE
                # fused launch + ONE host sync
                audit = (getattr(self.model, "FUSED_STEP", False)
                         and getattr(self.kv, "_check", False))
                if audit:
                    from brpc_tpu.tpu.device_lane import step_dispatch
                    d_before = step_dispatch.snapshot()
                if spec_on:
                    outs = self.model.verify_step(tokens, positions,
                                                  tables, drafts)
                else:
                    nxt = self.model.decode_step(tokens, positions,
                                                 tables)
                if audit:
                    launches, _, syncs = step_dispatch.delta(
                        d_before, step_dispatch.snapshot())
                    assert (launches, syncs) == (1, 1), (
                        f"decode step dispatched {launches} launches / "
                        f"{syncs} host syncs for {len(batch)} seqs; the "
                        f"step contract is exactly (1, 1)")
                decode_us = (time.perf_counter_ns() - td0) / 1000.0
                shards_live: Dict[int, int] = {}
                for tbl in tables:
                    sh = getattr(tbl, "shard", 0)
                    shards_live[sh] = shards_live.get(sh, 0) + 1
                for sh, n_live in shards_live.items():
                    st = self._shard_step.setdefault(sh, [0, 0.0, 0.0, 0])
                    st[0] += 1
                    st[1] += decode_us
                    st[2] = decode_us
                    st[3] += n_live
                if spec_on:
                    self._commit_speculative(batch, drafts, outs)
                else:
                    for s, tok in zip(batch, nxt):
                        self._append_token(s, int(tok))
                for s in batch:
                    span = getattr(s.cntl, "span", None)
                    if span is not None:
                        span.add_phase("decode_us",
                                       decode_us / len(batch))
            except KVCacheFull:
                # mid-decode exhaustion: shed the youngest sequences until
                # the pool has headroom again — admission watermark should
                # make this rare, never fatal. Speculative headroom blocks
                # grabbed before the failure are handed back first so the
                # shed is no bigger than the non-speculative lane's.
                if self.config.spec_k > 0:
                    for s in batch:
                        try:
                            self.kv.truncate_sequence(s.seq_id,
                                                      s.context_len())
                        except KeyError:
                            pass
                victim = batch[-1]
                self._finish(victim, errors.EOVERCROWDED,
                             "kv pool exhausted mid-decode")
            finally:
                _prof.set_phase(prev)
        self._reap_finished()
        self.steps += 1
        self._occupancy_sum += len(batch)
        g_serving_steps.put(1)
        self.last_step_us = (time.perf_counter_ns() - t0) / 1000.0
        g_serving_step.record(self.last_step_us)

    def _commit_speculative(self, batch: List[Sequence],
                            drafts: List[List[int]],
                            outs: List[np.ndarray]) -> None:
        """Greedy acceptance + KV rollback for one verify step. Per
        sequence: commit the longest draft prefix agreeing with the
        verifier's argmax plus the one bonus token (cut short at
        stop/max_new), stream ONE TokenDelta carrying the accepted
        count, roll rejected tail blocks back via ``truncate_sequence``
        (the garbage K/V left *inside* retained blocks sits past the
        committed context, and next step's contiguous verify rows
        rewrite every such position before any row can attend to it),
        and feed the AdaptiveK controller."""
        step_drafted = step_accepted = 0
        for s, d, m in zip(batch, drafts, outs):
            a, committed = _spec.accept_longest_prefix(d, m)
            ncommit = 0
            for tok in committed:
                self._append_token(s, tok, stream=False)
                ncommit += 1
                if s.state == STATE_DONE:
                    break
            accepted_sent = min(ncommit, a)
            self._stream_delta(s, committed[:ncommit],
                               s.state == STATE_DONE,
                               accepted=accepted_sent)
            # rejected rows wrote K/V past the committed context; drop
            # whole tail blocks now, let next step's writes mask the rest
            self.kv.truncate_sequence(s.seq_id, s.context_len())
            was_collapsed = s.spec.collapsed
            s.spec.update(len(d), a)
            # the +1 bonus is only a *speculative* gain when the step
            # drafted; an empty-draft step is a plain decode token
            bonus = (ncommit - accepted_sent) if d else 0
            step_drafted += len(d)
            step_accepted += a
            if self.spec_stats is not None:
                st = self.spec_stats
                st.drafted += len(d)
                st.accepted += a
                st.rejected += len(d) - a
                st.bonus += bonus
                if d:
                    st.spec_steps += 1
                if s.spec.collapsed and not was_collapsed:
                    st.collapsed_seqs += 1
            if d:
                _spec.g_serving_spec_draft_tokens.put(len(d))
                if a:
                    _spec.g_serving_spec_accepted_tokens.put(a)
                if len(d) - a:
                    _spec.g_serving_spec_rejected_tokens.put(len(d) - a)
            if bonus:
                _spec.g_serving_spec_bonus_tokens.put(bonus)
        _spec.note_step(step_drafted, step_accepted)

    # ----------------------------------------------------------- completion
    def _append_token(self, seq: Sequence, tok: int,
                      stream: bool = True) -> None:
        now = time.monotonic()
        if not seq.out_tokens:
            seq.t_first_token = now
            g_serving_ttft.record((now - seq.t_submit) * 1e6)
            if len(self.ttft_samples) < 65536:
                self.ttft_samples.append((now - seq.t_submit) * 1e6)
        elif seq.t_last_token:
            g_serving_itl.record((now - seq.t_last_token) * 1e6)
            if len(self.itl_samples) < 65536:
                self.itl_samples.append((now - seq.t_last_token) * 1e6)
        seq.t_last_token = now
        seq.out_tokens.append(tok)
        self.tokens_generated += 1
        g_serving_tokens.put(1)
        finished = (len(seq.out_tokens) >= seq.max_new_tokens
                    or (seq.stop_token and tok == seq.stop_token))
        if stream:
            self._stream_delta(seq, [tok], finished)
        if finished:
            seq.finish_reason = ("stop_token"
                                 if seq.stop_token and tok == seq.stop_token
                                 else "length")
            seq.state = STATE_DONE

    def _stream_delta(self, seq: Sequence, toks: List[int],
                      done: bool, accepted: int = 0) -> None:
        if not seq.stream_id:
            return
        from brpc_tpu.proto import serving_pb2
        from brpc_tpu.rpc.stream import stream_write

        delta = serving_pb2.TokenDelta(
            seq_id=seq.seq_id, tokens=toks,
            step=len(seq.out_tokens), done=done, accepted=accepted)
        rc = stream_write(seq.stream_id, delta.SerializeToString())
        if rc != 0:
            seq.stream_id = 0  # stream died; finish via the RPC response

    def _reap_finished(self) -> None:
        still: List[Sequence] = []
        for seq in self._running:
            sock = getattr(seq.cntl, "_srv_socket", None)
            if sock is not None and getattr(sock, "failed", False):
                # tunnel/connection died mid-generation: retriable error
                # to the sequence, blocks back to the pool
                self._finish(seq, errors.EFAILEDSOCKET,
                             "connection failed mid-generation")
            elif seq.state == STATE_DONE:
                self._finish(seq, 0, "")
            else:
                still.append(seq)
        self._running = still

    # ------------------------------------------------------------- handoff
    def _migrate_handoff(self) -> None:
        """Ship every live chain to the decode shard (runs on the engine
        thread between phases, so each sequence is quiescent). Successes
        complete the stage-1 RPC with the handoff meta; failures stay in
        the running set and decode locally."""
        moved = []
        for seq in list(self._running):
            if seq.state == STATE_DONE or seq.adopted:
                continue
            dest = self.migrator.migrate(seq, self.kv)
            if dest is not None:
                moved.append((seq, dest))
        if not moved:
            return
        gone = {id(s) for s, _ in moved}
        self._running = [s for s in self._running if id(s) not in gone]
        for seq, dest in moved:
            self._finish_handoff(seq, dest)

    def _finish_handoff(self, seq: Sequence, dest_seq_id: int) -> None:
        """Complete the stage-1 RPC: the reply's meta (finish_reason
        "handoff" + handoff_shard + the adopted seq_id) tells the client
        where its generation keeps running. The chain was released by
        the migrator on the destination's ACK — nothing to free here."""
        from brpc_tpu.proto import serving_pb2

        seq.state = STATE_DONE
        seq.finish_reason = "handoff"
        self._stream_delta(seq, [], True)  # stage-1 stream is complete
        done, seq.done = seq.done, None
        if done is None:
            return
        ttft_us = 0
        if seq.t_first_token:
            ttft_us = int((seq.t_first_token - seq.t_submit) * 1e6)
        resp = serving_pb2.GenerateResponse(
            tokens=seq.out_tokens, seq_id=dest_seq_id,
            prompt_len=len(seq.prompt), steps=len(seq.out_tokens),
            ttft_us=ttft_us, finish_reason="handoff",
            handoff_shard=self.migrator.dest_shard)
        try:
            done(resp)
        except Exception:
            pass

    def _drain_migrate(self) -> None:
        """stop()-path recovery: move live chains to the survivor. The
        client RPC still fails retriably (its engine IS going away), but
        the retry attaches to the migrated sequence on the destination —
        zero re-prefilled tokens."""
        with self._cv:
            live = [s for s in self._running
                    if s.state != STATE_DONE and not s.adopted]
        for seq in live:
            dest = self.migrator.migrate(seq, self.kv, recovery=True)
            if dest is None:
                continue  # the abort fan below will clean it up
            with self._cv:
                if seq in self._running:
                    self._running.remove(seq)
            self._finish(seq, errors.EFAILEDSOCKET,
                         "shard draining: sequence migrated to survivor "
                         "(retriable)")

    def _finish(self, seq: Sequence, code: int, reason: str) -> None:
        if code == 0 and self.prefix is not None and seq.out_tokens:
            # commit the fully-written blocks back into the radix tree
            # (insert-or-share) before the table drops; the last sampled
            # token's K/V was never written, hence the -1 valid length
            self.prefix.commit(
                seq.seq_id, list(seq.prompt) + seq.out_tokens,
                len(seq.prompt) + len(seq.out_tokens) - 1)
        self.kv.free_sequence(seq.seq_id)
        if seq.state != STATE_DONE:
            seq.state = STATE_DONE
        if code == 0:
            g_serving_completed.put(1)
        else:
            g_serving_aborted.put(1)
        if seq.stream_id and code != 0:
            from brpc_tpu.rpc.stream import stream_close

            stream_close(seq.stream_id)
            seq.stream_id = 0
        with self._cv:
            if seq.adopted and seq.done is None and not seq._attached:
                # migrated-in with no client yet: park the result for
                # the stage-2/retry attach (blocks already freed above)
                seq._deferred = (code, reason)
                return
            self._adopted.pop(seq.seq_id, None)
        done, seq.done = seq.done, None
        if done is None:
            return
        try:
            if code != 0 and seq.cntl is not None:
                seq.cntl.set_failed(code, reason)
                done(None)
            else:
                done(self._response_for(seq))
        except Exception:
            pass

    def _response_for(self, seq: Sequence):
        from brpc_tpu.proto import serving_pb2

        ttft_us = 0
        if seq.t_first_token:
            ttft_us = int((seq.t_first_token - seq.t_submit) * 1e6)
        # a resume (stage-2) attach already received the prefill shard's
        # tokens in the stage-1 reply — return only the suffix decoded
        # here; a recovery attach replaces the lost reply entirely
        toks = (seq.out_tokens[seq.handoff_base:] if seq.resume_attach
                else seq.out_tokens)
        return serving_pb2.GenerateResponse(
            tokens=toks, seq_id=seq.seq_id,
            prompt_len=len(seq.prompt), steps=len(toks),
            ttft_us=ttft_us, finish_reason=seq.finish_reason or "length")

    def _abort_all_locked_out(self, code: int, reason: str) -> None:
        with self._cv:
            pending = (list(self._waiting) + list(self._running)
                       + list(self._adopted_pending))
            self._waiting.clear()
            self._running = []
            self._adopted_pending.clear()
            if self.qos is not None:
                for seq in list(self.qos.iter_waiting()):
                    self.qos.drop(seq)
                    pending.append(seq)
        for seq in pending:
            self._finish(seq, code, reason)

    # ------------------------------------------------------------ visibility
    def snapshot(self) -> Dict[str, object]:
        kv = self.kv.snapshot()
        occ = (self._occupancy_sum / self.steps) if self.steps else 0.0
        migration = None
        if self.migrator is not None or self._migration_rx is not None:
            migration = {"parked": len(self._adopted)}
            if self.migrator is not None:
                migration["out"] = self.migrator.snapshot()
            if self._migration_rx is not None:
                migration["in"] = self._migration_rx.snapshot()
        return {
            "role": self.config.role,
            "migration": migration,
            "scheduling": self.config.scheduling,
            "max_batch": self.config.max_batch,
            "token_budget": self.config.token_budget,
            "queue_depth": self.queue_depth,
            "running": self.running_count,
            "steps": self.steps,
            "tokens_generated": self.tokens_generated,
            "batch_occupancy_avg": round(occ, 3),
            "last_step_us": round(self.last_step_us, 1),
            "step_us_p50": g_serving_step.latency_percentile(0.5),
            "step_us_p99": g_serving_step.latency_percentile(0.99),
            "ttft_us_p50": g_serving_ttft.latency_percentile(0.5),
            "ttft_us_p99": g_serving_ttft.latency_percentile(0.99),
            "itl_us_p50": g_serving_itl.latency_percentile(0.5),
            "shard_steps": {
                sh: {"steps": int(st[0]),
                     "avg_us": round(st[1] / st[0], 1) if st[0] else 0.0,
                     "last_us": round(st[2], 1),
                     "seq_steps": int(st[3])}
                for sh, st in sorted(self._shard_step.items())
            },
            "kv": kv,
            "prefix": (self.prefix.snapshot()
                       if self.prefix is not None else None),
            "spec": (dict(self.spec_stats.snapshot(),
                          k_max=self.config.spec_k)
                     if self.spec_stats is not None else None),
            "qos": (self.qos.snapshot()
                    if self.qos is not None else None),
        }
