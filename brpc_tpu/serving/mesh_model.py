"""Mesh-sharded serving model: shard_map prefill/decode over dp/sp/tp.

:class:`MeshTransformer` lowers the toy transformer's serving programs
onto the full device mesh (``tpu/mesh.serving_mesh``) against a
:class:`~brpc_tpu.serving.kv_cache.ShardedKVCache`:

- **decode** — ONE shard_map program over the WHOLE mesh per engine step:
  the batch is grouped by owning dp shard, each dp group runs the exact
  single-device decode body (``model._decode_body``) against its local
  pool slice, and the step still costs one fused launch + one host
  materialization regardless of mesh size (the dispatch-count invariant
  the engine asserts under BRPC_TPU_CHECK).
- **prefill** — flash/reference attention tp-sharded over heads: each tp
  device attends its head slice, the head outputs are all_gather'ed back
  before the output projection (gather, not row-parallel psum, so the
  projection contracts the identical operands in the identical order as
  single-device — greedy equivalence stays BIT-exact, not just
  approximate). Every dp group traces the same program SPMD-style; only
  the owner's pool slice takes the K/V scatter.
- **ring lane** — prompts past ``ring_threshold`` run the ring-attention
  sequence-parallel path over this mesh's ``sp`` axis (``tpu/ring.py``),
  scattering into the owner's slice of the stacked pools.

jax-0.4.37: shard_map comes through ``tpu/collective.py``'s
version-guarded shim (``shard_map_norep`` keeps the ``check_rep`` /
``check_vma`` spelling inside the shim module); weights are replicated
across the mesh and the stacked KV pools are sharded over ``dp`` by
``named_sharding`` — jit follows the input shardings, which is the pjit
lowering on this jax line.
"""

from __future__ import annotations

import functools
from typing import List, Sequence

import numpy as np

from brpc_tpu.serving.kv_cache import ShardedKVCache
from brpc_tpu.serving.model import (ModelConfig, TinyTransformer,
                                    _decode_body, _next_pow2, _rms)


class MeshTransformer(TinyTransformer):
    """TinyTransformer lowered across the serving mesh."""

    def __init__(self, config: ModelConfig, kv: ShardedKVCache,
                 store=None, mesh=None):
        mesh = mesh if mesh is not None else kv.mesh
        for ax in ("dp", "sp", "tp"):
            if ax not in mesh.axis_names:
                raise ValueError(f"serving mesh needs a {ax!r} axis, "
                                 f"got {mesh.axis_names}")
        self.dp = int(mesh.shape["dp"])
        self.tp = int(mesh.shape["tp"])
        if config.n_heads % self.tp:
            raise ValueError(
                f"n_heads={config.n_heads} must divide tp={self.tp}")
        if self.dp != kv.n_shards:
            raise ValueError(f"mesh dp={self.dp} != kv shards "
                             f"{kv.n_shards}")
        super().__init__(config, kv, store=store, mesh=mesh)

    # ------------------------------------------------------------- prefill
    def _mesh_prefill_fn(self, s_bucket: int, use_flash: bool):
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from brpc_tpu.tpu import pallas_ops
        from brpc_tpu.tpu.collective import shard_map_norep

        cfg = self.config
        H, hd = cfg.n_heads, cfg.head_dim
        Hl = H // self.tp
        kernel = (pallas_ops.flash_attention if use_flash
                  else pallas_ops.attention_reference)

        def local(params, kpools, vpools, tokens, slots, length, owner):
            # every device traces the same prompt SPMD-style; tp shards
            # the attention heads, dp decides who keeps the K/V scatter
            kp, vp = kpools[0], vpools[0]
            dp_i = lax.axis_index("dp")
            tp_i = lax.axis_index("tp")
            own = (dp_i == owner)
            x = params["embed"][tokens]                      # (S, D)
            for l in range(cfg.n_layers):
                h = _rms(x)
                qkv = h @ params[f"wqkv{l}"]
                q, k, vv = jnp.split(qkv, 3, axis=-1)
                kp = jnp.where(own, kp.at[l, slots].set(k), kp)
                vp = jnp.where(own, vp.at[l, slots].set(vv), vp)
                qh = q.reshape(s_bucket, H, hd)
                kh = k.reshape(s_bucket, H, hd)
                vh = vv.reshape(s_bucket, H, hd)
                # tp head shard: attend only this device's head slice
                qh = lax.dynamic_slice_in_dim(qh, tp_i * Hl, Hl, 1)
                kh = lax.dynamic_slice_in_dim(kh, tp_i * Hl, Hl, 1)
                vh = lax.dynamic_slice_in_dim(vh, tp_i * Hl, Hl, 1)
                attn = jax.vmap(functools.partial(kernel, causal=True),
                                in_axes=1, out_axes=1)(qh, kh, vh)
                # gather heads back before the projection: the matmul then
                # contracts the same (S, H*hd) operand as single-device,
                # keeping greedy decode bit-identical across mesh shapes
                attn = lax.all_gather(attn, "tp", axis=1, tiled=True)
                x = x + attn.reshape(s_bucket, -1) @ params[f"wo{l}"]
                h2 = _rms(x)
                x = x + jax.nn.relu(h2 @ params[f"w1{l}"]) @ params[f"w2{l}"]
            last = _rms(x[length - 1])
            logits = last @ params["embed"].T
            nxt = jnp.argmax(logits).astype(jnp.int32)
            return kp[None], vp[None], nxt

        sm = shard_map_norep(
            local, self.mesh,
            in_specs=(P(), P("dp"), P("dp"), P(), P(), P(), P()),
            out_specs=(P("dp"), P("dp"), P()))
        return jax.jit(sm, donate_argnums=(1, 2))

    def prefill(self, tokens: np.ndarray, table: Sequence[int]) -> int:
        cfg = self.config
        s = len(tokens)
        if s >= cfg.ring_threshold:
            return self._prefill_ring(tokens, table)
        self.kv.assert_writable(table, 0, s)
        shard = getattr(table, "shard", 0)
        bucket = max(16, _next_pow2(s))
        if bucket > 128:
            bucket = ((s + 127) // 128) * 128  # flash wants S % 128 == 0
        use_flash = self._use_flash()
        key = (bucket, use_flash)
        with self._lock:
            fn = self._prefill_cache.get(key)
            if fn is None:
                fn = self._mesh_prefill_fn(bucket, use_flash)
                self._prefill_cache[key] = fn
        toks = np.zeros(bucket, dtype=np.int32)
        toks[:s] = tokens
        slots = self._slots_for(table, s, bucket)
        from brpc_tpu.tpu.device_lane import step_dispatch
        step_dispatch.note_launch(1)
        kpools, vpools, nxt = fn(self._params, self.kv.k_pools,
                                 self.kv.v_pools, toks, slots,
                                 np.int32(s), np.int32(shard))
        self.kv.update_pools(kpools, vpools)
        first = int(nxt)
        step_dispatch.note_host_sync()
        return first

    def _prefill_ring(self, tokens: np.ndarray,
                      table: Sequence[int]) -> int:
        """Long-context lane over THIS mesh's sp axis: ring attention per
        layer, K/V scattered into the owner's slice of the stacked
        pools. Host-side layer loop as in the single-device lane."""
        import jax
        import jax.numpy as jnp

        from brpc_tpu.tpu import ring
        from brpc_tpu.tpu.device_lane import step_dispatch

        cfg = self.config
        H, hd = cfg.n_heads, cfg.head_dim
        shard = int(getattr(table, "shard", 0))
        n = int(self.mesh.shape["sp"])
        s = len(tokens)
        self.kv.assert_writable(table, 0, s)
        pad = ((s + n - 1) // n) * n
        p = self._params
        toks = np.zeros(pad, dtype=np.int32)
        toks[:s] = tokens
        x = p["embed"][jnp.asarray(toks)]
        kpools, vpools = self.kv.k_pools, self.kv.v_pools
        slots = jnp.asarray(self._slots_for(table, s, pad))
        for l in range(cfg.n_layers):
            h = _rms(x)
            qkv = h @ p[f"wqkv{l}"]
            q, k, vv = jnp.split(qkv, 3, axis=-1)
            kpools = kpools.at[shard, l, slots].set(k)
            vpools = vpools.at[shard, l, slots].set(vv)
            qh = q.reshape(1, pad, H, hd)
            kh = k.reshape(1, pad, H, hd)
            vh = vv.reshape(1, pad, H, hd)
            step_dispatch.note_launch(1)
            attn = ring.ring_attention(qh, kh, vh, self.mesh, "sp",
                                       causal=True)
            x = x + attn.reshape(pad, -1) @ p[f"wo{l}"]
            h2 = _rms(x)
            x = x + jax.nn.relu(h2 @ p[f"w1{l}"]) @ p[f"w2{l}"]
        self.kv.update_pools(kpools, vpools)
        logits = _rms(x[s - 1]) @ p["embed"].T
        first = int(jnp.argmax(logits))
        step_dispatch.note_host_sync()
        return first

    # -------------------------------------------------------------- decode
    def _decode_fn(self, b_bucket: int, l_bucket: int):
        import jax
        from jax.sharding import PartitionSpec as P

        from brpc_tpu.tpu.collective import shard_map_norep

        cfg = self.config

        def local(params, kpools, vpools, tokens, positions, slot_tables):
            # each dp group decodes its own sub-batch from its own pool
            # slice; sp/tp devices in the group replicate the compute so
            # the whole mesh stays inside ONE program launch
            kp, vp, nxt = _decode_body(
                cfg, params, kpools[0], vpools[0], tokens[0], positions[0],
                slot_tables[0], b_bucket, l_bucket)
            return kp[None], vp[None], nxt[None]

        sm = shard_map_norep(
            local, self.mesh,
            in_specs=(P(), P("dp"), P("dp"), P("dp"), P("dp"), P("dp")),
            out_specs=(P("dp"), P("dp"), P("dp")))
        return jax.jit(sm, donate_argnums=(1, 2))

    def decode_step(self, tokens: np.ndarray, positions: np.ndarray,
                    tables: List[Sequence[int]]) -> np.ndarray:
        """ONE fused launch for the WHOLE mesh: sequences grouped by
        owning dp shard, per-shard sub-batches padded to a common bucket,
        one shard_map program, one host materialization.

        Speculative ``verify_step`` rides this unchanged: a sequence's
        k+1 verify rows share its ShardTable, so the shard grouping keeps
        them contiguous and in position order on the owning dp shard —
        the per-shard ``_decode_body`` sees exactly the single-pool row
        layout and the verify lowering stays bit-identical across tp/dp
        splits, still one launch and one sync for the whole mesh."""
        bs = self.kv.block_size
        B = len(tokens)
        self.kv.assert_writable_batch(tables, positions)
        dp = self.dp
        groups: List[List[int]] = [[] for _ in range(dp)]
        for i, t in enumerate(tables):
            groups[getattr(t, "shard", 0)].append(i)
        # bucket by TOTAL batch, not the max per-shard group: the shard
        # split depends on seq-id hashing, so group-derived buckets churn
        # the jit cache across otherwise-identical workloads (a cold
        # compile mid-serving is a multi-hundred-ms step); total-batch
        # buckets cost a little padding and make the combo set a pure
        # function of the workload
        b_bucket = max(2, _next_pow2(B))
        max_blocks = max(len(t) for t in tables)
        l_bucket = max(2, _next_pow2(max_blocks)) * bs
        key = (b_bucket, l_bucket)
        with self._lock:
            fn = self._decode_cache.get(key)
            if fn is None:
                fn = self._decode_fn(b_bucket, l_bucket)
                self._decode_cache[key] = fn
        toks = np.zeros((dp, b_bucket), dtype=np.int32)
        pos = np.zeros((dp, b_bucket), dtype=np.int32)
        slot_tables = np.zeros((dp, b_bucket, l_bucket), dtype=np.int32)
        for shard, g in enumerate(groups):
            for j, i in enumerate(g):
                toks[shard, j] = tokens[i]
                pos[shard, j] = positions[i]
                slot_tables[shard, j] = self._slots_for(
                    tables[i], positions[i] + 1, l_bucket)
        from brpc_tpu.tpu.device_lane import step_dispatch
        step_dispatch.note_launch(1)
        kpools, vpools, nxt = fn(self._params, self.kv.k_pools,
                                 self.kv.v_pools, toks, pos, slot_tables)
        self.kv.update_pools(kpools, vpools)
        flat = np.asarray(nxt)
        step_dispatch.note_host_sync()
        out = np.zeros(B, dtype=np.int32)
        for shard, g in enumerate(groups):
            for j, i in enumerate(g):
                out[i] = flat[shard, j]
        return out
