"""Multi-tenant QoS: weighted fair-share admission + closed-loop overload.

This module is the serving plane's answer to "an overload wave takes
every tenant down together". Three mechanisms compose, all actuating at
the same place — the engine's admission boundary:

- **TenantScheduler** — weighted fair share over the per-step
  ``token_budget``. Each tenant owns a FIFO lane and a virtual-time
  clock (stride scheduling: admitting ``cost`` prefill tokens advances
  the clock by ``cost / weight``); the scheduler always serves the
  backlogged tenant with the smallest clock, so token share converges to
  the weight ratio and an idle tenant's share redistributes to the
  backlogged ones for free. A returning tenant's clock is clamped up to
  the current virtual time — it competes again within one admission
  step, without a catch-up burst that would starve everyone else.
  Per-tenant queue caps shed EOVERCROWDED on the existing retriable
  path, and the deadline is re-checked at every admission boundary
  exactly as ``deadline_mono`` already is.

- **QosLimiter** — the closed loop: an AutoLimiter-style gradient/AIMD
  limiter (policy/limiters.py:60 ported to the serving path) driven by
  the observed queue-phase latency. The engine records every admitted
  sequence's queue wait into ``g_serving_qos_queue_wait``; the series
  rings sweep it once per second, and the sampler's post-tick hook
  (:meth:`QosGovernor.tick`) samples the ring and updates a dynamic
  admission ceiling: latency at the empty-queue floor grows the ceiling
  additively, latency above it shrinks the ceiling multiplicatively
  (``ceiling * clamp(min/avg, 0.5, 1.5) + 1``, the AutoLimiter
  gradient).

- **Priority-aware shedding** — when load exceeds the ceiling, the
  best-effort lanes (``priority < protected_priority``) shed first:
  new arrivals are rejected EOVERCROWDED at :meth:`admission_check`, and
  the governor's tick sheds already-queued best-effort work
  oldest-queued/lowest-priority first. The protected lane is only
  touched when the protected lane *alone* exceeds the ceiling.

Identity rides the wire on ``RequestMeta.tenant_id``/``priority``
(client Controller setters → both Python dispatch paths → ``cntl`` →
the engine), is recorded by rpc_dump and replayed by rpc_replay — so an
overload wave captured in production sheds the same tenants when
replayed through the tier-1 gate.
"""

from __future__ import annotations

import collections
import re
import threading
import time
from typing import Callable, Deque, Dict, List, Optional

from brpc_tpu import fault as _fault
from brpc_tpu.metrics.latency_recorder import LatencyRecorder
from brpc_tpu.metrics.reducer import Adder
from brpc_tpu.metrics.status import PassiveStatus
from brpc_tpu.rpc import errors

_fault.register("serving.qos.burst",
                "inflate a tenant's arrival rate at serving admission "
                "(factor=N clones each submit; match_tenant= filters)")

DEFAULT_TENANT = "default"

g_serving_qos_admitted = Adder("g_serving_qos_admitted")
g_serving_qos_shed = Adder("g_serving_qos_shed")
# queue-phase latency of the serving admission boundary (submit →
# admitted into the running batch) — the control SIGNAL: its series ring
# is what the governor samples each sampler tick
g_serving_qos_queue_wait = LatencyRecorder().expose("g_serving_qos_queue_wait")


def _fleet_qos(attr: str, reduce=sum, default=0.0):
    """Reduce a TenantScheduler property across live qos engines."""
    from brpc_tpu.serving.engine import active_engines

    vals = [getattr(e.qos, attr)() for e in active_engines()
            if getattr(e, "qos", None) is not None]
    return reduce(vals) if vals else default


# fair-share occupancy: fraction of the dynamic admission ceiling the
# fleet's queued+running load occupies — > 1.0 means the closed loop is
# actively shedding down to the ceiling
g_serving_qos_occupancy = PassiveStatus(
    lambda: round(_fleet_qos("occupancy", reduce=max), 3)) \
    .expose("g_serving_qos_occupancy")
g_serving_qos_occupancy.prometheus_type = "gauge"
# starvation signal: the oldest queued wait (ms) across every tenant
# lane of every live qos engine — watched by serving_qos_starvation
g_serving_qos_max_wait_ms = PassiveStatus(
    lambda: round(_fleet_qos("oldest_wait_ms", reduce=max), 1)) \
    .expose("g_serving_qos_max_wait_ms")
g_serving_qos_max_wait_ms.prometheus_type = "gauge"

_VAR_SAFE = re.compile(r"[^A-Za-z0-9_]+")
_tenant_vars: Dict[str, Dict[str, Adder]] = {}
_tenant_vars_lock = threading.Lock()


def _vars_for_tenant(name: str) -> Dict[str, Adder]:
    """Per-tenant admitted/shed counters + queue-depth gauge, created
    once per tenant NAME process-wide (fleet-style, like g_serving_*) —
    never per request and never per engine, so the metric-churn rule's
    no-construction-on-the-request-path contract holds: tenants are
    registered at config time or on a lane's FIRST request only."""
    with _tenant_vars_lock:
        vars = _tenant_vars.get(name)
        if vars is None:
            safe = _VAR_SAFE.sub("_", name) or "_"
            depth = PassiveStatus(
                lambda n=name: int(_fleet_qos_depth(n))) \
                .expose(f"g_serving_qos_queue_depth_{safe}")
            depth.prometheus_type = "gauge"
            vars = _tenant_vars[name] = {
                "admitted": Adder(f"g_serving_qos_admitted_{safe}"),
                "shed": Adder(f"g_serving_qos_shed_{safe}"),
                "depth": depth,
            }
        return vars


def _fleet_qos_depth(tenant: str) -> int:
    from brpc_tpu.serving.engine import active_engines

    return sum(e.qos.tenant_depth(tenant) for e in active_engines()
               if getattr(e, "qos", None) is not None)


class QosConfig:
    """Knobs for one engine's QoS plane (docs/serving.md §Multi-tenant
    QoS has the full table)."""

    def __init__(self, tenants: Optional[Dict[str, float]] = None,
                 default_weight: float = 1.0, queue_cap: int = 32,
                 protected_priority: int = 1,
                 ceiling_min: float = 2.0, ceiling_max: float = 256.0,
                 ceiling_start: float = 0.0, smoothing: float = 0.5):
        if default_weight <= 0:
            raise ValueError("default_weight must be > 0")
        if queue_cap < 1:
            raise ValueError("queue_cap must be >= 1")
        if not (ceiling_min >= 1 and ceiling_max >= ceiling_min):
            raise ValueError("need 1 <= ceiling_min <= ceiling_max")
        # tenant -> fair-share weight; unknown tenants auto-register at
        # default_weight on their first request
        self.tenants = dict(tenants or {})
        for t, w in self.tenants.items():
            if w <= 0:
                raise ValueError(f"tenant {t!r} weight must be > 0")
        self.default_weight = default_weight
        self.queue_cap = queue_cap
        # requests with priority >= protected_priority ride the protected
        # lane: shed only when the protected lane alone exceeds capacity
        self.protected_priority = protected_priority
        self.ceiling_min = ceiling_min
        self.ceiling_max = ceiling_max
        # 0 = start wide open (ceiling_max) and let the loop close in
        self.ceiling_start = ceiling_start or ceiling_max
        self.smoothing = smoothing


class QosLimiter:
    """Gradient/AIMD concurrency ceiling — policy/limiters.py's
    AutoLimiter shape, re-targeted from per-call inflight accounting to
    a once-per-tick update off the queue-wait series ring.

    ``observe`` keeps an exponentially-drifting minimum of the queue
    wait (the empty-queue service floor) and multiplies the ceiling by
    ``clamp(min/avg, 0.5, 1.5)``: waits at the floor grow the ceiling
    (additive +1 — the AIMD probe), waits above it shrink it toward
    what the hardware actually drains."""

    GRADIENT_MIN = 0.5
    GRADIENT_MAX = 1.5
    MIN_DRIFT = 1.01  # min-latency EMA decays upward 1%/tick

    def __init__(self, config: QosConfig):
        self.config = config
        self.ceiling = float(config.ceiling_start)
        self._min_wait_us = 0.0
        self._avg_wait_us = 0.0
        self.updates = 0

    def observe(self, queue_wait_us: float, inflight: int) -> float:
        """One control-loop update; returns the new ceiling."""
        cfg = self.config
        self.updates += 1
        if queue_wait_us <= 0.0:
            # idle tick (no admissions sampled): recover additively, but
            # only while load isn't pinned at the ceiling — an empty
            # sample under saturation means nothing got through, which
            # is not evidence of headroom
            if inflight < self.ceiling:
                self.ceiling = min(cfg.ceiling_max, self.ceiling + 1.0)
            return self.ceiling
        a = cfg.smoothing
        self._avg_wait_us = (queue_wait_us if self._avg_wait_us <= 0.0
                             else a * self._avg_wait_us
                             + (1.0 - a) * queue_wait_us)
        if self._min_wait_us <= 0.0:
            self._min_wait_us = self._avg_wait_us
        else:
            self._min_wait_us = min(self._min_wait_us * self.MIN_DRIFT,
                                    self._avg_wait_us)
        gradient = self._min_wait_us / self._avg_wait_us
        gradient = max(self.GRADIENT_MIN, min(self.GRADIENT_MAX, gradient))
        self.ceiling = max(cfg.ceiling_min,
                           min(cfg.ceiling_max,
                               self.ceiling * gradient + 1.0))
        return self.ceiling

    def snapshot(self) -> Dict[str, float]:
        return {"ceiling": round(self.ceiling, 1),
                "min_wait_us": round(self._min_wait_us, 1),
                "avg_wait_us": round(self._avg_wait_us, 1),
                "updates": self.updates}


class _Tenant:
    __slots__ = ("name", "weight", "cap", "vtime", "waiting",
                 "admitted_reqs", "admitted_tokens", "shed", "vars")

    def __init__(self, name: str, weight: float, cap: int):
        self.name = name
        self.weight = weight
        self.cap = cap
        self.vtime = 0.0
        self.waiting: Deque = collections.deque()
        self.admitted_reqs = 0
        self.admitted_tokens = 0
        self.shed = 0
        self.vars = _vars_for_tenant(name)


class TenantScheduler:
    """Weighted fair-share admission in front of the engine's
    ``_admit_locked``. All mutating calls run under the ENGINE's
    condition lock (the scheduler is part of the engine's queue state);
    read-only gauges tolerate racy reads."""

    def __init__(self, config: QosConfig, engine=None):
        self.config = config
        self.engine = engine
        self.limiter = QosLimiter(config)
        self._tenants: Dict[str, _Tenant] = {}
        # config-time registration so the per-tenant vars exist before
        # the first request (and the request path never constructs)
        for name in config.tenants:
            self.tenant(name)

    # ------------------------------------------------------------- tenants
    def tenant(self, name: str) -> _Tenant:
        name = name or DEFAULT_TENANT
        t = self._tenants.get(name)
        if t is None:
            weight = self.config.tenants.get(name,
                                             self.config.default_weight)
            t = self._tenants[name] = _Tenant(name, weight,
                                              self.config.queue_cap)
        return t

    def tenant_depth(self, name: str) -> int:
        t = self._tenants.get(name or DEFAULT_TENANT)
        return len(t.waiting) if t is not None else 0

    # ------------------------------------------------------------ admission
    def _running_load(self, protected_only: bool = False) -> int:
        if self.engine is None:
            return 0
        running = self.engine._running
        if not protected_only:
            return len(running)
        p = self.config.protected_priority
        return sum(1 for s in running
                   if getattr(s, "priority", 0) >= p)

    def total_depth(self) -> int:
        return sum(len(t.waiting) for t in self._tenants.values())

    def _protected_depth(self) -> int:
        p = self.config.protected_priority
        return sum(1 for t in self._tenants.values() for s in t.waiting
                   if s.priority >= p)

    def inflight(self) -> int:
        """Queued + running sequences — what the ceiling meters."""
        return self.total_depth() + self._running_load()

    def occupancy(self) -> float:
        return self.inflight() / max(self.limiter.ceiling, 1.0)

    def oldest_wait_ms(self, now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else now
        oldest = 0.0
        for t in self._tenants.values():
            if t.waiting:
                oldest = max(oldest, now - t.waiting[0].t_submit)
        return oldest * 1e3

    def admission_check(self, tenant_id: str, priority: int,
                        deadline_mono: float = 0.0,
                        now: Optional[float] = None) -> int:
        """The QoS admission predicate — deadline + tenant queue cap +
        limiter ceiling, in that order (cheapest evidence of death
        first). Returns 0 to admit, ERPCTIMEDOUT for an already-dead
        request, EOVERCROWDED (retriable) for a shed one. Consulted
        before ANY append to a waiting lane (the shed-before-queue lint
        rule pins that contract)."""
        now = time.monotonic() if now is None else now
        if deadline_mono and now >= deadline_mono:
            return errors.ERPCTIMEDOUT
        t = self.tenant(tenant_id)
        if len(t.waiting) >= t.cap:
            self._note_shed(t)
            return errors.EOVERCROWDED
        ceiling = self.limiter.ceiling
        if self.inflight() >= ceiling:
            if priority >= self.config.protected_priority:
                # protected lane: shed only when the protected lane
                # ALONE exceeds the ceiling
                prot = (self._protected_depth()
                        + self._running_load(protected_only=True))
                if prot < ceiling:
                    return 0
            self._note_shed(t)
            return errors.EOVERCROWDED
        return 0

    def enqueue(self, seq) -> int:
        """Queue ``seq`` on its tenant's lane (engine lock held). The
        admission predicate is re-evaluated here — enqueue and check are
        one decision, so no append can bypass it."""
        code = self.admission_check(seq.tenant_id, seq.priority,
                                    getattr(seq.cntl, "deadline_mono", 0.0)
                                    if seq.cntl is not None else 0.0)
        if code != 0:
            return code
        t = self.tenant(seq.tenant_id)
        if not t.waiting:
            # returning from idle: clamp the clock up to the current
            # virtual time so the lane competes again immediately (share
            # reclaimed within one step) without a catch-up burst
            t.vtime = max(t.vtime, self._virtual_time())
        t.waiting.append(seq)
        return 0

    def _virtual_time(self) -> float:
        backlogged = [t.vtime for t in self._tenants.values() if t.waiting]
        if backlogged:
            return min(backlogged)
        return max((t.vtime for t in self._tenants.values()), default=0.0)

    # ------------------------------------------------------------ scheduling
    def peek(self, budget: int, cost_fn: Callable[[object], int]):
        """Head-of-line candidate: the backlogged tenant with the
        smallest virtual clock. Returns its head sequence when the
        prefill cost fits ``budget``, else None (the lane keeps its
        clock, so it is first in line for the NEXT step's full budget —
        the same no-starvation property the FIFO path had)."""
        best = None
        for t in self._tenants.values():
            if t.waiting and (best is None or t.vtime < best.vtime):
                best = t
        if best is None:
            return None
        head = best.waiting[0]
        if cost_fn(head) > budget:
            return None
        return head

    def drop(self, seq) -> None:
        """Remove a queued sequence without billing it (deadline death,
        shed): it never consumed share."""
        t = self._tenants.get(seq.tenant_id or DEFAULT_TENANT)
        if t is not None:
            try:
                t.waiting.remove(seq)
            except ValueError:
                pass

    def commit(self, seq, cost: int) -> None:
        """Bill an admission: pop from the lane, advance the tenant's
        clock by cost/weight (stride accounting), record the queue-phase
        wait the governor's loop closes on."""
        t = self.tenant(seq.tenant_id)
        try:
            t.waiting.remove(seq)
        except ValueError:
            pass
        cost = max(1, int(cost))
        t.vtime += cost / t.weight
        t.admitted_reqs += 1
        t.admitted_tokens += cost
        t.vars["admitted"].put(1)
        g_serving_qos_admitted.put(1)
        g_serving_qos_queue_wait.record(
            (time.monotonic() - seq.t_submit) * 1e6)

    def _note_shed(self, t: _Tenant) -> None:
        t.shed += 1
        t.vars["shed"].put(1)
        g_serving_qos_shed.put(1)

    # ------------------------------------------------------------- shedding
    def shed_victims(self, excess: int) -> List:
        """Pick up to ``excess`` queued sequences to shed (engine lock
        held): best-effort lanes first, lowest priority then
        oldest-queued within it; the protected lane only contributes
        when it alone still exceeds the ceiling after every best-effort
        lane is empty."""
        if excess <= 0:
            return []
        p = self.config.protected_priority
        queued = [s for t in self._tenants.values() for s in t.waiting]
        best_effort = sorted((s for s in queued if s.priority < p),
                             key=lambda s: (s.priority, s.t_submit))
        victims = best_effort[:excess]
        excess -= len(victims)
        if excess > 0:
            ceiling = self.limiter.ceiling
            prot = sorted((s for s in queued if s.priority >= p),
                          key=lambda s: (s.priority, s.t_submit))
            prot_load = len(prot) + self._running_load(protected_only=True)
            over = int(prot_load - ceiling)
            if over > 0:
                victims.extend(prot[:min(over, excess)])
        for s in victims:
            self.drop(s)
            self._note_shed(self.tenant(s.tenant_id))
        return victims

    # ---------------------------------------------------------- visibility
    def iter_waiting(self):
        for t in self._tenants.values():
            for s in t.waiting:
                yield s

    def snapshot(self) -> Dict[str, object]:
        total_tokens = sum(t.admitted_tokens
                           for t in self._tenants.values()) or 1
        return {
            "limiter": self.limiter.snapshot(),
            "inflight": self.inflight(),
            "occupancy": round(self.occupancy(), 3),
            "oldest_wait_ms": round(self.oldest_wait_ms(), 1),
            "protected_priority": self.config.protected_priority,
            "tenants": {
                t.name: {
                    "weight": t.weight,
                    "queued": len(t.waiting),
                    "admitted": t.admitted_reqs,
                    "admitted_tokens": t.admitted_tokens,
                    "token_share": round(t.admitted_tokens / total_tokens,
                                         3),
                    "shed": t.shed,
                    "vtime": round(t.vtime, 1),
                } for t in sorted(self._tenants.values(),
                                  key=lambda t: t.name)
            },
        }


class QosGovernor:
    """The sampler-tick half of the closed loop: installed on the series
    registry's post-tick hooks by the engine, so once per second —
    right after the rings swept — it samples the queue-wait ring,
    updates the gradient ceiling, and sheds queued work down to it."""

    VAR = "g_serving_qos_queue_wait_latency"

    def __init__(self, engine):
        self.engine = engine
        self.ticks = 0
        self.sheds = 0

    def __call__(self, registry) -> None:
        self.tick(registry=registry)

    def sample_queue_wait(self, registry) -> float:
        """Latest 1-second sample of the queue-wait latency ring (µs);
        0.0 when the ring has no real samples yet."""
        if registry is None:
            return 0.0
        series = registry.get(self.VAR)
        if series is None or series.count < 1:
            return 0.0
        return float(series.second.ordered()[-1])

    def tick(self, registry=None, sample_us: Optional[float] = None) -> None:
        """One control-loop iteration (tests drive this directly with an
        explicit ``sample_us``; production runs it off the sampler)."""
        engine = self.engine
        qos = engine.qos
        if qos is None:
            return
        self.ticks += 1
        if sample_us is None:
            sample_us = self.sample_queue_wait(registry)
        with engine._cv:
            inflight = qos.inflight()
            ceiling = qos.limiter.observe(sample_us, inflight)
            excess = qos.total_depth() + qos._running_load() - int(ceiling)
            victims = qos.shed_victims(excess) if excess > 0 else []
            self.sheds += len(victims)
        for seq in victims:
            engine._finish(seq, errors.EOVERCROWDED,
                           "qos: shed under sustained overload "
                           "(retriable)")
