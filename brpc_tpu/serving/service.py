"""LlmService — the serving plane's RPC surface.

``Generate`` is an async method in the server's dispatch contract: the
handler returns None without calling ``done`` and the engine completes the
RPC from its step loop when generation finishes (or is rejected/aborted).
A request that arrives with stream settings (client created a stream and
set ``cntl.stream_id``) is accepted before admission; TokenDelta frames
then flow per step, so the client's first token arrives while the RPC is
still in flight — TTFT < full-generation latency by construction. On a
speculative engine (``EngineConfig(spec_k=)``) one step can commit up to
k+1 tokens, so a frame carries a token *list* plus ``accepted`` — how
many of those tokens were drafted and verifier-accepted (the +1 bonus
token is excluded); non-speculative frames stream one token with
``accepted == 0``. Frame concatenation equals the final response token
list either way.

Requests carrying stream settings take the server's full dispatch path
(the slim/fast lanes only accept requests without them), which is also
what stamps ``cntl.deadline_mono`` for the engine's admission re-check and
carries the span the engine annotates with prefill/decode phases.

With the radix prefix cache enabled the engine's admission path matches
the prompt (``prompt_tokens``, or the deterministic ``synth_prompt``
expansion of ``prompt_len``) against cached block chains — repeated
prompts fork the chain and prefill only the suffix, bit-identical to a
cold run by the greedy-decode contract. On a sharded fleet the client's
:class:`~brpc_tpu.serving.router.ShardedLlmChannel` prefix-hash routes
the request to the shard whose tree holds the chain; this service never
needs to know — placement agreement is in the route key.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from brpc_tpu.proto import serving_pb2
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.server import Service
from brpc_tpu.rpc.stream import StreamOptions, stream_accept
from brpc_tpu.serving.engine import ServingEngine


class LlmServingService(Service):
    DESCRIPTOR = serving_pb2.DESCRIPTOR.services_by_name["LlmService"]

    def __init__(self, engine: ServingEngine):
        super().__init__()
        self.engine = engine
        # inbound KV migration (disaggregated decode side / shard-death
        # survivor); built lazily so co-located deployments never touch
        # the migration module
        self._receiver = None

    def Generate(self, cntl, request, done):
        if request.resume_seq_id:
            # stage-2 of the disaggregated dispatch: attach to the
            # migrated sequence (no prompt, no admission, no allocation)
            stream_id = 0
            meta = getattr(cntl, "_srv_meta", None)
            if meta is not None and meta.stream_settings.stream_id:
                stream_id = stream_accept(cntl, StreamOptions())
            code, _seq = self.engine.submit(
                np.zeros(0, dtype=np.int32), 0, cntl=cntl, done=done,
                stream_id=stream_id,
                resume_seq_id=request.resume_seq_id)
            if code != 0:
                cntl.set_failed(code, "no such migrated sequence")
                return serving_pb2.GenerateResponse()
            return None  # async: completion comes from the step loop
        if request.prompt_tokens:
            prompt = np.asarray(request.prompt_tokens, dtype=np.int32)
        elif request.prompt_len > 0:
            prompt = self.engine.model.synth_prompt(request.prompt_len)
        else:
            cntl.set_failed(errors.EREQUEST,
                            "need prompt_tokens or prompt_len")
            return serving_pb2.GenerateResponse()
        stream_id = 0
        meta = getattr(cntl, "_srv_meta", None)
        if meta is not None and meta.stream_settings.stream_id:
            stream_id = stream_accept(cntl, StreamOptions())
        # QoS identity decoded off RequestMeta by the dispatch path; the
        # engine bills the named tenant's fair-share lane and sheds the
        # low-priority lanes first under overload
        code, _seq = self.engine.submit(
            prompt, request.max_new_tokens or 16,
            stop_token=request.stop_token, cntl=cntl, done=done,
            stream_id=stream_id,
            tenant_id=getattr(cntl, "tenant_id", ""),
            priority=getattr(cntl, "priority", 0))
        if code != 0:
            cntl.set_failed(code, "serving admission rejected")
            return serving_pb2.GenerateResponse()
        return None  # async: the engine's step loop calls done()

    def _migration_receiver(self):
        if self._receiver is None:
            from brpc_tpu.serving.migration import MigrationReceiver

            self._receiver = MigrationReceiver(self.engine)
            self.engine._migration_rx = self._receiver
        return self._receiver

    def MigrateOpen(self, cntl, request, done):
        """Inbound KV migration, phase 1: validate the manifest, stage a
        block chain, accept the caller's record stream. Synchronous —
        the reply only says "start streaming"."""
        return self._migration_receiver().open(cntl, request)

    def MigrateCommit(self, cntl, request, done):
        """Inbound KV migration, phase 2: block until every block is
        consumed and the sequence adopted (or the transfer failed /
        timed out). The reply IS the adoption ACK the source releases
        its chain on."""
        return self._migration_receiver().commit(cntl, request)

    def Stats(self, cntl, request, done):
        e = self.engine
        kv = e.kv.snapshot()
        return serving_pb2.ServingStats(
            seqs_running=e.running_count, seqs_waiting=e.queue_depth,
            kv_blocks_total=kv["blocks_total"],
            kv_blocks_used=kv["blocks_used"],
            steps=e.steps, tokens_generated=e.tokens_generated)
