"""Toy sharded transformer for the serving plane.

Small enough to decode on the CPU test substrate, shaped enough that every
device-side mechanism in the repo carries weight on the request path:

- **Weights by handle** — parameters are packed into one flat buffer and
  staged into HBM through ``DeviceStore.put`` (the device lane's single
  host→device crossing); compute looks them up by handle and unpacks
  device-side, so the serving plane owns no host-resident copy.
- **Paged KV** — prefill scatters K/V into the :class:`PagedKVCache`
  pools at block-table slots; decode gathers context pages and appends
  the new token's K/V, all inside ONE jitted program per engine step
  (donated pools → in-place updates, one dispatch for the whole mixed
  batch — the op-coalescing trick the device lane's dispatch thread plays,
  applied to the decode path).
- **Flash-attention prefill** — prompt self-attention runs the Pallas
  flash kernel from ``tpu/pallas_ops.py`` (interpret-mode on CPU), with
  the O(S²) reference as the numerics oracle; long prompts route through
  the ring-attention path (``tpu/ring.py``) which shard_maps across the
  ``sp`` mesh axis.
- **jax-0.4.37 shims** — shard_map comes through the same version-guarded
  import ``tpu/collective.py`` uses; sharded placement goes through
  ``tpu/mesh.named_sharding`` (jit follows input shardings — the pjit
  lowering on this jax line).

Shapes are bucketed (batch to powers of two, sequence to block-size
multiples) so the jit cache stays bounded across traffic mixes.
"""

from __future__ import annotations

import functools
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

from brpc_tpu.serving.kv_cache import PagedKVCache


class ModelConfig:
    def __init__(self, vocab: int = 512, d_model: int = 64,
                 n_heads: int = 4, n_layers: int = 2,
                 max_context: int = 1024, seed: int = 0,
                 attn: str = "auto", ring_threshold: int = 4096):
        if d_model % n_heads:
            raise ValueError("d_model must divide n_heads")
        self.vocab = vocab
        self.d_model = d_model
        self.n_heads = n_heads
        self.n_layers = n_layers
        self.max_context = max_context
        self.seed = seed
        # "auto": flash kernel on TPU, reference einsum on the CPU
        # substrate (interpret-mode Pallas is correct but slow); tests pin
        # "flash" to exercise the kernel path end to end.
        self.attn = attn
        self.ring_threshold = ring_threshold

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def kv_dim(self) -> int:
        return self.d_model


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def _rms(x):
    import jax
    import jax.numpy as jnp

    return x * jax.lax.rsqrt(
        jnp.mean(jnp.square(x), axis=-1, keepdims=True) + 1e-6)


def _decode_body(cfg: ModelConfig, params, kpool, vpool, tokens, positions,
                 slot_tables, B: int, L: int):
    """The fused decode math for ONE device's pool slice — shared verbatim
    by the single-device jit and the mesh shard_map body
    (serving/mesh_model.py), so sharded greedy decode is token-identical
    to single-device by construction.

    tokens (B,), positions (B,), slot_tables (B, L): flat pool slot for
    every context position (pads -> scratch block 0)."""
    import jax
    import jax.numpy as jnp

    H, hd = cfg.n_heads, cfg.head_dim
    x = params["embed"][tokens]                       # (B, D)
    write = slot_tables[jnp.arange(B), positions]     # (B,)
    mask = (jnp.arange(L)[None, :]
            <= positions[:, None])                    # (B, L)
    for l in range(cfg.n_layers):
        h = _rms(x)
        qkv = h @ params[f"wqkv{l}"]
        q, k, vv = jnp.split(qkv, 3, axis=-1)
        kpool = kpool.at[l, write].set(k)
        vpool = vpool.at[l, write].set(vv)
        ks = kpool[l][slot_tables]                    # (B, L, D)
        vs = vpool[l][slot_tables]
        qh = q.reshape(B, H, hd)
        kh = ks.reshape(B, L, H, hd)
        vh = vs.reshape(B, L, H, hd)
        s = jnp.einsum("bhd,blhd->bhl", qh, kh) / np.sqrt(hd)
        s = jnp.where(mask[:, None, :], s, -1e30)
        patt = jax.nn.softmax(s, axis=-1)
        attn = jnp.einsum("bhl,blhd->bhd", patt, vh)
        x = x + attn.reshape(B, -1) @ params[f"wo{l}"]
        h2 = _rms(x)
        x = x + jax.nn.relu(h2 @ params[f"w1{l}"]) @ params[f"w2{l}"]
    logits = _rms(x) @ params["embed"].T              # (B, V)
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return kpool, vpool, nxt


class TinyTransformer:
    """Weights + the fused prefill/decode programs over a PagedKVCache."""

    # the step-dispatch contract the engine asserts under BRPC_TPU_CHECK:
    # decode_step is ONE fused launch + ONE host materialization, counted
    # through tpu/device_lane.step_dispatch
    FUSED_STEP = True

    def __init__(self, config: ModelConfig, kv: PagedKVCache,
                 store=None, mesh=None):
        import jax

        from brpc_tpu.tpu.device_lane import global_store

        self.config = config
        self.kv = kv
        self.store = store if store is not None else kv.store
        self.mesh = mesh
        self._lock = threading.Lock()
        self._prefill_cache = {}
        self._decode_cache = {}
        self._on_tpu = jax.default_backend() == "tpu"

        # ---- weights: pack host-side once, stream into HBM by handle
        flat, self._offsets = self._init_weights(config)
        self.param_handle, self.param_nbytes = self.store.put(
            flat.tobytes())
        params_u8 = self.store.lookup(self.param_handle)
        self._params = self._unpack_params(params_u8)
        if mesh is not None:
            # replicate params across the mesh; jit follows the placement
            from brpc_tpu.tpu.mesh import named_sharding

            self._params = jax.device_put(
                self._params, named_sharding(mesh))

    # ------------------------------------------------------------- weights
    def _init_weights(self, cfg: ModelConfig):
        rng = np.random.RandomState(cfg.seed)
        d, v = cfg.d_model, cfg.vocab
        shapes = [("embed", (v, d))]
        for l in range(cfg.n_layers):
            shapes += [(f"wqkv{l}", (d, 3 * d)), (f"wo{l}", (d, d)),
                       (f"w1{l}", (d, 2 * d)), (f"w2{l}", (2 * d, d))]
        offsets = []
        pos = 0
        parts = []
        for name, shape in shapes:
            n = int(np.prod(shape))
            offsets.append((name, pos, shape))
            parts.append((rng.standard_normal(n) *
                          (0.5 / np.sqrt(shape[0]))).astype(np.float32))
            pos += n
        return np.concatenate(parts), offsets

    def _unpack_params(self, params_u8):
        """Device-side: reinterpret the staged byte buffer as the weight
        pytree (one bitcast + views, no host copy)."""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def unpack(u8):
            f32 = jax.lax.bitcast_convert_type(
                u8.reshape(-1, 4), jnp.float32).reshape(-1)
            return {name: f32[pos:pos + int(np.prod(shape))].reshape(shape)
                    for name, pos, shape in self._offsets}

        return jax.tree_util.tree_map(lambda x: x, unpack(params_u8))

    # ----------------------------------------------------------- attention
    def _use_flash(self) -> bool:
        if self.config.attn == "flash":
            return True
        if self.config.attn == "reference":
            return False
        return self._on_tpu

    # ------------------------------------------------------------- prefill
    def _prefill_fn(self, s_bucket: int, use_flash: bool):
        import jax
        import jax.numpy as jnp

        from brpc_tpu.tpu import pallas_ops

        cfg = self.config
        H, hd = cfg.n_heads, cfg.head_dim

        def rms(x):
            return x * jax.lax.rsqrt(
                jnp.mean(jnp.square(x), axis=-1, keepdims=True) + 1e-6)

        def impl(params, kpool, vpool, tokens, slots, length):
            x = params["embed"][tokens]                      # (S, D)
            for l in range(cfg.n_layers):
                h = rms(x)
                qkv = h @ params[f"wqkv{l}"]
                q, k, vv = jnp.split(qkv, 3, axis=-1)
                kpool = kpool.at[l, slots].set(k)
                vpool = vpool.at[l, slots].set(vv)
                qh = q.reshape(s_bucket, H, hd)
                kh = k.reshape(s_bucket, H, hd)
                vh = vv.reshape(s_bucket, H, hd)
                if use_flash:
                    attn = jax.vmap(
                        functools.partial(pallas_ops.flash_attention,
                                          causal=True),
                        in_axes=1, out_axes=1)(qh, kh, vh)
                else:
                    attn = jax.vmap(
                        functools.partial(pallas_ops.attention_reference,
                                          causal=True),
                        in_axes=1, out_axes=1)(qh, kh, vh)
                x = x + attn.reshape(s_bucket, -1) @ params[f"wo{l}"]
                h2 = rms(x)
                x = x + jax.nn.relu(h2 @ params[f"w1{l}"]) @ params[f"w2{l}"]
            last = rms(x[length - 1])
            logits = last @ params["embed"].T
            return kpool, vpool, jnp.argmax(logits).astype(jnp.int32)

        return jax.jit(impl, donate_argnums=(1, 2))

    def _slots_for(self, table: Sequence[int], upto: int,
                   pad_to: int) -> np.ndarray:
        """Flat pool slot per token position (host-side); padded positions
        point at scratch block 0."""
        bs = self.kv.block_size
        t = np.arange(pad_to, dtype=np.int32)
        tab = np.asarray(table, dtype=np.int32)
        blocks = np.where(t < upto, tab[np.minimum(t // bs,
                                                   len(tab) - 1)], 0)
        live = (t < upto).astype(np.int32)
        return (blocks * bs + (t % bs)) * live

    def prefill(self, tokens: np.ndarray, table: Sequence[int]) -> int:
        """Run prompt prefill for ONE sequence: scatter its K/V pages into
        the pool and return the first generated token (greedy). Long
        prompts take the ring-attention path."""
        cfg = self.config
        s = len(tokens)
        if s >= cfg.ring_threshold:
            return self._prefill_ring(tokens, table)
        self.kv.assert_writable(table, 0, s)
        bucket = max(16, _next_pow2(s))
        if bucket > 128:
            bucket = ((s + 127) // 128) * 128  # flash wants S % 128 == 0
        use_flash = self._use_flash()
        key = (bucket, use_flash)
        with self._lock:
            fn = self._prefill_cache.get(key)
            if fn is None:
                fn = self._prefill_fn(bucket, use_flash)
                self._prefill_cache[key] = fn
        toks = np.zeros(bucket, dtype=np.int32)
        toks[:s] = tokens
        slots = self._slots_for(table, s, bucket)
        from brpc_tpu.tpu.device_lane import step_dispatch
        step_dispatch.note_launch(1)
        kpool, vpool, nxt = fn(self._params, self.kv.k_pool,
                               self.kv.v_pool, toks, slots, s)
        self.kv.update_pools(kpool, vpool)
        first = int(nxt)
        step_dispatch.note_host_sync()
        return first

    def prefill_suffix(self, tokens: np.ndarray, table: Sequence[int],
                       start: int) -> int:
        """Prefill only ``tokens[start:]`` against a table whose first
        ``start`` positions already hold committed K/V (a forked prefix
        chain). Runs through the SAME fused decode program as steady-state
        decode — one row per suffix token, each gathering the full paged
        context — so a cache hit costs one decode-shaped launch and the
        written K/V (and the sampled token, row ``s - 1``'s argmax) are
        bit-identical to what cold prefill produces. Inherits to the mesh
        model unchanged: decode_step places rows by ``table.shard``."""
        s = len(tokens)
        if not 0 < start < s:
            raise ValueError(f"suffix start {start} outside (0, {s})")
        suffix = np.asarray(tokens[start:], dtype=np.int32)
        positions = np.arange(start, s, dtype=np.int32)
        out = self.decode_step(suffix, positions, [table] * (s - start))
        return int(out[-1])

    def _prefill_ring(self, tokens: np.ndarray,
                      table: Sequence[int]) -> int:
        """Long-context prefill: per-layer attention through the ring
        (sequence-sharded shard_map over the ``sp`` axis; single-device
        meshes degenerate to one hop). Layer loop runs host-side — prompts
        this long are rare and the per-layer ring call is itself fused."""
        import jax
        import jax.numpy as jnp

        from brpc_tpu.tpu import ring
        from brpc_tpu.tpu.mesh import default_mesh

        cfg = self.config
        H, hd = cfg.n_heads, cfg.head_dim
        mesh = self.mesh if (self.mesh is not None
                             and "sp" in self.mesh.axis_names) \
            else default_mesh("sp")
        n = mesh.shape["sp"]
        s = len(tokens)
        self.kv.assert_writable(table, 0, s)
        pad = ((s + n - 1) // n) * n
        p = self._params

        def rms(x):
            return x * jax.lax.rsqrt(
                jnp.mean(jnp.square(x), axis=-1, keepdims=True) + 1e-6)

        toks = np.zeros(pad, dtype=np.int32)
        toks[:s] = tokens
        x = p["embed"][jnp.asarray(toks)]
        kpool, vpool = self.kv.k_pool, self.kv.v_pool
        slots = jnp.asarray(self._slots_for(table, s, pad))
        from brpc_tpu.tpu.device_lane import step_dispatch
        for l in range(cfg.n_layers):
            h = rms(x)
            qkv = h @ p[f"wqkv{l}"]
            q, k, vv = jnp.split(qkv, 3, axis=-1)
            kpool = kpool.at[l, slots].set(k)
            vpool = vpool.at[l, slots].set(vv)
            qh = q.reshape(1, pad, H, hd)
            kh = k.reshape(1, pad, H, hd)
            vh = vv.reshape(1, pad, H, hd)
            step_dispatch.note_launch(1)
            attn = ring.ring_attention(qh, kh, vh, mesh, "sp", causal=True)
            x = x + attn.reshape(pad, -1) @ p[f"wo{l}"]
            h2 = rms(x)
            x = x + jax.nn.relu(h2 @ p[f"w1{l}"]) @ p[f"w2{l}"]
        self.kv.update_pools(kpool, vpool)
        logits = rms(x[s - 1]) @ p["embed"].T
        first = int(jnp.argmax(logits))
        step_dispatch.note_host_sync()
        return first

    # -------------------------------------------------------------- decode
    def _decode_fn(self, b_bucket: int, l_bucket: int):
        import jax

        cfg = self.config

        def impl(params, kpool, vpool, tokens, positions, slot_tables):
            return _decode_body(cfg, params, kpool, vpool, tokens,
                                positions, slot_tables, b_bucket, l_bucket)

        return jax.jit(impl, donate_argnums=(1, 2))

    def decode_step(self, tokens: np.ndarray, positions: np.ndarray,
                    tables: List[Sequence[int]]) -> np.ndarray:
        """ONE fused device dispatch for the whole decode batch: append
        each sequence's token at its position, gather paged context, and
        return the next token per sequence (host-materialized once, here,
        not per token)."""
        bs = self.kv.block_size
        B = len(tokens)
        self.kv.assert_writable_batch(tables, positions)
        b_bucket = max(2, _next_pow2(B))
        max_blocks = max(len(t) for t in tables)
        l_bucket = max(2, _next_pow2(max_blocks)) * bs
        key = (b_bucket, l_bucket)
        with self._lock:
            fn = self._decode_cache.get(key)
            if fn is None:
                fn = self._decode_fn(b_bucket, l_bucket)
                self._decode_cache[key] = fn
        toks = np.zeros(b_bucket, dtype=np.int32)
        toks[:B] = tokens
        pos = np.zeros(b_bucket, dtype=np.int32)
        pos[:B] = positions
        slot_tables = np.zeros((b_bucket, l_bucket), dtype=np.int32)
        for i, table in enumerate(tables):
            slot_tables[i] = self._slots_for(table, positions[i] + 1,
                                             l_bucket)
        from brpc_tpu.tpu.device_lane import step_dispatch
        step_dispatch.note_launch(1)
        kpool, vpool, nxt = fn(self._params, self.kv.k_pool,
                               self.kv.v_pool, toks, pos, slot_tables)
        self.kv.update_pools(kpool, vpool)
        out = np.asarray(nxt[:B])
        step_dispatch.note_host_sync()
        return out

    def verify_step(self, last_tokens: Sequence[int],
                    positions: Sequence[int], tables: List[Sequence[int]],
                    drafts: List[Sequence[int]]) -> List[np.ndarray]:
        """Speculative verify: ONE fused launch scoring every sequence's
        last committed token plus its k drafted tokens — k+1 rows per
        sequence flattened into the same fused decode program steady-state
        decode uses (the ``prefill_suffix`` trick, batched). Inside one
        launch every row's K/V write lands before any row's gather and
        the causal mask limits row j to positions ≤ its own, so row j
        attends over rows 0..j-1's *same-launch* writes: the returned
        argmax per row is exactly what k+1 sequential decode steps would
        produce. One launch, one host materialization — the (1,1)
        dispatch invariant holds for arbitrary k. Returns one array of
        k_i+1 argmax tokens per sequence (``m_0..m_k``: the verifier's
        next-token at the last committed position and after each draft).
        Rows of a sequence share its table, so the mesh model's
        shard-grouped ``decode_step`` keeps them on the owning dp shard
        in order — verify inherits bit-identical tp/dp lowering with no
        mesh-specific code."""
        flat_tokens: List[int] = []
        flat_pos: List[int] = []
        flat_tables: List[Sequence[int]] = []
        counts: List[int] = []
        for t0, p0, table, d in zip(last_tokens, positions, tables, drafts):
            row_toks = [int(t0)] + [int(x) for x in d]
            for j, tok in enumerate(row_toks):
                flat_tokens.append(tok)
                flat_pos.append(int(p0) + j)
                flat_tables.append(table)
            counts.append(len(row_toks))
        out = self.decode_step(np.asarray(flat_tokens, dtype=np.int32),
                               np.asarray(flat_pos, dtype=np.int32),
                               flat_tables)
        res: List[np.ndarray] = []
        off = 0
        for c in counts:
            res.append(out[off:off + c])
            off += c
        return res

    # ------------------------------------------------------------- helpers
    def close(self) -> None:
        self.store.free(self.param_handle)

    def synth_prompt(self, length: int) -> np.ndarray:
        """Deterministic prompt for bench/replay traffic (keyed only by
        length so a dumped corpus replays bit-identically)."""
        v = self.config.vocab
        return ((np.arange(length, dtype=np.int64) * 31 + 7)
                % (v - 1)).astype(np.int32) + 1
