"""Radix prefix cache: copy-on-write KV reuse over the paged pools.

The SGLang step on top of the vLLM one (RadixAttention, Zheng et al.
2024 over PagedAttention, Kwon et al. 2023): a radix tree over token-id
prefixes whose nodes map to committed, refcounted KV block chains in the
:class:`~brpc_tpu.serving.kv_cache.PagedKVCache` ledger. Each tree node
covers exactly ONE full block (``block_size`` token ids) and pins one
physical block via ``retain_block``; a root-to-node path is a
block-aligned prefix chain.

On admission the engine matches the longest block-aligned cached prefix
of the prompt and *forks* the chain — ``adopt_sequence`` bumps refcounts,
zero device copies — then prefills only the suffix. The match is capped
at ``len(prompt) - 1`` tokens so at least one suffix token always runs
through the model (the engine needs a first sampled token, and position
``len(prompt) - 1``'s K/V must be written by the new sequence anyway).
Writes into the divergence block go copy-on-write (``cow_block``): a
shared block is never mutated, so forked generations stay bit-identical
to cold-start.

On sequence completion the engine *commits* the sequence's full blocks
back into the tree: walking existing nodes shares them (the committer's
duplicate block simply frees with the sequence), new nodes take a cache
hold on the committer's block (insert-or-share).

Eviction is LRU over refcount-1 chains ONLY — a block some live sequence
still shares is never evicted, so decode headroom is never stolen — and
watermark-aware: commits trim the tree back under
``serving_prefix_evict_watermark`` occupancy, and admission that would
reject with EOVERCROWDED first asks the tree to give blocks back
(``evict_for_admission``). ``KVCacheFull`` semantics are unchanged: the
tree only ever *releases* holds, it cannot defer a rejection the
watermark would still make.

**Sharded mode** (:class:`ShardedPrefixCache`): one tree per dp shard,
each over its shard's ledger pool. Placement is prefix-hash routed —
``prefix_route_key`` folds the first cached-block-aligned window of
token ids (same FNV-1a spread as ``generate_route_key``) so same-prefix
traffic lands on the shard that holds the chain, fleet-wide, and the
:class:`~brpc_tpu.serving.router.GenerateRouter` computes the identical
shard client-side.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from brpc_tpu import fault as _fault
from brpc_tpu import flags as _flags
from brpc_tpu.metrics.reducer import Adder
from brpc_tpu.metrics.status import PassiveStatus
from brpc_tpu.serving.kv_cache import PagedKVCache, ShardedKVCache

_fault.register("serving.prefix.evict",
                "force radix prefix-cache eviction churn (blocks=)")

g_serving_prefix_hit_seqs = Adder("g_serving_prefix_hit_seqs")
g_serving_prefix_hit_blocks = Adder("g_serving_prefix_hit_blocks")
g_serving_prefix_hit_tokens = Adder("g_serving_prefix_hit_tokens")
g_serving_prefix_miss_seqs = Adder("g_serving_prefix_miss_seqs")
g_serving_prefix_inserted_blocks = Adder("g_serving_prefix_inserted_blocks")
g_serving_prefix_evicted_blocks = Adder("g_serving_prefix_evicted_blocks")


def _hit_ratio() -> float:
    hits = g_serving_prefix_hit_seqs.get_value()
    misses = g_serving_prefix_miss_seqs.get_value()
    total = hits + misses
    return hits / total if total else 0.0


g_serving_prefix_hit_ratio = PassiveStatus(_hit_ratio) \
    .expose("g_serving_prefix_hit_ratio")
g_serving_prefix_hit_ratio.prometheus_type = "gauge"


def prefix_route_key(tokens, block_size: int) -> Optional[int]:
    """Fold the first cached-block-aligned window of token ids into a
    64-bit route key — the SAME FNV-1a spread ``generate_route_key``
    uses, but over only ``tokens[:block_size]``, so every prompt sharing
    a cacheable first block hashes to the same shard. Returns None when
    the prompt cannot produce a cache hit at all (shorter than one full
    block plus the mandatory suffix token), letting callers fall back to
    whole-prompt routing."""
    if len(tokens) < block_size + 1:
        return None
    key = 0xCBF29CE484222325
    for t in tokens[:block_size]:
        key = ((key ^ (int(t) & 0xFFFFFFFF)) * 0x100000001B3) \
            & 0xFFFFFFFFFFFFFFFF
    return key


class _Node:
    """One full block of token ids; pins one physical block in the pool
    ledger while it lives in the tree."""

    __slots__ = ("key", "block", "children", "parent", "stamp")

    def __init__(self, key: Tuple[int, ...], block: int, parent):
        self.key = key
        self.block = block
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.parent = parent
        self.stamp = 0


class PrefixCache:
    """Radix tree over one pool's block-aligned prefixes.

    Lock order: the tree lock is OUTER, the pool's ledger lock inner
    (every ``kv.*`` call below takes it) — never the reverse."""

    def __init__(self, kv: PagedKVCache, shard: int = 0):
        self.kv = kv
        self.shard = shard
        self._lock = threading.Lock()
        self._root = _Node((), -1, None)
        self._tick = 0  # monotonic LRU clock (stamps, not wall time)
        self._nodes = 0
        self.hit_seqs = 0
        self.miss_seqs = 0
        self.hit_blocks = 0
        self.hit_tokens = 0
        self.inserted_blocks = 0
        self.evicted_blocks = 0

    @staticmethod
    def enabled() -> bool:
        return bool(_flags.get("serving_prefix_cache_enabled"))

    # ------------------------------------------------------------- matching
    def _walk_locked(self, tokens) -> List[_Node]:
        """Longest cached block-aligned chain covering a PROPER prefix of
        ``tokens`` — capped at ``len(tokens) - 1`` so the suffix prefill
        always has at least one token to run."""
        bs = self.kv.config.block_size
        limit = max(0, (len(tokens) - 1) // bs)
        chain: List[_Node] = []
        node = self._root
        for i in range(limit):
            key = tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])
            child = node.children.get(key)
            if child is None:
                break
            chain.append(child)
            node = child
        return chain

    def match_len(self, tokens) -> int:
        """Cached-prefix length in tokens (block-aligned, < len(tokens))."""
        with self._lock:
            return len(self._walk_locked(tokens)) * self.kv.config.block_size

    def route_shard(self, tokens) -> Optional[int]:
        return None  # single pool: nowhere to route

    # ---------------------------------------------------------------- fork
    def fork(self, seq_id: int, tokens) -> int:
        """Admission-side hit path: match the longest cached prefix, adopt
        its chain for ``seq_id`` (refcount++, zero copies), and return the
        matched token count — 0 on a miss (caller allocates cold)."""
        self._maybe_fault_evict()
        if not self.enabled():
            return 0
        with self._lock:
            chain = self._walk_locked(tokens)
            if not chain:
                self.miss_seqs += 1
                g_serving_prefix_miss_seqs.put(1)
                return 0
            self._tick += 1
            for n in chain:
                n.stamp = self._tick
            blocks = [n.block for n in chain]
            matched = len(blocks) * self.kv.config.block_size
            self.kv.adopt_sequence(seq_id, blocks, matched)
            self.hit_seqs += 1
            self.hit_blocks += len(blocks)
            self.hit_tokens += matched
        g_serving_prefix_hit_seqs.put(1)
        g_serving_prefix_hit_blocks.put(len(blocks))
        g_serving_prefix_hit_tokens.put(matched)
        return matched

    # -------------------------------------------------------------- commit
    def commit(self, seq_id: int, tokens, valid_len: int) -> int:
        """Completion-side insert-or-share: walk ``seq_id``'s table along
        the tree, sharing existing nodes and pinning new ones. Only FULL
        blocks whose K/V are entirely written (``valid_len``) commit; the
        committer's duplicate of an already-cached block simply frees
        with the sequence. Returns blocks newly inserted."""
        if not self.enabled():
            return 0
        table = self.kv.block_table(seq_id)
        if table is None:
            return 0
        bs = self.kv.config.block_size
        n_full = min(int(valid_len), len(tokens)) // bs
        inserted = 0
        with self._lock:
            self._tick += 1
            node = self._root
            for i in range(n_full):
                key = tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])
                child = node.children.get(key)
                if child is None:
                    self.kv.retain_block(table[i])
                    child = _Node(key, table[i], node)
                    node.children[key] = child
                    self._nodes += 1
                    inserted += 1
                child.stamp = self._tick
                node = child
        if inserted:
            self.inserted_blocks += inserted
            g_serving_prefix_inserted_blocks.put(inserted)
        self._trim()
        return inserted

    # ------------------------------------------------------------ eviction
    def _evictable_leaves_locked(self) -> List[_Node]:
        """Leaves whose block the tree is the SOLE owner of (refcount 1):
        chains a live sequence still shares are never stolen from."""
        out: List[_Node] = []
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            elif self.kv.block_ref(n.block) == 1:
                out.append(n)
        return out

    def _evict_locked(self, nblocks: int) -> int:
        """LRU-evict up to ``nblocks`` leaf blocks; freeing a leaf can
        expose its parent, so the candidate set is recomputed as the
        walk unwinds."""
        evicted = 0
        while evicted < nblocks:
            leaves = self._evictable_leaves_locked()
            if not leaves:
                break
            victim = min(leaves, key=lambda n: n.stamp)
            del victim.parent.children[victim.key]
            self._nodes -= 1
            self.kv.release_block(victim.block)
            evicted += 1
        if evicted:
            self.evicted_blocks += evicted
            g_serving_prefix_evicted_blocks.put(evicted)
        return evicted

    def _maybe_fault_evict(self) -> None:
        p = _fault.hit("serving.prefix.evict")
        if p is None:
            return
        with self._lock:
            self._evict_locked(int(p.get("blocks", 1)))

    def _trim(self) -> int:
        """Watermark-aware trim: give blocks back until pool occupancy is
        under ``serving_prefix_evict_watermark`` (or nothing evictable
        remains — shared chains stay)."""
        mark = float(_flags.get("serving_prefix_evict_watermark"))
        total = 0
        while self.kv.used_ratio() > mark:
            with self._lock:
                if not self._evict_locked(1):
                    break
            total += 1
        return total

    def evict_for_admission(self, ntokens: int, shard: Optional[int] = None,
                            route_key: Optional[int] = None) -> bool:
        """Give blocks back until the pool would admit ``ntokens`` —
        called on the EOVERCROWDED path BEFORE rejecting. Returns True if
        admission now passes; the watermark itself is unchanged, only
        tree-held (refcount-1) blocks are released."""
        while not self.kv.can_admit(ntokens):
            with self._lock:
                if not self._evict_locked(1):
                    return False
        return True

    # ------------------------------------------------------------ lifecycle
    def clear(self) -> int:
        """Release every tree hold (engine stop): the pool must audit
        idle afterwards."""
        released = 0
        with self._lock:
            stack = list(self._root.children.values())
            while stack:
                n = stack.pop()
                stack.extend(n.children.values())
                self.kv.release_block(n.block)
                released += 1
            self._root.children.clear()
            self._nodes = 0
        return released

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            nodes = self._nodes
        hits, misses = self.hit_seqs, self.miss_seqs
        total = hits + misses
        return {
            "enabled": self.enabled(),
            "nodes": nodes,
            "blocks": nodes,
            "hit_seqs": hits,
            "miss_seqs": misses,
            "hit_blocks": self.hit_blocks,
            "hit_tokens": self.hit_tokens,
            "inserted_blocks": self.inserted_blocks,
            "evicted_blocks": self.evicted_blocks,
            "hit_ratio": hits / total if total else 0.0,
        }


class ShardedPrefixCache:
    """One radix tree per dp shard, each over its shard's ledger pool.

    Placement must agree end to end: ``route_shard`` (server-side
    admission) and :class:`~brpc_tpu.serving.router.GenerateRouter`
    (client-side stub routing) both put ``prefix_route_key`` through the
    dispatch plane's splitmix64 ``shard_for`` — same-prefix traffic
    lands where the chain lives."""

    def __init__(self, kv: ShardedKVCache):
        from brpc_tpu.shard.plane import shard_for
        self.kv = kv
        self._route = shard_for
        self.trees = [PrefixCache(pool, shard=i)
                      for i, pool in enumerate(kv.pools)]

    @staticmethod
    def enabled() -> bool:
        return PrefixCache.enabled()

    def route_shard(self, tokens) -> Optional[int]:
        """Prefix-hash placement for a prompt, or None when it cannot hit
        the cache (too short) — callers fall back to seq-id routing."""
        if not self.enabled():
            return None
        key = prefix_route_key(tokens, self.kv.config.block_size)
        if key is None:
            return None
        return self._route(key, self.kv.n_shards)

    def match_len(self, tokens) -> int:
        shard = self.route_shard(tokens)
        if shard is None:
            return 0
        return self.trees[shard].match_len(tokens)

    def fork(self, seq_id: int, tokens) -> int:
        shard = self.route_shard(tokens)
        if shard is None:
            return 0
        matched = self.trees[shard].fork(seq_id, tokens)
        if matched:
            # the chain pins the sequence to its shard (adopt registered
            # it in that pool's ledger; routing must agree)
            self.kv.pin_shard(seq_id, shard)
        return matched

    def commit(self, seq_id: int, tokens, valid_len: int) -> int:
        got = self.kv._pool_of(seq_id)
        if got is None:
            return 0
        return self.trees[got[0]].commit(seq_id, tokens, valid_len)

    def evict_for_admission(self, ntokens: int, shard: Optional[int] = None,
                            route_key: Optional[int] = None) -> bool:
        if shard is None and route_key is not None:
            shard = self.kv.shard_of(route_key)
        if shard is None:
            return any(t.evict_for_admission(ntokens) for t in self.trees)
        return self.trees[shard].evict_for_admission(ntokens)

    def clear(self) -> int:
        return sum(t.clear() for t in self.trees)

    def snapshot(self) -> Dict[str, object]:
        shards = [t.snapshot() for t in self.trees]
        agg = {k: sum(s[k] for s in shards)
               for k in ("nodes", "blocks", "hit_seqs", "miss_seqs",
                         "hit_blocks", "hit_tokens", "inserted_blocks",
                         "evicted_blocks")}
        total = agg["hit_seqs"] + agg["miss_seqs"]
        agg["hit_ratio"] = agg["hit_seqs"] / total if total else 0.0
        agg["enabled"] = self.enabled()
        agg["shards"] = shards
        return agg


def build_prefix_cache(kv):
    """The engine's factory: per-shard trees over a ShardedKVCache, one
    tree over a plain pool."""
    if isinstance(kv, ShardedKVCache):
        return ShardedPrefixCache(kv)
    return PrefixCache(kv)
