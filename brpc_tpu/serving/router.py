"""Client-side sharded Generate routing over PartitionChannel.

When the serving fleet runs one engine per KV shard (each server owning
one slice of the paged pools), ``Generate`` must land on the shard that
will own the sequence's blocks — a partitioned call, not a fan-out. This
module rides :class:`~brpc_tpu.rpc.combo_channels.PartitionChannel`
(partition_channel.h:46-136 semantics) with a :class:`CallMapper` that
maps each Generate onto exactly ONE partition and ``SKIP``s the rest,
using the same splitmix64 spread (``shard.plane.shard_for``) the
server-side :class:`~brpc_tpu.serving.kv_cache.ShardedKVCache` uses for
block routing — so client routing and block ownership agree by
construction and stay stable under VersionedPool cid reuse.

Failure contract: a sub-call failure during Generate is a SHARD failure,
not a fleet failure. PartitionChannel surfaces it as ETOOMANYFAILS (the
parallel-call verdict); :class:`ShardedLlmChannel` translates that back
to retriable EFAILEDSOCKET so tunnel retry policies back off and retry —
while the owning engine's reap path frees every device-local block the
dead sequence held (tests/test_serving_mesh.py proves zero leaks under
an armed ledger).

``Stats`` stays a true fan-out: every shard reports, and
:class:`StatsMerger` sums the per-shard gauges into one fleet view.
"""

from __future__ import annotations

from typing import Optional

from brpc_tpu.proto import serving_pb2
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.channel import ChannelOptions, MethodDescriptor, RpcError
from brpc_tpu.rpc.combo_channels import (SKIP, CallMapper, PartitionChannel,
                                         PartitionParser, ResponseMerger,
                                         SubCall)
from brpc_tpu.rpc.controller import Controller

GENERATE_MD = MethodDescriptor("LlmService", "Generate",
                               serving_pb2.GenerateRequest,
                               serving_pb2.GenerateResponse)
STATS_MD = MethodDescriptor("LlmService", "Stats",
                            serving_pb2.ServingStatsRequest,
                            serving_pb2.ServingStats)


def generate_route_key(request) -> int:
    """Deterministic 64-bit route key for a GenerateRequest: fold the
    prompt (or its synth length) so identical requests land on the same
    shard and the splitmix64 avalanche in ``shard_for`` does the
    spreading — the raw fold does NOT need to be well-distributed."""
    key = 0xCBF29CE484222325
    toks = list(request.prompt_tokens) or [request.prompt_len]
    for t in toks:
        key = ((key ^ (int(t) & 0xFFFFFFFF)) * 0x100000001B3) \
            & 0xFFFFFFFFFFFFFFFF
    return key


def _request_route_key(request, block_size: int) -> int:
    """Prefix-hash route key: fold only the first cached-block-aligned
    window of the prompt (``prefix_route_key``) so every request sharing
    a cacheable first block routes to the shard whose radix tree holds
    the chain — the SAME placement the server's ShardedPrefixCache
    computes. Falls back to ``generate_route_key`` (whole-prompt fold)
    when the prompt cannot hit the cache."""
    from brpc_tpu.serving.prefix_cache import prefix_route_key

    toks = list(request.prompt_tokens)
    key = prefix_route_key(toks, block_size) if toks else None
    return key if key is not None else generate_route_key(request)


class GenerateRouter(CallMapper):
    """Generate -> the owning partition only; everything else fans out.

    The owning partition is ``shard_for(route_key, n)`` — the SAME spread
    the server's ShardedKVCache applies to seq ids, so a fleet whose
    shard i serves KV shard i gets client routing consistent with block
    ownership. With ``block_size`` set, the route key is the prefix hash
    (first cached block of token ids) so same-prefix traffic lands on the
    shard that holds the cached chain."""

    def __init__(self, partition_count: int, block_size: int = 0,
                 prefill_partitions: Optional[list] = None):
        self.partition_count = partition_count
        self.block_size = block_size
        # disaggregated two-stage dispatch: stage-1 Generates (fresh
        # prompts) spread over the prefill partitions only; stage-2
        # (resume_seq_id set) go to the decode shard the handoff named
        self.prefill_partitions = (list(prefill_partitions)
                                   if prefill_partitions else None)

    def route_key(self, request) -> int:
        if self.block_size:
            return _request_route_key(request, self.block_size)
        return generate_route_key(request)

    def owner_of(self, request) -> int:
        from brpc_tpu.shard.plane import shard_for

        if getattr(request, "resume_seq_id", 0):
            return int(request.resume_shard)
        if self.prefill_partitions is not None:
            return self.prefill_partitions[
                shard_for(self.route_key(request),
                          len(self.prefill_partitions))]
        return shard_for(self.route_key(request), self.partition_count)

    def map(self, channel_index: int, method: MethodDescriptor,
            request, response) -> object:
        if method.method_name == "Generate":
            if channel_index != self.owner_of(request):
                return SKIP
        return SubCall(method, request,
                       method.response_class() if method.response_class
                       else None)


class StatsMerger(ResponseMerger):
    """Sum per-shard ServingStats into the fleet view (proto3 MergeFrom
    would overwrite scalars, not add them). The parallel channel runs the
    merger on EVERY successful sub-call, Generate included — a Generate
    has exactly one live sub-call (the owner), so anything that isn't a
    ServingStats copies straight through."""

    FIELDS = ("seqs_running", "seqs_waiting", "kv_blocks_total",
              "kv_blocks_used", "steps", "tokens_generated")

    def merge(self, response, sub_response) -> int:
        if response is None or sub_response is None:
            return self.MERGED
        if not isinstance(sub_response, serving_pb2.ServingStats):
            response.CopyFrom(sub_response)
            return self.MERGED
        for f in self.FIELDS:
            setattr(response, f,
                    getattr(response, f) + getattr(sub_response, f))
        return self.MERGED


class ShardedLlmChannel:
    """Generate/Stats front door for a shard-per-server serving fleet.

    Wraps a PartitionChannel whose naming tags are ``i/n`` (server i owns
    KV shard i of n). ``fail_limit=1``: Generate issues exactly one
    sub-call, so its first failure IS the call's failure — and it comes
    back as EFAILEDSOCKET (retriable), never ETOOMANYFAILS, because the
    caller should treat a dead shard like a dead connection: back off,
    retry, land on the shard's replacement."""

    def __init__(self, ns_url: str, partition_count: int,
                 options: Optional[ChannelOptions] = None,
                 parser: Optional[PartitionParser] = None,
                 block_size: int = 0,
                 prefill_partitions: Optional[list] = None):
        self.partition_count = partition_count
        self._router = GenerateRouter(partition_count,
                                      block_size=block_size,
                                      prefill_partitions=prefill_partitions)
        self._pc = PartitionChannel(fail_limit=1)
        self._pc.init(ns_url, partition_count, parser=parser,
                      options=options,
                      call_mapper=self._router,
                      response_merger=StatsMerger())

    def shard_of(self, request) -> int:
        return self._router.owner_of(request)

    def _call_generate(self, request, cntl):
        try:
            return self._pc.call_method(GENERATE_MD, request,
                                        controller=cntl)
        except RpcError:
            # ONE sub-call was issued (the owner); its failure is a shard
            # failure — retriable, the engine's reap already returned the
            # sequence's device-local blocks
            detail = cntl.error_text()
            cntl.set_failed(
                errors.EFAILEDSOCKET,
                f"shard {self.shard_of(request)}/{self.partition_count} "
                f"failed mid-generate (retriable): {detail}")
            raise RpcError(cntl)

    def generate(self, request,
                 controller: Optional[Controller] = None,
                 timeout_ms: Optional[float] = None,
                 stream_factory=None):
        """One logical generation, any number of physical hops.

        On a disaggregated fleet the prefill shard answers with
        ``finish_reason == "handoff"`` and names the decode shard that
        adopted the sequence (``handoff_shard``/``seq_id``); this follows
        the handoff with a stage-2 resume call and stitches the two
        replies into the response a co-located fleet would have returned:
        tokens concatenated, prompt_len/ttft from the prefill stage,
        steps summed, seq_id/finish_reason from the decode stage.
        ``stream_factory()`` (optional) supplies a fresh stream id per
        hop so TokenDelta frames keep flowing across the handoff."""
        cntl = controller or Controller()
        if timeout_ms is not None:
            cntl.timeout_ms = timeout_ms
        resp = self._call_generate(request, cntl)
        hops = 0
        while (resp is not None and resp.finish_reason == "handoff"
               and hops < 4):
            hops += 1
            follow = serving_pb2.GenerateRequest(
                resume_seq_id=resp.seq_id,
                resume_shard=resp.handoff_shard)
            cntl2 = Controller()
            if timeout_ms is not None:
                cntl2.timeout_ms = timeout_ms
            if stream_factory is not None:
                cntl2.stream_id = stream_factory()
            stage2 = self._call_generate(follow, cntl2)
            stitched = serving_pb2.GenerateResponse(
                tokens=list(resp.tokens) + list(stage2.tokens),
                seq_id=stage2.seq_id,
                prompt_len=resp.prompt_len,
                steps=resp.steps + stage2.steps,
                ttft_us=resp.ttft_us,
                finish_reason=stage2.finish_reason,
                handoff_shard=stage2.handoff_shard)
            resp = stitched
        return resp

    def stats(self, controller: Optional[Controller] = None):
        return self._pc.call_method(
            STATS_MD, serving_pb2.ServingStatsRequest(),
            controller=controller)
