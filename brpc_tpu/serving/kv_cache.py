"""Paged KV cache over DeviceStore HBM handles.

vLLM-style block-paged KV management mapped onto this repo's device lane:
the K and V pools are single device arrays of ``num_blocks`` fixed-size
blocks, registered in a :class:`~brpc_tpu.tpu.device_lane.DeviceStore`
under stable handles (``adopt``/``replace``), so pool residency is visible
to /vars and DeviceStats like any other staged payload. Sequences own
*block tables* — host-side lists of physical block ids — that grow on
demand as decode appends tokens; allocation and free are refcounted so a
forked prefix can share blocks.

Admission backpressure is watermark-based: a new sequence is admitted only
while the pool (after its prefill blocks) stays under ``watermark`` of
capacity. The slack above the watermark is decode headroom — blocks that
*running* sequences may still grow into — so admission rejections
(surfaced as EOVERCROWDED, which the tunnel retry policy already treats as
retriable) come before mid-generation exhaustion, not instead of it.

Physical block 0 is a scratch block: padded lanes of the fused
prefill/decode programs scatter there, so it is never handed out and never
counted in capacity.

Under ``BRPC_TPU_CHECK=1`` every alloc/free re-audits the invariants
(free + used = capacity, refcounts consistent with tables), and
:meth:`PagedKVCache.assert_idle` gives teardown the same discipline the
CreditLedger gives tunnel windows: a chaos-killed generation must return
every block before the engine reports the pool whole.

**Sharded mode** (:class:`ShardedKVCache`): one block pool per ``dp``
shard of the serving mesh. Each shard keeps its own ledger-only
:class:`PagedKVCache` (free list, refcounts, watermark, CHECK audits —
per pool, exactly as single-device), while the device-resident K/V live
as ONE stacked ``(dp, layers, slots, kv_dim)`` pair sharded over the
``dp`` axis, so every shard's pool is resident on its own devices and
the fused decode program still launches ONCE for the whole mesh. Block
tables name (shard, block) pairs — a :class:`ShardTable` is the block-id
list plus the owning shard — and a sequence routes to its shard with the
same splitmix64 ``shard_for`` the dispatch plane uses (VersionedPool
``version << 32`` cids must spread, not pin to shard 0). fork/extend/
free stay device-local: they only ever touch the owning shard's ledger.
"""

from __future__ import annotations

import threading
import weakref
from typing import Dict, List, Optional, Tuple

import numpy as np

from brpc_tpu.metrics.reducer import Adder
from brpc_tpu.metrics.status import PassiveStatus

g_serving_kv_block_allocs = Adder("g_serving_kv_block_allocs")
g_serving_kv_block_frees = Adder("g_serving_kv_block_frees")
g_serving_kv_admission_rejects = Adder("g_serving_kv_admission_rejects")

_caches: "weakref.WeakSet[PagedKVCache]" = weakref.WeakSet()


def _sum_caches(attr) -> int:
    return sum(attr(c) for c in list(_caches))


g_serving_kv_blocks_total = PassiveStatus(
    lambda: _sum_caches(lambda c: c.num_blocks)) \
    .expose("g_serving_kv_blocks_total")
g_serving_kv_blocks_total.prometheus_type = "gauge"
g_serving_kv_blocks_used = PassiveStatus(
    lambda: _sum_caches(lambda c: c.used_blocks)) \
    .expose("g_serving_kv_blocks_used")
g_serving_kv_blocks_used.prometheus_type = "gauge"


class KVCacheFull(Exception):
    """Raised when the pool cannot satisfy an allocation (maps to
    EOVERCROWDED at the RPC surface)."""


class KVCacheConfig:
    def __init__(self, block_size: int = 16, num_blocks: int = 128,
                 watermark: float = 0.90):
        if block_size < 1 or num_blocks < 1:
            raise ValueError("block_size/num_blocks must be >= 1")
        if not 0.0 < watermark <= 1.0:
            raise ValueError("watermark must be in (0, 1]")
        self.block_size = block_size
        self.num_blocks = num_blocks
        self.watermark = watermark


class PagedKVCache:
    """Block manager + the device-resident K/V pools behind it.

    ``device_pools=False`` runs ledger-only: the full block/refcount/
    watermark/audit machinery with no device arrays of its own — how
    :class:`ShardedKVCache` gives every shard its own ledger while the
    device residency lives in the stacked per-mesh pools."""

    def __init__(self, config: KVCacheConfig, layers: int, kv_dim: int,
                 store=None, dtype=None, device_pools: bool = True):
        self.config = config
        self.layers = layers
        self.kv_dim = kv_dim
        self._lock = threading.Lock()
        self.store = store
        self.k_pool = self.v_pool = None
        self.k_handle = self.v_handle = 0
        if device_pools:
            import jax.numpy as jnp

            from brpc_tpu.tpu.device_lane import global_store

            if store is None:
                self.store = global_store()
            # physical block 0 is scratch (pad scatter target): +1 below
            slots = (config.num_blocks + 1) * config.block_size
            dtype = dtype or jnp.float32
            self.k_pool = jnp.zeros((layers, slots, kv_dim), dtype=dtype)
            self.v_pool = jnp.zeros((layers, slots, kv_dim), dtype=dtype)
            self.k_handle, _ = self.store.adopt(self.k_pool)
            self.v_handle, _ = self.store.adopt(self.v_pool)
        self._free: List[int] = list(range(config.num_blocks, 0, -1))
        self._ref: Dict[int, int] = {}
        self._tables: Dict[int, List[int]] = {}
        self._seq_len: Dict[int, int] = {}
        # blocks held by the prefix cache's radix tree (no table): each
        # hold contributes to _ref, audited as cache-held, not table-held
        self._cache_ref: Dict[int, int] = {}
        # sharded pools are ledger-only; their owner installs the device
        # copy used by cow_block against the stacked per-mesh pools
        self._cow_copy_fn = None
        # sequences audited + frozen for export (migration); any write
        # (extend/cow) clears the mark, so export_chain can only see a
        # chain with no in-flight mutations since its quiesce
        self._quiesced: set = set()
        self._check = False
        try:
            from brpc_tpu.analysis import runtime_check
            self._check = bool(runtime_check.ACTIVE)
        except Exception:
            pass
        if device_pools:
            # ledger-only shards are accounted by their ShardedKVCache,
            # not double-counted in the fleet totals
            _caches.add(self)

    # ------------------------------------------------------------- geometry
    @property
    def num_blocks(self) -> int:
        return self.config.num_blocks

    @property
    def block_size(self) -> int:
        return self.config.block_size

    @property
    def used_blocks(self) -> int:
        with self._lock:
            return self.config.num_blocks - len(self._free)

    @property
    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    def used_ratio(self) -> float:
        return self.used_blocks / float(self.config.num_blocks)

    def blocks_for(self, ntokens: int) -> int:
        bs = self.config.block_size
        return max(1, (ntokens + bs - 1) // bs)

    # ------------------------------------------------------------ admission
    def can_admit(self, ntokens: int, route_key: Optional[int] = None,
                  shard: Optional[int] = None) -> bool:
        """Watermark admission: the pool after this sequence's prefill
        blocks must stay at or under ``watermark`` of capacity, leaving
        the slack as decode headroom for sequences already running.
        (``shard`` is accepted for interface parity with the sharded
        cache; a single pool has nowhere else to route.)"""
        need = self.blocks_for(ntokens)
        limit = int(self.config.watermark * self.config.num_blocks)
        with self._lock:
            used = self.config.num_blocks - len(self._free)
            return used + need <= limit

    def note_rejected(self) -> None:
        g_serving_kv_admission_rejects.put(1)

    # ----------------------------------------------------------- block ops
    def _take_block_locked(self) -> int:
        if not self._free:
            raise KVCacheFull(
                f"kv pool exhausted ({self.config.num_blocks} blocks)")
        b = self._free.pop()
        self._ref[b] = 1
        return b

    def alloc_sequence(self, seq_id: int, ntokens: int) -> List[int]:
        """Allocate blocks covering an ``ntokens``-long prefix; returns the
        block table (physical ids, in position order)."""
        need = self.blocks_for(ntokens)
        with self._lock:
            if seq_id in self._tables:
                raise ValueError(f"sequence {seq_id} already has a table")
            if len(self._free) < need:
                g_serving_kv_admission_rejects.put(1)
                raise KVCacheFull(
                    f"need {need} blocks, {len(self._free)} free")
            table = [self._take_block_locked() for _ in range(need)]
            self._tables[seq_id] = table
            self._seq_len[seq_id] = ntokens
            self._audit_locked()
        g_serving_kv_block_allocs.put(need)
        return list(table)

    def extend_sequence(self, seq_id: int, new_len: int) -> List[int]:
        """Grow a block table so it covers ``new_len`` tokens (decode
        append). Shared blocks stay shared — only fresh tail blocks are
        allocated."""
        with self._lock:
            table = self._tables.get(seq_id)
            if table is None:
                raise KeyError(f"unknown sequence {seq_id}")
            need = self.blocks_for(new_len)
            grew = 0
            while len(table) < need:
                table.append(self._take_block_locked())
                grew += 1
            self._seq_len[seq_id] = new_len
            self._quiesced.discard(seq_id)
            self._audit_locked()
        if grew:
            g_serving_kv_block_allocs.put(grew)
        return list(table)

    def truncate_sequence(self, seq_id: int, new_len: int) -> int:
        """Shrink a table back to ``new_len`` tokens (speculative-decode
        rollback): tail blocks past ``blocks_for(new_len)`` drop one ref
        and return to the free list at zero, exactly mirroring
        ``free_sequence``'s accounting so the armed audit and the
        prefix-cache refcounts stay balanced. Returns blocks freed."""
        freed = 0
        with self._lock:
            table = self._tables.get(seq_id)
            if table is None:
                raise KeyError(f"unknown sequence {seq_id}")
            keep = self.blocks_for(new_len)
            while len(table) > keep:
                b = table.pop()
                self._ref[b] -= 1
                if self._ref[b] == 0:
                    del self._ref[b]
                    self._free.append(b)
                    freed += 1
            self._seq_len[seq_id] = min(self._seq_len.get(seq_id, new_len),
                                        new_len)
            self._quiesced.discard(seq_id)
            self._audit_locked()
        if freed:
            g_serving_kv_block_frees.put(freed)
        return freed

    def fork_sequence(self, src_seq: int, dst_seq: int) -> List[int]:
        """Share ``src``'s blocks with a new sequence (refcount++); the
        caller copies the partial tail block device-side before either
        sequence appends."""
        with self._lock:
            table = self._tables.get(src_seq)
            if table is None:
                raise KeyError(f"unknown sequence {src_seq}")
            if dst_seq in self._tables:
                raise ValueError(f"sequence {dst_seq} already has a table")
            for b in table:
                self._ref[b] += 1
            self._tables[dst_seq] = list(table)
            self._seq_len[dst_seq] = self._seq_len[src_seq]
            self._audit_locked()
        return list(self._tables[dst_seq])

    def adopt_sequence(self, seq_id: int, blocks: List[int],
                       ntokens: int) -> List[int]:
        """Register a new sequence whose table IS an existing block chain
        (a prefix-cache hit): refcount++ on every chain block, zero
        allocations, zero copies. The chain must be live (held by the
        radix tree and/or other sequences) and must cover ``ntokens``."""
        bs = self.config.block_size
        if ntokens > len(blocks) * bs:
            raise ValueError(f"chain of {len(blocks)} blocks cannot cover "
                             f"{ntokens} tokens (block_size {bs})")
        with self._lock:
            if seq_id in self._tables:
                raise ValueError(f"sequence {seq_id} already has a table")
            for b in blocks:
                if b not in self._ref:
                    raise KeyError(f"block {b} is not live")
            for b in blocks:
                self._ref[b] += 1
            self._tables[seq_id] = list(blocks)
            self._seq_len[seq_id] = ntokens
            self._audit_locked()
        return list(blocks)

    def retain_block(self, block: int) -> None:
        """Take a prefix-cache hold on a live block (radix-tree commit):
        the block survives free_sequence until release_block drops the
        hold. Cache holds are audited separately from table holds."""
        with self._lock:
            if block not in self._ref:
                raise KeyError(f"block {block} is not live")
            self._ref[block] += 1
            self._cache_ref[block] = self._cache_ref.get(block, 0) + 1
            self._audit_locked()

    def release_block(self, block: int) -> int:
        """Drop a prefix-cache hold (eviction / tree clear); the block
        returns to the free list when its refcount hits zero. Returns
        blocks actually freed (0 or 1)."""
        freed = 0
        with self._lock:
            held = self._cache_ref.get(block, 0)
            if held < 1:
                raise KeyError(f"block {block} has no cache hold")
            if held == 1:
                del self._cache_ref[block]
            else:
                self._cache_ref[block] = held - 1
            self._ref[block] -= 1
            if self._ref[block] == 0:
                del self._ref[block]
                self._free.append(block)
                freed = 1
            self._audit_locked()
        if freed:
            g_serving_kv_block_frees.put(freed)
        return freed

    def block_ref(self, block: int) -> int:
        with self._lock:
            return self._ref.get(block, 0)

    def cache_held_blocks(self) -> int:
        """Distinct blocks currently pinned by prefix-cache holds."""
        with self._lock:
            return len(self._cache_ref)

    # -------------------------------------------------------- copy-on-write
    def cow_block(self, seq_id: int, block_index: int) -> int:
        """Copy-on-write split: make ``table[block_index]`` exclusively
        owned by ``seq_id`` before a write lands in it. Exclusive blocks
        (refcount == 1) pass through untouched; shared ones get a fresh
        block, a device-side page copy, and the table entry swapped —
        the writer never mutates a block another chain can still read."""
        with self._lock:
            table = self._tables.get(seq_id)
            if table is None:
                raise KeyError(f"unknown sequence {seq_id}")
            if not 0 <= block_index < len(table):
                raise IndexError(f"block index {block_index} outside "
                                 f"table of {len(table)}")
            src = table[block_index]
            self._quiesced.discard(seq_id)  # a write is coming
            if self._ref.get(src, 0) == 1:
                return src  # exclusive already — no split needed
            dst = self._take_block_locked()
        g_serving_kv_block_allocs.put(1)
        # device page copy OUTSIDE the ledger lock (it dispatches); the
        # source stays refcounted by this sequence until the swap below
        copy = self._cow_copy_fn or self._cow_copy_block_device
        copy(dst, src)
        with self._lock:
            table[block_index] = dst
            self._ref[src] -= 1
            if self._ref[src] == 0:
                del self._ref[src]
                self._free.append(src)
            self._audit_locked()
        return dst

    def ensure_writable(self, seq_id: int, pos: int) -> int:
        """COW front door for the engine: split the block that the write
        at token position ``pos`` lands in, if shared. Returns the
        (possibly fresh) physical block id."""
        return self.cow_block(seq_id, pos // self.config.block_size)

    def _cow_copy_block_device(self, dst: int, src: int) -> None:
        if self.k_pool is None:
            return  # ledger-only pool with no cow hook installed
        bs = self.config.block_size
        d0, s0 = dst * bs, src * bs
        k = self.k_pool.at[:, d0:d0 + bs, :].set(
            self.k_pool[:, s0:s0 + bs, :])
        v = self.v_pool.at[:, d0:d0 + bs, :].set(
            self.v_pool[:, s0:s0 + bs, :])
        self.update_pools(k, v)

    def assert_writable(self, table, start: int, stop: int) -> None:
        """COW-contract guard (armed ledger only): every block the write
        range ``[start, stop)`` lands in must be exclusively owned —
        refcount 1 — else a shared (forked or tree-held) page would be
        silently clobbered. The serving model calls this before every
        pool-scattering launch; tpulint's cow-before-write rule keeps
        future write sites doing the same."""
        if not self._check or stop <= start:
            return
        bs = self.config.block_size
        with self._lock:
            for bi in range(start // bs, (stop - 1) // bs + 1):
                b = table[bi]
                ref = self._ref.get(b, 0)
                if ref != 1:
                    raise AssertionError(
                        f"cow violation: write in [{start},{stop}) hits "
                        f"block {b} (table[{bi}]) with refcount {ref}; "
                        f"shared blocks must be cow-split before writing")

    def assert_writable_batch(self, tables, positions) -> None:
        """Per-row COW guard for a decode batch: row i writes exactly at
        ``positions[i]`` in ``tables[i]``."""
        if not self._check:
            return
        for t, p in zip(tables, positions):
            self.assert_writable(t, int(p), int(p) + 1)

    # ------------------------------------------------------------ migration
    def quiesce_sequence(self, seq_id: int) -> int:
        """Freeze a sequence for export: re-audit the ledger and mark the
        chain quiesced. Any subsequent write (extend/cow) clears the mark,
        so :meth:`export_chain` can never serialize a chain with in-flight
        writes or un-audited refcounts. Returns the chain length covered
        (tokens). The engine calls this only once the step loop has no
        launch outstanding for the sequence."""
        with self._lock:
            if seq_id not in self._tables:
                raise KeyError(f"unknown sequence {seq_id}")
            # force the audit even on disarmed ledgers — exporting a chain
            # whose refcounts disagree with the tables ships corruption
            problems = self._invariant_problems_locked()
            if problems:
                raise AssertionError(
                    "refusing to quiesce over a broken ledger: " +
                    "; ".join(problems))
            self._quiesced.add(seq_id)
            return self._seq_len[seq_id]

    def export_chain(self, seq_id: int) -> Tuple[List[int], int]:
        """Snapshot a quiesced sequence's (block table, ntokens) for
        migration. The chain stays owned by the source until
        :meth:`release_exported` — the destination ACK is what moves
        ownership, so there is no window where the blocks belong to
        nobody (or to both sides)."""
        with self._lock:
            if seq_id not in self._tables:
                raise KeyError(f"unknown sequence {seq_id}")
            if seq_id not in self._quiesced:
                raise AssertionError(
                    f"export of sequence {seq_id} without quiesce: call "
                    f"quiesce_sequence first (no in-flight writes may be "
                    f"outstanding when a chain leaves the pool)")
            return list(self._tables[seq_id]), self._seq_len[seq_id]

    def release_exported(self, seq_id: int) -> int:
        """Drop the source's ownership of a migrated chain after the
        destination ACKed adoption. Returns blocks freed."""
        with self._lock:
            self._quiesced.discard(seq_id)
        return self.free_sequence(seq_id)

    def unquiesce_sequence(self, seq_id: int) -> None:
        """Abort an export (migration failed): the chain stays local and
        writable again."""
        with self._lock:
            self._quiesced.discard(seq_id)

    def free_sequence(self, seq_id: int) -> int:
        """Drop a sequence's table; blocks return to the free list when
        their refcount hits zero. Returns blocks actually freed."""
        freed = 0
        with self._lock:
            table = self._tables.pop(seq_id, None)
            self._seq_len.pop(seq_id, None)
            self._quiesced.discard(seq_id)
            if table is None:
                return 0
            for b in table:
                self._ref[b] -= 1
                if self._ref[b] == 0:
                    del self._ref[b]
                    self._free.append(b)
                    freed += 1
            self._audit_locked()
        if freed:
            g_serving_kv_block_frees.put(freed)
        return freed

    def block_table(self, seq_id: int) -> Optional[List[int]]:
        with self._lock:
            t = self._tables.get(seq_id)
            return list(t) if t is not None else None

    def seq_len(self, seq_id: int) -> int:
        with self._lock:
            return self._seq_len.get(seq_id, 0)

    def live_sequences(self) -> List[int]:
        with self._lock:
            return sorted(self._tables)

    # ------------------------------------------------------------ pool swap
    def update_pools(self, k_pool, v_pool) -> None:
        """Install the post-step pool arrays (functional update output) and
        re-point the DeviceStore handles at them — one swap per engine
        step, not per token."""
        self.k_pool = k_pool
        self.v_pool = v_pool
        self.store.replace(self.k_handle, k_pool)
        self.store.replace(self.v_handle, v_pool)

    # ---------------------------------------------------------------- audit
    def _audit_locked(self) -> None:
        if not self._check:
            return
        problems = self._invariant_problems_locked()
        if problems:
            raise AssertionError("kv ledger violation: " +
                                 "; ".join(problems))

    def _invariant_problems_locked(self) -> List[str]:
        problems: List[str] = []
        held: Dict[int, int] = {}
        for seq, table in self._tables.items():
            for b in table:
                held[b] = held.get(b, 0) + 1
        for b, n in self._cache_ref.items():
            held[b] = held.get(b, 0) + n
        if held != self._ref:
            problems.append(
                f"refcounts {self._ref} disagree with tables {held}")
        in_free = set(self._free)
        if len(in_free) != len(self._free):
            problems.append("duplicate block on the free list")
        overlap = in_free & set(held)
        if overlap:
            problems.append(f"blocks {sorted(overlap)} both free and held")
        if len(self._free) + len(self._ref) != self.config.num_blocks:
            problems.append(
                f"{len(self._free)} free + {len(self._ref)} held != "
                f"{self.config.num_blocks} capacity")
        return problems

    def assert_idle(self, context: str = "") -> None:
        """Teardown wholeness check, mirroring CreditLedger.assert_balanced:
        every block must be back on the free list with no refs held."""
        with self._lock:
            problems = self._invariant_problems_locked()
            if self._tables:
                problems.append(
                    f"{len(self._tables)} sequence table(s) still live: "
                    f"{sorted(self._tables)}")
            if self._cache_ref:
                problems.append(
                    f"{len(self._cache_ref)} block hold(s) still owned by "
                    f"the prefix cache: {sorted(self._cache_ref)}")
            if len(self._free) != self.config.num_blocks:
                problems.append(
                    f"{self.config.num_blocks - len(self._free)} "
                    f"block(s) leaked")
        if problems:
            where = f" [{context}]" if context else ""
            raise AssertionError(f"kv pool not idle{where}: " +
                                 "; ".join(problems))

    def close(self) -> None:
        if self.k_handle:
            self.store.free(self.k_handle)
            self.store.free(self.v_handle)

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            used = self.config.num_blocks - len(self._free)
            return {
                "block_size": self.config.block_size,
                "blocks_total": self.config.num_blocks,
                "blocks_used": used,
                "blocks_free": len(self._free),
                "watermark": self.config.watermark,
                "used_ratio": used / float(self.config.num_blocks),
                "sequences": len(self._tables),
                "blocks_cached": len(self._cache_ref),
            }


class ShardTable(list):
    """A block table that knows which dp shard owns it — the (device,
    block) naming of the sharded plane. It IS the plain block-id list
    everywhere the single-device path expects one; the mesh model reads
    ``.shard`` to place the sequence's compute and K/V scatter."""

    def __init__(self, shard: int, blocks):
        super().__init__(blocks)
        self.shard = shard


_sharded: "weakref.WeakSet[ShardedKVCache]" = weakref.WeakSet()


def _fleet_skew() -> float:
    """Worst per-device occupancy excess over its cache's fleet mean —
    the quantity the serving_shard_skew watch rule fires on. 0 when
    perfectly balanced (or nothing sharded is live)."""
    worst = 0.0
    for c in list(_sharded):
        ratios = [p.used_ratio() for p in c.pools]
        if ratios:
            worst = max(worst, max(ratios) - sum(ratios) / len(ratios))
    return worst


g_serving_kv_shard_skew = PassiveStatus(_fleet_skew) \
    .expose("g_serving_kv_shard_skew")
g_serving_kv_shard_skew.prometheus_type = "gauge"


class ShardedKVCache:
    """Per-device block pools over the serving mesh's ``dp`` axis.

    One ledger-only :class:`PagedKVCache` per shard carries the block
    accounting (watermark, refcounts, BRPC_TPU_CHECK audits — enforced
    PER POOL), while the device-resident K/V are ONE stacked
    ``(dp, layers, slots, kv_dim)`` array pair sharded over ``dp`` via
    :func:`~brpc_tpu.tpu.mesh.named_sharding`, registered once in the
    DeviceStore. Sequences route to shards with the dispatch plane's
    splitmix64 ``shard_for`` (stable under VersionedPool cid reuse);
    fork/extend/free only ever touch the owning shard's ledger."""

    def __init__(self, config: KVCacheConfig, layers: int, kv_dim: int,
                 mesh=None, store=None, dtype=None):
        import jax
        import jax.numpy as jnp

        from brpc_tpu.shard.plane import shard_for
        from brpc_tpu.tpu.device_lane import global_store
        from brpc_tpu.tpu.mesh import named_sharding, serving_mesh

        if mesh is None:
            mesh = serving_mesh()
        if "dp" not in mesh.axis_names:
            raise ValueError(f"mesh {mesh.axis_names} has no dp axis")
        self.config = config
        self.layers = layers
        self.kv_dim = kv_dim
        self.mesh = mesh
        self.n_shards = int(mesh.shape["dp"])
        self.store = store if store is not None else global_store()
        self._route = shard_for
        self._lock = threading.Lock()
        self.pools = [PagedKVCache(config, layers, kv_dim,
                                   device_pools=False)
                      for _ in range(self.n_shards)]
        for i, p in enumerate(self.pools):
            # ledger-only shards cow-copy through the stacked mesh pools
            p._cow_copy_fn = (lambda dst, src, _s=i:
                              self._cow_copy_block_shard(_s, dst, src))
        self._shard_of: Dict[int, int] = {}
        slots = (config.num_blocks + 1) * config.block_size
        dtype = dtype or jnp.float32
        shape = (self.n_shards, layers, slots, kv_dim)
        sharding = named_sharding(mesh, "dp")
        self.k_pools = jax.device_put(jnp.zeros(shape, dtype=dtype),
                                      sharding)
        self.v_pools = jax.device_put(jnp.zeros(shape, dtype=dtype),
                                      sharding)
        self.k_handle, _ = self.store.adopt(self.k_pools)
        self.v_handle, _ = self.store.adopt(self.v_pools)
        _caches.add(self)   # fleet totals (/vars) see the aggregate
        _sharded.add(self)  # skew gauge sees the per-shard spread

    # ------------------------------------------------------------- geometry
    @property
    def num_blocks(self) -> int:
        return self.config.num_blocks * self.n_shards

    @property
    def block_size(self) -> int:
        return self.config.block_size

    @property
    def used_blocks(self) -> int:
        return sum(p.used_blocks for p in self.pools)

    @property
    def free_blocks(self) -> int:
        return sum(p.free_blocks for p in self.pools)

    def used_ratio(self) -> float:
        return self.used_blocks / float(self.num_blocks)

    def blocks_for(self, ntokens: int) -> int:
        return self.pools[0].blocks_for(ntokens)

    # the CHECK arming surface tests use (kv._check = True) fans out to
    # every shard ledger — the audit contract is per pool
    @property
    def _check(self) -> bool:
        return any(p._check for p in self.pools)

    @_check.setter
    def _check(self, v: bool) -> None:
        for p in self.pools:
            p._check = v

    # -------------------------------------------------------------- routing
    def shard_of(self, seq_id: int) -> int:
        """The dp shard owning (or that would own) a sequence. Live
        sequences keep their pinned shard; new ones route by splitmix64,
        so a retry re-submitted with the same id lands on the same pool."""
        with self._lock:
            pinned = self._shard_of.get(seq_id)
        if pinned is not None:
            return pinned
        return self._route(seq_id, self.n_shards)

    def _pool_of(self, seq_id: int) -> Optional[Tuple[int, PagedKVCache]]:
        with self._lock:
            shard = self._shard_of.get(seq_id)
        if shard is None:
            return None
        return shard, self.pools[shard]

    # ------------------------------------------------------------ admission
    def can_admit(self, ntokens: int, route_key: Optional[int] = None,
                  shard: Optional[int] = None) -> bool:
        """Watermark admission against the OWNING shard's pool when the
        placement is known — an explicit ``shard`` (prefix-hash routing)
        beats the ``route_key`` hash — and against the fleet aggregate
        otherwise."""
        if shard is not None:
            return self.pools[shard].can_admit(ntokens)
        if route_key is not None:
            return self.pools[self.shard_of(route_key)].can_admit(ntokens)
        need = self.blocks_for(ntokens)
        limit = int(self.config.watermark * self.num_blocks)
        return self.used_blocks + need <= limit

    def note_rejected(self) -> None:
        g_serving_kv_admission_rejects.put(1)

    # ----------------------------------------------------------- block ops
    def alloc_sequence(self, seq_id: int, ntokens: int,
                       shard: Optional[int] = None) -> ShardTable:
        if shard is None:
            shard = self.shard_of(seq_id)
        table = self.pools[shard].alloc_sequence(seq_id, ntokens)
        with self._lock:
            self._shard_of[seq_id] = shard
        return ShardTable(shard, table)

    def pin_shard(self, seq_id: int, shard: int) -> None:
        """Pin a sequence to a shard ahead of ledger registration — the
        prefix cache pins hits to the shard whose tree holds the chain,
        overriding the splitmix64 route."""
        if not 0 <= shard < self.n_shards:
            raise ValueError(f"shard {shard} outside [0, {self.n_shards})")
        with self._lock:
            self._shard_of[seq_id] = shard

    def extend_sequence(self, seq_id: int, new_len: int) -> ShardTable:
        got = self._pool_of(seq_id)
        if got is None:
            raise KeyError(f"unknown sequence {seq_id}")
        shard, pool = got
        return ShardTable(shard, pool.extend_sequence(seq_id, new_len))

    def truncate_sequence(self, seq_id: int, new_len: int) -> int:
        got = self._pool_of(seq_id)
        if got is None:
            raise KeyError(f"unknown sequence {seq_id}")
        return got[1].truncate_sequence(seq_id, new_len)

    def fork_sequence(self, src_seq: int, dst_seq: int) -> ShardTable:
        """Device-local fork: the child shares the parent's blocks, so it
        MUST live on the parent's shard — the fork pins it there, not the
        hash route."""
        got = self._pool_of(src_seq)
        if got is None:
            raise KeyError(f"unknown sequence {src_seq}")
        shard, pool = got
        table = pool.fork_sequence(src_seq, dst_seq)
        with self._lock:
            self._shard_of[dst_seq] = shard
        return ShardTable(shard, table)

    def free_sequence(self, seq_id: int) -> int:
        with self._lock:
            shard = self._shard_of.pop(seq_id, None)
        if shard is None:
            return 0
        return self.pools[shard].free_sequence(seq_id)

    def adopt_sequence(self, seq_id: int, blocks, ntokens: int,
                       shard: Optional[int] = None) -> ShardTable:
        """Register a sequence over an existing live chain on ``shard``
        (migration staging adopt): refcount++ on every chain block, no
        allocation. Defaults to the chain's own shard when ``blocks`` is
        a :class:`ShardTable`."""
        if shard is None:
            shard = getattr(blocks, "shard", None)
        if shard is None:
            raise ValueError("adopt_sequence on a sharded pool needs the "
                             "owning shard (ShardTable or shard=)")
        table = self.pools[shard].adopt_sequence(seq_id, list(blocks),
                                                 ntokens)
        with self._lock:
            self._shard_of[seq_id] = shard
        return ShardTable(shard, table)

    # ------------------------------------------------------------ migration
    def quiesce_sequence(self, seq_id: int) -> int:
        got = self._pool_of(seq_id)
        if got is None:
            raise KeyError(f"unknown sequence {seq_id}")
        return got[1].quiesce_sequence(seq_id)

    def export_chain(self, seq_id: int) -> Tuple[ShardTable, int]:
        got = self._pool_of(seq_id)
        if got is None:
            raise KeyError(f"unknown sequence {seq_id}")
        shard, pool = got
        blocks, ntokens = pool.export_chain(seq_id)
        return ShardTable(shard, blocks), ntokens

    def release_exported(self, seq_id: int) -> int:
        with self._lock:
            shard = self._shard_of.pop(seq_id, None)
        if shard is None:
            return 0
        return self.pools[shard].release_exported(seq_id)

    def unquiesce_sequence(self, seq_id: int) -> None:
        got = self._pool_of(seq_id)
        if got is not None:
            got[1].unquiesce_sequence(seq_id)

    # -------------------------------------------------------- copy-on-write
    def cow_block(self, seq_id: int, block_index: int) -> int:
        got = self._pool_of(seq_id)
        if got is None:
            raise KeyError(f"unknown sequence {seq_id}")
        return got[1].cow_block(seq_id, block_index)

    def ensure_writable(self, seq_id: int, pos: int) -> int:
        return self.cow_block(seq_id, pos // self.config.block_size)

    def _cow_copy_block_shard(self, shard: int, dst: int, src: int) -> None:
        """Device page copy for a ledger-only shard pool, against the
        stacked per-mesh arrays (one functional update, one swap)."""
        bs = self.config.block_size
        d0, s0 = dst * bs, src * bs
        k = self.k_pools.at[shard, :, d0:d0 + bs, :].set(
            self.k_pools[shard, :, s0:s0 + bs, :])
        v = self.v_pools.at[shard, :, d0:d0 + bs, :].set(
            self.v_pools[shard, :, s0:s0 + bs, :])
        self.update_pools(k, v)

    def assert_writable(self, table, start: int, stop: int) -> None:
        self.pools[getattr(table, "shard", 0)].assert_writable(
            table, start, stop)

    def assert_writable_batch(self, tables, positions) -> None:
        if not self._check:
            return
        for t, p in zip(tables, positions):
            self.assert_writable(t, int(p), int(p) + 1)

    def block_table(self, seq_id: int) -> Optional[ShardTable]:
        got = self._pool_of(seq_id)
        if got is None:
            return None
        shard, pool = got
        table = pool.block_table(seq_id)
        return ShardTable(shard, table) if table is not None else None

    def seq_len(self, seq_id: int) -> int:
        got = self._pool_of(seq_id)
        return got[1].seq_len(seq_id) if got else 0

    def live_sequences(self) -> List[int]:
        out: List[int] = []
        for p in self.pools:
            out.extend(p.live_sequences())
        return sorted(out)

    # ------------------------------------------------------------ pool swap
    def update_pools(self, k_pools, v_pools) -> None:
        """Install the post-step stacked pools (functional update output)
        and re-point the DeviceStore handles — one swap per engine step
        for the WHOLE mesh, not per shard."""
        self.k_pools = k_pools
        self.v_pools = v_pools
        self.store.replace(self.k_handle, k_pools)
        self.store.replace(self.v_handle, v_pools)

    # ---------------------------------------------------------------- audit
    def assert_idle(self, context: str = "") -> None:
        for i, p in enumerate(self.pools):
            where = f"shard {i}" + (f", {context}" if context else "")
            p.assert_idle(where)
        with self._lock:
            if self._shard_of:
                raise AssertionError(
                    f"sharded kv not idle [{context}]: routing entries "
                    f"for {sorted(self._shard_of)} still pinned")

    def close(self) -> None:
        self.store.free(self.k_handle)
        self.store.free(self.v_handle)

    def snapshot(self) -> Dict[str, object]:
        used = self.used_blocks
        total = self.num_blocks
        dev_rows = np.asarray(self.mesh.devices).reshape(self.n_shards, -1)
        shards = []
        for i, p in enumerate(self.pools):
            s = p.snapshot()
            s["shard"] = i
            s["devices"] = [str(d) for d in dev_rows[i]]
            shards.append(s)
        with self._lock:
            shard_map = dict(sorted(self._shard_of.items()))
        ratios = [s["used_ratio"] for s in shards]
        return {
            "block_size": self.config.block_size,
            "blocks_total": total,
            "blocks_used": used,
            "blocks_free": total - used,
            "watermark": self.config.watermark,
            "used_ratio": used / float(total),
            "sequences": sum(s["sequences"] for s in shards),
            "blocks_cached": sum(s["blocks_cached"] for s in shards),
            "n_shards": self.n_shards,
            "shard_skew": max(ratios) - sum(ratios) / len(ratios),
            "shards": shards,
            "shard_map": shard_map,
        }
