"""Paged KV cache over DeviceStore HBM handles.

vLLM-style block-paged KV management mapped onto this repo's device lane:
the K and V pools are single device arrays of ``num_blocks`` fixed-size
blocks, registered in a :class:`~brpc_tpu.tpu.device_lane.DeviceStore`
under stable handles (``adopt``/``replace``), so pool residency is visible
to /vars and DeviceStats like any other staged payload. Sequences own
*block tables* — host-side lists of physical block ids — that grow on
demand as decode appends tokens; allocation and free are refcounted so a
forked prefix can share blocks.

Admission backpressure is watermark-based: a new sequence is admitted only
while the pool (after its prefill blocks) stays under ``watermark`` of
capacity. The slack above the watermark is decode headroom — blocks that
*running* sequences may still grow into — so admission rejections
(surfaced as EOVERCROWDED, which the tunnel retry policy already treats as
retriable) come before mid-generation exhaustion, not instead of it.

Physical block 0 is a scratch block: padded lanes of the fused
prefill/decode programs scatter there, so it is never handed out and never
counted in capacity.

Under ``BRPC_TPU_CHECK=1`` every alloc/free re-audits the invariants
(free + used = capacity, refcounts consistent with tables), and
:meth:`PagedKVCache.assert_idle` gives teardown the same discipline the
CreditLedger gives tunnel windows: a chaos-killed generation must return
every block before the engine reports the pool whole.
"""

from __future__ import annotations

import threading
import weakref
from typing import Dict, List, Optional, Tuple

from brpc_tpu.metrics.reducer import Adder
from brpc_tpu.metrics.status import PassiveStatus

g_serving_kv_block_allocs = Adder("g_serving_kv_block_allocs")
g_serving_kv_block_frees = Adder("g_serving_kv_block_frees")
g_serving_kv_admission_rejects = Adder("g_serving_kv_admission_rejects")

_caches: "weakref.WeakSet[PagedKVCache]" = weakref.WeakSet()


def _sum_caches(attr) -> int:
    return sum(attr(c) for c in list(_caches))


g_serving_kv_blocks_total = PassiveStatus(
    lambda: _sum_caches(lambda c: c.num_blocks)) \
    .expose("g_serving_kv_blocks_total")
g_serving_kv_blocks_total.prometheus_type = "gauge"
g_serving_kv_blocks_used = PassiveStatus(
    lambda: _sum_caches(lambda c: c.used_blocks)) \
    .expose("g_serving_kv_blocks_used")
g_serving_kv_blocks_used.prometheus_type = "gauge"


class KVCacheFull(Exception):
    """Raised when the pool cannot satisfy an allocation (maps to
    EOVERCROWDED at the RPC surface)."""


class KVCacheConfig:
    def __init__(self, block_size: int = 16, num_blocks: int = 128,
                 watermark: float = 0.90):
        if block_size < 1 or num_blocks < 1:
            raise ValueError("block_size/num_blocks must be >= 1")
        if not 0.0 < watermark <= 1.0:
            raise ValueError("watermark must be in (0, 1]")
        self.block_size = block_size
        self.num_blocks = num_blocks
        self.watermark = watermark


class PagedKVCache:
    """Block manager + the device-resident K/V pools behind it."""

    def __init__(self, config: KVCacheConfig, layers: int, kv_dim: int,
                 store=None, dtype=None):
        import jax.numpy as jnp

        from brpc_tpu.tpu.device_lane import global_store

        self.config = config
        self.layers = layers
        self.kv_dim = kv_dim
        self.store = store if store is not None else global_store()
        self._lock = threading.Lock()
        # physical block 0 is scratch (pad scatter target): +1 below
        slots = (config.num_blocks + 1) * config.block_size
        dtype = dtype or jnp.float32
        self.k_pool = jnp.zeros((layers, slots, kv_dim), dtype=dtype)
        self.v_pool = jnp.zeros((layers, slots, kv_dim), dtype=dtype)
        self.k_handle, _ = self.store.adopt(self.k_pool)
        self.v_handle, _ = self.store.adopt(self.v_pool)
        self._free: List[int] = list(range(config.num_blocks, 0, -1))
        self._ref: Dict[int, int] = {}
        self._tables: Dict[int, List[int]] = {}
        self._seq_len: Dict[int, int] = {}
        self._check = False
        try:
            from brpc_tpu.analysis import runtime_check
            self._check = bool(runtime_check.ACTIVE)
        except Exception:
            pass
        _caches.add(self)

    # ------------------------------------------------------------- geometry
    @property
    def num_blocks(self) -> int:
        return self.config.num_blocks

    @property
    def block_size(self) -> int:
        return self.config.block_size

    @property
    def used_blocks(self) -> int:
        with self._lock:
            return self.config.num_blocks - len(self._free)

    @property
    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    def used_ratio(self) -> float:
        return self.used_blocks / float(self.config.num_blocks)

    def blocks_for(self, ntokens: int) -> int:
        bs = self.config.block_size
        return max(1, (ntokens + bs - 1) // bs)

    # ------------------------------------------------------------ admission
    def can_admit(self, ntokens: int) -> bool:
        """Watermark admission: the pool after this sequence's prefill
        blocks must stay at or under ``watermark`` of capacity, leaving
        the slack as decode headroom for sequences already running."""
        need = self.blocks_for(ntokens)
        limit = int(self.config.watermark * self.config.num_blocks)
        with self._lock:
            used = self.config.num_blocks - len(self._free)
            return used + need <= limit

    def note_rejected(self) -> None:
        g_serving_kv_admission_rejects.put(1)

    # ----------------------------------------------------------- block ops
    def _take_block_locked(self) -> int:
        if not self._free:
            raise KVCacheFull(
                f"kv pool exhausted ({self.config.num_blocks} blocks)")
        b = self._free.pop()
        self._ref[b] = 1
        return b

    def alloc_sequence(self, seq_id: int, ntokens: int) -> List[int]:
        """Allocate blocks covering an ``ntokens``-long prefix; returns the
        block table (physical ids, in position order)."""
        need = self.blocks_for(ntokens)
        with self._lock:
            if seq_id in self._tables:
                raise ValueError(f"sequence {seq_id} already has a table")
            if len(self._free) < need:
                g_serving_kv_admission_rejects.put(1)
                raise KVCacheFull(
                    f"need {need} blocks, {len(self._free)} free")
            table = [self._take_block_locked() for _ in range(need)]
            self._tables[seq_id] = table
            self._seq_len[seq_id] = ntokens
            self._audit_locked()
        g_serving_kv_block_allocs.put(need)
        return list(table)

    def extend_sequence(self, seq_id: int, new_len: int) -> List[int]:
        """Grow a block table so it covers ``new_len`` tokens (decode
        append). Shared blocks stay shared — only fresh tail blocks are
        allocated."""
        with self._lock:
            table = self._tables.get(seq_id)
            if table is None:
                raise KeyError(f"unknown sequence {seq_id}")
            need = self.blocks_for(new_len)
            grew = 0
            while len(table) < need:
                table.append(self._take_block_locked())
                grew += 1
            self._seq_len[seq_id] = new_len
            self._audit_locked()
        if grew:
            g_serving_kv_block_allocs.put(grew)
        return list(table)

    def fork_sequence(self, src_seq: int, dst_seq: int) -> List[int]:
        """Share ``src``'s blocks with a new sequence (refcount++); the
        caller copies the partial tail block device-side before either
        sequence appends."""
        with self._lock:
            table = self._tables.get(src_seq)
            if table is None:
                raise KeyError(f"unknown sequence {src_seq}")
            if dst_seq in self._tables:
                raise ValueError(f"sequence {dst_seq} already has a table")
            for b in table:
                self._ref[b] += 1
            self._tables[dst_seq] = list(table)
            self._seq_len[dst_seq] = self._seq_len[src_seq]
            self._audit_locked()
        return list(self._tables[dst_seq])

    def free_sequence(self, seq_id: int) -> int:
        """Drop a sequence's table; blocks return to the free list when
        their refcount hits zero. Returns blocks actually freed."""
        freed = 0
        with self._lock:
            table = self._tables.pop(seq_id, None)
            self._seq_len.pop(seq_id, None)
            if table is None:
                return 0
            for b in table:
                self._ref[b] -= 1
                if self._ref[b] == 0:
                    del self._ref[b]
                    self._free.append(b)
                    freed += 1
            self._audit_locked()
        if freed:
            g_serving_kv_block_frees.put(freed)
        return freed

    def block_table(self, seq_id: int) -> Optional[List[int]]:
        with self._lock:
            t = self._tables.get(seq_id)
            return list(t) if t is not None else None

    def seq_len(self, seq_id: int) -> int:
        with self._lock:
            return self._seq_len.get(seq_id, 0)

    def live_sequences(self) -> List[int]:
        with self._lock:
            return sorted(self._tables)

    # ------------------------------------------------------------ pool swap
    def update_pools(self, k_pool, v_pool) -> None:
        """Install the post-step pool arrays (functional update output) and
        re-point the DeviceStore handles at them — one swap per engine
        step, not per token."""
        self.k_pool = k_pool
        self.v_pool = v_pool
        self.store.replace(self.k_handle, k_pool)
        self.store.replace(self.v_handle, v_pool)

    # ---------------------------------------------------------------- audit
    def _audit_locked(self) -> None:
        if not self._check:
            return
        problems = self._invariant_problems_locked()
        if problems:
            raise AssertionError("kv ledger violation: " +
                                 "; ".join(problems))

    def _invariant_problems_locked(self) -> List[str]:
        problems: List[str] = []
        held: Dict[int, int] = {}
        for seq, table in self._tables.items():
            for b in table:
                held[b] = held.get(b, 0) + 1
        if held != self._ref:
            problems.append(
                f"refcounts {self._ref} disagree with tables {held}")
        in_free = set(self._free)
        if len(in_free) != len(self._free):
            problems.append("duplicate block on the free list")
        overlap = in_free & set(held)
        if overlap:
            problems.append(f"blocks {sorted(overlap)} both free and held")
        if len(self._free) + len(self._ref) != self.config.num_blocks:
            problems.append(
                f"{len(self._free)} free + {len(self._ref)} held != "
                f"{self.config.num_blocks} capacity")
        return problems

    def assert_idle(self, context: str = "") -> None:
        """Teardown wholeness check, mirroring CreditLedger.assert_balanced:
        every block must be back on the free list with no refs held."""
        with self._lock:
            problems = self._invariant_problems_locked()
            if self._tables:
                problems.append(
                    f"{len(self._tables)} sequence table(s) still live: "
                    f"{sorted(self._tables)}")
            if len(self._free) != self.config.num_blocks:
                problems.append(
                    f"{self.config.num_blocks - len(self._free)} "
                    f"block(s) leaked")
        if problems:
            where = f" [{context}]" if context else ""
            raise AssertionError(f"kv pool not idle{where}: " +
                                 "; ".join(problems))

    def close(self) -> None:
        self.store.free(self.k_handle)
        self.store.free(self.v_handle)

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            used = self.config.num_blocks - len(self._free)
            return {
                "block_size": self.config.block_size,
                "blocks_total": self.config.num_blocks,
                "blocks_used": used,
                "blocks_free": len(self._free),
                "watermark": self.config.watermark,
                "used_ratio": used / float(self.config.num_blocks),
                "sequences": len(self._tables),
            }
