"""policy — pluggable protocols, load balancers, naming services, limiters.

Counterpart of the reference's ``src/brpc/policy`` + the registration moment
``GlobalInitializeOrDie`` (global.cpp:370-626): ``ensure_registered()`` is
idempotent and wires every built-in policy into the registries.
"""

from __future__ import annotations

import threading

_done = False
_lock = threading.Lock()


def ensure_registered() -> None:
    global _done
    with _lock:
        if _done:
            return
        from brpc_tpu.rpc.protocol import register_protocol
        from brpc_tpu.policy.trpc_std import TrpcStdProtocol
        from brpc_tpu.policy.trpc_stream import TrpcStreamProtocol
        from brpc_tpu.policy.http_protocol import HttpProtocol
        from brpc_tpu.policy.grpc_protocol import GrpcProtocol

        from brpc_tpu.policy.mongo_protocol import MongoProtocol
        from brpc_tpu.policy.rtmp import RtmpProtocol
        from brpc_tpu.policy.redis_protocol import RedisProtocol
        from brpc_tpu.policy.thrift_protocol import ThriftProtocol
        from brpc_tpu.policy.memcache import MemcacheProtocol
        from brpc_tpu.policy.nshead import NsheadProtocol

        from brpc_tpu.tpu.transport import TpuCtrlProtocol

        register_protocol(TrpcStdProtocol())
        register_protocol(TrpcStreamProtocol())
        # early: TPUC magic must never reach text-probing protocols (redis
        # inline commands would happily eat it)
        register_protocol(TpuCtrlProtocol())
        # grpc before http: the h2 preface ("PRI * HTTP/2.0...") would
        # otherwise parse as an HTTP/1 request-line
        register_protocol(GrpcProtocol())
        register_protocol(RedisProtocol())
        register_protocol(MongoProtocol())
        register_protocol(RtmpProtocol())
        register_protocol(ThriftProtocol())
        register_protocol(MemcacheProtocol())
        register_protocol(NsheadProtocol())
        register_protocol(HttpProtocol())  # probed last: magic-less
        try:  # activate the C++ core (crc32c/fast_rand); fall back silently
            from brpc_tpu import native

            native.install()
        except Exception:
            pass
        _done = True
