"""mongo — MongoDB wire protocol (OP_MSG), client + server side.

Counterpart of the reference's ``policy/mongo_protocol.cpp``. Modern wire
format only (OP_MSG, opcode 2013, mongo >= 3.6): 16-byte little-endian
header (messageLength, requestID, responseTo, opCode) + uint32 flagBits +
one kind-0 section carrying a single BSON command/reply document.

Correlation is native to the wire: each request gets a fresh requestID and
the reply's responseTo names it — so unlike RESP there is no positional
FIFO; out-of-order replies (mongo exhaust/parallel cursors) correlate
correctly.

Client:   ch = Channel(ChannelOptions(protocol="mongo")).init(addr)
          resp = ch.call_method(mongo_method(),
                                MongoRequest({"ping": 1, "$db": "admin"}))
          resp.document -> {"ok": 1.0, ...}
Server:   ServerOptions(mongo_service=MongoService()) with
          add_command_handler("find", fn(doc) -> reply_doc) — the fake-
          mongod test substrate (the reference tests the same way).
"""

from __future__ import annotations

import struct
import threading
from typing import Callable, Dict, Optional, Tuple

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.fiber import runtime
from brpc_tpu.policy import bson
from brpc_tpu.proto import rpc_meta_pb2
from brpc_tpu.rpc.protocol import (
    PARSE_BAD,
    PARSE_NOT_ENOUGH_DATA,
    PARSE_TRY_OTHERS,
    ParsedMessage,
    Protocol,
)

OP_MSG = 2013
HEADER = "<iiii"
HEADER_SIZE = 16
MAX_MESSAGE = 48 << 20  # mongo's own maxMessageSizeBytes

_next_request_id = [1]
_rid_lock = threading.Lock()


def _fresh_request_id() -> int:
    with _rid_lock:
        rid = _next_request_id[0]
        _next_request_id[0] = (rid + 1) & 0x7FFFFFFF or 1
        return rid


def pack_msg(request_id: int, response_to: int, doc: dict) -> bytes:
    body = struct.pack("<I", 0) + b"\x00" + bson.encode(doc)
    return struct.pack(HEADER, HEADER_SIZE + len(body), request_id,
                       response_to, OP_MSG) + body


def unpack_msg_body(body: bytes) -> dict:
    if len(body) < 5:
        raise bson.BsonError("OP_MSG body too short")
    # flagBits(4) + section kind byte; only kind 0 (single document) is
    # accepted — and nothing may FOLLOW it (silently dropping a kind-1
    # document sequence would lose payload, e.g. a driver's insert docs)
    if body[4] != 0:
        raise bson.BsonError(f"unsupported OP_MSG section kind {body[4]}")
    doc, end = bson._decode_doc(bytes(body), 5)
    if end != len(body):
        raise bson.BsonError("unsupported extra OP_MSG sections")
    return doc


class MongoRequest:
    """One command document (SerializeToString carries flags+section; the
    header with its fresh requestID is added at issue time)."""

    def __init__(self, document: Optional[dict] = None):
        self.document = dict(document or {})

    def SerializeToString(self) -> bytes:
        return struct.pack("<I", 0) + b"\x00" + bson.encode(self.document)

    def ParseFromString(self, data: bytes) -> None:  # for rpc_replay
        self.document = unpack_msg_body(bytes(data))


class MongoResponse:
    def __init__(self):
        self.document: dict = {}

    @property
    def ok(self) -> bool:
        return float(self.document.get("ok", 0)) == 1.0

    def ParseFromString(self, data: bytes) -> None:
        self.document = unpack_msg_body(bytes(data))

    def SerializeToString(self) -> bytes:
        return struct.pack("<I", 0) + b"\x00" + bson.encode(self.document)


def mongo_method():
    from brpc_tpu.rpc.channel import MethodDescriptor

    return MethodDescriptor("mongo", "command", MongoRequest, MongoResponse)


class MongoService:
    """Server-side command registry (the fake-mongod test substrate)."""

    def __init__(self):
        self._handlers: Dict[str, Callable[[dict], dict]] = {}
        self.add_command_handler("ping", lambda doc: {"ok": 1.0})
        self.add_command_handler(
            "hello", lambda doc: {"ok": 1.0, "isWritablePrimary": True,
                                  "maxWireVersion": 17})

    def add_command_handler(self, name: str,
                            handler: Callable[[dict], dict]) -> "MongoService":
        self._handlers[name.lower()] = handler
        return self

    def handle(self, doc: dict) -> dict:
        if not doc:
            return {"ok": 0.0, "errmsg": "empty command", "code": 22}
        cmd = next(iter(doc)).lower()
        handler = self._handlers.get(cmd)
        if handler is None:
            return {"ok": 0.0, "errmsg": f"no such command: '{cmd}'",
                    "code": 59}
        try:
            return handler(doc)
        except Exception as e:
            return {"ok": 0.0, "errmsg": str(e), "code": 8}


class _MongoClientState:
    __slots__ = ("inflight", "lock")

    def __init__(self):
        self.inflight: Dict[int, Tuple[int, int]] = {}  # rid -> (cid, ver)
        self.lock = threading.Lock()


class MongoProtocol(Protocol):
    name = "mongo"
    stateful = True

    # ------------------------------------------------------------- recv path
    def parse(self, buf: IOBuf, sock=None):
        # consume EVERY complete message in the buffer: dispatch is a side
        # effect here (wire-native correlation), so returning early would
        # strand pipelined messages until bytes that may never come
        first = True
        while True:
            if len(buf) < HEADER_SIZE:
                if first:
                    return self._probe_short(buf, sock)
                return PARSE_NOT_ENOUGH_DATA, None
            head = buf.fetch(HEADER_SIZE)
            length, request_id, response_to, opcode = struct.unpack(HEADER,
                                                                    head)
            if opcode != OP_MSG:
                return (PARSE_TRY_OTHERS if first else PARSE_BAD), None
            if not HEADER_SIZE + 5 <= length <= MAX_MESSAGE:
                return PARSE_BAD, None
            if not self._ours(sock):
                return PARSE_TRY_OTHERS, None
            if len(buf) < length:
                return PARSE_NOT_ENOUGH_DATA, None
            buf.pop_front(HEADER_SIZE)
            body = buf.cutn(length - HEADER_SIZE).tobytes()
            cst: Optional[_MongoClientState] = getattr(sock, "mongo_client",
                                                       None)
            if cst is not None:
                rc = self._client_reply(sock, cst, response_to, body)
            else:
                rc = self._server_request(sock, request_id, body)
            if rc[0] == PARSE_BAD:
                return rc
            first = False

    def _probe_short(self, buf: IOBuf, sock) -> tuple:
        # not enough for a header: ours if the socket already speaks mongo,
        # otherwise let other protocols probe
        if getattr(sock, "mongo_client", None) is not None or \
                getattr(sock, "mongo_server", False):
            return PARSE_NOT_ENOUGH_DATA, None
        return PARSE_TRY_OTHERS, None

    def _ours(self, sock) -> bool:
        if sock is None:
            return False
        if getattr(sock, "mongo_client", None) is not None or \
                getattr(sock, "mongo_server", False):
            return True
        srv = sock.owner_server
        service = getattr(srv.options, "mongo_service", None) if srv else None
        if service is not None:
            sock.mongo_server = True
            sock.preferred_protocol = self
            return True
        return False

    def _client_reply(self, sock, cst: _MongoClientState, response_to: int,
                      body: bytes):
        with cst.lock:
            entry = cst.inflight.pop(response_to, None)
        if entry is None:
            return PARSE_NOT_ENOUGH_DATA, None  # late reply of a dead call
        cid, ver = entry
        meta = rpc_meta_pb2.RpcMeta()
        meta.correlation_id = cid
        meta.attempt_version = ver
        msg = ParsedMessage(self, meta, IOBuf(body))
        msg.socket = sock
        sock.in_messages += 1
        from brpc_tpu.rpc.protocol import dispatch_response

        runtime.start_background(dispatch_response, msg)
        return PARSE_NOT_ENOUGH_DATA, None

    def _server_request(self, sock, request_id: int, body: bytes):
        srv = sock.owner_server
        service = getattr(srv.options, "mongo_service", None) if srv else None
        if service is None:
            return PARSE_BAD, None
        sock.in_messages += 1

        def work():
            try:
                doc = unpack_msg_body(body)
                reply = service.handle(doc)
            except bson.BsonError as e:
                reply = {"ok": 0.0, "errmsg": f"bad BSON: {e}", "code": 22}
            try:
                packet = pack_msg(_fresh_request_id(), request_id, reply)
            except Exception as e:
                # a handler returning something unencodable must still get
                # SOME reply out — a swallowed exception hangs the client
                packet = pack_msg(_fresh_request_id(), request_id,
                                  {"ok": 0.0, "code": 8,
                                   "errmsg": f"unencodable reply: {e}"})
            sock.write(IOBuf(packet))

        runtime.start_background(work)
        return PARSE_NOT_ENOUGH_DATA, None

    # ------------------------------------------------------------- send path
    def issue_request(self, sock, meta, payload: bytes,
                      attachment: bytes = b"", checksum: bool = False,
                      id_wait=None) -> int:
        from brpc_tpu.rpc.protocol import init_socket_state

        cst: _MongoClientState = init_socket_state(
            sock, "mongo_client", _MongoClientState, self)
        rid = _fresh_request_id()
        packet = struct.pack(HEADER, HEADER_SIZE + len(payload), rid, 0,
                             OP_MSG) + payload
        with cst.lock:
            cst.inflight[rid] = (meta.correlation_id, meta.attempt_version)
            if len(cst.inflight) > 4096:
                # timed-out calls never get a reply to clear their entry;
                # shed oldest first (stale late replies are rejected by the
                # call-id version check anyway)
                cst.inflight.pop(next(iter(cst.inflight)))
        rc = sock.write(IOBuf(packet), id_wait=id_wait)
        if rc != 0:
            with cst.lock:
                cst.inflight.pop(rid, None)
        return rc

    # ------------------------------------------------------ engine contracts
    @staticmethod
    def split_attachment(msg: ParsedMessage) -> Tuple[bytes, bytes]:
        return msg.body.tobytes(), b""

    @staticmethod
    def verify_checksum(meta, payload: bytes) -> bool:
        return True
