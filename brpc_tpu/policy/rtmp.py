"""rtmp — RTMP live-media protocol: server + client (reference
``policy/rtmp_protocol.cpp`` / ``rtmp.cpp``; re-derived subset covering the
live streaming core: handshake, chunk streams, AMF0 command plane,
publish/play relay).

Server side plugs into the normal Server like redis/mongo services::

    server = Server(ServerOptions(rtmp_service=RtmpService()))
    server.start("127.0.0.1:1935")

A publisher connects, issues connect/createStream/publish and pushes
audio (8) / video (9) / data (18) messages; players issuing play on the
same stream name receive every message from that point (live relay, the
reference's RtmpServerStream model). The chunk layer handles fmt0-3
headers, per-csid state, SetChunkSize both ways, and extended timestamps.

``RtmpClient`` is the client stub (reference RtmpClientStream):
blocking control plane + a reader thread delivering frames to callbacks —
examples/tests drive a publisher + player pair end to end.
"""

from __future__ import annotations

import os
import socket as _socket
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.policy import amf0
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.protocol import (
    PARSE_BAD,
    PARSE_NOT_ENOUGH_DATA,
    PARSE_TRY_OTHERS,
    Protocol,
)

HANDSHAKE_SIZE = 1536
RTMP_VERSION = 3

MSG_SET_CHUNK_SIZE = 1
MSG_ACK = 3
MSG_USER_CONTROL = 4
MSG_WINDOW_ACK = 5
MSG_SET_PEER_BW = 6
MSG_AUDIO = 8
MSG_VIDEO = 9
MSG_DATA_AMF0 = 18
MSG_COMMAND_AMF0 = 20

UC_STREAM_BEGIN = 0

DEFAULT_CHUNK = 128
OUR_CHUNK = 4096
MAX_MSG = 16 << 20


# ------------------------------------------------------------ chunk muxing
def pack_chunks(csid: int, mtype: int, stream_id: int, payload: bytes,
                timestamp: int = 0, chunk_size: int = OUR_CHUNK) -> bytes:
    """One message as a fmt0 chunk + fmt3 continuations. Timestamps past
    0xFFFFFF emit the extended-timestamp field on the fmt0 header AND on
    every fmt3 continuation (spec §5.3.1.3)."""
    ext = timestamp >= 0xFFFFFF
    ts_field = 0xFFFFFF if ext else timestamp
    ext_bytes = struct.pack(">I", timestamp & 0xFFFFFFFF) if ext else b""
    out = bytearray()
    out += bytes([(0 << 6) | csid])
    out += struct.pack(">I", ts_field)[1:]      # 24-bit timestamp
    out += struct.pack(">I", len(payload))[1:]  # 24-bit length
    out += bytes([mtype])
    out += struct.pack("<I", stream_id)         # little-endian, per spec
    out += ext_bytes
    pos = 0
    first = True
    while pos < len(payload) or first:
        if not first:
            out += bytes([(3 << 6) | csid])
            out += ext_bytes
        out += payload[pos:pos + chunk_size]
        pos += chunk_size
        first = False
    return bytes(out)


class _ChunkState:
    """Per-csid demux state (timestamp/length/type carry over fmt1-3)."""

    __slots__ = ("timestamp", "ts_delta", "length", "mtype", "stream_id",
                 "acc", "ext_ts")

    def __init__(self):
        self.timestamp = 0
        self.ts_delta = 0
        self.length = 0
        self.mtype = 0
        self.stream_id = 0
        self.acc = bytearray()
        self.ext_ts = False  # last type-0/1/2 header carried 0xFFFFFF


class ChunkReader:
    """Incremental RTMP chunk demuxer: feed bytes, get whole messages."""

    def __init__(self):
        self.chunk_size = DEFAULT_CHUNK
        self._states: Dict[int, _ChunkState] = {}

    def feed(self, buf: IOBuf) -> List[Tuple[int, int, int, bytes, int]]:
        """Consume complete chunks; returns [(csid, mtype, stream_id,
        payload, timestamp)] for every COMPLETED message. Raises
        ValueError on malformed input."""
        done = []
        while True:
            if len(buf) < 1:
                return done
            head = buf.fetch(min(len(buf), 18))
            fmt = head[0] >> 6
            csid = head[0] & 0x3F
            pos = 1
            if csid == 0:
                if len(head) < 2:
                    return done
                csid = 64 + head[1]
                pos = 2
            elif csid == 1:
                if len(head) < 3:
                    return done
                csid = 64 + head[1] + (head[2] << 8)
                pos = 3
            need_hdr = {0: 11, 1: 7, 2: 3, 3: 0}[fmt]
            if len(buf) < pos + need_hdr:
                return done
            hdr = buf.fetch(pos + need_hdr + 4)  # +4 for possible ext ts
            st = self._states.get(csid)
            if st is None:
                if fmt != 0:
                    raise ValueError(f"chunk fmt{fmt} before fmt0 on "
                                     f"csid {csid}")
                st = self._states[csid] = _ChunkState()
            p = pos
            ts = None
            if fmt <= 2:
                ts = (hdr[p] << 16) | (hdr[p + 1] << 8) | hdr[p + 2]
                p += 3
            if fmt <= 1:
                new_len = (hdr[p] << 16) | (hdr[p + 1] << 8) | hdr[p + 2]
                if st.acc and new_len != st.length:
                    # a header must not redefine the length mid-message
                    raise ValueError("chunk header changes length "
                                     "mid-message")
                st.length = new_len
                st.mtype = hdr[p + 3]
                p += 4
            if fmt == 0:
                st.stream_id = struct.unpack_from("<I", hdr, p)[0]
                p += 4
            if fmt <= 2:
                st.ext_ts = ts == 0xFFFFFF
            # when the governing header carried 0xFFFFFF, EVERY chunk of
            # the message (fmt3 continuations included) carries the 4-byte
            # extended timestamp (spec §5.3.1.3)
            if st.ext_ts:
                if len(buf) < p + 4:
                    return done
                if fmt <= 2:
                    ts = struct.unpack_from(">I", hdr, p)[0]
                p += 4
            if st.length > MAX_MSG:
                raise ValueError(f"rtmp message too large: {st.length}")
            if fmt == 0:
                st.timestamp = ts
            elif fmt in (1, 2):
                st.ts_delta = ts
                st.timestamp += ts
            take = min(self.chunk_size, st.length - len(st.acc))
            if len(buf) < p + take:
                return done
            buf.pop_front(p)
            st.acc += buf.cutn(take).tobytes()
            if len(st.acc) >= st.length:
                payload = bytes(st.acc)
                st.acc = bytearray()
                if st.mtype == MSG_SET_CHUNK_SIZE and len(payload) >= 4:
                    # applies IMMEDIATELY (spec §5.4.1): later messages in
                    # this same burst are already chunked at the new size
                    size = struct.unpack(">I", payload[:4])[0] & 0x7FFFFFFF
                    if 1 <= size <= (1 << 24):
                        self.chunk_size = size
                done.append((csid, st.mtype, st.stream_id, payload,
                             st.timestamp))


# -------------------------------------------------------------- the service
class RtmpStream:
    """One live stream: a publisher relaying to subscribers."""

    def __init__(self, name: str):
        self.name = name
        self.publisher = None          # _RtmpConn
        self.subscribers: List[Tuple[object, int]] = []  # (conn, stream_id)
        self.metadata: Optional[bytes] = None  # last @setDataFrame payload
        self.lock = threading.Lock()


class RtmpService:
    """Server-side RTMP app: stream registry + relay (the reference's
    RtmpService/RtmpServerStream pair)."""

    def __init__(self):
        self._streams: Dict[str, RtmpStream] = {}
        self._lock = threading.Lock()

    def stream(self, name: str) -> RtmpStream:
        with self._lock:
            s = self._streams.get(name)
            if s is None:
                s = self._streams[name] = RtmpStream(name)
            return s

    # Attach under the registry lock: a lookup followed by a later attach
    # could otherwise interleave with release_if_idle deleting the entry,
    # leaving the publisher/viewer on an orphaned stream object forever.
    def attach_publisher(self, name: str, conn) -> RtmpStream:
        with self._lock:
            s = self._streams.get(name)
            if s is None:
                s = self._streams[name] = RtmpStream(name)
            with s.lock:
                s.publisher = conn
            return s

    def attach_subscriber(self, name: str, conn,
                          stream_id: int) -> Tuple[RtmpStream, bytes]:
        with self._lock:
            s = self._streams.get(name)
            if s is None:
                s = self._streams[name] = RtmpStream(name)
            with s.lock:
                s.subscribers.append((conn, stream_id))
                meta = s.metadata
            return s, meta

    def stream_names(self) -> List[str]:
        with self._lock:
            return sorted(self._streams)

    def release_if_idle(self, stream: "RtmpStream") -> None:
        """Drop a registry entry once nobody publishes or plays it — an
        untrusted publisher cycling fresh names must not grow the registry
        for the server's lifetime."""
        with self._lock:
            with stream.lock:
                idle = stream.publisher is None and not stream.subscribers
            if idle and self._streams.get(stream.name) is stream:
                del self._streams[stream.name]


# ------------------------------------------------------- server connection
class _RtmpConn:
    """Per-connection server state machine."""

    HS_C0C1 = 0
    HS_C2 = 1
    READY = 2

    def __init__(self, sock, service: RtmpService):
        self.sock = sock
        self.service = service
        self.phase = self.HS_C0C1
        self.reader = ChunkReader()
        self.next_stream_id = 1
        self.publishing: Optional[RtmpStream] = None
        self.playing: List[RtmpStream] = []

    # ---------------------------------------------------------- write side
    def send_msg(self, csid: int, mtype: int, stream_id: int,
                 payload: bytes, timestamp: int = 0) -> None:
        self.sock.write(IOBuf(pack_chunks(csid, mtype, stream_id, payload,
                                          timestamp=timestamp)))

    def send_command(self, stream_id: int, *values) -> None:
        self.send_msg(3, MSG_COMMAND_AMF0, stream_id, amf0.encode(*values))

    # ----------------------------------------------------------- dispatch
    def on_message(self, csid: int, mtype: int, stream_id: int,
                   payload: bytes, timestamp: int = 0) -> None:
        if mtype == MSG_SET_CHUNK_SIZE and len(payload) >= 4:
            size = struct.unpack(">I", payload[:4])[0] & 0x7FFFFFFF
            if 1 <= size <= (1 << 24):
                self.reader.chunk_size = size
            return
        if mtype == MSG_COMMAND_AMF0:
            self.on_command(stream_id, payload)
            return
        if mtype in (MSG_AUDIO, MSG_VIDEO, MSG_DATA_AMF0):
            self.on_media(mtype, payload, timestamp)
            return
        # ACK/window/user-control from clients: bookkeeping only

    def on_command(self, stream_id: int, payload: bytes) -> None:
        try:
            vals = amf0.decode_all(payload)
        except amf0.Amf0Error:
            self.sock.set_failed(errors.EREQUEST, "bad AMF0 command")
            return
        if not vals or not isinstance(vals[0], str):
            return
        cmd, txn = vals[0], vals[1] if len(vals) > 1 else 0.0
        if cmd == "connect":
            # window/bandwidth/StreamBegin preamble like real servers
            self.send_msg(2, MSG_WINDOW_ACK, 0, struct.pack(">I", 2500000))
            self.send_msg(2, MSG_SET_PEER_BW, 0,
                          struct.pack(">IB", 2500000, 2))
            self.send_msg(2, MSG_SET_CHUNK_SIZE, 0,
                          struct.pack(">I", OUR_CHUNK))
            self.send_command(
                0, "_result", txn,
                {"fmsVer": "BRPC-TPU/2", "capabilities": 31.0},
                {"level": "status", "code": "NetConnection.Connect.Success",
                 "description": "Connection succeeded."})
        elif cmd == "createStream":
            sid = self.next_stream_id
            self.next_stream_id += 1
            self.send_command(0, "_result", txn, None, float(sid))
        elif cmd == "publish":
            name = vals[3] if len(vals) > 3 and isinstance(vals[3], str) \
                else ""
            stream = self.service.attach_publisher(name, self)
            self.publishing = stream
            self.send_command(
                stream_id, "onStatus", 0.0, None,
                {"level": "status", "code": "NetStream.Publish.Start",
                 "description": f"{name} is now published."})
        elif cmd == "play":
            name = vals[3] if len(vals) > 3 and isinstance(vals[3], str) \
                else ""
            stream, meta = self.service.attach_subscriber(name, self,
                                                          stream_id)
            self.send_msg(2, MSG_USER_CONTROL, 0,
                          struct.pack(">HI", UC_STREAM_BEGIN, stream_id))
            self.send_command(
                stream_id, "onStatus", 0.0, None,
                {"level": "status", "code": "NetStream.Play.Start",
                 "description": f"Started playing {name}."})
            if meta:  # late joiners still get the stream metadata
                self.send_msg(5, MSG_DATA_AMF0, stream_id, meta)
            self.playing.append(stream)
        elif cmd == "deleteStream" or cmd == "closeStream":
            self.teardown()

    def on_media(self, mtype: int, payload: bytes,
                 timestamp: int = 0) -> None:
        stream = self.publishing
        if stream is None:
            return
        if mtype == MSG_DATA_AMF0:
            stream.metadata = payload
        with stream.lock:
            subs = list(stream.subscribers)
        for conn, sid in subs:
            try:
                conn.send_msg(5 if mtype != MSG_VIDEO else 6, mtype, sid,
                              payload, timestamp)
            except Exception:
                pass

    def teardown(self) -> None:
        released = []
        if self.publishing is not None:
            with self.publishing.lock:
                if self.publishing.publisher is self:
                    self.publishing.publisher = None
            released.append(self.publishing)
            self.publishing = None
        for stream in self.playing:
            with stream.lock:
                stream.subscribers = [(c, s) for c, s in stream.subscribers
                                      if c is not self]
            released.append(stream)
        self.playing = []
        for stream in released:
            self.service.release_if_idle(stream)


class RtmpProtocol(Protocol):
    """Wire adapter: handshake then chunk demux, riding the normal Socket/
    InputMessenger machinery (stateful protocol like tpu_ctrl)."""

    name = "rtmp"
    stateful = True
    inline_process = True  # chunk order is stream order

    def parse(self, buf: IOBuf, sock=None):
        conn: Optional[_RtmpConn] = getattr(sock, "rtmp_conn", None)
        if conn is None:
            srv = sock.owner_server if sock is not None else None
            service = getattr(srv.options, "rtmp_service", None) if srv \
                else None
            if service is None:
                return PARSE_TRY_OTHERS, None
            head = buf.fetch(1)
            if not head or head[0] != RTMP_VERSION:
                return PARSE_TRY_OTHERS, None
            if len(buf) < 1 + HANDSHAKE_SIZE:
                return PARSE_NOT_ENOUGH_DATA, None
            conn = _RtmpConn(sock, service)
            sock.rtmp_conn = conn
            sock.preferred_protocol = self
            sock.on_failed_hook = lambda code, reason: conn.teardown()
            # C0+C1 -> S0+S1+S2 (S2 echoes C1, RTMP spec §5.2)
            buf.pop_front(1)
            c1 = buf.cutn(HANDSHAKE_SIZE).tobytes()
            s1 = struct.pack(">II", int(time.time()) & 0x7FFFFFFF, 0) \
                + os.urandom(HANDSHAKE_SIZE - 8)
            sock.write(IOBuf(bytes([RTMP_VERSION]) + s1 + c1))
            conn.phase = _RtmpConn.HS_C2
            return PARSE_NOT_ENOUGH_DATA, None
        if conn.phase == _RtmpConn.HS_C2:
            if len(buf) < HANDSHAKE_SIZE:
                return PARSE_NOT_ENOUGH_DATA, None
            buf.pop_front(HANDSHAKE_SIZE)  # C2: ignore contents
            conn.phase = _RtmpConn.READY
        try:
            for csid, mtype, stream_id, payload, ts in conn.reader.feed(buf):
                conn.on_message(csid, mtype, stream_id, payload, ts)
        except ValueError:
            return PARSE_BAD, None
        return PARSE_NOT_ENOUGH_DATA, None

    def process(self, msg, server) -> None:  # all work happens in parse
        pass


# ----------------------------------------------------------------- client
class RtmpClient:
    """Minimal RTMP client (reference RtmpClientStream): blocking control
    plane + reader thread for media callbacks."""

    def __init__(self, host: str, port: int, app: str = "live",
                 timeout: float = 5.0):
        self._sock = _socket.create_connection((host, port), timeout=timeout)
        self._sock.settimeout(timeout)
        self._reader = ChunkReader()
        self._buf = IOBuf()
        self._results: Dict[float, list] = {}
        self._cv = threading.Condition()
        self._txn = 0.0
        self.on_frame: Optional[Callable[[int, int, bytes], None]] = None
        self._closed = False
        self._handshake()
        self._thread = threading.Thread(target=self._read_loop,
                                        name="rtmp-read", daemon=True)
        self._thread.start()
        # announce our chunk size BEFORE any message that exceeds the
        # 128-byte protocol default (RTMP spec §5.4.1)
        self._send_msg(2, MSG_SET_CHUNK_SIZE, 0, struct.pack(">I", OUR_CHUNK))
        self._command("connect", {"app": app, "tcUrl":
                                  f"rtmp://{host}:{port}/{app}"})

    # ----------------------------------------------------------- plumbing
    def _handshake(self) -> None:
        c1 = struct.pack(">II", int(time.time()) & 0x7FFFFFFF, 0) \
            + os.urandom(HANDSHAKE_SIZE - 8)
        self._sock.sendall(bytes([RTMP_VERSION]) + c1)
        need = 1 + 2 * HANDSHAKE_SIZE
        got = b""
        while len(got) < need:
            chunk = self._sock.recv(need - len(got))
            if not chunk:
                raise ConnectionError("rtmp handshake EOF")
            got += chunk
        if got[0] != RTMP_VERSION:
            raise ConnectionError(f"bad rtmp version {got[0]}")
        self._sock.sendall(got[1:1 + HANDSHAKE_SIZE])  # C2 echoes S1

    def _send_msg(self, csid: int, mtype: int, stream_id: int,
                  payload: bytes) -> None:
        self._sock.sendall(pack_chunks(csid, mtype, stream_id, payload,
                                       chunk_size=OUR_CHUNK))

    def _command(self, cmd: str, *args, stream_id: int = 0,
                 wait: bool = True):
        self._txn += 1.0
        txn = self._txn
        self._send_msg(3, MSG_COMMAND_AMF0, stream_id,
                       amf0.encode(cmd, txn, *args))
        if not wait:
            return None
        with self._cv:
            ok = self._cv.wait_for(lambda: txn in self._results or
                                   self._closed, timeout=5.0)
            if not ok or self._closed:
                raise TimeoutError(f"rtmp command {cmd!r} timed out")
            return self._results.pop(txn)

    def _read_loop(self) -> None:
        from brpc_tpu.profiling import registry as _prof

        _prof.register_current_thread(_prof.ROLE_POLLER)
        try:
            while not self._closed:
                try:
                    data = self._sock.recv(65536)
                except (TimeoutError, _socket.timeout):
                    continue
                except OSError:
                    break
                if not data:
                    break
                self._buf.append(data)
                for csid, mtype, sid, payload, ts in \
                        self._reader.feed(self._buf):
                    self._on_message(mtype, sid, payload, ts)
        finally:
            with self._cv:
                self._closed = True
                self._cv.notify_all()

    def _on_message(self, mtype: int, sid: int, payload: bytes,
                    timestamp: int = 0) -> None:
        if mtype == MSG_SET_CHUNK_SIZE and len(payload) >= 4:
            self._reader.chunk_size = \
                struct.unpack(">I", payload[:4])[0] & 0x7FFFFFFF
            return
        if mtype == MSG_COMMAND_AMF0:
            try:
                vals = amf0.decode_all(payload)
            except amf0.Amf0Error:
                return
            if vals and vals[0] in ("_result", "_error") and len(vals) > 1:
                with self._cv:
                    self._results[vals[1]] = vals
                    self._cv.notify_all()
            return
        if mtype in (MSG_AUDIO, MSG_VIDEO, MSG_DATA_AMF0):
            cb = self.on_frame
            if cb is not None:
                cb(mtype, sid, payload)

    # -------------------------------------------------------------- calls
    def create_stream(self) -> int:
        vals = self._command("createStream", None)
        return int(vals[3])

    def publish(self, name: str, stream_id: int) -> None:
        self._command("publish", None, name, "live", stream_id=stream_id,
                      wait=False)
        time.sleep(0.05)  # onStatus is advisory; give the server a beat

    def play(self, name: str, stream_id: int) -> None:
        self._command("play", None, name, stream_id=stream_id, wait=False)
        time.sleep(0.05)

    def send_frame(self, mtype: int, stream_id: int, payload: bytes,
                   timestamp: int = 0) -> None:
        self._sock.sendall(pack_chunks(
            5 if mtype != MSG_VIDEO else 6, mtype, stream_id, payload,
            timestamp=timestamp, chunk_size=OUR_CHUNK))

    def send_metadata(self, stream_id: int, name: str, data: dict) -> None:
        self._send_msg(5, MSG_DATA_AMF0, stream_id,
                       amf0.encode(name, data))

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass
