"""amf0 — Action Message Format 0 codec (the RTMP command/data encoding).

Counterpart of the reference's ``policy/amf.cpp`` (AMF0 subset used by the
RTMP control plane). Python mapping:

  float/int <-> Number (0x00)     bool <-> Boolean (0x01)
  str <-> String/LongString       dict <-> Object (0x03) / ECMA (0x08)
  None <-> Null (0x05)            Undefined (0x06) -> None
  list <-> Strict Array (0x0A)

Decode raises Amf0Error on malformed bytes (fuzz-facing contract).
"""

from __future__ import annotations

import struct
from typing import Any, List, Tuple


class Amf0Error(ValueError):
    pass


T_NUMBER = 0x00
T_BOOL = 0x01
T_STRING = 0x02
T_OBJECT = 0x03
T_NULL = 0x05
T_UNDEFINED = 0x06
T_ECMA = 0x08
T_OBJECT_END = 0x09
T_STRICT_ARRAY = 0x0A
T_LONG_STRING = 0x0C


def _enc_str_body(s: str) -> bytes:
    b = s.encode("utf-8")
    if len(b) > 0xFFFF:
        return struct.pack(">BI", T_LONG_STRING, len(b)) + b
    return struct.pack(">BH", T_STRING, len(b)) + b


def encode_value(v: Any) -> bytes:
    if isinstance(v, bool):
        return struct.pack(">BB", T_BOOL, 1 if v else 0)
    if isinstance(v, (int, float)):
        return struct.pack(">Bd", T_NUMBER, float(v))
    if isinstance(v, str):
        return _enc_str_body(v)
    if v is None:
        return bytes([T_NULL])
    if isinstance(v, dict):
        out = bytes([T_OBJECT])
        for k, val in v.items():
            kb = str(k).encode("utf-8")
            out += struct.pack(">H", len(kb)) + kb + encode_value(val)
        return out + b"\x00\x00" + bytes([T_OBJECT_END])
    if isinstance(v, (list, tuple)):
        out = struct.pack(">BI", T_STRICT_ARRAY, len(v))
        for item in v:
            out += encode_value(item)
        return out
    raise Amf0Error(f"cannot AMF0-encode {type(v).__name__}")


def encode(*values: Any) -> bytes:
    return b"".join(encode_value(v) for v in values)


def _need(data: bytes, pos: int, n: int) -> None:
    if pos + n > len(data):
        raise Amf0Error("truncated AMF0 value")


def _dec_key(data: bytes, pos: int) -> Tuple[str, int]:
    _need(data, pos, 2)
    (n,) = struct.unpack_from(">H", data, pos)
    pos += 2
    _need(data, pos, n)
    try:
        return data[pos:pos + n].decode("utf-8"), pos + n
    except UnicodeDecodeError as e:
        raise Amf0Error(f"bad utf-8 key: {e}") from None


def decode_value(data: bytes, pos: int, depth: int = 0) -> Tuple[Any, int]:
    if depth > 32:
        raise Amf0Error("AMF0 nesting too deep")
    _need(data, pos, 1)
    t = data[pos]
    pos += 1
    if t == T_NUMBER:
        _need(data, pos, 8)
        return struct.unpack_from(">d", data, pos)[0], pos + 8
    if t == T_BOOL:
        _need(data, pos, 1)
        return data[pos] != 0, pos + 1
    if t == T_STRING:
        _need(data, pos, 2)
        (n,) = struct.unpack_from(">H", data, pos)
        pos += 2
        _need(data, pos, n)
        try:
            return data[pos:pos + n].decode("utf-8"), pos + n
        except UnicodeDecodeError as e:
            raise Amf0Error(f"bad utf-8 string: {e}") from None
    if t == T_LONG_STRING:
        _need(data, pos, 4)
        (n,) = struct.unpack_from(">I", data, pos)
        pos += 4
        _need(data, pos, n)
        try:
            return data[pos:pos + n].decode("utf-8"), pos + n
        except UnicodeDecodeError as e:
            raise Amf0Error(f"bad utf-8 string: {e}") from None
    if t in (T_OBJECT, T_ECMA):
        if t == T_ECMA:
            _need(data, pos, 4)
            pos += 4  # associative count: advisory, ignore
        obj = {}
        while True:
            key, pos = _dec_key(data, pos)
            _need(data, pos, 1)
            if key == "" and data[pos] == T_OBJECT_END:
                return obj, pos + 1
            obj[key], pos = decode_value(data, pos, depth + 1)
    if t == T_NULL or t == T_UNDEFINED:
        return None, pos
    if t == T_STRICT_ARRAY:
        _need(data, pos, 4)
        (n,) = struct.unpack_from(">I", data, pos)
        pos += 4
        if n > 1 << 20:
            raise Amf0Error("array too large")
        out: List[Any] = []
        for _ in range(n):
            v, pos = decode_value(data, pos, depth + 1)
            out.append(v)
        return out, pos
    raise Amf0Error(f"unsupported AMF0 type 0x{t:02x}")


def decode_all(data: bytes) -> List[Any]:
    out, pos = [], 0
    while pos < len(data):
        v, pos = decode_value(data, pos)
        out.append(v)
    return out
