"""HTTP/1.1 protocol — browser dashboard, JSON/pb RPC, builtin services.

Counterpart of the reference's ``policy/http_rpc_protocol.cpp`` (+ the
vendored ``details/http_parser.cpp``): the same server port that speaks
trpc_std also answers HTTP — the InputMessenger probes protocols per
connection, so ``curl`` and browsers hit the builtin dashboard while RPC
clients use the binary protocol (bRPC's single-port multi-protocol
hallmark).

Three server-side paths:
  - builtin services: ``/``, ``/status``, ``/vars``, ``/flags``, … routed to
    ``brpc_tpu.builtin`` handlers.
  - pb services over JSON: ``POST /<Service>/<Method>`` with a JSON body
    (or GET with query-less empty request) — json2pb both ways.
  - pb services over binary pb: same path with content-type
    ``application/proto`` — what our own Channel(protocol="http") sends.

Client side: ``Channel(options.protocol="http")`` packs RPCs as pb-over-
HTTP; responses correlate by the ``x-trpc-cid`` header our servers echo
(attempt version rides the same header — the retry race guard works the
same as trpc_std). For plain external HTTP servers use ``http_fetch``,
a self-contained blocking client.
"""

from __future__ import annotations

import socket as _socket
from typing import Dict, List, Optional, Tuple
from urllib.parse import parse_qsl, urlsplit

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.proto import rpc_meta_pb2
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.protocol import (
    PARSE_BAD,
    PARSE_NOT_ENOUGH_DATA,
    PARSE_TRY_OTHERS,
    ChunkedBodyCursor,
    ParsedMessage,
    PendingBodyCursor,
    Protocol,
    can_stream_body,
    stream_body_min,
)

MAX_HEADER = 64 * 1024
_METHODS = (b"GET", b"POST", b"PUT", b"DELETE", b"HEAD", b"OPTIONS",
            b"PATCH", b"TRACE", b"CONNECT")
_STARTS = _METHODS + (b"HTTP/",)

CONTENT_JSON = "application/json"
CONTENT_PROTO = "application/proto"
CONTENT_TEXT = "text/plain"
CONTENT_HTML = "text/html"

# correlation header: "<call_id>.<attempt_version>" — echoed by the server
H_CID = "x-trpc-cid"
H_ERROR_CODE = "x-trpc-error-code"
H_ERROR_TEXT = "x-trpc-error-text"
H_COMPRESS = "x-trpc-compress"
H_ATTACHMENT = "x-trpc-attachment-size"
H_LOG_ID = "x-trpc-log-id"
H_AUTH = "authorization"

_STATUS_REASON = {
    200: "OK", 400: "Bad Request", 403: "Forbidden", 404: "Not Found",
    405: "Method Not Allowed", 500: "Internal Server Error",
    503: "Service Unavailable",
}

# RPC error code -> HTTP status (reference http_status_code.h mapping)
_ERR_TO_STATUS = {
    errors.OK: 200,
    errors.ENOSERVICE: 404,
    errors.ENOMETHOD: 404,
    errors.EREQUEST: 400,
    errors.EAUTH: 403,
    errors.ELIMIT: 503,
    errors.ELOGOFF: 503,
    errors.EOVERCROWDED: 503,
}


class HttpMessage:
    """One parsed HTTP request or response."""

    __slots__ = ("is_request", "method", "uri", "path", "query", "version",
                 "status", "reason", "headers", "body")

    def __init__(self):
        self.is_request = True
        self.method = ""
        self.uri = ""
        self.path = ""
        self.query: Dict[str, str] = {}
        self.version = "HTTP/1.1"
        self.status = 200
        self.reason = "OK"
        self.headers: Dict[str, str] = {}   # keys lower-cased
        self.body = b""

    def header(self, name: str, default: str = "") -> str:
        return self.headers.get(name.lower(), default)

    @property
    def content_type(self) -> str:
        return self.header("content-type").split(";")[0].strip().lower()

    def keep_alive(self) -> bool:
        conn = self.header("connection").lower()
        if self.version == "HTTP/1.0":
            return conn == "keep-alive"
        return conn != "close"


def _could_be_http(head: bytes) -> bool:
    """Could these first bytes still become an HTTP start-line?"""
    for s in _STARTS:
        n = min(len(head), len(s))
        if head[:n] == s[:n]:
            return True
    return False


def _parse_headers(block: bytes) -> Optional[Tuple[List[str], Dict[str, str]]]:
    lines = block.split(b"\r\n")
    try:
        start = lines[0].decode("latin-1")
    except UnicodeDecodeError:
        return None
    headers: Dict[str, str] = {}
    for line in lines[1:]:
        if not line:
            continue
        k, sep, v = line.partition(b":")
        if not sep:
            return None
        headers[k.decode("latin-1").strip().lower()] = (
            v.decode("latin-1").strip())
    return start.split(" ", 2), headers


def _decode_chunked(data: bytes) -> Optional[Tuple[bytes, int]]:
    """Decode a chunked body. Returns (body, consumed) or None if
    incomplete; raises ValueError on malformed framing."""
    out = []
    pos = 0
    while True:
        nl = data.find(b"\r\n", pos)
        if nl < 0:
            if len(data) - pos > 16:
                raise ValueError("oversized chunk-size line")
            return None
        size_token = data[pos:nl].split(b";")[0].strip()
        size = int(size_token, 16)  # ValueError -> malformed
        chunk_start = nl + 2
        chunk_end = chunk_start + size
        if len(data) < chunk_end + 2:
            return None
        if data[chunk_end:chunk_end + 2] != b"\r\n":
            raise ValueError("missing chunk terminator")
        if size == 0:
            return b"".join(out), chunk_end + 2
        out.append(data[chunk_start:chunk_end])
        pos = chunk_end + 2


def parse_http_message(buf: IOBuf, sock=None,
                       proto=None) -> Tuple[int, Optional[HttpMessage]]:
    """Cut one HTTP/1.1 message. With ``sock`` + ``proto`` (the cut-loop
    entry), a large incomplete content-length body registers a streaming
    pending-body cursor instead of waiting for full buffering; standalone
    callers (http_fetch) omit both and keep whole-message semantics."""
    head = buf.fetch(min(len(buf), MAX_HEADER))
    if not head:
        return PARSE_NOT_ENOUGH_DATA, None
    if not _could_be_http(head):
        return PARSE_TRY_OTHERS, None
    idx = head.find(b"\r\n\r\n")
    if idx < 0:
        if len(head) >= MAX_HEADER:
            return PARSE_BAD, None
        return PARSE_NOT_ENOUGH_DATA, None
    parsed = _parse_headers(head[:idx])
    if parsed is None:
        return PARSE_BAD, None
    start, headers = parsed
    msg = HttpMessage()
    msg.headers = headers
    if start[0].startswith("HTTP/"):
        if len(start) < 2:
            return PARSE_BAD, None
        msg.is_request = False
        msg.version = start[0]
        try:
            msg.status = int(start[1])
        except ValueError:
            return PARSE_BAD, None
        msg.reason = start[2] if len(start) > 2 else ""
    else:
        if len(start) < 3:
            return PARSE_BAD, None
        msg.method, msg.uri, msg.version = start[0], start[1], start[2]
        parts = urlsplit(msg.uri)
        msg.path = parts.path or "/"
        msg.query = dict(parse_qsl(parts.query, keep_blank_values=True))
    body_start = idx + 4
    if headers.get("transfer-encoding", "").lower() == "chunked":
        data = buf.fetch(len(buf))
        try:
            decoded = _decode_chunked(data[body_start:])
        except ValueError:
            return PARSE_BAD, None
        if decoded is None:
            if proto is not None and can_stream_body(sock):
                # incomplete chunked body on the cut-loop entry: pop the
                # parsed headers and stream the chunk frames through an
                # incremental cursor — each arriving chunk is claimed on
                # arrival (credits return mid-message), and the unknown
                # total length is discovered at the 0-size chunk
                buf.pop_front(body_start)

                def _finish_chunked(cur, msg=msg, proto=proto):
                    msg.body = cur.body()
                    return ParsedMessage(proto, msg, IOBuf(msg.body))

                cursor = ChunkedBodyCursor(proto, finish=_finish_chunked)
                cursor.feed(buf)
                if cursor.failed:
                    return PARSE_BAD, None
                # cannot already be done: the whole-buffer decode above
                # just said the body is incomplete
                sock.pending_body = cursor
            return PARSE_NOT_ENOUGH_DATA, None
        msg.body, consumed = decoded
        buf.pop_front(body_start + consumed)
        return 0, msg
    try:
        clen = int(headers.get("content-length", "0") or "0")
    except ValueError:
        return PARSE_BAD, None
    if clen < 0:
        return PARSE_BAD, None
    if len(buf) < body_start + clen:
        if (proto is not None and clen >= stream_body_min()
                and can_stream_body(sock)):
            # headers are parsed and the declared body is large: stream the
            # remainder through a cursor so arriving bytes are consumed
            # (and any transport credits returned) before the body completes
            buf.pop_front(body_start)

            def _finish(cur, msg=msg, proto=proto):
                msg.body = bytes(cur.claimed())
                return ParsedMessage(proto, msg, IOBuf(msg.body))

            cursor = PendingBodyCursor(proto, clen, finish=_finish)
            cursor.feed(buf)
            sock.pending_body = cursor
        return PARSE_NOT_ENOUGH_DATA, None
    buf.pop_front(body_start)
    msg.body = buf.cutn(clen).tobytes() if clen else b""
    return 0, msg


def render_response(status: int, content_type: str, body,
                    extra_headers: Optional[Dict[str, str]] = None,
                    keep_alive: bool = True, chunked: bool = False) -> bytes:
    """chunked=True emits Transfer-Encoding: chunked headers with NO body
    (the caller streams chunks afterwards — progressive attachments)."""
    if isinstance(body, str):
        body = body.encode("utf-8")
    if chunked and body:
        raise ValueError("chunked=True renders headers only; the caller "
                         "streams the body as chunks")
    reason = _STATUS_REASON.get(status, "Unknown")
    lines = [f"HTTP/1.1 {status} {reason}",
             f"Content-Type: {content_type}",
             ("Transfer-Encoding: chunked" if chunked
              else f"Content-Length: {len(body)}"),
             "Connection: " + ("keep-alive" if keep_alive else "close")]
    for k, v in (extra_headers or {}).items():
        lines.append(f"{k}: {v}")
    head = ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1")
    return head if chunked else head + body


def render_request(method: str, path: str, host: str, body: bytes = b"",
                   content_type: str = CONTENT_JSON,
                   extra_headers: Optional[Dict[str, str]] = None) -> bytes:
    lines = [f"{method} {path} HTTP/1.1",
             f"Host: {host}",
             f"Content-Length: {len(body)}"]
    if content_type:
        # even for an empty body: the server classifies json vs pb by it
        lines.append(f"Content-Type: {content_type}")
    for k, v in (extra_headers or {}).items():
        lines.append(f"{k}: {v}")
    head = ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1")
    return head + body


class HttpProtocol(Protocol):
    name = "http"
    stateful = True  # parse(buf, sock): streams large content-length bodies

    # ------------------------------------------------------------------ wire
    def parse(self, buf: IOBuf, sock=None):
        rc, msg = parse_http_message(buf, sock=sock, proto=self)
        if rc != 0:
            return rc, None
        return 0, ParsedMessage(self, msg, IOBuf(msg.body))

    # ----------------------------------------------------------- client pack
    def pack_request(self, meta: rpc_meta_pb2.RpcMeta, payload: bytes,
                     attachment: bytes = b"", checksum: bool = False) -> IOBuf:
        """pb-over-HTTP: POST /<service>/<method>, correlation in headers."""
        path = f"/{meta.request.service_name}/{meta.request.method_name}"
        headers = {
            H_CID: f"{meta.correlation_id}.{meta.attempt_version}",
            "Accept": CONTENT_PROTO,
        }
        if meta.compress_type:
            headers[H_COMPRESS] = str(meta.compress_type)
        if meta.request.log_id:
            headers[H_LOG_ID] = str(meta.request.log_id)
        if meta.auth_token:
            headers[H_AUTH] = meta.auth_token
        if attachment:
            headers[H_ATTACHMENT] = str(len(attachment))
        out = IOBuf()
        out.append(render_request(
            "POST", path, "trpc", payload + attachment,
            content_type=CONTENT_PROTO, extra_headers=headers))
        return out

    # ------------------------------------------------------------ dispatch
    def process(self, msg: ParsedMessage, server) -> None:
        if msg.meta.is_request:
            self.process_request(msg, server)
        else:
            self.process_response(msg)

    def process_request(self, msg: ParsedMessage, server) -> None:
        from brpc_tpu.policy import http_server

        http_server.process_http_request(msg, server)

    def process_response(self, msg: ParsedMessage) -> None:
        """Synthesize an RpcMeta from the response headers and feed the
        shared client completion path."""
        from brpc_tpu.rpc.controller import handle_response_message

        http: HttpMessage = msg.meta
        cid_hdr = http.header(H_CID)
        if not cid_hdr:
            return  # not an RPC response we can correlate — drop
        meta = rpc_meta_pb2.RpcMeta()
        try:
            cid_s, _, ver_s = cid_hdr.partition(".")
            meta.correlation_id = int(cid_s)
            meta.attempt_version = int(ver_s or "0")
        except ValueError:
            return
        try:
            code = http.header(H_ERROR_CODE)
            if code:
                meta.response.error_code = int(code)
                meta.response.error_text = http.header(H_ERROR_TEXT)
            elif http.status != 200:
                meta.response.error_code = errors.EINTERNAL
                meta.response.error_text = f"HTTP {http.status} {http.reason}"
            meta.compress_type = int(http.header(H_COMPRESS, "0") or "0")
            meta.attachment_size = int(http.header(H_ATTACHMENT, "0") or "0")
        except ValueError:
            # malformed headers must still complete the call, not strand it
            meta.response.error_code = errors.ERESPONSE
            meta.response.error_text = "malformed response headers"
            meta.compress_type = 0
            meta.attachment_size = 0
        msg.meta = meta
        handle_response_message(msg)

    # --------------------------------------------------------------- helpers
    @staticmethod
    def split_attachment(msg: ParsedMessage) -> Tuple[bytes, bytes]:
        att = msg.meta.attachment_size
        body = msg.body.tobytes()
        if att:
            return body[:-att], body[-att:]
        return body, b""

    @staticmethod
    def verify_checksum(meta, payload: bytes) -> bool:
        return True  # TCP + HTTP framing; no separate body checksum


# ----------------------------------------------------------- blocking client
def _recv_chunk(s) -> bytes:
    # blocking read lives in its own frame so the sampling profiler
    # classifies threads parked here as waiting, not on-cpu (socket reads
    # happen at C level — the Python leaf frame is all the sampler sees)
    return s.recv(65536)


def http_fetch(hostport: str, method: str = "GET", path: str = "/",
               body: bytes = b"", content_type: str = CONTENT_JSON,
               headers: Optional[Dict[str, str]] = None,
               timeout: float = 5.0) -> HttpMessage:
    """Self-contained HTTP client for tools/tests (talks to any server)."""
    host, _, port = hostport.rpartition(":")
    with _socket.create_connection((host, int(port)), timeout=timeout) as s:
        s.sendall(render_request(method, path, hostport, body,
                                 content_type=content_type,
                                 extra_headers=headers))
        buf = IOBuf()
        while True:
            rc, msg = parse_http_message(buf)
            if rc == 0:
                return msg
            if rc == PARSE_BAD:
                raise ValueError("malformed HTTP response")
            chunk = _recv_chunk(s)
            if not chunk:
                raise ConnectionError("connection closed mid-response")
            buf.append(chunk)
