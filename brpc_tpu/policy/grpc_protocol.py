"""gRPC over HTTP/2 (h2c prior-knowledge) — wire-compatible unary RPC.

Counterpart of the reference's ``policy/http2_rpc_protocol.cpp`` +
``grpc.cpp`` (status mapping, grpc-timeout): requests are POSTs to
``/<package.Service>/<Method>`` with ``content-type: application/grpc``,
messages carry the 5-byte length-prefix, responses end with
``grpc-status``/``grpc-message`` trailers. Both directions funnel into the
same engine paths as trpc_std: ``process_rpc_request`` server-side and
``handle_response_message`` client-side, so limiters, auth, spans, retries
and metrics all apply unchanged.

The protocol is *stateful*: each socket owns an ``H2Conn`` (HPACK contexts,
windows, stream table). parse() consumes frames and dispatches completed
streams itself, returning PARSE_NOT_ENOUGH_DATA to the InputMessenger —
h2 frames are connection-scoped, not per-message cuttable.
"""

from __future__ import annotations

import threading
import time as _time
import urllib.parse
from typing import List, Optional, Tuple

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.fiber import call_id as _cid
from brpc_tpu.fiber import runtime
from brpc_tpu.policy import compress as _compress
from brpc_tpu.policy import h2 as _h2
from brpc_tpu.proto import rpc_meta_pb2
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.protocol import (
    PARSE_BAD,
    PARSE_NOT_ENOUGH_DATA,
    PARSE_TRY_OTHERS,
    ParsedMessage,
    Protocol,
)

CONTENT_GRPC = "application/grpc"

_conn_init_lock = threading.Lock()

# gRPC status codes (subset we map; google.rpc.Code)
G_OK = 0
G_CANCELLED = 1
G_UNKNOWN = 2
G_INVALID_ARGUMENT = 3
G_DEADLINE_EXCEEDED = 4
G_NOT_FOUND = 5
G_RESOURCE_EXHAUSTED = 8
G_UNIMPLEMENTED = 12
G_INTERNAL = 13
G_UNAVAILABLE = 14
G_UNAUTHENTICATED = 16

# reference grpc.cpp ErrorCodeToGrpcStatus equivalent
BRPC_TO_GRPC = {
    errors.OK: G_OK,
    errors.ENOSERVICE: G_UNIMPLEMENTED,
    errors.ENOMETHOD: G_UNIMPLEMENTED,
    errors.EREQUEST: G_INVALID_ARGUMENT,
    errors.ERPCTIMEDOUT: G_DEADLINE_EXCEEDED,
    errors.ELIMIT: G_RESOURCE_EXHAUSTED,
    errors.EOVERCROWDED: G_RESOURCE_EXHAUSTED,
    errors.ELOGOFF: G_UNAVAILABLE,
    errors.EHOSTDOWN: G_UNAVAILABLE,
    errors.EFAILEDSOCKET: G_UNAVAILABLE,
    errors.EAUTH: G_UNAUTHENTICATED,
    errors.ECANCELED: G_CANCELLED,
    errors.EINTERNAL: G_INTERNAL,
    errors.ERESPONSE: G_INTERNAL,
}
GRPC_TO_BRPC = {
    G_OK: errors.OK,
    G_CANCELLED: errors.ECANCELED,
    G_INVALID_ARGUMENT: errors.EREQUEST,
    G_DEADLINE_EXCEEDED: errors.ERPCTIMEDOUT,
    G_NOT_FOUND: errors.ENOMETHOD,
    G_RESOURCE_EXHAUSTED: errors.ELIMIT,
    G_UNIMPLEMENTED: errors.ENOMETHOD,
    G_UNAVAILABLE: errors.EHOSTDOWN,
    G_UNAUTHENTICATED: errors.EAUTH,
    G_INTERNAL: errors.EINTERNAL,
}


def encode_timeout(ms: int) -> str:
    """grpc-timeout header value (largest unit that fits 8 digits)."""
    if ms % 3600000 == 0 and ms // 3600000 < 10 ** 8:
        return f"{ms // 3600000}H" if ms >= 3600000 else f"{ms}m"
    if ms < 10 ** 8:
        return f"{ms}m"
    return f"{ms // 1000}S"


def decode_timeout(value: str) -> Optional[int]:
    """-> milliseconds, None if unparseable."""
    if not value:
        return None
    unit = value[-1]
    try:
        n = int(value[:-1])
    except ValueError:
        return None
    scale = {"H": 3600000, "M": 60000, "S": 1000, "m": 1,
             "u": 0.001, "n": 0.000001}.get(unit)
    if scale is None:
        return None
    return max(1, int(n * scale))


def _prefix(payload: bytes, compressed: bool) -> bytes:
    return bytes([1 if compressed else 0]) + len(payload).to_bytes(4, "big")


def _split_message(data: bytes) -> Tuple[bool, bytes]:
    """Strip the 5-byte gRPC message prefix -> (compressed_flag, message)."""
    if len(data) < 5:
        return False, b""
    compressed = data[0] == 1
    n = int.from_bytes(data[1:5], "big")
    return compressed, bytes(data[5:5 + n])


def _encoding_to_compress(name: str) -> int:
    if name == "gzip":
        return _compress.COMPRESS_GZIP
    if name == "deflate":
        return _compress.COMPRESS_ZLIB
    return _compress.COMPRESS_NONE


def _compress_to_encoding(ctype: int) -> str:
    if ctype == _compress.COMPRESS_GZIP:
        return "gzip"
    if ctype == _compress.COMPRESS_ZLIB:
        return "deflate"
    return "identity"


class GrpcProtocol(Protocol):
    name = "grpc"
    stateful = True  # parse() receives the socket; state lives on it

    # ------------------------------------------------------------- recv path
    def parse(self, buf: IOBuf, sock=None):
        conn: Optional[_h2.H2Conn] = getattr(sock, "h2_conn", None)
        if conn is None:
            # server side: detect the client connection preface
            head = buf.fetch(min(len(buf), len(_h2.PREFACE)))
            if not _h2.PREFACE.startswith(head):
                return PARSE_TRY_OTHERS, None
            if len(head) < len(_h2.PREFACE):
                return PARSE_NOT_ENOUGH_DATA, None
            conn = _h2.H2Conn(sock, "server",
                              on_stream_complete=self._on_server_stream,
                              on_stream_reset=self._on_reset)
            sock.h2_conn = conn
            sock.preferred_protocol = self
            conn.send_preamble()
        try:
            conn.feed(buf)
        except _h2.H2Error as e:
            try:
                conn.send_goaway(e.h2_code)
            except Exception:
                pass
            return PARSE_BAD, None
        return PARSE_NOT_ENOUGH_DATA, None

    # ------------------------------------------------------------- send path
    def issue_request(self, sock, meta, payload: bytes,
                      attachment: bytes = b"", checksum: bool = False,
                      id_wait=None) -> int:
        """Client side — called by Controller._issue_rpc in place of
        pack_request+write (gRPC needs per-connection stream state)."""
        conn: Optional[_h2.H2Conn] = getattr(sock, "h2_conn", None)
        if conn is None:
            with _conn_init_lock:  # two first-callers must not double-preface
                conn = getattr(sock, "h2_conn", None)
                if conn is None:
                    conn = _h2.H2Conn(
                        sock, "client",
                        on_stream_complete=self._on_client_stream,
                        on_stream_reset=self._on_reset)
                    sock.h2_conn = conn
                    sock.preferred_protocol = self
                    conn.send_preamble()
        if conn.goaway_received:
            # drain the connection: fail the socket so the SocketMap makes a
            # fresh one, and surface a retryable error through the id channel
            sock.set_failed(errors.EFAILEDSOCKET, "h2 GOAWAY received")
            return errors.EHOSTDOWN
        path = f"/{meta.request.service_name}/{meta.request.method_name}"
        headers: List[Tuple[str, str]] = [
            (":method", "POST"),
            (":scheme", "http"),
            (":path", path),
            (":authority", str(sock.remote or "localhost")),
            ("content-type", CONTENT_GRPC),
            ("te", "trailers"),
            ("user-agent", "grpc-brpc-tpu/1.0"),
        ]
        if meta.request.timeout_ms:
            headers.append(("grpc-timeout", encode_timeout(meta.request.timeout_ms)))
        if meta.compress_type:
            headers.append(("grpc-encoding", _compress_to_encoding(meta.compress_type)))
        if meta.auth_token:
            headers.append(("authorization", meta.auth_token))
        if meta.request.log_id:
            headers.append(("x-brpc-log-id", str(meta.request.log_id)))
        if meta.request.trace_id:
            headers.append(("x-brpc-trace-id", str(meta.request.trace_id)))
            headers.append(("x-brpc-span-id", str(meta.request.span_id)))
        body = payload + attachment  # gRPC has no attachment: ride the body
        ctx = (meta.correlation_id, meta.attempt_version,
               meta.request.service_name, meta.request.method_name)
        st, rc = conn.open_stream_with_headers(
            headers, end_stream=False, id_wait=id_wait, call_ctx=ctx)
        if rc != 0:
            conn.close_stream(st.sid)
            return rc
        conn.send_data(st.sid, _prefix(body, meta.compress_type != 0) + body,
                       end_stream=True)
        return 0

    # ----------------------------------------------- server stream complete
    def _on_server_stream(self, conn: _h2.H2Conn, st: _h2.H2Stream,
                          trailers_only: bool) -> None:
        sock = conn.sock
        sock.in_messages += 1
        hdrs = dict(st.headers or [])
        path = hdrs.get(":path", "")
        parts = path.strip("/").split("/")
        if not hdrs.get("content-type", "").startswith(CONTENT_GRPC):
            # plain HTTP/2 request (browser/curl --http2): the builtin
            # dashboard answers on h2 exactly like it does on HTTP/1.1
            # (the reference serves /status etc. over h2 too). Dispatch on
            # a fiber: builtins may block (e.g. /hotspots/cpu profiles for
            # seconds) and must not stall this connection's frame parsing
            runtime.start_background(self._serve_plain_http, conn, st,
                                     hdrs)
            return
        if hdrs.get(":method") != "POST" or len(parts) != 2:
            self._reject(conn, st.sid, G_UNIMPLEMENTED, f"bad path {path!r}")
            return
        service_full, method = parts
        meta = rpc_meta_pb2.RpcMeta()
        # accept both full (pkg.Service) and bare (Service) names
        meta.request.service_name = service_full.rpartition(".")[2]
        meta.request.method_name = method
        meta.correlation_id = st.sid
        timeout = decode_timeout(hdrs.get("grpc-timeout", ""))
        if timeout:
            meta.request.timeout_ms = timeout
        if hdrs.get("authorization"):
            meta.auth_token = hdrs["authorization"]
        try:
            meta.request.log_id = int(hdrs.get("x-brpc-log-id", "0"))
            meta.request.trace_id = int(hdrs.get("x-brpc-trace-id", "0"))
            meta.request.span_id = int(hdrs.get("x-brpc-span-id", "0"))
        except ValueError:
            pass
        compressed, message = _split_message(st.data)
        meta.compress_type = (_encoding_to_compress(
            hdrs.get("grpc-encoding", "gzip")) if compressed
            else _compress.COMPRESS_NONE)
        shim = _H2ServerCall(conn, st.sid)
        msg = ParsedMessage(shim, meta, IOBuf(message))
        msg.socket = sock
        server = sock.owner_server
        from brpc_tpu.rpc.server_processing import process_rpc_request

        runtime.start_background(process_rpc_request, shim, msg, server)

    def _serve_plain_http(self, conn: _h2.H2Conn, st: _h2.H2Stream,
                          hdrs: dict) -> None:
        """Builtin-dashboard dispatch for non-grpc h2 requests."""
        import urllib.parse as _up

        from brpc_tpu import builtin
        from brpc_tpu.policy.http_protocol import HttpMessage

        http = HttpMessage()
        http.method = hdrs.get(":method", "GET")
        http.uri = hdrs.get(":path", "/")
        path, _, query = http.uri.partition("?")
        http.path = path
        http.query = dict(_up.parse_qsl(query))
        http.headers = {k: v for k, v in (st.headers or [])
                        if not k.startswith(":")}
        http.body = bytes(st.data)
        server = conn.sock.owner_server
        try:
            handled = builtin.dispatch(server, http)
        except Exception as e:
            handled = (500, "text/plain", f"builtin service failed: {e}\n",
                       None)
        if handled is None:
            handled = (404, "text/plain",
                       f"no such builtin path {http.path!r} "
                       f"(rpc over h2 needs content-type {CONTENT_GRPC})\n",
                       None)
        status, ctype, body, extra = handled
        if isinstance(body, str):
            body = body.encode()
        headers = [(":status", str(status)), ("content-type", ctype)]
        if extra:
            headers += [(str(k).lower(), str(v)) for k, v in extra.items()]
        st.close_on_end = True  # pop only after the tail + END_STREAM flush
        conn.send_headers(st.sid, headers, end_stream=False)
        conn.send_data(st.sid, body, end_stream=True)

    def _reject(self, conn, sid, grpc_code, text) -> None:
        conn.send_headers(sid, [
            (":status", "200"), ("content-type", CONTENT_GRPC),
            ("grpc-status", str(grpc_code)),
            ("grpc-message", urllib.parse.quote(text)),
        ], end_stream=True)
        conn.close_stream(sid)

    # ----------------------------------------------- client stream complete
    def _on_client_stream(self, conn: _h2.H2Conn, st: _h2.H2Stream,
                          trailers_only: bool) -> None:
        ctx = conn.calls.pop(st.sid, None)
        conn.close_stream(st.sid)
        if ctx is None:
            return
        cid, attempt_version, _svc, _method = ctx
        conn.sock.in_messages += 1
        t0 = _time.perf_counter_ns()
        hdrs = dict(st.headers or [])
        trailer = dict(st.trailers or [])
        meta = rpc_meta_pb2.RpcMeta()
        meta.correlation_id = cid
        meta.attempt_version = attempt_version
        status_s = trailer.get("grpc-status", hdrs.get("grpc-status"))
        http_status = hdrs.get(":status", "200")
        if status_s is None:
            if http_status != "200":
                meta.response.error_code = errors.EINTERNAL
                meta.response.error_text = f"HTTP/2 status {http_status}"
            else:
                meta.response.error_code = errors.ERESPONSE
                meta.response.error_text = "missing grpc-status"
        else:
            try:
                g = int(status_s)
            except ValueError:
                g = G_UNKNOWN
            meta.response.error_code = GRPC_TO_BRPC.get(g, errors.EINTERNAL)
            if g != G_OK:
                meta.response.error_text = urllib.parse.unquote(
                    trailer.get("grpc-message", hdrs.get("grpc-message", ""))
                ) or f"grpc-status {g}"
        compressed, message = _split_message(st.data)
        meta.compress_type = (_encoding_to_compress(
            hdrs.get("grpc-encoding", "gzip")) if compressed
            else _compress.COMPRESS_NONE)
        msg = ParsedMessage(self, meta, IOBuf(message))
        # trailer/meta assembly + length-prefix split is wire-format
        # parsing done on the h2 frame path; credit it to the span's
        # parse mark when the dispatcher stamps it
        msg.pre_parse_us = (_time.perf_counter_ns() - t0) / 1000.0
        msg.socket = conn.sock
        from brpc_tpu.rpc.controller import handle_response_message

        runtime.start_background(handle_response_message, msg)

    def _on_reset(self, conn: _h2.H2Conn, sid: int, h2_code: int) -> None:
        if conn.role != "client":
            return
        ctx = conn.calls.pop(sid, None)
        if ctx is None:
            return
        code = (errors.EFAILEDSOCKET if h2_code == _h2.REFUSED_STREAM
                else errors.ECANCELED)
        _cid.id_error(ctx[0], code)

    # ------------------------------------------------------ engine contracts
    @staticmethod
    def split_attachment(msg: ParsedMessage) -> Tuple[bytes, bytes]:
        return msg.body.tobytes(), b""  # prefix already stripped

    @staticmethod
    def verify_checksum(meta, payload: bytes) -> bool:
        return True  # h2 framing; gRPC has no body checksum


class _H2ServerCall:
    """Per-request response path handed to process_rpc_request: packs the
    response as HEADERS + DATA + trailers on this request's stream."""

    name = "grpc"

    def __init__(self, conn: _h2.H2Conn, sid: int):
        self.conn = conn
        self.sid = sid

    split_attachment = staticmethod(GrpcProtocol.split_attachment)
    verify_checksum = staticmethod(GrpcProtocol.verify_checksum)

    def pack_response(self, meta, payload: bytes, attachment: bytes = b"",
                      checksum: bool = False) -> IOBuf:
        """Sends the response itself (frame emission must be atomic with
        HPACK encoding); returns an empty IOBuf for the engine's write."""
        conn, sid = self.conn, self.sid
        code = meta.response.error_code
        if code == errors.OK:
            body = payload + (attachment or b"")
            headers = [(":status", "200"), ("content-type", CONTENT_GRPC)]
            if meta.compress_type:
                headers.append(
                    ("grpc-encoding", _compress_to_encoding(meta.compress_type)))
            conn.send_headers(sid, headers, end_stream=False)
            conn.send_data(sid, _prefix(body, meta.compress_type != 0) + body,
                           end_stream=False)
            conn.send_trailers(sid, [("grpc-status", "0")])
        else:
            grpc_code = BRPC_TO_GRPC.get(code, G_UNKNOWN)
            conn.send_headers(sid, [
                (":status", "200"), ("content-type", CONTENT_GRPC),
                ("grpc-status", str(grpc_code)),
                ("grpc-message",
                 urllib.parse.quote(meta.response.error_text or "")),
            ], end_stream=True)
            conn.close_stream(sid)
        return IOBuf()
