"""Cluster recover policy — de-thunder recovery after total cluster loss.

Counterpart of the reference's DefaultClusterRecoverPolicy
(/root/reference/src/brpc/cluster_recover_policy.h:60-80, .cpp): when a
naming-service cluster comes back from "every instance down", letting the
full client fleet hammer the first instance that reappears knocks it over
again. While *recovering*, a request is shed (EREJECT) with probability
``1 - usable/min_working_instances``, so traffic ramps in proportion to
capacity; recovery ends when the usable count stops changing for
``hold_seconds`` (the cluster has converged) or reaches
``min_working_instances``.

Attach to a load balancer via the LB spec string
(``"rr:min_working_instances=3 hold_seconds=2"`` — the reference's
flag-style params), or construct directly and assign to
``lb.recover_policy``. Channel consults it on every pick.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from brpc_tpu.butil.misc import fast_rand_less_than


class DefaultClusterRecoverPolicy:
    def __init__(self, min_working_instances: int, hold_seconds: float):
        if min_working_instances <= 0:
            raise ValueError("min_working_instances must be > 0")
        self.min_working_instances = int(min_working_instances)
        self.hold_seconds = float(hold_seconds)
        self._lock = threading.Lock()
        self._recovering = False
        self._last_usable = 0
        self._last_usable_change = 0.0

    # ------------------------------------------------------------ lifecycle
    def start_recover(self) -> None:
        """The LB found no usable server — recovery begins when they return
        (reference StartRecover)."""
        with self._lock:
            if not self._recovering:
                self._recovering = True
                self._last_usable = 0
                self._last_usable_change = time.monotonic()

    @property
    def recovering(self) -> bool:
        with self._lock:
            return self._recovering

    # -------------------------------------------------------------- verdict
    def do_reject(self, usable: int) -> bool:
        """True = shed this request (reference DoReject). ``usable`` is the
        LB's count of not-parked instances."""
        with self._lock:
            if not self._recovering:
                return False
            now = time.monotonic()
            if usable != self._last_usable:
                self._last_usable = usable
                self._last_usable_change = now
            # StopRecoverIfNecessary: converged (stable for hold_seconds)
            # or enough capacity came back
            if usable >= self.min_working_instances or (
                    usable > 0 and
                    now - self._last_usable_change >= self.hold_seconds):
                self._recovering = False
                return False
            if usable <= 0:
                return True
            # shed proportionally to the missing capacity
            return int(fast_rand_less_than(self.min_working_instances)) \
                >= usable


def parse_recover_params(params: str) -> Optional[DefaultClusterRecoverPolicy]:
    """Parse the reference's param syntax: ``min_working_instances=N
    hold_seconds=S`` (space or comma separated). Unknown keys or malformed
    values raise ValueError — a typo must not silently disable the
    protection (reference GetRecoverPolicyByParams rejects them too,
    cluster_recover_policy.cpp:140-146). Returns None only for an empty
    params string."""
    params = params.strip()
    if not params:
        return None
    min_working = None
    hold = 3.0
    for part in params.replace(",", " ").split():
        key, _, val = part.partition("=")
        try:
            if key == "min_working_instances":
                min_working = int(val)
            elif key == "hold_seconds":
                hold = float(val)
            else:
                raise ValueError(f"unknown cluster-recover param {key!r}")
        except ValueError as e:
            raise ValueError(
                f"bad cluster-recover params {params!r}: {e}") from None
    if min_working is None:
        raise ValueError(
            f"cluster-recover params {params!r} missing min_working_instances")
    return DefaultClusterRecoverPolicy(min_working, hold)
