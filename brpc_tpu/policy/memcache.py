"""Memcached binary protocol client.

Counterpart of the reference's ``policy/memcache_binary_protocol.cpp`` +
``memcache.h`` (MemcacheRequest/MemcacheResponse): N pipelined operations
per RPC, responses arrive in order on the connection (memcached guarantees
request order), so correlation is positional like redis. Each op carries an
opaque token we verify on the way back.

Wire (public memcached binary protocol): 24-byte header
``magic op keylen extlen datatype vbucket bodylen opaque cas`` followed by
extras + key + value.
"""

from __future__ import annotations

import struct
import threading
from collections import deque
from typing import List, Optional, Tuple

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.fiber import runtime
from brpc_tpu.proto import rpc_meta_pb2
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.protocol import (
    PARSE_BAD,
    PARSE_NOT_ENOUGH_DATA,
    PARSE_TRY_OTHERS,
    ParsedMessage,
    Protocol,
    dispatch_response,
    init_socket_state,
)

MAGIC_REQUEST = 0x80
MAGIC_RESPONSE = 0x81
HEADER_FMT = "!BBHBBHIIQ"
HEADER_SIZE = struct.calcsize(HEADER_FMT)  # 24

# opcodes
OP_GET = 0x00
OP_SET = 0x01
OP_ADD = 0x02
OP_REPLACE = 0x03
OP_DELETE = 0x04
OP_INCREMENT = 0x05
OP_DECREMENT = 0x06
OP_FLUSH = 0x08
OP_NOOP = 0x0A
OP_VERSION = 0x0B
OP_APPEND = 0x0E
OP_PREPEND = 0x0F
OP_TOUCH = 0x1C

# response status
STATUS_OK = 0x0000
STATUS_KEY_NOT_FOUND = 0x0001
STATUS_KEY_EXISTS = 0x0002
STATUS_VALUE_TOO_LARGE = 0x0003
STATUS_ITEM_NOT_STORED = 0x0005
STATUS_UNKNOWN_COMMAND = 0x0081


def pack_op(opcode: int, key: bytes = b"", extras: bytes = b"",
            value: bytes = b"", opaque: int = 0, cas: int = 0) -> bytes:
    body_len = len(extras) + len(key) + len(value)
    return struct.pack(HEADER_FMT, MAGIC_REQUEST, opcode, len(key),
                       len(extras), 0, 0, body_len, opaque,
                       cas) + extras + key + value


class MemcacheOpResult:
    __slots__ = ("opcode", "status", "key", "value", "extras", "cas")

    def __init__(self, opcode, status, key, value, extras, cas):
        self.opcode = opcode
        self.status = status
        self.key = key
        self.value = value
        self.extras = extras
        self.cas = cas

    @property
    def ok(self) -> bool:
        return self.status == STATUS_OK

    def error_text(self) -> str:
        return self.value.decode("utf-8", "replace") if not self.ok else ""


class MemcacheRequest:
    """Pipelined op batch; pb-duck-typed for the engine (see RedisRequest)."""

    def __init__(self):
        self._ops: List[bytes] = []
        self._next_opaque = 1

    def _add(self, opcode, key=b"", extras=b"", value=b"", cas=0):
        if isinstance(key, str):
            key = key.encode()
        if isinstance(value, str):
            value = value.encode()
        self._ops.append(pack_op(opcode, key, extras, value,
                                 opaque=self._next_opaque, cas=cas))
        self._next_opaque += 1
        return self

    def get(self, key):
        return self._add(OP_GET, key)

    def set(self, key, value, flags: int = 0, exptime: int = 0, cas: int = 0):
        return self._add(OP_SET, key, struct.pack("!II", flags, exptime),
                         value, cas)

    def add(self, key, value, flags: int = 0, exptime: int = 0):
        return self._add(OP_ADD, key, struct.pack("!II", flags, exptime), value)

    def replace(self, key, value, flags: int = 0, exptime: int = 0):
        return self._add(OP_REPLACE, key,
                         struct.pack("!II", flags, exptime), value)

    def append(self, key, value):
        return self._add(OP_APPEND, key, b"", value)

    def prepend(self, key, value):
        return self._add(OP_PREPEND, key, b"", value)

    def delete(self, key):
        return self._add(OP_DELETE, key)

    def incr(self, key, delta: int = 1, initial: int = 0, exptime: int = 0):
        return self._add(OP_INCREMENT, key,
                         struct.pack("!QQI", delta, initial, exptime))

    def decr(self, key, delta: int = 1, initial: int = 0, exptime: int = 0):
        return self._add(OP_DECREMENT, key,
                         struct.pack("!QQI", delta, initial, exptime))

    def touch(self, key, exptime: int = 0):
        return self._add(OP_TOUCH, key, struct.pack("!I", exptime))

    def flush_all(self):
        return self._add(OP_FLUSH)

    def version(self):
        return self._add(OP_VERSION)

    @property
    def op_count(self) -> int:
        return len(self._ops)

    def SerializeToString(self) -> bytes:
        return b"".join(self._ops)

    def ParseFromString(self, data: bytes) -> None:
        self._ops = [bytes(data)] if data else []


class MemcacheResponse:
    def __init__(self):
        self._results: List[MemcacheOpResult] = []
        self._pop_at = 0

    @property
    def result_size(self) -> int:
        return len(self._results)

    def result(self, i: int) -> MemcacheOpResult:
        return self._results[i]

    def pop(self) -> Optional[MemcacheOpResult]:
        """Results in op order (the reference's PopGet/PopSet pattern)."""
        if self._pop_at >= len(self._results):
            return None
        r = self._results[self._pop_at]
        self._pop_at += 1
        return r

    def ParseFromString(self, data: bytes) -> None:
        self._results = []
        self._pop_at = 0
        pos = 0
        while pos + HEADER_SIZE <= len(data):
            (magic, opcode, keylen, extlen, _dt, status, bodylen, _opaque,
             cas) = struct.unpack_from(HEADER_FMT, data, pos)
            pos += HEADER_SIZE
            extras = bytes(data[pos:pos + extlen])
            key = bytes(data[pos + extlen:pos + extlen + keylen])
            value = bytes(data[pos + extlen + keylen:pos + bodylen])
            pos += bodylen
            self._results.append(
                MemcacheOpResult(opcode, status, key, value, extras, cas))

    def SerializeToString(self) -> bytes:
        return b""


def memcache_method():
    from brpc_tpu.rpc.channel import MethodDescriptor

    return MethodDescriptor("memcache", "batch",
                            MemcacheRequest, MemcacheResponse)


def count_ops(payload: bytes) -> int:
    n = 0
    pos = 0
    while pos + HEADER_SIZE <= len(payload):
        bodylen = struct.unpack_from("!I", payload, pos + 8)[0]
        pos += HEADER_SIZE + bodylen
        n += 1
    return n


class _McClientState:
    __slots__ = ("fifo", "lock", "acc")

    def __init__(self):
        self.fifo = deque()  # (cid, ver, n_expected)
        self.lock = threading.Lock()
        self.acc: List[bytes] = []


class MemcacheProtocol(Protocol):
    name = "memcache"
    stateful = True

    # ------------------------------------------------------------- recv path
    def parse(self, buf: IOBuf, sock=None):
        cst: Optional[_McClientState] = getattr(sock, "memcache_client", None)
        if cst is None:
            return PARSE_TRY_OTHERS, None
        if len(buf) >= HEADER_SIZE:
            # peek the head op's total before flattening a big buffer that
            # holds one still-incomplete value (quadratic copy otherwise)
            head = buf.fetch(HEADER_SIZE)
            first_total = HEADER_SIZE + struct.unpack_from("!I", head, 8)[0]
            if len(buf) < first_total:
                return PARSE_NOT_ENOUGH_DATA, None
        data = buf.fetch(len(buf))
        pos = 0
        completed = []
        with cst.lock:
            while pos + HEADER_SIZE <= len(data) and cst.fifo:
                if data[pos] != MAGIC_RESPONSE:
                    buf.pop_front(pos)
                    return PARSE_BAD, None
                bodylen = struct.unpack_from("!I", data, pos + 8)[0]
                total = HEADER_SIZE + bodylen
                if pos + total > len(data):
                    break
                cst.acc.append(data[pos:pos + total])
                pos += total
                cid, ver, need = cst.fifo[0]
                if len(cst.acc) >= need:
                    completed.append((cid, ver, b"".join(cst.acc)))
                    cst.acc = []
                    cst.fifo.popleft()
            unsolicited = not cst.fifo and pos < len(data) \
                and len(data) - pos >= 1 and data[pos] == MAGIC_RESPONSE
        buf.pop_front(pos)
        if unsolicited:
            return PARSE_BAD, None
        for cid, ver, body in completed:
            meta = rpc_meta_pb2.RpcMeta()
            meta.correlation_id = cid
            meta.attempt_version = ver
            msg = ParsedMessage(self, meta, IOBuf(body))
            msg.socket = sock
            sock.in_messages += 1
            runtime.start_background(dispatch_response, msg)
        return PARSE_NOT_ENOUGH_DATA, None

    # ------------------------------------------------------------- send path
    def issue_request(self, sock, meta, payload: bytes,
                      attachment: bytes = b"", checksum: bool = False,
                      id_wait=None) -> int:
        cst: _McClientState = init_socket_state(
            sock, "memcache_client", _McClientState, self)
        n = count_ops(payload)
        if n == 0:
            return errors.EREQUEST
        entry = (meta.correlation_id, meta.attempt_version, n)
        with cst.lock:
            # FIFO order IS the wire order (see redis_protocol)
            cst.fifo.append(entry)
            rc = sock.write(IOBuf(payload), id_wait=id_wait)
            if rc != 0:
                try:
                    cst.fifo.remove(entry)
                except ValueError:
                    pass
        return rc

    # ------------------------------------------------------ engine contracts
    @staticmethod
    def split_attachment(msg: ParsedMessage) -> Tuple[bytes, bytes]:
        return msg.body.tobytes(), b""

    @staticmethod
    def verify_checksum(meta, payload: bytes) -> bool:
        return True
