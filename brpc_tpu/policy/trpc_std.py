"""trpc_std — the canonical binary protocol of the rebuild.

Counterpart of the reference's baidu_std (``policy/baidu_rpc_protocol.cpp``):
fixed 12-byte header ``b"TRPC" + u32 meta_size + u32 body_size`` followed by
an RpcMeta protobuf and the body (serialized user message + optional trailing
attachment of ``meta.attachment_size`` bytes). One protocol serves both
directions; requests and responses are distinguished by which sub-meta is set.

The server-side dispatch mirrors ``ProcessRpcRequest`` (baidu_rpc_protocol.
cpp:565): admission -> method lookup -> parse -> user code -> SendResponse;
the client side mirrors ``ProcessRpcResponse`` (:907): verify call id ->
deserialize -> end RPC.
"""

from __future__ import annotations

import struct
from typing import Optional, Tuple

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.butil.misc import crc32c
from brpc_tpu.proto import rpc_meta_pb2
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.protocol import (
    PARSE_BAD,
    PARSE_NOT_ENOUGH_DATA,
    PARSE_TRY_OTHERS,
    ParsedMessage,
    PendingBodyCursor,
    Protocol,
    can_stream_body,
    stream_body_min,
)

MAGIC = b"TRPC"
HEADER_FMT = "!4sII"
HEADER_SIZE = struct.calcsize(HEADER_FMT)  # 12
MAX_BODY_SIZE = 1 << 31  # hard ceiling; the runtime limit is the flag


def max_body_size() -> int:
    """Largest accepted wire message — runtime-settable via /flags."""
    from brpc_tpu import flags as _flags

    return min(_flags.get("max_body_size"), MAX_BODY_SIZE)


class TrpcStdProtocol(Protocol):
    name = "trpc_std"
    magic = MAGIC
    stateful = True  # parse(buf, sock): registers streaming body cursors

    # ------------------------------------------------------------------ wire
    def parse(self, buf: IOBuf,
              sock=None) -> Tuple[int, Optional[ParsedMessage]]:
        if len(buf) < HEADER_SIZE:
            # can we at least rule the protocol out?
            head = buf.fetch(min(len(buf), 4))
            if head and not MAGIC.startswith(head):
                return PARSE_TRY_OTHERS, None
            return PARSE_NOT_ENOUGH_DATA, None
        header = buf.fetch(HEADER_SIZE)
        magic, meta_size, body_size = struct.unpack(HEADER_FMT, header)
        if magic != MAGIC:
            return PARSE_TRY_OTHERS, None
        if meta_size + body_size > max_body_size():
            return PARSE_BAD, None
        total = HEADER_SIZE + meta_size + body_size
        if len(buf) < total:
            if (body_size >= stream_body_min()
                    and len(buf) >= HEADER_SIZE + meta_size
                    and can_stream_body(sock)):
                # header + meta are in hand and the body is large: consume
                # what has arrived NOW and register a cursor for the rest,
                # so the transport's flow-control credits return mid-message
                # instead of after the whole body buffers up
                buf.pop_front(HEADER_SIZE)
                meta_bytes = buf.cutn(meta_size).tobytes()
                try:
                    meta = rpc_meta_pb2.RpcMeta.FromString(meta_bytes)
                except Exception:
                    return PARSE_BAD, None
                cursor = PendingBodyCursor(
                    self, body_size,
                    finish=lambda cur, meta=meta: ParsedMessage(
                        self, meta, cur.body()))
                cursor.feed(buf)
                sock.pending_body = cursor
            return PARSE_NOT_ENOUGH_DATA, None
        buf.pop_front(HEADER_SIZE)
        meta_bytes = buf.cutn(meta_size).tobytes()
        body = buf.cutn(body_size)
        try:
            meta = rpc_meta_pb2.RpcMeta.FromString(meta_bytes)
        except Exception:
            return PARSE_BAD, None
        return 0, ParsedMessage(self, meta, body)

    @staticmethod
    def _pack(meta: rpc_meta_pb2.RpcMeta, payload: bytes,
              attachment: bytes = b"", checksum: bool = False) -> IOBuf:
        meta.attachment_size = len(attachment)
        body_size = len(payload) + len(attachment)
        if payload and checksum:
            meta.checksum = crc32c(payload)
        meta_bytes = meta.SerializeToString()
        out = IOBuf()
        out.append(struct.pack(HEADER_FMT, MAGIC, len(meta_bytes), body_size))
        out.append(meta_bytes)
        if payload:
            out.append(payload)
        if attachment:
            out.append(attachment)
        return out

    def pack_request(self, meta, payload: bytes, attachment: bytes = b"",
                     checksum: bool = False) -> IOBuf:
        return self._pack(meta, payload, attachment, checksum)

    def pack_response(self, meta, payload: bytes, attachment: bytes = b"",
                      checksum: bool = False) -> IOBuf:
        return self._pack(meta, payload, attachment, checksum)

    # ------------------------------------------------------------ server side
    def process_request(self, msg: ParsedMessage, server) -> None:
        # deferred import: protocol layer must not depend on server at import
        from brpc_tpu.rpc.server_processing import process_rpc_request

        process_rpc_request(self, msg, server)

    # ------------------------------------------------------------ client side
    def process_response(self, msg: ParsedMessage) -> None:
        from brpc_tpu.rpc.controller import handle_response_message

        handle_response_message(msg)

    def claim_cid(self, msg: ParsedMessage):
        meta = msg.meta
        if meta.HasField("response"):
            return meta.correlation_id
        return None

    # --------------------------------------------------------------- helpers
    @staticmethod
    def split_attachment(msg: ParsedMessage) -> Tuple[bytes, bytes]:
        """body -> (serialized message bytes, attachment bytes).

        Splits by ref first (cutn), so each side is materialized exactly
        once — flatten-then-slice copied an attachment'd body twice, and on
        the tpu tunnel's zero-copy receive path the body refs are borrowed
        registered blocks whose flow-control credit returns when these
        copies drop the refs."""
        att_size = msg.meta.attachment_size
        body = msg.body
        if att_size and att_size <= len(body):
            payload = body.cutn(len(body) - att_size).tobytes()
            return payload, body.cutn(att_size).tobytes()
        data = body.tobytes()
        body.clear()  # drop refs now, not at message GC
        return data, b""

    @staticmethod
    def verify_checksum(meta, payload: bytes) -> bool:
        return not meta.checksum or crc32c(payload) == meta.checksum
