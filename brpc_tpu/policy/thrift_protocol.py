"""Thrift framed-transport protocol (TBinaryProtocol, strict).

Counterpart of the reference's ``policy/thrift_protocol.cpp`` +
``thrift_service.h``: clients call thrift servers (framed binary), and a
Server can answer thrift clients through ``ServerOptions.thrift_service``.

Wire: u32 frame length, then a TBinary message — strict header
``0x8001_00_0t`` (t = message type), method name, i32 seqid, then the
args/result struct. Correlation is the seqid: a per-socket map seqid ->
(call id, attempt version); thrift servers may reply out of order.

Payloads are raw struct bytes (``ThriftRawMessage``) — bring serialized
structs from any generator — plus a small TBinary writer/reader for
building/parsing structs without generated code.
"""

from __future__ import annotations

import struct
import threading
from typing import Dict, Optional, Tuple

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.fiber import runtime
from brpc_tpu.proto import rpc_meta_pb2
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.protocol import (
    PARSE_BAD,
    PARSE_NOT_ENOUGH_DATA,
    PARSE_TRY_OTHERS,
    ParsedMessage,
    Protocol,
    dispatch_response,
    init_socket_state,
)

VERSION_MASK = 0xFFFF0000
VERSION_1 = 0x80010000

# message types
MT_CALL = 1
MT_REPLY = 2
MT_EXCEPTION = 3
MT_ONEWAY = 4

# field types
T_STOP = 0
T_BOOL = 2
T_BYTE = 3
T_DOUBLE = 4
T_I16 = 6
T_I32 = 8
T_I64 = 10
T_STRING = 11
T_STRUCT = 12
T_MAP = 13
T_SET = 14
T_LIST = 15

MAX_FRAME = 64 << 20


# ------------------------------------------------------------ binary codec
class ThriftBinaryWriter:
    """Minimal TBinaryProtocol writer (struct body only)."""

    def __init__(self):
        self._out = bytearray()

    def bytes(self) -> bytes:
        return bytes(self._out)

    def field_stop(self) -> "ThriftBinaryWriter":
        self._out.append(T_STOP)
        return self

    def _field(self, ftype: int, fid: int) -> None:
        self._out += struct.pack("!bh", ftype, fid)

    def write_bool(self, fid: int, v: bool):
        self._field(T_BOOL, fid)
        self._out.append(1 if v else 0)
        return self

    def write_byte(self, fid: int, v: int):
        self._field(T_BYTE, fid)
        self._out += struct.pack("!b", v)
        return self

    def write_i16(self, fid: int, v: int):
        self._field(T_I16, fid)
        self._out += struct.pack("!h", v)
        return self

    def write_i32(self, fid: int, v: int):
        self._field(T_I32, fid)
        self._out += struct.pack("!i", v)
        return self

    def write_i64(self, fid: int, v: int):
        self._field(T_I64, fid)
        self._out += struct.pack("!q", v)
        return self

    def write_double(self, fid: int, v: float):
        self._field(T_DOUBLE, fid)
        self._out += struct.pack("!d", v)
        return self

    def write_string(self, fid: int, v):
        if isinstance(v, str):
            v = v.encode("utf-8")
        self._field(T_STRING, fid)
        self._out += struct.pack("!i", len(v)) + v
        return self

    def write_struct(self, fid: int, body: bytes):
        """body must already end with T_STOP."""
        self._field(T_STRUCT, fid)
        self._out += body
        return self


class ThriftBinaryReader:
    """Reads a flat struct into {field_id: (type, value)}; nested structs
    come back as raw bytes for a second reader pass."""

    def __init__(self, data: bytes, pos: int = 0):
        self.data = data
        self.pos = pos

    def _take(self, fmt: str):
        v = struct.unpack_from(fmt, self.data, self.pos)[0]
        self.pos += struct.calcsize(fmt)
        return v

    def read_struct(self) -> Dict[int, Tuple[int, object]]:
        fields: Dict[int, Tuple[int, object]] = {}
        while True:
            ftype = self._take("!b")
            if ftype == T_STOP:
                return fields
            fid = self._take("!h")
            fields[fid] = (ftype, self._read_value(ftype))

    def _read_value(self, ftype: int):
        if ftype == T_BOOL:
            return bool(self._take("!b"))
        if ftype == T_BYTE:
            return self._take("!b")
        if ftype == T_I16:
            return self._take("!h")
        if ftype == T_I32:
            return self._take("!i")
        if ftype == T_I64:
            return self._take("!q")
        if ftype == T_DOUBLE:
            return self._take("!d")
        if ftype == T_STRING:
            n = self._take("!i")
            v = self.data[self.pos:self.pos + n]
            self.pos += n
            return bytes(v)
        if ftype == T_STRUCT:
            start = self.pos
            self.read_struct()  # skip over it
            return bytes(self.data[start:self.pos])
        if ftype in (T_LIST, T_SET):
            etype = self._take("!b")
            n = self._take("!i")
            return [self._read_value(etype) for _ in range(n)]
        if ftype == T_MAP:
            ktype = self._take("!b")
            vtype = self._take("!b")
            n = self._take("!i")
            return {self._read_value(ktype): self._read_value(vtype)
                    for _ in range(n)}
        raise ValueError(f"unsupported thrift type {ftype}")


def pack_message(mtype: int, method: str, seqid: int, body: bytes) -> bytes:
    name = method.encode("utf-8")
    msg = (struct.pack("!I", VERSION_1 | mtype)
           + struct.pack("!i", len(name)) + name
           + struct.pack("!i", seqid) + body)
    return struct.pack("!I", len(msg)) + msg


def unpack_message(frame: bytes) -> Tuple[int, str, int, bytes]:
    """frame = one message without the length prefix."""
    ver = struct.unpack_from("!I", frame, 0)[0]
    if ver & VERSION_MASK != VERSION_1:
        raise ValueError("not a strict TBinary message")
    mtype = ver & 0xFF
    nlen = struct.unpack_from("!i", frame, 4)[0]
    name = frame[8:8 + nlen].decode("utf-8", "replace")
    seqid = struct.unpack_from("!i", frame, 8 + nlen)[0]
    return mtype, name, seqid, bytes(frame[12 + nlen:])


# TApplicationException (what servers throw for unknown methods etc.)
AE_UNKNOWN_METHOD = 1
AE_INTERNAL_ERROR = 6


def pack_application_exception(method: str, seqid: int, code: int,
                               message: str) -> bytes:
    body = (ThriftBinaryWriter()
            .write_string(1, message)
            .write_i32(2, code)
            .field_stop().bytes())
    return pack_message(MT_EXCEPTION, method, seqid, body)


# --------------------------------------------------------- message classes
class ThriftRawMessage:
    """method + raw TBinary struct body, pb-message duck-typed. The method
    name rides the wire in the thrift header, set from the RPC's
    method_name (use ``thrift_method(name)``)."""

    def __init__(self, body: bytes = b"\x00"):
        self.body = body  # b"\x00" = empty struct (just T_STOP)

    def SerializeToString(self) -> bytes:
        return self.body

    def ParseFromString(self, data: bytes) -> None:
        self.body = bytes(data)


def thrift_method(name: str):
    from brpc_tpu.rpc.channel import MethodDescriptor

    return MethodDescriptor("thrift", name, ThriftRawMessage, ThriftRawMessage)


class ThriftService:
    """Server half: method name -> handler(args_body: bytes) -> bytes
    (result struct body). Raise to return a TApplicationException."""

    def __init__(self):
        self._methods: Dict[str, object] = {}

    def add_method(self, name: str, handler) -> "ThriftService":
        self._methods[name] = handler
        return self

    def find(self, name: str):
        return self._methods.get(name)


# ------------------------------------------------------------ client state
class _ThriftClientState:
    __slots__ = ("lock", "next_seqid", "calls")

    def __init__(self):
        self.lock = threading.Lock()
        self.next_seqid = 1
        self.calls: Dict[int, Tuple[int, int]] = {}  # seqid -> (cid, ver)


class ThriftProtocol(Protocol):
    name = "thrift"
    stateful = True

    # ------------------------------------------------------------- recv path
    def parse(self, buf: IOBuf, sock=None):
        """Consumes EVERY complete frame in buf (returning
        PARSE_NOT_ENOUGH_DATA stops the messenger's cut loop, so leaving a
        complete frame buffered would strand it until the next read event)."""
        cst = getattr(sock, "thrift_client", None)
        srv = sock.owner_server
        service = getattr(srv.options, "thrift_service", None) if srv else None
        if cst is None and service is None:
            return PARSE_TRY_OTHERS, None
        first = True
        while True:
            rc = self._parse_one(buf, sock, cst, service, probe=first)
            if rc is not None:
                return rc, None
            first = False
            cst = getattr(sock, "thrift_client", None)

    def _parse_one(self, buf, sock, cst, service, probe):
        """-> None when one frame was consumed; a PARSE_* code otherwise."""
        if len(buf) < 8:
            head = buf.fetch(min(len(buf), 8))
            if probe and len(head) >= 6 and head[4] != 0x80:
                return PARSE_TRY_OTHERS
            return PARSE_NOT_ENOUGH_DATA
        head = buf.fetch(8)
        n = struct.unpack("!I", head[:4])[0]
        if head[4] != 0x80 or n > MAX_FRAME:
            return PARSE_TRY_OTHERS if probe else PARSE_BAD
        if len(buf) < 4 + n:
            return PARSE_NOT_ENOUGH_DATA
        sock.preferred_protocol = self
        buf.pop_front(4)
        frame = buf.cutn(n).tobytes()
        try:
            mtype, name, seqid, body = unpack_message(frame)
        except (ValueError, struct.error):
            return PARSE_BAD
        sock.in_messages += 1
        if mtype in (MT_CALL, MT_ONEWAY):
            if service is None:
                return PARSE_BAD
            runtime.start_background(
                self._run_server_method, sock, service, mtype, name, seqid,
                body)
            return None
        # REPLY / EXCEPTION -> complete the matching call
        if cst is None:
            return None  # stale reply: drop
        with cst.lock:
            ctx = cst.calls.pop(seqid, None)
        if ctx is None:
            return None  # timed-out call: drop
        meta = rpc_meta_pb2.RpcMeta()
        meta.correlation_id, meta.attempt_version = ctx
        if mtype == MT_EXCEPTION:
            try:
                fields = ThriftBinaryReader(body).read_struct()
                text = fields.get(1, (0, b""))[1].decode("utf-8", "replace")
            except Exception:
                text = "thrift exception"
            meta.response.error_code = errors.EINTERNAL
            meta.response.error_text = text
            body = b"\x00"
        msg = ParsedMessage(self, meta, IOBuf(body))
        msg.socket = sock
        runtime.start_background(dispatch_response, msg)
        return None

    def _run_server_method(self, sock, service, mtype, name, seqid, body):
        handler = service.find(name)
        if handler is None:
            if mtype != MT_ONEWAY:
                sock.write(IOBuf(pack_application_exception(
                    name, seqid, AE_UNKNOWN_METHOD,
                    f"unknown method {name!r}")))
            return
        try:
            result = handler(body)
        except Exception as e:
            if mtype != MT_ONEWAY:
                sock.write(IOBuf(pack_application_exception(
                    name, seqid, AE_INTERNAL_ERROR, str(e))))
            return
        if mtype != MT_ONEWAY:
            sock.write(IOBuf(pack_message(MT_REPLY, name, seqid,
                                          result or b"\x00")))

    # ------------------------------------------------------------- send path
    def issue_request(self, sock, meta, payload: bytes,
                      attachment: bytes = b"", checksum: bool = False,
                      id_wait=None) -> int:
        cst: _ThriftClientState = init_socket_state(
            sock, "thrift_client", _ThriftClientState, self)
        with cst.lock:
            seqid = cst.next_seqid
            cst.next_seqid = (cst.next_seqid + 1) & 0x7FFFFFFF or 1
            cst.calls[seqid] = (meta.correlation_id, meta.attempt_version)
        frame = pack_message(MT_CALL, meta.request.method_name, seqid,
                             payload or b"\x00")
        rc = sock.write(IOBuf(frame), id_wait=id_wait)
        if rc != 0:
            with cst.lock:
                cst.calls.pop(seqid, None)
        return rc

    # ------------------------------------------------------ engine contracts
    @staticmethod
    def split_attachment(msg: ParsedMessage) -> Tuple[bytes, bytes]:
        return msg.body.tobytes(), b""

    @staticmethod
    def verify_checksum(meta, payload: bytes) -> bool:
        return True
