"""Naming services — cluster membership -> load balancer.

Rebuild of the reference's interface (naming_service.h:36-61: RunNamingService
pushes ResetServers), the periodic base class, and the per-url shared thread
(details/naming_service_thread.cpp). Schemes (reference global.cpp:370-381
has bns/file/list/http/consul/...; ours):

  list://h1:p1,h2:p2 w=3     static list, optional w= weight and tag
  file:///path               re-read periodically, one server per line
  dns://host:port            resolve A records each refresh
  tpu://[host]               the device mesh as a server list — one node
                             per local chip (the TPU-native "cluster")

Threads are shared per url: channels naming the same url reuse one watcher.
"""

from __future__ import annotations

import os
import socket as _socket
import threading
from typing import Callable, Dict, List, Optional

from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.policy.load_balancers import ServerNode

DEFAULT_INTERVAL_S = 5.0


def parse_server_item(item: str) -> Optional[ServerNode]:
    """'host:port', 'host:port w=3', 'host:port w=3 tag'."""
    parts = item.strip().split()
    if not parts:
        return None
    ep = EndPoint.parse(parts[0])
    weight, tag = 1, ""
    for p in parts[1:]:
        if p.startswith("w="):
            weight = int(p[2:])
        else:
            tag = p
    return ServerNode(ep, weight=weight, tag=tag)


class NamingService:
    """Subclass: implement get_servers() -> List[ServerNode].

    Watch-style services (consul blocking queries etc.) additionally set
    ``supports_watch = True`` and implement ``watch(push, stop_event)`` — a
    blocking loop calling ``push(nodes)`` on every membership change; the
    NamingServiceThread then pushes changes the moment they happen instead
    of on a polling interval."""

    scheme = "base"
    supports_watch = False

    def __init__(self, path: str):
        self.path = path

    def get_servers(self) -> List[ServerNode]:
        raise NotImplementedError

    def watch(self, push, stop_event) -> None:
        raise NotImplementedError


class ListNamingService(NamingService):
    scheme = "list"

    def get_servers(self) -> List[ServerNode]:
        nodes = []
        for item in self.path.split(","):
            node = parse_server_item(item)
            if node is not None:
                nodes.append(node)
        return nodes


class FileNamingService(NamingService):
    scheme = "file"

    def get_servers(self) -> List[ServerNode]:
        nodes = []
        with open(self.path) as f:
            for line in f:
                line = line.split("#", 1)[0].strip()
                if not line:
                    continue
                node = parse_server_item(line)
                if node is not None:
                    nodes.append(node)
        return nodes


class DnsNamingService(NamingService):
    scheme = "dns"

    def get_servers(self) -> List[ServerNode]:
        host, _, port = self.path.partition(":")
        port = int(port or 80)
        infos = _socket.getaddrinfo(host, port, _socket.AF_INET,
                                    _socket.SOCK_STREAM)
        seen, nodes = set(), []
        for _, _, _, _, addr in infos:
            ep = EndPoint.from_ip_port(addr[0], addr[1])
            if ep not in seen:
                seen.add(ep)
                nodes.append(ServerNode(ep))
        return nodes


class TpuNamingService(NamingService):
    """The device mesh as a cluster: every local chip is a server."""

    scheme = "tpu"

    def get_servers(self) -> List[ServerNode]:
        from brpc_tpu.tpu.mesh import list_device_endpoints

        host = self.path.strip("/") or "localhost"
        return [ServerNode(ep) for ep in list_device_endpoints(host)]


_schemes: Dict[str, Callable[[str], NamingService]] = {
    "list": ListNamingService,
    "file": FileNamingService,
    "dns": DnsNamingService,
    "tpu": TpuNamingService,
}


class ConsulNamingService(NamingService):
    """Watch-style membership via consul's blocking queries (reference
    policy/consul_naming_service.cpp: GET /v1/health/service/<name> with
    index/wait long-poll; changes push IMMEDIATELY, no polling interval).

    url: consul://host:port/service_name
    """

    scheme = "consul"
    supports_watch = True
    WAIT = "10s"

    def __init__(self, path: str):
        super().__init__(path)
        authority, _, service = path.partition("/")
        host, _, port = authority.partition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port or 8500)
        self.service = service
        self._index = 0

    def _query(self, index: int, wait: str = "") -> tuple:
        import http.client
        import json

        conn = http.client.HTTPConnection(self.host, self.port, timeout=30)
        try:
            q = f"/v1/health/service/{self.service}?passing=1&index={index}"
            if wait:
                q += f"&wait={wait}"
            conn.request("GET", q)
            resp = conn.getresponse()
            body = resp.read()
            if resp.status != 200:
                raise RuntimeError(f"consul HTTP {resp.status}")
            new_index = int(resp.headers.get("X-Consul-Index", "0") or 0)
            nodes = []
            for entry in json.loads(body.decode() or "[]"):
                svc = entry.get("Service", {})
                addr = svc.get("Address") or \
                    entry.get("Node", {}).get("Address", "")
                port_num = int(svc.get("Port", 0))
                if not addr or not port_num:
                    continue
                tags = svc.get("Tags") or []
                nodes.append(ServerNode(EndPoint.from_ip_port(addr, port_num),
                                        tag=tags[0] if tags else ""))
            return nodes, new_index
        finally:
            conn.close()

    def get_servers(self) -> List[ServerNode]:
        nodes, self._index = self._query(0)
        return nodes

    def watch(self, push, stop_event) -> None:
        """Blocking-query loop: each call hangs until membership changes
        (or the wait expires); every change pushes instantly."""
        while not stop_event.is_set():
            nodes, new_index = self._query(self._index, wait=self.WAIT)
            if stop_event.is_set():
                return
            if new_index <= 0:
                # a 200 without X-Consul-Index isn't consul — raising lets
                # the watch thread back off instead of busy-looping
                # immediate index=0 queries
                raise RuntimeError(
                    "consul response missing X-Consul-Index "
                    "(is the endpoint really a consul agent?)")
            if new_index != self._index:
                self._index = new_index
                push(nodes)


class RemoteFileNamingService(NamingService):
    """Server list fetched from an HTTP URL, refreshed periodically
    (reference policy/remote_file_naming_service.cpp).

    url: remotefile://host:port/path
    """

    scheme = "remotefile"

    def __init__(self, path: str):
        super().__init__(path)
        authority, _, rel = path.partition("/")
        host, _, port = authority.partition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port or 80)
        self.rel = "/" + rel

    def get_servers(self) -> List[ServerNode]:
        import http.client

        conn = http.client.HTTPConnection(self.host, self.port, timeout=10)
        try:
            conn.request("GET", self.rel)
            resp = conn.getresponse()
            if resp.status != 200:
                raise RuntimeError(f"remotefile HTTP {resp.status}")
            nodes = []
            for line in resp.read().decode().splitlines():
                line = line.split("#", 1)[0].strip()
                if not line:
                    continue
                node = parse_server_item(line)
                if node is not None:
                    nodes.append(node)
            return nodes
        finally:
            conn.close()


def register_naming_service(scheme: str,
                            factory: Callable[[str], NamingService]) -> None:
    _schemes[scheme] = factory


_schemes["consul"] = ConsulNamingService
_schemes["remotefile"] = RemoteFileNamingService


class NamingServiceThread:
    """Periodic watcher pushing reset_servers to its listeners.

    Shared per url (reference details/naming_service_thread.cpp): all
    channels on the same url observe one refresh loop.
    """

    def __init__(self, ns: NamingService, interval_s: float):
        self._ns = ns
        self._interval = interval_s
        self._listeners = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.last_servers: List[ServerNode] = []
        self.last_error: Optional[str] = None
        self._refresh()  # first resolution is synchronous (like Init)
        self._thread = threading.Thread(
            target=self._run, name=f"ns-{ns.scheme}", daemon=True)
        self._thread.start()

    def add_listener(self, lb) -> None:
        with self._lock:
            self._listeners.append(lb)
            lb.reset_servers(self.last_servers)

    def _refresh(self) -> None:
        try:
            nodes = self._ns.get_servers()
        except Exception as e:
            self.last_error = str(e)
            return  # keep the previous list on resolution failure
        self._push(nodes)

    def _run(self) -> None:
        if self._ns.supports_watch:
            # watch loop: changes push instantly; reconnect with backoff
            backoff = 0.1
            while not self._stop.is_set():
                try:
                    self._ns.watch(self._push, self._stop)
                    backoff = 0.1
                except Exception as e:
                    self.last_error = str(e)
                    self._stop.wait(backoff)
                    backoff = min(backoff * 2, 5.0)
            return
        while not self._stop.wait(self._interval):
            self._refresh()

    def _push(self, nodes: List[ServerNode]) -> None:
        """Watch callback: deliver a membership change to every listener."""
        self.last_error = None
        with self._lock:
            self.last_servers = nodes
            listeners = list(self._listeners)
        for lb in listeners:
            lb.reset_servers(nodes)

    def stop(self) -> None:
        self._stop.set()


_threads: Dict[str, NamingServiceThread] = {}
_threads_lock = threading.Lock()


def start_naming_service(url: str, lb,
                         interval_s: float = DEFAULT_INTERVAL_S
                         ) -> NamingServiceThread:
    """url 'scheme://path' -> shared watcher thread feeding the lb."""
    scheme, sep, path = url.partition("://")
    if not sep:
        raise ValueError(f"naming url needs scheme://, got {url!r}")
    factory = _schemes.get(scheme)
    if factory is None:
        raise ValueError(f"unknown naming scheme {scheme!r}; "
                         f"have {sorted(_schemes)}")
    with _threads_lock:
        thread = _threads.get(url)
        if thread is None or thread._stop.is_set():
            thread = NamingServiceThread(factory(path), interval_s)
            _threads[url] = thread
    thread.add_listener(lb)
    return thread
