"""Naming services — cluster membership -> load balancer.

Rebuild of the reference's interface (naming_service.h:36-61: RunNamingService
pushes ResetServers), the periodic base class, and the per-url shared thread
(details/naming_service_thread.cpp). Schemes (reference global.cpp:370-381
has bns/file/list/http/consul/...; ours):

  list://h1:p1,h2:p2 w=3     static list, optional w= weight and tag
  file:///path               re-read periodically, one server per line
  dns://host:port            resolve A records each refresh
  tpu://[host]               the device mesh as a server list — one node
                             per local chip (the TPU-native "cluster")

Threads are shared per url: channels naming the same url reuse one watcher.
"""

from __future__ import annotations

import os
import socket as _socket
import threading
from typing import Callable, Dict, List, Optional

from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.policy.load_balancers import ServerNode

DEFAULT_INTERVAL_S = 5.0


def parse_server_item(item: str) -> Optional[ServerNode]:
    """'host:port', 'host:port w=3', 'host:port w=3 tag'."""
    parts = item.strip().split()
    if not parts:
        return None
    ep = EndPoint.parse(parts[0])
    weight, tag = 1, ""
    for p in parts[1:]:
        if p.startswith("w="):
            weight = int(p[2:])
        else:
            tag = p
    return ServerNode(ep, weight=weight, tag=tag)


class NamingService:
    """Subclass: implement get_servers() -> List[ServerNode]."""

    scheme = "base"

    def __init__(self, path: str):
        self.path = path

    def get_servers(self) -> List[ServerNode]:
        raise NotImplementedError


class ListNamingService(NamingService):
    scheme = "list"

    def get_servers(self) -> List[ServerNode]:
        nodes = []
        for item in self.path.split(","):
            node = parse_server_item(item)
            if node is not None:
                nodes.append(node)
        return nodes


class FileNamingService(NamingService):
    scheme = "file"

    def get_servers(self) -> List[ServerNode]:
        nodes = []
        with open(self.path) as f:
            for line in f:
                line = line.split("#", 1)[0].strip()
                if not line:
                    continue
                node = parse_server_item(line)
                if node is not None:
                    nodes.append(node)
        return nodes


class DnsNamingService(NamingService):
    scheme = "dns"

    def get_servers(self) -> List[ServerNode]:
        host, _, port = self.path.partition(":")
        port = int(port or 80)
        infos = _socket.getaddrinfo(host, port, _socket.AF_INET,
                                    _socket.SOCK_STREAM)
        seen, nodes = set(), []
        for _, _, _, _, addr in infos:
            ep = EndPoint.from_ip_port(addr[0], addr[1])
            if ep not in seen:
                seen.add(ep)
                nodes.append(ServerNode(ep))
        return nodes


class TpuNamingService(NamingService):
    """The device mesh as a cluster: every local chip is a server."""

    scheme = "tpu"

    def get_servers(self) -> List[ServerNode]:
        from brpc_tpu.tpu.mesh import list_device_endpoints

        host = self.path.strip("/") or "localhost"
        return [ServerNode(ep) for ep in list_device_endpoints(host)]


_schemes: Dict[str, Callable[[str], NamingService]] = {
    "list": ListNamingService,
    "file": FileNamingService,
    "dns": DnsNamingService,
    "tpu": TpuNamingService,
}


def register_naming_service(scheme: str,
                            factory: Callable[[str], NamingService]) -> None:
    _schemes[scheme] = factory


class NamingServiceThread:
    """Periodic watcher pushing reset_servers to its listeners.

    Shared per url (reference details/naming_service_thread.cpp): all
    channels on the same url observe one refresh loop.
    """

    def __init__(self, ns: NamingService, interval_s: float):
        self._ns = ns
        self._interval = interval_s
        self._listeners = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.last_servers: List[ServerNode] = []
        self.last_error: Optional[str] = None
        self._refresh()  # first resolution is synchronous (like Init)
        self._thread = threading.Thread(
            target=self._run, name=f"ns-{ns.scheme}", daemon=True)
        self._thread.start()

    def add_listener(self, lb) -> None:
        with self._lock:
            self._listeners.append(lb)
            lb.reset_servers(self.last_servers)

    def _refresh(self) -> None:
        try:
            nodes = self._ns.get_servers()
            self.last_error = None
        except Exception as e:
            self.last_error = str(e)
            return  # keep the previous list on resolution failure
        with self._lock:
            self.last_servers = nodes
            listeners = list(self._listeners)
        for lb in listeners:
            lb.reset_servers(nodes)

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            self._refresh()

    def stop(self) -> None:
        self._stop.set()


_threads: Dict[str, NamingServiceThread] = {}
_threads_lock = threading.Lock()


def start_naming_service(url: str, lb,
                         interval_s: float = DEFAULT_INTERVAL_S
                         ) -> NamingServiceThread:
    """url 'scheme://path' -> shared watcher thread feeding the lb."""
    scheme, sep, path = url.partition("://")
    if not sep:
        raise ValueError(f"naming url needs scheme://, got {url!r}")
    factory = _schemes.get(scheme)
    if factory is None:
        raise ValueError(f"unknown naming scheme {scheme!r}; "
                         f"have {sorted(_schemes)}")
    with _threads_lock:
        thread = _threads.get(url)
        if thread is None or thread._stop.is_set():
            thread = NamingServiceThread(factory(path), interval_s)
            _threads[url] = thread
    thread.add_listener(lb)
    return thread
