"""Concurrency limiters — server-side admission control.

Rebuild of the reference's three policies (registered global.cpp:624-626):
  constant — fixed max concurrent requests
  auto     — gradient-style (policy/auto_concurrency_limiter.h:40-70):
             track the best latency ever seen (min_latency EMA); when
             current latency degrades well past it, shrink the limit, when
             near it, grow. Self-tunes to the knee of the latency curve.
  timeout  — (policy/timeout_concurrency_limiter.cpp) reject when expected
             queue time exceeds the caller's budget.

Wire-in: MethodEntry.limiter (rpc/server.py) consults on_request/on_response.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional


class ConcurrencyLimiter:
    name = "base"

    def on_request(self) -> bool:
        raise NotImplementedError

    def on_response(self, latency_us: float, error_code: int) -> None:
        raise NotImplementedError

    @property
    def current(self) -> int:
        raise NotImplementedError


class ConstantLimiter(ConcurrencyLimiter):
    name = "constant"

    def __init__(self, max_concurrency: int):
        self.max_concurrency = max_concurrency
        self._current = 0
        self._lock = threading.Lock()

    def on_request(self) -> bool:
        with self._lock:
            if self._current >= self.max_concurrency:
                return False
            self._current += 1
            return True

    def on_response(self, latency_us: float, error_code: int) -> None:
        with self._lock:
            self._current -= 1

    @property
    def current(self) -> int:
        return self._current


class AutoLimiter(ConcurrencyLimiter):
    """Gradient limiter: limit chases the concurrency that keeps latency
    near the observed floor."""

    name = "auto"

    def __init__(self, initial: int = 32, min_limit: int = 4,
                 max_limit: int = 4096, sample_window: int = 64):
        self._limit = float(initial)
        self.min_limit = min_limit
        self.max_limit = max_limit
        self._current = 0
        self._lock = threading.Lock()
        self._min_latency_us: Optional[float] = None
        self._window_total = 0.0
        self._window_count = 0
        self._sample_window = sample_window

    def on_request(self) -> bool:
        with self._lock:
            if self._current >= int(self._limit):
                return False
            self._current += 1
            return True

    def on_response(self, latency_us: float, error_code: int) -> None:
        with self._lock:
            self._current -= 1
            if error_code != 0:
                return
            self._window_total += latency_us
            self._window_count += 1
            if self._window_count < self._sample_window:
                return
            avg = self._window_total / self._window_count
            self._window_total = 0.0
            self._window_count = 0
            if self._min_latency_us is None or avg < self._min_latency_us:
                self._min_latency_us = avg
            else:
                # slow drift so a transient floor doesn't pin us forever
                self._min_latency_us += 0.01 * (avg - self._min_latency_us)
            gradient = self._min_latency_us / max(avg, 1e-9)
            # gradient ~1: healthy -> grow; latency inflated -> shrink
            new_limit = self._limit * max(0.5, min(1.5, gradient)) + 2.0
            self._limit = max(self.min_limit,
                              min(self.max_limit, new_limit))

    @property
    def current(self) -> int:
        return self._current

    @property
    def limit(self) -> int:
        return int(self._limit)


class TimeoutLimiter(ConcurrencyLimiter):
    """Reject when the expected wait (queued x avg latency) would blow the
    caller's budget."""

    name = "timeout"

    def __init__(self, timeout_ms: float = 500.0):
        self.timeout_ms = timeout_ms
        self._current = 0
        self._avg_latency_us = 0.0
        self._lock = threading.Lock()

    def on_request(self) -> bool:
        with self._lock:
            expected_us = self._current * self._avg_latency_us
            if expected_us > self.timeout_ms * 1000.0:
                return False
            self._current += 1
            return True

    def on_response(self, latency_us: float, error_code: int) -> None:
        with self._lock:
            self._current -= 1
            if error_code == 0:
                self._avg_latency_us += 0.1 * (latency_us
                                               - self._avg_latency_us)

    @property
    def current(self) -> int:
        return self._current


def create_limiter(spec) -> Optional[ConcurrencyLimiter]:
    """spec: int -> constant; 'auto' | 'timeout' | 'timeout:MS' | 'constant:N'."""
    if spec in (None, 0, "", "unlimited"):
        return None
    if isinstance(spec, int):
        return ConstantLimiter(spec)
    name, _, arg = str(spec).partition(":")
    if name == "constant":
        return ConstantLimiter(int(arg or 64))
    if name == "auto":
        return AutoLimiter(initial=int(arg) if arg else 32)
    if name == "timeout":
        return TimeoutLimiter(timeout_ms=float(arg) if arg else 500.0)
    raise ValueError(f"unknown concurrency limiter {spec!r}")
