"""trpc_stream — the stream frame protocol.

Counterpart of the reference's ``policy/streaming_rpc_protocol.cpp`` ("STRM"
frames parsed off the same connection as RPC traffic). Wire: ``b"TSTR"`` +
u32 meta_size + u32 body_size, meta = StreamFrameMeta. Frames address the
DESTINATION stream id directly; routing is a versioned-pool lookup, so
frames for a closed stream drop harmlessly (stale-id semantics).
"""

from __future__ import annotations

import struct
from typing import Optional, Tuple

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.policy.trpc_std import max_body_size
from brpc_tpu.proto import rpc_meta_pb2
from brpc_tpu.rpc.protocol import (
    PARSE_BAD,
    PARSE_NOT_ENOUGH_DATA,
    PARSE_TRY_OTHERS,
    ParsedMessage,
    Protocol,
)

MAGIC = b"TSTR"
HEADER_FMT = "!4sII"
HEADER_SIZE = struct.calcsize(HEADER_FMT)


def pack_stream_frame(meta: rpc_meta_pb2.StreamFrameMeta,
                      payload: bytes) -> IOBuf:
    meta_bytes = meta.SerializeToString()
    out = IOBuf()
    out.append(struct.pack(HEADER_FMT, MAGIC, len(meta_bytes), len(payload)))
    out.append(meta_bytes)
    if payload:
        out.append(payload)
    return out


class TrpcStreamProtocol(Protocol):
    name = "trpc_stream"
    magic = MAGIC
    inline_process = True  # frame order = arrival order; see Protocol

    def parse(self, buf: IOBuf) -> Tuple[int, Optional[ParsedMessage]]:
        if len(buf) < HEADER_SIZE:
            head = buf.fetch(min(len(buf), 4))
            if head and not MAGIC.startswith(head):
                return PARSE_TRY_OTHERS, None
            return PARSE_NOT_ENOUGH_DATA, None
        magic, meta_size, body_size = struct.unpack(
            HEADER_FMT, buf.fetch(HEADER_SIZE))
        if magic != MAGIC:
            return PARSE_TRY_OTHERS, None
        if meta_size + body_size > max_body_size():
            return PARSE_BAD, None  # corrupt size field: fail the socket
        total = HEADER_SIZE + meta_size + body_size
        if len(buf) < total:
            return PARSE_NOT_ENOUGH_DATA, None
        buf.pop_front(HEADER_SIZE)
        meta_bytes = buf.cutn(meta_size).tobytes()
        body = buf.cutn(body_size)
        try:
            meta = rpc_meta_pb2.StreamFrameMeta.FromString(meta_bytes)
        except Exception:
            return PARSE_BAD, None
        return 0, ParsedMessage(self, meta, body)

    def process(self, msg: ParsedMessage, server) -> None:
        from brpc_tpu.rpc.stream import (
            FRAME_CLOSE,
            FRAME_DATA,
            FRAME_FEEDBACK,
            get_stream,
        )

        meta = msg.meta
        stream = get_stream(meta.stream_id)
        if stream is None:
            return  # closed/stale stream: drop
        if meta.frame_type == FRAME_DATA:
            stream.on_data(meta.seq, msg.body.tobytes())
        elif meta.frame_type == FRAME_FEEDBACK:
            stream.on_feedback(meta.consumed_bytes)
        elif meta.frame_type == FRAME_CLOSE:
            stream.close(send_frame=False)
