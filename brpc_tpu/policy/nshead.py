"""nshead protocol — the UB legacy family's framing (client + server).

Counterpart of the reference's ``policy/nshead_protocol.cpp`` +
``nshead_service.h`` + ``nshead_message.h``: a 36-byte little-endian header
(id, version, log_id, provider[16], magic 0xfb709394, reserved, body_len)
followed by an opaque body. The ubrpc/mcpack/compack protocols of the
reference are all nshead-framed payload dialects; here the body is opaque
bytes and payload dialects layer on top (mcpack2pb provides one).

No correlation id on the wire -> positional FIFO correlation per
connection, like redis/memcache. Server side: ``ServerOptions.
nshead_service`` gets (controller-ish peer info, NsheadMessage) and returns
an NsheadMessage.
"""

from __future__ import annotations

import struct
import threading
from collections import deque
from typing import Optional, Tuple

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.fiber import runtime
from brpc_tpu.proto import rpc_meta_pb2
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.protocol import (
    PARSE_BAD,
    PARSE_NOT_ENOUGH_DATA,
    PARSE_TRY_OTHERS,
    ParsedMessage,
    Protocol,
    dispatch_response,
    init_socket_state,
)

NSHEAD_MAGIC = 0xFB709394
HEADER_FMT = "<HHI16sIII"
HEADER_SIZE = struct.calcsize(HEADER_FMT)  # 36
MAX_BODY = 64 << 20


class NsheadMessage:
    """head fields + opaque body; pb-duck-typed for the engine."""

    def __init__(self, body: bytes = b"", id: int = 0, version: int = 0,
                 log_id: int = 0, provider: bytes = b"brpc-tpu"):
        self.id = id
        self.version = version
        self.log_id = log_id
        self.provider = provider
        self.body = body if isinstance(body, bytes) else body.encode()

    def SerializeToString(self) -> bytes:
        return struct.pack(HEADER_FMT, self.id, self.version, self.log_id,
                           self.provider[:16].ljust(16, b"\x00"),
                           NSHEAD_MAGIC, 0, len(self.body)) + self.body

    def ParseFromString(self, data: bytes) -> None:
        (self.id, self.version, self.log_id, provider, magic, _res,
         body_len) = struct.unpack_from(HEADER_FMT, data, 0)
        if magic != NSHEAD_MAGIC:
            raise ValueError("bad nshead magic")
        self.provider = provider.rstrip(b"\x00")
        self.body = bytes(data[HEADER_SIZE:HEADER_SIZE + body_len])


def nshead_method():
    from brpc_tpu.rpc.channel import MethodDescriptor

    return MethodDescriptor("nshead", "call", NsheadMessage, NsheadMessage)


class NsheadService:
    """Subclass and override process(). Runs in a fiber per request; the
    response is written back in arrival order per connection."""

    def process(self, peer, request: NsheadMessage) -> NsheadMessage:
        raise NotImplementedError


class _NsClientState:
    __slots__ = ("fifo", "lock")

    def __init__(self):
        self.fifo = deque()  # (cid, ver)
        self.lock = threading.Lock()


class _NsServerState:
    __slots__ = ("queue",)

    def __init__(self, sock, service):
        def consume(items):
            if items is None:
                return
            out = IOBuf()
            for req in items:
                try:
                    resp = service.process(sock.remote, req)
                except Exception:
                    resp = NsheadMessage(b"", id=req.id, log_id=req.log_id)
                out.append(resp.SerializeToString())
            sock.write(out)

        from brpc_tpu.fiber.execution_queue import ExecutionQueue

        self.queue = ExecutionQueue(consume)


class NsheadProtocol(Protocol):
    name = "nshead"
    stateful = True

    # ------------------------------------------------------------- recv path
    def parse(self, buf: IOBuf, sock=None):
        cst = getattr(sock, "nshead_client", None)
        srv = sock.owner_server
        service = getattr(srv.options, "nshead_service", None) if srv else None
        if cst is None and service is None:
            return PARSE_TRY_OTHERS, None
        first = True
        while True:
            if len(buf) < HEADER_SIZE:
                if first and len(buf) >= 28:
                    # the magic (offset 24) is already visible: only reject
                    # when it genuinely isn't nshead
                    head = buf.fetch(28)
                    magic, = struct.unpack_from("<I", head, 24)
                    if magic != NSHEAD_MAGIC:
                        return PARSE_TRY_OTHERS, None
                return PARSE_NOT_ENOUGH_DATA, None
            head = buf.fetch(HEADER_SIZE)
            magic, = struct.unpack_from("<I", head, 24)
            body_len, = struct.unpack_from("<I", head, 32)
            if magic != NSHEAD_MAGIC or body_len > MAX_BODY:
                return (PARSE_TRY_OTHERS if first else PARSE_BAD), None
            if len(buf) < HEADER_SIZE + body_len:
                return PARSE_NOT_ENOUGH_DATA, None
            sock.preferred_protocol = self
            raw = buf.cutn(HEADER_SIZE + body_len).tobytes()
            msg_obj = NsheadMessage()
            msg_obj.ParseFromString(raw)
            sock.in_messages += 1
            first = False
            if service is not None and cst is None:
                sst = getattr(sock, "nshead_server", None)
                if sst is None:
                    sst = _NsServerState(sock, service)
                    sock.nshead_server = sst
                sst.queue.execute(msg_obj)
                continue
            with cst.lock:
                ctx = cst.fifo.popleft() if cst.fifo else None
            if ctx is None:
                return PARSE_BAD, None  # unsolicited response
            meta = rpc_meta_pb2.RpcMeta()
            meta.correlation_id, meta.attempt_version = ctx
            msg = ParsedMessage(self, meta, IOBuf(raw))
            msg.socket = sock
            runtime.start_background(dispatch_response, msg)

    # ------------------------------------------------------------- send path
    def issue_request(self, sock, meta, payload: bytes,
                      attachment: bytes = b"", checksum: bool = False,
                      id_wait=None) -> int:
        cst: _NsClientState = init_socket_state(
            sock, "nshead_client", _NsClientState, self)
        entry = (meta.correlation_id, meta.attempt_version)
        with cst.lock:
            # FIFO order IS the wire order (see redis_protocol)
            cst.fifo.append(entry)
            rc = sock.write(IOBuf(payload), id_wait=id_wait)
            if rc != 0:
                try:
                    cst.fifo.remove(entry)
                except ValueError:
                    pass
        return rc

    # ------------------------------------------------------ engine contracts
    @staticmethod
    def split_attachment(msg: ParsedMessage) -> Tuple[bytes, bytes]:
        return msg.body.tobytes(), b""

    @staticmethod
    def verify_checksum(meta, payload: bytes) -> bool:
        return True
