"""HTTP/2 connection layer (RFC 7540) — framing, streams, flow control.

Counterpart of the reference's ``policy/http2_rpc_protocol.cpp`` connection
machinery (H2Context/H2Stream there): per-connection HPACK contexts, frame
codec, SETTINGS negotiation, credit-based send windows with queued flushing
on WINDOW_UPDATE, and CONTINUATION reassembly. Protocol semantics (gRPC
message framing, status mapping, dispatch) live in ``grpc_protocol.py``.

Thread model: the receive path (``feed``) runs on the socket's serial parse
loop; the send path (``send_headers``/``send_data``) is called from fiber
workers. Send-side state — the HPACK encoder (whose emission order must
match wire order) and the credit windows — is guarded by ``send_lock``, and
every header block is encoded+written under it in one socket write.
"""

from __future__ import annotations

import struct
import threading
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.policy.hpack import HpackDecoder, HpackEncoder, HpackError

PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"

# frame types (RFC 7540 §6)
DATA = 0x0
HEADERS = 0x1
PRIORITY = 0x2
RST_STREAM = 0x3
SETTINGS = 0x4
PUSH_PROMISE = 0x5
PING = 0x6
GOAWAY = 0x7
WINDOW_UPDATE = 0x8
CONTINUATION = 0x9

FLAG_END_STREAM = 0x1
FLAG_ACK = 0x1
FLAG_END_HEADERS = 0x4
FLAG_PADDED = 0x8
FLAG_PRIORITY = 0x20

# settings ids (RFC 7540 §6.5.2)
S_HEADER_TABLE_SIZE = 0x1
S_ENABLE_PUSH = 0x2
S_MAX_CONCURRENT_STREAMS = 0x3
S_INITIAL_WINDOW_SIZE = 0x4
S_MAX_FRAME_SIZE = 0x5
S_MAX_HEADER_LIST_SIZE = 0x6

# error codes (RFC 7540 §7)
NO_ERROR = 0x0
PROTOCOL_ERROR = 0x1
INTERNAL_ERROR = 0x2
FLOW_CONTROL_ERROR = 0x3
STREAM_CLOSED = 0x5
FRAME_SIZE_ERROR = 0x6
REFUSED_STREAM = 0x7
CANCEL = 0x8

DEFAULT_WINDOW = 65535
DEFAULT_MAX_FRAME = 16384
# our receive windows: effectively unbounded, replenished by thresholds
RECV_STREAM_WINDOW = (1 << 31) - 1
CONN_REPLENISH_AT = 1 << 28
STREAM_REPLENISH_AT = 1 << 26
# reassembled header block ceiling (CONTINUATION-flood guard)
MAX_HEADER_BLOCK = 1 << 20


class H2Error(Exception):
    def __init__(self, code: int, msg: str = ""):
        super().__init__(msg or f"h2 error {code}")
        self.h2_code = code


def pack_frame(ftype: int, flags: int, stream_id: int, payload: bytes) -> bytes:
    n = len(payload)
    return (bytes([(n >> 16) & 0xFF, (n >> 8) & 0xFF, n & 0xFF, ftype, flags])
            + struct.pack("!I", stream_id & 0x7FFFFFFF) + payload)


def pack_settings(pairs: List[Tuple[int, int]], ack: bool = False) -> bytes:
    payload = b"".join(struct.pack("!HI", k, v) for k, v in pairs)
    return pack_frame(SETTINGS, FLAG_ACK if ack else 0, 0, payload)


class H2Stream:
    __slots__ = ("sid", "headers", "trailers", "data", "recv_end",
                 "send_window", "pending", "pending_end", "end_sent", "rst",
                 "headers_done", "recv_consumed", "user", "pending_trailers",
                 "close_on_end")

    def __init__(self, sid: int, send_window: int):
        self.sid = sid
        self.headers: Optional[List[Tuple[str, str]]] = None
        self.trailers: Optional[List[Tuple[str, str]]] = None
        self.data = bytearray()
        self.recv_end = False
        self.headers_done = False
        self.send_window = send_window
        self.pending = deque()       # queued bytes blocked on flow control
        self.pending_end = False     # END_STREAM owed after pending drains
        self.end_sent = False
        self.rst = False
        self.recv_consumed = 0
        self.user = None             # per-stream payload for the protocol
        self.pending_trailers = None  # trailers owed after pending drains
        self.close_on_end = False    # auto-pop once END_STREAM flushed


class H2Conn:
    """One HTTP/2 connection riding a Socket. Role 'client' or 'server'."""

    def __init__(self, sock, role: str,
                 on_stream_complete: Callable,
                 on_stream_reset: Optional[Callable] = None):
        self.sock = sock
        self.role = role
        self.on_stream_complete = on_stream_complete  # (conn, H2Stream, trailers_only)
        self.on_stream_reset = on_stream_reset        # (conn, sid, h2_code)
        self.encoder = HpackEncoder()
        self.decoder = HpackDecoder()
        self.send_lock = threading.Lock()
        self.streams: Dict[int, H2Stream] = {}
        self.next_stream_id = 1 if role == "client" else 2
        self.conn_send_window = DEFAULT_WINDOW
        self.peer_initial_window = DEFAULT_WINDOW
        self.peer_max_frame = DEFAULT_MAX_FRAME
        self.conn_recv_consumed = 0
        self.goaway_received = False
        self.preface_received = role == "client"  # only servers expect one
        self.settings_acked = False
        # CONTINUATION reassembly
        self._hdr_block: Optional[bytearray] = None
        self._hdr_sid = 0
        self._hdr_flags = 0
        self.calls: Dict[int, object] = {}  # client: sid -> call context

    # ------------------------------------------------------------- handshake
    def send_preamble(self) -> None:
        """Client preface / server settings — the first bytes on the wire."""
        out = IOBuf()
        if self.role == "client":
            out.append(PREFACE)
        out.append(pack_settings([
            (S_INITIAL_WINDOW_SIZE, RECV_STREAM_WINDOW),
            (S_MAX_CONCURRENT_STREAMS, 1 << 20),
        ]))
        out.append(pack_frame(WINDOW_UPDATE, 0, 0,
                              struct.pack("!I", RECV_STREAM_WINDOW - DEFAULT_WINDOW)))
        self.sock.write(out)

    # ------------------------------------------------------------- send side
    def _emit_headers_locked(self, sid: int, headers: List[Tuple[str, str]],
                             end_stream: bool, id_wait=None) -> int:
        """Encode+write one header block (HPACK order == wire order), split
        into HEADERS (+CONTINUATIONs) per the peer's frame limit. Caller
        holds send_lock."""
        block = self.encoder.encode(headers)
        frames = IOBuf()
        first, rest = block[:self.peer_max_frame], block[self.peer_max_frame:]
        flags = (FLAG_END_STREAM if end_stream else 0)
        if not rest:
            flags |= FLAG_END_HEADERS
        frames.append(pack_frame(HEADERS, flags, sid, first))
        while rest:
            chunk, rest = rest[:self.peer_max_frame], rest[self.peer_max_frame:]
            frames.append(pack_frame(
                CONTINUATION, FLAG_END_HEADERS if not rest else 0,
                sid, chunk))
        return self.sock.write(frames, id_wait=id_wait)

    def send_headers(self, sid: int, headers: List[Tuple[str, str]],
                     end_stream: bool = False, id_wait=None) -> int:
        with self.send_lock:
            return self._emit_headers_locked(sid, headers, end_stream, id_wait)

    def open_stream_with_headers(self, headers: List[Tuple[str, str]],
                                 end_stream: bool = False, id_wait=None,
                                 call_ctx=None) -> Tuple[H2Stream, int]:
        """Allocate a stream id and emit its HEADERS atomically, so stream
        ids appear on the wire in increasing order (RFC 7540 §5.1.1) and a
        response can never beat the call registration."""
        with self.send_lock:
            sid = self.next_stream_id
            self.next_stream_id += 2
            st = H2Stream(sid, self.peer_initial_window)
            self.streams[sid] = st
            if call_ctx is not None:
                self.calls[sid] = call_ctx
            rc = self._emit_headers_locked(sid, headers, end_stream, id_wait)
            return st, rc

    def send_trailers(self, sid: int, trailers: List[Tuple[str, str]]) -> None:
        """Queue the trailing header block; it must follow all DATA on the
        wire, so it waits for any flow-control-blocked bytes to drain."""
        with self.send_lock:
            st = self.streams.get(sid)
            if st is None or st.rst:
                return
            if st.pending:
                st.pending_trailers = trailers
            else:
                self._emit_trailers_locked(st, trailers)

    def _emit_trailers_locked(self, st: H2Stream, trailers) -> None:
        st.end_sent = True
        self._emit_headers_locked(st.sid, trailers, end_stream=True)
        if self.role == "server":  # response fully sent — stream is done
            self.streams.pop(st.sid, None)

    def send_data(self, sid: int, data: bytes, end_stream: bool = True) -> int:
        """Flow-controlled DATA: write what the windows allow, queue the
        rest for WINDOW_UPDATE-driven flushing."""
        with self.send_lock:
            st = self.streams.get(sid)
            if st is None or st.rst:
                return 0
            if data:
                st.pending.append(memoryview(bytes(data)))
            # empty payloads only carry END_STREAM — a zero-length pending
            # head would wedge the flush loop (allowed=0) and never emit it
            if end_stream:
                st.pending_end = True
            return self._flush_stream_locked(st)

    def _flush_stream_locked(self, st: H2Stream) -> int:
        out = IOBuf()
        while st.pending:
            head = st.pending[0]
            allowed = min(len(head), st.send_window, self.conn_send_window,
                          self.peer_max_frame)
            if allowed <= 0:
                break
            chunk = head[:allowed]
            if allowed == len(head):
                st.pending.popleft()
            else:
                st.pending[0] = head[allowed:]
            st.send_window -= allowed
            self.conn_send_window -= allowed
            end = (not st.pending) and st.pending_end
            if end:
                st.end_sent = True
            out.append(pack_frame(DATA, FLAG_END_STREAM if end else 0,
                                  st.sid, bytes(chunk)))
        if st.pending_end and not st.pending and not st.end_sent:
            # END_STREAM owed but no bytes left to carry it (empty message)
            st.end_sent = True
            out.append(pack_frame(DATA, FLAG_END_STREAM, st.sid, b""))
        rc = self.sock.write(out) if len(out) else 0
        if not st.pending and st.pending_trailers is not None:
            trailers, st.pending_trailers = st.pending_trailers, None
            self._emit_trailers_locked(st, trailers)
        if st.end_sent and st.close_on_end:
            # deferred close: only once the flow-controlled tail (and its
            # END_STREAM) actually went out — an immediate close_stream
            # would strand pending bytes when the peer's window is small
            self.streams.pop(st.sid, None)
        return rc

    def _flush_all_locked(self) -> None:
        for st in list(self.streams.values()):
            if st.pending:
                self._flush_stream_locked(st)

    def send_rst(self, sid: int, code: int) -> None:
        self.sock.write(pack_frame(RST_STREAM, 0, sid, struct.pack("!I", code)))

    def send_goaway(self, code: int, last_sid: int = 0) -> None:
        self.sock.write(pack_frame(GOAWAY, 0, 0,
                                   struct.pack("!II", last_sid, code)))

    def close_stream(self, sid: int) -> None:
        with self.send_lock:
            self.streams.pop(sid, None)
            self.calls.pop(sid, None)

    # ------------------------------------------------------------ recv side
    def feed(self, buf: IOBuf) -> None:
        """Consume every complete frame in buf (serial parse loop). Raises
        H2Error for connection-level errors."""
        if not self.preface_received:
            if len(buf) < len(PREFACE):
                return
            got = buf.fetch(len(PREFACE))
            if got != PREFACE:
                raise H2Error(PROTOCOL_ERROR, "bad connection preface")
            buf.pop_front(len(PREFACE))
            self.preface_received = True
        while True:
            if len(buf) < 9:
                return
            head = buf.fetch(9)
            length = (head[0] << 16) | (head[1] << 8) | head[2]
            ftype, flags = head[3], head[4]
            sid = struct.unpack("!I", head[5:9])[0] & 0x7FFFFFFF
            if length > (1 << 24):
                raise H2Error(FRAME_SIZE_ERROR, "frame too large")
            if len(buf) < 9 + length:
                return
            buf.pop_front(9)
            payload = buf.cutn(length).tobytes()
            self._on_frame(ftype, flags, sid, payload)

    # ---------------------------------------------------------- frame logic
    def _on_frame(self, ftype: int, flags: int, sid: int, payload: bytes) -> None:
        if self._hdr_block is not None and ftype != CONTINUATION:
            raise H2Error(PROTOCOL_ERROR, "expected CONTINUATION")
        if ftype == DATA:
            self._on_data(flags, sid, payload)
        elif ftype == HEADERS:
            # RFC 7540 §6.2 field order: pad length byte (if PADDED), THEN
            # priority fields (if PRIORITY), then the fragment + padding
            pad = 0
            if flags & FLAG_PADDED:
                if not payload:
                    raise H2Error(FRAME_SIZE_ERROR, "HEADERS missing pad len")
                pad = payload[0]
                payload = payload[1:]
            if flags & FLAG_PRIORITY:
                if len(payload) < 5:
                    raise H2Error(FRAME_SIZE_ERROR,
                                  "HEADERS missing priority fields")
                payload = payload[5:]
            if pad > len(payload):
                raise H2Error(PROTOCOL_ERROR, "padding exceeds payload")
            payload = payload[:len(payload) - pad]
            if len(payload) > MAX_HEADER_BLOCK:
                raise H2Error(PROTOCOL_ERROR, "header block too large")
            self._hdr_block = bytearray(payload)
            self._hdr_sid = sid
            self._hdr_flags = flags
            if flags & FLAG_END_HEADERS:
                self._finish_header_block()
        elif ftype == CONTINUATION:
            if self._hdr_block is None or sid != self._hdr_sid:
                raise H2Error(PROTOCOL_ERROR, "unexpected CONTINUATION")
            self._hdr_block += payload
            if len(self._hdr_block) > MAX_HEADER_BLOCK:
                # unbounded reassembly is the h2 CONTINUATION-flood DoS
                raise H2Error(PROTOCOL_ERROR, "header block too large")
            if flags & FLAG_END_HEADERS:
                self._finish_header_block()
        elif ftype == SETTINGS:
            self._on_settings(flags, payload)
        elif ftype == WINDOW_UPDATE:
            self._on_window_update(sid, payload)
        elif ftype == PING:
            if not flags & FLAG_ACK:
                self.sock.write(pack_frame(PING, FLAG_ACK, 0, payload))
        elif ftype == RST_STREAM:
            code = struct.unpack("!I", payload[:4])[0] if len(payload) >= 4 else 0
            st = self.streams.get(sid)
            if st is not None:
                st.rst = True
            if self.on_stream_reset is not None:
                self.on_stream_reset(self, sid, code)
            self.close_stream(sid)
        elif ftype == GOAWAY:
            self.goaway_received = True
        elif ftype == PUSH_PROMISE:
            raise H2Error(PROTOCOL_ERROR, "push not enabled")
        # PRIORITY and unknown frame types: ignore (RFC 7540 §4.1)

    def _on_data(self, flags: int, sid: int, payload: bytes) -> None:
        # flow-control credits cover the WHOLE frame payload, padding
        # included (RFC 7540 §6.9.1) — account before stripping
        frame_len = len(payload)
        if flags & FLAG_PADDED:
            if not payload:
                raise H2Error(FRAME_SIZE_ERROR, "DATA missing pad length")
            pad = payload[0]
            if pad > len(payload) - 1:
                raise H2Error(PROTOCOL_ERROR, "padding exceeds payload")
            payload = payload[1:len(payload) - pad]
        st = self.streams.get(sid)
        if st is not None and st.recv_end:
            raise H2Error(STREAM_CLOSED, "DATA after END_STREAM")
        if st is not None and not st.rst:
            st.data += payload
            st.recv_consumed += frame_len
            if st.recv_consumed > STREAM_REPLENISH_AT and not flags & FLAG_END_STREAM:
                self.sock.write(pack_frame(
                    WINDOW_UPDATE, 0, sid,
                    struct.pack("!I", st.recv_consumed)))
                st.recv_consumed = 0
        # connection window credits are consumed regardless of stream state
        self.conn_recv_consumed += frame_len
        if self.conn_recv_consumed > CONN_REPLENISH_AT:
            self.sock.write(pack_frame(
                WINDOW_UPDATE, 0, 0,
                struct.pack("!I", self.conn_recv_consumed)))
            self.conn_recv_consumed = 0
        if st is not None and flags & FLAG_END_STREAM:
            st.recv_end = True
            self.on_stream_complete(self, st, trailers_only=False)

    def _finish_header_block(self) -> None:
        block, sid, flags = bytes(self._hdr_block), self._hdr_sid, self._hdr_flags
        self._hdr_block = None
        try:
            headers = self.decoder.decode(block)
        except HpackError as e:
            raise H2Error(INTERNAL_ERROR, f"hpack: {e}")
        st = self.streams.get(sid)
        if st is None:
            if self.role != "server":
                return  # response headers for a finished/unknown stream
            st = H2Stream(sid, self.peer_initial_window)
            self.streams[sid] = st
        if st.recv_end:
            # a completed request was already dispatched — a second
            # END_STREAM must not run user code twice
            raise H2Error(STREAM_CLOSED, "HEADERS after END_STREAM")
        if not st.headers_done:
            st.headers = headers
            st.headers_done = True
        else:
            st.trailers = headers
        if flags & FLAG_END_STREAM:
            st.recv_end = True
            self.on_stream_complete(self, st,
                                    trailers_only=st.trailers is not None)

    def _on_settings(self, flags: int, payload: bytes) -> None:
        if flags & FLAG_ACK:
            self.settings_acked = True
            return
        flush = False
        with self.send_lock:
            for off in range(0, len(payload) - 5, 6):
                k, v = struct.unpack_from("!HI", payload, off)
                if k == S_INITIAL_WINDOW_SIZE:
                    delta = v - self.peer_initial_window
                    self.peer_initial_window = v
                    for st in self.streams.values():
                        st.send_window += delta
                    flush = delta > 0
                elif k == S_MAX_FRAME_SIZE:
                    if DEFAULT_MAX_FRAME <= v <= (1 << 24) - 1:
                        self.peer_max_frame = v
                elif k == S_HEADER_TABLE_SIZE:
                    self.encoder.table.resize(min(v, 4096))
            if flush:
                self._flush_all_locked()
        self.sock.write(pack_settings([], ack=True))

    def _on_window_update(self, sid: int, payload: bytes) -> None:
        if len(payload) < 4:
            raise H2Error(FRAME_SIZE_ERROR, "short WINDOW_UPDATE")
        inc = struct.unpack("!I", payload[:4])[0] & 0x7FFFFFFF
        with self.send_lock:
            if sid == 0:
                self.conn_send_window += inc
                self._flush_all_locked()
            else:
                st = self.streams.get(sid)
                if st is not None:
                    st.send_window += inc
                    if st.pending:
                        self._flush_stream_locked(st)
