"""bson — minimal BSON codec for the mongo wire protocol.

Counterpart of the reference's vendored bson slice under
``policy/mongo_protocol.cpp`` usage. Covers the types mongo commands and
replies actually use; everything is plain Python values:

  float <-> double (0x01)        str <-> string (0x02)
  dict <-> document (0x03)       list <-> array (0x04)
  bytes <-> binary/generic(0x05) ObjectId <-> ObjectId (0x07)
  bool <-> boolean (0x08)        datetime <-> UTC datetime (0x09)
  None <-> null (0x0A)           int <-> int32/int64 (0x10/0x12)

Unknown element types raise BsonError on decode (a malformed reply must
not be silently mis-read).
"""

from __future__ import annotations

import datetime as _dt
import os
import struct
import threading
import time


class BsonError(ValueError):
    pass


class ObjectId:
    """12-byte mongo object id (4B time + 5B random + 3B counter)."""

    _counter = int.from_bytes(os.urandom(3), "big")
    _rand = os.urandom(5)
    _lock = threading.Lock()

    __slots__ = ("binary",)

    def __init__(self, binary: bytes = b""):
        if binary:
            if len(binary) != 12:
                raise BsonError("ObjectId needs 12 bytes")
            self.binary = bytes(binary)
        else:
            with ObjectId._lock:
                ObjectId._counter = (ObjectId._counter + 1) & 0xFFFFFF
                cnt = ObjectId._counter
            self.binary = (struct.pack(">I", int(time.time()))
                           + ObjectId._rand + cnt.to_bytes(3, "big"))

    def __repr__(self):
        return f"ObjectId({self.binary.hex()})"

    def __eq__(self, other):
        return isinstance(other, ObjectId) and other.binary == self.binary

    def __hash__(self):
        return hash(self.binary)


_EPOCH = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)


def _encode_value(key: str, value, out: bytearray) -> None:
    kb = key.encode("utf-8") + b"\x00"
    if isinstance(value, bool):  # before int (bool is an int subclass)
        out += b"\x08" + kb + (b"\x01" if value else b"\x00")
    elif isinstance(value, float):
        out += b"\x01" + kb + struct.pack("<d", value)
    elif isinstance(value, int):
        if -(1 << 31) <= value < (1 << 31):
            out += b"\x10" + kb + struct.pack("<i", value)
        elif -(1 << 63) <= value < (1 << 63):
            out += b"\x12" + kb + struct.pack("<q", value)
        else:
            raise BsonError(f"int out of int64 range: {value}")
    elif isinstance(value, str):
        vb = value.encode("utf-8") + b"\x00"
        out += b"\x02" + kb + struct.pack("<i", len(vb)) + vb
    elif isinstance(value, dict):
        out += b"\x03" + kb + encode(value)
    elif isinstance(value, (list, tuple)):
        out += b"\x04" + kb + encode(
            {str(i): v for i, v in enumerate(value)})
    elif isinstance(value, (bytes, bytearray, memoryview)):
        vb = bytes(value)
        out += b"\x05" + kb + struct.pack("<i", len(vb)) + b"\x00" + vb
    elif isinstance(value, ObjectId):
        out += b"\x07" + kb + value.binary
    elif isinstance(value, _dt.datetime):
        if value.tzinfo is None:  # the common naive idiom means UTC
            value = value.replace(tzinfo=_dt.timezone.utc)
        ms = int((value - _EPOCH).total_seconds() * 1000)
        out += b"\x09" + kb + struct.pack("<q", ms)
    elif value is None:
        out += b"\x0a" + kb
    else:
        raise BsonError(f"cannot BSON-encode {type(value).__name__}")


def encode(doc: dict) -> bytes:
    out = bytearray()
    for key, value in doc.items():
        _encode_value(str(key), value, out)
    return struct.pack("<i", len(out) + 5) + bytes(out) + b"\x00"


def _decode_cstring(data: bytes, pos: int) -> tuple:
    end = data.find(b"\x00", pos)
    if end < 0:
        raise BsonError("unterminated cstring")
    try:
        return data[pos:end].decode("utf-8"), end + 1
    except UnicodeDecodeError as e:
        raise BsonError(f"invalid utf-8 in key: {e}") from None


def _decode_value(etype: int, data: bytes, pos: int,
                  depth: int = 0) -> tuple:
    if etype == 0x01:
        if pos + 8 > len(data):
            raise BsonError("truncated double")
        return struct.unpack_from("<d", data, pos)[0], pos + 8
    if etype == 0x02:
        if pos + 4 > len(data):
            raise BsonError("truncated string length")
        (n,) = struct.unpack_from("<i", data, pos)
        pos += 4
        if n < 1 or pos + n > len(data):
            raise BsonError("bad string length")
        try:
            return data[pos:pos + n - 1].decode("utf-8"), pos + n
        except UnicodeDecodeError as e:
            raise BsonError(f"invalid utf-8 in string: {e}") from None
    if etype in (0x03, 0x04):
        doc, pos = _decode_doc(data, pos, depth + 1)
        if etype == 0x04:
            try:
                keys = sorted(doc, key=int)
            except ValueError:
                raise BsonError("array with non-numeric index keys") \
                    from None
            return [doc[k] for k in keys], pos
        return doc, pos
    if etype == 0x05:
        if pos + 5 > len(data):
            raise BsonError("truncated binary")
        (n,) = struct.unpack_from("<i", data, pos)
        pos += 5  # length + subtype byte
        if n < 0 or pos + n > len(data):
            raise BsonError("bad binary length")
        return bytes(data[pos:pos + n]), pos + n
    if etype == 0x07:
        if pos + 12 > len(data):
            raise BsonError("truncated ObjectId")
        return ObjectId(data[pos:pos + 12]), pos + 12
    if etype == 0x08:
        if pos >= len(data):
            raise BsonError("truncated bool")
        return data[pos] != 0, pos + 1
    if etype == 0x09:
        if pos + 8 > len(data):
            raise BsonError("truncated datetime")
        (ms,) = struct.unpack_from("<q", data, pos)
        try:
            return _EPOCH + _dt.timedelta(milliseconds=ms), pos + 8
        except (OverflowError, OSError):
            raise BsonError("datetime out of range") from None
    if etype == 0x0A:
        return None, pos
    if etype == 0x10:
        if pos + 4 > len(data):
            raise BsonError("truncated int32")
        return struct.unpack_from("<i", data, pos)[0], pos + 4
    if etype == 0x12:
        if pos + 8 > len(data):
            raise BsonError("truncated int64")
        return struct.unpack_from("<q", data, pos)[0], pos + 8
    raise BsonError(f"unsupported BSON type 0x{etype:02x}")


MAX_DEPTH = 100  # mongo's own nesting limit


def _decode_doc(data: bytes, pos: int, depth: int = 0) -> tuple:
    if depth > MAX_DEPTH:
        raise BsonError("document nesting exceeds limit")
    if pos + 4 > len(data):
        raise BsonError("truncated document length")
    (total,) = struct.unpack_from("<i", data, pos)
    if total < 5 or pos + total > len(data):
        raise BsonError("bad document length")
    end = pos + total
    if data[end - 1] != 0:
        raise BsonError("document missing terminator")
    pos += 4
    doc = {}
    while pos < end - 1:
        etype = data[pos]
        pos += 1
        key, pos = _decode_cstring(data, pos)
        value, pos = _decode_value(etype, data, pos, depth)
        doc[key] = value
    if pos != end - 1:
        raise BsonError("document element overrun")
    return doc, end


def decode(data: bytes, pos: int = 0) -> dict:
    doc, end = _decode_doc(bytes(data), pos)
    return doc
