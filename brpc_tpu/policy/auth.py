"""Authentication — pluggable credential exchange per connection.

Rebuild of the reference's ``Authenticator`` interface (authenticator.h;
per-socket "fight" resolved in controller.cpp:1186-1199): the client
generates credentials once per channel, sends them in ``RpcMeta.auth_token``
(trpc_std) or the ``Authorization`` header (http); the server verifies and
may attach an AuthContext the service reads via ``cntl.auth_context``.

Our simplification, stated up front: the reference authenticates once per
*connection* (first RPC carries credentials, later ones inherit); we carry
the token on every request — stateless, replay-window-free, and immune to
the connection-pool sharing races the reference's per-socket fight exists
to resolve.
"""

from __future__ import annotations

import hashlib
import hmac
import time
from typing import Optional


class AuthContext:
    """What a successful verification learned (reference auth_context.h)."""

    __slots__ = ("user", "group", "roles", "is_service")

    def __init__(self, user: str = "", group: str = "", roles=(),
                 is_service: bool = False):
        self.user = user
        self.group = group
        self.roles = tuple(roles)
        self.is_service = is_service


class Authenticator:
    """Subclass and pass to ChannelOptions.auth / ServerOptions.auth."""

    def generate_credential(self) -> str:
        """Client side: the token sent with each request."""
        raise NotImplementedError

    def verify_credential(self, token: str,
                          peer) -> Optional[AuthContext]:
        """Server side — THE framework entry point: return an AuthContext
        to accept (it becomes ``cntl.auth_context``), None to reject.
        Called concurrently from request-processing fibers; implementations
        must be thread-safe and must not stash per-request state on self."""
        raise NotImplementedError


class SharedSecretAuthenticator(Authenticator):
    """HMAC over a timestamp with a pre-shared key — a usable default (the
    reference ships the interface only; this is our batteries-included
    implementation for tests/examples)."""

    def __init__(self, secret: bytes, user: str = "default",
                 max_skew_s: float = 300.0):
        self.secret = secret if isinstance(secret, bytes) else secret.encode()
        self.user = user
        self.max_skew_s = max_skew_s

    def generate_credential(self) -> str:
        ts = str(int(time.time()))
        mac = hmac.new(self.secret, f"{self.user}:{ts}".encode(),
                       hashlib.sha256).hexdigest()
        return f"{self.user}:{ts}:{mac}"

    def verify_credential(self, token: str, peer) -> Optional[AuthContext]:
        try:
            user, ts, mac = token.split(":")
            if abs(time.time() - int(ts)) > self.max_skew_s:
                return None
            expect = hmac.new(self.secret, f"{user}:{ts}".encode(),
                              hashlib.sha256).hexdigest()
            if not hmac.compare_digest(mac, expect):
                return None
            return AuthContext(user=user)
        except (ValueError, AttributeError):
            return None
