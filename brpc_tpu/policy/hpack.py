"""HPACK (RFC 7541) — header compression for HTTP/2 and gRPC.

Counterpart of the reference's ``details/hpack.cpp`` (used by
``policy/http2_rpc_protocol.cpp``). Full implementation: static table,
per-connection dynamic table with size eviction, integer/string literals,
and the complete Huffman code. Tables below are the public RFC 7541
Appendix A/B data, not reference code.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

# --------------------------------------------------------------- static table
# RFC 7541 Appendix A (1-indexed).
STATIC_TABLE: List[Tuple[str, str]] = [
    (":authority", ""),
    (":method", "GET"),
    (":method", "POST"),
    (":path", "/"),
    (":path", "/index.html"),
    (":scheme", "http"),
    (":scheme", "https"),
    (":status", "200"),
    (":status", "204"),
    (":status", "206"),
    (":status", "304"),
    (":status", "400"),
    (":status", "404"),
    (":status", "500"),
    ("accept-charset", ""),
    ("accept-encoding", "gzip, deflate"),
    ("accept-language", ""),
    ("accept-ranges", ""),
    ("accept", ""),
    ("access-control-allow-origin", ""),
    ("age", ""),
    ("allow", ""),
    ("authorization", ""),
    ("cache-control", ""),
    ("content-disposition", ""),
    ("content-encoding", ""),
    ("content-language", ""),
    ("content-length", ""),
    ("content-location", ""),
    ("content-range", ""),
    ("content-type", ""),
    ("cookie", ""),
    ("date", ""),
    ("etag", ""),
    ("expect", ""),
    ("expires", ""),
    ("from", ""),
    ("host", ""),
    ("if-match", ""),
    ("if-modified-since", ""),
    ("if-none-match", ""),
    ("if-range", ""),
    ("if-unmodified-since", ""),
    ("last-modified", ""),
    ("link", ""),
    ("location", ""),
    ("max-forwards", ""),
    ("proxy-authenticate", ""),
    ("proxy-authorization", ""),
    ("range", ""),
    ("referer", ""),
    ("refresh", ""),
    ("retry-after", ""),
    ("server", ""),
    ("set-cookie", ""),
    ("strict-transport-security", ""),
    ("transfer-encoding", ""),
    ("user-agent", ""),
    ("vary", ""),
    ("via", ""),
    ("www-authenticate", ""),
]
STATIC_COUNT = len(STATIC_TABLE)  # 61

# index lookups for encoding: full (name,value) match, then name-only
_STATIC_FULL: Dict[Tuple[str, str], int] = {}
_STATIC_NAME: Dict[str, int] = {}
for _i, (_n, _v) in enumerate(STATIC_TABLE):
    _STATIC_FULL.setdefault((_n, _v), _i + 1)
    _STATIC_NAME.setdefault(_n, _i + 1)

# -------------------------------------------------------------- Huffman table
# RFC 7541 Appendix B: (code, bit-length) for symbols 0..255 + EOS(256).
HUFFMAN_CODES: List[Tuple[int, int]] = [
    (0x1FF8, 13), (0x7FFFD8, 23), (0xFFFFFE2, 28), (0xFFFFFE3, 28),
    (0xFFFFFE4, 28), (0xFFFFFE5, 28), (0xFFFFFE6, 28), (0xFFFFFE7, 28),
    (0xFFFFFE8, 28), (0xFFFFEA, 24), (0x3FFFFFFC, 30), (0xFFFFFE9, 28),
    (0xFFFFFEA, 28), (0x3FFFFFFD, 30), (0xFFFFFEB, 28), (0xFFFFFEC, 28),
    (0xFFFFFED, 28), (0xFFFFFEE, 28), (0xFFFFFEF, 28), (0xFFFFFF0, 28),
    (0xFFFFFF1, 28), (0xFFFFFF2, 28), (0x3FFFFFFE, 30), (0xFFFFFF3, 28),
    (0xFFFFFF4, 28), (0xFFFFFF5, 28), (0xFFFFFF6, 28), (0xFFFFFF7, 28),
    (0xFFFFFF8, 28), (0xFFFFFF9, 28), (0xFFFFFFA, 28), (0xFFFFFFB, 28),
    (0x14, 6), (0x3F8, 10), (0x3F9, 10), (0xFFA, 12),
    (0x1FF9, 13), (0x15, 6), (0xF8, 8), (0x7FA, 11),
    (0x3FA, 10), (0x3FB, 10), (0xF9, 8), (0x7FB, 11),
    (0xFA, 8), (0x16, 6), (0x17, 6), (0x18, 6),
    (0x0, 5), (0x1, 5), (0x2, 5), (0x19, 6),
    (0x1A, 6), (0x1B, 6), (0x1C, 6), (0x1D, 6),
    (0x1E, 6), (0x1F, 6), (0x5C, 7), (0xFB, 8),
    (0x7FFC, 15), (0x20, 6), (0xFFB, 12), (0x3FC, 10),
    (0x1FFA, 13), (0x21, 6), (0x5D, 7), (0x5E, 7),
    (0x5F, 7), (0x60, 7), (0x61, 7), (0x62, 7),
    (0x63, 7), (0x64, 7), (0x65, 7), (0x66, 7),
    (0x67, 7), (0x68, 7), (0x69, 7), (0x6A, 7),
    (0x6B, 7), (0x6C, 7), (0x6D, 7), (0x6E, 7),
    (0x6F, 7), (0x70, 7), (0x71, 7), (0x72, 7),
    (0xFC, 8), (0x73, 7), (0xFD, 8), (0x1FFB, 13),
    (0x7FFF0, 19), (0x1FFC, 13), (0x3FFC, 14), (0x22, 6),
    (0x7FFD, 15), (0x3, 5), (0x23, 6), (0x4, 5),
    (0x24, 6), (0x5, 5), (0x25, 6), (0x26, 6),
    (0x27, 6), (0x6, 5), (0x74, 7), (0x75, 7),
    (0x28, 6), (0x29, 6), (0x2A, 6), (0x7, 5),
    (0x2B, 6), (0x76, 7), (0x2C, 6), (0x8, 5),
    (0x9, 5), (0x2D, 6), (0x77, 7), (0x78, 7),
    (0x79, 7), (0x7A, 7), (0x7B, 7), (0x7FFE, 15),
    (0x7FC, 11), (0x3FFD, 14), (0x1FFD, 13), (0xFFFFFFC, 28),
    (0xFFFE6, 20), (0x3FFFD2, 22), (0xFFFE7, 20), (0xFFFE8, 20),
    (0x3FFFD3, 22), (0x3FFFD4, 22), (0x3FFFD5, 22), (0x7FFFD9, 23),
    (0x3FFFD6, 22), (0x7FFFDA, 23), (0x7FFFDB, 23), (0x7FFFDC, 23),
    (0x7FFFDD, 23), (0x7FFFDE, 23), (0xFFFFEB, 24), (0x7FFFDF, 23),
    (0xFFFFEC, 24), (0xFFFFED, 24), (0x3FFFD7, 22), (0x7FFFE0, 23),
    (0xFFFFEE, 24), (0x7FFFE1, 23), (0x7FFFE2, 23), (0x7FFFE3, 23),
    (0x7FFFE4, 23), (0x1FFFDC, 21), (0x3FFFD8, 22), (0x7FFFE5, 23),
    (0x3FFFD9, 22), (0x7FFFE6, 23), (0x7FFFE7, 23), (0xFFFFEF, 24),
    (0x3FFFDA, 22), (0x1FFFDD, 21), (0xFFFE9, 20), (0x3FFFDB, 22),
    (0x3FFFDC, 22), (0x7FFFE8, 23), (0x7FFFE9, 23), (0x1FFFDE, 21),
    (0x7FFFEA, 23), (0x3FFFDD, 22), (0x3FFFDE, 22), (0xFFFFF0, 24),
    (0x1FFFDF, 21), (0x3FFFDF, 22), (0x7FFFEB, 23), (0x7FFFEC, 23),
    (0x1FFFE0, 21), (0x1FFFE1, 21), (0x3FFFE0, 22), (0x1FFFE2, 21),
    (0x7FFFED, 23), (0x3FFFE1, 22), (0x7FFFEE, 23), (0x7FFFEF, 23),
    (0xFFFEA, 20), (0x3FFFE2, 22), (0x3FFFE3, 22), (0x3FFFE4, 22),
    (0x7FFFF0, 23), (0x3FFFE5, 22), (0x3FFFE6, 22), (0x7FFFF1, 23),
    (0x3FFFFE0, 26), (0x3FFFFE1, 26), (0xFFFEB, 20), (0x7FFF1, 19),
    (0x3FFFE7, 22), (0x7FFFF2, 23), (0x3FFFE8, 22), (0x1FFFFEC, 25),
    (0x3FFFFE2, 26), (0x3FFFFE3, 26), (0x3FFFFE4, 26), (0x7FFFFDE, 27),
    (0x7FFFFDF, 27), (0x3FFFFE5, 26), (0xFFFFF1, 24), (0x1FFFFED, 25),
    (0x7FFF2, 19), (0x1FFFE3, 21), (0x3FFFFE6, 26), (0x7FFFFE0, 27),
    (0x7FFFFE1, 27), (0x3FFFFE7, 26), (0x7FFFFE2, 27), (0xFFFFF2, 24),
    (0x1FFFE4, 21), (0x1FFFE5, 21), (0x3FFFFE8, 26), (0x3FFFFE9, 26),
    (0xFFFFFFD, 28), (0x7FFFFE3, 27), (0x7FFFFE4, 27), (0x7FFFFE5, 27),
    (0xFFFEC, 20), (0xFFFFF3, 24), (0xFFFED, 20), (0x1FFFE6, 21),
    (0x3FFFE9, 22), (0x1FFFE7, 21), (0x1FFFE8, 21), (0x7FFFF3, 23),
    (0x3FFFEA, 22), (0x3FFFEB, 22), (0x1FFFFEE, 25), (0x1FFFFEF, 25),
    (0xFFFFF4, 24), (0xFFFFF5, 24), (0x3FFFFEA, 26), (0x7FFFF4, 23),
    (0x3FFFFEB, 26), (0x7FFFFE6, 27), (0x3FFFFEC, 26), (0x3FFFFED, 26),
    (0x7FFFFE7, 27), (0x7FFFFE8, 27), (0x7FFFFE9, 27), (0x7FFFFEA, 27),
    (0x7FFFFEB, 27), (0xFFFFFFE, 28), (0x7FFFFEC, 27), (0x7FFFFED, 27),
    (0x7FFFFEE, 27), (0x7FFFFEF, 27), (0x7FFFFF0, 27), (0x3FFFFEE, 26),
    (0x3FFFFFFF, 30),
]

# decode dict: (bit-length, code) -> symbol; max code length is 30 bits so
# decoding probes at most 26 lengths per symbol (shortest code is 5 bits)
_HUFF_DECODE: Dict[Tuple[int, int], int] = {
    (bits, code): sym for sym, (code, bits) in enumerate(HUFFMAN_CODES)
}
_MIN_BITS = min(b for _, b in HUFFMAN_CODES)  # 5


class HpackError(Exception):
    pass


def huffman_encode(data: bytes) -> bytes:
    acc = 0
    nbits = 0
    out = bytearray()
    for b in data:
        code, blen = HUFFMAN_CODES[b]
        acc = (acc << blen) | code
        nbits += blen
        while nbits >= 8:
            nbits -= 8
            out.append((acc >> nbits) & 0xFF)
    if nbits:
        # pad with EOS prefix (all-ones)
        out.append(((acc << (8 - nbits)) | ((1 << (8 - nbits)) - 1)) & 0xFF)
    return bytes(out)


def huffman_decode(data: bytes) -> bytes:
    out = bytearray()
    cur = 0
    curlen = 0
    decode = _HUFF_DECODE
    for byte in data:
        for i in range(7, -1, -1):
            cur = (cur << 1) | ((byte >> i) & 1)
            curlen += 1
            if curlen < _MIN_BITS:
                continue
            sym = decode.get((curlen, cur))
            if sym is not None:
                if sym == 256:
                    raise HpackError("EOS symbol in huffman data")
                out.append(sym)
                cur = 0
                curlen = 0
            elif curlen > 30:
                raise HpackError("invalid huffman code")
    # remaining bits must be a prefix of EOS (all ones), < 8 bits
    if curlen >= 8 or cur != (1 << curlen) - 1:
        raise HpackError("invalid huffman padding")
    return bytes(out)


# ------------------------------------------------------------ integer coding
def encode_int(value: int, prefix_bits: int, flags: int = 0) -> bytearray:
    """RFC 7541 §5.1 — N-bit prefix integer, high bits carry flags."""
    limit = (1 << prefix_bits) - 1
    out = bytearray()
    if value < limit:
        out.append(flags | value)
        return out
    out.append(flags | limit)
    value -= limit
    while value >= 128:
        out.append((value & 0x7F) | 0x80)
        value >>= 7
    out.append(value)
    return out


def decode_int(data: bytes, pos: int, prefix_bits: int) -> Tuple[int, int]:
    if pos >= len(data):
        raise HpackError("truncated integer")
    limit = (1 << prefix_bits) - 1
    value = data[pos] & limit
    pos += 1
    if value < limit:
        return value, pos
    shift = 0
    while True:
        if pos >= len(data):
            raise HpackError("truncated integer continuation")
        b = data[pos]
        pos += 1
        value += (b & 0x7F) << shift
        shift += 7
        if not b & 0x80:
            return value, pos
        if shift > 35:
            raise HpackError("integer overflow")


def _encode_string(s: str, huffman: bool = True) -> bytes:
    raw = s.encode("utf-8") if isinstance(s, str) else s
    if huffman:
        enc = huffman_encode(raw)
        if len(enc) < len(raw):
            return bytes(encode_int(len(enc), 7, 0x80)) + enc
    return bytes(encode_int(len(raw), 7, 0x00)) + raw


def _decode_string(data: bytes, pos: int) -> Tuple[str, int]:
    if pos >= len(data):
        raise HpackError("truncated string")
    huff = bool(data[pos] & 0x80)
    length, pos = decode_int(data, pos, 7)
    if pos + length > len(data):
        raise HpackError("truncated string payload")
    raw = data[pos:pos + length]
    pos += length
    if huff:
        raw = huffman_decode(raw)
    return raw.decode("utf-8", "replace"), pos


# ------------------------------------------------------------- dynamic table
class _DynamicTable:
    """FIFO of (name, value); size-bounded per RFC 7541 §4 (32-byte overhead
    per entry). Index 1 = most recently inserted."""

    def __init__(self, max_size: int = 4096):
        self.entries: List[Tuple[str, str]] = []
        self.size = 0
        self.max_size = max_size

    @staticmethod
    def entry_size(name: str, value: str) -> int:
        return len(name.encode()) + len(value.encode()) + 32

    def add(self, name: str, value: str) -> None:
        need = self.entry_size(name, value)
        self._evict_to(self.max_size - need)
        if need <= self.max_size:
            self.entries.insert(0, (name, value))
            self.size += need
        # an entry larger than the table empties it (already evicted)

    def resize(self, new_max: int) -> None:
        self.max_size = new_max
        self._evict_to(new_max)

    def _evict_to(self, budget: int) -> None:
        while self.entries and self.size > max(budget, 0):
            n, v = self.entries.pop()
            self.size -= self.entry_size(n, v)

    def get(self, index: int) -> Tuple[str, str]:
        """index is 1-based within the dynamic table."""
        if 1 <= index <= len(self.entries):
            return self.entries[index - 1]
        raise HpackError(f"dynamic table index {index} out of range")

    def find(self, name: str, value: str) -> Tuple[int, int]:
        """-> (full_match_index, name_match_index) 1-based, 0 = none."""
        full = name_only = 0
        for i, (n, v) in enumerate(self.entries):
            if n == name:
                if v == value and not full:
                    full = i + 1
                if not name_only:
                    name_only = i + 1
            if full:
                break
        return full, name_only


class HpackEncoder:
    def __init__(self, max_table_size: int = 4096):
        self.table = _DynamicTable(max_table_size)

    def encode(self, headers: List[Tuple[str, str]]) -> bytes:
        out = bytearray()
        for name, value in headers:
            name = name.lower()
            out += self._encode_one(name, value)
        return bytes(out)

    def _encode_one(self, name: str, value: str) -> bytearray:
        static_full = _STATIC_FULL.get((name, value), 0)
        if static_full:
            return encode_int(static_full, 7, 0x80)  # indexed
        dyn_full, dyn_name = self.table.find(name, value)
        if dyn_full:
            return encode_int(STATIC_COUNT + dyn_full, 7, 0x80)
        # literal with incremental indexing (0x40), name indexed if possible
        name_idx = _STATIC_NAME.get(name, 0) or (
            STATIC_COUNT + dyn_name if dyn_name else 0)
        out = encode_int(name_idx, 6, 0x40)
        if not name_idx:
            out += _encode_string(name)
        out += _encode_string(value)
        self.table.add(name, value)
        return out


class HpackDecoder:
    def __init__(self, max_table_size: int = 4096):
        self.table = _DynamicTable(max_table_size)
        # RFC 7541 §4.2: the peer may shrink/restore the table but never
        # grow it past the size we advertised (our default: 4096) — an
        # uncapped resize lets one connection grow memory without bound
        self._advertised_max = max_table_size

    def _lookup(self, index: int) -> Tuple[str, str]:
        if index == 0:
            raise HpackError("index 0")
        if index <= STATIC_COUNT:
            return STATIC_TABLE[index - 1]
        return self.table.get(index - STATIC_COUNT)

    def decode(self, data: bytes) -> List[Tuple[str, str]]:
        headers: List[Tuple[str, str]] = []
        pos = 0
        while pos < len(data):
            b = data[pos]
            if b & 0x80:  # indexed field
                index, pos = decode_int(data, pos, 7)
                headers.append(self._lookup(index))
            elif b & 0x40:  # literal, incremental indexing
                index, pos = decode_int(data, pos, 6)
                name = self._lookup(index)[0] if index else None
                if name is None:
                    name, pos = _decode_string(data, pos)
                value, pos = _decode_string(data, pos)
                self.table.add(name, value)
                headers.append((name, value))
            elif b & 0x20:  # dynamic table size update
                new_size, pos = decode_int(data, pos, 5)
                if new_size > self._advertised_max:
                    raise HpackError(
                        f"table size update {new_size} exceeds advertised "
                        f"maximum {self._advertised_max}")
                self.table.resize(new_size)
            else:  # literal without indexing (0x00) / never indexed (0x10)
                index, pos = decode_int(data, pos, 4)
                name = self._lookup(index)[0] if index else None
                if name is None:
                    name, pos = _decode_string(data, pos)
                value, pos = _decode_string(data, pos)
                headers.append((name, value))
        return headers
