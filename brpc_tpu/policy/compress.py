"""Compression handlers (reference global.cpp:395-404 gzip/zlib/snappy).

snappy has no stdlib codec and deps are frozen, so the registry carries
gzip/zlib (stdlib) and is open for registration like the reference's.
"""

from __future__ import annotations

import gzip as _gzip
import zlib as _zlib
from typing import Callable, Dict, Tuple

COMPRESS_NONE = 0
COMPRESS_GZIP = 1
COMPRESS_ZLIB = 2

_handlers: Dict[int, Tuple[Callable[[bytes], bytes], Callable[[bytes], bytes]]] = {
    COMPRESS_GZIP: (_gzip.compress, _gzip.decompress),
    COMPRESS_ZLIB: (_zlib.compress, _zlib.decompress),
}


def register_compression(ctype: int, compress_fn, decompress_fn) -> None:
    _handlers[ctype] = (compress_fn, decompress_fn)


def compress(data: bytes, ctype: int) -> bytes:
    if ctype == COMPRESS_NONE:
        return data
    try:
        return _handlers[ctype][0](data)
    except KeyError:
        raise ValueError(f"unknown compress type {ctype}")


def decompress(data: bytes, ctype: int) -> bytes:
    if ctype == COMPRESS_NONE:
        return data
    try:
        return _handlers[ctype][1](data)
    except KeyError:
        raise ValueError(f"unknown compress type {ctype}")
