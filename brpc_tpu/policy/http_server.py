"""Server-side HTTP request processing.

The HTTP analog of ``rpc/server_processing.py`` (reference
``policy/http_rpc_protocol.cpp`` ProcessHttpRequest): route builtin
observability paths to ``brpc_tpu.builtin`` handlers, and ``/Service/Method``
paths to registered pb services — JSON bodies through json2pb, binary pb
bodies straight through. Admission (server concurrency, method limiters,
auth) and per-method stats flow through the same MethodEntry hooks as the
binary protocol, so /status numbers are protocol-agnostic.
"""

from __future__ import annotations

import time

from brpc_tpu import json2pb
from brpc_tpu.policy import compress as _compress
from brpc_tpu.policy.http_protocol import (
    CONTENT_JSON,
    CONTENT_PROTO,
    CONTENT_TEXT,
    H_ATTACHMENT,
    H_AUTH,
    H_CID,
    H_COMPRESS,
    H_ERROR_CODE,
    H_ERROR_TEXT,
    H_LOG_ID,
    _ERR_TO_STATUS,
    HttpMessage,
    render_response,
)
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.controller import Controller


def _reply(sock, http: HttpMessage, status: int, content_type: str, body,
           extra=None) -> None:
    keep = http.keep_alive()
    sock.write(render_response(status, content_type, body,
                               extra_headers=extra, keep_alive=keep))
    sock.out_messages += 1
    if not keep:
        sock.close()


def _rpc_error_reply(sock, http: HttpMessage, code: int, text: str,
                     as_json: bool) -> None:
    status = _ERR_TO_STATUS.get(code, 500)
    extra = {H_ERROR_CODE: str(code),
             H_ERROR_TEXT: text.replace("\r", " ").replace("\n", " ")}
    cid = http.header(H_CID)
    if cid:
        extra[H_CID] = cid
    if as_json:
        import json

        body = json.dumps({"error_code": code, "error_text": text})
        _reply(sock, http, status, CONTENT_JSON, body, extra)
    else:
        _reply(sock, http, status, CONTENT_TEXT, text, extra)


def process_http_request(msg, server) -> None:
    http: HttpMessage = msg.meta
    sock = msg.socket
    if server is None:
        return  # HTTP request on a client-only connection
    server.requests_processed.put(1)

    # ------------------------------------------------------ builtin services
    from brpc_tpu import builtin

    try:
        handled = builtin.dispatch(server, http)
    except Exception as e:
        # a broken handler must still answer — a swallowed exception would
        # leave the client hanging until its timeout
        return _reply(sock, http, 500, CONTENT_TEXT,
                      f"builtin service failed: {e}\n")
    if handled is not None:
        status, ctype, body, extra = handled
        return _reply(sock, http, status, ctype, body, extra)

    # ------------------------------------------------------------- RPC path
    parts = [p for p in http.path.split("/") if p]
    as_json = http.content_type != CONTENT_PROTO
    if len(parts) != 2:
        return _rpc_error_reply(sock, http, errors.ENOSERVICE,
                                f"no such path {http.path!r}", as_json)
    service_name, method_name = parts

    # synthesized request meta so server Controllers look protocol-uniform;
    # created before admission so rejections reach /rpcz like the binary path
    from brpc_tpu.proto import rpc_meta_pb2
    from brpc_tpu.trace import span as _span_mod

    meta = rpc_meta_pb2.RpcMeta()
    meta.request.service_name = service_name
    meta.request.method_name = method_name
    try:
        meta.request.log_id = int(http.header(H_LOG_ID, "0") or "0")
    except ValueError:
        pass
    cntl = Controller.server_controller(server, sock, meta)
    cntl.http_request = http
    cntl.span = _span_mod.start_server_span(
        meta, service_name, method_name, peer=str(sock.remote))

    def reject(code: int, text: str) -> None:
        if cntl.span is not None:
            cntl.span.end(code)
        _rpc_error_reply(sock, http, code, text, as_json)

    if not server.is_running:
        return reject(errors.ELOGOFF, errors.error_text(errors.ELOGOFF))
    if not server.add_concurrency():
        return reject(errors.ELIMIT, "server max_concurrency reached")
    start_us = time.perf_counter_ns() // 1000

    err = None
    entry = None
    auth_ctx = None
    try:
        if server.options.auth is not None:
            auth_ctx = server.options.auth.verify_credential(
                http.header(H_AUTH), sock.remote)
        if server.options.auth is not None and auth_ctx is None:
            err = (errors.EAUTH, errors.error_text(errors.EAUTH))
        else:
            cntl.auth_context = auth_ctx
            # global hook, HTTP RPC lane — after auth like the binary lane
            # (process_rpc_request), so cntl.auth_context is populated
            if err is None and server.options.interceptor is not None:
                from brpc_tpu.rpc.server_processing import run_interceptor

                err = run_interceptor(server, cntl)
        if err is None:
            service = server.find_service(service_name)
            if service is None:
                err = (errors.ENOSERVICE, f"no service {service_name!r}")
            else:
                entry = service.find_method(method_name)
                if entry is None:
                    err = (errors.ENOMETHOD, f"no method {method_name!r}")
                elif not entry.on_request():
                    entry = None
                    err = (errors.ELIMIT, "method concurrency limit")
    except BaseException:
        server.sub_concurrency()
        raise
    if entry is None:
        server.sub_concurrency()
        return reject(*err)

    settled = [False]

    def _settle(error_code: int) -> None:
        if settled[0]:
            return
        settled[0] = True
        entry.on_response(time.perf_counter_ns() // 1000 - start_us,
                          error_code)
        server.sub_concurrency()
        if cntl.span is not None:
            cntl.span.end(error_code)

    responded = [False]

    def done(response=None) -> None:
        if responded[0]:
            return
        responded[0] = True
        if cntl.failed():
            _rpc_error_reply(sock, http, cntl.error_code, cntl.error_text(),
                             as_json)
            return _settle(cntl.error_code)
        pa = getattr(cntl, "_progressive", None)
        if pa is not None:
            # streamed body (reference progressive_attachment.cpp): chunked
            # headers now, chunks from the attachment — the pb response is
            # NOT serialized into the body. HTTP/1.0 peers don't understand
            # chunked framing at all — reject rather than corrupt
            from brpc_tpu.rpc.progressive import render_chunked_headers

            if http.version == "HTTP/1.0":
                pa._abort()  # pump threads must see ESTREAMCLOSED, not
                #              buffer into a response that never starts
                _rpc_error_reply(sock, http, errors.EREQUEST,
                                 "progressive responses need HTTP/1.1",
                                 as_json)
                return _settle(errors.EREQUEST)
            keep = http.keep_alive()
            ctype = http.header("accept") or "application/octet-stream"
            if "," in ctype or ctype == "*/*":
                ctype = "application/octet-stream"
            sock.write(render_chunked_headers(200, ctype, keep_alive=keep))
            sock.out_messages += 1
            # pa closes the socket after the terminator when keep is False
            pa._start(sock, keep_alive=keep)
            return _settle(errors.OK)
        extra = {}
        cid = http.header(H_CID)
        if cid:
            extra[H_CID] = cid
        try:
            if as_json:
                body = json2pb.pb_to_json(response) if response is not None else ""
                ctype = CONTENT_JSON
            else:
                payload = (response.SerializeToString()
                           if response is not None else b"")
                compress_type = cntl.compress_type
                payload = _compress.compress(payload, compress_type)
                if compress_type:
                    extra[H_COMPRESS] = str(compress_type)
                att = cntl.response_attachment or b""
                if att:
                    extra[H_ATTACHMENT] = str(len(att))
                body = payload + att
                ctype = CONTENT_PROTO
        except Exception as e:
            _rpc_error_reply(sock, http, errors.ERESPONSE,
                             f"serialize response: {e}", as_json)
            return _settle(errors.ERESPONSE)
        _reply(sock, http, 200, ctype, body, extra)
        _settle(errors.OK)

    try:
        t_parse = time.perf_counter_ns()
        try:
            if as_json:
                request = json2pb.json_to_pb(http.body, entry.request_class)
            else:
                compress_type = int(http.header(H_COMPRESS, "0") or "0")
                att_size = int(http.header(H_ATTACHMENT, "0") or "0")
                raw = http.body[:-att_size] if att_size else http.body
                cntl.request_attachment = (
                    http.body[-att_size:] if att_size else b"")
                request = entry.request_class()
                request.ParseFromString(
                    _compress.decompress(raw, compress_type))
        except Exception as e:
            cntl.set_failed(errors.EREQUEST, f"parse request: {e}")
            return done()
        if cntl.span is not None:
            cntl.span.request_size = len(http.body)
            cntl.span.add_phase(
                "parse_us", (time.perf_counter_ns() - t_parse) / 1000.0)

        from brpc_tpu.trace import span as _span

        prev_span = _span.set_current(cntl.span)
        t_exec = time.perf_counter_ns()
        try:
            ret = entry.fn(cntl, request, done)
        except Exception as e:
            cntl.set_failed(errors.EINTERNAL, f"method raised: {e}")
            ret = None
        finally:
            _span.set_current(prev_span)
            if cntl.span is not None:
                cntl.span.add_phase(
                    "execute_us",
                    (time.perf_counter_ns() - t_exec) / 1000.0)
        if not responded[0] and (ret is not None or cntl.failed()):
            done(ret)
    except BaseException:
        _settle(errors.EINTERNAL)
        raise
