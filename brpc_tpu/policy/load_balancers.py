"""Load balancers (reference load_balancer.h:35-100 + policy/*_load_balancer
.cpp, registered at global.cpp:384-392).

Carried-over design: the server list lives in DoublyBufferedData so selection
never locks against membership changes; ``feedback`` closes the loop for the
locality-aware balancer (latency EWMA) and the failure tracker (consecutive
errors park a node until its next probe — the health-check half lives in
rpc/health_check.py).

Names: rr, random, wrr, wr (weighted-random), la (locality-aware),
c_hash (consistent hashing).
"""

from __future__ import annotations

import bisect
import hashlib
import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from brpc_tpu.butil.doubly_buffered import DoublyBufferedData
from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.butil.misc import fast_rand_less_than
from brpc_tpu.rpc import errors


@dataclass
class ServerNode:
    endpoint: EndPoint
    weight: int = 1
    tag: str = ""

    def __hash__(self):
        return hash((self.endpoint, self.tag))


class _NodeState:
    """Per-node feedback state: latency EWMA, failure streak, and the EMA
    circuit breaker (rpc/circuit_breaker.py) for error-rate isolation."""

    __slots__ = ("latency_ewma_us", "fail_streak", "down_until", "breaker",
                 "inflight")

    def __init__(self):
        from brpc_tpu.rpc.circuit_breaker import CircuitBreaker

        self.latency_ewma_us = 1000.0
        self.fail_streak = 0
        self.down_until = 0.0
        self.breaker = CircuitBreaker()
        # calls selected but not yet fed back (la punishes queueing: the
        # reference charges in-flight requests their expected latency,
        # locality_aware_load_balancer.cpp)
        self.inflight = 0

    def on_feedback(self, error_code: int, latency_us: float,
                    isolation_s: float = 2.0) -> None:
        self.breaker.on_call_end(error_code, latency_us)
        if self.inflight > 0:
            self.inflight -= 1
        if error_code == errors.OK:
            self.fail_streak = 0
            self.latency_ewma_us += 0.2 * (latency_us - self.latency_ewma_us)
        else:
            self.fail_streak += 1
            if self.fail_streak >= 3:
                # park the node; naming refresh / health check revives it
                self.down_until = time.monotonic() + isolation_s

    @property
    def is_down(self) -> bool:
        if time.monotonic() < self.down_until:
            return True
        from brpc_tpu import flags as _flags

        return (self.breaker.isolated
                and _flags.get("circuit_breaker_enabled"))


class LoadBalancer:
    name = "base"

    def __init__(self):
        self._servers: DoublyBufferedData[List[ServerNode]] = (
            DoublyBufferedData(list))
        self._state: Dict[EndPoint, _NodeState] = {}
        self._state_lock = threading.Lock()
        # cluster-recover policy (policy/cluster_recover.py); set via the
        # LB spec string ("rr:min_working_instances=3 hold_seconds=2")
        self.recover_policy = None
        self._usable_cache = (0.0, 0)  # (expires_monotonic, count)

    # ---------------------------------------------------------- membership
    def reset_servers(self, nodes: List[ServerNode]) -> None:
        nodes = list(nodes)

        def apply(lst):
            lst.clear()
            lst.extend(nodes)

        self._servers.modify(apply)
        with self._state_lock:
            for n in nodes:
                self._state.setdefault(n.endpoint, _NodeState())

    def add_server(self, node: ServerNode) -> None:
        self._servers.modify(lambda lst: lst.append(node))
        with self._state_lock:
            self._state.setdefault(node.endpoint, _NodeState())

    def remove_server(self, endpoint: EndPoint) -> None:
        def apply(lst):
            lst[:] = [n for n in lst if n.endpoint != endpoint]

        self._servers.modify(apply)

    def server_count(self) -> int:
        with self._servers.read() as lst:
            return len(lst)

    def usable_count(self) -> int:
        """Instances not parked by feedback/breaker (cluster-recover input).
        Cached ~10ms: it sits on the per-request path while recovering
        (the reference caches for detect_available_server_interval_ms,
        cluster_recover_policy.cpp GetUsableServerCount)."""
        now = time.monotonic()
        expires, count = self._usable_cache
        if now < expires:
            return count
        with self._servers.read() as lst:
            count = sum(1 for n in lst
                        if not self._node_state(n.endpoint).is_down)
        self._usable_cache = (now + 0.01, count)
        return count

    # ------------------------------------------------------------ feedback
    def feedback(self, endpoint: EndPoint, error_code: int,
                 latency_us: float) -> None:
        with self._state_lock:
            st = self._state.get(endpoint)
        if st is not None:
            st.on_feedback(error_code, latency_us)

    def _node_state(self, ep: EndPoint) -> _NodeState:
        with self._state_lock:
            return self._state.setdefault(ep, _NodeState())

    def _alive(self, nodes: List[ServerNode]) -> List[ServerNode]:
        alive = [n for n in nodes if not self._node_state(n.endpoint).is_down]
        if alive:
            return alive
        if self.recover_policy is not None and nodes:
            # selection exhausted every candidate — the cluster is down;
            # arm de-thundered recovery (the reference arms whenever
            # selection exhausts, round_robin_load_balancer.cpp:128-132)
            self.recover_policy.start_recover()
        return list(nodes)  # all parked -> try anyway

    # ------------------------------------------------------------- select
    def select_server(self, cntl=None) -> Optional[EndPoint]:
        raise NotImplementedError


class RoundRobinLB(LoadBalancer):
    name = "rr"

    def __init__(self):
        super().__init__()
        self._counter = itertools.count()

    def select_server(self, cntl=None) -> Optional[EndPoint]:
        with self._servers.read() as lst:
            nodes = self._alive(lst)
            if not nodes:
                return None
            return nodes[next(self._counter) % len(nodes)].endpoint


class RandomLB(LoadBalancer):
    name = "random"

    def select_server(self, cntl=None) -> Optional[EndPoint]:
        with self._servers.read() as lst:
            nodes = self._alive(lst)
            if not nodes:
                return None
            return nodes[fast_rand_less_than(len(nodes))].endpoint


class WeightedRoundRobinLB(LoadBalancer):
    name = "wrr"

    def __init__(self):
        super().__init__()
        self._lock = threading.Lock()
        self._current: Dict[EndPoint, float] = {}

    def select_server(self, cntl=None) -> Optional[EndPoint]:
        # smooth weighted rr (nginx-style): current += weight; pick max;
        # picked -= total
        with self._servers.read() as lst:
            nodes = self._alive(lst)
            if not nodes:
                return None
            with self._lock:
                total = 0
                best, best_cur = None, float("-inf")
                for n in nodes:
                    w = max(1, n.weight)
                    total += w
                    cur = self._current.get(n.endpoint, 0.0) + w
                    self._current[n.endpoint] = cur
                    if cur > best_cur:
                        best, best_cur = n, cur
                self._current[best.endpoint] -= total
                return best.endpoint


class WeightedRandomLB(LoadBalancer):
    name = "wr"

    def select_server(self, cntl=None) -> Optional[EndPoint]:
        with self._servers.read() as lst:
            nodes = self._alive(lst)
            if not nodes:
                return None
            total = sum(max(1, n.weight) for n in nodes)
            pick = fast_rand_less_than(total)
            acc = 0
            for n in nodes:
                acc += max(1, n.weight)
                if pick < acc:
                    return n.endpoint
            return nodes[-1].endpoint


class LocalityAwareLB(LoadBalancer):
    """Latency-feedback balancer (policy/locality_aware_load_balancer.cpp):
    a node's share ~ weight / (EWMA latency x (1 + in-flight)). The
    in-flight term is the reference's queueing punishment: every selected-
    but-unanswered call charges the node its expected latency again, so a
    stalling replica sheds load IMMEDIATELY (before any response confirms
    the stall), and traffic returns as feedback lands. The reference's
    divide-tree makes the weighted pick O(log n) at its 10k-server scale;
    cluster sizes here make the O(n) prefix walk the simpler win (the
    server list already lives in DoublyBufferedData for lock-free reads)."""

    name = "la"

    # in-flight charges are repaid by feedback, but selections that never
    # complete (retry re-picks, recovery shedding, connect failures) would
    # leak theirs forever — a periodic half-life decay forgives stale
    # charges so a once-punished node always earns its way back
    _DECAY_S = 0.5

    def __init__(self):
        super().__init__()
        self._last_decay = time.monotonic()

    def _decay_inflight(self) -> None:
        now = time.monotonic()
        if now - self._last_decay < self._DECAY_S:
            return
        self._last_decay = now
        with self._state_lock:
            for st in self._state.values():
                if st.inflight > 0:
                    st.inflight //= 2

    def select_server(self, cntl=None) -> Optional[EndPoint]:
        self._decay_inflight()
        with self._servers.read() as lst:
            nodes = self._alive(lst)
            if not nodes:
                return None
            states = [self._node_state(n.endpoint) for n in nodes]
            inv = [
                max(1, n.weight)
                / (max(1.0, st.latency_ewma_us) * (1 + max(0, st.inflight)))
                for n, st in zip(nodes, states)
            ]
            total = sum(inv)
            # weighted-random draw over punished inverse latencies
            r = (fast_rand_less_than(1 << 30) / float(1 << 30)) * total
            acc = 0.0
            chosen = nodes[-1]
            chosen_st = states[-1]
            for n, st, w in zip(nodes, states, inv):
                acc += w
                if r < acc:
                    chosen, chosen_st = n, st
                    break
            chosen_st.inflight += 1  # repaid by the call's feedback
            return chosen.endpoint


class ConsistentHashingLB(LoadBalancer):
    """Ketama-style ring (policy/consistent_hashing_load_balancer.cpp).
    The request code (cntl.log_id by default) picks the ring position, so
    one key consistently lands on one server, with minimal movement on
    membership change."""

    name = "c_hash"
    VIRTUAL_NODES = 64

    def __init__(self):
        super().__init__()
        self._ring_lock = threading.Lock()
        self._ring: List[int] = []
        self._ring_eps: List[EndPoint] = []

    def reset_servers(self, nodes: List[ServerNode]) -> None:
        super().reset_servers(nodes)
        ring = []
        for n in nodes:
            for v in range(self.VIRTUAL_NODES * max(1, n.weight)):
                h = int.from_bytes(
                    hashlib.md5(f"{n.endpoint}#{v}".encode()).digest()[:8],
                    "big")
                ring.append((h, n.endpoint))
        ring.sort(key=lambda he: he[0])
        with self._ring_lock:
            self._ring = [h for h, _ in ring]
            self._ring_eps = [e for _, e in ring]

    def select_server(self, cntl=None) -> Optional[EndPoint]:
        code = getattr(cntl, "log_id", 0) if cntl is not None else 0
        h = int.from_bytes(
            hashlib.md5(str(code).encode()).digest()[:8], "big")
        with self._ring_lock:
            if not self._ring:
                return None
            idx = bisect.bisect(self._ring, h) % len(self._ring)
            return self._ring_eps[idx]


_registry: Dict[str, Callable[[], LoadBalancer]] = {
    "rr": RoundRobinLB,
    "random": RandomLB,
    "wrr": WeightedRoundRobinLB,
    "wr": WeightedRandomLB,
    "la": LocalityAwareLB,
    "c_hash": ConsistentHashingLB,
}


def register_load_balancer(name: str, factory: Callable[[], LoadBalancer]) -> None:
    _registry[name] = factory


def create_load_balancer(name: str) -> LoadBalancer:
    """``name`` or ``name:params``. Params currently configure the
    cluster-recover policy (reference LB spec strings, e.g.
    ``"rr:min_working_instances=3 hold_seconds=2"``)."""
    base, _, params = name.partition(":")
    try:
        lb = _registry[base]()
    except KeyError:
        raise ValueError(f"unknown load balancer {base!r}; "
                         f"have {sorted(_registry)}")
    if params:
        from brpc_tpu.policy.cluster_recover import parse_recover_params

        lb.recover_policy = parse_recover_params(params)
    return lb
