"""Retry policy — user-customizable "should this error retry?".

Rebuild of the reference's ``retry_policy.h`` (RetryPolicy::DoRetry) and
``backup_request_policy.h``: the Controller consults the channel's policy on
every error-channel event; the default retries connection-level failures
only (reference DefaultRetryPolicy — application errors and timeouts do
not retry, a timeout means the deadline budget is already spent).
"""

from __future__ import annotations

from brpc_tpu.rpc import errors


class RetryPolicy:
    def do_retry(self, controller) -> bool:
        """Called with the failed controller (error_code set, call-id lock
        held). True -> re-issue on a (possibly different) server."""
        raise NotImplementedError


class DefaultRetryPolicy(RetryPolicy):
    def do_retry(self, controller) -> bool:
        return controller.error_code in errors.DEFAULT_RETRYABLE


class RetryOnCodes(RetryPolicy):
    """Retry on an explicit set of codes (plus the connection-level set)."""

    def __init__(self, codes, include_default: bool = True):
        self.codes = frozenset(codes) | (
            errors.DEFAULT_RETRYABLE if include_default else frozenset())

    def do_retry(self, controller) -> bool:
        return controller.error_code in self.codes


class TunnelRetryPolicy(RetryOnCodes):
    """Retry posture for tpu:// tunnel clients.

    On top of the connection-level set (which a tunnel kill maps onto via
    the transport's retriable-code fanout), also retries EOVERCROWDED:
    during a heal the rebuilt window starts empty, so the first calls can
    race a still-wedged credit ledger — re-issuing lands them on the fresh
    epoch instead of surfacing a transient overload."""

    def __init__(self, include_default: bool = True):
        super().__init__({errors.EOVERCROWDED}, include_default)


class BackupRequestPolicy:
    """Decides whether a backup (hedged) request fires for this call
    (reference backup_request_policy.h)."""

    def do_backup(self, controller) -> bool:
        return True


_default = DefaultRetryPolicy()


def default_retry_policy() -> RetryPolicy:
    return _default
