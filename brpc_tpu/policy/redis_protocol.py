"""Redis protocol (RESP) — pipelined client + server-side RedisService.

Counterpart of the reference's ``policy/redis_protocol.cpp`` +
``redis_command.cpp`` + ``redis.h`` (RedisRequest/RedisResponse/RedisReply)
and the server half that lets a Server answer redis-cli directly
(``ServerOptions.redis_service``).

Client model (same as the reference): one RPC = N pipelined commands = N
replies, strictly ordered on the connection. Correlation is positional — a
per-socket FIFO of (call id, expected reply count) — so timeouts/retries
rely on the engine's stale-attempt rejection while later replies keep
popping in order. Server model: commands dispatch to a ``RedisService``'s
command handlers through a per-connection ExecutionQueue (responses must be
emitted in arrival order even when handlers run in fibers).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.fiber import runtime
from brpc_tpu.proto import rpc_meta_pb2
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.protocol import (
    PARSE_BAD,
    PARSE_NOT_ENOUGH_DATA,
    PARSE_TRY_OTHERS,
    ParsedMessage,
    Protocol,
    dispatch_response,
    init_socket_state,
)

CRLF = b"\r\n"

# ---------------------------------------------------------------- RESP codec
REPLY_STRING = 1    # + simple string
REPLY_ERROR = 2     # - error
REPLY_INTEGER = 3   # : integer
REPLY_BULK = 4      # $ bulk string (None = nil)
REPLY_ARRAY = 5     # * array (None = nil array)


class RedisReply:
    __slots__ = ("type", "value")

    def __init__(self, type_: int, value):
        self.type = type_
        self.value = value

    def is_nil(self) -> bool:
        return self.value is None

    def is_error(self) -> bool:
        return self.type == REPLY_ERROR

    def __repr__(self) -> str:
        return f"RedisReply({self.type}, {self.value!r})"

    def __eq__(self, other) -> bool:
        if isinstance(other, RedisReply):
            return self.type == other.type and self.value == other.value
        return self.value == other


def pack_command(*args) -> bytes:
    """One command -> RESP array of bulk strings."""
    out = [b"*%d\r\n" % len(args)]
    for a in args:
        if isinstance(a, str):
            a = a.encode()
        elif isinstance(a, (int, float)):
            a = str(a).encode()
        out.append(b"$%d\r\n%s\r\n" % (len(a), a))
    return b"".join(out)


def pack_reply(reply: RedisReply) -> bytes:
    """Serialize one reply (server side)."""
    t, v = reply.type, reply.value
    if t == REPLY_STRING:
        return b"+%s\r\n" % (v.encode() if isinstance(v, str) else v)
    if t == REPLY_ERROR:
        return b"-%s\r\n" % (v.encode() if isinstance(v, str) else v)
    if t == REPLY_INTEGER:
        return b":%d\r\n" % v
    if t == REPLY_BULK:
        if v is None:
            return b"$-1\r\n"
        if isinstance(v, str):
            v = v.encode()
        return b"$%d\r\n%s\r\n" % (len(v), v)
    if t == REPLY_ARRAY:
        if v is None:
            return b"*-1\r\n"
        return b"*%d\r\n" % len(v) + b"".join(pack_reply(r) for r in v)
    raise ValueError(f"bad reply type {t}")


def parse_reply(data: bytes, pos: int) -> Tuple[Optional[RedisReply], int]:
    """Parse one reply at pos. Returns (reply, new_pos); (None, pos) when
    incomplete. Raises ValueError on malformed bytes."""
    if pos >= len(data):
        return None, pos
    marker = data[pos:pos + 1]
    nl = data.find(CRLF, pos + 1)
    if nl < 0:
        return None, pos
    line = data[pos + 1:nl]
    after = nl + 2
    if marker == b"+":
        return RedisReply(REPLY_STRING, line.decode("utf-8", "replace")), after
    if marker == b"-":
        return RedisReply(REPLY_ERROR, line.decode("utf-8", "replace")), after
    if marker == b":":
        return RedisReply(REPLY_INTEGER, int(line)), after
    if marker == b"$":
        n = int(line)
        if n < 0:
            return RedisReply(REPLY_BULK, None), after
        if len(data) < after + n + 2:
            return None, pos
        return RedisReply(REPLY_BULK, bytes(data[after:after + n])), after + n + 2
    if marker == b"*":
        n = int(line)
        if n < 0:
            return RedisReply(REPLY_ARRAY, None), after
        items = []
        p = after
        for _ in range(n):
            item, p2 = parse_reply(data, p)
            if item is None:
                return None, pos
            items.append(item)
            p = p2
        return RedisReply(REPLY_ARRAY, items), p
    raise ValueError(f"bad RESP marker {marker!r}")


def first_needed(window: bytes, pos: int = 0) -> Optional[int]:
    """Minimum ABSOLUTE length the buffer must reach for the reply at pos
    to be complete, derived from the prefix alone — or None when the prefix
    itself is still too short to tell. Lets the parse paths skip flattening
    a large buffer whose (bulk-heavy) head reply is known-incomplete."""
    if pos >= len(window):
        return None
    marker = window[pos:pos + 1]
    nl = window.find(CRLF, pos + 1)
    if nl < 0:
        return None
    after = nl + 2
    if marker in (b"+", b"-", b":"):
        return after
    try:
        n = int(window[pos + 1:nl])
    except ValueError:
        return after  # malformed: let the real parser report it
    if marker == b"$":
        return after if n < 0 else after + n + 2
    if marker == b"*":
        p = after
        for _ in range(max(n, 0)):
            need = first_needed(window, p)
            if need is None or need > len(window):
                return need  # element extends past the window
            p = need
        return p
    return after


# ------------------------------------------------------- request / response
class RedisRequest:
    """Pipelined command batch; duck-types the pb message surface so it
    rides the normal Channel.call_method path."""

    def __init__(self):
        self._commands: List[bytes] = []

    def add_command(self, *args) -> "RedisRequest":
        if not args:
            raise ValueError("empty redis command")
        self._commands.append(pack_command(*args))
        return self

    @property
    def command_count(self) -> int:
        return len(self._commands)

    def clear(self) -> None:
        self._commands.clear()

    def SerializeToString(self) -> bytes:
        return b"".join(self._commands)

    def ParseFromString(self, data: bytes) -> None:  # for rpc_replay
        self._commands = [bytes(data)] if data else []


class RedisResponse:
    def __init__(self):
        self._replies: List[RedisReply] = []

    def reply(self, i: int) -> RedisReply:
        return self._replies[i]

    @property
    def reply_size(self) -> int:
        return len(self._replies)

    def ParseFromString(self, data: bytes) -> None:
        self._replies = []
        pos = 0
        while pos < len(data):
            r, pos2 = parse_reply(data, pos)
            if r is None:
                break
            self._replies.append(r)
            pos = pos2

    def SerializeToString(self) -> bytes:
        return b"".join(pack_reply(r) for r in self._replies)


# the pseudo-method redis calls ride on (service/method never hit the wire)
def redis_method():
    from brpc_tpu.rpc.channel import MethodDescriptor

    return MethodDescriptor("redis", "command", RedisRequest, RedisResponse)


def count_commands(payload: bytes) -> int:
    """Count top-level RESP arrays (= expected replies) in a request blob."""
    n = 0
    pos = 0
    while pos < len(payload):
        r, pos2 = parse_reply(payload, pos)
        if r is None:
            break
        n += 1
        pos = pos2
    return n


# ------------------------------------------------------------ server service
class RedisService:
    """Server half: register command handlers; unknown commands get -ERR.

    handler(args: List[bytes]) -> RedisReply  (args[0] = command name)
    """

    def __init__(self):
        self._handlers: Dict[str, Callable] = {}

    def add_command_handler(self, name: str, handler: Callable) -> "RedisService":
        self._handlers[name.lower()] = handler
        return self

    def handle(self, args: List[bytes]) -> RedisReply:
        if not args or args[0] is None:
            return RedisReply(REPLY_ERROR, "ERR empty command")
        name = args[0].decode("utf-8", "replace").lower()
        if name == "ping" and name not in self._handlers:
            return RedisReply(REPLY_STRING, "PONG")
        h = self._handlers.get(name)
        if h is None:
            return RedisReply(REPLY_ERROR, f"ERR unknown command '{name}'")
        try:
            return h(args)
        except Exception as e:
            return RedisReply(REPLY_ERROR, f"ERR handler failed: {e}")


class _RedisClientState:
    __slots__ = ("fifo", "lock", "acc")

    def __init__(self):
        self.fifo = deque()   # (cid, attempt_version, n_expected)
        self.lock = threading.Lock()
        self.acc: List[bytes] = []  # serialized replies for the FIFO head


class _RedisServerState:
    __slots__ = ("queue",)

    def __init__(self, sock, service):
        def consume(items):
            if items is None:
                return
            out = IOBuf()
            for args in items:
                # one bad command must not drop the whole batch's replies —
                # positional correlation would desync for every client
                try:
                    reply = service.handle(args)
                except Exception as e:
                    reply = RedisReply(REPLY_ERROR, f"ERR {e}")
                try:
                    out.append(pack_reply(reply))
                except Exception:
                    out.append(pack_reply(
                        RedisReply(REPLY_ERROR, "ERR unserializable reply")))
            sock.write(out)

        from brpc_tpu.fiber.execution_queue import ExecutionQueue

        self.queue = ExecutionQueue(consume)


class RedisProtocol(Protocol):
    """RESP on both sides, positional correlation (see module docstring)."""

    name = "redis"
    stateful = True

    # ------------------------------------------------------------- recv path
    def parse(self, buf: IOBuf, sock=None):
        cst: Optional[_RedisClientState] = getattr(sock, "redis_client", None)
        if cst is not None:
            return self._parse_client(buf, sock, cst)
        srv = sock.owner_server
        service = getattr(srv.options, "redis_service", None) if srv else None
        sst: Optional[_RedisServerState] = getattr(sock, "redis_server", None)
        if sst is not None:
            return self._parse_server(buf, sock, sst)
        if service is not None and buf.fetch(1) in (b"*",):
            sst = _RedisServerState(sock, service)
            sock.redis_server = sst
            sock.preferred_protocol = self
            return self._parse_server(buf, sock, sst)
        return PARSE_TRY_OTHERS, None

    @staticmethod
    def _head_incomplete(buf: IOBuf) -> bool:
        """True when the first reply/command provably extends past the
        buffered bytes — skip the full flatten (quadratic on big values)."""
        window = buf.fetch(min(len(buf), 65536))
        need = first_needed(window)
        return need is not None and need > len(buf)

    def _parse_server(self, buf: IOBuf, sock, sst: _RedisServerState):
        if self._head_incomplete(buf):
            return PARSE_NOT_ENOUGH_DATA, None
        data = buf.fetch(len(buf))
        pos = 0
        while pos < len(data):
            try:
                r, pos2 = parse_reply(data, pos)  # commands are RESP arrays
            except (ValueError, IndexError):
                buf.pop_front(pos)
                return PARSE_BAD, None
            if r is None:
                break
            if r.type != REPLY_ARRAY or r.value is None:
                buf.pop_front(pos)
                return PARSE_BAD, None
            args = [item.value if item.type == REPLY_BULK else
                    str(item.value).encode() for item in r.value]
            sock.in_messages += 1
            sst.queue.execute(args)  # ordered per-connection execution
            pos = pos2
        buf.pop_front(pos)
        return PARSE_NOT_ENOUGH_DATA, None

    def _parse_client(self, buf: IOBuf, sock, cst: _RedisClientState):
        if self._head_incomplete(buf):
            return PARSE_NOT_ENOUGH_DATA, None
        data = buf.fetch(len(buf))
        pos = 0
        completed = []  # (cid, ver, reply_bytes)
        with cst.lock:
            while pos < len(data) and cst.fifo:
                cid, ver, need = cst.fifo[0]
                try:
                    r, pos2 = parse_reply(data, pos)
                except (ValueError, IndexError):
                    buf.pop_front(pos)
                    return PARSE_BAD, None
                if r is None:
                    break
                cst.acc.append(data[pos:pos2])
                pos = pos2
                if len(cst.acc) >= need:
                    completed.append((cid, ver, b"".join(cst.acc)))
                    cst.acc = []
                    cst.fifo.popleft()
        buf.pop_front(pos)
        with cst.lock:
            unsolicited = not cst.fifo and pos < len(data)
        if unsolicited:
            # bytes with no outstanding request: protocol confusion — fail
            # the connection rather than buffering forever
            return PARSE_BAD, None
        for cid, ver, body in completed:
            meta = rpc_meta_pb2.RpcMeta()
            meta.correlation_id = cid
            meta.attempt_version = ver
            msg = ParsedMessage(self, meta, IOBuf(body))
            msg.socket = sock
            sock.in_messages += 1
            runtime.start_background(dispatch_response, msg)
        return PARSE_NOT_ENOUGH_DATA, None

    # ------------------------------------------------------------- send path
    def issue_request(self, sock, meta, payload: bytes,
                      attachment: bytes = b"", checksum: bool = False,
                      id_wait=None) -> int:
        cst: _RedisClientState = init_socket_state(
            sock, "redis_client", _RedisClientState, self)
        n = count_commands(payload)
        if n == 0:
            return errors.EREQUEST
        entry = (meta.correlation_id, meta.attempt_version, n)
        with cst.lock:
            # registration and write must be atomic: FIFO order IS the wire
            # order, so a second writer must not slip its bytes in between
            cst.fifo.append(entry)
            rc = sock.write(IOBuf(payload), id_wait=id_wait)
            if rc != 0:
                try:
                    cst.fifo.remove(entry)
                except ValueError:
                    pass
        return rc

    # ------------------------------------------------------ engine contracts
    @staticmethod
    def split_attachment(msg: ParsedMessage) -> Tuple[bytes, bytes]:
        return msg.body.tobytes(), b""

    @staticmethod
    def verify_checksum(meta, payload: bytes) -> bool:
        return True
