"""EndPoint — addressable peer, extended with the ``tpu://`` scheme.

Rebuild of the reference's ``butil/endpoint.h`` (ip:port value type with
parsing; unix-socket extension in ``details/extended_endpoint.hpp``). The TPU
build adds first-class device endpoints: ``tpu://<host>/<device_ordinal>``
names one chip of a mesh, and ``tpu://mesh/<axis-spec>`` names a whole mesh
axis (the target of ParallelChannel/PartitionChannel lowering, SURVEY §5.8).
"""

from __future__ import annotations

import re
import socket as _socket
from dataclasses import dataclass
from typing import Optional, Tuple


class EndPointError(ValueError):
    pass


@dataclass(frozen=True)
class EndPoint:
    """A peer address.

    kind:
      - "ip":   host:port TCP endpoint (the bootstrap/control transport)
      - "unix": unix domain socket path
      - "tpu":  device endpoint — host names the process, ordinal the chip
    """

    kind: str = "ip"
    host: str = ""
    port: int = 0
    path: str = ""          # unix path
    device_ordinal: int = -1  # tpu: which local device
    mesh_axis: str = ""       # tpu: optional axis name for collective targets

    # ------------------------------------------------------------- factories
    @staticmethod
    def from_ip_port(host: str, port: int) -> "EndPoint":
        return EndPoint(kind="ip", host=host, port=int(port))

    @staticmethod
    def from_unix(path: str) -> "EndPoint":
        return EndPoint(kind="unix", path=path)

    @staticmethod
    def from_tpu(host: str, device_ordinal: int, port: int = 0,
                 mesh_axis: str = "") -> "EndPoint":
        return EndPoint(kind="tpu", host=host, port=int(port),
                        device_ordinal=int(device_ordinal), mesh_axis=mesh_axis)

    # --------------------------------------------------------------- parsing
    _HOSTPORT_RE = re.compile(r"^(?P<host>\[[0-9a-fA-F:]+\]|[^:/]+):(?P<port>\d+)$")

    @staticmethod
    def parse(text: str) -> "EndPoint":
        """Parse "host:port", "unix:/path", "tpu://host:port/ordinal"."""
        text = text.strip()
        if text.startswith("unix:"):
            return EndPoint.from_unix(text[len("unix:"):])
        if text.startswith("tpu://"):
            rest = text[len("tpu://"):]
            # tpu://host[:port]/ordinal | tpu://host[:port] (ordinal 0)
            # | tpu://mesh/<axis-name>  (collective target: a whole mesh axis)
            had_slash = "/" in rest
            if had_slash:
                hostpart, _, ordpart = rest.partition("/")
            else:
                hostpart, ordpart = rest, "0"
            if hostpart == "mesh" and had_slash:
                if not ordpart:
                    raise EndPointError(f"missing mesh axis in {text!r}")
                return EndPoint(kind="tpu", host="mesh", mesh_axis=ordpart)
            host, port = EndPoint._split_hostport(hostpart, default_port=0)
            if not host:
                raise EndPointError(f"missing host in tpu endpoint {text!r}")
            try:
                ordinal = int(ordpart)
            except ValueError:
                raise EndPointError(f"bad tpu device ordinal in {text!r}")
            return EndPoint.from_tpu(host, ordinal, port=port)
        host, port = EndPoint._split_hostport(text, default_port=None)
        if port is None:
            raise EndPointError(f"missing port in endpoint {text!r}")
        return EndPoint.from_ip_port(host, port)

    @staticmethod
    def _split_hostport(text: str, default_port) -> Tuple[str, Optional[int]]:
        m = EndPoint._HOSTPORT_RE.match(text)
        if m:
            host = m.group("host")
            if host.startswith("["):
                host = host[1:-1]
            port = int(m.group("port"))
            if port > 65535:
                raise EndPointError(f"port out of range in {text!r}")
            return host, port
        if text.startswith("[") and text.endswith("]"):
            return text[1:-1], default_port  # bare bracketed ipv6
        if ":" in text:
            # has a colon but didn't match host:port -> malformed, never
            # fold junk into the hostname
            raise EndPointError(f"cannot parse endpoint {text!r}")
        return text, default_port

    # ----------------------------------------------------------------- sugar
    def is_tpu(self) -> bool:
        return self.kind == "tpu"

    def is_unix(self) -> bool:
        return self.kind == "unix"

    def sockaddr(self):
        """(family, address) usable with the socket module (ip/unix only)."""
        if self.kind == "ip":
            fam = _socket.AF_INET6 if ":" in self.host else _socket.AF_INET
            return fam, (self.host, self.port)
        if self.kind == "unix":
            return _socket.AF_UNIX, self.path
        raise EndPointError("tpu endpoints have no sockaddr; use the device transport")

    def __str__(self) -> str:
        if self.kind == "ip":
            host = f"[{self.host}]" if ":" in self.host else self.host
            return f"{host}:{self.port}"
        if self.kind == "unix":
            return f"unix:{self.path}"
        if self.mesh_axis:
            return f"tpu://mesh/{self.mesh_axis}"
        hostpart = self.host if not self.port else f"{self.host}:{self.port}"
        return f"tpu://{hostpart}/{self.device_ordinal}"


def str2endpoint(text: str) -> EndPoint:
    return EndPoint.parse(text)
