"""vlog — verbose-logging sites with runtime-tunable levels.

Counterpart of the reference's VLOG() + /vlog builtin
(butil/logging.h VLOG_IS_ON, builtin/vlog_service.cpp): call sites
register themselves by module name on first use; each module's verbosity
level can be raised/lowered at runtime from the dashboard without
restarting. Disabled sites cost one dict lookup + int compare.

    from brpc_tpu.butil import vlog
    if vlog.vlog_is_on("socket", 2):
        ...expensive formatting...
    vlog.vlog("socket", 1, "conn %s drained %d bytes", conn, n)

Module levels: 0 = off (default); a site at level L logs when the
module's level >= L. ``set_vlevel`` accepts fnmatch patterns like the
reference's --vmodule flag ("socket*=2").
"""

from __future__ import annotations

import fnmatch
import logging
import threading
from typing import Dict, List, Tuple

_lock = threading.Lock()
_levels: Dict[str, int] = {}     # module -> enabled level
_seen: Dict[str, int] = {}       # module -> max level seen at call sites
_patterns: List[Tuple[str, int]] = []  # applied to later-registered modules

log = logging.getLogger("brpc_tpu.vlog")


def vlog_is_on(module: str, level: int = 1) -> bool:
    lv = _levels.get(module)
    if lv is None:
        _register(module, level)
        lv = _levels.get(module, 0)
    elif _seen.get(module, 0) < level:
        with _lock:
            _seen[module] = max(_seen.get(module, 0), level)
    return lv >= level


def _register(module: str, level: int) -> None:
    with _lock:
        if module not in _levels:
            lv = 0
            for pat, plv in _patterns:
                if fnmatch.fnmatch(module, pat):
                    lv = plv
            _levels[module] = lv
        _seen[module] = max(_seen.get(module, 0), level)


def vlog(module: str, level: int, fmt: str, *args) -> None:
    if vlog_is_on(module, level):
        log.info("[%s/%d] " + fmt, module, level, *args)


def set_vlevel(pattern: str, level: int) -> int:
    """Set every matching module's level (fnmatch, reference --vmodule);
    remembered for modules that register later. Returns match count."""
    with _lock:
        _patterns.append((pattern, level))
        n = 0
        for module in _levels:
            if fnmatch.fnmatch(module, pattern):
                _levels[module] = level
                n += 1
        return n


def dump() -> List[Tuple[str, int, int]]:
    """(module, enabled_level, max_site_level) sorted — the /vlog view."""
    with _lock:
        return sorted((m, _levels[m], _seen.get(m, 0)) for m in _levels)
