"""DoublyBufferedData — RCU-like read-mostly data.

Rebuild of ``butil/containers/doubly_buffered_data.h:87``: readers read the
foreground buffer without locking; a modifier mutates the background buffer,
atomically swaps the index, waits for in-flight readers of the old foreground
to drain, then applies the same mutation to the (new) background so both
copies converge. Every load balancer's server list lives in one of these
(SURVEY §2.1).

Python adaptation: the foreground reference swap is a single attribute store
(atomic under the GIL); reader drain is tracked with per-buffer epoch counters
instead of thread-local mutexes.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Generic, TypeVar

T = TypeVar("T")


class DoublyBufferedData(Generic[T]):
    def __init__(self, factory: Callable[[], T]):
        self._bufs = [factory(), factory()]
        self._fg = 0  # index of foreground buffer
        self._readers = [0, 0]
        self._reader_lock = threading.Lock()
        self._modify_lock = threading.Lock()

    # ------------------------------------------------------------------ read
    def read(self) -> "_ScopedRead[T]":
        """Context manager yielding the foreground buffer.

        with data.read() as servers: ...
        """
        return _ScopedRead(self)

    # ---------------------------------------------------------------- modify
    def modify(self, fn: Callable[[T], object]) -> object:
        """Apply fn to both buffers with the foreground swapped in between.

        fn must be deterministic w.r.t. the buffer it receives. Returns fn's
        result from the second (now-background) application, matching the
        reference's return-value contract.
        """
        with self._modify_lock:
            bg = 1 - self._fg
            fn(self._bufs[bg])
            # Swap foreground: new readers now land on the freshly-modified
            # buffer; the old foreground becomes background once drained.
            self._fg = bg
            old_fg = 1 - bg
            self._wait_readers(old_fg)
            return fn(self._bufs[old_fg])

    def _wait_readers(self, idx: int, spin_s: float = 0.0005) -> None:
        while True:
            with self._reader_lock:
                if self._readers[idx] == 0:
                    return
            time.sleep(spin_s)

    # -------------------------------------------------------------- internal
    def _pin(self) -> int:
        with self._reader_lock:
            idx = self._fg
            self._readers[idx] += 1
            return idx

    def _unpin(self, idx: int) -> None:
        with self._reader_lock:
            self._readers[idx] -= 1


class _ScopedRead(Generic[T]):
    __slots__ = ("_data", "_idx")

    def __init__(self, data: DoublyBufferedData[T]):
        self._data = data
        self._idx = -1

    def __enter__(self) -> T:
        self._idx = self._data._pin()
        return self._data._bufs[self._idx]

    def __exit__(self, *exc) -> None:
        self._data._unpin(self._idx)
