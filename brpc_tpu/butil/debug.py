"""debug — crash handler + stack dump kit (reference butil/debug/:
stack_trace.cc, crash logging).

``install_crash_handler()`` arms faulthandler so SIGSEGV/SIGFPE/SIGABRT
dump every thread's Python stack to stderr (and optionally a crash log
file) before dying — the runtime equivalent of the reference's
stack-trace-on-crash. ``dump_all_stacks()`` is the on-demand variant
backing /threads. Server.start() installs the handler once.
"""

from __future__ import annotations

import faulthandler
import sys
import threading
import traceback
from typing import Optional

_installed = [False]
_crash_file = [None]


def install_crash_handler(crash_log_path: Optional[str] = None) -> None:
    """Re-arming is allowed: a later call with a crash_log_path re-points
    the dump there (Server.start() claims the first, stderr-bound install;
    an application asking for a persistent crash file must still get it).
    Keeps the file object alive for faulthandler's sake."""
    if _installed[0] and not crash_log_path:
        return
    if not _installed[0] and not crash_log_path \
            and faulthandler.is_enabled():
        # the application armed faulthandler itself (own crash log):
        # the default stderr install must not silently re-point it
        _installed[0] = True
        return
    stream = sys.stderr
    old = None
    if crash_log_path:
        try:
            f = open(crash_log_path, "a")
        except OSError:
            if _installed[0]:
                return
        else:
            old = _crash_file[0]
            _crash_file[0] = f
            stream = f
    elif _crash_file[0] is not None:
        stream = _crash_file[0]
    try:
        faulthandler.enable(file=stream, all_threads=True)
        _installed[0] = True
    except (RuntimeError, ValueError):
        return  # no usable stream; keep the previous arming intact
    if old is not None:
        # close the superseded crash file only AFTER faulthandler moved to
        # the new one — never leave it armed on a closed/reused fd
        try:
            old.close()
        except OSError:
            pass


def dump_all_stacks() -> str:
    """Every thread's current Python stack — THE implementation behind
    /threads (builtin/services.py delegates here); covers threads not in
    threading.enumerate() (foreign/ctypes threads) by tid."""
    frames = sys._current_frames()
    by_id = {t.ident: t for t in threading.enumerate()}
    out = []
    for tid, frame in frames.items():
        t = by_id.get(tid)
        name = t.name if t else f"tid{tid}"
        out.append(f"-- {name} (tid={tid}) --")
        out.extend(line.rstrip()
                   for line in traceback.format_stack(frame))
        out.append("")
    return "\n".join(out) + "\n"
