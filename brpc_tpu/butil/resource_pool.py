"""Versioned resource pool — the addressing scheme behind SocketId/CallId.

Rebuild of the reference's ``butil/resource_pool.h`` + the 64-bit versioned-id
pattern of ``brpc/versioned_ref_with_id.h:54-64``: an id is
``(version << 32) | slot``; a slot is recycled with its version bumped by 2 so
stale ids can never address a reincarnated object ("weak-reference" semantics
without per-lookup locks). Lookup is O(1) into a slot table; a mismatched
version means the object the caller knew is gone.

In the reference this is lock-free slab allocation; here slot reuse is guarded
by one lock (allocation is off the hot path — lookups, the hot operation, are
lock-free thanks to the GIL's atomic list reads).
"""

from __future__ import annotations

import threading
from typing import Generic, List, Optional, TypeVar

T = TypeVar("T")

VERSION_SHIFT = 32
SLOT_MASK = (1 << 32) - 1


def make_id(version: int, slot: int) -> int:
    return (version << VERSION_SHIFT) | slot


def id_version(vid: int) -> int:
    return vid >> VERSION_SHIFT


def id_slot(vid: int) -> int:
    return vid & SLOT_MASK


class _Slot(Generic[T]):
    __slots__ = ("version", "obj")

    def __init__(self):
        # Even version == free, odd == live (mirrors the reference's
        # versioned-ref convention where an in-use ref has odd parity).
        self.version = 0
        self.obj: Optional[T] = None


class VersionedPool(Generic[T]):
    """Slot pool handing out 64-bit versioned ids."""

    def __init__(self):
        self._slots: List[_Slot[T]] = []
        self._free: List[int] = []
        self._lock = threading.Lock()

    def insert(self, obj: T) -> int:
        with self._lock:
            if self._free:
                slot_idx = self._free.pop()
            else:
                slot_idx = len(self._slots)
                self._slots.append(_Slot())
            slot = self._slots[slot_idx]
            slot.version += 1  # even -> odd: live
            slot.obj = obj
            return make_id(slot.version, slot_idx)

    def address(self, vid: int) -> Optional[T]:
        """Resolve id -> object; None if recycled (stale id)."""
        slot_idx = id_slot(vid)
        slots = self._slots
        if slot_idx >= len(slots):
            return None
        slot = slots[slot_idx]
        # Read obj BEFORE version: if a concurrent remove+insert reincarnates
        # the slot between the two reads, the version check fails and we
        # return None instead of handing a stale id the new object.
        obj = slot.obj
        if slot.version != id_version(vid):
            return None
        return obj

    def remove(self, vid: int) -> Optional[T]:
        """Free the slot; returns the object if the id was still live."""
        slot_idx = id_slot(vid)
        with self._lock:
            if slot_idx >= len(self._slots):
                return None
            slot = self._slots[slot_idx]
            if slot.version != id_version(vid):
                return None
            obj, slot.obj = slot.obj, None
            slot.version += 1  # odd -> even: free
            self._free.append(slot_idx)
            return obj

    def __len__(self) -> int:
        return len(self._slots) - len(self._free)

    def live_objects(self) -> List[T]:
        out = []
        for slot in self._slots:
            obj = slot.obj
            if obj is not None and (slot.version & 1):
                out.append(obj)
        return out

    def live_ids(self) -> List[int]:
        out = []
        for idx, slot in enumerate(self._slots):
            if slot.obj is not None and (slot.version & 1):
                out.append(make_id(slot.version, idx))
        return out


class ObjectPool(Generic[T]):
    """Free-list object pool (reference ``butil/object_pool.h``)."""

    def __init__(self, factory, reset=None, max_free: int = 1024):
        self._factory = factory
        self._reset = reset
        self._free: List[T] = []
        self._lock = threading.Lock()
        self._max_free = max_free

    def get(self) -> T:
        with self._lock:
            if self._free:
                return self._free.pop()
        return self._factory()

    def put(self, obj: T) -> None:
        if self._reset is not None:
            self._reset(obj)
        with self._lock:
            if len(self._free) < self._max_free:
                self._free.append(obj)
