"""IOBuf — zero-copy chained buffer, the unit of all payload movement.

TPU-native rebuild of the reference's ``butil/iobuf.h:62`` (IOBuf: ref-counted
block chain, ``append``/``cutn`` at iobuf.h:141,207). Our design keeps the
same contract — cheap append, cheap cut, no large copies — but is built on
Python ``memoryview`` slices over immutable blocks instead of manual
refcounting (the CPython GC plays the role of the block refcount). A pluggable
block source lets pinned-host buffers back blocks later (the reference's RDMA
``block_pool.cpp`` / our PJRT pinned-host allocator, see SURVEY §5.8).
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Iterable, Iterator, List, Optional, Tuple

try:  # numpy backs the owned-block exporter; gate, don't require
    import numpy as _np
except Exception:  # pragma: no cover - numpy ships with the jax toolchain
    _np = None

DEFAULT_BLOCK_SIZE = 8192


if _np is not None:

    class _OwnedBlock(_np.ndarray):
        """Buffer exporter that runs a release hook when the LAST memoryview
        over it dies (the reference's iobuf block refcount, done with the
        CPython refcount: every slice/re-wrap of a memoryview keeps its
        exporter alive through ``Py_buffer.obj``, so the hook fires exactly
        when no live view can read the block anymore — however the views
        were split by ``cutn``/``pop_front`` or queued for a socket write).
        """

        _release: Optional[Callable[[], None]] = None

        def __del__(self):
            cb = self._release
            if cb is not None:
                self._release = None
                try:
                    cb()
                except Exception:
                    pass

else:  # pragma: no cover - degraded environment without numpy
    _OwnedBlock = None


def supports_block_ownership() -> bool:
    """True when append_user_data(..., release=) can defer the release to
    actual consumption instead of copying eagerly."""
    return _OwnedBlock is not None


class IOBuf:
    """A chain of (memoryview, offset, length) refs over shared blocks.

    Appending bytes stores a view; cutting N bytes moves views (splitting at
    most one block) — no payload copy in either direction. ``tobytes`` is the
    only full-copy operation and is what crosses into the device transport.
    """

    __slots__ = ("_refs", "_size")

    def __init__(self, data: Optional[bytes] = None):
        self._refs: deque = deque()  # of memoryview
        self._size = 0
        if data:
            self.append(data)

    # ------------------------------------------------------------------ size
    def __len__(self) -> int:
        return self._size

    def empty(self) -> bool:
        return self._size == 0

    @property
    def size(self) -> int:
        return self._size

    # ---------------------------------------------------------------- append
    def append(self, data) -> None:
        """Append bytes-like or another IOBuf (steals its refs — O(blocks))."""
        if isinstance(data, IOBuf):
            if data is self:
                # self-append duplicates content instead of losing it
                self.append(self.tobytes())
                return
            self._refs.extend(data._refs)
            self._size += data._size
            data._refs = deque()
            data._size = 0
            return
        mv = memoryview(data) if not isinstance(data, memoryview) else data
        if mv.nbytes == 0:
            return
        if mv.format != "B":
            mv = mv.cast("B")
        self._refs.append(mv)
        self._size += mv.nbytes

    def append_copy(self, data) -> None:
        """Append a private copy (when the caller will mutate its buffer)."""
        self.append(bytes(data))

    def append_user_data(self, mv: memoryview,
                         release: Optional[Callable[[], None]] = None) -> bool:
        """Append a caller-owned block without copy.

        Mirrors ``append_user_data_with_meta`` (reference iobuf.h:141) used
        for registered/pinned memory on the zero-copy path. With ``release``,
        the block is wrapped in a refcounted exporter and the callback fires
        exactly once, when the last live view over the block dies — i.e. when
        ``cutn``/``pop_front``/``clear`` consumption (or any downstream
        holder: a parsed message body, a socket write queue) has let go of
        every byte. Returns True when the append was zero-copy with deferred
        release; False when the environment forced a private copy (release
        already ran — the caller may reuse the buffer immediately).
        """
        if release is None:
            self.append(mv)
            return True
        if not isinstance(mv, memoryview):
            mv = memoryview(mv)
        if mv.nbytes == 0:
            release()
            return True
        if _OwnedBlock is None:
            # no exporter available: keep the CONTRACT (caller may recycle
            # the block once release ran) by copying, then releasing now
            self.append(bytes(mv))
            release()
            return False
        blk = _np.frombuffer(mv, dtype=_np.uint8).view(_OwnedBlock)
        blk._release = release
        self.append(memoryview(blk))
        return True

    def has_owned_blocks(self) -> bool:
        """True if any ref aliases a release-tracked block (borrowed
        registered memory): wholesale snapshot copies of such a buffer
        defeat the zero-copy receive path, so batch cutters bail to the
        ref-moving parse path when this holds."""
        if _OwnedBlock is None:
            return False
        for mv in self._refs:
            if type(mv.obj) is _OwnedBlock:
                return True
        return False

    # ------------------------------------------------------------------- cut
    def cutn(self, n: int) -> "IOBuf":
        """Cut the first n bytes into a new IOBuf (zero-copy)."""
        if n < 0:  # a negative n would silently corrupt the size invariant
            raise ValueError(f"cutn({n})")
        out = IOBuf()
        self.cutn_into(n, out)
        return out

    def cutn_into(self, n: int, out: "IOBuf") -> int:
        n = min(n, self._size)
        remain = n
        refs = self._refs
        while remain > 0:
            mv = refs[0]
            ln = mv.nbytes
            if ln <= remain:
                out._refs.append(refs.popleft())
                out._size += ln
                remain -= ln
            else:
                out._refs.append(mv[:remain])
                out._size += remain
                refs[0] = mv[remain:]
                remain = 0
        self._size -= n
        return n

    def cutn_into_buffer(self, n: int, dest) -> int:
        """Copy the first n bytes into writable buffer ``dest`` and pop them.

        The claiming consume of the streaming parse path: unlike ``cutn``
        (which moves refs and keeps source blocks alive inside the result),
        this drops the source refs as it copies, so release hooks of borrowed
        registered blocks fire immediately — mid-message, not when the parsed
        message is eventually dropped. Returns bytes copied.
        """
        if n < 0:
            raise ValueError(f"cutn_into_buffer({n})")
        n = min(n, self._size)
        if n == 0:
            return 0
        target = dest if isinstance(dest, memoryview) else memoryview(dest)
        if target.format != "B":
            target = target.cast("B")
        remain = n
        off = 0
        refs = self._refs
        while remain > 0:
            mv = refs[0]
            ln = mv.nbytes
            if ln <= remain:
                target[off:off + ln] = mv
                off += ln
                remain -= ln
                refs.popleft()
            else:
                target[off:off + remain] = mv[:remain]
                refs[0] = mv[remain:]
                remain = 0
        self._size -= n
        return n

    def pop_front(self, n: int) -> int:
        """Drop the first n bytes."""
        if n < 0:
            raise ValueError(f"pop_front({n})")
        n = min(n, self._size)
        remain = n
        refs = self._refs
        while remain > 0:
            mv = refs[0]
            ln = mv.nbytes
            if ln <= remain:
                refs.popleft()
                remain -= ln
            else:
                refs[0] = mv[remain:]
                remain = 0
        self._size -= n
        return n

    def clear(self) -> None:
        self._refs.clear()
        self._size = 0

    # ------------------------------------------------------------------ peek
    def fetch(self, n: int) -> bytes:
        """Copy out the first n bytes without consuming them."""
        n = min(n, self._size)
        if n == 0:
            return b""
        first = self._refs[0]
        if first.nbytes >= n:  # fast path: one block
            return bytes(first[:n])
        parts = []
        remain = n
        for mv in self._refs:
            take = min(mv.nbytes, remain)
            parts.append(bytes(mv[:take]))
            remain -= take
            if remain == 0:
                break
        return b"".join(parts)

    def fetch1(self) -> Optional[int]:
        if self._size == 0:
            return None
        return self._refs[0][0]

    # ------------------------------------------------------------- full copy
    def tobytes(self) -> bytes:
        if not self._refs:
            return b""
        if len(self._refs) == 1:
            return bytes(self._refs[0])
        return b"".join(bytes(mv) for mv in self._refs)

    def readinto(self, buf) -> int:
        """Copy the whole chain into a writable buffer; returns bytes copied."""
        target = memoryview(buf).cast("B")
        off = 0
        for mv in self._refs:
            ln = mv.nbytes
            target[off : off + ln] = mv
            off += ln
        return off

    # -------------------------------------------------------------- chunking
    def iter_blocks(self) -> Iterator[memoryview]:
        return iter(self._refs)

    def block_count(self) -> int:
        return len(self._refs)

    def cut_into_writer(self, write_fn, max_bytes: int = 1 << 20) -> int:
        """Feed blocks to write_fn(bytes-like)->int until it short-writes.

        The analog of ``cut_into_file_descriptor`` (iobuf.h:163): writes as
        much as the sink accepts and pops exactly that many bytes.
        """
        written = 0
        while self._refs and written < max_bytes:
            mv = self._refs[0]
            try:
                n = write_fn(mv)
            except BlockingIOError:
                break
            if n is None:  # SSL-style would-block
                break
            self.pop_front(n)
            written += n
            if n < mv.nbytes:
                break
        return written

    def __bytes__(self) -> bytes:
        return self.tobytes()

    def __eq__(self, other) -> bool:
        if isinstance(other, IOBuf):
            return self.tobytes() == other.tobytes()
        if isinstance(other, (bytes, bytearray, memoryview)):
            return self.tobytes() == bytes(other)
        return NotImplemented

    def __repr__(self) -> str:
        return f"IOBuf(size={self._size}, blocks={len(self._refs)})"


class IOBufAppender:
    """Buffered small-write appender (reference ``IOBufAppender``).

    Batches many tiny appends into DEFAULT_BLOCK_SIZE blocks so the chain does
    not degrade into one ref per byte.
    """

    __slots__ = ("_buf", "_pending", "_pending_len")

    def __init__(self):
        self._buf = IOBuf()
        self._pending: List[bytes] = []
        self._pending_len = 0

    def append(self, data: bytes) -> None:
        self._pending.append(bytes(data))
        self._pending_len += len(data)
        if self._pending_len >= DEFAULT_BLOCK_SIZE:
            self.flush()

    def flush(self) -> None:
        if self._pending:
            self._buf.append(b"".join(self._pending))
            self._pending.clear()
            self._pending_len = 0

    def buf(self) -> IOBuf:
        self.flush()
        return self._buf
