"""butil — base library for the TPU-native bRPC rebuild (SURVEY §2.1)."""

from brpc_tpu.butil.iobuf import IOBuf, IOBufAppender
from brpc_tpu.butil.endpoint import EndPoint, EndPointError, str2endpoint
from brpc_tpu.butil.resource_pool import (
    VersionedPool,
    ObjectPool,
    make_id,
    id_version,
    id_slot,
)
from brpc_tpu.butil.doubly_buffered import DoublyBufferedData
from brpc_tpu.butil.misc import (
    crc32c,
    fast_rand,
    fast_rand_less_than,
    cpuwide_time_us,
    gettimeofday_us,
)

__all__ = [
    "IOBuf",
    "IOBufAppender",
    "EndPoint",
    "EndPointError",
    "str2endpoint",
    "VersionedPool",
    "ObjectPool",
    "make_id",
    "id_version",
    "id_slot",
    "DoublyBufferedData",
    "crc32c",
    "fast_rand",
    "fast_rand_less_than",
    "cpuwide_time_us",
    "gettimeofday_us",
]
