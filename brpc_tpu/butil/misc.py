"""Small perf utilities: crc32c, fast_rand, monotonic time helpers.

Counterparts of the reference's ``butil/crc32c.cc`` (HW-accelerated CRC32-C
used as the attachment checksum), ``butil/fast_rand.cpp`` and
``butil/time.h`` (cpuwide_time_us). The CRC32-C here is the Castagnoli
polynomial via a 256-entry table; the native core (brpc_tpu/native) provides
an SSE4.2/tabled C++ version that is preferred when built.
"""

from __future__ import annotations

import random
import time

# ----------------------------------------------------------------- crc32c
_CRC32C_POLY = 0x82F63B78
_TABLE = []


def _build_table():
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ _CRC32C_POLY if crc & 1 else crc >> 1
        _TABLE.append(crc)


_build_table()

_native_crc32c = None  # installed by brpc_tpu.native when available


def crc32c(data, value: int = 0) -> int:
    """CRC32-C (Castagnoli) of bytes-like; chainable via ``value``."""
    if _native_crc32c is not None:
        return _native_crc32c(bytes(data), value)
    crc = value ^ 0xFFFFFFFF
    table = _TABLE
    for b in bytes(data):
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


# --------------------------------------------------------------- fast_rand
_rng = random.Random()
# hook pattern (same as _native_crc32c): callers `from`-import these
# functions, so the native core installs via indirection, not rebinding
_native_fast_rand = None
_native_fast_rand_less_than = None


def fast_rand() -> int:
    if _native_fast_rand is not None:
        return _native_fast_rand()
    return _rng.getrandbits(64)


def fast_rand_less_than(n: int) -> int:
    if _native_fast_rand_less_than is not None:
        return _native_fast_rand_less_than(n)
    return _rng.randrange(n) if n > 0 else 0


# -------------------------------------------------------------------- time
def cpuwide_time_us() -> int:
    return time.perf_counter_ns() // 1000


def monotonic_time_ns() -> int:
    return time.monotonic_ns()


def gettimeofday_us() -> int:
    return time.time_ns() // 1000
