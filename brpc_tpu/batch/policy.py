"""Batch policy — the knobs of one BatchQueue.

Sizing follows the device-lane lesson (tpu/device_lane.py): jit retraces
per shape, so batch sizes are padded up to a small set of buckets and the
compiled-call cache stays bounded no matter what sizes traffic produces.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple, Union

DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32, 64)


def _pow2_buckets(max_batch_size: int) -> Tuple[int, ...]:
    out = []
    k = 1
    while k < max_batch_size:
        out.append(k)
        k <<= 1
    out.append(max_batch_size)
    return tuple(out)


@dataclass
class BatchPolicy:
    """Flush triggers, padding buckets, and backpressure for one queue.

    max_batch_size  — hard cap per flushed batch (largest bucket).
    max_delay_us    — oldest queued item waits at most this long before a
                      deadline flush; 0 disables the timer (size/poll only).
    max_queue       — admission cap: queued items beyond this are rejected
                      with ELIMIT instead of queueing unboundedly.
    bucket_shapes   — padded batch sizes (jit cache keys); defaults to
                      powers of two up to max_batch_size.
    flush_on_poll_batch — also flush at poll-batch boundaries (the
                      cut-batch hook), trading batch size for latency when
                      the wire goes quiet.
    limiter         — optional policy/limiters.py spec (int | 'auto' |
                      'constant:N' | 'timeout[:ms]') consulted at admission
                      and settled per item at completion.
    """

    max_batch_size: int = 32
    max_delay_us: int = 2000
    max_queue: int = 1024
    bucket_shapes: Tuple[int, ...] = field(default_factory=tuple)
    flush_on_poll_batch: bool = True
    limiter: Union[int, str, None] = None

    def __post_init__(self):
        if self.max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        if self.max_queue < self.max_batch_size:
            self.max_queue = self.max_batch_size
        if not self.bucket_shapes:
            self.bucket_shapes = _pow2_buckets(self.max_batch_size)
        buckets = sorted(set(int(b) for b in self.bucket_shapes if b >= 1))
        if not buckets:
            raise ValueError("bucket_shapes must name at least one size >= 1")
        # the largest bucket must be able to carry a full batch, else a
        # size-triggered flush could never be padded to a known shape
        if buckets[-1] < self.max_batch_size:
            buckets.append(self.max_batch_size)
        self.bucket_shapes = tuple(buckets)

    def bucket_for(self, n: int) -> int:
        """Smallest declared bucket >= n (n is capped at max_batch_size)."""
        for b in self.bucket_shapes:
            if b >= n:
                return b
        return self.bucket_shapes[-1]
