"""batch — adaptive request batching: N concurrent RPCs, one device call.

The missing layer between bRPC's per-message dispatch and a jitted model:
InputMessenger already parses a poll batch at a time, but every parsed
request still reaches the service callback alone, so a TPU-backed service
pays one interpreter round-trip and one tiny device dispatch per RPC.
This package coalesces concurrent calls to the same (service, method) into
one padded, vectorized invocation:

  - :class:`BatchPolicy` — the knobs (max_batch_size, max_delay_us,
    size-bucketed padding, queue cap, limiter spec).
  - :class:`BatchQueue` — per-(service, method) admission queue; flushes on
    size, deadline, or poll-batch boundary.
  - :func:`batched_method` — decorator declaring a vectorized handler on a
    Service; the runtime stacks/pads request tensors, invokes the handler
    once per batch, and scatters per-item responses/errors.
  - :func:`make_batched` — the same wrapping as a plain callable, for
    manual ``Service.add_method`` registration.

Closest reference analog: bthread/execution_queue.h (serialize work onto a
consumer that drains opportunistically large batches); see
docs/adaptive-batching.md for the mapping and failure semantics.
"""

from brpc_tpu.batch.policy import BatchPolicy, DEFAULT_BUCKETS
from brpc_tpu.batch.queue import BatchItem, BatchQueue
from brpc_tpu.batch.runtime import (
    BatchContext,
    batched_method,
    flush_poll_batch,
    make_batched,
)

__all__ = [
    "BatchPolicy",
    "DEFAULT_BUCKETS",
    "BatchItem",
    "BatchQueue",
    "BatchContext",
    "batched_method",
    "make_batched",
    "flush_poll_batch",
]
