"""BatchQueue — per-(service, method) admission queue with three flush
triggers: size (queue reached max_batch_size), deadline (oldest item aged
max_delay_us, via the fiber timer), and poll-batch boundary (the
dispatcher finished cutting a read batch — brpc_tpu.batch.runtime installs
the hook).

Admission happens on whatever thread runs the service callback (fiber
worker on the generic path, the poller itself under usercode_inline);
flushed batches always run on a fresh fiber so a long vectorized call
never blocks the dispatcher or the timer thread.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, List, Optional

from brpc_tpu.batch import metrics as bmetrics
from brpc_tpu.batch.policy import BatchPolicy
from brpc_tpu.fiber import runtime as _runtime
from brpc_tpu.fiber.timer import timer_add, timer_del
from brpc_tpu.policy.limiters import create_limiter
from brpc_tpu.rpc import errors

log = logging.getLogger("brpc_tpu.batch")


def _now_us() -> int:
    return time.monotonic_ns() // 1000


class BatchItem:
    """One admitted request parked until its batch flushes."""

    __slots__ = ("cntl", "request", "done", "enqueue_us", "settled")

    def __init__(self, cntl, request, done):
        self.cntl = cntl
        self.request = request
        self.done = done
        self.enqueue_us = _now_us()
        self.settled = False


class BatchQueue:
    """Admission + flush machinery for one batched method.

    ``runner(queue, items, reason)`` is invoked on a fiber per flushed
    chunk (brpc_tpu.batch.runtime.run_batch pads, calls the vectorized
    handler, scatters responses).
    """

    def __init__(self, name: str, policy: BatchPolicy,
                 runner: Callable[["BatchQueue", List[BatchItem], str], None]):
        self.name = name
        self.policy = policy
        self.runner = runner
        self.limiter = create_limiter(policy.limiter)
        self.vector_fn = None            # set by the runtime wrapper
        self._lock = threading.Lock()
        self._items: List[BatchItem] = []
        self._outstanding = 0            # admitted, not yet settled
        self._timer_id: Optional[int] = None
        self._pending_flag = False       # on the poll-boundary flush list
        # lifetime counters (rendered by /vars status + tests)
        self.admitted = 0
        self.rejected = 0
        self.flushes = 0

    # ------------------------------------------------------------ admission
    def admit(self, item: BatchItem) -> int:
        """Queue one request; returns 0 or an error code (ELIMIT)."""
        if self.limiter is not None and not self.limiter.on_request():
            self.rejected += 1
            bmetrics.g_batch_elimit.put(1)
            return errors.ELIMIT
        full_chunk = None
        with self._lock:
            # the cap counts OUTSTANDING work (queued + batches still
            # executing), not just parked items — a slow vectorized handler
            # must push back on admission, not let fibers pile up behind it
            if self._outstanding >= self.policy.max_queue:
                self.rejected += 1
                bmetrics.g_batch_elimit.put(1)
                if self.limiter is not None:
                    # hand back the slot the probe above took
                    self.limiter.on_response(0.0, errors.ELIMIT)
                return errors.ELIMIT
            self._items.append(item)
            self._outstanding += 1
            self.admitted += 1
            n = len(self._items)
            if n >= self.policy.max_batch_size:
                full_chunk = self._take_locked(self.policy.max_batch_size)
            elif n == 1 and self.policy.max_delay_us > 0 \
                    and self._timer_id is None:
                self._timer_id = timer_add(self._on_deadline,
                                           self.policy.max_delay_us / 1e6)
        if full_chunk is not None:
            self._dispatch(full_chunk, "size")
        elif self.policy.flush_on_poll_batch:
            from brpc_tpu.batch import runtime as brt

            brt.note_pending(self)
        return 0

    # -------------------------------------------------------------- flushing
    def flush(self, reason: str = "manual") -> int:
        """Drain everything queued, in max_batch_size chunks; returns the
        number of items dispatched."""
        dispatched = 0
        while True:
            with self._lock:
                if not self._items:
                    return dispatched
                chunk = self._take_locked(self.policy.max_batch_size)
            dispatched += len(chunk)
            self._dispatch(chunk, reason)

    def depth(self) -> int:
        with self._lock:
            return len(self._items)

    def _take_locked(self, k: int) -> List[BatchItem]:
        chunk, self._items = self._items[:k], self._items[k:]
        if not self._items and self._timer_id is not None:
            timer_del(self._timer_id)
            self._timer_id = None
        return chunk

    def _on_deadline(self):
        with self._lock:
            self._timer_id = None
            if not self._items:
                return
        self.flush("deadline")

    def _dispatch(self, items: List[BatchItem], reason: str) -> None:
        self.flushes += 1
        bmetrics.note_flush(reason, len(items))
        now = _now_us()
        for it in items:
            bmetrics.note_queue_delay(now - it.enqueue_us)
        _runtime.start_background(self._run_safe, items, reason)

    def _run_safe(self, items: List[BatchItem], reason: str) -> None:
        try:
            self.runner(self, items, reason)
        except Exception:
            # the runner already isolates handler errors; reaching here
            # means the scatter machinery itself broke — fail the items so
            # no caller hangs until timeout
            log.exception("batch runner failed (queue=%s)", self.name)
            for it in items:
                try:
                    it.cntl.set_failed(errors.EINTERNAL,
                                       "batch runner failed")
                    it.done(None)
                except Exception:
                    pass
                finally:
                    self.settle(it, errors.EINTERNAL)

    # ------------------------------------------------------------ settlement
    def settle(self, item: BatchItem, error_code: int) -> None:
        """Per-item completion: releases the outstanding slot and the
        limiter slot taken at admission. Idempotent per item (the error
        fallback path may race a partial scatter)."""
        with self._lock:
            if item.settled:
                return
            item.settled = True
            self._outstanding -= 1
        if self.limiter is not None:
            self.limiter.on_response(_now_us() - item.enqueue_us, error_code)
