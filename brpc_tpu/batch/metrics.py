"""Batching observability — the /vars view of the coalescing machinery.

Global (all queues) recorders; per-queue numbers live on the queue object
and are rendered by its own exposed status variable. ``g_batch_size`` and
``g_batch_queue_delay_us`` are the two counters the bench sweep and CI
smoke assert on.
"""

from __future__ import annotations

import threading
from typing import Dict

from brpc_tpu.metrics import Adder, IntRecorder, Variable


class AvgVariable(Variable):
    """Running average of an IntRecorder as `avg (count=N)` — whole-run, not
    windowed, so a post-hoc /vars fetch still sees the bench's traffic."""

    def __init__(self, recorder: IntRecorder):
        super().__init__()
        self._recorder = recorder

    def get_value(self):
        return self._recorder.average()

    def describe(self) -> str:
        s, c = self._recorder.get_value()
        return f"{(s / c if c else 0.0):.1f} (count={c})"


# batch size at flush time (items per vectorized call)
batch_size_recorder = IntRecorder()
# per-item time from admission to flush dispatch
queue_delay_recorder = IntRecorder()

g_batch_size = AvgVariable(batch_size_recorder).expose("g_batch_size")
g_batch_queue_delay_us = AvgVariable(queue_delay_recorder).expose(
    "g_batch_queue_delay_us")

g_batch_items = Adder("g_batch_items")                # items batched, total
g_batch_flush_size = Adder("g_batch_flush_size")      # flushes by trigger
g_batch_flush_deadline = Adder("g_batch_flush_deadline")
g_batch_flush_poll = Adder("g_batch_flush_poll")
g_batch_elimit = Adder("g_batch_elimit")              # admissions rejected
g_batch_item_errors = Adder("g_batch_item_errors")    # items failed alone
g_batch_isolations = Adder("g_batch_isolations")      # batches re-run 1-by-1

_FLUSH_ADDERS = {
    "size": g_batch_flush_size,
    "deadline": g_batch_flush_deadline,
    "poll": g_batch_flush_poll,
}


def note_flush(reason: str, size: int) -> None:
    batch_size_recorder.record(size)
    g_batch_items.put(size)
    adder = _FLUSH_ADDERS.get(reason)
    if adder is not None:
        adder.put(1)


def note_queue_delay(delay_us: float) -> None:
    queue_delay_recorder.record(delay_us)


# ---------------------------------------------------------------------------
# per-bucket pad waste: a flush padded to jit-bucket B with S live items
# wasted B-S padded rows of compute. One recorder per bucket size, exposed
# lazily as g_batch_pad_waste_<bucket> ("avg wasted rows (count=flushes)"),
# so /vars shows exactly which bucket boundaries burn padding — the signal
# for retuning BatchPolicy.buckets.
_pad_waste_lock = threading.Lock()
_pad_waste_recorders: Dict[int, IntRecorder] = {}
_pad_waste_vars: Dict[int, AvgVariable] = {}  # keep exposed vars alive


def note_pad_waste(bucket: int, size: int) -> None:
    waste = bucket - size
    if waste < 0:  # unbucketed policy (bucket_for returned size)
        return
    rec = _pad_waste_recorders.get(bucket)
    if rec is None:
        with _pad_waste_lock:
            rec = _pad_waste_recorders.get(bucket)
            if rec is None:
                rec = IntRecorder()
                _pad_waste_vars[bucket] = AvgVariable(rec).expose(
                    f"g_batch_pad_waste_{bucket}")
                _pad_waste_recorders[bucket] = rec
    rec.record(waste)


def pad_waste_buckets() -> Dict[int, IntRecorder]:
    """Snapshot of the per-bucket recorders (tests, dashboards)."""
    with _pad_waste_lock:
        return dict(_pad_waste_recorders)
