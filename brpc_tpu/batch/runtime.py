"""Batch runtime — the wrapper between the dispatch path and a vectorized
handler.

Integration contract (rpc/server_processing.py, both dispatch paths): a
service method that returns None without invoking ``done`` has gone async;
the wrapper produced by :func:`batched_method` / :func:`make_batched`
enqueues the request and returns None, so batched methods ride the normal
and fast dispatch paths with no dispatcher changes. Rejections use the
other half of the contract: ``cntl.set_failed(ELIMIT); return None`` makes
the dispatcher send the error itself.

Flush-on-poll-boundary: queues that admitted items register here; the
InputMessenger calls :func:`flush_poll_batch` after cutting each read
batch (and the native poll loop after each event batch), so a burst parsed
together is batched together.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Sequence

from brpc_tpu.batch import metrics as bmetrics
from brpc_tpu.batch.policy import BatchPolicy
from brpc_tpu.batch.queue import BatchItem, BatchQueue
from brpc_tpu.profiling import registry as _prof
from brpc_tpu.rpc import errors

log = logging.getLogger("brpc_tpu.batch")


# --------------------------------------------------------------------------
#  BatchContext — what a vectorized handler receives
# --------------------------------------------------------------------------
class BatchContext:
    """One flushed batch: the live items plus stack/pad helpers.

    ``size`` is the number of real requests, ``bucket`` the padded batch
    the handler should compute at (a declared bucket_shape, so the jit
    cache stays bounded). Rows ``size..bucket-1`` are padding; the runtime
    discards their outputs at scatter time.
    """

    def __init__(self, items: List[BatchItem], bucket: int, reason: str):
        self.items = items
        self.size = len(items)
        self.bucket = bucket
        self.reason = reason
        self._errors: Dict[int, tuple] = {}

    @property
    def requests(self) -> list:
        return [it.request for it in self.items]

    @property
    def controllers(self) -> list:
        return [it.cntl for it in self.items]

    def fail(self, index: int, error_code: int, text: str = "") -> None:
        """Fail one item without touching the rest of the batch."""
        self._errors[index] = (error_code, text)

    def failed(self, index: int) -> bool:
        return index in self._errors

    def stack(self, rows: Sequence, dtype=None, pad_value=0):
        """Stack per-item rows into a (bucket, ...) array, padding the tail.

        A row that cannot be coerced to the leading row's shape/dtype fails
        alone (EREQUEST) and its slot is left as padding — one malformed
        tensor must not poison the batch.
        """
        import numpy as np

        first = None
        for i, r in enumerate(rows):
            try:
                first = np.asarray(r, dtype=dtype)
                break
            except Exception as e:
                self.fail(i, errors.EREQUEST, f"bad request tensor: {e}")
        if first is None:
            raise ValueError("every row in the batch was malformed")
        out = np.full((self.bucket,) + first.shape, pad_value,
                      dtype=first.dtype)
        for i, r in enumerate(rows):
            if i in self._errors:
                continue
            try:
                out[i] = np.asarray(r, dtype=first.dtype)
            except Exception as e:
                self.fail(i, errors.EREQUEST, f"bad request tensor: {e}")
        return out

    def device_arrays(self, handles: Sequence[int], store=None) -> list:
        """Resolve DeviceStore handles to device-resident arrays; an
        unknown handle fails its item alone and yields None in its slot."""
        if store is None:
            from brpc_tpu.tpu.device_lane import global_store

            store = global_store()
        out = []
        for i, h in enumerate(handles):
            arr = store.lookup(h)
            if arr is None:
                self.fail(i, errors.EREQUEST, f"unknown device handle {h}")
            out.append(arr)
        return out


# --------------------------------------------------------------------------
#  Batch execution: pad -> one vectorized call -> scatter
# --------------------------------------------------------------------------
def _finish(queue: BatchQueue, item: BatchItem, response,
            error_code: int, text: str) -> None:
    try:
        if error_code:
            item.cntl.set_failed(error_code, text)
        item.done(response)
    except Exception:
        # a dead connection must not take down the rest of the scatter
        log.exception("batch done callback failed (queue=%s)", queue.name)
    finally:
        queue.settle(item, error_code)


def run_batch(queue: BatchQueue, items: List[BatchItem], reason: str) -> None:
    """Runner installed on every BatchQueue: build the context, invoke the
    vectorized handler once, scatter per-item responses/errors."""
    bucket = queue.policy.bucket_for(len(items))
    bmetrics.note_pad_waste(bucket, len(items))
    ctx = BatchContext(items, bucket, reason)
    now_us = time.monotonic_ns() // 1000
    note = (f"batch: size={ctx.size} bucket={bucket} reason={reason} "
            f"queue={queue.name}")
    spans = []
    for it in items:
        span = getattr(it.cntl, "span", None)
        if span is not None:
            span.annotate(f"{note} queue_delay={now_us - it.enqueue_us}us")
            # phase marks ride the full Span API only (controllers under
            # test may carry duck-typed spans with just annotate())
            if hasattr(span, "add_phase"):
                span.add_phase("batch_wait_us",
                               max(0, now_us - it.enqueue_us))
                span.event("batch", size=ctx.size, bucket=bucket,
                           pad=bucket - ctx.size, reason=reason,
                           queue=queue.name)
                spans.append(span)
    t_exec = time.monotonic_ns()
    prev_ph = _prof.set_phase("execute")
    try:
        responses = queue.vector_fn(ctx)
    except Exception as e:
        _prof.set_phase(prev_ph)
        if len(items) == 1:
            _finish(queue, items[0], None, errors.EINTERNAL,
                    f"batched handler raised: {e!r}")
            bmetrics.g_batch_item_errors.put(1)
            return
        # isolation: the handler died on the batch — re-run every item as
        # its own singleton so one poisoned request fails alone
        bmetrics.g_batch_isolations.put(1)
        log.warning("batched handler raised on %d items (queue=%s): %r — "
                    "isolating", len(items), queue.name, e)
        for it in items:
            run_batch(queue, [it], "isolate")
        return
    _prof.set_phase(prev_ph)
    # the vectorized call's wall time is every rider's device time: each
    # item waited for the whole call, so each span carries the full mark
    exec_us = (time.monotonic_ns() - t_exec) / 1000.0
    for span in spans:
        span.add_phase("execute_us", exec_us)
    n_resp = len(responses) if responses is not None else 0
    for i, it in enumerate(items):
        err = ctx._errors.get(i)
        if err is not None:
            bmetrics.g_batch_item_errors.put(1)
            _finish(queue, it, None, err[0],
                    err[1] or errors.error_text(err[0]))
        elif i < n_resp and responses[i] is not None:
            _finish(queue, it, responses[i], 0, "")
        else:
            bmetrics.g_batch_item_errors.put(1)
            _finish(queue, it, None, errors.EINTERNAL,
                    "batched handler produced no response for item")


# --------------------------------------------------------------------------
#  Poll-batch-boundary flushing
# --------------------------------------------------------------------------
_pending_lock = threading.Lock()
_pending: List[BatchQueue] = []
_hooks_installed = False


def note_pending(queue: BatchQueue) -> None:
    """Mark a queue for flushing at the next poll-batch boundary."""
    install = False
    with _pending_lock:
        if not queue._pending_flag:
            queue._pending_flag = True
            _pending.append(queue)
        global _hooks_installed
        if not _hooks_installed:
            _hooks_installed = True
            install = True
    if install:
        _install_hooks()


def flush_poll_batch() -> None:
    """Poll-batch boundary: drain every queue that admitted since the last
    boundary. Called by InputMessenger.cut_messages and the native poll
    loop; cheap no-op when nothing is pending."""
    if not _pending:
        return
    with _pending_lock:
        queues = _pending[:]
        _pending.clear()
        for q in queues:
            q._pending_flag = False
    for q in queues:
        q.flush("poll")


def _install_hooks() -> None:
    from brpc_tpu.rpc import input_messenger

    input_messenger.poll_batch_hook = flush_poll_batch
    try:
        from brpc_tpu.rpc import native_transport

        native_transport.poll_batch_hook = flush_poll_batch
    except Exception:  # pragma: no cover - native lane absent
        pass


def _reset_hooks_for_test() -> None:
    global _hooks_installed
    with _pending_lock:
        for q in _pending:
            q._pending_flag = False
        _pending.clear()
        _hooks_installed = False


# --------------------------------------------------------------------------
#  The user-facing wrappers
# --------------------------------------------------------------------------
class _BoundBatchedMethod:
    """The callable the dispatcher sees: (cntl, request, done) -> None.

    Enqueues into its BatchQueue and returns None (async per the dispatch
    contract); on rejection marks the controller ELIMIT so the dispatcher
    sends the error."""

    __slots__ = ("queue", "__name__")

    def __init__(self, name: str, vector_fn, policy: BatchPolicy):
        self.queue = BatchQueue(name, policy, run_batch)
        self.queue.vector_fn = vector_fn
        self.__name__ = name

    def __call__(self, cntl, request, done):
        # server-side deadline: don't enqueue work whose client budget is
        # already spent — it would occupy a batch slot only to have its
        # response dropped by the caller
        dl = getattr(cntl, "deadline_mono", 0.0)
        if dl and time.monotonic() >= dl:
            from brpc_tpu.rpc.server_processing import \
                g_server_deadline_expired

            g_server_deadline_expired.put(1)
            cntl.set_failed(errors.ERPCTIMEDOUT,
                            "request deadline already spent before batch "
                            "enqueue")
            return None
        rc = self.queue.admit(BatchItem(cntl, request, done))
        if rc != 0:
            cntl.set_failed(rc, f"batch queue {self.queue.name} over "
                                f"capacity")
        elif getattr(getattr(cntl, "_srv_socket", None),
                     "priority_lane", False):
            # latency-sensitive lane: a request arriving on the tpu
            # tunnel's priority sub-stream is exempt from batch_wait —
            # flush whatever this admission formed immediately
            self.queue.flush("priority")
        return None


def make_batched(name: str, vector_fn, **policy_knobs) -> _BoundBatchedMethod:
    """Wrap a vectorized callable ``fn(BatchContext) -> [responses]`` for
    manual ``Service.add_method(name, make_batched(...), req, resp)``."""
    return _BoundBatchedMethod(name, vector_fn, BatchPolicy(**policy_knobs))


class _BatchedMethodDescriptor:
    """What @batched_method leaves on the class: binding an instance builds
    that instance's _BoundBatchedMethod (one BatchQueue per service object,
    named <service>.<method>) and caches it in the instance dict — so
    Service.__init__'s getattr() wires the wrapper straight into the
    MethodEntry."""

    def __init__(self, fn, policy: BatchPolicy):
        self._fn = fn
        self._policy = policy
        self._name = fn.__name__
        self.__doc__ = fn.__doc__

    def __set_name__(self, owner, name):
        self._name = name

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        service = getattr(obj, "service_name", type(obj).__name__)
        bound = _BoundBatchedMethod(f"{service}.{self._name}",
                                    self._fn.__get__(obj), self._policy)
        obj.__dict__[self._name] = bound
        return bound


def batched_method(fn=None, *, max_batch_size: int = 32,
                   max_delay_us: int = 2000, max_queue: int = 1024,
                   bucket_shapes: Sequence[int] = (),
                   flush_on_poll_batch: bool = True,
                   limiter=None):
    """Declare a vectorized service method.

    The decorated function takes ``(self, batch: BatchContext)`` and
    returns a list of >= batch.size responses (index-aligned; slots the
    handler ``batch.fail()``-ed may hold None). Example::

        class Inference(Service):
            @batched_method(bucket_shapes=(1, 4, 16, 64), max_delay_us=1000)
            def Infer(self, batch):
                x = batch.stack([parse(r) for r in batch.requests])
                y = self.model(x)              # ONE jitted call
                return [make_resp(y[i]) for i in range(batch.size)]
    """
    policy = BatchPolicy(max_batch_size=max_batch_size,
                         max_delay_us=max_delay_us, max_queue=max_queue,
                         bucket_shapes=tuple(bucket_shapes),
                         flush_on_poll_batch=flush_on_poll_batch,
                         limiter=limiter)

    def wrap(f):
        return _BatchedMethodDescriptor(f, policy)

    if fn is not None:  # bare @batched_method
        return wrap(fn)
    return wrap
