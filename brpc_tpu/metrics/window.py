"""Window / PerSecond — time-windowed views of reducers (bvar/window.h:174).

A Window(reducer, window_size) shows "the reducer's delta over the last W
seconds"; PerSecond divides by W. Implementation: a Sampler snapshots the
reducer once per second; for invertible ops (Adder) the window value is
``newest - oldest``; for non-invertible ops the sampler stores per-tick
deltas via reset() and the window combines them.
"""

from __future__ import annotations

from typing import Generic, TypeVar

from brpc_tpu.metrics.reducer import Reducer
from brpc_tpu.metrics.sampler import Sampler, global_collector
from brpc_tpu.metrics.percentile import Percentile, PercentileSamples

T = TypeVar("T")


class Window(Generic[T]):
    def __init__(self, reducer: Reducer, window_size: int = 10,
                 collector=None):
        self._reducer = reducer
        self.window_size = max(1, window_size)
        if reducer.has_inverse:
            take = reducer.get_value  # cumulative snapshots
        else:
            take = reducer.reset      # per-tick deltas
        self._sampler = Sampler(take, self.window_size + 1)
        (collector or global_collector()).register(self._sampler)

    def get_value(self) -> T:
        if self._reducer.has_inverse:
            # Cumulative snapshots: window value = now - state W seconds ago.
            # If the series began inside the window, that state is identity.
            samples = self._sampler.recent(self.window_size + 1)
            current = self._reducer.get_value()
            if len(samples) <= self.window_size:
                oldest = self._reducer.identity
            else:
                oldest = samples[0]
            return self._reducer.inverse(current, oldest)
        # non-invertible: combine in-window deltas + live agents in the op's
        # raw domain, clamping only at the end (Maxer/Miner finalize maps
        # their +-inf identity to 0 — combining clamped values would pin
        # windowed min at <=0)
        samples = self._sampler.recent(self.window_size)
        result = self._reducer.get_raw_value()
        for s in samples:
            result = self._reducer._op(result, s)
        return self._reducer.finalize(result)

    def get_span_seconds(self) -> int:
        return min(self._sampler.sample_count(), self.window_size) or 1

    def expose(self, name: str) -> "Window":
        from brpc_tpu.metrics.variable import Variable

        win = self

        class _Wrap(Variable):
            def __init__(w):
                super().__init__()
                # a windowed reading is a point-in-time value: always a
                # gauge, even when the underlying reducer is a monotonic
                # counter (scraping it as a counter would make rate() of
                # an already-rated value)
                w.prometheus_type = "gauge"

            def get_value(w):
                return win.get_value()

        self._var = _Wrap().expose(name)
        return self

    def hide(self) -> None:
        var = getattr(self, "_var", None)
        if var is not None:
            var.hide()


class PerSecond(Window):
    def get_value(self):
        total = super().get_value()
        return total / self.get_span_seconds()


class WindowedPercentile:
    """Percentile over the last W seconds (backs LatencyRecorder p99s)."""

    def __init__(self, percentile: Percentile, window_size: int = 10,
                 collector=None):
        self._p = percentile
        self.window_size = max(1, window_size)
        self._sampler = Sampler(percentile.reset, self.window_size + 1)
        (collector or global_collector()).register(self._sampler)

    def get_value(self) -> PercentileSamples:
        merged = PercentileSamples()
        for s in self._sampler.recent(self.window_size):
            merged.merge(s)
        merged.merge(self._p.get_value())  # not-yet-harvested samples
        return merged

    def get_number(self, ratio: float) -> float:
        return self.get_value().get_number(ratio)
