"""Declarative watch rules over series rings.

A WatchRule names an exposed variable and a condition over its one-second
series tier — ``threshold`` (the latest sample vs a bound), ``delta`` (change
across the last ``window_s`` seconds) or ``rate`` (change per second over the
window). Rules are evaluated inside the sampler tick, right after the series
sweep, via a :attr:`SeriesRegistry.post_tick_hooks` hook — no extra thread,
no extra clock.

Each rule is a tiny state machine: ``no_data`` → ``ok`` ⇄ ``firing``. The
condition must hold for ``for_ticks`` consecutive ticks to fire (debounce)
and stay false for ``clear_ticks`` ticks to clear, so a single spiky sample
can't flap a rule. Transitions bump ``g_watch_transitions``, update the
``/watch`` builtin, and emit a short structured span (service ``watch``) so
firings land in the span DB, ``/rpcz`` and OTLP export.

``install_default_rules()`` pre-wires the plane's canonical failure signals:
deadline-expiry rate, tunnel healer trips, block-pool/credit exhaustion and
shard worker death.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from brpc_tpu.metrics.reducer import Adder
from brpc_tpu.metrics.series import SeriesRegistry, global_series
from brpc_tpu.metrics.status import PassiveStatus

STATE_NO_DATA = "no_data"
STATE_OK = "ok"
STATE_FIRING = "firing"

_OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b,
}

KIND_THRESHOLD = "threshold"
KIND_DELTA = "delta"
KIND_RATE = "rate"


class WatchRule:
    """One named condition over a variable's 1-second series tier."""

    def __init__(self, name: str, var: str, kind: str, op: str, value: float,
                 window_s: int = 10, for_ticks: int = 1, clear_ticks: int = 3,
                 value_fn: Optional[Callable[[], float]] = None):
        if kind not in (KIND_THRESHOLD, KIND_DELTA, KIND_RATE):
            raise ValueError(f"unknown watch kind {kind!r}")
        if op not in _OPS:
            raise ValueError(f"unknown watch op {op!r}")
        if window_s < 1 or for_ticks < 1 or clear_ticks < 1:
            raise ValueError("window_s/for_ticks/clear_ticks must be >= 1")
        self.name = name
        self.var = var
        self.kind = kind
        self.op = op
        self.value = value
        # reloadable bound: when set, the comparison value is re-read every
        # tick (e.g. from a runtime flag), so /flags?setvalue= retunes the
        # rule without re-installing it; `value` stays as the fallback
        self.value_fn = value_fn
        self.window_s = window_s
        self.for_ticks = for_ticks
        self.clear_ticks = clear_ticks
        # state
        self.state = STATE_NO_DATA
        self.observed = 0.0        # the measured quantity at last evaluation
        self.true_streak = 0
        self.false_streak = 0
        self.transitions = 0
        self.last_transition_s = 0.0

    # ------------------------------------------------------------ evaluate
    def _measure(self, series) -> Optional[float]:
        # series tiers are identity-prefilled; use the real-sample count to
        # avoid reading fill as data
        have = min(series.count, len(series.second.data))
        if have < 1:
            return None
        ordered = series.second.ordered()
        if self.kind == KIND_THRESHOLD:
            return float(ordered[-1])
        span = min(self.window_s, have - 1)
        if span < 1:
            return None
        delta = float(ordered[-1]) - float(ordered[-1 - span])
        if self.kind == KIND_DELTA:
            return delta
        return delta / span  # rate: per-second change over the window

    def evaluate(self, registry: SeriesRegistry) -> Optional[str]:
        """Advance the state machine one tick. Returns the new state when a
        transition happened, else None."""
        series = registry.get(self.var)
        measured = self._measure(series) if series is not None else None
        if measured is None:
            if self.state == STATE_FIRING:
                # var disappeared mid-fire: treat as cleared
                return self._transition(STATE_NO_DATA)
            self.state = STATE_NO_DATA
            return None
        self.observed = measured
        cond = _OPS[self.op](measured, self.bound())
        if cond:
            self.true_streak += 1
            self.false_streak = 0
        else:
            self.false_streak += 1
            self.true_streak = 0
        if self.state != STATE_FIRING and self.true_streak >= self.for_ticks:
            return self._transition(STATE_FIRING)
        if self.state == STATE_FIRING and self.false_streak >= self.clear_ticks:
            return self._transition(STATE_OK)
        if self.state == STATE_NO_DATA:
            self.state = STATE_OK
        return None

    def _transition(self, new_state: str) -> str:
        self.state = new_state
        self.transitions += 1
        self.last_transition_s = time.time()  # tpulint: disable=monotonic-clock
        return new_state

    def bound(self) -> float:
        if self.value_fn is None:
            return self.value
        try:
            return float(self.value_fn())
        except Exception:
            return self.value

    def condition(self) -> str:
        return f"{self.kind}({self.var}, {self.window_s}s) " \
               f"{self.op} {self.bound():g}"

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "var": self.var,
            "kind": self.kind,
            "op": self.op,
            "value": self.bound(),
            "window_s": self.window_s,
            "state": self.state,
            "observed": self.observed,
            "transitions": self.transitions,
        }


g_watch_transitions = Adder("g_watch_transitions")


class WatchRegistry:
    """All rules + the post-tick evaluation hook."""

    def __init__(self):
        self._rules: Dict[str, WatchRule] = {}
        self._lock = threading.Lock()
        self._vars = []
        # called as hook(rule, new_state) on every state transition, after
        # the transition span — tail retention correlates in-flight traces
        # with firings through this. Hooks must not raise (guarded anyway)
        # and must not block: they run inside the sampler tick.
        self.transition_hooks: List[Callable[[WatchRule, str], None]] = []

    def add(self, rule: WatchRule) -> WatchRule:
        with self._lock:
            self._rules[rule.name] = rule
        return rule

    def remove(self, name: str) -> None:
        with self._lock:
            self._rules.pop(name, None)

    def rules(self) -> List[WatchRule]:
        with self._lock:
            return sorted(self._rules.values(), key=lambda r: r.name)

    def firing(self) -> List[WatchRule]:
        return [r for r in self.rules() if r.state == STATE_FIRING]

    def clear(self) -> None:
        """Test hook."""
        with self._lock:
            self._rules.clear()

    # ---------------------------------------------------------------- tick
    def evaluate_all(self, registry: SeriesRegistry) -> None:
        for rule in self.rules():
            transition = rule.evaluate(registry)
            if transition is not None:
                self._report(rule, transition)

    def _report(self, rule: WatchRule, new_state: str) -> None:
        g_watch_transitions.put(1)
        # a short span so the firing lands in the span DB + OTLP export
        try:
            from brpc_tpu.trace.span import KIND_SERVER, Span, _gen_id
            tid = _gen_id()
            span = Span(tid, tid, 0, KIND_SERVER, "watch", rule.name)
            span.event(
                "watch_firing" if new_state == STATE_FIRING
                else "watch_cleared",
                rule=rule.name, var=rule.var, state=new_state,
                condition=rule.condition(), observed=rule.observed)
            span.end(error_code=1 if new_state == STATE_FIRING else 0)
        except Exception:
            pass
        for hook in list(self.transition_hooks):
            try:
                hook(rule, new_state)
            except Exception:
                pass

    # -------------------------------------------------------------exposure
    def expose_vars(self) -> None:
        """Expose g_watch_rules / g_watch_firing passive gauges (idempotent;
        re-exposes after a test's clear_registry() hid them)."""
        if self._vars and self._vars[0].name is not None:
            return
        self._vars = []
        rules_var = PassiveStatus(lambda: len(self.rules()))
        rules_var.prometheus_type = "gauge"
        firing_var = PassiveStatus(lambda: len(self.firing()))
        firing_var.prometheus_type = "gauge"
        self._vars = [rules_var.expose("g_watch_rules"),
                      firing_var.expose("g_watch_firing")]


_global_watch = WatchRegistry()
_hooked = False
_defaults_installed = False
_install_lock = threading.Lock()


def global_watch() -> WatchRegistry:
    return _global_watch


def ensure_watch_hooked(series: Optional[SeriesRegistry] = None) -> WatchRegistry:
    """Chain watch evaluation onto the series sweep (idempotent)."""
    global _hooked
    with _install_lock:
        if not _hooked:
            (series or global_series()).post_tick_hooks.append(
                _global_watch.evaluate_all)
            _hooked = True
        _global_watch.expose_vars()
    return _global_watch


def install_default_rules() -> None:
    """Pre-wire the canonical plane-health rules (idempotent)."""
    global _defaults_installed
    with _install_lock:
        if _defaults_installed:
            return
        _defaults_installed = True
    w = _global_watch
    w.add(WatchRule(
        "deadline_expiry_rate", "g_server_deadline_expired", KIND_RATE,
        ">", 0.5, window_s=10, for_ticks=2, clear_ticks=5))
    w.add(WatchRule(
        "tunnel_healer_trips", "g_tunnel_reconnect_failures", KIND_DELTA,
        ">=", 1, window_s=30, for_ticks=1, clear_ticks=5))
    w.add(WatchRule(
        "block_pool_exhaustion", "g_tunnel_credit_stalls", KIND_RATE,
        ">", 10, window_s=10, for_ticks=2, clear_ticks=5))
    w.add(WatchRule(
        "shard_worker_death", "g_shard_worker_deaths", KIND_DELTA,
        ">=", 1, window_s=60, for_ticks=1, clear_ticks=10))
    # serving plane: sustained admission rejects mean the paged KV pool is
    # pinned above its watermark — clients are being shed EOVERCROWDED
    w.add(WatchRule(
        "serving_kv_exhaustion", "g_serving_kv_admission_rejects",
        KIND_DELTA, ">=", 1, window_s=10, for_ticks=1, clear_ticks=5))
    # sharded serving: one KV shard filling while its siblings idle means
    # routing (or a hot sequence) is concentrating load — the bound is
    # the reloadable serving_shard_skew_ratio flag
    from brpc_tpu import flags as _flags
    w.add(WatchRule(
        "serving_shard_skew", "g_serving_kv_shard_skew",
        KIND_THRESHOLD, ">", 0.25, window_s=10, for_ticks=2, clear_ticks=5,
        value_fn=lambda: _flags.get("serving_shard_skew_ratio")))
    # prefix cache: sustained eviction means the radix tree is thrashing —
    # the working set of prefixes outruns the pool's cache headroom, so
    # chains are evicted before they can be re-hit. Bound is the
    # reloadable serving_prefix_thrash_rate flag (blocks/s)
    w.add(WatchRule(
        "serving_prefix_thrash", "g_serving_prefix_evicted_blocks",
        KIND_RATE, ">", 20, window_s=10, for_ticks=2, clear_ticks=5,
        value_fn=lambda: _flags.get("serving_prefix_thrash_rate")))
    # disaggregated serving: migrations stacking up in flight mean the
    # record lane (or the decode side's adoption path) cannot keep pace
    # with prefill handoffs — decode shards are about to see TTFT cliffs.
    # Bound is the reloadable serving_migrate_backlog_max flag
    w.add(WatchRule(
        "serving_migrate_backlog", "g_serving_migrate_inflight",
        KIND_THRESHOLD, ">", 8, window_s=10, for_ticks=2, clear_ticks=5,
        value_fn=lambda: _flags.get("serving_migrate_backlog_max")))
    # speculative decoding: the accept-rate gauge sliding below the
    # bound means prompt-lookup drafts stopped matching the model's
    # output — every verify row past the first is wasted compute. The
    # per-sequence AdaptiveK guard collapses offenders to plain decode;
    # this rule surfaces a FLEET-wide collapse (workload shift,
    # misdraft-shaped bug) the per-sequence guard can only mask. Bound
    # is the reloadable serving_spec_accept_rate_min flag
    w.add(WatchRule(
        "serving_spec_collapse", "g_serving_spec_accept_rate",
        KIND_THRESHOLD, "<", 0.2, window_s=10, for_ticks=2, clear_ticks=5,
        value_fn=lambda: _flags.get("serving_spec_accept_rate_min")))
    # multi-tenant QoS: the oldest queued request sitting past the bound
    # means a tenant lane is starving — the fair-share weights, the
    # limiter ceiling, or a protected flood is locking a lane out of
    # admission faster than the governor sheds. Bound is the reloadable
    # serving_qos_starvation_ms flag
    w.add(WatchRule(
        "serving_qos_starvation", "g_serving_qos_max_wait_ms",
        KIND_THRESHOLD, ">", 2000, window_s=10, for_ticks=2, clear_ticks=5,
        value_fn=lambda: _flags.get("serving_qos_starvation_ms")))
