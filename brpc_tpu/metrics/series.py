"""Multi-tier series rings — the bvar ``detail::SeriesSampler`` analog.

Every exposed numeric Variable grows a fixed-size time series: 60 one-second
samples, 60 one-minute samples and 24 one-hour samples (the reference keeps a
fourth 30-day tier; a Python process rarely lives that long, so we stop at
hours). Rings are identity-filled (0) before the first real sample, exactly
like the reference, so renderers never need a "no data" special case.

Rollups are **append-count based**, not wall-clock based: every 60 appends to
the second ring reduce into one minute sample; every 60 minute samples reduce
into one hour sample. The once-per-second sampler daemon
(:mod:`brpc_tpu.metrics.sampler`) provides the 1 Hz cadence in production,
while tests drive ``tick()`` manually and get exact, clock-free rollups.

The sweep itself (`SeriesRegistry.tick`) is one O(vars) pass appending one
value per var — gated by the reloadable ``var_series_enabled`` flag, with
per-var opt-out for high-cardinality names (``var_series_optout`` glob list,
a ``series_opt_out`` attribute on the Variable, or ``opt_out()``).
"""

from __future__ import annotations

import fnmatch
import threading
import time
from typing import Callable, Dict, List, Optional

from brpc_tpu import flags
from brpc_tpu.metrics.sampler import Sampler, SamplerCollector, global_collector
from brpc_tpu.metrics.variable import exposed_variables

SECOND_SAMPLES = 60
MINUTE_SAMPLES = 60
HOUR_SAMPLES = 24

# How a tier-N window collapses into one tier-N+1 sample. "avg" suits gauges
# and qps-style rates (the common case); vars carrying a ``series_reduce``
# attribute pick another op (e.g. Maxer-backed vars want "max").
_REDUCERS = {
    "avg": lambda xs: sum(xs) / len(xs),
    "max": max,
    "min": min,
    "sum": sum,
    "last": lambda xs: xs[-1],
}

flags.define(
    "var_series_enabled", True,
    "retain a 60x1s/60x1m/24x1h series ring for every exposed numeric "
    "variable, appended by the sampler daemon tick", reloadable=True)
flags.define(
    "var_series_optout", "",
    "comma-separated name globs excluded from series retention "
    "(high-cardinality families, e.g. 'worker*_*')", reloadable=True)


class _Ring:
    """Fixed-size ring, identity(0)-prefilled, oldest-first on read."""

    __slots__ = ("data", "pos")

    def __init__(self, capacity: int):
        self.data = [0] * capacity
        self.pos = 0  # next write slot

    def append(self, value) -> None:
        self.data[self.pos] = value
        self.pos = (self.pos + 1) % len(self.data)

    def ordered(self) -> list:
        return self.data[self.pos:] + self.data[: self.pos]


class VarSeries:
    """The three-tier ring attached to one variable."""

    __slots__ = ("second", "minute", "hour", "reduce_op", "is_float",
                 "count", "last", "_pending_minutes")

    def __init__(self, reduce_op: str = "avg"):
        self.second = _Ring(SECOND_SAMPLES)
        self.minute = _Ring(MINUTE_SAMPLES)
        self.hour = _Ring(HOUR_SAMPLES)
        self.reduce_op = reduce_op if reduce_op in _REDUCERS else "avg"
        self.is_float = False
        self.count = 0       # real samples appended (not identity fill)
        self.last = 0
        # minute samples accumulated since the last hour rollup; kept as a
        # plain list (not read off the ring) so the hour sample reduces over
        # exactly the minutes that produced it, even across ring wrap
        self._pending_minutes: List[float] = []

    def append(self, value) -> None:
        if isinstance(value, float):
            self.is_float = True
        self.last = value
        self.second.append(value)
        self.count += 1
        if self.count % SECOND_SAMPLES == 0:
            reduce_fn = _REDUCERS[self.reduce_op]
            minute = self._coerce(reduce_fn(self.second.ordered()))
            self.minute.append(minute)
            self._pending_minutes.append(minute)
            if len(self._pending_minutes) == MINUTE_SAMPLES:
                self.hour.append(self._coerce(reduce_fn(self._pending_minutes)))
                self._pending_minutes = []

    def _coerce(self, value):
        """Integer-aware rollup: int series stay int (floor the mean) so
        '/vars' plots of counters don't sprout decimals."""
        if not self.is_float and isinstance(value, float):
            return int(value)
        return value

    def to_dict(self) -> dict:
        return {
            "second": self.second.ordered(),
            "minute": self.minute.ordered(),
            "hour": self.hour.ordered(),
            "count": self.count,
            "last": self.last,
            "reduce": self.reduce_op,
            "float": self.is_float,
        }


class SeriesRegistry:
    """Sweeps the exposed-variable registry once per tick, appending one
    sample per numeric var. One of these hangs off the global sampler
    collector; tests build private instances and tick them directly."""

    def __init__(self):
        self._series: Dict[str, VarSeries] = {}
        self._lock = threading.Lock()
        self._optout: set = set()          # programmatic opt-outs (exact names)
        self._optout_globs: tuple = ()     # programmatic opt-outs (patterns)
        self.post_tick_hooks: List[Callable[["SeriesRegistry"], None]] = []
        self.ticks = 0
        self.last_tick_s = 0.0
        self.total_tick_s = 0.0

    # ------------------------------------------------------------- opt-out
    def opt_out(self, pattern: str) -> None:
        """Exclude a name (or glob) from series retention and drop any
        series already accumulated for it."""
        with self._lock:
            if any(ch in pattern for ch in "*?["):
                self._optout_globs += (pattern,)
                for name in [n for n in self._series
                             if fnmatch.fnmatchcase(n, pattern)]:
                    del self._series[name]
            else:
                self._optout.add(pattern)
                self._series.pop(pattern, None)

    def _opted_out(self, name: str, var) -> bool:
        if getattr(var, "series_opt_out", False):
            return True
        if name in self._optout:
            return True
        for pat in self._optout_globs:
            if fnmatch.fnmatchcase(name, pat):
                return True
        flag_pats = flags.get("var_series_optout")
        if flag_pats:
            for pat in flag_pats.split(","):
                pat = pat.strip()
                if pat and fnmatch.fnmatchcase(name, pat):
                    return True
        return False

    # ---------------------------------------------------------------- tick
    def tick(self) -> None:
        if not flags.get("var_series_enabled"):
            return
        t0 = time.perf_counter()
        snapshot = exposed_variables()
        live = set()
        with self._lock:
            for name, var in snapshot:
                if self._opted_out(name, var):
                    continue
                try:
                    value = var.get_value()
                except Exception:
                    continue
                # bool is an int subclass — a flag mirror, not a series
                if isinstance(value, bool) or \
                        not isinstance(value, (int, float)):
                    continue
                live.add(name)
                series = self._series.get(name)
                if series is None:
                    series = VarSeries(
                        reduce_op=getattr(var, "series_reduce", "avg"))
                    self._series[name] = series
                series.append(value)
            # GC series whose vars were hidden (cheap: set difference)
            for name in [n for n in self._series if n not in live]:
                del self._series[name]
        self.ticks += 1
        self.last_tick_s = time.perf_counter() - t0
        self.total_tick_s += self.last_tick_s
        for hook in list(self.post_tick_hooks):
            try:
                hook(self)
            except Exception:
                pass

    # ---------------------------------------------------------------- read
    def get(self, name: str) -> Optional[VarSeries]:
        with self._lock:
            return self._series.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def dump(self, name_glob: str = "*") -> Dict[str, dict]:
        """Snapshot for ``/vars?series=json`` — name glob -> tier dict."""
        with self._lock:
            items = sorted(self._series.items())
        return {name: s.to_dict() for name, s in items
                if fnmatch.fnmatchcase(name, name_glob)}

    def clear(self) -> None:
        """Test hook."""
        with self._lock:
            self._series.clear()
            self._optout.clear()
            self._optout_globs = ()
        self.ticks = 0
        self.total_tick_s = 0.0
        self.last_tick_s = 0.0


_global_series = SeriesRegistry()
_install_lock = threading.Lock()
_installed_sampler: Optional[Sampler] = None


def global_series() -> SeriesRegistry:
    return _global_series


def ensure_series_installed(
        collector: Optional[SamplerCollector] = None) -> SeriesRegistry:
    """Register the global series sweep with the sampler daemon (idempotent).
    Called from Server.start; harmless to call from anywhere else."""
    global _installed_sampler
    with _install_lock:
        if _installed_sampler is None:
            # capacity 1: the Sampler ring is unused — the registry keeps
            # its own tiers; the Sampler is just the 1 Hz tick hook
            _installed_sampler = Sampler(
                lambda: (_global_series.tick(), None)[1], 1)
            (collector or global_collector()).register(_installed_sampler)
    return _global_series
