"""Status / PassiveStatus / MultiDimension / prometheus exposition.

Rebuilds bvar's gauge family: Status (set-once-read-many gauge,
``bvar/status.h``), PassiveStatus (callback-backed gauge,
``passive_status.h:42``), MultiDimension (labeled metrics,
``multi_dimension.h``), and the Prometheus text format exporter
(``builtin/prometheus_metrics_service.cpp:224``).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Tuple

from brpc_tpu.metrics.variable import Variable, dump_exposed


class Status(Variable):
    """A plain settable gauge."""

    def __init__(self, value=0):
        super().__init__()
        self._value = value

    def set_value(self, value) -> None:
        self._value = value

    def get_value(self):
        return self._value


class PassiveStatus(Variable):
    """Gauge computed by a callback at read time."""

    def __init__(self, fn: Callable[[], object]):
        super().__init__()
        self._fn = fn

    def get_value(self):
        return self._fn()


class MultiDimension(Variable):
    """Labeled metric family: get_stats(labels) -> per-combination variable."""

    def __init__(self, label_names: Tuple[str, ...], factory=None):
        super().__init__()
        self.label_names = tuple(label_names)
        self._factory = factory or (lambda: Status(0))
        self._stats: Dict[Tuple[str, ...], Variable] = {}
        self._lock = threading.Lock()

    def get_stats(self, labels: Tuple[str, ...]) -> Variable:
        labels = tuple(labels)
        if len(labels) != len(self.label_names):
            raise ValueError("label arity mismatch")
        with self._lock:
            var = self._stats.get(labels)
            if var is None:
                var = self._factory()
                self._stats[labels] = var
            return var

    def get_value(self):
        with self._lock:
            return {k: v.get_value() for k, v in self._stats.items()}

    def count_stats(self) -> int:
        with self._lock:
            return len(self._stats)


def prometheus_text() -> str:
    """Render every exposed variable in Prometheus exposition format."""
    lines = []
    for name, value in dump_exposed().items():
        metric = name.replace(".", "_").replace("-", "_")
        try:
            num = float(value)
        except (TypeError, ValueError):
            continue  # prometheus only carries numeric samples
        lines.append(f"# TYPE {metric} gauge")
        lines.append(f"{metric} {num:g}")
    return "\n".join(lines) + ("\n" if lines else "")
