"""Status / PassiveStatus / prometheus exposition.

Rebuilds bvar's gauge family: Status (set-once-read-many gauge,
``bvar/status.h``), PassiveStatus (callback-backed gauge,
``passive_status.h:42``), MultiDimension (labeled metrics,
``multi_dimension.h``), and the Prometheus text format exporter
(``builtin/prometheus_metrics_service.cpp:224``).
"""

from __future__ import annotations

from typing import Callable

from brpc_tpu.metrics.variable import Variable


class Status(Variable):
    """A plain settable gauge."""

    def __init__(self, value=0):
        super().__init__()
        self._value = value

    def set_value(self, value) -> None:
        self._value = value

    def get_value(self):
        return self._value


class PassiveStatus(Variable):
    """Gauge computed by a callback at read time."""

    def __init__(self, fn: Callable[[], object]):
        super().__init__()
        self._fn = fn

    def get_value(self):
        return self._fn()


def _escape_label(v: str) -> str:
    # exposition format: backslash, quote, newline must be escaped
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _escape_help(v: str) -> str:
    # HELP lines escape backslash and newline only (exposition format)
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def prometheus_text() -> str:
    """Render every exposed variable in Prometheus exposition format.
    MultiDimension families render one labeled sample per combination
    (reference builtin/prometheus_metrics_service.cpp)."""
    from brpc_tpu.metrics.variable import exposed_variables

    lines = []
    for name, var in exposed_variables():
        metric = name.replace(".", "_").replace("-", "_")
        mtype = getattr(var, "prometheus_type", "gauge")
        help_text = getattr(var, "prometheus_help", None)
        samples = getattr(var, "prometheus_samples", None)
        if samples is not None:
            rendered = False
            for labels, num in samples():
                if not rendered:
                    if help_text:
                        lines.append(f"# HELP {metric} "
                                     f"{_escape_help(help_text)}")
                    lines.append(f"# TYPE {metric} {mtype}")
                    rendered = True
                lbl = ",".join(
                    f'{k}="{_escape_label(v)}"'
                    for k, v in sorted(labels.items()))
                lines.append(f"{metric}{{{lbl}}} {num:g}")
            continue
        try:
            num = float(var.describe())
        except (TypeError, ValueError):
            continue  # prometheus only carries numeric samples
        if help_text:
            lines.append(f"# HELP {metric} {_escape_help(help_text)}")
        lines.append(f"# TYPE {metric} {mtype}")
        lines.append(f"{metric} {num:g}")
    return "\n".join(lines) + ("\n" if lines else "")
