"""Sampler — the once-per-second background sweep (bvar/detail/sampler.cpp:52).

Every windowed variable registers a sampler; one daemon thread ticks them all
each second, pushing a sample into the variable's ring. Tests drive ticks
manually via ``tick_all()`` so they never sleep.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Callable, List


class Sampler:
    """One registered sampling callback, holding a ring of samples."""

    def __init__(self, take_sample: Callable[[], object], window_capacity: int):
        self._take_sample = take_sample
        self.capacity = window_capacity
        self.samples: List[object] = []
        self._lock = threading.Lock()

    def tick(self) -> None:
        sample = self._take_sample()
        with self._lock:
            self.samples.append(sample)
            if len(self.samples) > self.capacity:
                del self.samples[: len(self.samples) - self.capacity]

    def recent(self, n: int) -> List[object]:
        with self._lock:
            return self.samples[-n:] if n else []

    def sample_count(self) -> int:
        with self._lock:
            return len(self.samples)


class SamplerCollector:
    """The background thread sweeping all samplers once per second."""

    def __init__(self, interval_s: float = 1.0):
        self._samplers: "weakref.WeakSet[Sampler]" = weakref.WeakSet()
        self._lock = threading.Lock()
        self._interval = interval_s
        self._thread = None
        self._stop = threading.Event()

    def register(self, sampler: Sampler) -> None:
        with self._lock:
            self._samplers.add(sampler)
            self._ensure_thread()  # under the lock: exactly one sweeper

    def tick_all(self) -> None:
        """Manual tick — the test substrate (no 1 s waits in tests)."""
        with self._lock:
            samplers = list(self._samplers)
        for s in samplers:
            s.tick()

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="bvar-sampler", daemon=True
            )
            self._thread.start()

    def _run(self) -> None:
        from brpc_tpu.profiling import registry as _prof

        _prof.register_current_thread(_prof.ROLE_SAMPLER)
        while not self._stop.wait(self._interval):
            try:
                self.tick_all()
            except Exception:
                pass

    def shutdown(self) -> None:
        self._stop.set()


_global_collector = SamplerCollector()


def global_collector() -> SamplerCollector:
    return _global_collector
