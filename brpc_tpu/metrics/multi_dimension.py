"""MultiDimension — labeled metric families (reference bvar/multi_dimension.h).

One exposed name fans out into per-label-combination sub-metrics, created
on first touch and enumerable for dumps:

    errs = MultiDimension(Adder, ["method", "status"]).expose("rpc_errors")
    errs.stats(["Echo", "ok"]).put(1)

Prometheus exposition renders each combination as a labeled sample
(reference builtin/prometheus_metrics_service.cpp renders MultiDimension
the same way):

    rpc_errors{method="Echo",status="ok"} 1
"""

from __future__ import annotations

import threading
from typing import Dict, List, Sequence, Tuple

from brpc_tpu.metrics.variable import Variable


class MultiDimension(Variable):
    def __init__(self, arg1=None, arg2=None):
        """Accepted forms (both argument orders are unambiguous because a
        factory is callable and label names are a sequence of strings):

            MultiDimension(Adder, ["method", "status"])   # canonical
            MultiDimension(["method", "status"], Adder)
            MultiDimension(("method", "status"))          # Status default
        """
        super().__init__()
        if callable(arg1) and not isinstance(arg1, (list, tuple)):
            factory, label_names = arg1, arg2
        elif isinstance(arg1, (list, tuple)):
            label_names, factory = arg1, arg2
            if factory is not None and not callable(factory):
                raise TypeError(f"factory must be callable, got {factory!r}")
        else:
            raise TypeError(
                "MultiDimension wants (factory, label_names) or "
                f"(label_names[, factory]); got {arg1!r}, {arg2!r}")
        if factory is None:
            from brpc_tpu.metrics.status import Status

            factory = lambda: Status(0)  # noqa: E731
        if not label_names:
            raise ValueError("MultiDimension needs at least one label")
        self._factory = factory
        self.label_names: Tuple[str, ...] = tuple(label_names)
        self._stats: Dict[Tuple[str, ...], object] = {}
        self._lock = threading.Lock()

    # ----------------------------------------------------------- sub-metrics
    def _key(self, label_values: Sequence[str]) -> Tuple[str, ...]:
        if len(label_values) != len(self.label_names):
            raise ValueError(
                f"expected {len(self.label_names)} label values "
                f"{self.label_names}, got {list(label_values)!r}")
        return tuple(str(v) for v in label_values)

    def stats(self, label_values: Sequence[str]):
        """The sub-metric for this label combination (created on demand —
        reference get_stats/LevelStats)."""
        key = self._key(label_values)
        with self._lock:
            m = self._stats.get(key)
            if m is None:
                m = self._stats[key] = self._factory()
            return m

    # reference bvar get_stats spelling
    get_stats = stats

    def has_stats(self, label_values: Sequence[str]) -> bool:
        with self._lock:
            return self._key(label_values) in self._stats

    def delete_stats(self, label_values: Sequence[str]) -> None:
        with self._lock:
            self._stats.pop(self._key(label_values), None)

    def count_stats(self) -> int:
        with self._lock:
            return len(self._stats)

    def list_stats(self) -> List[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            return sorted(self._stats.items())

    # -------------------------------------------------------------- Variable
    def get_value(self):
        return self.count_stats()

    def describe(self) -> str:
        parts = []
        for key, m in self.list_stats():
            labels = ",".join(f'{n}={v}' for n, v in
                              zip(self.label_names, key))
            val = m.get_value() if hasattr(m, "get_value") else m
            parts.append(f"{{{labels}}}: {val}")
        return "; ".join(parts) or "(empty)"

    def prometheus_samples(self) -> List[Tuple[Dict[str, str], float]]:
        """(labels, numeric value) per combination; non-numeric sub-metrics
        are skipped (prometheus only carries numbers)."""
        out = []
        for key, m in self.list_stats():
            try:
                val = float(m.get_value() if hasattr(m, "get_value") else m)
            except (TypeError, ValueError):
                continue
            out.append((dict(zip(self.label_names, key)), val))
        return out
