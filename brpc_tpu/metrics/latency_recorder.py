"""LatencyRecorder — the latency/qps/max/percentile bundle.

Rebuild of ``bvar/latency_recorder.h:49-126``: one ``record(us)`` feeds (a) an
IntRecorder for windowed average latency, (b) a Percentile for p50..p99.99,
(c) a Maxer for max latency, (d) an Adder counted per second for qps. Every
RPC method/socket owns one; /status renders them.
"""

from __future__ import annotations

from brpc_tpu.metrics.reducer import Adder, Maxer, Reducer
from brpc_tpu.metrics.window import PerSecond, Window, WindowedPercentile
from brpc_tpu.metrics.percentile import Percentile
from brpc_tpu.metrics.variable import Variable


class IntRecorder(Reducer):
    """(sum, count) pair reducer — windowed average (bvar/recorder.h:98)."""

    def __init__(self):
        super().__init__(
            (0, 0),
            lambda a, b: (a[0] + b[0], a[1] + b[1]),
            lambda a, b: (a[0] - b[0], a[1] - b[1]),
        )

    def record(self, value: float) -> None:
        self.put((value, 1))

    def average(self) -> float:
        s, c = self.get_value()
        return s / c if c else 0.0


class LatencyRecorder:
    def __init__(self, window_size: int = 10, collector=None):
        import threading as _threading

        self._recorder = IntRecorder()
        self._percentile = Percentile()
        self._maxer = Maxer()
        self._count = Adder()
        self._fused_tls = _threading.local()
        self.window_size = window_size
        self._win_recorder = Window(self._recorder, window_size, collector)
        self._win_percentile = WindowedPercentile(
            self._percentile, window_size, collector
        )
        self._win_max = Window(self._maxer, window_size, collector)
        self._qps = PerSecond(self._count, window_size, collector)

    # ------------------------------------------------------------ write side
    def record(self, latency_us: float) -> "LatencyRecorder":
        # fused fast path: one TLS lookup, direct agent mutation (at 100k+
        # records/s the four dispatch+lambda rounds of the naive version
        # are measurable wall clock on the shared core); read side is the
        # component reducers', untouched
        tls = self._fused_tls
        f = getattr(tls, "agents", None)
        if f is None:
            f = (self._recorder._agent(), self._percentile._reservoir(),
                 self._maxer._agent(), self._count._agent())
            tls.agents = f
        ra, res, ma, ca = f
        s, c = ra.value
        ra.value = (s + latency_us, c + 1)
        res.add(latency_us)
        if latency_us > ma.value:
            ma.value = latency_us
        ca.value += 1
        return self

    __lshift__ = record

    # ------------------------------------------------------------- read side
    def latency(self) -> float:
        """Windowed average latency (us)."""
        s, c = self._win_recorder.get_value()
        return s / c if c else 0.0

    def latency_percentile(self, ratio: float) -> float:
        return self._win_percentile.get_number(ratio)

    def max_latency(self) -> float:
        return self._win_max.get_value()

    def qps(self) -> float:
        return self._qps.get_value()

    def count(self) -> int:
        return self._count.get_value()

    def describe(self) -> str:
        return (
            f"avg={self.latency():.1f}us qps={self.qps():.1f} "
            f"p50={self.latency_percentile(0.5):.0f} "
            f"p90={self.latency_percentile(0.9):.0f} "
            f"p99={self.latency_percentile(0.99):.0f} "
            f"p999={self.latency_percentile(0.999):.0f} "
            f"max={self.max_latency():.0f}"
        )

    def expose(self, prefix: str) -> "LatencyRecorder":
        rec = self

        class _V(Variable):
            def __init__(self, fn):
                super().__init__()
                self._fn = fn

            def get_value(self):
                return self._fn()

        self._vars = [
            _V(rec.latency).expose(f"{prefix}_latency"),
            _V(rec.qps).expose(f"{prefix}_qps"),
            _V(rec.count).expose(f"{prefix}_count"),
            _V(rec.max_latency).expose(f"{prefix}_max_latency"),
            _V(lambda: rec.latency_percentile(0.5)).expose(f"{prefix}_latency_p50"),
            _V(lambda: rec.latency_percentile(0.9)).expose(f"{prefix}_latency_p90"),
            _V(lambda: rec.latency_percentile(0.99)).expose(f"{prefix}_latency_p99"),
            _V(lambda: rec.latency_percentile(0.999)).expose(f"{prefix}_latency_p999"),
        ]
        # the count var is monotonically increasing; the rest are gauges
        self._vars[2].prometheus_type = "counter"
        return self
