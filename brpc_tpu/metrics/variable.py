"""Variable registry — name -> metric, the backbone of observability.

Rebuild of the reference's ``bvar/variable.cpp``: every metric can be
``expose()``d under a global name, enumerated (``list_exposed``), described
(``describe_exposed``) and dumped. The /vars builtin service and the
Prometheus exporter read this registry.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

# RLock: dropping the dict's last reference to a Variable can run its
# __del__ -> hide() on the same thread that already holds the lock.
_registry: Dict[str, "Variable"] = {}
_registry_lock = threading.RLock()


class Variable:
    """Base class of every metric. Subclasses implement get_value()."""

    def __init__(self):
        self._name: Optional[str] = None

    # ------------------------------------------------------------- exposure
    def expose(self, name: str, prefix: str = "") -> "Variable":
        full = f"{prefix}_{name}" if prefix else name
        full = full.replace("::", "_").replace(" ", "_").lower()
        with _registry_lock:
            old = _registry.get(full)
            if old is not None and old is not self:
                old._name = None
            _registry[full] = self
            self._name = full
        return self

    def hide(self) -> None:
        with _registry_lock:
            if self._name and _registry.get(self._name) is self:
                del _registry[self._name]
            self._name = None

    @property
    def name(self) -> Optional[str]:
        return self._name

    # ---------------------------------------------------------------- value
    def get_value(self):
        raise NotImplementedError

    def describe(self) -> str:
        return str(self.get_value())

    def __del__(self):
        try:
            self.hide()
        except Exception:
            pass


def describe_exposed(name: str) -> Optional[str]:
    with _registry_lock:
        var = _registry.get(name)
    return var.describe() if var is not None else None


def get_exposed(name: str) -> Optional[Variable]:
    with _registry_lock:
        return _registry.get(name)


def list_exposed() -> List[str]:
    with _registry_lock:
        return sorted(_registry)


def exposed_variables():
    """Sorted (name, Variable) snapshot (labeled families need the object,
    not just the describe() string)."""
    with _registry_lock:
        return sorted(_registry.items())


def dump_exposed() -> Dict[str, str]:
    """Snapshot of every exposed variable (for /vars and file dumps)."""
    with _registry_lock:
        items = list(_registry.items())
    return {name: var.describe() for name, var in sorted(items)}


def clear_registry() -> None:
    """Test hook."""
    with _registry_lock:
        dropped = list(_registry.values())
        for var in dropped:
            var._name = None
        _registry.clear()
    del dropped  # destructors run here, outside the lock
