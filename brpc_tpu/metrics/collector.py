"""Collector — the shared, rate-limited sampling budget.

Counterpart of the reference's bvar Collector (``bvar/collector.h``, used
by rpc_dump's speed limiter ``rpc_dump.h:46-57``, span sampling and the
contention profiler): every sampled subsystem draws grants from ONE
process-wide token bucket, so the combined overhead of observability stays
bounded no matter how many subsystems sample at once — a trace storm
cannot multiply with a dump storm.

Callers keep their own *selection* policy (ratio flags); the collector is
the global budget behind them:

    if ratio_ok and global_collector().ask_to_be_sampled():
        ...record the sample...

Budget: ``collector_max_samples_per_second`` (reloadable flag; <=0 turns
the cap off). Grants/denies are exposed via /vars.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from brpc_tpu import flags as _flags
from brpc_tpu.metrics.reducer import Adder

collector_max_samples_per_second = _flags.define(
    "collector_max_samples_per_second", 1000,
    "process-wide budget shared by every sampling subsystem "
    "(rpcz, rpc_dump, contention); <=0 disables the cap",
    reloadable=True)


class Collector:
    def __init__(self, max_per_second: Optional[int] = None):
        self._fixed_rate = max_per_second
        self._lock = threading.Lock()
        self._tokens = None  # primed to a full bucket on first ask
        self._last_refill = time.monotonic()
        # monotonic instant before which asks are denied without taking the
        # lock — under sustained sampling pressure (every RPC asks) nearly
        # all asks hit this branch (GIL-atomic read; small approximation
        # races only ever deny a touch early)
        # CONTRACT: rpc/server_processing.py's fast path reads _deny_until
        # directly (one attribute load; any accessor would cost the frames
        # the read exists to avoid) — keep name + semantics stable
        self._deny_until = 0.0
        self._deferred_denies = 0  # counted outside the Adder on the hot path
        self.grants = Adder()
        self.denies = Adder()
        self.grants.expose_as("collector_grants")
        self.denies.expose_as("collector_denies")

    def _rate(self) -> int:
        if self._fixed_rate is not None:
            return self._fixed_rate
        return int(_flags.get("collector_max_samples_per_second"))

    def ask_to_be_sampled(self, weight: int = 1) -> bool:
        """Draw ``weight`` grants from the shared budget. True = sample."""
        now = time.monotonic()
        if now < self._deny_until:
            # hot deny path (every RPC asks under load): one plain int +=,
            # no flags read, no reducer; the deferred count flushes into
            # the denies Adder the next time the gate opens
            self._deferred_denies += weight
            return False
        rate = self._rate()
        if rate <= 0:
            if self._deferred_denies:  # cap was just disabled: flush
                d, self._deferred_denies = self._deferred_denies, 0
                self.denies.put(d)
            self.grants.put(weight)
            return True  # cap disabled
        with self._lock:
            if self._deferred_denies:
                d, self._deferred_denies = self._deferred_denies, 0
                self.denies.put(d)
            if self._tokens is None:
                self._tokens = float(rate)  # full bucket at startup
            elapsed = now - self._last_refill
            if elapsed > 0:
                self._tokens = min(float(rate),
                                   self._tokens + elapsed * rate)
                self._last_refill = now
            if self._tokens >= weight:
                self._tokens -= weight
                granted = True
            else:
                granted = False
                # bucket refills at `rate`/s: deny lock-free until the
                # missing fraction of a token has accrued — capped at 1s
                # so a runtime rate change (including disabling the cap)
                # takes effect within a second
                self._deny_until = now + min(
                    (weight - self._tokens) / rate, 1.0)
        (self.grants if granted else self.denies).put(weight)
        return granted


_collector: Optional[Collector] = None
_collector_lock = threading.Lock()


def global_collector() -> Collector:
    global _collector
    c = _collector  # GIL-atomic read: no lock once initialized (hot path)
    if c is not None:
        return c
    with _collector_lock:
        if _collector is None:
            _collector = Collector()
        return _collector
