"""metrics — bvar equivalent: contention-free instrumentation (SURVEY §2.3)."""

from brpc_tpu.metrics.variable import (
    Variable,
    describe_exposed,
    get_exposed,
    list_exposed,
    dump_exposed,
    clear_registry,
)
from brpc_tpu.metrics.reducer import Reducer, Adder, Maxer, Miner
from brpc_tpu.metrics.percentile import Percentile, PercentileSamples
from brpc_tpu.metrics.sampler import Sampler, SamplerCollector, global_collector
from brpc_tpu.metrics.window import Window, PerSecond, WindowedPercentile
from brpc_tpu.metrics.latency_recorder import IntRecorder, LatencyRecorder
from brpc_tpu.metrics.status import (
    Status,
    PassiveStatus,
    prometheus_text,
)
from brpc_tpu.metrics.series import (
    VarSeries,
    SeriesRegistry,
    global_series,
    ensure_series_installed,
)
from brpc_tpu.metrics.watch import (
    WatchRule,
    WatchRegistry,
    global_watch,
    ensure_watch_hooked,
    install_default_rules,
)

__all__ = [
    "Variable",
    "describe_exposed",
    "get_exposed",
    "list_exposed",
    "dump_exposed",
    "clear_registry",
    "Reducer",
    "Adder",
    "Maxer",
    "Miner",
    "Percentile",
    "PercentileSamples",
    "Sampler",
    "SamplerCollector",
    "global_collector",
    "Window",
    "PerSecond",
    "WindowedPercentile",
    "IntRecorder",
    "LatencyRecorder",
    "Status",
    "PassiveStatus",
    "MultiDimension",
    "prometheus_text",
    "VarSeries",
    "SeriesRegistry",
    "global_series",
    "ensure_series_installed",
    "WatchRule",
    "WatchRegistry",
    "global_watch",
    "ensure_watch_hooked",
    "install_default_rules",
]
from brpc_tpu.metrics.multi_dimension import MultiDimension  # noqa: E402,F401
